"""On-device input transforms (reference C4's transforms + C13's GPU normalize).

The reference normalizes on CPU via ToTensor+Normalize (reference
2.distributed.py:127-136) or on GPU in the prefetcher's side stream with
x255 mean/std (reference 4.apex_distributed.py:86-99). TPU-first: the step
function receives raw uint8 NHWC batches and this module's pure functions run
*inside jit*, so uint8->bf16 conversion, normalize, and augmentation all fuse
into the forward pass (one HBM read, VPU elementwise — no host preprocessing
bottleneck).

Augmentation mirrors the reference per dataset:
* CIFAR10/MNIST train: normalize only (reference 2.distributed.py:127-136 uses
  no augmentation);
* ImageNet train: random crop jitter + horizontal flip ≈ RandomResizedCrop/
  RandomHorizontalFlip (reference 6.distributed_slurm_main.py:130-141); the
  host decode already center-crops with a 256/224 margin, so the on-device
  jitter shifts within that margin with static shapes.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp


def normalize(images_u8: jax.Array, mean: jax.Array, std: jax.Array,
              dtype=jnp.float32) -> jax.Array:
    """uint8 (B,H,W,C) -> normalized float, matching torchvision ToTensor+Normalize."""
    x = images_u8.astype(dtype) / jnp.asarray(255.0, dtype)
    return (x - mean.astype(dtype)) / std.astype(dtype)


def random_flip(images: jax.Array, key: jax.Array) -> jax.Array:
    """Per-sample horizontal flip (reference 6...py:137 RandomHorizontalFlip)."""
    flip = jax.random.bernoulli(key, 0.5, (images.shape[0], 1, 1, 1))
    return jnp.where(flip, images[:, :, ::-1, :], images)


def random_shift(images: jax.Array, key: jax.Array, max_shift: int = 4) -> jax.Array:
    """Static-shape random translation via pad+dynamic_slice (crop-jitter).

    The TPU-native stand-in for RandomResizedCrop's translation component
    (reference 6...py:136): per-batch shift keeps shapes static for XLA.
    """
    if max_shift == 0:
        return images
    b, h, w, c = images.shape
    padded = jnp.pad(images, ((0, 0), (max_shift, max_shift),
                              (max_shift, max_shift), (0, 0)), mode="edge")
    dy, dx = jax.random.randint(key, (2,), 0, 2 * max_shift + 1)
    return jax.lax.dynamic_slice(padded, (0, dy, dx, 0), (b, h, w, c))


def make_transform(mean, std, augment: bool = False, max_shift: int = 4,
                   dtype=jnp.float32):
    """Returns transform(images_u8, key|None) for use inside the jitted step."""
    mean = jnp.asarray(mean)
    std = jnp.asarray(std)

    def transform(images_u8: jax.Array, key: Optional[jax.Array] = None) -> jax.Array:
        x = normalize(images_u8, mean, std, dtype)
        if augment and key is not None:
            k1, k2 = jax.random.split(key)
            x = random_shift(x, k1, max_shift)
            x = random_flip(x, k2)
        return x

    return transform
