"""ImageFolder-style ImageNet loading (reference component C4, variant 6).

The reference uses ``datasets.ImageFolder`` with RandomResizedCrop/Flip for
ImageNet (reference 6.distributed_slurm_main.py:130-159). Here: a lazy dataset
scanning ``root/{train,val}/<class>/<img>`` that decodes JPEGs per batch on the
host (PIL) and resizes to 224x224; flip/crop augmentation runs on device like
the other datasets (tpu_dist.data.pipeline).

Decode throughput on a 1-core host will not feed a TPU pod — that is a known
host-input-pipeline limit (SURVEY.md §7 'Host input pipeline throughput');
the per-batch decode is threaded and the device prefetcher double-buffers, so
the structure is right even where this container's CPU is not.
"""

from __future__ import annotations

import os
from concurrent.futures import ThreadPoolExecutor
from typing import Optional, Tuple

import numpy as np

from tpu_dist.data.datasets import ArrayDataset, IMAGENET_MEAN, IMAGENET_STD

_EXTS = {".jpg", ".jpeg", ".png", ".bmp"}


class ImageFolderDataset:
    """Lazy ImageFolder with the ArrayDataset batch protocol (get_batch)."""

    def __init__(self, split_dir: str, size: int = 224, workers: int = 8,
                 name: str = "imagefolder"):
        classes = sorted(d for d in os.listdir(split_dir)
                         if os.path.isdir(os.path.join(split_dir, d)))
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(split_dir, c)
            for fn in sorted(os.listdir(cdir)):
                if os.path.splitext(fn)[1].lower() in _EXTS:
                    self.samples.append((os.path.join(cdir, fn), self.class_to_idx[c]))
        if not self.samples:
            raise FileNotFoundError(f"no images under {split_dir}")
        self.labels = np.array([s[1] for s in self.samples], np.int32)
        self.size = size
        self.num_classes = len(classes)
        self.mean, self.std = IMAGENET_MEAN, IMAGENET_STD
        self.name = name
        self._pool = ThreadPoolExecutor(max_workers=workers)

    def __len__(self):
        return len(self.samples)

    @property
    def image_shape(self) -> Tuple[int, int, int]:
        return (self.size, self.size, 3)

    def _decode(self, idx: int) -> np.ndarray:
        from PIL import Image
        path, _ = self.samples[idx]
        from tpu_dist import _native
        if (path.lower().endswith((".jpg", ".jpeg"))
                and _native.decode_available()):  # gate BEFORE reading the
            # file — a host without the native decoder must not pay a full
            # read just to learn it, then read again for PIL
            # native libjpeg path (csrc/decode.cpp): DCT-scaled decode +
            # bilinear + center crop, GIL released for the whole call so
            # the pool's threads decode in parallel; None -> PIL fallback
            with open(path, "rb") as f:
                out = _native.decode_jpeg(f.read(), self.size)
            if out is not None:
                return out
        with Image.open(path) as im:
            im = im.convert("RGB")
            # resize shorter side to size*1.14 then center crop (device handles
            # random crop jitter); matches the reference's val transform scale
            # (6.distributed_slurm_main.py:148-159 Resize(256)/CenterCrop(224)).
            w, h = im.size
            scale = (self.size * 256 // 224) / min(w, h)
            im = im.resize((max(1, round(w * scale)), max(1, round(h * scale))))
            arr = np.asarray(im, np.uint8)
        top = (arr.shape[0] - self.size) // 2
        left = (arr.shape[1] - self.size) // 2
        return arr[top:top + self.size, left:left + self.size]

    def get_batch(self, indices: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        imgs = list(self._pool.map(self._decode, [int(i) for i in indices]))
        return np.stack(imgs), self.labels[indices]


def load_imagefolder(root: str) -> Optional[Tuple[ImageFolderDataset, ImageFolderDataset]]:
    tr_dir, va_dir = os.path.join(root, "train"), os.path.join(root, "val")
    if not (os.path.isdir(tr_dir) and os.path.isdir(va_dir)):
        return None
    return (ImageFolderDataset(tr_dir, name="imagenet-train"),
            ImageFolderDataset(va_dir, name="imagenet-val"))
