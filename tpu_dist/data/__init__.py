from tpu_dist.data.datasets import ArrayDataset, load_dataset  # noqa: F401
from tpu_dist.data.loader import DataLoader, prefetch_to_device  # noqa: F401
from tpu_dist.data.pipeline import make_transform  # noqa: F401
from tpu_dist.data.sampler import DistributedSampler  # noqa: F401
