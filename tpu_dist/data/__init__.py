from tpu_dist.data.datasets import ArrayDataset, load_dataset  # noqa: F401
from tpu_dist.data.loader import (DataLoader, assemble_global,  # noqa: F401
                                  prefetch_to_device)
from tpu_dist.data.pipeline import make_transform  # noqa: F401
from tpu_dist.data.sampler import DistributedSampler  # noqa: F401
