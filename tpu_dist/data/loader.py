"""Host-side batch loader + device prefetcher (reference C4/C13).

Replaces two reference mechanisms TPU-first:

* the ``DataLoader(num_workers=...)`` host pipeline (reference
  2.distributed.py:137-160) — here a background thread assembles uint8 numpy
  batches from the sampler's index stream (decode/gather overlapped with the
  device step);
* the CUDA-stream ``data_prefetcher`` that overlapped H2D copy + normalize
  with compute and which upstream disabled as buggy (reference
  4.apex_distributed.py:80-133, 4.apex_distributed2.py:80) — here
  :func:`prefetch_to_device` keeps N batches in flight with
  ``jax.device_put`` onto the step's input sharding. JAX transfers are async
  (dispatch returns immediately), so compute/copy overlap falls out of the
  runtime instead of hand-managed streams; normalization happens on device
  inside the jitted step (tpu_dist.data.pipeline).
"""

from __future__ import annotations

import queue
import threading
import time
from functools import partial
from typing import Callable, Iterator, Optional, Tuple

import jax
import numpy as np

from tpu_dist.data.sampler import DistributedSampler


class DataLoader:
    """Yields (images_u8, labels_i32) numpy batches for this process's shard."""

    def __init__(self, dataset, sampler: DistributedSampler, batch_size: int,
                 workers: int = 2, queue_depth: int = 4,
                 emit_valid: bool = False):
        if sampler.batch_size not in (None, batch_size):
            raise ValueError("sampler.batch_size disagrees with loader batch_size")
        self.dataset = dataset
        self.sampler = sampler
        self.batch_size = batch_size
        self.queue_depth = queue_depth
        # emit_valid: also yield a float32 validity mask distinguishing real
        # samples from the sampler's wrap-around padding (exact eval metrics)
        self.emit_valid = emit_valid

    def __len__(self) -> int:
        return self.sampler.num_samples // self.batch_size

    def __iter__(self) -> Iterator[Tuple[np.ndarray, ...]]:
        idx, valid = self.sampler.indices_with_valid()

        def batches():
            for b in range(len(self)):
                sel = slice(b * self.batch_size, (b + 1) * self.batch_size)
                batch = self.dataset.get_batch(idx[sel])
                if self.emit_valid:
                    batch = (*batch, valid[sel].astype(np.float32))
                yield batch

        # ONE queue pipeline for the whole data layer: stream_prefetch owns
        # the producer thread, bounded staging, error propagation, and
        # consumer-abandonment shutdown
        yield from stream_prefetch(batches(), depth=self.queue_depth)


def stream_prefetch(iterable, depth: int = 2):
    """Bounded background pipeline over ANY iterable: items are produced —
    including any host-side assembly and async device-transfer dispatch the
    iterable performs — in a producer thread while the consumer computes,
    with at most ``depth`` items staged. The generic engine behind the
    trainers' streamed host->device window paths (datasets too large for
    HBM residency); exceptions propagate to the consumer, and abandoning
    the generator stops the producer."""
    q: "queue.Queue" = queue.Queue(maxsize=depth)
    stop = threading.Event()

    def _put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    # control flows in tagged envelopes, so items that happen to be None or
    # exception instances pass through untouched (ADVICE r3)
    def producer():
        try:
            for item in iterable:
                if not _put(("item", item)):
                    return
            _put(("done", None))
        except BaseException as e:  # surface assembly/upload errors
            _put(("err", e))

    threading.Thread(target=producer, daemon=True).start()
    try:
        while True:
            tag, payload = q.get()
            if tag == "done":
                return
            if tag == "err":
                raise payload
            yield payload
    finally:
        stop.set()


def assemble_global(sharding, batch):
    """Device-put a host batch (array or tuple of arrays) onto ``sharding``.

    THE one place that knows the multi-controller rule: when >1 process
    feeds, each holds only its own sampler shard, so the global array must be
    assembled with ``jax.make_array_from_process_local_data`` — a bare
    device_put would treat the local shard as the whole global array and
    silently drop the other processes' data.
    """
    if jax.process_count() > 1:
        if isinstance(batch, tuple):
            return tuple(jax.make_array_from_process_local_data(sharding, a)
                         for a in batch)
        return jax.make_array_from_process_local_data(sharding, batch)
    return jax.device_put(batch, sharding)


class DevicePrefetcher:
    """Double-buffered host->device prefetcher: the next batch's upload is
    STAGED ON A BACKGROUND THREAD while the current step runs.

    The reference's CUDA-stream ``data_prefetcher`` (4.apex_distributed.py:
    80-133 — the one upstream shipped disabled as buggy) solved exactly
    this on GPUs; the TPU-native version needs no streams: a daemon
    producer thread pulls host batches from ``iterable``, dispatches each
    one's ``jax.device_put`` onto ``sharding`` (or
    ``jax.make_array_from_process_local_data`` in the multi-host path —
    the :func:`assemble_global` rule), and keeps up to ``depth`` staged
    batches in a bounded queue. The consumer's wait — the ``data_s`` phase
    in the engines' step records — collapses to ~0 whenever the device
    step outlasts host assembly + copy dispatch.

    Composition: the iterable IS the sampler/epoch logic (one prefetcher
    per epoch, built over that epoch's loader/index stream), so epoch
    boundaries and step-exact resume need no special casing here.

    Shutdown: exhaustion, consumer abandonment (generator close), and
    :meth:`close` all stop the producer and JOIN the thread — daemon=True
    is the crash backstop, the join is the clean path (distlint DL103).

    :meth:`stats` reports the overlap ledger: ``put_s`` (producer seconds
    spent staging uploads — the un-overlapped copy cost), ``wait_s``
    (consumer seconds actually blocked), and the achieved overlap
    efficiency, which tools/data_rate.py turns into a standalone number.
    """

    def __init__(self, iterable, sharding=None, depth: int = 2,
                 put: Optional[Callable] = None):
        if put is not None:
            self._put = put
        elif sharding is not None:
            self._put = partial(assemble_global, sharding)
        else:
            self._put = lambda b: jax.tree.map(jax.device_put, b)
        self._q: "queue.Queue" = queue.Queue(maxsize=max(depth, 1))
        self._stop = threading.Event()
        self._iterable = iterable
        self.put_s = 0.0     # producer: seconds inside the staging put
        self.wait_s = 0.0    # consumer: seconds blocked on the queue
        self.batches = 0
        self._thread = threading.Thread(target=self._produce, daemon=True)
        self._thread.start()

    def _enqueue(self, item) -> bool:
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def _produce(self) -> None:
        # tagged envelopes (the stream_prefetch protocol) so None / exception
        # instances pass through as payload, never as control
        try:
            for batch in self._iterable:
                t0 = time.perf_counter()
                staged = self._put(batch)
                self.put_s += time.perf_counter() - t0
                if not self._enqueue(("item", staged)):
                    return
            self._enqueue(("done", None))
        except BaseException as e:  # surface assembly/upload errors
            self._enqueue(("err", e))

    def __iter__(self) -> Iterator:
        try:
            while True:
                t0 = time.perf_counter()
                tag, payload = self._q.get()
                self.wait_s += time.perf_counter() - t0
                if tag == "done":
                    return
                if tag == "err":
                    raise payload
                self.batches += 1
                yield payload
        finally:
            self.close()

    def close(self) -> None:
        """Stop the producer and join it (idempotent). Abandoning the
        iterator calls this too, so a break out of the epoch loop never
        leaves an upload thread feeding a dead consumer."""
        self._stop.set()
        # unblock a producer parked on a full queue
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5.0)

    def stats(self) -> dict:
        """Overlap ledger: achieved consumer wait vs the un-overlapped
        copy/assembly cost. ``overlap_efficiency`` = 1 - wait/put (clamped
        to [0, 1]); 1.0 means the uploads were fully hidden behind
        compute, 0.0 means nothing was hidden (the un-prefetched world)."""
        eff = None
        if self.put_s > 0:
            eff = max(0.0, min(1.0, 1.0 - self.wait_s / self.put_s))
        return {"batches": self.batches,
                "put_s": round(self.put_s, 6),
                "wait_s": round(self.wait_s, 6),
                "overlap_efficiency": eff}


def prefetch_to_device(iterator, sharding=None, size: int = 2):
    """Keep ``size`` device-put batches in flight (C13 equivalent, stream-free).

    Since round 9 this is a thin wrapper over :class:`DevicePrefetcher`,
    so the ``device_put`` dispatch itself (and multi-host
    ``make_array_from_process_local_data`` assembly, which can block on
    cross-host coordination) runs on the background thread instead of the
    consumer's — every existing call site gets the overlap for free.
    ``sharding`` is a ``jax.sharding.Sharding`` describing the step
    function's input layout; batches land pre-sharded so the jitted step
    never re-lays data out.

    Still a GENERATOR (lazy like the pre-round-9 version): the producer
    thread only starts at the first ``next()``, so building the iterator
    and abandoning it before iterating leaks no thread and stages no HBM
    buffers; closing it after a partial consume joins the producer via
    DevicePrefetcher's own shutdown path.
    """
    yield from DevicePrefetcher(iterator, sharding, depth=size)
