"""Host-side batch loader + device prefetcher (reference C4/C13).

Replaces two reference mechanisms TPU-first:

* the ``DataLoader(num_workers=...)`` host pipeline (reference
  2.distributed.py:137-160) — here a background thread assembles uint8 numpy
  batches from the sampler's index stream (decode/gather overlapped with the
  device step);
* the CUDA-stream ``data_prefetcher`` that overlapped H2D copy + normalize
  with compute and which upstream disabled as buggy (reference
  4.apex_distributed.py:80-133, 4.apex_distributed2.py:80) — here
  :func:`prefetch_to_device` keeps N batches in flight with
  ``jax.device_put`` onto the step's input sharding. JAX transfers are async
  (dispatch returns immediately), so compute/copy overlap falls out of the
  runtime instead of hand-managed streams; normalization happens on device
  inside the jitted step (tpu_dist.data.pipeline).
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator, Optional, Tuple

import jax
import numpy as np

from tpu_dist.data.sampler import DistributedSampler


class DataLoader:
    """Yields (images_u8, labels_i32) numpy batches for this process's shard."""

    def __init__(self, dataset, sampler: DistributedSampler, batch_size: int,
                 workers: int = 2, queue_depth: int = 4,
                 emit_valid: bool = False):
        if sampler.batch_size not in (None, batch_size):
            raise ValueError("sampler.batch_size disagrees with loader batch_size")
        self.dataset = dataset
        self.sampler = sampler
        self.batch_size = batch_size
        self.queue_depth = queue_depth
        # emit_valid: also yield a float32 validity mask distinguishing real
        # samples from the sampler's wrap-around padding (exact eval metrics)
        self.emit_valid = emit_valid

    def __len__(self) -> int:
        return self.sampler.num_samples // self.batch_size

    def __iter__(self) -> Iterator[Tuple[np.ndarray, ...]]:
        idx, valid = self.sampler.indices_with_valid()

        def batches():
            for b in range(len(self)):
                sel = slice(b * self.batch_size, (b + 1) * self.batch_size)
                batch = self.dataset.get_batch(idx[sel])
                if self.emit_valid:
                    batch = (*batch, valid[sel].astype(np.float32))
                yield batch

        # ONE queue pipeline for the whole data layer: stream_prefetch owns
        # the producer thread, bounded staging, error propagation, and
        # consumer-abandonment shutdown
        yield from stream_prefetch(batches(), depth=self.queue_depth)


def stream_prefetch(iterable, depth: int = 2):
    """Bounded background pipeline over ANY iterable: items are produced —
    including any host-side assembly and async device-transfer dispatch the
    iterable performs — in a producer thread while the consumer computes,
    with at most ``depth`` items staged. The generic engine behind the
    trainers' streamed host->device window paths (datasets too large for
    HBM residency); exceptions propagate to the consumer, and abandoning
    the generator stops the producer."""
    q: "queue.Queue" = queue.Queue(maxsize=depth)
    stop = threading.Event()

    def _put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    # control flows in tagged envelopes, so items that happen to be None or
    # exception instances pass through untouched (ADVICE r3)
    def producer():
        try:
            for item in iterable:
                if not _put(("item", item)):
                    return
            _put(("done", None))
        except BaseException as e:  # surface assembly/upload errors
            _put(("err", e))

    threading.Thread(target=producer, daemon=True).start()
    try:
        while True:
            tag, payload = q.get()
            if tag == "done":
                return
            if tag == "err":
                raise payload
            yield payload
    finally:
        stop.set()


def assemble_global(sharding, batch):
    """Device-put a host batch (array or tuple of arrays) onto ``sharding``.

    THE one place that knows the multi-controller rule: when >1 process
    feeds, each holds only its own sampler shard, so the global array must be
    assembled with ``jax.make_array_from_process_local_data`` — a bare
    device_put would treat the local shard as the whole global array and
    silently drop the other processes' data.
    """
    if jax.process_count() > 1:
        if isinstance(batch, tuple):
            return tuple(jax.make_array_from_process_local_data(sharding, a)
                         for a in batch)
        return jax.make_array_from_process_local_data(sharding, batch)
    return jax.device_put(batch, sharding)


def prefetch_to_device(iterator, sharding=None, size: int = 2):
    """Keep ``size`` device-put batches in flight (C13 equivalent, stream-free).

    ``sharding`` is a ``jax.sharding.Sharding`` describing the step function's
    input layout; batches land pre-sharded so the jitted step never re-lays
    data out. In multi-process runs each process feeds only its OWN sampler
    shard, so the global batch is assembled with
    ``jax.make_array_from_process_local_data`` (a bare device_put would treat
    the local shard as the whole global array and silently drop the other
    processes' data — the multi-controller JAX pitfall).
    """
    buf = []

    def put(batch):
        if sharding is None:
            return jax.tree.map(jax.device_put, batch)
        return assemble_global(sharding, batch)
    it = iter(iterator)
    try:
        for _ in range(size):
            buf.append(put(next(it)))
    except StopIteration:
        pass
    while buf:
        yield buf.pop(0)
        try:
            buf.append(put(next(it)))
        except StopIteration:
            pass
