"""Token-stream corpus for the LM family (the LM analog of datasets.py C4).

The reference has no language-model pipeline at all (SURVEY.md §2c); this
gives the LM half of the framework the same data contract the image half
has, so ONE loop drives both:

* a corpus is a flat int token stream on the host — loaded from a binary
  token file (``.bin`` uint16/uint32, memmap'd — the standard nanoGPT-style
  format — or ``.npy``), or generated as the deterministic synthetic affine
  stream (x -> 5x+7 mod V with 5% noise) so training curves are meaningful
  in a zero-egress environment;
* training examples are overlapping (seq_len+1)-token ROWS cut at stride
  seq_len: row i = stream[i*L : i*L + L + 1], so consecutive rows share one
  boundary token and every next-token target exists. Rows are the unit the
  DistributedSampler shuffles/shards — giving the LM path the exact same
  N-process bit-exactness story as images (tpu_dist.data.sampler);
* train/val split is by STREAM PREFIX/SUFFIX (val = held-out tail), never
  by row shuffle — rows overlap, so a shuffled split would leak val tokens
  into train.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np


@dataclass
class TokenDataset:
    """Host-side token corpus view: rows of (seq_len+1) int32 tokens."""

    stream: np.ndarray          # (n_tokens,) int — possibly a memmap
    seq_len: int
    vocab_size: int
    name: str = "tokens"

    def __post_init__(self):
        if self.stream.ndim != 1:
            raise ValueError("token stream must be 1-D")
        if len(self.stream) < self.seq_len + 1:
            raise ValueError(
                f"corpus of {len(self.stream)} tokens is shorter than one "
                f"{self.seq_len + 1}-token row")

    def __len__(self) -> int:
        # stride-L rows needing L+1 tokens each
        return (len(self.stream) - 1) // self.seq_len

    @property
    def num_tokens(self) -> int:
        return len(self) * self.seq_len  # target tokens per epoch

    def get_rows(self, indices: np.ndarray) -> np.ndarray:
        """(n,) row indices -> (n, seq_len+1) int32 rows (vectorized gather;
        works on memmaps — only the touched pages are read)."""
        l = self.seq_len
        idx = np.asarray(indices, np.int64)
        pos = idx[:, None] * l + np.arange(l + 1)
        return np.asarray(self.stream[pos.ravel()], np.int32).reshape(
            len(idx), l + 1)

    def rows_array(self) -> np.ndarray:
        """ALL rows as one (n_rows, seq_len+1) int32 array (HBM-resident
        path). Materialized from the stream view; for CIFAR-scale synthetic
        corpora this is a few MB."""
        n, l = len(self), self.seq_len
        # stride trick: rows overlap by one token, so a strided view of the
        # stream IS the row matrix (no copy until ascontiguousarray)
        base = np.lib.stride_tricks.as_strided(
            self.stream[: n * l + 1], shape=(n, l + 1),
            strides=(self.stream.strides[0] * l, self.stream.strides[0]))
        return np.ascontiguousarray(base, np.int32)


def synthetic_stream(n_tokens: int, vocab_size: int, seed: int = 0,
                     noise: float = 0.05) -> np.ndarray:
    """Deterministic learnable stream: x_{t+1} = 5*x_t + 7 (mod V), with
    ``noise`` fraction of uniform re-draws — the affine rule the round-2 LM
    demo trained on, now as a corpus (vectorized generation)."""
    rng = np.random.default_rng(seed)
    # fully vectorized: noise re-draws cut the stream into segments, and
    # within a segment position t is the affine orbit of its segment's seed:
    # x_{s+d} = 5^d * x_s + c_d (mod V), with c_d = 7 * (5^d - 1) / 4
    flips = rng.random(n_tokens) < noise
    flips[0] = True
    draws = rng.integers(0, vocab_size, n_tokens).astype(np.int64)
    seg = np.cumsum(flips) - 1                      # segment id per position
    starts = np.flatnonzero(flips)                  # segment start positions
    d = np.arange(n_tokens) - starts[seg]           # steps since segment seed
    max_d = int(d.max()) + 1
    a = np.empty(max_d, np.int64)                   # 5^d mod V
    c = np.empty(max_d, np.int64)                   # additive orbit term
    a[0], c[0] = 1, 0
    for i in range(1, max_d):                       # loop over max segment
        a[i] = (a[i - 1] * 5) % vocab_size          # length (~100s), not N
        c[i] = (c[i - 1] * 5 + 7) % vocab_size
    seeds = draws[starts][seg]
    return ((a[d] * seeds + c[d]) % vocab_size).astype(np.int32)


def _load_stream(path: str) -> Tuple[np.ndarray, int]:
    """(stream, inferred_vocab) from a token file. ``.npy`` loads through
    numpy; ``.bin`` memmaps with the dtype named by TPU_DIST_TOKEN_DTYPE
    (default uint16, nanoGPT's format), after checking the file size is a
    whole number of items — a wrong dtype setting on a uint16 file would
    otherwise yield garbage token ids (ADVICE r3)."""
    if path.endswith(".npy"):
        arr = np.load(path, mmap_mode="r")
    else:
        dtype = np.dtype(os.environ.get("TPU_DIST_TOKEN_DTYPE", "uint16"))
        size = os.path.getsize(path)
        if size % dtype.itemsize:
            raise ValueError(
                f"{path}: {size} bytes is not a whole number of "
                f"{dtype.name} tokens — set TPU_DIST_TOKEN_DTYPE to the "
                "dtype the file was written with")
        arr = np.memmap(path, dtype=dtype, mode="r")
    # FULL scan for the max id (chunked — sequential memmap reads run at
    # disk bandwidth): a sampled max would under-size the embedding table
    # and out-of-range ids clamp SILENTLY under jit
    vocab = 0
    for start in range(0, len(arr), 1 << 24):
        vocab = max(vocab, int(np.max(arr[start: start + (1 << 24)])))
    return arr, vocab + 1


def load_token_dataset(data: str, seq_len: int, vocab_size: int,
                       val_frac: float = 0.05,
                       synth_tokens: int = 2_000_000,
                       seed: int = 0,
                       val_data: str = "",
                       ) -> Tuple[TokenDataset, TokenDataset]:
    """Returns (train, val) TokenDatasets.

    ``data`` = path to a token file; empty/missing -> the synthetic affine
    corpus (``synth_tokens`` long). ``val_data`` names a separate val file;
    otherwise the last ``val_frac`` of the stream is held out (prefix/suffix
    split — rows overlap, so a shuffled split would leak).
    """
    if data and os.path.exists(data):
        stream, inferred = _load_stream(data)
        vocab = max(vocab_size, inferred)
        name = os.path.basename(data)
    else:
        if data:
            print(f"token file {data!r} not found — synthetic affine corpus",
                  flush=True)
        stream = synthetic_stream(synth_tokens, vocab_size, seed)
        vocab = vocab_size
        name = "synth-affine"
    if val_data and os.path.exists(val_data):
        val_stream, val_vocab = _load_stream(val_data)
        vocab = max(vocab, val_vocab)  # val ids must fit the embedding too
        train_stream = stream
    else:
        n_val = max(seq_len + 1, int(len(stream) * val_frac))
        if n_val >= len(stream):
            raise ValueError(f"val fraction {val_frac} leaves no train data")
        train_stream, val_stream = stream[:-n_val], stream[-n_val:]
    return (TokenDataset(train_stream, seq_len, vocab, f"{name}-train"),
            TokenDataset(val_stream, seq_len, vocab, f"{name}-val"))
