"""Per-process shard sampling (reference component C5).

Reproduces ``torch.utils.data.DistributedSampler`` semantics TPU-first
(reference 2.distributed.py:138,155 and set_epoch at :167-168):

* deterministic shuffle per epoch — torch reseeds a generator with
  ``seed + epoch``; here the epoch is folded into the sampler seed the same
  way (``set_epoch`` ≡ new permutation key), SURVEY.md §7 'Per-epoch
  reshuffling';
* the index list is padded by wrap-around so every replica sees the same
  number of samples — torch pads to ``ceil(N / world) * world``; we
  additionally pad to a multiple of ``world * batch`` so every *batch* has a
  static shape (XLA requires static shapes for a single compiled step);
* per-rank assignment: with ``batch_size`` set, each *global batch* is a
  contiguous window of the permutation and rank r takes rows
  ``[r*B:(r+1)*B]`` of it, so the global device array assembled across
  processes is ordering-identical to the single-process batch — an N-process
  run reproduces the 1-process trajectory exactly (positional randomness like
  dropout included; verified by tests/test_multiprocess.py). This is a
  deliberate delta from torch's strided ``indices[rank::world]``, which
  permutes samples within the global batch per world size; the strided
  flavor is kept for the batch-unaware mode (``batch_size=None``).
"""

from __future__ import annotations

import math
from typing import Iterator, Optional

import numpy as np


class DistributedSampler:
    """Index sampler for one process's shard of a dataset."""

    def __init__(self, dataset_len: int, num_replicas: int, rank: int,
                 shuffle: bool = True, seed: int = 0,
                 batch_size: Optional[int] = None, drop_last: bool = False):
        if rank >= num_replicas or rank < 0:
            raise ValueError(f"invalid rank {rank} for world size {num_replicas}")
        self.dataset_len = dataset_len
        self.num_replicas = num_replicas
        self.rank = rank
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        self.batch_size = batch_size  # per-replica batch; None = no batch padding
        self.drop_last = drop_last
        chunk = num_replicas * (batch_size or 1)
        if drop_last:
            self.total_size = (dataset_len // chunk) * chunk
            if self.total_size == 0:
                raise ValueError("dataset smaller than one global batch with drop_last")
        else:
            self.total_size = max(1, math.ceil(dataset_len / chunk)) * chunk
        self.num_samples = self.total_size // num_replicas

    def set_epoch(self, epoch: int) -> None:
        """Reference 2.distributed.py:167-168 — reshuffle shard assignment."""
        self.epoch = epoch

    def indices(self) -> np.ndarray:
        return self.indices_with_valid()[0]

    def indices_with_valid(self) -> tuple[np.ndarray, np.ndarray]:
        """(indices, valid) for this rank; valid=False marks wrap-around
        padding entries. Exact metrics divide by sum(valid), not len(indices)
        — the reference counted padding duplicates in eval (its val set is
        padded by DistributedSampler too), which tpu_dist fixes."""
        if self.shuffle:
            rng = np.random.default_rng(self.seed * 1_000_003 + self.epoch)
            idx = rng.permutation(self.dataset_len)
        else:
            idx = np.arange(self.dataset_len)
        valid = np.ones(len(idx), bool)
        if self.drop_last:
            idx = idx[: self.total_size]
            valid = valid[: self.total_size]
        else:
            pad = self.total_size - len(idx)
            if pad > 0:
                # wrap-around padding, as torch DistributedSampler does
                reps = int(np.ceil(pad / len(idx)))
                idx = np.concatenate([idx] + [idx] * reps)[: self.total_size]
                valid = np.concatenate(
                    [valid, np.zeros(self.total_size - len(valid), bool)])
        if self.batch_size:
            # batch-blocked: global batch b = idx[b*W*B:(b+1)*W*B]; rank r
            # holds its contiguous sub-block, so cross-process assembly
            # reconstructs the exact single-process ordering (see module doc)
            def take(a: np.ndarray) -> np.ndarray:
                blocks = a.reshape(-1, self.num_replicas, self.batch_size)
                return blocks[:, self.rank, :].reshape(-1)
            return take(idx), take(valid)
        return (idx[self.rank :: self.num_replicas],
                valid[self.rank :: self.num_replicas])

    def __iter__(self) -> Iterator[int]:
        return iter(self.indices())

    def __len__(self) -> int:
        return self.num_samples
