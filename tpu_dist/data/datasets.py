"""Datasets (reference component C4).

The reference uses torchvision CIFAR10 (auto-download, Normalize with CIFAR
stats — reference 1.dataparallel.py:124-129), MNIST with per-rank data dirs
(reference 5.2.horovod_pytorch_mnist.py:134-155) and ImageFolder for ImageNet
(reference 6.distributed_slurm_main.py:130-159).

TPU-first redesign:

* datasets are in-memory uint8 numpy arrays on the host; normalization and
  train-time augmentation (random crop + flip) happen **on device inside the
  jitted step** — the idiomatic replacement for the reference's buggy
  CUDA-stream GPU prefetcher that normalized on a side stream
  (reference 4.apex_distributed.py:80-133, disabled in 4b:80);
* real CIFAR-10 (cifar-10-batches-py pickles) and MNIST (idx files) are loaded
  if present under ``--data``; otherwise a deterministic *synthetic* set with
  class-conditional structure is generated, because this environment has no
  network egress (torchvision's auto-download cannot work). Synthetic data is
  learnable, so convergence tests remain meaningful.
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct
from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np

# CIFAR10 channel stats, as hard-coded by the reference
# (reference 1.dataparallel.py:127-129: mean=[0.4914,0.4822,0.4465], std=[0.2023,0.1994,0.2010])
CIFAR10_MEAN = np.array([0.4914, 0.4822, 0.4465], np.float32)
CIFAR10_STD = np.array([0.2023, 0.1994, 0.2010], np.float32)
# MNIST stats (reference 5.2.horovod_pytorch_mnist.py:140: Normalize((0.1307,), (0.3081,)))
MNIST_MEAN = np.array([0.1307], np.float32)
MNIST_STD = np.array([0.3081], np.float32)
IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)  # reference 6...py:133
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)


@dataclass
class ArrayDataset:
    """Host-side dataset: uint8 images (N,H,W,C) + int32 labels (N,)."""

    images: np.ndarray
    labels: np.ndarray
    mean: np.ndarray
    std: np.ndarray
    num_classes: int
    name: str = "dataset"

    def __len__(self) -> int:
        return self.images.shape[0]

    @property
    def image_shape(self) -> Tuple[int, int, int]:
        return self.images.shape[1:]

    def get_batch(self, indices: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Assemble a uint8 batch for the given sample indices.

        The common protocol between in-memory arrays and lazy ImageFolder-style
        datasets (tpu_dist.data.imagefolder); the loader only ever calls this.
        Uses the native row-gather library (csrc/gather.cpp) when built —
        whole-row memcpy with the GIL released, so batch assembly overlaps the
        device step; numpy fallback otherwise.
        """
        from tpu_dist import _native
        return _native.gather_batch(self.images, self.labels, indices)


def _synthetic(num: int, shape: Tuple[int, int, int], num_classes: int,
               proto_seed: int, sample_seed: int, name: str) -> ArrayDataset:
    """Deterministic learnable synthetic data: per-class low-frequency pattern
    + per-sample noise. Class prototypes depend only on ``proto_seed`` so the
    train and val splits share one distribution; samples/noise differ via
    ``sample_seed``. Class signal is strong enough that a CNN separates it in
    a few steps (used by convergence tests, SURVEY.md §4)."""
    proto_rng = np.random.default_rng(proto_seed)
    rng = np.random.default_rng(sample_seed)
    h, w, c = shape
    # low-frequency class prototypes: upsampled 4x4 random grids
    protos = proto_rng.normal(0.0, 1.0, size=(num_classes, 4, 4, c)).astype(np.float32)
    protos = np.repeat(np.repeat(protos, (h + 3) // 4, axis=1), (w + 3) // 4, axis=2)
    protos = protos[:, :h, :w, :]
    labels = rng.integers(0, num_classes, size=num).astype(np.int32)
    noise = rng.normal(0.0, 0.6, size=(num, h, w, c)).astype(np.float32)
    imgs = protos[labels] + noise
    imgs = np.clip((imgs + 3.0) / 6.0, 0.0, 1.0)
    images = (imgs * 255).astype(np.uint8)
    mean = np.full((c,), 0.5, np.float32)
    std = np.full((c,), 0.25, np.float32)
    return ArrayDataset(images, labels, mean, std, num_classes, name)


def _load_cifar10_pickles(root: str) -> Optional[Tuple[ArrayDataset, ArrayDataset]]:
    d = os.path.join(root, "cifar-10-batches-py")
    if not os.path.isdir(d):
        return None
    def load(names):
        xs, ys = [], []
        for n in names:
            with open(os.path.join(d, n), "rb") as f:
                batch = pickle.load(f, encoding="bytes")
            xs.append(np.asarray(batch[b"data"], np.uint8))
            ys.append(np.asarray(batch[b"labels"], np.int32))
        x = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        return np.ascontiguousarray(x), np.concatenate(ys)
    xtr, ytr = load([f"data_batch_{i}" for i in range(1, 6)])
    xte, yte = load(["test_batch"])
    mk = lambda x, y, nm: ArrayDataset(x, y, CIFAR10_MEAN, CIFAR10_STD, 10, nm)
    return mk(xtr, ytr, "cifar10-train"), mk(xte, yte, "cifar10-val")


def _read_idx(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        zero, dtype, ndim = struct.unpack(">HBB", f.read(4))
        shape = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        return np.frombuffer(f.read(), np.uint8).reshape(shape)


def _load_mnist_idx(root: str) -> Optional[Tuple[ArrayDataset, ArrayDataset]]:
    candidates = [root, os.path.join(root, "MNIST", "raw")]
    for d in candidates:
        tri = os.path.join(d, "train-images-idx3-ubyte")
        if os.path.exists(tri) or os.path.exists(tri + ".gz"):
            def get(stem):
                p = os.path.join(d, stem)
                return _read_idx(p if os.path.exists(p) else p + ".gz")
            xtr = get("train-images-idx3-ubyte")[..., None]
            ytr = get("train-labels-idx1-ubyte").astype(np.int32)
            xte = get("t10k-images-idx3-ubyte")[..., None]
            yte = get("t10k-labels-idx1-ubyte").astype(np.int32)
            mk = lambda x, y, nm: ArrayDataset(x, y, MNIST_MEAN, MNIST_STD, 10, nm)
            return mk(xtr, ytr, "mnist-train"), mk(xte, yte, "mnist-val")
    return None


def load_dataset(name: str, root: str, synth_train: int = 50000,
                 synth_val: int = 10000, seed: int = 1234,
                 ) -> Tuple[ArrayDataset, ArrayDataset]:
    """Returns (train, val). Falls back to synthetic when files are absent."""
    name = name.lower()
    if name in ("cifar10", "synthetic", "synthetic-cifar10"):
        if name == "cifar10":
            real = _load_cifar10_pickles(root)
            if real is not None:
                return real
        tr = _synthetic(synth_train, (32, 32, 3), 10, seed, seed + 1, "synth-cifar10-train")
        va = _synthetic(synth_val, (32, 32, 3), 10, seed, seed + 2, "synth-cifar10-val")
        return tr, va
    if name in ("mnist", "synthetic-mnist"):
        if name == "mnist":
            real = _load_mnist_idx(root)
            if real is not None:
                return real
        tr = _synthetic(synth_train, (28, 28, 1), 10, seed, seed + 1, "synth-mnist-train")
        va = _synthetic(synth_val, (28, 28, 1), 10, seed, seed + 2, "synth-mnist-val")
        return tr, va
    if name == "imagenet":
        from tpu_dist.data.imagefolder import load_imagefolder
        real = load_imagefolder(root)
        if real is not None:
            return real
        tr = _synthetic(synth_train, (224, 224, 3), 1000, seed, seed + 1, "synth-imagenet-train")
        va = _synthetic(synth_val, (224, 224, 3), 1000, seed, seed + 2, "synth-imagenet-val")
        return tr, va
    raise ValueError(f"unknown dataset {name!r}")
