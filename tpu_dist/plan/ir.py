"""Step-plan IR: ONE declarative object for what used to be a dozen knobs.

The reference repo's whole value proposition is "pick the right
launcher/backend variant for your hardware" (PAPER.md: 5-6 hand-tuned
script variants); rounds 1-14 reproduced that as a combinatorial matrix of
hand-built step builders (``engine/steps.py`` x ``engine/lm_steps.py``:
jit / shard_map / windowed / bucketed / ring / sp, x quant x health x
fused), every new feature touching all of them. :class:`Plan` collapses
the matrix into one declarative record:

* **parallelism layout** — ``layout`` (dp | tp | sp) + ``sync`` (gspmd |
  explicit: compiler-inserted vs hand-written collectives);
* **precision/quant** — ``precision``, ``quant``, ``fused_quant``
  (the ops.pallas_quant kernel switch);
* **overlap** — ``tp_impl`` (gspmd | ring collective matmul),
  ``grad_bucket_mb`` (DDP bucket decomposition), ``steps_per_dispatch`` +
  ``window`` (dispatch amortization);
* **probes/health** — ``health`` (obs.health policy fused into the step);
* **Pallas block sizes** — ``quant_block`` (bm, bn, bk) for the fused
  int8 matmul and ``opt_block_rows`` for the fused optimizer kernels
  (both hard-coded constants through round 14, searchable now).

A Plan is frozen (hashable), JSON-round-trippable, and content-addressed:
:func:`plan_hash` is a sha256 over the canonical JSON, so tuner outputs,
ledger stamps, and bench tags can all name a plan by one stable id.
``plan/compile.py`` lowers a Plan to the actual train/eval step callables;
``plan/tune.py`` searches the plan space against measured artifacts.

THIS MODULE IMPORTS NO JAX (the parallel.supervisor convention): the
``scripts/lint.sh`` plan gate imports it under a jax-import blocker, and
``tools/tune.py`` runs on a login host. The mesh-axis vocabulary is
therefore declared here as :data:`KNOWN_AXES` and pinned against the
``parallel/mesh.py`` authority by AST in tests/test_plan.py (the same
no-import trick distlint's DL003 uses), not imported from it.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from dataclasses import dataclass
from typing import Tuple

PLAN_VERSION = 1

# the mesh-axis vocabulary (parallel/mesh.py *_AXIS authority, mirrored
# jax-free; tests AST-extract mesh.py and assert this tuple matches)
KNOWN_AXES = ("data", "fsdp", "model", "seq", "stage", "expert", "sp")

ENGINES = ("image", "lm")
LAYOUTS = ("dp", "tp", "sp")
SYNCS = ("gspmd", "explicit")
WINDOWS = ("none", "stacked", "indexed")
PRECISIONS = ("fp32", "bf16", "bf16_params")
QUANTS = ("none", "int8", "int8_wo")
FUSED_QUANT = ("auto", "on", "off")
TP_IMPLS = ("gspmd", "ring")
HEALTH = ("record", "skip", "halt")
COMPRESSIONS = ("none", "bf16")

# defaults of the previously hard-coded Pallas tiles (ops.pallas_quant
# BLOCK_M/BLOCK_N, ops.pallas_sgd/pallas_adamw BLOCK_ROWS); bk = 0 means
# "whole contracting dim per grid cell" — the pre-plan behavior
DEFAULT_QUANT_BLOCK = (128, 128, 0)
DEFAULT_OPT_BLOCK_ROWS = 512


class PlanError(ValueError):
    """A plan that names an invalid or inconsistent knob combination."""


def validate_quant_block(bm: int, bn: int, bk: int) -> None:
    """THE (bm, bn, bk) tile legality for the fused int8 kernel — shared
    by :meth:`Plan.validate` and ``ops.pallas_quant.set_quant_blocks``
    (incl. its env seed), so the IR and the kernel can never disagree on
    what a legal tile is. Raises :class:`PlanError`."""
    if bm < 8 or bm % 8:
        raise PlanError(f"quant_block bm={bm}: Mosaic needs a positive "
                        "multiple of the fp32 sublane (8)")
    if bn < 128 or bn % 128:
        raise PlanError(f"quant_block bn={bn}: a positive multiple of "
                        "the lane width (128)")
    if bk != 0 and (bk < 128 or bk % 128):
        raise PlanError(f"quant_block bk={bk}: 0 (whole contracting "
                        "dim) or a positive multiple of 128")


def validate_opt_block_rows(rows: int) -> None:
    """The fused-optimizer row-tile legality — shared by
    :meth:`Plan.validate` and ``ops.pallas_sgd.set_block_rows``."""
    if rows < 8 or rows % 8:
        raise PlanError(f"opt_block_rows={rows}: a positive multiple "
                        "of 8 (fp32 sublane)")


@dataclass(frozen=True)
class Plan:
    """One declarative step plan. Every field is a trace-time-static knob
    of the step compiler; cross-field legality lives in :meth:`validate`
    (the same exclusion rules the engines enforced by hand, in one place).
    """

    engine: str = "lm"                  # image | lm
    # -- parallelism layout
    layout: str = "dp"                  # dp | tp | sp
    sync: str = "gspmd"                 # gspmd (jit/GSPMD) | explicit (shard_map)
    data_axis: str = "data"
    model_axis: str = "model"           # rides with layout='tp'
    seq_axis: str = "seq"               # rides with layout='sp'
    # -- precision / quantization
    precision: str = "fp32"             # fp32 | bf16 | bf16_params (image)
    quant: str = "none"                 # none | int8 | int8_wo (ops.quant)
    fused_quant: str = "auto"           # ops.pallas_quant dispatch: auto|on|off
    # -- comm/compute overlap
    tp_impl: str = "gspmd"              # gspmd | ring (parallel.overlap)
    grad_bucket_mb: float = 0.0         # >0: DDP-style bucketed grad sync
    grad_compression: str = "none"      # none | bf16 (image explicit step)
    predivide_factor: float = 1.0       # horovod predivide (image explicit)
    adasum: bool = False                # Adasum reduction (image explicit)
    # -- dispatch / window
    window: str = "none"                # none | stacked | indexed
    steps_per_dispatch: int = 1         # K steps per dispatch (window != none)
    grad_accum_steps: int = 1           # microbatches per optimizer step
    loss_chunk: int = 0                 # chunked head+CE (lm, ops.fused_xent)
    # -- probes / health
    health: str = "record"              # obs.health policy fused into the step
    # -- objective / memory
    aux_weight: float = 0.01            # MoE aux-loss weight (lm)
    donate: bool = True                 # donate the TrainState buffers
    # -- Pallas block sizes (previously hard-coded)
    quant_block: Tuple[int, int, int] = DEFAULT_QUANT_BLOCK  # (bm, bn, bk)
    opt_block_rows: int = DEFAULT_OPT_BLOCK_ROWS

    # ------------------------------------------------------------------
    def validate(self) -> "Plan":
        """Raise :class:`PlanError` on any invalid field or combination;
        returns self so call sites can chain. These are exactly the
        exclusion rules engine/loop.py + engine/lm_loop.py enforce (one
        home now, so a new mode cannot drift between them)."""
        def _enum(name, value, allowed):
            if value not in allowed:
                raise PlanError(f"plan.{name}={value!r} "
                                f"({'|'.join(map(str, allowed))})")

        _enum("engine", self.engine, ENGINES)
        _enum("layout", self.layout, LAYOUTS)
        _enum("sync", self.sync, SYNCS)
        _enum("window", self.window, WINDOWS)
        _enum("precision", self.precision, PRECISIONS)
        _enum("quant", self.quant, QUANTS)
        _enum("fused_quant", self.fused_quant, FUSED_QUANT)
        _enum("tp_impl", self.tp_impl, TP_IMPLS)
        _enum("health", self.health, HEALTH)
        _enum("grad_compression", self.grad_compression, COMPRESSIONS)
        for name in ("data_axis", "model_axis", "seq_axis"):
            _enum(name, getattr(self, name), KNOWN_AXES)
        if self.engine == "image":
            if self.layout == "sp":
                raise PlanError("layout='sp' (ring attention) is an LM "
                                "layout; the image engine has no sequence "
                                "axis")
            if self.loss_chunk:
                raise PlanError("loss_chunk is an LM knob (chunked head+CE)")
        else:
            if self.adasum or self.grad_compression != "none" \
                    or self.predivide_factor != 1.0:
                raise PlanError("adasum/grad_compression/predivide are "
                                "image explicit-step knobs (the horovod "
                                "surface); the LM explicit step carries "
                                "grad_bucket_mb only")
            if self.precision == "bf16_params":
                raise PlanError("precision='bf16_params' is image-only")
            if self.window == "stacked":
                raise PlanError("window='stacked' is the image engine's "
                                "host-fed K-step window; the LM windowed "
                                "path is 'indexed' (HBM-resident rows)")
        if self.tp_impl == "ring" and not (self.layout == "tp"
                                           and self.sync == "explicit"):
            raise PlanError("tp_impl='ring' is the explicit collective "
                            "matmul: it needs layout='tp' + "
                            "sync='explicit' (a 'model' axis for the "
                            "ppermute rings to ride)")
        if self.layout == "tp" and self.sync == "explicit" \
                and self.tp_impl != "ring":
            raise PlanError("layout='tp' + sync='explicit' IS the ring "
                            "path (tp_impl='ring'); GSPMD TP lowers "
                            "through sync='gspmd'")
        if self.layout == "sp" and self.sync != "explicit":
            raise PlanError("layout='sp' runs ring attention inside "
                            "shard_map; it requires sync='explicit'")
        if self.grad_bucket_mb < 0:
            raise PlanError("grad_bucket_mb must be >= 0")
        if self.grad_bucket_mb > 0:
            if self.sync != "explicit":
                raise PlanError("grad_bucket_mb decomposes the EXPLICIT "
                                "gradient allreduce; it requires "
                                "sync='explicit' (the gspmd flavor's sync "
                                "is GSPMD-scheduled)")
            if self.layout == "sp" or (self.layout == "tp"
                                       and self.engine == "lm"):
                raise PlanError("grad_bucket_mb decomposes the data-axis "
                                "gradient allreduce of replicated params; "
                                "lm tp/sp layouts keep their own sync "
                                "(the image explicit step may bucket over "
                                "'data' while ring-pmean'ing over 'model')")
        if self.adasum and self.grad_bucket_mb > 0:
            raise PlanError("grad_bucket_mb decomposes the mean allreduce; "
                            "adasum replaces it — the two are exclusive")
        if self.adasum and self.grad_compression != "none":
            raise PlanError("adasum replaces the compressed-mean "
                            "allreduce; use grad_compression='none'")
        if self.steps_per_dispatch < 1:
            raise PlanError("steps_per_dispatch must be >= 1")
        if self.grad_accum_steps < 1:
            raise PlanError("grad_accum_steps must be >= 1")
        if self.grad_accum_steps > 1:
            if self.steps_per_dispatch > 1 or self.window != "none":
                raise PlanError("grad_accum_steps and windowed dispatch "
                                "(steps_per_dispatch/window) are mutually "
                                "exclusive")
            if self.sync != "gspmd" or self.layout == "sp":
                raise PlanError("grad_accum_steps > 1 rides the gspmd "
                                "(jit) modes only")
        if self.window != "none" and self.steps_per_dispatch < 1:
            raise PlanError("a windowed plan needs steps_per_dispatch >= 1")
        if self.window == "stacked" and self.sync != "gspmd":
            raise PlanError("window='stacked' is compiler-partitioned "
                            "(sync='gspmd')")
        if self.window == "indexed" and self.engine == "image" \
                and self.sync != "gspmd":
            raise PlanError("the image indexed window is compiler-"
                            "partitioned (sync='gspmd'); routing an "
                            "explicit config through it would drop grad "
                            "compression/predivide semantics")
        if self.loss_chunk < 0:
            raise PlanError("loss_chunk must be >= 0")
        validate_quant_block(*self.quant_block)
        validate_opt_block_rows(self.opt_block_rows)
        return self

    def validate_against_mesh(self, axis_sizes: dict) -> "Plan":
        """Check the plan's layout against a mesh's {axis: size} dict
        (jax-free on purpose — compile passes ``dict(mesh.shape)``)."""
        self.validate()
        for name in set(axis_sizes) - set(KNOWN_AXES):
            raise PlanError(f"mesh axis {name!r} is not in the "
                            f"parallel/mesh.py vocabulary {KNOWN_AXES}")
        if self.data_axis not in axis_sizes:
            raise PlanError(f"plan data_axis {self.data_axis!r} not in "
                            f"mesh axes {tuple(axis_sizes)}")
        if self.layout == "tp" and axis_sizes.get(self.model_axis, 1) < 2:
            raise PlanError(f"layout='tp' needs mesh axis "
                            f"{self.model_axis!r} of size >= 2 "
                            f"(mesh: {axis_sizes})")
        if self.layout == "sp" and axis_sizes.get(self.seq_axis, 1) < 2:
            raise PlanError(f"layout='sp' needs mesh axis "
                            f"{self.seq_axis!r} of size >= 2 "
                            f"(mesh: {axis_sizes})")
        return self

    # ------------------------------------------------------------------
    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["quant_block"] = list(self.quant_block)
        d["version"] = PLAN_VERSION
        return d

    def to_json(self) -> str:
        """Canonical JSON: sorted keys, no whitespace variance — the byte
        stream :func:`plan_hash` digests and the tuner emits."""
        return json.dumps(self.to_dict(), sort_keys=True,
                          separators=(",", ":"))

    @classmethod
    def from_dict(cls, d: dict) -> "Plan":
        d = dict(d)
        version = d.pop("version", PLAN_VERSION)
        if version != PLAN_VERSION:
            raise PlanError(f"plan version {version} != {PLAN_VERSION} "
                            "(re-emit with this tree's tools/tune.py)")
        d.pop("hash", None)    # tuner outputs carry the stamp; recomputed
        d.pop("score", None)   # tuner diagnostics ride beside the knobs
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise PlanError(f"unknown plan field(s) {sorted(unknown)} "
                            f"(known: {sorted(known)})")
        if "quant_block" in d:
            qb = d["quant_block"]
            if not (isinstance(qb, (list, tuple)) and len(qb) == 3):
                raise PlanError(f"quant_block must be [bm, bn, bk], got "
                                f"{qb!r}")
            d["quant_block"] = tuple(int(v) for v in qb)
        return cls(**d).validate()

    @classmethod
    def from_json(cls, s: str) -> "Plan":
        return cls.from_dict(json.loads(s))


def plan_hash(plan: Plan) -> str:
    """Content address of a plan: sha256 over the canonical JSON (12 hex
    chars — enough to tag benches/ledgers, short enough to read)."""
    return hashlib.sha256(plan.to_json().encode()).hexdigest()[:12]


# ---- plan files -----------------------------------------------------------
# The tuner emits {"version", "plans": {"<device_kind>": {...plan...}}};
# a bare single-plan object {"engine": ...} is accepted too (hand-written
# plans). select-by-device-kind falls back to a "default" entry.

def load_plan_file(path: str) -> dict:
    """Parse a plan JSON file into {device_kind: Plan}. Accepts the tuner
    output shape or one bare plan object (keyed as 'default')."""
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        raise PlanError(f"{path}: not a JSON object")
    if "plans" in doc:
        plans = doc["plans"]
        if not isinstance(plans, dict) or not plans:
            raise PlanError(f"{path}: 'plans' must be a non-empty object "
                            "of device_kind -> plan")
        return {k: Plan.from_dict(v) for k, v in plans.items()}
    return {"default": Plan.from_dict(doc)}


def plan_for_device(plans: dict, device_kind: str) -> Plan:
    """Pick the plan for ``device_kind``: exact key, then substring match
    (the PEAK_TFLOPS table convention — 'v5 lite' matches
    'TPU v5 lite'), then the 'default' entry."""
    if device_kind in plans:
        return plans[device_kind]
    kind = (device_kind or "").lower()
    for key, plan in sorted(plans.items()):
        if key != "default" and key.lower() in kind:
            return plan
    if "default" in plans:
        return plans["default"]
    raise PlanError(f"no plan for device kind {device_kind!r} and no "
                    f"'default' entry (have: {sorted(plans)})")


# ---- plan -> config -------------------------------------------------------

# config fields a plan owns, by engine; everything else in the config
# (data paths, schedules, observability) is run-level, not plan-level
_SHARED_FIELDS = ("quant", "tp_impl", "grad_bucket_mb", "steps_per_dispatch",
                  "grad_accum_steps", "health", "precision")
_LM_FIELDS = _SHARED_FIELDS + ("loss_chunk",)
_IMAGE_FIELDS = _SHARED_FIELDS + ("grad_compression", "adasum")


def apply_plan_to_config(cfg, plan: Plan):
    """dataclasses.replace the plan-owned knobs into a TrainConfig/LMConfig
    (pure: no jax, no global state — the fused-kernel/block activation is
    plan.compile.activate_plan's job). Returns the new config."""
    plan.validate()
    fields = {f.name for f in dataclasses.fields(type(cfg))}
    is_image = "variant" in fields      # TrainConfig carries the jit/
    #                                     shard_map flavor tag; LMConfig
    #                                     picks the mode from the mesh
    want = _IMAGE_FIELDS if is_image else _LM_FIELDS
    if is_image and plan.engine != "image":
        raise PlanError(f"plan engine {plan.engine!r} applied to a "
                        "TrainConfig (image engine)")
    if not is_image and plan.engine != "lm":
        raise PlanError(f"plan engine {plan.engine!r} applied to an "
                        "LMConfig")
    updates = {k: getattr(plan, k) for k in want if k in fields}
    if is_image:
        updates["variant"] = ("shard_map" if plan.sync == "explicit"
                              else "jit")
        updates["gradient_predivide_factor"] = plan.predivide_factor
    if plan.window == "indexed":
        updates["data_placement"] = "device"
    return dataclasses.replace(cfg, **updates)


def plan_knob_summary(plan: Plan) -> dict:
    """The compact non-default knob view stamped into ledgers and bench
    headlines (full plans live in the plan file; records carry the diff)."""
    base = Plan(engine=plan.engine)
    return {k: v for k, v in plan.to_dict().items()
            if k != "version" and v != getattr(
                base, k, None) and not (k == "quant_block"
                                        and tuple(v) == base.quant_block)}
