"""Hardware auto-tuner over the step-plan space (ROADMAP item 2's search).

The Alpa/AutoTVM shape — enumerate a layout x schedule space, score each
candidate against a cost model, optionally refine with measured trials —
specialized to this repo's measured artifacts:

* the **roofline cost model** (PR 6): analytic compute/memory seconds per
  step at the device peaks (``utils.mfu.PEAK_TFLOPS`` +
  ``obs.attr.PEAK_GBPS`` — both importable jax-free);
* **``tools/comm_bench.py --json`` sweeps**: measured ring-vs-psum,
  bucketed-vs-monolithic and ring-vs-GSPMD-matmul seconds, interpolated to
  the workload's gradient/activation bytes;
* **ledger-read trials** (``tools/ledger_report.py --json`` MFU /
  ``data_s`` / ``comm_s``, or a ``trials`` list in the measurement file):
  a measured step time for a knob subset OVERRIDES the analytic estimate
  for every candidate matching it — short real runs sharpen the search
  where the model is crude.

Determinism is a hard contract (the ``scripts/lint.sh`` plan gate runs
the tuner twice over a canned file and asserts byte-identical output):
the space enumerates in one fixed order, scores are pure arithmetic
rounded once at the end, and ties break on the candidate's plan hash.

THIS MODULE IMPORTS NO JAX — it runs on a login host, in CI, and under
the lint gate's jax-import blocker. The device is a *string* (device
kind) matched against the peak tables, exactly like the roofline section.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

from tpu_dist.plan.ir import (DEFAULT_OPT_BLOCK_ROWS, DEFAULT_QUANT_BLOCK,
                              Plan, PlanError, plan_hash, plan_knob_summary)

TUNE_VERSION = 1

# int8 MXU dots run up to 2x the bf16 rate, but ONLY when the quantize/
# dequant ladder stays in VMEM (the fused Pallas kernel, PR 9); the
# reference einsum path pays int8/int32 HBM round trips that eat the gain
# (BASELINE.md round-9 measurement). Encoded as compute-peak factors.
_COMPUTE_FACTOR = {
    ("none", False): 1.0, ("none", True): 1.0,
    ("int8", False): 1.0, ("int8", True): 2.0,
    ("int8_wo", False): 1.0, ("int8_wo", True): 1.0,
}
# weight-only int8 halves the per-step weight traffic (the memory-bound
# lever); full int8 halves the matmul operand traffic only when fused
# (no intermediates), modeled conservatively
_WEIGHT_BYTES_FACTOR = {
    ("none", False): 1.0, ("none", True): 1.0,
    ("int8", False): 1.0, ("int8", True): 0.5,
    ("int8_wo", False): 0.5, ("int8_wo", True): 0.5,
}

# per-dispatch host latency the window amortizes (seconds; the remote-
# controller figure the K-step window exists for — BASELINE.md round 3)
_DISPATCH_S = 2e-3
# fraction of the bucketed grad sync the XLA scheduler overlaps with
# compute (DDP's design point; the monolithic allreduce overlaps nothing)
_BUCKET_OVERLAP = 0.7

# compute-peak table (bf16 TFLOP/s) + HBM GB/s, matched by substring —
# the SAME tables the roofline uses (imported, not duplicated)
from tpu_dist.obs.attr import PEAK_GBPS        # noqa: E402


def _peak_tflops_table():
    """utils.mfu.PEAK_TFLOPS — via the file itself when jax is absent:
    mfu.py's module body is stdlib-only, but the ``tpu_dist.utils``
    PACKAGE __init__ imports the jax-bound meters, which the lint gate's
    no-jax blocker (rightly) refuses."""
    try:
        from tpu_dist.utils.mfu import PEAK_TFLOPS
        return PEAK_TFLOPS
    except ImportError:
        import importlib.util
        import os
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "utils", "mfu.py")
        spec = importlib.util.spec_from_file_location("_tpu_dist_mfu", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod.PEAK_TFLOPS


_FALLBACK_TFLOPS = 1.0   # nominal peaks keep CPU/virtual runs rankable
_FALLBACK_GBPS = 1.0     # (the TPU_DIST_NOMINAL_* convention)


def _peak_for(kind: str, table) -> Optional[float]:
    kind = (kind or "").lower()
    for key, peak in table:
        if key in kind:
            return peak
    return None


def device_peaks(device_kind: str) -> dict:
    """{'tflops', 'gbps', 'nominal'} for a device-kind string."""
    tf = _peak_for(device_kind, _peak_tflops_table())
    gb = _peak_for(device_kind, PEAK_GBPS)
    return {"tflops": tf or _FALLBACK_TFLOPS, "gbps": gb or _FALLBACK_GBPS,
            "nominal": tf is None or gb is None}


# ---- workload -------------------------------------------------------------

_WORKLOAD_DEFAULTS = {
    # the r06 LM bench geometry (bench.py BENCH_* defaults): 8 layers,
    # d1024, seq 2048, vocab 32k — flops/bytes derived below
    "engine": "lm", "n_params": 113_000_000, "tokens_per_step": 16_384,
    "devices": 8, "seq_len": 2048,
}


def normalize_workload(workload: Optional[dict]) -> dict:
    """Fill a workload spec: n_params / tokens_per_step / devices (+
    optional flops_per_step / bytes_per_step overrides). Derivations are
    the repo's own accounting: 6*N fwd+bwd model FLOPs per token
    (utils.mfu), 3 passes of fp32 param traffic per step + one grad sync
    payload (param bytes)."""
    w = dict(_WORKLOAD_DEFAULTS)
    w.update(workload or {})
    n = float(w["n_params"])
    toks = float(w["tokens_per_step"])
    w.setdefault("flops_per_step", 6.0 * n * toks)
    w.setdefault("param_bytes", 4.0 * n)
    # fwd reads W, bwd reads W and writes dW, update reads+writes P/opt:
    # ~3 full weight passes per optimizer step — the memory-bound floor
    w.setdefault("bytes_per_step", 3.0 * w["param_bytes"])
    w.setdefault("grad_sync_bytes", w["param_bytes"])
    return w


# ---- measurements ---------------------------------------------------------

def _interp_seconds(rows: List[dict], key_s: str, nbytes: float,
                    size_key: str = "bytes") -> Optional[float]:
    """Seconds for ``nbytes`` from comm_bench rows: effective GB/s of the
    nearest-sized measurement, scaled linearly (collectives are bandwidth-
    bound at these sizes)."""
    usable = [r for r in rows if r.get(key_s) and r.get(size_key)]
    if not usable:
        return None
    near = min(usable, key=lambda r: (abs(r[size_key] - nbytes), r[size_key]))
    return near[key_s] * (nbytes / near[size_key])


def comm_estimates(measurements: Optional[dict], workload: dict) -> dict:
    """Per-plan-knob comm seconds from a comm_bench --json sweep:
    {'sync_monolithic_s', 'sync_bucketed_s', 'matmul_ring_ratio'}.
    Absent measurements -> empty dict (the analytic model abstains from
    comm rather than invent numbers)."""
    out: dict = {}
    rows = (measurements or {}).get("results") or []
    gbytes = workload["grad_sync_bytes"]
    grad = [r for r in rows if r.get("bench") == "grad_sync"]
    allr = [r for r in rows if r.get("bench") == "allreduce"]
    mono = _interp_seconds(grad, "monolithic_s", gbytes) \
        or _interp_seconds(allr, "psum_s", gbytes)
    buck = _interp_seconds(grad, "bucketed_s", gbytes)
    if mono is not None:
        out["sync_monolithic_s"] = mono
    if buck is not None:
        out["sync_bucketed_s"] = buck
    mm = [r for r in rows if r.get("bench") == "collective_matmul"
          and r.get("ring_s") and r.get("gspmd_s")]
    if mm:
        out["matmul_ring_ratio"] = (sum(r["ring_s"] for r in mm)
                                    / sum(r["gspmd_s"] for r in mm))
    return out


def _trial_matches(trial_knobs: dict, plan: Plan) -> bool:
    d = plan.to_dict()
    for k, v in trial_knobs.items():
        have = d.get(k)
        if isinstance(have, (list, tuple)):
            have, v = list(have), list(v)
        if have != v:
            return False
    return True


def trial_step_seconds(trials: List[dict], plan: Plan,
                       workload: dict) -> Optional[float]:
    """Measured step seconds for ``plan`` from refinement trials: entries
    are {'knobs': {...subset...}, 'step_s': float} or {'knobs', 'mfu'}
    (converted through the workload's flops at the device peak by the
    caller). The MOST SPECIFIC matching trial (largest knob subset) wins;
    ties break on list order."""
    best = None
    best_n = -1
    for t in trials or []:
        knobs = t.get("knobs") or {}
        if t.get("plan_hash") and t["plan_hash"] != plan_hash(plan):
            continue
        if not _trial_matches(knobs, plan):
            continue
        n = len(knobs) + (100 if t.get("plan_hash") else 0)
        if n > best_n and t.get("step_s"):
            best, best_n = float(t["step_s"]), n
    return best


def trials_from_ledger_summaries(summaries: List[dict],
                                 workload: dict,
                                 peaks: dict) -> List[dict]:
    """Convert ledger_report --json summaries of short measured runs into
    refinement trials: a summary whose run_start stamped a plan
    (``run.plan_knobs``/``run.plan_hash``, PR 15) and reported a mean MFU
    becomes {'knobs'|'plan_hash', 'step_s'} through the workload's
    per-device flops at the device compute peak."""
    out = []
    flops_dev = workload["flops_per_step"] / max(workload["devices"], 1)
    for s in summaries or []:
        run = s.get("run") or {}
        mfu = (s.get("mfu") or {}).get("mean")
        if mfu is None or not (run.get("plan_knobs")
                               or run.get("plan_hash")):
            continue
        step_s = flops_dev / (mfu * peaks["tflops"] * 1e12)
        t = {"step_s": step_s}
        if run.get("plan_hash"):
            t["plan_hash"] = run["plan_hash"]
        t["knobs"] = run.get("plan_knobs") or {}
        out.append(t)
    return out


# ---- the cost model -------------------------------------------------------

def estimate_step_seconds(plan: Plan, workload: dict, peaks: dict,
                          comm: dict) -> dict:
    """Analytic roofline estimate of one optimizer step under ``plan``:
    {'compute_s', 'memory_s', 'comm_s', 'dispatch_s', 'total_s'}. The
    absolute numbers are crude by design — the tuner RANKS candidates, so
    only the knob-to-knob deltas must point the right way, and measured
    trials override whole candidates where they exist."""
    fused = (plan.quant == "int8"
             and plan.fused_quant in ("on", "auto"))  # auto = on-TPU
    cf = _COMPUTE_FACTOR[(plan.quant, fused)]
    wf = _WEIGHT_BYTES_FACTOR[(plan.quant, fused)]
    ndev = max(workload["devices"], 1)
    flops = workload["flops_per_step"] / ndev
    nbytes = workload["bytes_per_step"] * wf   # per-device: params replicate
    compute_s = flops / (peaks["tflops"] * 1e12 * cf)
    memory_s = nbytes / (peaks["gbps"] * 1e9)
    # comm: the dp grad sync (per step), overlapped when bucketed
    comm_s = 0.0
    if ndev > 1:
        if plan.grad_bucket_mb > 0 and "sync_bucketed_s" in comm:
            comm_s = comm["sync_bucketed_s"] * (1.0 - _BUCKET_OVERLAP)
        elif "sync_monolithic_s" in comm:
            comm_s = comm["sync_monolithic_s"]
    device_s = max(compute_s, memory_s)
    if plan.layout == "tp" and plan.tp_impl == "ring" \
            and "matmul_ring_ratio" in comm:
        # ring overlap measured against GSPMD at the matmul geometry:
        # scale the whole device block by the measured ratio
        device_s *= comm["matmul_ring_ratio"]
    dispatch_s = _DISPATCH_S / max(plan.steps_per_dispatch, 1)
    total = device_s + comm_s + dispatch_s
    return {"compute_s": compute_s, "memory_s": memory_s,
            "comm_s": comm_s, "dispatch_s": dispatch_s, "total_s": total}


# ---- the search -----------------------------------------------------------

def default_space(engine: str = "lm", devices: int = 8) -> List[Plan]:
    """The enumerated candidate space, in ONE fixed order (determinism
    contract). Kept deliberately small — every dimension here is a knob a
    user used to hand-pick; the tuner's job is the cross product."""
    plans: List[Plan] = []
    quants = ("none", "int8")
    fused = ("auto", "off")
    buckets = (0.0, 25.0)
    windows = ((("none", 1),) if devices < 2 else
               (("none", 1), ("indexed", 16)))
    qblocks = (DEFAULT_QUANT_BLOCK, (256, 128, 0), (128, 256, 0),
               (128, 128, 512))
    oblocks = (DEFAULT_OPT_BLOCK_ROWS, 1024)
    for quant in quants:
        for fq in (fused if quant == "int8" else ("auto",)):
            for bucket in buckets:
                for window, k in windows:
                    for qb in (qblocks if quant == "int8"
                               else (DEFAULT_QUANT_BLOCK,)):
                        for ob in oblocks:
                            try:
                                plans.append(Plan(
                                    engine=engine,
                                    sync=("explicit" if bucket > 0
                                          else "gspmd"),
                                    quant=quant, fused_quant=fq,
                                    grad_bucket_mb=bucket,
                                    window=window, steps_per_dispatch=k,
                                    quant_block=qb, opt_block_rows=ob,
                                ).validate())
                            except PlanError:
                                continue   # illegal combination: pruned
    return plans


def search(workload: Optional[dict] = None,
           device_kind: str = "",
           measurements: Optional[dict] = None,
           trials: Optional[List[dict]] = None,
           space: Optional[List[Plan]] = None) -> dict:
    """Score the plan space and return the full deterministic result:
    {'device_kind', 'peaks', 'workload', 'candidates', 'best', 'ranked'}.
    ``measurements`` is a comm_bench --json object; ``trials`` the
    measured-refinement list (see :func:`trial_step_seconds`)."""
    workload = normalize_workload(workload)
    device_kind = device_kind or (measurements or {}).get(
        "device_kind") or "unknown"
    peaks = device_peaks(device_kind)
    comm = comm_estimates(measurements, workload)
    trials = list(trials or []) + list((measurements or {}).get(
        "trials") or [])
    space = space if space is not None else default_space(
        workload["engine"], int(workload["devices"]))
    scored = []
    for plan in space:
        est = estimate_step_seconds(plan, workload, peaks, comm)
        measured = trial_step_seconds(trials, plan, workload)
        total = measured if measured is not None else est["total_s"]
        scored.append({
            "plan": plan, "hash": plan_hash(plan),
            "step_s": round(total, 9), "measured": measured is not None,
            "estimate": {k: round(v, 9) for k, v in est.items()},
        })
    # deterministic order: score, then hash (pure tie-break)
    scored.sort(key=lambda c: (c["step_s"], c["hash"]))
    return {"device_kind": device_kind, "peaks": peaks,
            "workload": {k: workload[k] for k in sorted(workload)},
            "candidates": len(scored), "comm": {k: round(v, 9)
                                                for k, v in comm.items()},
            "best": scored[0] if scored else None, "ranked": scored}


def emit_plan_file(results: Dict[str, dict]) -> str:
    """Serialize {device_kind: search-result} as the best-plan-per-device
    JSON the config knob consumes — canonical bytes (sorted keys, fixed
    rounding), so two identical searches emit identical files."""
    plans = {}
    for kind in sorted(results):
        best = results[kind]["best"]
        if best is None:
            continue
        entry = best["plan"].to_dict()
        entry["hash"] = best["hash"]
        entry["score"] = {
            "step_s": best["step_s"], "measured": best["measured"],
            "candidates": results[kind]["candidates"],
            "peaks_nominal": results[kind]["peaks"]["nominal"],
        }
        plans[kind] = entry
    return json.dumps({"version": TUNE_VERSION, "plans": plans},
                      sort_keys=True, indent=1) + "\n"


def tune(measurement_files: Optional[List[str]] = None,
         ledger_summary_files: Optional[List[str]] = None,
         device_kinds: Optional[List[str]] = None,
         workload: Optional[dict] = None) -> Tuple[str, Dict[str, dict]]:
    """The tools/tune.py entry: load measurement/summary files, search per
    device kind, return (plan-file text, {kind: full result})."""
    measurements = None
    for path in measurement_files or []:
        with open(path) as f:
            doc = json.load(f)
        if measurements is None:
            measurements = doc
        else:  # later files extend the sweep + trials
            measurements.setdefault("results", []).extend(
                doc.get("results") or [])
            measurements.setdefault("trials", []).extend(
                doc.get("trials") or [])
    summaries = []
    for path in ledger_summary_files or []:
        with open(path) as f:
            summaries.append(json.load(f))
    kinds = device_kinds or [(measurements or {}).get("device_kind")
                             or "unknown"]
    results = {}
    for kind in kinds:
        w = normalize_workload(workload)
        peaks = device_peaks(kind)
        trials = trials_from_ledger_summaries(summaries, w, peaks)
        results[kind] = search(workload=w, device_kind=kind,
                               measurements=measurements, trials=trials)
    return emit_plan_file(results), results
