"""tpu_dist.plan — step-plan IR, compiler, and hardware auto-tuner.

Lazy (PEP 562) like ``tpu_dist.parallel``: ``plan.ir`` and ``plan.tune``
are stdlib-only and must import under the scripts/lint.sh jax-import
blocker; ``plan.compile`` (the lowerer) pulls jax and is resolved only
when asked for.
"""

from __future__ import annotations

import importlib

# the submodules themselves resolve FIRST (``from tpu_dist.plan import
# tune`` must yield the module, not the re-exported tune() function —
# the import machinery's _handle_fromlist getattr would otherwise recurse)
_SUBMODULES = ("ir", "tune", "compile")

_IR = ("Plan", "PlanError", "plan_hash", "load_plan_file",
       "plan_for_device", "apply_plan_to_config", "plan_knob_summary",
       "KNOWN_AXES")
_TUNE = ("search", "default_space", "device_peaks",
         "estimate_step_seconds", "emit_plan_file")
_COMPILE = ("compile_plan", "Bindings", "CompiledPlan", "activate_plan",
            "resolve_config_plan")

__all__ = list(_IR + _TUNE + _COMPILE)


def __getattr__(name):
    if name in _SUBMODULES:
        return importlib.import_module(f"tpu_dist.plan.{name}")
    if name in _IR:
        return getattr(importlib.import_module("tpu_dist.plan.ir"), name)
    if name in _TUNE:
        return getattr(importlib.import_module("tpu_dist.plan.tune"), name)
    if name in _COMPILE:
        return getattr(importlib.import_module("tpu_dist.plan.compile"),
                       name)
    raise AttributeError(f"module 'tpu_dist.plan' has no attribute {name!r}")
