"""Plan compiler: lower a :class:`tpu_dist.plan.ir.Plan` to step callables.

ONE pass pipeline replaces the hand-built step-builder matrix (PR 15):

1. **validate** — :meth:`Plan.validate` + the mesh-axis check (the same
   exclusion rules the engines enforced ad hoc);
2. **template** — pick the engine's pure step function (the ONE step
   template per engine: ``engine/steps.py:_train_step_fn`` for images,
   ``engine/lm_steps.py:_lm_step_fn`` and its explicit/ring/sp per-device
   flavors for tokens — the templates stay in the engine modules, the
   compiler composes them);
3. **window** — optionally wrap the template in a ``lax.scan`` dispatch
   window (host-fed stacked batches, or HBM-resident indexed gathers with
   the engine's gather prelude);
4. **partition** — ``jit`` with GSPMD shardings (``sync='gspmd'``) or
   ``shard_map`` + ``jit`` with explicit specs (``sync='explicit'`` /
   ``layout='sp'``).

The legacy ``make_*`` builders in ``engine/steps.py`` and
``engine/lm_steps.py`` are now thin shims over :func:`compile_plan`
(loss/param parity pinned bit-for-bit in tests/test_plan.py): every
wrapper body that used to live in a ``make_*`` lives HERE, once.

``activate_plan`` applies a plan's global trace-time switches (fused
int8 kernel, Pallas block sizes) and ``resolve_config_plan`` implements
the configs' ``plan: auto|<path>|none`` knob for both engines.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_dist._compat import shard_map
from tpu_dist.engine.state import TrainState
from tpu_dist.plan.ir import (Plan, PlanError, apply_plan_to_config,
                              plan_hash, plan_knob_summary)


@dataclass
class Bindings:
    """What a plan lowers AGAINST: the run's concrete objects. The model
    binding must already embody the plan's quant/tp_impl (flax modules
    bake those in at construction — the engines build them from the same
    config the plan was applied to)."""

    mesh: Mesh
    model: Any = None                 # flax module (non-sp paths)
    model_ctor: Optional[Callable] = None  # sp: ctor(attn_fn=...) -> model
    tx: Any = None                    # optimizer (optax or fused protocol)
    transform: Optional[Callable] = None       # image train transform
    eval_transform: Optional[Callable] = None  # image eval transform
    image_shape: Optional[Tuple[int, int, int]] = None  # indexed image paths
    explicit_step_fn: Optional[Callable] = None  # pre-built per-device step
    #                                    (the lm explicit window wrapper)


class CompiledPlan:
    """Lazy pair of compiled callables for one (plan, bindings):
    ``train_step`` and ``eval_step`` lower on first access (a maker shim
    that only needs one never builds the other)."""

    def __init__(self, plan: Plan, binds: Bindings):
        _pass_validate(plan, binds)
        self.plan = plan
        self.binds = binds
        self._train = None
        self._eval = None

    @property
    def train_step(self) -> Callable:
        if self._train is None:
            self._train = _lower_train(self.plan, self.binds)
        return self._train

    @property
    def eval_step(self) -> Callable:
        if self._eval is None:
            self._eval = _lower_eval(self.plan, self.binds)
        return self._eval


def compile_plan(plan: Plan, binds: Bindings) -> CompiledPlan:
    """THE entry point: validate + return the lazy compiled pair."""
    return CompiledPlan(plan, binds)


def compile_train_step(plan: Plan, binds: Bindings) -> Callable:
    """Validate + lower the train step directly (the make_* shim entry:
    a plain `return compile_train_step(...)` chain keeps the builders
    inside distlint's jit-factory fixpoint, so the engines' loops still
    derive as hot — an attribute hop through CompiledPlan would not)."""
    _pass_validate(plan, binds)
    return _lower_train(plan, binds)


def compile_eval_step(plan: Plan, binds: Bindings) -> Callable:
    """Validate + lower the eval step directly (compile_train_step's
    forward-only twin)."""
    _pass_validate(plan, binds)
    return _lower_eval(plan, binds)


# ---- pass 1: validate -----------------------------------------------------

def _pass_validate(plan: Plan, binds: Bindings) -> None:
    plan.validate()
    if binds.mesh is None:
        raise PlanError("Bindings.mesh is required")
    plan.validate_against_mesh(dict(binds.mesh.shape))
    if plan.layout == "sp" and binds.model_ctor is None:
        raise PlanError("layout='sp' lowers a model_ctor(attn_fn=...) — "
                        "the ring attention binds per seq axis")
    if plan.engine == "image" and binds.model is not None \
            and binds.transform is None and binds.tx is not None:
        raise PlanError("the image templates need a transform binding")


# ---- program audit (tpu_dist.analysis.proglint) ---------------------------
# A module-level switch in the activate_plan mold: the engines arm it from
# cfg.audit before their first dispatch, the partition helpers below
# REGISTER every program they mint as a side effect (never a wrapper — an
# attribute hop would take the builders out of distlint's jit-factory
# fixpoint and DL002's hot-loop derivation with it), and the engines run
# the compile-time pass at the same first-dispatch probe that already
# lowers the program for telemetry. The runtime half (the recompile
# sentry) is a host-only counter read at the drain boundaries.

AUDIT_MODES = ("none", "record", "halt")

_AUDIT = {"mode": "none", "ledger": None, "sentry": None}


def set_audit(mode: str, ledger=None) -> None:
    """Arm (or disarm) the program audit for this process. ``record``
    emits ``audit`` ledger events; ``halt`` additionally raises
    :class:`~tpu_dist.analysis.proglint.AuditError` on any unwaivered
    finding. A fresh sentry per call: each run watches its own caches."""
    mode = mode or "none"
    if mode not in AUDIT_MODES:
        raise ValueError(f"audit={mode!r}: pick one of {AUDIT_MODES}")
    _AUDIT["mode"], _AUDIT["ledger"] = mode, ledger
    if mode == "none":
        _AUDIT["sentry"] = None
    else:
        from tpu_dist.analysis.proglint import RecompileSentry

        _AUDIT["sentry"] = RecompileSentry()


def audit_mode() -> str:
    return _AUDIT["mode"]


def register_audit_program(program: str, fn, allowed: int = 1) -> None:
    """Put a jitted program under the recompile sentry (PL005).
    ``allowed`` is its legal trace-cache size — 1 for fixed-shape step
    programs, the bucket count for deliberately shape-specializing ones
    (serve prefill). No-op when the audit is off."""
    if _AUDIT["sentry"] is not None:
        _AUDIT["sentry"].register(program, fn, allowed)


def _emit_audit(program: str, findings) -> None:
    led = _AUDIT["ledger"]
    if led is not None:
        led.emit("audit", program=program, mode=_AUDIT["mode"],
                 findings=len([f for f in findings if not f.waived]),
                 waived=len([f for f in findings if f.waived]),
                 detail=[f.to_json() for f in findings] or None)


def audit_program(program: str, fn, *args, hlo=None, precision=None,
                  allowed: int = 1):
    """The compile-time pass over ONE program: retrace abstractly
    (make_jaxpr — no compile, no execution), run the jaxpr checks, check
    donation against the caller's already-compiled HLO text (the
    telemetry.program_stats artifact — zero extra lowering), register
    the program with the sentry, and emit exactly one ``audit`` ledger
    event. Returns the (waiver-applied) findings; raises AuditError
    under ``halt`` when any survive."""
    if _AUDIT["mode"] == "none":
        return []
    from tpu_dist.analysis import proglint

    register_audit_program(program, fn, allowed)
    closed = jax.make_jaxpr(fn)(*args)
    findings = proglint.audit_jaxpr(program, closed,
                                    precision=precision, hlo=hlo)
    waivers, meta = proglint.load_waivers()
    findings = proglint.apply_waivers(findings, waivers) + meta
    _emit_audit(program, findings)
    bad = proglint.unwaivered(findings)
    if bad and _AUDIT["mode"] == "halt":
        raise proglint.AuditError(
            "audit=halt: " + "; ".join(f.render() for f in bad))
    return findings


def check_audit_sentry() -> None:
    """The drain-boundary PL005 check: one host-side ``_cache_size``
    read per registered program (no device sync — DL002 stays clean).
    Findings latch per program, so ``record`` emits exactly one
    ``audit`` event per offender; ``halt`` raises on unwaivered ones."""
    sentry = _AUDIT["sentry"]
    if sentry is None:
        return
    findings = sentry.check()
    if not findings:
        return
    from tpu_dist.analysis import proglint

    waivers, _ = proglint.load_waivers()
    findings = proglint.apply_waivers(findings, waivers)
    for f in findings:
        _emit_audit(f.program, [f])
    bad = proglint.unwaivered(findings)
    if bad and _AUDIT["mode"] == "halt":
        raise proglint.AuditError(
            "audit=halt: " + "; ".join(f.render() for f in bad))


# ---- pass 4 helpers: partition --------------------------------------------

def _jit_gspmd(fn, in_shardings, out_shardings, donate: bool):
    jf = jax.jit(fn, in_shardings=in_shardings,
                 out_shardings=out_shardings,
                 donate_argnums=(0,) if donate else ())
    register_audit_program(getattr(fn, "__name__", "step"), jf)
    return jf


def _shard_map_jit(fn, mesh, in_specs, out_specs, donate: bool):
    sharded = shard_map(fn, mesh=mesh, in_specs=in_specs,
                        out_specs=out_specs, check_vma=False)
    jf = jax.jit(sharded, donate_argnums=(0,) if donate else ())
    register_audit_program(getattr(fn, "__name__", "step"), jf)
    return jf


# ---- image lowerings ------------------------------------------------------

def _image_accum_train(plan: Plan, b: Bindings) -> Callable:
    """ONE optimizer step from K microbatches (the grad-accum template;
    the steps.py make_grad_accum_train_step body, verbatim)."""
    from tpu_dist.engine.steps import _apply_update, _loss_and_metrics

    mesh, model, tx, transform = b.mesh, b.model, b.tx, b.transform
    health = plan.health
    repl = NamedSharding(mesh, P())
    batch_sh = NamedSharding(mesh, P(None, plan.data_axis))

    def step(state: TrainState, images_u8, labels, rng):
        k = images_u8.shape[0]
        dropout_rng, aug_rng = jax.random.split(
            jax.random.fold_in(rng, state.step))

        def micro(carry, batch):
            grads_acc, stats, i = carry
            imgs, lbls = batch
            d_rng = jax.random.fold_in(dropout_rng, i)
            a_rng = jax.random.fold_in(aug_rng, i)
            grad_fn = jax.value_and_grad(
                lambda p: _loss_and_metrics(model, transform, p, stats,
                                            imgs, lbls, d_rng, a_rng,
                                            state.loss_scale, True),
                has_aux=True)
            (_, (new_stats, metrics)), grads = grad_fn(state.params)
            grads_acc = jax.tree.map(lambda a, g: a + g / k, grads_acc,
                                     grads)
            return (grads_acc, new_stats, i + 1), metrics

        zeros = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32),
                             state.params)
        (grads, new_stats, _), metrics_k = jax.lax.scan(
            micro, (zeros, state.batch_stats, jnp.int32(0)),
            (images_u8, labels))
        metrics = jax.tree.map(lambda m: jnp.sum(m, axis=0), metrics_k)
        return _apply_update(tx, state, grads, new_stats, metrics, health)

    return _jit_gspmd(step, (None, batch_sh, batch_sh, repl), (None, repl),
                      plan.donate)


def _image_explicit_train(plan: Plan, b: Bindings) -> Callable:
    """Explicit-collective image step (the make_shard_map_train_step
    per-device body, verbatim): horovod allreduce with predivide /
    compression / Adasum / DDP bucket decomposition / ring-TP pmean."""
    from tpu_dist.engine.steps import _apply_update, _loss_and_metrics
    from tpu_dist.parallel.collectives import compress_grads

    mesh, model, tx, transform = b.mesh, b.model, b.tx, b.transform
    data_axis = plan.data_axis
    health = plan.health
    grad_compression = plan.grad_compression
    predivide_factor = plan.predivide_factor
    adasum = plan.adasum
    grad_bucket_mb = plan.grad_bucket_mb
    model_axis = plan.model_axis if plan.tp_impl == "ring" else None
    nrep = mesh.shape[data_axis]

    def per_device(state: TrainState, images_u8, labels, rng):
        dropout_rng, aug_rng = jax.random.split(
            jax.random.fold_in(jax.random.fold_in(rng, state.step),
                               jax.lax.axis_index(data_axis)))
        grad_fn = jax.value_and_grad(
            lambda p: _loss_and_metrics(model, transform, p,
                                        state.batch_stats, images_u8,
                                        labels, dropout_rng, aug_rng,
                                        state.loss_scale, True),
            has_aux=True)
        (_, (new_stats, metrics)), grads = grad_fn(state.params)
        if model_axis is not None:
            # ring TP: params are replicated over the model axis while the
            # per-device losses are identical across it — the mean restores
            # the single-loss gradient (overlap.py scaling note)
            grads = jax.tree.map(
                lambda g: jax.lax.pmean(g, model_axis), grads)
        if adasum:
            from tpu_dist.parallel.collectives import adasum_reduce
            grads = adasum_reduce(grads, data_axis, nrep)
        else:
            # horovod allreduce: predivide -> (compress) -> psum -> postdivide
            pre = predivide_factor if predivide_factor != 1.0 else nrep
            grads = jax.tree.map(lambda g: g / pre, grads)
            down, up = compress_grads(grads, grad_compression)
            if grad_bucket_mb > 0:
                from tpu_dist.parallel.overlap import bucketed_grad_sync
                down = bucketed_grad_sync(down, data_axis, grad_bucket_mb,
                                          mean=False, axis_size=nrep)
            else:
                down = jax.tree.map(lambda g: jax.lax.psum(g, data_axis),
                                    down)
            grads = up(down)
            if predivide_factor != 1.0:
                grads = jax.tree.map(lambda g: g * (predivide_factor / nrep),
                                     grads)
        # per-replica BN stats -> pmean (≈ horovod local BN + periodic sync)
        new_stats = jax.tree.map(lambda s: jax.lax.pmean(s, data_axis),
                                 new_stats)
        metrics = jax.tree.map(lambda m: jax.lax.psum(m, data_axis), metrics)
        return _apply_update(tx, state, grads, new_stats, metrics, health)

    return _shard_map_jit(per_device, mesh,
                          (P(), P(data_axis), P(data_axis), P()),
                          (P(), P()), plan.donate)


def _image_train(plan: Plan, b: Bindings) -> Callable:
    """The gspmd image train lowerings: plain jit, stacked K-step window,
    or HBM-resident indexed window around ONE template
    (engine.steps._train_step_fn)."""
    from tpu_dist.engine.steps import _train_step_fn

    mesh = b.mesh
    data_axis = plan.data_axis
    repl = NamedSharding(mesh, P())
    step = _train_step_fn(b.model, b.tx, b.transform, plan.health)

    if plan.window == "none":
        batch_sh = NamedSharding(mesh, P(data_axis))
        return _jit_gspmd(step, (None, batch_sh, batch_sh, repl),
                          (None, repl), plan.donate)

    if plan.window == "stacked":
        batch_sh = NamedSharding(mesh, P(None, data_axis))

        def multi(state: TrainState, images_u8, labels, rng):
            def body(st, batch):
                imgs, lbls = batch
                st, metrics = step(st, imgs, lbls, rng)
                return st, metrics
            state, metrics_k = jax.lax.scan(body, state,
                                            (images_u8, labels))
            return state, jax.tree.map(lambda m: jnp.sum(m, axis=0),
                                       metrics_k)

        return _jit_gspmd(multi, (None, batch_sh, batch_sh, repl),
                          (None, repl), plan.donate)

    # window == "indexed": device-resident dataset, (K, B) index windows
    if b.image_shape is None:
        raise PlanError("the image indexed window needs an image_shape "
                        "binding")
    h, w, c = b.image_shape
    idx_sh = NamedSharding(mesh, P(None, data_axis))

    def multi(state: TrainState, images_all, labels_all, idx, rng):
        def body(st, idx_b):
            rows = jnp.take(images_all, idx_b, axis=0)
            if rows.dtype == jnp.int32:  # packed: bitcast words back to bytes
                rows = jax.lax.bitcast_convert_type(rows, jnp.uint8)
            imgs = rows.reshape(-1, h, w, c)
            lbls = jnp.take(labels_all, idx_b, axis=0)
            return step(st, imgs, lbls, rng)
        state, metrics_k = jax.lax.scan(body, state, idx)
        return state, jax.tree.map(lambda m: jnp.sum(m, axis=0), metrics_k)

    return _jit_gspmd(multi, (None, repl, repl, idx_sh, repl), (None, repl),
                      plan.donate)


def _image_eval(plan: Plan, b: Bindings) -> Callable:
    """Image eval lowerings: per-batch metric sums, or the whole-val-set
    indexed scan (engine.steps make_eval_step / make_indexed_eval_step
    bodies, verbatim)."""
    from tpu_dist.engine.steps import _metric_sums, cross_entropy_sum

    mesh = b.mesh
    model = b.model
    transform = b.eval_transform or b.transform
    data_axis = plan.data_axis
    repl = NamedSharding(mesh, P())

    if plan.window != "indexed":
        batch_sh = NamedSharding(mesh, P(data_axis))

        def step(params, batch_stats, images_u8, labels, valid):
            x = transform(images_u8, None)
            logits = model.apply({"params": params,
                                  "batch_stats": batch_stats}, x,
                                 train=False)
            return _metric_sums(logits, labels,
                                cross_entropy_sum(logits, labels, valid),
                                valid)

        return jax.jit(step, in_shardings=(None, None, batch_sh, batch_sh,
                                           batch_sh),
                       out_shardings=repl)

    if b.image_shape is None:
        raise PlanError("the image indexed eval needs an image_shape "
                        "binding")
    h, w, c = b.image_shape
    idx_sh = NamedSharding(mesh, P(None, data_axis))

    def step(params, batch_stats, images_all, labels_all, idx, valid):
        def body(sums, blk):
            idx_b, valid_b = blk
            rows = jnp.take(images_all, idx_b, axis=0)
            if rows.dtype == jnp.int32:
                rows = jax.lax.bitcast_convert_type(rows, jnp.uint8)
            x = transform(rows.reshape(-1, h, w, c), None)
            labels = jnp.take(labels_all, idx_b, axis=0)
            logits = model.apply({"params": params,
                                  "batch_stats": batch_stats}, x,
                                 train=False)
            m = _metric_sums(logits, labels,
                             cross_entropy_sum(logits, labels, valid_b),
                             valid_b)
            return jax.tree.map(jnp.add, sums, m), None

        zeros = {k: jnp.float32(0.0)
                 for k in ("loss_sum", "correct1", "correct5", "count")}
        sums, _ = jax.lax.scan(body, zeros, (idx, valid))
        return sums

    return jax.jit(step, in_shardings=(None, None, repl, repl, idx_sh,
                                       idx_sh),
                   out_shardings=repl)


# ---- lm lowerings ---------------------------------------------------------

def _lm_accum_train(plan: Plan, b: Bindings) -> Callable:
    """LM grad-accum step (make_lm_grad_accum_train_step body)."""
    from tpu_dist.engine.lm_steps import _lm_grads_and_metrics
    from tpu_dist.engine.steps import _apply_update

    mesh, model, tx = b.mesh, b.model, b.tx
    aux_weight, loss_chunk, health = (plan.aux_weight, plan.loss_chunk,
                                      plan.health)
    repl = NamedSharding(mesh, P())
    batch_sh = NamedSharding(mesh, P(None, plan.data_axis))

    def step(state: TrainState, inputs, targets, rng):
        k = inputs.shape[0]
        dropout_rng = jax.random.fold_in(rng, state.step)

        def micro(carry, batch):
            grads_acc, i = carry
            mb_in, mb_tg = batch
            grads, metrics = _lm_grads_and_metrics(
                model, aux_weight, state.params, mb_in, mb_tg,
                jax.random.fold_in(dropout_rng, i), loss_chunk)
            grads_acc = jax.tree.map(lambda a, g: a + g / k, grads_acc,
                                     grads)
            return (grads_acc, i + 1), metrics

        zeros = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32),
                             state.params)
        (grads, _), metrics_k = jax.lax.scan(
            micro, (zeros, jnp.int32(0)), (inputs, targets))
        metrics = jax.tree.map(lambda m: jnp.sum(m, axis=0), metrics_k)
        return _apply_update(tx, state, grads, {}, metrics, health)

    return _jit_gspmd(step, (None, batch_sh, batch_sh, repl), (None, repl),
                      plan.donate)


def _lm_explicit_template(plan: Plan, b: Bindings) -> Callable:
    """The explicit per-device LM step the plan names: a pre-built
    ``explicit_step_fn`` binding wins (the engines build ring/bucketed
    flavors once and window them); otherwise ring or bucketed-dp from the
    engine templates."""
    if b.explicit_step_fn is not None:
        return b.explicit_step_fn
    from tpu_dist.engine.lm_steps import (_lm_explicit_dp_step_fn,
                                          _lm_tp_ring_step_fn)

    if plan.tp_impl == "ring":
        return _lm_tp_ring_step_fn(
            b.model, b.tx, plan.aux_weight, plan.data_axis,
            plan.model_axis, b.mesh.shape[plan.model_axis],
            plan.loss_chunk, plan.health)
    return _lm_explicit_dp_step_fn(
        b.model, b.tx, plan.aux_weight, plan.data_axis,
        b.mesh.shape[plan.data_axis], plan.grad_bucket_mb,
        plan.loss_chunk, plan.health)


def _lm_explicit_train(plan: Plan, b: Bindings) -> Callable:
    """Partition an explicit per-device LM step: single-batch shard_map
    (the _wrap_explicit_step body) or the indexed scan-inside-shard_map
    window (make_lm_explicit_indexed_multi_train_step body)."""
    step_fn = _lm_explicit_template(plan, b)
    mesh = b.mesh
    data_axis = plan.data_axis

    if plan.window == "none":
        return _shard_map_jit(step_fn, mesh,
                              (P(), P(data_axis), P(data_axis), P()),
                              (P(), P()), plan.donate)

    def per_device(state: TrainState, rows_all, idx, rng):
        def body(st, idx_b):
            rows = jnp.take(rows_all, idx_b, axis=0)     # (B_local, L+1)
            return step_fn(st, rows[:, :-1], rows[:, 1:], rng)
        state, metrics_k = jax.lax.scan(body, state, idx)
        return state, jax.tree.map(lambda m: jnp.sum(m, axis=0), metrics_k)

    return _shard_map_jit(per_device, mesh,
                          (P(), P(), P(None, data_axis), P()),
                          (P(), P()), plan.donate)


def _lm_sp_train(plan: Plan, b: Bindings) -> Callable:
    """Sequence-parallel LM lowerings (ring attention inside shard_map):
    single-batch or the indexed device-side-shift window
    (make_lm_sp_train_step / make_lm_sp_indexed_multi_train_step bodies)."""
    from tpu_dist.engine.lm_steps import _lm_sp_step_fn, _sp_window_slices
    from tpu_dist.parallel.ring_attention import ring_attention_fn

    mesh = b.mesh
    data_axis, seq_axis = plan.data_axis, plan.seq_axis
    model = b.model_ctor(attn_fn=ring_attention_fn(seq_axis))
    one_step = _lm_sp_step_fn(model, b.tx, plan.aux_weight, data_axis,
                              seq_axis, plan.loss_chunk, plan.health)

    if plan.window == "none":
        return _shard_map_jit(
            one_step, mesh,
            (P(), P(data_axis, seq_axis), P(data_axis, seq_axis), P()),
            (P(), P()), plan.donate)

    n_seq = mesh.shape[seq_axis]

    def per_device(state: TrainState, rows_all, idx, rng):
        shard_len = (rows_all.shape[1] - 1) // n_seq
        seq_idx = jax.lax.axis_index(seq_axis)

        def body(st, idx_b):
            rows = jnp.take(rows_all, idx_b, axis=0)
            inputs, targets = _sp_window_slices(rows, seq_idx, shard_len)
            return one_step(st, inputs, targets, rng)

        state, metrics_k = jax.lax.scan(body, state, idx)
        return state, jax.tree.map(lambda m: jnp.sum(m, axis=0), metrics_k)

    return _shard_map_jit(per_device, mesh,
                          (P(), P(), P(None, data_axis), P()),
                          (P(), P()), plan.donate)


def _lm_train(plan: Plan, b: Bindings) -> Callable:
    """The gspmd LM train lowerings: plain jit (dp and every GSPMD-placed
    layout) or the HBM-resident indexed window, around the ONE template
    (engine.lm_steps._lm_step_fn)."""
    from tpu_dist.engine.lm_steps import _lm_step_fn

    mesh = b.mesh
    data_axis = plan.data_axis
    repl = NamedSharding(mesh, P())
    one_step = _lm_step_fn(b.model, b.tx, plan.aux_weight, plan.loss_chunk,
                           plan.health)

    if plan.window == "none":
        batch_sh = NamedSharding(mesh, P(data_axis))
        # With TP the state arrives pre-sharded (parallel.tp
        # shard_lm_params) and in_shardings=None lets GSPMD propagate that
        # layout through the step; pure DP states arrive replicated — the
        # same jit serves both. out_shardings=None likewise.
        return jax.jit(one_step,
                       in_shardings=(None, batch_sh, batch_sh, repl),
                       out_shardings=None,
                       donate_argnums=(0,) if plan.donate else ())

    idx_sh = NamedSharding(mesh, P(None, data_axis))

    def multi(state: TrainState, rows_all, idx, rng):
        def body(st, idx_b):
            rows = jnp.take(rows_all, idx_b, axis=0)     # (B, L+1)
            return one_step(st, rows[:, :-1], rows[:, 1:], rng)
        state, metrics_k = jax.lax.scan(body, state, idx)
        return state, jax.tree.map(lambda m: jnp.sum(m, axis=0), metrics_k)

    return _jit_gspmd(multi, (None, repl, idx_sh, repl), (None, repl),
                      plan.donate)


def _lm_sp_eval(plan: Plan, b: Bindings) -> Callable:
    """SP eval lowerings (make_lm_sp_eval_step /
    make_lm_sp_indexed_eval_step bodies)."""
    from tpu_dist.engine.lm_steps import (_lm_eval_metrics,
                                          _sp_window_slices,
                                          zeros_lm_metrics)
    from tpu_dist.parallel.ring_attention import ring_attention_fn

    mesh = b.mesh
    data_axis, seq_axis = plan.data_axis, plan.seq_axis
    loss_chunk = plan.loss_chunk
    model = b.model_ctor(attn_fn=ring_attention_fn(seq_axis))

    if plan.window != "indexed":
        def per_device(params, inputs, targets, valid):
            seq_idx = jax.lax.axis_index(seq_axis)
            pos_offset = seq_idx * inputs.shape[1]
            mask = jnp.broadcast_to(valid[:, None], targets.shape).astype(
                jnp.float32)
            metrics = _lm_eval_metrics(model, params, inputs, targets,
                                       mask, loss_chunk, pos_offset)
            return jax.tree.map(
                lambda m: jax.lax.psum(jax.lax.psum(m, seq_axis),
                                       data_axis), metrics)

        sharded = shard_map(
            per_device, mesh=mesh,
            in_specs=(P(), P(data_axis, seq_axis), P(data_axis, seq_axis),
                      P(data_axis)),
            out_specs=P(), check_vma=False)
        return jax.jit(sharded)

    n_seq = mesh.shape[seq_axis]

    def per_device(params, rows_all, idx, valid):
        shard_len = (rows_all.shape[1] - 1) // n_seq
        seq_idx = jax.lax.axis_index(seq_axis)
        pos_offset = seq_idx * shard_len

        def body(sums, blk):
            idx_b, valid_b = blk
            rows = jnp.take(rows_all, idx_b, axis=0)
            inputs, targets = _sp_window_slices(rows, seq_idx, shard_len)
            mask = jnp.broadcast_to(valid_b[:, None], targets.shape).astype(
                jnp.float32)
            m = _lm_eval_metrics(model, params, inputs, targets, mask,
                                 loss_chunk, pos_offset)
            return jax.tree.map(jnp.add, sums, m), None

        sums, _ = jax.lax.scan(body, zeros_lm_metrics(), (idx, valid))
        return jax.tree.map(
            lambda m: jax.lax.psum(jax.lax.psum(m, seq_axis), data_axis),
            sums)

    sharded = shard_map(
        per_device, mesh=mesh,
        in_specs=(P(), P(), P(None, data_axis), P(None, data_axis)),
        out_specs=P(), check_vma=False)
    return jax.jit(sharded)


def _lm_eval(plan: Plan, b: Bindings) -> Callable:
    """GSPMD LM eval lowerings (make_lm_eval_step /
    make_lm_indexed_eval_step bodies)."""
    from tpu_dist.engine.lm_steps import _lm_eval_metrics, zeros_lm_metrics

    mesh = b.mesh
    model = b.model
    data_axis = plan.data_axis
    loss_chunk = plan.loss_chunk
    repl = NamedSharding(mesh, P())

    if plan.window != "indexed":
        batch_sh = NamedSharding(mesh, P(data_axis))

        def step(params, inputs, targets, valid):
            mask = jnp.broadcast_to(valid[:, None], targets.shape).astype(
                jnp.float32)
            return _lm_eval_metrics(model, params, inputs, targets, mask,
                                    loss_chunk)

        return jax.jit(step, in_shardings=(None, batch_sh, batch_sh,
                                           batch_sh),
                       out_shardings=NamedSharding(mesh, P()))

    idx_sh = NamedSharding(mesh, P(None, data_axis))

    def step(params, rows_all, idx, valid):
        def body(sums, blk):
            idx_b, valid_b = blk
            rows = jnp.take(rows_all, idx_b, axis=0)
            inputs, targets = rows[:, :-1], rows[:, 1:]
            mask = jnp.broadcast_to(valid_b[:, None], targets.shape).astype(
                jnp.float32)
            m = _lm_eval_metrics(model, params, inputs, targets, mask,
                                 loss_chunk)
            return jax.tree.map(jnp.add, sums, m), None

        sums, _ = jax.lax.scan(body, zeros_lm_metrics(), (idx, valid))
        return sums

    return jax.jit(step, in_shardings=(None, repl, idx_sh, idx_sh),
                   out_shardings=repl)


# ---- dispatch -------------------------------------------------------------

def _lower_train(plan: Plan, b: Bindings) -> Callable:
    if plan.engine == "image":
        if plan.grad_accum_steps > 1:
            return _image_accum_train(plan, b)
        if plan.sync == "explicit":
            return _image_explicit_train(plan, b)
        return _image_train(plan, b)
    if plan.grad_accum_steps > 1:
        return _lm_accum_train(plan, b)
    if plan.layout == "sp":
        return _lm_sp_train(plan, b)
    if plan.sync == "explicit":
        return _lm_explicit_train(plan, b)
    return _lm_train(plan, b)


def _lower_eval(plan: Plan, b: Bindings) -> Callable:
    if plan.engine == "image":
        return _image_eval(plan, b)
    if plan.layout == "sp":
        return _lm_sp_eval(plan, b)
    return _lm_eval(plan, b)


# ---- plan activation + the config knob ------------------------------------

def activate_plan(plan: Plan) -> None:
    """Apply the plan's global TRACE-TIME switches: the fused int8 Pallas
    kernel dispatch (ops.quant.set_fused_quant) and the searchable Pallas
    block sizes (ops.pallas_quant / pallas_sgd / pallas_adamw). Call
    BEFORE building step functions — these are read at trace time."""
    from tpu_dist.ops import pallas_adamw, pallas_quant, pallas_sgd
    from tpu_dist.ops.quant import set_fused_quant

    set_fused_quant({"auto": None, "on": True, "off": False}[
        plan.fused_quant])
    pallas_quant.set_quant_blocks(*plan.quant_block)
    pallas_sgd.set_block_rows(plan.opt_block_rows)
    pallas_adamw.set_block_rows(plan.opt_block_rows)


def _auto_workload(cfg, engine: str) -> dict:
    """A tuner workload from a config (the 'auto' knob's input): crude
    param counts are fine — the search ranks knobs, it does not predict
    wall time."""
    if engine == "lm":
        n = (cfg.vocab_size * cfg.d_model
             + cfg.num_layers * 12 * cfg.d_model * cfg.d_model)
        toks = cfg.batch_size * cfg.seq_len
    else:
        n = 25_000_000                       # resnet50-scale placeholder
        toks = cfg.batch_size
    return {"engine": engine, "n_params": float(n),
            "tokens_per_step": float(toks),
            "devices": jax.device_count()}


def _auto_filter(cfg, engine: str):
    """Prune 'auto' candidates to what THIS config can legally run (an
    explicit plan file is applied as-is and may fail loudly; auto must
    never break a working config)."""
    mesh_shape = getattr(cfg, "mesh_shape", None) or ()
    mesh_axes = tuple(getattr(cfg, "mesh_axes", ("data",)))
    multi = {a for a, s in zip(mesh_axes, mesh_shape) if a != "data"
             and (s is None or s > 1)}
    pure_dp = not multi and not getattr(cfg, "fsdp", False)
    accum = getattr(cfg, "grad_accum_steps", 1) > 1
    host_data = getattr(cfg, "data_placement", "auto") == "host"
    quant_ok = (engine == "lm"
                or getattr(cfg, "arch", "").startswith("vit"))

    def keep(plan: Plan) -> bool:
        if plan.quant != "none" and not quant_ok:
            return False
        if plan.grad_bucket_mb > 0 and not (pure_dp and not accum):
            return False
        if plan.sync == "explicit" and not pure_dp:
            return False
        if plan.window != "none" and (host_data or accum):
            return False
        if plan.window != "none" and engine == "image" \
                and getattr(cfg, "dataset", "") == "imagenet":
            # imagefolder datasets are not HBM-resident ArrayDatasets;
            # the indexed window would refuse at Trainer init
            return False
        return True

    return keep


def resolve_config_plan(cfg):
    """Implement the configs' ``plan`` knob: ``''``/``'none'`` -> no-op;
    a path -> load the (per-device-kind) plan file; ``'auto'`` -> run the
    tuner's analytic search for this device kind, pruned to what the
    config can run. Returns ``(new_cfg, plan_info | None)`` where
    plan_info is the {'source', 'hash', 'knobs', 'plan'} record the
    engines stamp into run_start + the ``plan`` ledger event. Applies the
    plan's trace-time switches (:func:`activate_plan`) as a side effect.
    """
    spec = getattr(cfg, "plan", "") or ""
    if spec in ("", "none"):
        return cfg, None
    from tpu_dist.plan import ir

    engine = "image" if any(f.name == "variant"
                            for f in dataclasses.fields(type(cfg))) \
        else "lm"
    device_kind = getattr(jax.devices()[0], "device_kind", "unknown")
    if spec == "auto":
        from tpu_dist.plan import tune as tune_mod
        keep = _auto_filter(cfg, engine)
        # knobs the auto space does NOT search are carried from the
        # config, never reset to Plan defaults — 'auto' tunes what it
        # explores and must leave the rest of a working config alone
        # (precision/bf16, grad accumulation, chunked CE, health policy,
        # tp_impl all stay the user's choice)
        carried = {k: getattr(cfg, k) for k in
                   ("precision", "grad_accum_steps", "health", "tp_impl")
                   if hasattr(cfg, k)}
        if engine == "lm":
            carried["loss_chunk"] = getattr(cfg, "loss_chunk", 0)
        space = []
        for p in tune_mod.default_space(engine, jax.device_count()):
            try:
                p = dataclasses.replace(p, **carried).validate()
            except PlanError:
                continue   # carried knobs make this candidate illegal
            if keep(p):
                space.append(p)
        if not space:
            # abstaining must be LOUD: "the tuner found nothing legal for
            # this config" (e.g. tp_impl='ring' — outside the searched
            # space) is different from "the tuner never ran"
            import sys
            print("plan=auto: no legal candidate plans for this config "
                  "(its knobs fall outside the searched space); running "
                  "with the hand-set knobs", file=sys.stderr)
            return cfg, None
        result = tune_mod.search(workload=_auto_workload(cfg, engine),
                                 device_kind=device_kind, space=space)
        if result["best"] is None:
            return cfg, None
        plan = result["best"]["plan"]
        source = "auto"
    else:
        plans = ir.load_plan_file(spec)
        plan = ir.plan_for_device(plans, device_kind)
        source = spec
    if plan.engine != engine:
        raise PlanError(f"plan engine {plan.engine!r} does not drive the "
                        f"{engine} engine (plan source: {source})")
    new_cfg = apply_plan_to_config(cfg, plan)
    activate_plan(plan)
    info = {"source": source, "hash": plan_hash(plan),
            "knobs": plan_knob_summary(plan), "plan": plan,
            "device_kind": device_kind}
    return new_cfg, info
