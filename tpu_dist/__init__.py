"""tpu_dist — a TPU-native distributed-training framework.

Re-implements, TPU-first (JAX/XLA/pjit/shard_map/Pallas), the capabilities of the
reference cookbook ``Xianchao-Wu/pytorch-distributed`` (six data-parallel launcher /
backend variants training image classifiers with distributed evaluation, mixed
precision, checkpointing and metering — see /root/repo/SURVEY.md).

Unlike the reference's six flat scripts that each inline the same ~200 lines
(SURVEY.md §1), tpu_dist is a layered package; the cookbook surface survives as thin
scripts in ``scripts/`` that all drive one engine with different launch/parallelism
configs — mirroring the fact that the reference variants differ only in their
launcher/engine wrap lines (reference: 2.distributed.py:114, 5.horovod_distributed.py:125).
"""

__version__ = "0.1.0"

from tpu_dist import configs  # noqa: F401
