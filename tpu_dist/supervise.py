"""CLI for the elastic run supervisor (tpu_dist.parallel.supervisor).

    python -m tpu_dist.supervise --ledger run.jsonl --ckpt-dir ck -- \\
        python scripts/8.lm_longcontext.py --epochs 4 --batch-size 32

The supervisor launches the command after ``--``, appends the lineage
flags (``--ledger-path``/``--attempt -1``/``--checkpoint-dir`` and, on
restarts, ``--resume <newest valid checkpoint>``), watches liveness via
the attempt ledger's tail + a heartbeat file, classifies every exit, and
restarts under a bounded policy (exponential backoff, crash-loop cutoff,
degraded dp-only relaunch on confirmed host loss). Exit code 0 iff the
run completed cleanly. Runs without jax — the child owns the devices.
"""

from __future__ import annotations

import argparse
import sys
from typing import Optional, Sequence

from tpu_dist.parallel.supervisor import (RestartPolicy, Supervisor,
                                          SupervisorResult)


def build_parser() -> argparse.ArgumentParser:
    p = RestartPolicy()
    ap = argparse.ArgumentParser(
        prog="python -m tpu_dist.supervise",
        description=__doc__.splitlines()[0],
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    ap.add_argument("--ledger", required=True,
                    help="base ledger path; attempts write <stem>.aN.jsonl")
    ap.add_argument("--ckpt-dir", default="",
                    help="checkpoint dir to resume restarts from "
                    "(newest-valid pointer; empty = no auto-resume)")
    ap.add_argument("--max-restarts", type=int, default=p.max_restarts)
    ap.add_argument("--backoff-s", type=float, default=p.backoff_base_s,
                    help="restart backoff base (doubles per restart)")
    ap.add_argument("--backoff-max-s", type=float, default=p.backoff_max_s)
    ap.add_argument("--crash-loop-k", type=int, default=p.crash_loop_k,
                    help="stop after K consecutive pre-first-step deaths")
    ap.add_argument("--stall-timeout-s", type=float,
                    default=p.stall_timeout_s,
                    help="SIGKILL after this much ledger/heartbeat silence")
    ap.add_argument("--stall-grace-s", type=float, default=p.stall_grace_s,
                    help="SIGKILL this long after a watchdog 'stall' event "
                    "with no progress")
    ap.add_argument("--no-forward-flags", action="store_true",
                    help="do not append --ledger-path/--attempt/--resume "
                    "to the command (it manages its own lineage)")
    ap.add_argument("--no-shrink", action="store_true",
                    help="never shrink the mesh on rendezvous/host loss")
    ap.add_argument("--backoff-jitter", type=float,
                    default=p.backoff_jitter,
                    help="deterministic per-host restart-backoff spread "
                    "(fraction of the wait; de-stampedes the coordinator)")
    ap.add_argument("--preempt-deadline-s", type=float,
                    default=p.preempt_deadline_s,
                    help="seconds the child gets between SIGTERM and "
                    "SIGKILL to write its coordinated preemption snapshot")
    ap.add_argument("--consensus-dir", default="",
                    help="shared directory for cross-host supervisor "
                    "consensus (parallel.consensus): dense process-id "
                    "renumbering on host loss + mesh re-expansion when a "
                    "host returns; empty = single-host fallback behavior")
    ap.add_argument("--host-id", type=int, default=None,
                    help="this host's consensus id (default: "
                    "TPU_DIST_PROCESS_ID)")
    ap.add_argument("--planned-processes", type=int, default=None,
                    help="the job's full world size (default: "
                    "TPU_DIST_NUM_PROCESSES)")
    ap.add_argument("--lease-s", type=float, default=10.0,
                    help="consensus membership lease: a host whose "
                    "heartbeat ages past this is declared lost")
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="-- then the training command")
    return ap


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    cmd = list(args.cmd)
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    policy = RestartPolicy(
        max_restarts=args.max_restarts, backoff_base_s=args.backoff_s,
        backoff_max_s=args.backoff_max_s, crash_loop_k=args.crash_loop_k,
        stall_timeout_s=args.stall_timeout_s,
        stall_grace_s=args.stall_grace_s,
        shrink_on_host_loss=not args.no_shrink,
        backoff_jitter=args.backoff_jitter,
        preempt_deadline_s=args.preempt_deadline_s)
    consensus = None
    if args.consensus_dir:
        import os

        from tpu_dist.parallel.consensus import ConsensusDir

        host_id = (args.host_id if args.host_id is not None else
                   int(os.environ.get("TPU_DIST_PROCESS_ID", "0") or 0))
        planned = (args.planned_processes if args.planned_processes
                   is not None else
                   int(os.environ.get("TPU_DIST_NUM_PROCESSES", "1") or 1))
        consensus = ConsensusDir(args.consensus_dir, host_id=host_id,
                                 planned=planned, lease_s=args.lease_s)
        # startup join barrier: the first epoch should be the full mesh,
        # not a racey one-host view per supervisor start order
        consensus.wait_for_peers()
    sup = Supervisor(cmd, ledger=args.ledger, ckpt_dir=args.ckpt_dir,
                     policy=policy,
                     forward_flags=not args.no_forward_flags,
                     consensus=consensus)
    result: SupervisorResult = sup.run()
    print(f"[supervise] {result.status}: {len(result.attempts)} attempt(s) "
          + ", ".join(f"a{a.attempt}={a.failure_class}"
                      for a in result.attempts),
          file=sys.stderr, flush=True)
    return 0 if result.ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
