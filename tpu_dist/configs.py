"""Config / CLI layer (reference component C1).

The reference repeats an ~45-line argparse block in every script
(reference: 1.dataparallel.py:26-70, 2.distributed.py:25-68,
5.2.horovod_pytorch_mnist.py:11-33, 6.distributed_slurm_main.py:27-70).
Here the flags live once as a dataclass; each cookbook script builds its parser
from it and overrides per-variant defaults (e.g. variant 1 defaults to
resnet101 / 5 epochs, variants 2-6 to resnet18 — reference 1.dataparallel.py:33,
2.distributed.py:30).
"""

from __future__ import annotations

import argparse
import dataclasses
from dataclasses import dataclass, field
from typing import Optional, Sequence


@dataclass
class TrainConfig:
    """All knobs of the reference scripts, plus TPU-native ones.

    Reference flag provenance is noted per field; TPU-only fields are marked.
    """

    # -- data (reference 1.dataparallel.py:27-31)
    data: str = "data"                 # dataset root dir
    dataset: str = "cifar10"           # cifar10 | mnist | imagenet | synthetic
    workers: int = 4                   # loader worker threads (host-side)

    # -- model (reference 1.dataparallel.py:32-38)
    arch: str = "resnet18"
    pretrained: str = ""               # reference: bool (download torchvision
    # weights). Zero egress makes that a PATH: warm-start params/BN stats
    # from a local checkpoint (this repo's own model_best format), fresh
    # optimizer state — shape-mismatched leaves (a different-class head)
    # keep their init, the fine-tune contract. "" = train from scratch.
    norm: str = ""                     # ResNet-only: bn (default) | gn
    norm_dtype: str = ""               # ResNet-only: "" (fp32 norm outputs,
                                       # torch-AMP parity) | bf16 (MLPerf-TPU
                                       # practice: bf16 normalized activations,
                                       # fp32 statistics — models/resnet.py)
    stem: str = ""                     # ResNet-only: imagenet | cifar | s2d
                                       # (space-to-depth, models/resnet.py)

    # -- schedule (reference 1.dataparallel.py:39-56)
    epochs: int = 10
    start_epoch: int = 0
    batch_size: int = 3200             # GLOBAL batch (divided per process/device)
    lr: float = 0.1
    momentum: float = 0.9
    weight_decay: float = 1e-4
    lr_step_epochs: int = 30           # x0.1 every N epochs (1.dataparallel.py:332-336)
    lr_scale_by_world: bool = False    # horovod-style lr x world_size (5.2...py:159-171)
    optimizer: str = "sgd"             # sgd | adamw | fused_sgd (Pallas kernel,
                                       # apex fused-optimizer analog)
    adam_b1: float = 0.9               # adamw betas/eps; b2 defaults to the
    adam_b2: float = 0.999             # image convention here (the LM config
    adam_eps: float = 1e-8             # defaults to the LM one, 0.95)

    # -- loop control (reference 1.dataparallel.py:57-70)
    print_freq: int = 10
    evaluate: bool = False
    seed: Optional[int] = None
    resume: str = ""                   # TPU build adds REAL resume (reference has none,
                                       # SURVEY.md §5 checkpoint)
    checkpoint_dir: str = "checkpoints"

    # -- precision (reference variant 4 apex AMP -> XLA bf16; SURVEY.md §2b apex row)
    precision: str = "fp32"            # fp32 | bf16 | bf16_params
    quant: str = "none"                # none | int8 | int8_wo (ops.quant):
                                       # int8 quantized matmuls in the
                                       # transformer-family archs (vit_*) —
                                       # the rung above bf16 on the ladder;
                                       # composes with precision=bf16
    loss_scale: Optional[float] = None # only meaningful if emulating fp16 semantics
    grad_compression: str = "none"     # none | bf16  (hvd.Compression.fp16-equiv,
                                       # reference 5.horovod_distributed.py:123-125)

    # -- comm/compute overlap (parallel.overlap; no reference analog beyond
    #    DDP's own bucket overlap, which grad_bucket_mb reproduces)
    tp_impl: str = "gspmd"             # gspmd | ring: ring = manual
                                       # collective-matmul TP for the
                                       # transformer-family archs (vit_*)
                                       # under variant='shard_map' with a
                                       # 'model' mesh axis
    grad_bucket_mb: float = 0.0        # >0: explicit grad sync in DDP-style
                                       # size-targeted bucket collectives
                                       # (~25 is DDP's default) instead of
                                       # one fused allreduce; requires
                                       # variant='shard_map'

    # -- distribution (reference C5/C6/C25 + TPU mesh)
    variant: str = "jit"               # engine flavor tag for logging only
    mesh_shape: Optional[Sequence[int]] = None  # e.g. (8,) dp; (4,2) dp x model
    mesh_axes: Sequence[str] = ("data",)
    gradient_predivide_factor: float = 1.0      # reference 5.2...py:185
    adasum: bool = False                        # reference 5.2...py:184: REAL
                                                # Adasum recursive-halving
                                                # reduction (collectives.
                                                # adasum_reduce) in the
                                                # shard_map engine

    # -- dispatch/data-path tuning (TPU-only; no reference analog — its
    #    per-batch host loop was the bottleneck the prefetcher fought, C13)
    steps_per_dispatch: int = 1        # K optimizer steps per XLA dispatch
                                       # (lax.scan window; amortizes controller
                                       # latency — requires variant 'jit')
    grad_accum_steps: int = 1          # microbatches per optimizer step: the
                                       # global batch is split into N
                                       # sequential microbatches whose grads
                                       # average into ONE update (for global
                                       # batches beyond device memory)
    data_placement: str = "auto"       # host | device | auto: 'device' keeps
                                       # the whole uint8 dataset in HBM and
                                       # sends only index windows per step
                                       # (auto: device when in-memory and
                                       # steps_per_dispatch > 1)

    # -- observability (reference C21/C22 + the round-6 obs subsystem)
    log_csv: str = ""                  # per-epoch [start, seconds] CSV if set
                                       # (rendered as a ledger sink since
                                       # round 6 — same values, one source)
    profile_dir: str = ""              # jax.profiler trace dir if set
    telemetry_csv: str = ""            # 500ms device-HBM/host-RSS sampler CSV
                                       # (utils.telemetry — the reference's
                                       # nvidia-smi statistics.sh analog;
                                       # every process writes its own
                                       # .pN-suffixed file on multi-host)
    ledger_path: str = ""              # append-only JSONL run ledger
                                       # (obs.ledger: run_start/step/epoch/
                                       # eval/ckpt/... typed events; non-main
                                       # processes write <path>.pN)
    watchdog_factor: float = 10.0      # hang watchdog (obs.watchdog): dump
                                       # stacks+HBM when no step completes in
                                       # factor x trailing-median step time
                                       # (5s floor; 0 disables)
    skew_every: int = 0                # cross-host step-time skew allgather
                                       # every K steps (obs.skew; 0 = off)
    health: str = "record"             # numerical-health policy (obs.health):
                                       # record (probes + ledger events only)
                                       # | skip (zero a non-finite update,
                                       # advance data+RNG — multi-host
                                       # lockstep preserved) | halt (raise)
    health_spike_z: float = 8.0        # loss-spike z-score threshold of the
                                       # host-side EMA detector (0 disables)
    metrics_port: int = 0              # Prometheus scrape endpoint
                                       # (obs.metrics): process i serves
                                       # http://host:(port+i)/metrics; 0=off
    flightrec_dir: str = ""            # flight-recorder bundle root
                                       # (obs.flightrec); "" derives
                                       # <ledger_path>.flightrec (or a temp
                                       # dir) at first trigger
    flightrec_trace_steps: int = 3     # jax.profiler window: step records
                                       # captured after a trigger (0 = no
                                       # trace in the bundle)
    job_id: str = ""                   # run lineage (obs.goodput): stable
                                       # id shared by every restart attempt
                                       # of one logical job (default: the
                                       # ledger filename stem)
    attempt: int = 0                   # restart ordinal: 0 = first attempt
                                       # (bare ledger_path), N>0 writes
                                       # <path>.aN, -1 = auto (next free
                                       # index from the files on disk)
    goodput_every_s: float = 60.0      # periodic 'goodput' ledger-event
                                       # cadence in run seconds (0 = only
                                       # the final one at run_end)
    slo_steps_per_min: float = 0.0     # progress-SLO floor on EMA
                                       # optimizer steps/min (0 = off);
                                       # a breach emits an 'slo' event,
                                       # which auto-triggers the flight
                                       # recorder via the ledger sink
    slo_throughput: float = 0.0        # progress-SLO floor on EMA items/s
                                       # (img/s here, tok/s in LMConfig;
                                       # 0 = off)

    # -- self-healing (round 10: parallel.supervisor + obs.faults)
    faults: str = ""                   # deterministic fault-injection spec
                                       # (obs.faults grammar, e.g.
                                       # "hard_exit@step=10,attempt=0";
                                       # TPU_DIST_FAULTS env also honored)
    keep_checkpoints: int = 3          # retain the last K checkpoints as
                                       # step-stamped hard links + a
                                       # newest-valid pointer; a corrupt
                                       # newest falls back at load (0 =
                                       # newest only, pre-round-10)
    max_restarts: int = 0              # >0: wrap fit() in the in-process
                                       # supervised-restart loop
                                       # (parallel.supervisor.
                                       # run_supervised); halts/crashes
                                       # resume from the newest valid
                                       # checkpoint with attempt lineage
    restart_backoff_s: float = 1.0     # restart backoff base (doubles per
                                       # restart, capped at 60s)
    crash_loop_k: int = 3              # stop restarting after K
                                       # consecutive pre-first-step deaths

    # -- step plan (tpu_dist.plan): "" | "none" = hand-set knobs; "auto"
    #    = the tuner's analytic search for this device kind (pruned to
    #    what this config can run); a path = a tools/tune.py plan JSON
    #    (best-plan-per-device-kind). The plan-owned knobs (quant,
    #    tp_impl, grad_bucket_mb, steps_per_dispatch, health, precision,
    #    variant, Pallas block sizes) are overridden before the engine
    #    builds steps; run_start + a 'plan' ledger event record the hash
    plan: str = ""
    # -- program audit (tpu_dist.analysis.proglint via plan.compile):
    #    none = off; record = run the compile-time jaxpr/HLO pass on
    #    every step program + the drain-boundary recompile sentry,
    #    emitting 'audit' ledger events; halt = record, then raise
    #    AuditError on any unwaivered finding
    audit: str = "none"

    # -- synthetic-data knobs (TPU-only: zero-egress envs can't download datasets)
    synth_train_size: int = 50000
    synth_val_size: int = 10000

    def scaled_lr(self, world_size: int) -> float:
        """Horovod lr scaling rule (reference 5.2.horovod_pytorch_mnist.py:159-171)."""
        return self.lr * world_size if self.lr_scale_by_world else self.lr


@dataclass
class LMConfig:
    """Knobs of the LM half of the framework (no reference analog — the
    reference is image-only; SURVEY.md §2c). Mirrors TrainConfig's shape so
    scripts build their parsers the same way (add_args works on both)."""

    # -- corpus (tpu_dist.data.tokens)
    data: str = ""                 # token file (.bin uint16 / .npy); empty
                                   # or missing -> synthetic affine corpus
    val_data: str = ""             # separate val token file (else tail split)
    val_frac: float = 0.05         # held-out tail fraction of the stream
    synth_tokens: int = 2_000_000  # synthetic corpus length
    vocab_size: int = 512
    seq_len: int = 512

    # -- model
    num_layers: int = 4
    d_model: int = 256
    num_heads: int = 8
    num_experts: int = 0           # MoE feed-forward with N experts (0=dense)
    router_top_k: int = 1          # 1 = Switch top-1, 2 = GShard top-2
    moe_group_size: int = 512      # router group tokens (GShard grouping;
                                   # under sp, groups are shard-local — a
                                   # size dividing the shard keeps routing
                                   # identical to the dp grouping)
    moe_aux_weight: float = 0.01   # weight of the router balance+z losses
                                   # in the objective (every MoE mode)
    moe_capacity_factor: float = 1.25  # per-expert queue = S/E * factor * k
                                   # (>= E/k makes dispatch drop-free —
                                   # models/moe.py capacity notes)
    attn: str = "full"             # full | blockwise | flash (Pallas FA2)
    attn_block: int = 1024         # KV block for blockwise/flash (clamped
                                   # to seq_len; 1024 measured ~20% faster
                                   # than 512 for flash fwd+bwd on v5e)
    remat: bool = False            # jax.checkpoint each block (HBM lever)
    loss_chunk: int = 0            # >0: chunked head+CE (ops.fused_xent) —
                                   # the (B,L,V) logits never materialize;
                                   # N rows of logits at a time, backward
                                   # recomputes (jit, sp, and gpipe-pp)
    precision: str = "fp32"        # fp32 | bf16
    quant: str = "none"            # none | int8 | int8_wo (ops.quant):
                                   # int8 dense/attention/expert matmuls
                                   # with STE training (int8) or weight-only
                                   # quantization (int8_wo — the
                                   # memory-bound-decode mode; with
                                   # loss_chunk > 0 the chunked head stays
                                   # in the compute dtype)

    # -- schedule
    epochs: int = 1
    max_steps: int = 0             # stop after N optimizer steps (0 = off;
                                   # smoke tests / fixed-step runs)
    batch_size: int = 16           # GLOBAL batch in sequences
    optimizer: str = "sgd"         # sgd | adamw (decoupled, b2=0.95 LM
                                   # convention — ops.optim.make_optimizer)
                                   # | fused_adamw (Pallas single-pass
                                   # kernel, ops.pallas_adamw; measured
                                   # SLOWER than adamw at 0.9B — BASELINE.md
                                   # round-5 — kept as the apex-FusedAdam
                                   # capability analog)
    lr: float = 3e-2
    momentum: float = 0.9
    adam_b1: float = 0.9
    adam_b2: float = 0.95
    adam_eps: float = 1e-8
    weight_decay: float = 0.0
    grad_clip: float = 0.0         # >0: clip raw grads by global norm
                                   # before the optimizer statistics
    lr_schedule: str = "constant"  # constant | cosine | step, each with
                                   # linear warmup (ops.optim.lm_lr_schedule;
                                   # resume-safe — the step count rides in
                                   # the checkpointed optimizer state)
    warmup_steps: int = 0          # linear warmup updates before the decay
    lr_decay_steps: int = 0        # cosine horizon in optimizer steps
                                   # (0 = max_steps if set, else
                                   # epochs * steps_per_epoch)
    lr_min_frac: float = 0.0       # cosine floor as a fraction of base lr
    lr_step_epochs: int = 30       # 'step' decay interval (reference C19)

    # -- distribution (mesh axes pick the parallelism: data / model / seq /
    #    expert / stage — see scripts/8)
    mesh_shape: Optional[Sequence[int]] = None
    mesh_axes: Sequence[str] = ("data",)
    tp_impl: str = "gspmd"         # gspmd (declarative Megatron specs,
                                   # parallel.tp) | ring (manual collective
                                   # matmul with comm/compute overlap,
                                   # parallel.overlap) — picks HOW a
                                   # 'model' mesh axis is implemented;
                                   # identical param trees/checkpoints,
                                   # fp losses allclose (tests)
    grad_bucket_mb: float = 0.0    # >0: dp grad sync as DDP-style bucket
                                   # reduce-scatter collectives of ~this
                                   # many MB (25 = DDP's default) instead
                                   # of one fused tree-wide allreduce
                                   # (engine.lm_steps explicit dp step)
    fsdp: bool = False             # ZeRO-3 param+opt sharding over 'data'
    pp_microbatches: int = 4       # pipeline microbatches (with a 'stage' axis)
    pp_schedule: str = "gpipe"     # gpipe (autodiff through the tick scan;
                                   # stashes O(M) microbatch activations) |
                                   # 1f1b (manual-vjp PipeDream-flush:
                                   # activation stash O(S), M-independent —
                                   # the large-M / long-context schedule)

    # -- dispatch/data path (same TPU levers as TrainConfig)
    steps_per_dispatch: int = 1
    data_placement: str = "auto"   # auto | host | device (HBM-resident rows)
    grad_accum_steps: int = 1      # microbatches per optimizer step (jit
                                   # modes; global token batches beyond HBM)

    # -- loop control
    print_freq: int = 10
    evaluate: bool = False
    seed: Optional[int] = 0
    resume: str = ""
    pretrained: str = ""           # warm-start params from a local ckpt
                                   # (fresh opt state; see TrainConfig)
    checkpoint_dir: str = ""
    log_csv: str = ""              # per-epoch CSV (ledger sink since round 6)
    profile_dir: str = ""          # jax.profiler trace dir if set (C22)
    telemetry_csv: str = ""        # 500ms device-HBM sampler (utils.telemetry;
                                   # .pN-suffixed per process on multi-host)
    ledger_path: str = ""          # JSONL run ledger (obs.ledger; non-main
                                   # processes write <path>.pN)
    watchdog_factor: float = 10.0  # hang watchdog: factor x trailing-median
                                   # step time (5s floor; 0 disables)
    skew_every: int = 0            # cross-host skew allgather every K steps
    health: str = "record"         # numerical-health policy (obs.health):
                                   # record | skip (zero a non-finite
                                   # update, advance data+RNG) | halt
    health_spike_z: float = 8.0    # loss-spike z-score threshold (0 = off)
    metrics_port: int = 0          # Prometheus scrape endpoint: process i
                                   # serves port+i (obs.metrics; 0 = off)
    flightrec_dir: str = ""        # flight-recorder bundle root
                                   # (obs.flightrec; "" derives from
                                   # ledger_path or a temp dir)
    flightrec_trace_steps: int = 3 # profiler window after a trigger, in
                                   # step records (0 = no trace)
    job_id: str = ""               # run lineage (obs.goodput): stable id
                                   # across restart attempts of one job
                                   # (default: ledger filename stem)
    attempt: int = 0               # restart ordinal: 0 = bare ledger_path,
                                   # N>0 writes <path>.aN, -1 = auto
    goodput_every_s: float = 60.0  # periodic 'goodput' event cadence
                                   # (0 = only the final one at run_end)
    slo_steps_per_min: float = 0.0 # progress-SLO floor on EMA optimizer
                                   # steps/min (0 = off; breach emits
                                   # 'slo' -> flight-recorder bundle)
    slo_throughput: float = 0.0    # progress-SLO floor on EMA tok/s
                                   # (0 = off)
    faults: str = ""               # fault-injection spec (obs.faults;
                                   # TPU_DIST_FAULTS env also honored)
    keep_checkpoints: int = 3      # keep-last-K retention + newest-valid
                                   # pointer (corrupt newest falls back)
    max_restarts: int = 0          # >0: in-process supervised restarts
                                   # (parallel.supervisor.run_supervised)
    restart_backoff_s: float = 1.0 # restart backoff base (doubles, cap 60s)
    crash_loop_k: int = 3          # crash-loop cutoff: K consecutive
                                   # pre-first-step deaths stop the loop
    plan: str = ""                 # step plan (tpu_dist.plan): "" | "none"
                                   # = hand-set knobs; "auto" = analytic
                                   # tuner search for this device kind; a
                                   # path = a tools/tune.py plan JSON —
                                   # plan-owned knobs (quant/tp_impl/
                                   # grad_bucket_mb/steps_per_dispatch/
                                   # loss_chunk/health/precision/blocks)
                                   # override before steps build; the
                                   # hash lands in run_start + a 'plan'
                                   # ledger event
    audit: str = "none"            # program audit (analysis.proglint):
                                   # none | record (compile-time pass +
                                   # drain-boundary recompile sentry,
                                   # 'audit' ledger events) | halt
                                   # (record + raise on unwaivered)


def add_args(parser: argparse.ArgumentParser, defaults) -> None:
    """Register every config field as a --flag (reference C1 parity).
    Works for TrainConfig and LMConfig alike (fields come from the
    defaults instance's own dataclass)."""
    for f in dataclasses.fields(type(defaults)):
        name = "--" + f.name.replace("_", "-")
        default = getattr(defaults, f.name)
        if f.type == "bool" or isinstance(default, bool):
            # BooleanOptionalAction: --flag / --no-flag, so variant defaults
            # of True (e.g. 5.2's lr_scale_by_world) stay overridable
            parser.add_argument(name, action=argparse.BooleanOptionalAction,
                                default=default)
        elif f.name == "mesh_shape":
            # "" -> None (auto: all devices on the data axis) — the
            # supervisor's degraded relaunch uses --mesh-shape "" to reset
            # an explicit layout after mesh shrink
            parser.add_argument(
                name,
                type=lambda s: tuple(int(x) for x in s.split(",")) if s
                else None,
                default=default)
        elif f.name == "mesh_axes":
            parser.add_argument(name, type=lambda s: tuple(s.split(",")), default=default)
        else:
            typ = type(default) if default is not None else str
            if f.name in ("seed", "loss_scale"):
                typ = float if f.name == "loss_scale" else int
            parser.add_argument(name, type=typ, default=default)


def parse_config(argv: Optional[Sequence[str]] = None,
                 defaults: Optional[TrainConfig] = None,
                 description: str = "tpu_dist training"):
    """Parse argv into a config of the same dataclass as ``defaults``."""
    defaults = defaults if defaults is not None else TrainConfig()
    cls = type(defaults)
    parser = argparse.ArgumentParser(description=description)
    add_args(parser, defaults)
    ns = parser.parse_args(argv)
    return cls(**{f.name: getattr(ns, f.name)
                  for f in dataclasses.fields(cls)})
