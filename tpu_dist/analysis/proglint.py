"""proglint — the program-level SPMD auditor (jaxpr + compiled artifact).

tools/distlint proves source-level hazards by AST; this module audits what
only exists AFTER tracing: the jaxpr the step compiler built and the
executable XLA handed back. The reference's worst bugs were exactly this
class — silently wrong *programs* (the apex prefetcher corrupting its
stream, horovod double-averaging gradients), not wrong source lines.

Checks (each waivable through a distlint-style reason-required file):

=====  =======  ===========================================================
id     surface  hazard
=====  =======  ===========================================================
PL001  jaxpr    a collective equation runs over an axis name outside the
                parallel/mesh.py authority (the program twin of DL003)
PL002  jaxpr    cond branches issue DIFFERENT ordered collective
                sequences — under SPMD each device resolves the predicate
                locally, so divergent orders are a deadlock at runtime,
                provable statically (the MPI-matching rule; while bodies
                are exempt: one body, same trip count on every device)
PL003  HLO      declared donate_argnums not aliased in the compiled
                module — XLA silently drops donation on sharding/layout
                mismatch and the program runs with DOUBLE the state HBM
PL004  jaxpr    f32/f64 compute (dot/conv) inside a program the config
                declares bf16/int8 — a promotion leak that quietly
                refunds the precision win
PL005  runtime  trace-cache growth past the program's allowed shape
                count — a shape/dtype varying per dispatch retraces on
                the hot path (checked at drain boundaries only)
PL000  meta     a waiver without a written reason (debt is named, or it
                is a bug)
=====  =======  ===========================================================

Waiver grammar (default file ``scripts/proglint_waivers.txt``)::

    PLNNN <program-glob> -- reason text

Import discipline: jax loads lazily inside the tracing helpers, so waiver
parsing and finding/report rendering work on a bare host (the
tools/distlint convention).
"""

from __future__ import annotations

import fnmatch
import json
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

CHECKS = {
    "PL000": ("waiver hygiene",
              "a waiver with no written reason hides debt instead of "
              "naming it"),
    "PL001": ("unknown collective axis",
              "a collective equation uses an axis name outside the "
              "parallel/mesh.py authority"),
    "PL002": ("divergent branch collective order",
              "cond branches issue different ordered collective sequences "
              "— an SPMD deadlock, provable statically"),
    "PL003": ("dropped buffer donation",
              "declared donate_argnums not aliased in the compiled module "
              "(XLA drops donation silently on sharding/layout mismatch)"),
    "PL004": ("precision promotion leak",
              "f32/f64 dot/conv compute inside a program declared "
              "bf16/int8"),
    "PL005": ("hot-path recompilation",
              "the program's trace cache grew past its allowed shape "
              "count — a shape/dtype varies per dispatch"),
}

#: primitives whose equations carry a mesh axis (axes= on psum/psum2,
#: axis_name= on the rest). NOT a dtype/shape reduction like reduce_sum,
#: whose ``axes`` are positional ints — the walker only reads axis params
#: from this set and keeps string values only.
COLLECTIVE_PRIMS = frozenset({
    "psum", "psum2", "pmin", "pmax", "pmean", "all_gather", "all_to_all",
    "ppermute", "pbroadcast", "reduce_scatter", "axis_index",
})

#: compute-heavy primitives PL004 holds to the declared precision
_COMPUTE_PRIMS = frozenset({"dot_general", "conv_general_dilated"})

#: config precisions that declare a low-precision compute program.
#: ("bf16_params" keeps f32 compute on purpose — master-weights style —
#: so it is NOT in this set.)
LOW_PRECISION = frozenset({"bf16", "int8"})

DEFAULT_WAIVERS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "scripts", "proglint_waivers.txt")


class AuditError(RuntimeError):
    """Raised under ``audit=halt`` when a program carries unwaivered
    findings (compile-time checks) or trips the recompile sentry."""


@dataclass
class Finding:
    """One audit finding against one program."""

    check: str
    program: str
    message: str
    waived: bool = False
    reason: str = ""

    def render(self) -> str:
        tag = f" [waived: {self.reason}]" if self.waived else ""
        return (f"{self.program}: {self.check} "
                f"{CHECKS[self.check][0]}: {self.message}{tag}")

    def to_json(self) -> dict:
        return {"check": self.check, "program": self.program,
                "message": self.message, "waived": self.waived,
                "reason": self.reason}


# ---- waivers ---------------------------------------------------------------

@dataclass(frozen=True)
class Waiver:
    check: str
    pattern: str      # fnmatch glob over the program name
    reason: str
    line: int = 0


def parse_waivers(text: str,
                  origin: str = "<waivers>") -> Tuple[List[Waiver],
                                                      List[Finding]]:
    """Parse the waiver grammar. A syntactically-valid waiver missing its
    ``-- reason`` is returned as a PL000 finding, not silently honored —
    the reason requirement is the whole point of the grammar."""
    waivers: List[Waiver] = []
    meta: List[Finding] = []
    for i, raw in enumerate(text.splitlines(), 1):
        line = raw.split("#", 1)[0].strip()
        if not line:
            continue
        head, sep, reason = line.partition("--")
        parts = head.split()
        if len(parts) != 2 or parts[0] not in CHECKS:
            meta.append(Finding("PL000", origin,
                                f"line {i}: unparseable waiver {raw!r} "
                                "(grammar: 'PLNNN <program-glob> -- "
                                "reason')"))
            continue
        reason = reason.strip()
        if not sep or not reason:
            meta.append(Finding("PL000", origin,
                                f"line {i}: waiver for {parts[0]} on "
                                f"{parts[1]!r} has no reason"))
            continue
        waivers.append(Waiver(parts[0], parts[1], reason, i))
    return waivers, meta


def load_waivers(path: Optional[str] = None
                 ) -> Tuple[List[Waiver], List[Finding]]:
    path = DEFAULT_WAIVERS if path is None else path
    if not os.path.exists(path):
        return [], []
    with open(path) as f:
        return parse_waivers(f.read(), origin=os.path.basename(path))


def apply_waivers(findings: Iterable[Finding],
                  waivers: Sequence[Waiver]) -> List[Finding]:
    """Mark each finding waived when a (check, program-glob) waiver
    matches; findings are returned (same objects) for chaining."""
    out = list(findings)
    for f in out:
        for w in waivers:
            if w.check == f.check and fnmatch.fnmatch(f.program, w.pattern):
                f.waived, f.reason = True, w.reason
                break
    return out


def unwaivered(findings: Iterable[Finding]) -> List[Finding]:
    return [f for f in findings if not f.waived]


# ---- jaxpr walking ---------------------------------------------------------

def _sub_jaxprs(eqn):
    """Every jaxpr nested in an equation's params (pjit/shard_map jaxpr=,
    cond branches=, scan/while bodies, custom_vjp call_jaxpr, ...)."""
    from jax import core

    for v in eqn.params.values():
        for x in (v if isinstance(v, (tuple, list)) else (v,)):
            if isinstance(x, core.ClosedJaxpr):
                yield x.jaxpr
            elif isinstance(x, core.Jaxpr):
                yield x


def iter_eqns(jaxpr):
    """Depth-first over every equation, including nested jaxprs."""
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in _sub_jaxprs(eqn):
            yield from iter_eqns(sub)


def _axis_names(eqn) -> Tuple[str, ...]:
    """The mesh-axis names a collective equation runs over. psum/psum2
    spell them ``axes=``, the rest ``axis_name=``; both may be a bare
    string or a tuple, and non-string entries (positional reduce axes)
    are not mesh axes."""
    v = eqn.params.get("axes", eqn.params.get("axis_name", ()))
    if isinstance(v, str):
        v = (v,)
    return tuple(x for x in (v or ()) if isinstance(x, str))


def collective_signature(jaxpr) -> Tuple[Tuple[str, Tuple[str, ...]], ...]:
    """The ordered (primitive, axes) sequence of every collective in the
    jaxpr, nested bodies included — the thing PL002 compares across
    branches (MPI-matching: order IS the correctness condition)."""
    return tuple((eqn.primitive.name, _axis_names(eqn))
                 for eqn in iter_eqns(jaxpr)
                 if eqn.primitive.name in COLLECTIVE_PRIMS)


def mesh_axis_authority() -> frozenset:
    """The declared axis names, by reflection over parallel/mesh.py (the
    same authority distlint's DL003 AST-extracts)."""
    from tpu_dist.parallel import mesh as mesh_mod

    return frozenset(v for k, v in vars(mesh_mod).items()
                     if k.endswith("_AXIS") and isinstance(v, str))


# ---- the jaxpr checks ------------------------------------------------------

def _check_axes(program: str, jaxpr, authority) -> List[Finding]:
    unknown: Dict[str, str] = {}
    for eqn in iter_eqns(jaxpr):
        if eqn.primitive.name in COLLECTIVE_PRIMS:
            for ax in _axis_names(eqn):
                if ax not in authority:
                    unknown.setdefault(ax, eqn.primitive.name)
    return [Finding("PL001", program,
                    f"collective {prim} over axis {ax!r} not in the mesh "
                    f"authority {sorted(authority)}")
            for ax, prim in sorted(unknown.items())]


def _check_branches(program: str, jaxpr) -> List[Finding]:
    out = []
    for eqn in iter_eqns(jaxpr):
        if eqn.primitive.name != "cond":
            continue
        sigs = [collective_signature(br.jaxpr)
                for br in eqn.params["branches"]]
        if any(sigs) and len(set(sigs)) > 1:
            shown = [" -> ".join(f"{p}{list(a)}" for p, a in s) or "(none)"
                     for s in sigs]
            out.append(Finding(
                "PL002", program,
                "cond branches issue divergent collective sequences: "
                + " VS ".join(shown)))
    return out


def _check_precision(program: str, jaxpr,
                     precision: Optional[str]) -> List[Finding]:
    if precision not in LOW_PRECISION:
        return []
    import numpy as np

    leaks: Dict[str, int] = {}
    for eqn in iter_eqns(jaxpr):
        name = eqn.primitive.name
        try:
            out_dtypes = [np.dtype(v.aval.dtype) for v in eqn.outvars
                          if hasattr(v.aval, "dtype")]
            in_dtypes = [np.dtype(v.aval.dtype) for v in eqn.invars
                         if hasattr(v.aval, "dtype")]
        except Exception:
            continue
        if any(d == np.float64 for d in out_dtypes):
            leaks[f"{name}:f64"] = leaks.get(f"{name}:f64", 0) + 1
        elif (name in _COMPUTE_PRIMS and in_dtypes
                and all(d == np.float32 for d in in_dtypes)):
            leaks[f"{name}:f32"] = leaks.get(f"{name}:f32", 0) + 1
    return [Finding("PL004", program,
                    f"{n} {prim.split(':')[0]} equation(s) compute in "
                    f"{prim.split(':')[1]} inside a {precision} program")
            for prim, n in sorted(leaks.items())]


def _donation_declared(jaxpr) -> bool:
    for eqn in iter_eqns(jaxpr):
        if any(eqn.params.get("donated_invars") or ()):
            return True
    return False


def donation_aliased(hlo_text: str) -> bool:
    """Whether the compiled module's header carries any input/output
    alias. XLA states donation in the one-line ``HloModule`` header
    (``input_output_alias={ {}: (0, {}, may-alias) }``) and OMITS the
    field entirely when every donation was dropped."""
    head = hlo_text.splitlines()[0] if hlo_text else ""
    return "input_output_alias=" in head


def _check_donation(program: str, jaxpr,
                    hlo: Optional[str]) -> List[Finding]:
    if hlo is None or not _donation_declared(jaxpr):
        return []
    if donation_aliased(hlo):
        return []
    return [Finding(
        "PL003", program,
        "donate_argnums declared but the compiled module aliases NO "
        "buffer — donation was dropped (sharding/layout mismatch) and "
        "the state is double-buffered in HBM")]


def audit_jaxpr(program: str, closed, *, authority=None,
                precision: Optional[str] = None,
                hlo: Optional[str] = None) -> List[Finding]:
    """The compile-time pass over one traced program: PL001 + PL002 +
    PL004 on the jaxpr, PL003 against the compiled module's header when
    the caller has it (engines pass telemetry.program_stats' HLO text —
    no extra lowering). ``closed`` is a ClosedJaxpr or Jaxpr."""
    jaxpr = getattr(closed, "jaxpr", closed)
    authority = mesh_axis_authority() if authority is None else authority
    findings = _check_axes(program, jaxpr, authority)
    findings += _check_branches(program, jaxpr)
    findings += _check_precision(program, jaxpr, precision)
    findings += _check_donation(program, jaxpr, hlo)
    return findings


# ---- the runtime sentry (PL005) -------------------------------------------

class RecompileSentry:
    """Per-program trace-cache watch. ``register`` is idempotent (first
    dispatch re-registers freely); ``check`` is a host-only counter read
    sized for drain boundaries — no device sync, no tracing — and
    latches one finding per program so ``record`` mode emits exactly one
    ``audit`` event per offender."""

    def __init__(self):
        self._programs: Dict[str, dict] = {}

    def register(self, program: str, fn, allowed: int = 1) -> None:
        rec = self._programs.setdefault(
            program, {"fn": fn, "allowed": allowed, "flagged": False})
        rec["fn"] = fn
        rec["allowed"] = max(rec["allowed"], allowed)

    def check(self) -> List[Finding]:
        out = []
        for name in sorted(self._programs):
            rec = self._programs[name]
            size_fn = getattr(rec["fn"], "_cache_size", None)
            if size_fn is None or rec["flagged"]:
                continue
            n = size_fn()
            if n > rec["allowed"]:
                rec["flagged"] = True
                out.append(Finding(
                    "PL005", name,
                    f"trace cache holds {n} entries (allowed "
                    f"{rec['allowed']}): a shape/dtype is varying per "
                    "dispatch and every variation recompiles on the hot "
                    "path"))
        return out


# ---- tune-space audit (satellite: every plan the repo can execute) --------

def _structural_key(plan) -> tuple:
    """Plans that trace to the SAME program: quant_block/opt_block_rows/
    fused_quant only move Pallas block params (trace-time constants) —
    auditing one representative per key covers the whole space."""
    return (plan.engine, plan.sync, plan.layout, plan.tp_impl, plan.quant,
            plan.window, plan.steps_per_dispatch, plan.grad_bucket_mb > 0,
            plan.grad_accum_steps, plan.donate)


def _program_name(plan) -> str:
    return (f"{plan.engine}/{plan.sync}/quant={plan.quant}"
            f"/window={plan.window}"
            + (f"x{plan.steps_per_dispatch}"
               if plan.window != "none" else "")
            + ("/bucketed" if plan.grad_bucket_mb > 0 else ""))


def _tiny_lm_fixture(quant: str):
    """The 1-layer/32-dim trace fixture (tests/test_plan.py recipe):
    enough structure for every knob in the space, cheap enough to trace
    the whole deduped space inside the tier-1 budget."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_dist.engine.state import TrainState
    from tpu_dist.models.transformer import tiny_lm
    from tpu_dist.ops import make_optimizer

    V, L, D = 32, 16, 32
    model = tiny_lm(vocab_size=V, num_layers=1, d_model=D, num_heads=4,
                    max_len=L, quant=quant)
    rng = jax.random.PRNGKey(0)
    params = model.init({"params": rng},
                        np.zeros((1, L), np.int32), train=False)["params"]
    tx = make_optimizer(0.01, 0.9, 0.0)
    state = TrainState.create(jax.tree.map(jnp.copy, params), {}, tx)
    rows = np.random.RandomState(0).randint(0, V, (8, L + 1)).astype(
        np.int32)
    return model, tx, state, rows, rng


def audit_tune_space(space=None, *, waivers_path: Optional[str] = None,
                     devices: int = 8) -> dict:
    """Trace + audit every structurally-distinct program in the tuner's
    candidate space (CPU, abstract tracing only — nothing executes) and
    return a canonical, byte-deterministic report dict. Every plan is
    accounted for: ``plans`` counts the space, ``programs`` the deduped
    trace set — no silent caps."""
    import numpy as np

    import jax

    from tpu_dist.parallel.mesh import make_mesh
    from tpu_dist.plan.compile import (Bindings, activate_plan,
                                       compile_train_step)
    from tpu_dist.plan.tune import default_space

    if space is None:
        space = default_space("lm", devices)
    mesh = make_mesh((devices,), ("data",),
                     devices=jax.devices()[:devices])
    groups: Dict[tuple, list] = {}
    for plan in space:
        groups.setdefault(_structural_key(plan), []).append(plan)

    findings: List[Finding] = []
    programs: List[str] = []
    fixtures: Dict[str, tuple] = {}
    try:
        for key in sorted(groups, key=repr):
            plan = groups[key][0]
            if plan.quant not in fixtures:
                fixtures[plan.quant] = _tiny_lm_fixture(plan.quant)
            model, tx, state, rows, rng = fixtures[plan.quant]
            name = _program_name(plan)
            programs.append(name)
            activate_plan(plan)
            step = compile_train_step(plan, Bindings(mesh=mesh, model=model,
                                                     tx=tx))
            if plan.window == "none":
                args = (state, rows[:, :-1], rows[:, 1:], rng)
            else:
                k = plan.steps_per_dispatch
                big = np.tile(rows, (k, 1))
                idx = np.arange(k * 8, dtype=np.int32).reshape(k, 8)
                args = (state, big, idx, rng)
            closed = jax.make_jaxpr(step)(*args)
            findings += audit_jaxpr(name, closed)
    finally:
        # restore the plan-owned trace-time globals (the
        # clean_plan_globals contract in tests/test_plan.py)
        from tpu_dist.ops import pallas_adamw, pallas_quant, pallas_sgd
        from tpu_dist.ops.quant import set_fused_quant

        set_fused_quant(None)
        pallas_quant.set_quant_blocks()
        pallas_sgd.set_block_rows()
        pallas_adamw.set_block_rows()

    waivers, meta = load_waivers(waivers_path)
    findings = apply_waivers(findings, waivers) + meta
    findings.sort(key=lambda f: (f.program, f.check, f.message))
    return {
        "plans": len(space),
        "programs": len(programs),
        "program_names": programs,
        "findings": [f.to_json() for f in findings],
        "unwaivered": len(unwaivered(findings)),
    }


# ---- report side (mirrors tools/distlint/report.py) -----------------------

SARIF_SCHEMA = "https://json.schemastore.org/sarif-2.1.0.json"
SARIF_VERSION = "2.1.0"


def to_sarif(findings: Sequence[Finding]) -> dict:
    """SARIF 2.1.0 document, same shape as distlint's (driver name is
    the only divergence) so one CI code-scanning upload handles both."""
    rules_meta = [{
        "id": cid,
        "shortDescription": {"text": CHECKS[cid][0]},
        "fullDescription": {"text": CHECKS[cid][1]},
        "defaultConfiguration": {"level": "error"},
    } for cid in sorted(CHECKS)]
    results = [{
        "ruleId": f.check,
        "level": "note" if f.waived else "error",
        "message": {"text": f.message
                    + (f" [waived: {f.reason}]" if f.waived else "")},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {
                    "uri": f"programs/{f.program}",
                    "uriBaseId": "SRCROOT",
                },
                "region": {"startLine": 1, "startColumn": 1},
            },
        }],
    } for f in findings]
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {"name": "proglint",
                                "rules": rules_meta}},
            "results": results,
        }],
    }


def _main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m tpu_dist.analysis.proglint",
        description="audit every program in the tuner's candidate space")
    parser.add_argument("--tune-space", default=None, metavar="FILE",
                        help="comm_bench measurement JSON naming the "
                        "device kind (scripts/tune_ci.json); the audited "
                        "space is the tuner's enumeration for it")
    parser.add_argument("--devices", type=int, default=8,
                        help="virtual CPU device count for the trace mesh")
    parser.add_argument("--waivers", default=None,
                        help=f"waiver file (default {DEFAULT_WAIVERS})")
    parser.add_argument("--json", default=None, metavar="FILE",
                        help="write the canonical report JSON here "
                        "('-' for stdout)")
    parser.add_argument("--sarif-out", default=None, metavar="FILE",
                        help="write a SARIF 2.1.0 artifact here")
    args = parser.parse_args(argv)

    # same virtual-device setup as tests/conftest.py, before any backend
    # initializes (the sitecustomize pre-import makes env vars too late)
    import jax

    jax.config.update("jax_platforms", "cpu")
    from tpu_dist._compat import set_cpu_device_count

    set_cpu_device_count(max(args.devices, 1))

    from tpu_dist.plan.tune import default_space

    devices = args.devices
    if args.tune_space:
        with open(args.tune_space) as f:
            json.load(f)     # existence + shape check only: the space is
        #                      the tuner's enumeration, not the trials
    space = default_space("lm", devices)
    report = audit_tune_space(space, waivers_path=args.waivers,
                              devices=devices)
    text = json.dumps(report, indent=1, sort_keys=True) + "\n"
    if args.json == "-":
        print(text, end="")
    elif args.json:
        with open(args.json, "w") as f:
            f.write(text)
    if args.sarif_out:
        findings = [Finding(**d) for d in report["findings"]]
        with open(args.sarif_out, "w") as f:
            json.dump(to_sarif(findings), f, indent=2, sort_keys=True)
            f.write("\n")
    for d in report["findings"]:
        print(Finding(**d).render())
    print(f"proglint: {report['plans']} plan(s) -> {report['programs']} "
          f"distinct program(s), {len(report['findings'])} finding(s), "
          f"{report['unwaivered']} unwaivered")
    return 1 if report["unwaivered"] else 0


if __name__ == "__main__":
    raise SystemExit(_main())
