"""Program-level analysis (tpu_dist.analysis).

Home of :mod:`tpu_dist.analysis.proglint`, the jaxpr/compiled-program
auditor — the post-trace complement of the source-level tools/distlint.
Kept lazy (no submodule imports here) so `import tpu_dist.analysis`
stays jax-free; the auditor itself imports jax only inside the tracing
helpers.
"""
