"""Elastic run supervisor: close the detect->remediate loop.

Rounds 2-7 built the *detect* half of self-healing — watchdog stalls,
``health record|skip|halt``, auto-triggered flight recorder, restart-aware
``job_id``/``attempt`` lineage with ``restart_gap`` goodput — but nothing
ever acted: a hang, a ``HealthError`` halt, a preemption or a crashed host
simply ended the run, and recovery was a human re-running the script. The
reference's variant 6 (``6.distributed_slurm_main.py``) leaned on Slurm
``--requeue`` for exactly this; the torch ecosystem answer is
torchelastic's supervised restarts. This module is the TPU-native version,
in two flavors:

* **Subprocess CLI** — ``python -m tpu_dist.supervise --ledger run.jsonl
  --ckpt-dir ck -- python scripts/8.lm_longcontext.py ...``:
  :class:`Supervisor` launches the training command, watches liveness
  through the attempt ledger's tail and a heartbeat file, classifies every
  exit (:func:`classify_attempt`), and restarts under a bounded policy —
  ``attempt=-1`` auto-lineage so PR 7's stitching/goodput sees every
  attempt, ``--resume`` pointed at the newest VALID checkpoint
  (:func:`latest_checkpoint` — the pointer only ever names a committed
  container), exponential backoff, crash-loop cutoff when K consecutive
  attempts die before their first ``step`` event, and on confirmed
  rendezvous/host loss a degraded dp-only relaunch on the survivors
  (:func:`degraded_env`). A watchdog-confirmed stall (the child's own
  ``stall`` ledger event with no progress after it) is SIGKILLed and
  restarted — the one failure class where waiting is the wrong move.

* **Library API** — :func:`run_supervised` wraps a trainer factory in the
  same policy loop *in process* (both engine scripts opt in via the
  ``max_restarts`` config knob): ``HealthError`` halts and organic
  exceptions restart from the newest valid checkpoint with fresh attempt
  lineage. Process-killing failures (``os._exit``, SIGKILL, host loss)
  need the subprocess flavor by construction.

Round 13 makes the capacity ELASTIC, not just shrinking: with a
``consensus`` directory configured (:mod:`tpu_dist.parallel.consensus`,
file-based and jax-free like everything here), per-host supervisors agree
on the live host set every rendezvous epoch — a mid-numbered host loss
renumbers ``TPU_DIST_PROCESS_ID`` densely over the survivors instead of
dying in ``restarts_exhausted`` (the old ``degraded_env`` KNOWN LIMIT),
and a lost host re-registering bumps the epoch and relaunches the
children at the restored world size (shrink is two-way). A preemption
SIGTERM is forwarded into the child with a deadline
(``TPU_DIST_PREEMPT_DEADLINE_S``): the engine finishes the in-flight
step, writes a coordinated snapshot and exits
``preemption_snapshotted`` (rc ``PREEMPT_SNAPSHOT_RC``), so the restart
resumes from the pre-preemption step, not the last periodic checkpoint.
Every transition lands as a ``scale`` ledger event in the supervisor's
own ``<stem>.sup.jsonl`` sibling, which ``tools/ledger_report`` stitches
into the elasticity timeline.

Everything here is importable WITHOUT jax (``scripts/lint.sh`` runs the
policy math on a bare host as a CI gate); the training child owns all
device state. Deterministic fault injection for every path lives in
:mod:`tpu_dist.obs.faults`.
"""

from __future__ import annotations

import glob
import json
import os
import signal
import subprocess
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from tpu_dist.obs.goodput import attempt_path, next_attempt_index
from tpu_dist.parallel.consensus import ConsensusDir, MeshView, consensus_env

# every attempt ends in exactly one of these
FAILURE_CLASSES = ("clean", "health_halt", "stall", "preemption",
                   "preemption_snapshotted", "rendezvous", "crash")

# the exit code of a preemption honored WITH a coordinated snapshot
# (EX_TEMPFAIL: "try again later" — the engines exit with it after the
# barriered checkpoint lands, so the restart resumes the exact step)
PREEMPT_SNAPSHOT_RC = 75

# ledger events that prove the run is making forward progress (the stall
# event itself grows the ledger too — it must NOT reset the liveness clock)
_PROGRESS_EVENTS = frozenset({
    "run_start", "compile", "step", "epoch", "eval", "ckpt", "decode"})


class CrashLoopError(RuntimeError):
    """K consecutive attempts died before their first step — restarting
    again would burn the allocation on the same deterministic failure."""


@dataclass
class RestartPolicy:
    """Bounded-restart knobs (pure data; the no-jax lint gate imports it)."""

    max_restarts: int = 10          # restarts, not attempts (N+1 attempts)
    backoff_base_s: float = 1.0     # base * 2^(restart-1), capped below
    backoff_max_s: float = 60.0
    crash_loop_k: int = 3           # consecutive pre-first-step deaths
    # idle backstop, deliberately generous: the FIRST liveness signal is
    # the post-compile heartbeat, so this must exceed any first XLA
    # compile (large LM programs take many minutes) — SIGKILLing a
    # healthy compile would read as a pre-first-step death and trip the
    # crash-loop cutoff. Real hangs are caught much faster by the
    # child's own watchdog 'stall' event + stall_grace_s below.
    stall_timeout_s: float = 1800.0  # ledger/heartbeat silence -> SIGKILL
    stall_grace_s: float = 10.0     # after a watchdog 'stall' event lands
    shrink_on_host_loss: bool = True
    # deterministic per-host backoff spread (fraction of the base wait):
    # without it, N hosts restarting after one shared failure all sleep
    # the SAME exponential schedule and stampede the rendezvous
    # coordinator in lockstep on every retry
    backoff_jitter: float = 0.5
    # seconds the child gets between SIGTERM and SIGKILL to finish its
    # in-flight step and write the coordinated preemption snapshot
    preempt_deadline_s: float = 30.0


def _jitter_u(host_id: int, restart_no: int) -> float:
    """Deterministic uniform-ish [0, 1) from (host, restart): a tiny
    integer hash, NOT random — the same host always picks the same
    offset (reproducible runs), different hosts decorrelate, and the
    restart ordinal keeps repeat collisions from re-aligning."""
    x = (host_id * 2654435761 + restart_no * 40503 + 0x9E3779B9) \
        & 0xFFFFFFFF
    x ^= x >> 16
    x = (x * 2246822519) & 0xFFFFFFFF
    x ^= x >> 13
    return x / 2.0 ** 32


def compute_backoff(restart_no: int, policy: RestartPolicy,
                    host_id: Optional[int] = None) -> float:
    """Seconds to wait before restart #``restart_no`` (1-based):
    exponential from ``backoff_base_s``, capped at ``backoff_max_s``.
    With a ``host_id``, a deterministic per-host jitter stretches the
    wait by up to ``backoff_jitter`` x itself, de-synchronizing the
    cross-host restart stampede; without one (report-side/unit callers)
    the schedule is the bare exponential."""
    if restart_no <= 0:
        return 0.0
    wait = min(policy.backoff_base_s * (2.0 ** (restart_no - 1)),
               policy.backoff_max_s)
    if host_id is not None and policy.backoff_jitter > 0:
        wait *= 1.0 + policy.backoff_jitter * _jitter_u(host_id, restart_no)
    return wait


def classify_attempt(records: List[dict], returncode: Optional[int] = None,
                     killed_for_stall: bool = False,
                     stderr_tail: str = "") -> str:
    """One attempt's failure class, from its ledger records + exit status.

    Pure and jax-free: the supervisor calls it with the child's returncode
    and captured stderr tail; ``tools/ledger_report`` calls it with
    records alone (``returncode=None``) to classify attempts after the
    fact. Precedence: a supervisor-confirmed stall kill beats everything
    (the rc is just our own SIGKILL); then the run's own account
    (``run_end`` status/error), then the exit code, then stderr."""
    if killed_for_stall:
        return "stall"
    ends = [r for r in records if r.get("event") == "run_end"]
    end = ends[-1] if ends else None
    status = (end or {}).get("status")
    err = str((end or {}).get("error") or "")
    if returncode == 0 or (returncode is None and end is not None
                           and status in (None, "ok")):
        return "clean"
    if status == "preempted" or returncode == PREEMPT_SNAPSHOT_RC:
        # the preemption was HONORED: the engine finished its in-flight
        # step and committed the coordinated snapshot before exiting, so
        # the restart resumes the exact pre-preemption step
        return "preemption_snapshotted"
    if "HealthError" in err or "health=halt" in err:
        return "health_halt"
    if ("SIGTERM" in err or status == "interrupted"
            or returncode in (-signal.SIGTERM, 128 + signal.SIGTERM)):
        return "preemption"
    blob = (err + "\n" + stderr_tail).lower()
    # only a launch-phase death (no run_end: the child never got far
    # enough to account for itself) may be blamed on rendezvous, and only
    # on the EXHAUSTION message — the retry wrapper's per-attempt
    # "rendezvous attempt k/N ... retrying" warnings linger in the stderr
    # tail of runs that rendezvoused fine and died later of other causes
    if end is None and ("rendezvous failed" in blob
                        or "could not reach coordinator" in blob
                        or "deadline_exceeded" in blob):
        return "rendezvous"
    if end is None and any(r.get("event") == "stall" for r in records):
        # the child died mid-stall without our kill (OOM-killer, operator)
        return "stall"
    return "crash"


def latest_checkpoint(ckpt_dir: str) -> Optional[str]:
    """The newest VALID checkpoint in a dir, without jax or
    deserialization: the ``*-checkpoint.index.json`` pointer when present
    (engine.checkpoint writes it only after a fully-committed container,
    so an ENOSPC'd or torn write never advances it), else the newest
    ``*-checkpoint.msgpack`` by mtime."""
    if not ckpt_dir or not os.path.isdir(ckpt_dir):
        return None
    # newest pointer first, not alphabetical: a dir that ever held another
    # arch's checkpoints must not resume this run from the wrong model
    idx_files = sorted(glob.glob(
        os.path.join(ckpt_dir, "*-checkpoint.index.json")),
        key=os.path.getmtime, reverse=True)
    for idx in idx_files:
        try:
            with open(idx) as f:
                pointer = json.load(f)
            path = os.path.join(ckpt_dir, pointer["newest"])
        except (OSError, ValueError, KeyError, TypeError):
            continue
        if os.path.exists(path):
            return path
    cands = glob.glob(os.path.join(ckpt_dir, "*-checkpoint.msgpack"))
    return max(cands, key=os.path.getmtime) if cands else None


def degraded_env(env: Dict[str, str],
                 lost: int = 1) -> Tuple[Dict[str, str], int]:
    """The relaunch environment after confirmed host loss: the mesh
    re-forms on the survivors (``TPU_DIST_NUM_PROCESSES`` shrunk by
    ``lost``) and ``TPU_DIST_DEGRADED=1`` marks the run so reports can
    tell a degraded layout from the planned one. Returns (env, survivors).
    Pure — unit-testable without processes.

    NOTE: ``TPU_DIST_PROCESS_ID`` is NOT renumbered here — this is the
    consensus-LESS fallback (no shared dir configured), where each host's
    supervisor only sees its own env. It re-forms cleanly when the LOST
    host held the highest id (ids stay dense) and for the 1-survivor
    case. Closing a MID-numbered id hole needs the cross-host agreement
    of :mod:`tpu_dist.parallel.consensus` (round 13): with a
    ``--consensus-dir``, :func:`consensus_env` renumbers densely over the
    agreed survivor order and this function never runs."""
    n = int(env.get("TPU_DIST_NUM_PROCESSES", "1") or 1)
    survivors = max(n - max(lost, 0), 1)
    out = dict(env)
    if survivors < n:
        out["TPU_DIST_NUM_PROCESSES"] = str(survivors)
        out["TPU_DIST_DEGRADED"] = "1"
    return out, survivors


# the dp-only degraded layout: mesh shape reset to auto (all remaining
# devices) over the plain data axis — appended on relaunch after shrink
DEGRADED_FLAGS = ("--mesh-shape", "", "--mesh-axes", "data")


@dataclass
class AttemptResult:
    attempt: int
    returncode: Optional[int]
    failure_class: str
    steps: int
    seconds: float
    ledger: str = ""


@dataclass
class SupervisorResult:
    status: str  # clean | crash_loop | restarts_exhausted | stopped
    attempts: List[AttemptResult] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return self.status == "clean"


class _StderrTail(threading.Thread):
    """Forward the child's stderr to ours while keeping the last N lines
    (classification evidence for deaths that never reached the ledger)."""

    def __init__(self, pipe, maxlen: int = 50):
        super().__init__(name="supervise-stderr", daemon=True)
        self._pipe = pipe
        self.lines: deque = deque(maxlen=maxlen)

    def run(self) -> None:
        try:
            for line in self._pipe:
                self.lines.append(line)
                sys.stderr.write(line)
        except ValueError:
            pass  # pipe closed under us at kill time
        finally:
            try:
                self._pipe.close()
            except OSError:
                pass

    def tail(self) -> str:
        return "".join(self.lines)


class _LedgerTail:
    """Incremental reader of an attempt ledger: which events arrived since
    the last poll (partial trailing lines are held back, not mangled)."""

    def __init__(self, path: str):
        self.path = path
        self._offset = 0
        self._partial = b""

    def poll(self) -> List[str]:
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return []
        if size <= self._offset:
            return []
        with open(self.path, "rb") as f:
            f.seek(self._offset)
            chunk = f.read(size - self._offset)
        self._offset = size
        data = self._partial + chunk
        lines = data.split(b"\n")
        self._partial = lines.pop()  # "" on a complete trailing newline
        events = []
        for line in lines:
            try:
                rec = json.loads(line)
                ev = rec.get("event")
                if ev:
                    events.append(ev)
            except (ValueError, AttributeError):
                continue  # torn line mid-crash: liveness only, not truth
        return events


def _read_records(path: str) -> List[dict]:
    """Best-effort full read of an attempt ledger (schema-lenient: the
    crashed child is exactly the one with torn lines)."""
    from tpu_dist.obs.ledger import read_ledger

    try:
        return read_ledger(path, validate=False, strict=False)
    except OSError:
        return []


class Supervisor:
    """Launch, watch, classify, restart — the policy loop around one
    training command. See the module docstring for the contract; every
    knob of :class:`RestartPolicy` applies."""

    def __init__(self, cmd: List[str], ledger: str, ckpt_dir: str = "",
                 policy: Optional[RestartPolicy] = None,
                 env: Optional[Dict[str, str]] = None,
                 forward_flags: bool = True, poll_s: float = 0.25,
                 sleep: Callable[[float], None] = time.sleep,
                 consensus: Optional[ConsensusDir] = None,
                 consensus_poll_s: float = 1.0,
                 on_attempt: Optional[Callable[["AttemptResult"],
                                               None]] = None,
                 retune: Optional[Dict] = None):
        if not cmd:
            raise ValueError("supervisor needs a training command "
                             "(everything after '--')")
        if not ledger:
            raise ValueError("supervisor needs --ledger: the attempt "
                             "ledgers are its liveness + lineage signal")
        self.cmd = list(cmd)
        self.ledger = ledger
        self.ckpt_dir = ckpt_dir
        self.policy = policy or RestartPolicy()
        self.env = dict(os.environ if env is None else env)
        self.forward_flags = forward_flags
        self.poll_s = poll_s
        self._sleep = sleep
        self.degraded = False
        # elastic consensus (round 13): cross-host membership + dense
        # renumbering; None keeps the PR-10 single-host fallback paths
        self.consensus = consensus
        self.consensus_poll_s = consensus_poll_s
        try:
            self.host_id = (consensus.host_id if consensus is not None else
                            int(self.env.get("TPU_DIST_PROCESS_ID", "0")
                                or 0))
        except ValueError:
            self.host_id = 0
        self._view: Optional[MeshView] = None   # the view the child runs at
        self._scale_relaunch = False            # WE ended the attempt to
        self._peer_resume_next = False          # rescale, not a failure
        self._scale_ledger = None
        # scenario hooks (round 14, tpu_dist.sim): a fleet driver observes
        # every classified attempt and can end the policy loop externally
        self.on_attempt = on_attempt
        self._stop = threading.Event()
        # autoscaling (round 20, obs.autoscale): with a retune config
        # ({device_kind, devices_per_host, plan_dir, workload?,
        # measurement_files?}) every world-size transition re-runs
        # plan.tune deterministically at the new size and stamps the plan
        # hash into an `applied` event; the fleet driver sets
        # `autoscale_decision` just before the membership change so the
        # resulting scale + applied events carry the decision id
        self.retune = dict(retune) if retune else None
        self.autoscale_decision: Optional[str] = None

    def request_stop(self) -> None:
        """Ask the policy loop to end (thread-safe, callable from any
        thread): a running child is terminated gracefully — SIGTERM with
        the preemption deadline, so a snapshot-capable child drains and
        exits ``PREEMPT_SNAPSHOT_RC`` — and no further restarts happen
        (``SupervisorResult.status == "stopped"``). The fleet simulator's
        scenario-end teardown; also the backstop for a wedged run."""
        self._stop.set()

    def _log(self, msg: str) -> None:
        print(f"[supervise] {msg}", file=sys.stderr, flush=True)

    # -- scale events (the supervisor's own ledger sibling) --------------
    def _ensure_scale_ledger(self):
        """Lazily open ``<stem>.sup.jsonl`` — the supervisor's own ledger
        (obs.ledger is stdlib-only, so this stays jax-free);
        ledger_report merges it into the job timeline."""
        if self._scale_ledger is None:
            from tpu_dist.obs.goodput import sup_sibling_path
            from tpu_dist.obs.ledger import Ledger

            try:
                self._scale_ledger = Ledger(sup_sibling_path(self.ledger))
            except OSError as e:
                self._log(f"warning: no scale ledger ({e})")
                self._scale_ledger = False
        return self._scale_ledger or None

    def _emit_scale(self, action: str, processes: int,
                    epoch: Optional[int], **extra) -> None:
        self._ensure_scale_ledger()
        if self._scale_ledger:
            try:
                self._scale_ledger.emit("scale", action=action,
                                        processes=processes, epoch=epoch,
                                        **extra)
            except Exception as e:
                self._log(f"warning: scale event dropped ({e})")

    def _resolve_view(self) -> Optional[MeshView]:
        """One consensus round + the env/flag fallout: dense renumbering,
        degraded marking, shrink/expand scale events, and the one-shot
        peer-resume marker for a re-expansion relaunch."""
        if self.consensus is None:
            return None
        if self.consensus.fault_ledger is None:
            # a host_return injection must leave its `fault` event on the
            # record (injected-vs-organic accounting) — route it into the
            # scale-event sibling
            self.consensus.fault_ledger = self._ensure_scale_ledger()
        view = self.consensus.resolve()
        prev = self._view
        self.env = consensus_env(self.env, view, self.host_id)
        self.degraded = view.degraded
        if prev is None or view.epoch != prev.epoch:
            whence = f"{prev.world_size}->" if prev is not None else ""
            self._log(f"consensus epoch {view.epoch}: "
                      f"{whence}{view.world_size}/{view.planned} host(s) "
                      f"{list(view.hosts)} (process "
                      f"{view.process_id(self.host_id)} here)"
                      + (" DEGRADED" if view.degraded else ""))
        # transitions key on WORLD-SIZE changes, not degraded-flag edges:
        # a second loss while already degraded (3->2) is still a shrink,
        # and one of two lost hosts returning (2->3, still short of plan)
        # is still an expansion that needs the peer-resume relaunch
        world_from = prev.world_size if prev is not None else view.planned
        if view.world_size < world_from:
            dec, self.autoscale_decision = self.autoscale_decision, None
            self._emit_scale("shrink", view.world_size, view.epoch,
                             hosts=list(view.hosts), world_from=world_from,
                             decision=dec)
            self._maybe_retune(view, "shrink", dec)
        elif view.world_size > world_from:
            dec, self.autoscale_decision = self.autoscale_decision, None
            self._emit_scale("expand", view.world_size, view.epoch,
                             hosts=list(view.hosts), world_from=world_from,
                             decision=dec)
            self._maybe_retune(view, "expand", dec)
            # the grown world: a returning host has no local checkpoint,
            # so dp-pure engines pull state from a survivor over the wire
            # (engine.checkpoint.peer_restore_state)
            self._peer_resume_next = True
        self._view = view
        return view

    def _maybe_retune(self, view: MeshView, action: str,
                      decision: Optional[str]) -> None:
        """Close the decision's follow-up: re-run the deterministic plan
        autotuner (plan.tune — pure arithmetic, jax-free) at the NEW
        world size and stamp its best-plan hash into an ``applied`` event
        beside the scale event. The audit contract: a byte-identical
        re-run of tune at the same world size must reproduce the hash."""
        if not self.retune:
            return
        kind = self.retune.get("device_kind", "TPU v5 lite")
        devices = view.world_size * int(
            self.retune.get("devices_per_host", 1))
        plan_hash = None
        try:
            from tpu_dist.plan.tune import tune
            text, results = tune(
                measurement_files=self.retune.get("measurement_files"),
                device_kinds=[kind],
                workload={**(self.retune.get("workload") or {}),
                          "devices": devices})
            best = (results.get(kind) or {}).get("best")
            plan_hash = best["hash"] if best else None
            plan_dir = self.retune.get("plan_dir")
            if plan_dir:
                os.makedirs(plan_dir, exist_ok=True)
                with open(os.path.join(
                        plan_dir, f"plan_epoch{view.epoch}.json"), "w") as f:
                    f.write(text)
        except Exception as e:
            self._log(f"warning: retune at world {view.world_size} "
                      f"failed ({e})")
        self._ensure_scale_ledger()
        if self._scale_ledger:
            try:
                self._scale_ledger.emit(
                    "applied", decision=decision, action=action,
                    processes=view.world_size, epoch=view.epoch,
                    plan_hash=plan_hash, devices=devices)
            except Exception as e:
                self._log(f"warning: applied event dropped ({e})")

    # -- one attempt ----------------------------------------------------
    def _child_argv(self, resume: Optional[str]) -> List[str]:
        argv = list(self.cmd)
        if self.forward_flags:
            # argparse last-wins: the lineage/resume flags override
            # whatever the base command carries
            argv += ["--ledger-path", self.ledger, "--attempt", "-1"]
            if self.ckpt_dir:
                argv += ["--checkpoint-dir", self.ckpt_dir]
            if resume:
                argv += ["--resume", resume]
            if self.degraded:
                argv += list(DEGRADED_FLAGS)
        return argv

    def _run_child(self, argv: List[str], env: Dict[str, str],
                   attempt_file: str,
                   hb_file: str) -> Tuple[Optional[int], bool, str]:
        """(returncode, killed_for_stall, stderr_tail) for one attempt."""
        pol = self.policy
        proc = subprocess.Popen(argv, env=env, stderr=subprocess.PIPE,
                                text=True, errors="replace")
        tail = _StderrTail(proc.stderr)
        tail.start()
        try:
            ledger_tail = _LedgerTail(attempt_file)
            last_progress = time.monotonic()
            stall_confirmed: Optional[float] = None
            killed_for_stall = False
            scale_term = False
            launch_view = self._view
            last_consensus = time.monotonic()
            hb_mtime = 0.0
            while proc.poll() is None:
                self._sleep(self.poll_s)
                now = time.monotonic()
                if self._stop.is_set():
                    # external teardown (request_stop): same graceful
                    # SIGTERM-with-deadline path as a rescale — a
                    # snapshot-capable child drains and accounts for
                    # itself before the SIGKILL backstop
                    self._log("stop requested — SIGTERM, graceful "
                              "deadline, then teardown")
                    scale_term = True
                    proc.terminate()
                    break
                if (self.consensus is not None
                        and now - last_consensus >= self.consensus_poll_s):
                    # heartbeat our membership + watch for an epoch bump
                    # while the child runs: a returning host (or a further
                    # loss) re-forms the mesh NOW, not at the next crash
                    last_consensus = now
                    view = self._resolve_view()
                    if (launch_view is not None and view is not None
                            and view.epoch != launch_view.epoch):
                        grow = view.world_size > launch_view.world_size
                        self._log(
                            f"mesh epoch {launch_view.epoch} -> "
                            f"{view.epoch} mid-attempt "
                            f"({'re-expansion' if grow else 'shrink'} to "
                            f"{view.world_size}) — SIGTERM for snapshot, "
                            "then relaunch at the new world size")
                        self._scale_relaunch = True
                        scale_term = True
                        proc.terminate()
                        break
                progressed = False
                for ev in ledger_tail.poll():
                    if ev in _PROGRESS_EVENTS:
                        progressed = True
                        stall_confirmed = None  # the run moved again
                    elif ev == "stall":
                        stall_confirmed = stall_confirmed or now
                try:
                    mt = os.path.getmtime(hb_file)
                    if mt > hb_mtime:
                        hb_mtime = mt
                        # a heartbeat only counts while no stall is
                        # confirmed: the watchdog thread's own dump must
                        # not keep a hung step loop alive forever
                        if stall_confirmed is None:
                            progressed = True
                except OSError:
                    pass
                if progressed:
                    last_progress = now
                    continue
                idle = now - last_progress
                if ((stall_confirmed is not None
                     and now - stall_confirmed >= pol.stall_grace_s)
                        or idle >= pol.stall_timeout_s):
                    why = ("watchdog-confirmed stall" if stall_confirmed
                           else "no ledger/heartbeat progress for "
                                f"{idle:.0f}s")
                    self._log(f"{why} — SIGKILLing pid {proc.pid} "
                              "for restart")
                    killed_for_stall = True
                    proc.kill()
                    break
            if scale_term:
                # graceful rescale: the child gets the preemption deadline
                # to finish its in-flight step and commit the coordinated
                # snapshot (it exits PREEMPT_SNAPSHOT_RC), then SIGKILL
                try:
                    rc = proc.wait(timeout=pol.preempt_deadline_s)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    rc = proc.wait()
            else:
                rc = proc.wait()
        finally:
            # the supervisor must NEVER orphan a live trainer: a dying
            # supervisor (SIGTERM'd by the scheduler — run() converts it
            # to SystemExit so this unwinds — or any internal error)
            # would otherwise leave the child racing its own requeue on
            # the same ledger + checkpoint dir. SIGTERM first (the child
            # snapshots within the forwarded preemption deadline, or at
            # minimum the crash guard gets its run_end), SIGKILL after.
            if proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=max(5.0, pol.preempt_deadline_s))
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()
        tail.join(timeout=5.0)
        return rc, killed_for_stall, tail.tail()

    # -- the policy loop ------------------------------------------------
    def run(self) -> SupervisorResult:
        # a SIGTERM'd supervisor (scheduler preemption signals THIS pid,
        # not the child) must unwind through _run_child's finally and take
        # the child down with it; default SIGTERM disposition would kill
        # the supervisor instantly and orphan a live trainer. Library
        # callers on non-main threads keep their own handling.
        prev_term = None
        try:
            prev_term = signal.signal(
                signal.SIGTERM,
                lambda signum, frame: sys.exit(128 + signum))
        except ValueError:
            pass  # not the main thread
        try:
            return self._run_policy_loop()
        finally:
            if self.consensus is not None:
                # explicit deregistration: peers see this host's loss NOW
                # (clean finish or our own preemption) instead of waiting
                # out the membership lease
                self.consensus.leave()
            if prev_term is not None:
                signal.signal(signal.SIGTERM, prev_term)

    def _run_policy_loop(self) -> SupervisorResult:
        pol = self.policy
        attempts: List[AttemptResult] = []
        consecutive_dead = 0
        restarts = 0
        while True:
            if self._stop.is_set():
                # a stop that lands during backoff must not launch one
                # more child just to tear it down again
                return SupervisorResult("stopped", attempts)
            # two counters on purpose: the LEDGER ordinal only advances
            # when a child lived long enough to create its attempt file (a
            # pre-RunObs death must not burn a lineage slot), while the
            # supervisor's own attempt number always advances — it is what
            # TPU_DIST_ATTEMPT exports, so attempt-gated faults and
            # diagnostics see every launch, including the ledgerless ones
            attempt_no = len(attempts)
            ordinal = next_attempt_index(self.ledger)
            attempt_file = attempt_path(self.ledger, ordinal)
            # the consensus round: dense renumbering + degraded marking
            # land in self.env BEFORE the child env is derived from it
            self._resolve_view()
            resume = (latest_checkpoint(self.ckpt_dir)
                      if self.ckpt_dir else None)
            argv = self._child_argv(resume)
            env = dict(self.env)
            env["TPU_DIST_ATTEMPT"] = str(attempt_no)
            env["TPU_DIST_PREEMPT_DEADLINE_S"] = str(pol.preempt_deadline_s)
            if self._peer_resume_next:
                # one relaunch only: the re-expansion attempt pulls state
                # from a survivor over the wire where its local disk has
                # no (or a stale) checkpoint
                env["TPU_DIST_PEER_RESUME"] = "1"
                self._peer_resume_next = False
            else:
                env.pop("TPU_DIST_PEER_RESUME", None)
            hb_file = attempt_file + ".hb"
            env["TPU_DIST_HEARTBEAT_FILE"] = hb_file
            self._log(f"attempt {attempt_no}: {' '.join(argv)}"
                      + (f" (resume {resume})" if resume else ""))
            t0 = time.monotonic()
            rc, killed_for_stall, stderr_tail = self._run_child(
                argv, env, attempt_file, hb_file)
            records = _read_records(attempt_file)
            cls = classify_attempt(records, rc, killed_for_stall,
                                   stderr_tail)
            steps = sum(1 for r in records if r.get("event") == "step")
            result = AttemptResult(attempt_no, rc, cls, steps,
                                   round(time.monotonic() - t0, 3),
                                   ledger=attempt_file)
            attempts.append(result)
            self._log(f"attempt {attempt_no} ended: rc={rc} class={cls} "
                      f"({steps} step record(s) in {result.seconds:.1f}s)")
            if self.on_attempt is not None:
                try:
                    self.on_attempt(result)
                except Exception as e:  # an observer must never kill policy
                    self._log(f"warning: on_attempt hook failed ({e})")
            if self._stop.is_set():
                return SupervisorResult(
                    "clean" if cls == "clean" else "stopped", attempts)
            if self._scale_relaunch:
                # WE ended this attempt to re-form the mesh at a new
                # epoch: not a failure — no restart budget, no backoff,
                # no crash-loop accounting; relaunch immediately
                self._scale_relaunch = False
                self._log("rescale relaunch (no restart budget consumed)")
                continue
            if cls == "clean":
                return SupervisorResult("clean", attempts)
            consecutive_dead = consecutive_dead + 1 if steps == 0 else 0
            if consecutive_dead >= pol.crash_loop_k:
                self._log(
                    f"CRASH LOOP: {consecutive_dead} consecutive attempts "
                    f"died before their first step (last class {cls!r}) — "
                    "the failure is deterministic, not transient; fix the "
                    "run instead of restarting it")
                return SupervisorResult("crash_loop", attempts)
            if restarts >= pol.max_restarts:
                self._log(f"giving up: {restarts} restart(s) used "
                          f"(max_restarts={pol.max_restarts})")
                return SupervisorResult("restarts_exhausted", attempts)
            # shrink only on the SECOND consecutive rendezvous failure:
            # the first full-size retry rides out a transient coordinator
            # outage (the common case); a repeat is the host-loss signal.
            # Consensus-less fallback only — with a shared dir, membership
            # (lease expiry / explicit leave) is the loss signal and
            # _resolve_view owns sizing
            if (cls == "rendezvous" and pol.shrink_on_host_loss
                    and self.consensus is None):
                rdzv_streak = 0
                for a in reversed(attempts):
                    if a.failure_class != "rendezvous":
                        break
                    rdzv_streak += 1
                if rdzv_streak >= 2:
                    self.env, survivors = degraded_env(self.env)
                    if self.env.get("TPU_DIST_DEGRADED") == "1":
                        self.degraded = True
                        self._log("host loss confirmed (2 consecutive "
                                  "rendezvous failures) — re-forming the "
                                  f"mesh dp-only on {survivors} surviving "
                                  "process(es)")
            restarts += 1
            # per-host jitter: N hosts restarting after one shared failure
            # must not hit the rendezvous coordinator in lockstep
            wait = compute_backoff(restarts, pol, host_id=self.host_id)
            self._log(f"restart {restarts}/{pol.max_restarts} in "
                      f"{wait:.1f}s")
            if self.consensus is None:
                self._sleep(wait)
            else:
                # heartbeat THROUGH the backoff: a capped backoff (60s+)
                # dwarfs the membership lease (10s), and a silently
                # sleeping host would be declared lost by its peers —
                # one crash-looping host must not shrink a healthy mesh
                remaining = wait
                slice_s = max(self.consensus.lease_s / 3.0, 0.1)
                while remaining > 0:
                    self._sleep(min(remaining, slice_s))
                    remaining -= slice_s
                    self.consensus.register()


# -- in-process library API (the engines' config opt-in) --------------------

def run_supervised(make_trainer: Callable, cfg, *,
                   policy: Optional[RestartPolicy] = None,
                   sleep: Callable[[float], None] = time.sleep):
    """Policy-looped ``make_trainer(cfg).fit()``: the in-process flavor.

    Each attempt rebuilds the trainer with ``attempt=-1`` auto-lineage and
    ``resume`` pointed at the newest valid checkpoint, so a ``HealthError``
    halt (or any organic exception) restarts from the last good state with
    the restart visible in the stitched ledger. Bounded by the same
    :class:`RestartPolicy` (defaults come from the config's
    ``max_restarts`` / ``restart_backoff_s`` / ``crash_loop_k`` knobs);
    exhaustion re-raises the last failure, a crash loop raises
    :class:`CrashLoopError`. Process-killing failures (``os._exit``,
    SIGKILL, host loss) need the subprocess CLI by construction."""
    import dataclasses

    from tpu_dist.obs.health import HealthError

    if policy is None:
        policy = RestartPolicy(
            max_restarts=int(getattr(cfg, "max_restarts", 0) or 0),
            backoff_base_s=float(getattr(cfg, "restart_backoff_s", 1.0)
                                 or 0.0),
            crash_loop_k=int(getattr(cfg, "crash_loop_k", 3) or 3))
    restarts = 0
    consecutive_dead = 0
    while True:
        resume = getattr(cfg, "resume", "")
        if restarts > 0 and getattr(cfg, "checkpoint_dir", ""):
            resume = latest_checkpoint(cfg.checkpoint_dir) or resume
        run_cfg = dataclasses.replace(
            cfg, resume=resume,
            attempt=-1 if getattr(cfg, "ledger_path", "") else
            getattr(cfg, "attempt", 0))
        trainer = None  # drop the dead attempt's params/opt-state BEFORE
        # the rebuild re-allocates them — restarts must fit in HBM
        try:
            # construction is INSIDE the policy: an OOM while the rebuild
            # re-allocates, or an FS blip loading the resume checkpoint,
            # is a classifiable pre-first-step death (backoff + crash-loop
            # counting), same as a child dying at startup in the
            # subprocess flavor — not an abort of the whole supervised run
            trainer = make_trainer(run_cfg)
            return trainer.fit()
        except KeyboardInterrupt:
            raise  # the operator's ^C is not a failure to remediate
        except Exception as e:
            cls = "health_halt" if isinstance(e, HealthError) else "crash"
            steps = int(getattr(getattr(trainer, "obs", None), "steps", 0)
                        or 0)
            consecutive_dead = consecutive_dead + 1 if steps == 0 else 0
            if consecutive_dead >= policy.crash_loop_k:
                raise CrashLoopError(
                    f"{consecutive_dead} consecutive attempts died before "
                    f"their first step (last: {cls}: {e}) — deterministic "
                    "failure, not restarting") from e
            if restarts >= policy.max_restarts:
                raise
            restarts += 1
            wait = compute_backoff(restarts, policy)
            print(f"[supervise] {cls}: {e}\n[supervise] in-process restart "
                  f"{restarts}/{policy.max_restarts} in {wait:.1f}s",
                  file=sys.stderr, flush=True)
            sleep(wait)
