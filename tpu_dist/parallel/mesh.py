"""Device mesh construction + sharding helpers (the TPU 'backend' layer).

The reference's backend selection (`dist.init_process_group(backend='nccl')`,
reference 2.distributed.py:98) has no TPU analog — the XLA runtime over
ICI/DCN *is* the backend (SURVEY.md §2b NCCL row). What the framework owns is
the mesh: axis layout, shardings, and the collectives that ride it.

Axis conventions (scaling-book style):
* ``data``  — batch/data parallel (the only axis the reference exercises);
* ``fsdp``  — parameter-sharded data parallel (extension axis);
* ``model`` — tensor parallel (extension axis);
* ``seq``   — sequence/context parallel for long-context models.

All tpu_dist engines take a Mesh; single-host multi-device (reference variant
1), multi-host DDP (variants 2/3/6), and horovod-style (variant 5) differ only
in how many processes contribute devices to that mesh.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# THE axis-name authority: every mesh axis the framework can carry is
# declared here (tools/distlint rule DL003 validates PartitionSpec/collective
# axis literals across the tree against exactly this list, by AST — add an
# axis here FIRST, or the linter rejects its uses)
DATA_AXIS = "data"
FSDP_AXIS = "fsdp"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
STAGE_AXIS = "stage"    # pipeline parallel (parallel.pp)
EXPERT_AXIS = "expert"  # MoE expert parallel (parallel.ep)
SP_AXIS = "sp"          # serving sequence parallel (engine.serve long-context)


def make_mesh(shape: Optional[Sequence[int]] = None,
              axes: Sequence[str] = (DATA_AXIS,),
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a Mesh. Default: 1-D data-parallel over all addressable devices.

    ``shape=(dp, tp)`` with ``axes=("data", "model")`` etc. A -1 entry is
    inferred from the device count (like a reshape).
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if shape is None:
        shape = (n,)
    shape = list(shape)
    if -1 in shape:
        known = int(np.prod([s for s in shape if s != -1]))
        shape[shape.index(-1)] = n // max(known, 1)
    if int(np.prod(shape)) != n:
        raise ValueError(f"mesh shape {tuple(shape)} != {n} devices")
    if len(shape) != len(axes):
        raise ValueError(f"shape {tuple(shape)} rank != axes {tuple(axes)}")
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, tuple(axes))


def batch_sharding(mesh: Mesh, axis: str = DATA_AXIS) -> NamedSharding:
    """Shard dim 0 (batch) across the data axis; replicate the rest."""
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def world_info() -> Tuple[int, int, int, int]:
    """(process_index, process_count, local_devices, global_devices)."""
    return (jax.process_index(), jax.process_count(),
            jax.local_device_count(), jax.device_count())
