"""Expert-parallel sharding rules (GShard-style, compiler-partitioned).

Shards every MoE expert weight's leading experts dimension over the
``expert`` mesh axis; GSPMD turns the dispatch/combine einsums
(tpu_dist.models.moe) into the all-to-all exchanges of classic expert
parallelism. Everything non-expert stays replicated (or combines with the
other axes' specs when meshes are stacked).
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

EXPERT_AXIS = "expert"


def ep_param_specs(params, axis: str = EXPERT_AXIS) -> Any:
    """P(axis, ...) for expert weights (w_in/w_out carry the leading experts
    dim — tpu_dist.models.moe.MoEMLP); P() for everything else, including the
    gate projection (its dim 0 is d_model, not experts)."""
    def build(tree, key=""):
        if isinstance(tree, dict):
            return {k: build(v, k) for k, v in tree.items()}
        if key in ("w_in", "w_out") and tree.ndim == 3:
            return P(axis, None, None)
        return P()
    return build(params)


def shard_moe_params(mesh: Mesh, params, axis: str = EXPERT_AXIS):
    shardings = jax.tree.map(lambda s: NamedSharding(mesh, s),
                             ep_param_specs(params, axis),
                             is_leaf=lambda x: isinstance(x, P))
    return jax.device_put(params, shardings)


def shard_state_ep(mesh: Mesh, state, axis: str = EXPERT_AXIS):
    """Place a TrainState for expert parallelism: expert weights AND their
    optimizer state sharded over ``axis`` (the momentum buffers are the bulk
    of an MoE model's memory — leaving them replicated would defeat EP's
    scaling); everything else replicated.

    Optimizer-state pytrees (e.g. optax trace) mirror the params dict, so the
    expert leaves are identified by their tree PATH — a path ending in
    w_in/w_out with a 3-D leaf — never by shape (two tensors can share a
    shape without both being expert weights).
    """
    from jax.tree_util import tree_map_with_path

    from tpu_dist.engine.state import TrainState

    repl = NamedSharding(mesh, P())
    exp = lambda nd: NamedSharding(mesh, P(*([axis] + [None] * (nd - 1))))

    def place(path, leaf):
        names = {getattr(k, "key", getattr(k, "name", None)) for k in path}
        if names & {"w_in", "w_out"} and getattr(leaf, "ndim", 0) == 3:
            return jax.device_put(leaf, exp(leaf.ndim))
        return jax.device_put(leaf, repl)

    return TrainState(
        step=jax.device_put(state.step, repl),
        params=shard_moe_params(mesh, state.params, axis),
        batch_stats=jax.device_put(state.batch_stats, repl),
        opt_state=tree_map_with_path(place, state.opt_state),
        loss_scale=(None if state.loss_scale is None
                    else jax.device_put(state.loss_scale, repl)))
