"""Expert-parallel sharding rules (GShard-style, compiler-partitioned).

Shards every MoE expert weight's leading experts dimension over the
``expert`` mesh axis; GSPMD turns the dispatch/combine einsums
(tpu_dist.models.moe) into the all-to-all exchanges of classic expert
parallelism. Everything non-expert stays replicated (or combines with the
other axes' specs when meshes are stacked).
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_dist.parallel.mesh import EXPERT_AXIS


def _moe_leaf_spec(key: str, leaf, axis: str,
                   model_axis: str | None) -> P:
    """Spec for one MoE param leaf: expert weights shard their leading
    experts dim over ``axis``; with an active tensor-parallel axis the
    expert MLP additionally splits Megatron-style over ``model_axis``
    (w_in column-parallel on f, w_out row-parallel on f) and the attention
    qkv/proj + lm_head follow tpu_dist.parallel.tp's rules. The gate stays
    replicated (its output feeds the token-local routing argmax)."""
    if key in ("w_in", "w_out") and leaf.ndim == 3:
        if model_axis is None:
            return P(axis, None, None)
        return (P(axis, None, model_axis) if key == "w_in"
                else P(axis, model_axis, None))
    if model_axis is not None and leaf.ndim == 2:
        if key in ("qkv", "lm_head"):
            return P(None, model_axis)   # column-parallel
        if key == "proj":
            return P(model_axis, None)   # row-parallel
    return P()


def ep_param_specs(params, axis: str = EXPERT_AXIS,
                   model_axis: str | None = None) -> Any:
    """P(axis, ...) for expert weights (w_in/w_out carry the leading experts
    dim — tpu_dist.models.moe.MoEMLP); with ``model_axis`` set, the MoE x TP
    composition (VERDICT r3 #4); P() for everything else, including the
    gate projection (its dim 0 is d_model, not experts)."""
    names = ("w_in", "w_out", "qkv", "proj", "lm_head")

    def build(tree, path=()):
        if isinstance(tree, dict):
            return {k: build(v, path + (k,)) for k, v in tree.items()}
        if path and path[-1].endswith("_scale"):
            return P()  # weight-only int8 decode scales: tiny, replicated
        key = next((n for n in reversed(path) if n in names), "")
        return _moe_leaf_spec(key, tree, axis, model_axis)
    return build(params)


def shard_moe_params(mesh: Mesh, params, axis: str = EXPERT_AXIS,
                     model_axis: str | None = None):
    shardings = jax.tree.map(lambda s: NamedSharding(mesh, s),
                             ep_param_specs(params, axis, model_axis),
                             is_leaf=lambda x: isinstance(x, P))
    # distlint: disable=DL008 -- param placement at setup/resume, not a per-step input upload
    return jax.device_put(params, shardings)


def shard_state_ep(mesh: Mesh, state, axis: str = EXPERT_AXIS,
                   model_axis: str = "model"):
    """Place a TrainState for expert parallelism: expert weights AND their
    optimizer state sharded over ``axis`` (the momentum buffers are the bulk
    of an MoE model's memory — leaving them replicated would defeat EP's
    scaling); everything else replicated. When the mesh also carries a >1
    ``model_axis``, the MoE x TP composition applies (expert MLPs split
    Megatron-style over 'model' on top of their 'expert' shard; attention
    qkv/proj and lm_head follow the tp rules — VERDICT r3 #4).

    Optimizer-state pytrees (e.g. optax trace) mirror the params dict, so the
    sharded leaves are identified by their tree PATH — never by shape (two
    tensors can share a shape without both being expert weights).
    """
    from jax.tree_util import tree_map_with_path

    from tpu_dist.engine.state import TrainState

    use_tp = model_axis in mesh.axis_names and mesh.shape[model_axis] > 1
    tp_axis = model_axis if use_tp else None
    repl = NamedSharding(mesh, P())

    def place(path, leaf):
        names = [getattr(k, "key", getattr(k, "name", None)) for k in path]
        key = next((n for n in reversed(names)
                    if n in ("w_in", "w_out", "qkv", "proj", "lm_head")), "")
        spec = _moe_leaf_spec(key, leaf, axis, tp_axis) \
            if hasattr(leaf, "ndim") else P()
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    # distlint: disable=DL008 -- state placement at setup/resume, not a per-step input upload
    return TrainState(
        step=jax.device_put(state.step, repl),
        params=shard_moe_params(mesh, state.params, axis, tp_axis),
        batch_stats=jax.device_put(state.batch_stats, repl),
        opt_state=tree_map_with_path(place, state.opt_state),
        loss_scale=(None if state.loss_scale is None
                    else jax.device_put(state.loss_scale, repl)))
