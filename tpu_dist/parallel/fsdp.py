"""FSDP / ZeRO-style parameter + optimizer-state sharding (GSPMD-partitioned).

Absent from the reference (DP-only, SURVEY.md §2c). TPU-first FSDP is a
*placement policy*, not a wrapper class: shard every sizeable weight (and its
optimizer state) along one dimension over the data axis and let GSPMD insert
the all-gathers before use and reduce-scatters for gradients — the same
math as ZeRO-3, expressed as shardings. Per-device param+optimizer memory
drops ~n_data-fold; the step function is untouched.

Rule: shard the largest dimension divisible by the axis size; replicate small
leaves (norms, biases) where sharding would only add latency.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_dist.parallel.mesh import DATA_AXIS


def _leaf_spec(shape, axis_size: int, axis: str, min_size: int) -> P:
    if int(np.prod(shape)) < min_size or not shape:
        return P()
    # largest dim divisible by the axis size wins; ties -> earliest
    best = None
    for i, d in enumerate(shape):
        if d % axis_size == 0 and (best is None or d > shape[best]):
            best = i
    if best is None:
        return P()
    return P(*[axis if i == best else None for i in range(len(shape))])


def fsdp_specs(tree, axis_size: int, axis: str = DATA_AXIS,
               min_size: int = 1024) -> Any:
    """PartitionSpec pytree for params OR optimizer state (shape-driven, so
    the same rule shards momentum buffers identically to their params)."""
    return jax.tree.map(
        lambda leaf: _leaf_spec(leaf.shape, axis_size, axis, min_size), tree)


def fsdp_shardings(mesh: Mesh, tree, axis: str = DATA_AXIS,
                   min_size: int = 1024) -> Any:
    n = mesh.shape[axis]
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        fsdp_specs(tree, n, axis, min_size),
        is_leaf=lambda x: isinstance(x, P))


def shard_state_fsdp(mesh: Mesh, state, axis: str = DATA_AXIS,
                     min_size: int = 1024):
    """Place a TrainState with params+opt_state FSDP-sharded, scalars replicated."""
    from tpu_dist.engine.state import TrainState

    repl = NamedSharding(mesh, P())
    # distlint: disable=DL008 -- state placement at setup/resume, not a per-step input upload
    return TrainState(
        step=jax.device_put(state.step, repl),
        params=jax.device_put(state.params,
                              fsdp_shardings(mesh, state.params, axis, min_size)),
        batch_stats=jax.device_put(state.batch_stats, repl),
        opt_state=jax.device_put(state.opt_state,
                                 fsdp_shardings(mesh, state.opt_state, axis,
                                                min_size)),
        loss_scale=(None if state.loss_scale is None
                    else jax.device_put(state.loss_scale, repl)))
