"""Ring attention: exact causal attention over a sequence-sharded mesh axis.

Long-context support the reference never had (SURVEY.md §5 'Long-context':
absent upstream; first-class here). Each device holds one contiguous shard of
the sequence (Q fixed, K/V rotating): at ring step i the local K/V block is
``ppermute``'d to the next device while attention scores against the current
block are folded into an online-softmax accumulator (log-sum-exp rescaling,
fp32). After ``axis_size`` steps every Q row has attended to every K row —
numerically exact full attention, with O(L/n) memory per device and
communication that XLA overlaps with the block contractions on the ICI ring.

Causality is enforced by global positions: block pairs entirely in the future
are skipped-by-masking (their contribution is -inf before the fold), the
diagonal block gets the triangular mask.

Layout: q, k, v are (B, L_shard, H, D) inside shard_map; the axis name is the
mesh's sequence axis. Use with models whose attention fn is pluggable
(tpu_dist.models.transformer.TransformerLM(attn_fn=ring_attention_fn(axis))).
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from tpu_dist.parallel.mesh import SEQ_AXIS

NEG_INF = -1e30  # avoid nan from (-inf) - (-inf) in online-softmax rescaling


def ring_attention(q, k, v, axis_name: str = SEQ_AXIS, causal: bool = True):
    """Exact attention with K/V rotating around the ``axis_name`` ring.

    q/k/v: (B, L_shard, H, D) — this device's sequence shard.
    Returns (B, L_shard, H, D), fp32-accumulated, cast back to q.dtype.
    """
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    b, lq, h, d = q.shape
    scale = 1.0 / jnp.sqrt(d).astype(jnp.float32)
    qf = q.astype(jnp.float32)

    # right-rotation permutation: device p sends to p+1; after i steps the
    # resident K/V block originated at (my_idx - i) mod n
    perm = [(p, (p + 1) % axis_size) for p in range(axis_size)]

    def fold(carry, i):
        o_acc, m_acc, l_acc, k_cur, v_cur = carry
        kv_idx = (my_idx - i) % axis_size

        scores = jnp.einsum("bqhd,bkhd->bhqk", qf, k_cur.astype(jnp.float32),
                            preferred_element_type=jnp.float32) * scale
        if causal:
            qpos = my_idx * lq + jnp.arange(lq)
            kpos = kv_idx * k_cur.shape[1] + jnp.arange(k_cur.shape[1])
            mask = kpos[None, :] <= qpos[:, None]
            scores = jnp.where(mask[None, None], scores, NEG_INF)

        # online softmax fold (flash-attention accumulation, fp32)
        m_new = jnp.maximum(m_acc, jnp.max(scores, axis=-1))
        alpha = jnp.exp(m_acc - m_new)                       # rescale old
        p = jnp.exp(scores - m_new[..., None])               # (B,H,Q,K)
        l_new = l_acc * alpha + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p, v_cur.astype(jnp.float32),
                        preferred_element_type=jnp.float32)
        o_new = o_acc * alpha.transpose(0, 2, 1)[..., None] + pv

        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return (o_new, m_new, l_new, k_next, v_next), None

    o0 = jnp.zeros((b, lq, h, d), jnp.float32)
    m0 = jnp.full((b, h, lq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, lq), jnp.float32)
    (o, m, l, _, _), _ = lax.scan(fold, (o0, m0, l0, k, v),
                                  jnp.arange(axis_size))
    # rows with no visible keys (can't happen causally: every row sees itself)
    o = o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return o.astype(q.dtype)


@lru_cache(maxsize=None)
def ring_attention_fn(axis_name: str = SEQ_AXIS,
                      causal: bool = True) -> Callable:
    """attn_fn factory for TransformerLM: plugs the ring in for full_attention.

    Memoized so same-config calls return the SAME callable: flax modules
    hash by field value, so a per-call closure here would make two
    identical models compare unequal and defeat every module-keyed program
    cache downstream (engine.generate memoization; ADVICE r4)."""
    return partial(ring_attention, axis_name=axis_name, causal=causal)
