"""Tensor-parallel sharding rules (Megatron-style, compiler-partitioned).

The reference has no tensor parallelism (SURVEY.md §2c: ABSENT upstream);
tpu_dist provides it the TPU way: declare PartitionSpecs for the transformer
weights over a 'model' mesh axis and let GSPMD insert the collectives —
column-parallel first projection, row-parallel second projection, so each
block needs exactly one all-reduce (attention) + one (MLP), the Megatron
pattern, emitted by XLA rather than hand-written NCCL.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_dist.parallel.mesh import MODEL_AXIS

# path-substring -> spec for TransformerLM params (kernels are (in, out))
_RULES = (
    ("qkv", P(None, MODEL_AXIS)),       # column-parallel: heads split
    ("proj", P(MODEL_AXIS, None)),      # row-parallel: partial sums psum'd
    ("mlp_in", P(None, MODEL_AXIS)),    # column-parallel
    ("mlp_out", P(MODEL_AXIS, None)),   # row-parallel
    ("lm_head", P(None, MODEL_AXIS)),   # vocab-sharded logits
)


def _spec_for(path: str, leaf) -> P:
    if path.endswith("_scale"):
        # weight-only int8 decode scales (ops.quant.wo_quantize_params):
        # one fp32 per output channel, with broadcast dims of size 1 that
        # cannot shard — replicate (dequant distributes over the psum'd
        # row-parallel partials, so replication is exact)
        return P()
    for key, spec in _RULES:
        if key in path and leaf.ndim == len(spec):
            return spec
    return P()  # replicate everything else (norms, embeddings, biases)


def lm_param_specs(params) -> Any:
    """PartitionSpec pytree for TransformerLM params."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    specs = {}
    def build(tree, prefix=""):
        if isinstance(tree, dict):
            return {k: build(v, prefix + "/" + str(k)) for k, v in tree.items()}
        return _spec_for(prefix, tree)
    return build(params)


def lm_param_shardings(mesh: Mesh, params) -> Any:
    return jax.tree.map(lambda s: NamedSharding(mesh, s), lm_param_specs(params),
                        is_leaf=lambda x: isinstance(x, P))


def shard_lm_params(mesh: Mesh, params):
    """device_put params onto their TP shardings."""
    # distlint: disable=DL008 -- param placement at setup/resume, not a per-step input upload
    return jax.device_put(params, lm_param_shardings(mesh, params))
