"""tpu_dist.parallel — meshes, collectives, parallelism layouts, launch.

Attribute access is LAZY (PEP 562): ``tpu_dist.parallel.supervisor`` (the
elastic run supervisor) and its CLI must import on a login/CI host with no
jax installed, but the historical eager re-exports below pull
``parallel.mesh`` -> jax at package-import time. The mapping preserves the
public surface exactly — ``from tpu_dist.parallel import launch`` and
``from tpu_dist.parallel import make_mesh`` both still work — while
deferring the jax-heavy module imports to first use.
"""

import importlib

# public name -> submodule that defines it (None = the submodule itself)
_LAZY = {
    "launch": None,
    "mesh": None,
    "collectives": None,
    "consensus": None,
    "overlap": None,
    "supervisor": None,
    "fsdp": None,
    "tp": None,
    "ep": None,
    "pp": None,
    "ring_attention": None,
    # parallel.consensus (jax-free, like supervisor)
    "ConsensusDir": "consensus", "MeshView": "consensus",
    "consensus_env": "consensus",
    # parallel.mesh
    "DATA_AXIS": "mesh", "FSDP_AXIS": "mesh", "MODEL_AXIS": "mesh",
    "SEQ_AXIS": "mesh", "batch_sharding": "mesh", "make_mesh": "mesh",
    "replicated": "mesh", "world_info": "mesh",
    # parallel.collectives
    "allreduce_bench": "collectives", "barrier": "collectives",
    "compress_grads": "collectives", "pmean": "collectives",
    "psum": "collectives", "reduce_mean": "collectives",
    "ring_allreduce": "collectives",
    # parallel.overlap
    "RingDense": "overlap", "bucketed_grad_sync": "overlap",
    "ring_allgather_matmul": "overlap",
    "ring_matmul_reduce_scatter": "overlap", "validate_tp_impl": "overlap",
}

__all__ = sorted(_LAZY)


def __getattr__(name):
    target = _LAZY.get(name)
    if name not in _LAZY:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    if target is None:
        return importlib.import_module(f"{__name__}.{name}")
    module = importlib.import_module(f"{__name__}.{target}")
    return getattr(module, name)


def __dir__():
    return __all__
