from tpu_dist.parallel.mesh import (  # noqa: F401
    DATA_AXIS, FSDP_AXIS, MODEL_AXIS, SEQ_AXIS,
    batch_sharding, make_mesh, replicated, world_info)
from tpu_dist.parallel.collectives import (  # noqa: F401
    allreduce_bench, barrier, compress_grads, pmean, psum, reduce_mean,
    ring_allreduce)
from tpu_dist.parallel.overlap import (  # noqa: F401
    RingDense, bucketed_grad_sync, ring_allgather_matmul,
    ring_matmul_reduce_scatter, validate_tp_impl)
from tpu_dist.parallel import launch  # noqa: F401
