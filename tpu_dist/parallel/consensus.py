"""Supervisor consensus: cross-host agreement on the live host set.

PR 10's ``degraded_env`` shrink had a KNOWN LIMIT: each host's supervisor
only saw its own environment, so a MID-numbered host loss left an id hole
the survivors could not close — the shrunken rendezvous needed dense
``TPU_DIST_PROCESS_ID``s and nobody could renumber, so those runs ended in
``restarts_exhausted``. This module closes that limit with a small
file-based consensus protocol (the shared-FS substrate every checkpoint
dir already assumes; the reference's variant 6 keyed its file:// rendezvous
off the same assumption):

* each host's supervisor **registers** a member file
  (``host-<id>.json``) and refreshes its heartbeat timestamp while its
  child runs; a member whose heartbeat ages past ``lease_s`` — or whose
  file was removed by an explicit :meth:`~ConsensusDir.leave` — is dead;
* :meth:`~ConsensusDir.resolve` derives the agreed :class:`MeshView` from
  the membership: live hosts ordered **survivors-first** (the prior
  epoch's order filtered to the living, returners appended in id order —
  so process 0 is always a survivor holding the freshest state, the
  anchor both checkpoint resume and the peer-broadcast recovery pull
  from), process ids renumbered **densely** over that order, and a
  **rendezvous epoch** bumped on every membership change;
* the epoch record (``epoch.json``) is written atomically; because the
  successor view is a pure function of (previous view, live set), racing
  writers with the same inputs write identical bytes — the race is
  benign, and the next resolve converges any transient disagreement.

Everything here is importable WITHOUT jax (``scripts/lint.sh`` runs the
renumbering math on a bare host as a CI gate); the ``host_return`` fault
site (:mod:`tpu_dist.obs.faults`) re-registers lost planned hosts on
demand so the whole shrink -> re-expand cycle is provable on one CPU box.
"""

from __future__ import annotations

import glob
import json
import os
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from tpu_dist.obs import faults as _faults

_MEMBER_PREFIX = "host-"
_EPOCH_FILE = "epoch.json"


@dataclass(frozen=True)
class MeshView:
    """One agreed mesh layout: the consensus output of a resolve round."""

    epoch: int            # rendezvous epoch; bumped on membership change
    hosts: Tuple[int, ...]  # original host ids, survivors-first order
    planned: int          # the job's full world size

    @property
    def world_size(self) -> int:
        return len(self.hosts)

    @property
    def degraded(self) -> bool:
        return len(self.hosts) < self.planned

    def process_id(self, host_id: int) -> int:
        """The DENSE process id of ``host_id`` under this view — closing
        the id hole a mid-numbered loss leaves in the original numbering."""
        try:
            return self.hosts.index(host_id)
        except ValueError:
            raise KeyError(
                f"host {host_id} is not in the live set {list(self.hosts)} "
                f"(epoch {self.epoch})") from None


def successor_hosts(prev_hosts: List[int], live: List[int]) -> List[int]:
    """The next view's host order: survivors keep their relative order
    (so their dense ids only ever shift DOWN and process 0 stays a
    survivor), returners/joiners append in id order. Pure — the lint gate
    and racing epoch writers both rely on this being a function."""
    live_set = set(live)
    out = [h for h in prev_hosts if h in live_set]
    out += sorted(h for h in live_set if h not in set(prev_hosts))
    return out


class ConsensusDir:
    """One host's handle on the shared consensus directory.

    ``now`` is injectable (tests drive lease expiry with a virtual
    clock); everything else is stdlib file I/O.
    """

    def __init__(self, path: str, host_id: int, planned: int,
                 lease_s: float = 10.0,
                 now: Callable[[], float] = time.time):
        if planned < 1:
            raise ValueError("planned world size must be >= 1")
        self.path = path
        self.host_id = int(host_id)
        self.planned = int(planned)
        self.lease_s = float(lease_s)
        self._now = now
        # destination for host_return `fault` events (the supervisor
        # attaches its scale ledger; bare/unit use records to stderr only)
        self.fault_ledger = None
        os.makedirs(path, exist_ok=True)

    # -- membership -----------------------------------------------------
    def member_path(self, host_id: Optional[int] = None) -> str:
        h = self.host_id if host_id is None else host_id
        return os.path.join(self.path, f"{_MEMBER_PREFIX}{int(h)}.json")

    def register(self, host_id: Optional[int] = None) -> None:
        """Write/refresh a member heartbeat (atomic tmp+rename, unique tmp
        per writer so concurrent heartbeats never tear each other)."""
        h = self.host_id if host_id is None else int(host_id)
        rec = {"host": h, "ts": self._now()}
        tmp = self.member_path(h) + f".tmp.{self.host_id}"
        with open(tmp, "w") as f:
            json.dump(rec, f)
        os.replace(tmp, self.member_path(h))

    def leave(self, host_id: Optional[int] = None) -> None:
        """Deregister (clean shutdown: peers see the loss immediately
        instead of waiting out the lease)."""
        try:
            os.remove(self.member_path(host_id))
        except OSError:
            pass

    def alive(self) -> List[int]:
        """Live member ids: registered and heartbeat within the lease."""
        now = self._now()
        out = []
        for p in glob.glob(os.path.join(self.path, f"{_MEMBER_PREFIX}*.json")):
            try:
                with open(p) as f:
                    rec = json.load(f)
                host, ts = int(rec["host"]), float(rec["ts"])
            except (OSError, ValueError, KeyError, TypeError):
                continue  # torn write mid-crash: treat as absent this round
            if now - ts <= self.lease_s:
                out.append(host)
        return sorted(set(out))

    # -- the consensus round --------------------------------------------
    def _read_epoch(self) -> Optional[Dict]:
        try:
            with open(os.path.join(self.path, _EPOCH_FILE)) as f:
                rec = json.load(f)
            return {"epoch": int(rec["epoch"]),
                    "hosts": [int(h) for h in rec["hosts"]]}
        except (OSError, ValueError, KeyError, TypeError):
            return None

    def _write_epoch(self, epoch: int, hosts: List[int]) -> None:
        tmp = os.path.join(self.path, f"{_EPOCH_FILE}.tmp.{self.host_id}")
        with open(tmp, "w") as f:
            json.dump({"epoch": epoch, "hosts": hosts, "ts": self._now()}, f)
        os.replace(tmp, os.path.join(self.path, _EPOCH_FILE))

    def resolve(self, heartbeat: bool = True) -> MeshView:
        """One consensus round: heartbeat, observe the live set, and agree
        on (epoch, dense host order). Membership change -> epoch bump,
        written atomically; unchanged membership returns the recorded view
        verbatim (every host converges on the same bytes)."""
        if heartbeat:
            self.register()
        fault = _faults.fire("host_return", ledger=self.fault_ledger)
        if fault is not None:
            # deterministic re-expansion on demand: resurrect the lost
            # planned host(s) — `host=N` names one, default all missing
            live_now = set(self.alive())
            want = int(fault.args["host"]) if "host" in fault.args else None
            for h in range(self.planned):
                if h not in live_now and (want is None or h == want):
                    self.register(h)
        live = self.alive()
        if self.host_id not in live:
            live = sorted(set(live) | {self.host_id})
        prev = self._read_epoch()
        prev_hosts = prev["hosts"] if prev else []
        if prev is not None and set(prev_hosts) == set(live):
            return MeshView(prev["epoch"], tuple(prev_hosts), self.planned)
        hosts = (successor_hosts(prev_hosts, live) if prev is not None
                 else sorted(live))
        epoch = prev["epoch"] + 1 if prev is not None else 0
        self._write_epoch(epoch, hosts)
        return MeshView(epoch, tuple(hosts), self.planned)

    def wait_for_peers(self, timeout_s: float = 30.0,
                       sleep: Callable[[float], None] = time.sleep,
                       poll_s: float = 0.2) -> MeshView:
        """Block (bounded) until the planned world has registered — the
        startup join barrier, so the first epoch is the full mesh rather
        than a racey one-host view per supervisor start order."""
        deadline = self._now() + timeout_s
        self.register()
        while self._now() < deadline:
            if len(self.alive()) >= self.planned:
                break
            sleep(poll_s)
        return self.resolve()


def consensus_env(env: Dict[str, str], view: MeshView,
                  host_id: int) -> Dict[str, str]:
    """The relaunch environment under an agreed view: dense process id,
    agreed world size, the rendezvous epoch (parallel.launch offsets the
    coordinator port by it so a re-formed mesh never reconnects to the
    previous epoch's half-dead coordination service), and the degraded
    marker only while the mesh is actually short of plan. Pure."""
    out = dict(env)
    out["TPU_DIST_NUM_PROCESSES"] = str(view.world_size)
    out["TPU_DIST_PROCESS_ID"] = str(view.process_id(host_id))
    out["TPU_DIST_MESH_EPOCH"] = str(view.epoch)
    if view.degraded:
        out["TPU_DIST_DEGRADED"] = "1"
    else:
        out.pop("TPU_DIST_DEGRADED", None)
    return out
