"""Comm/compute overlap: ring collective matmul + bucketed gradient sync.

The reference repo's whole case for DDP over naive DataParallel is that DDP
overlaps the gradient all-reduce with the backward pass in ~25MB buckets
(Li et al., VLDB 2020). tpu_dist's round-1-7 answer to communication was
declarative: GSPMD decides where the collectives go (parallel.tp) and dp
grad sync is whatever single fused all-reduce XLA emits. This module adds
the MANUAL overlap path — decomposed, dependency-broken collectives that
XLA's latency-hiding scheduler can interleave with compute (Wang et al.,
ASPLOS 2023 'Overlap Communication with Dependent Computation via
Decomposition'):

* **ring collective matmul** — the Megatron column/row-parallel projection
  pair rebuilt as per-shard chunks exchanged with ``lax.ppermute`` inside
  shard_map: :func:`ring_allgather_matmul` (all-gather-then-matmul: each
  round matmuls the sequence chunk it holds while the next chunk's
  transfer is already in flight) and :func:`ring_matmul_reduce_scatter`
  (matmul-then-reduce-scatter: a rotating accumulator picks up one
  partial product per hop). :class:`RingDense` packages them as drop-in
  replacements for the column/row-parallel projections — same
  ``kernel``/``bias`` names, same FULL param shapes, so checkpoints and
  the ``quant`` knob apply unchanged (the per-chunk matmul routes through
  ops.quant.quant_matmul, so int8 rides the same ring).
* **bucketed gradient sync** — :func:`bucketed_grad_sync` groups grads
  into size-targeted buckets (DDP's ~25MB fusion-buffer rule) and reduces
  each as an independent reduce-scatter + all-gather instead of one
  tree-wide psum, so the scheduler may start bucket k+1's transfer while
  bucket k completes. Wired into the explicit-collective step builders
  (engine.steps / engine.lm_steps) behind the ``grad_bucket_mb`` knob.

Two ring flavors, because the sequence axis is not always shardable:

* ``'ring'``  — the headline AG/RS pair above; the residual stream is
  SEQUENCE-SHARDED over the model axis between projections (Megatron-LM
  sequence parallelism), so column projections gather and row projections
  scatter. Needs seq_len % tp == 0 (TransformerLM / MoE blocks).
* ``'ring_ar'`` — activations stay full-sequence; column projections are
  local slices (no comm) and row projections end in a chunked
  :func:`~tpu_dist.parallel.collectives.ring_allreduce` of the partial
  sums. No divisibility demand on the token axis — the ViT path, whose
  [CLS] token makes the token count odd by construction.

Everything here runs INSIDE shard_map with the model/data axis bound;
axis sizes are recovered statically via ``lax.psum(1, axis)`` (constant-
folded), so shapes stay trace-time constants.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from tpu_dist.ops.quant import quant_matmul
from tpu_dist.parallel.collectives import ring_allreduce
from tpu_dist.parallel.mesh import MODEL_AXIS

TP_IMPLS = ("gspmd", "ring")          # the public knob (configs.*.tp_impl)
_RING_FLAVORS = ("ring", "ring_ar")   # module-internal flavor set


def validate_tp_impl(mode: str) -> str:
    if mode not in TP_IMPLS:
        raise ValueError(f"unknown tp_impl {mode!r} ({'|'.join(TP_IMPLS)})")
    return mode


def static_axis_size(axis_name: str) -> int:
    """STATIC size of a bound mesh axis from inside shard_map: psum of a
    literal constant-folds to a Python int at trace time."""
    return jax.lax.psum(1, axis_name)


# ---- ring collective matmul ------------------------------------------------

def ring_allgather_matmul(x: jax.Array, w: jax.Array, axis_name: str,
                          *, matmul: Optional[Callable] = None) -> jax.Array:
    """all_gather-then-matmul, decomposed: (B, L/n, D) sequence shard x
    (D, F/n) column shard -> (B, L, F/n), without ever materializing the
    gathered (B, L, D).

    Round k matmuls the sequence chunk currently held (originally device
    idx+k's) while that chunk's ppermute to the left neighbor is already
    issued — the transfer of chunk k+1 hides behind the MXU work of chunk
    k, which is the whole point of the decomposition.
    """
    mm = matmul or jnp.dot
    n = static_axis_size(axis_name)
    if n == 1:
        return mm(x, w)
    idx = jax.lax.axis_index(axis_name)
    lm = x.shape[1]
    perm = [(i, (i - 1) % n) for i in range(n)]  # receive from the right
    cur = x
    out = None
    for k in range(n):
        # issue the next hop BEFORE this round's matmul: the two are
        # independent, so the scheduler may overlap transfer and compute
        nxt = jax.lax.ppermute(cur, axis_name, perm) if k < n - 1 else None
        y = mm(cur, w)                       # chunk owned by device idx+k
        if out is None:
            out = jnp.zeros((y.shape[0], n * lm, y.shape[-1]), y.dtype)
        out = jax.lax.dynamic_update_slice_in_dim(
            out, y, ((idx + k) % n) * lm, axis=1)
        cur = nxt
    return out


def ring_matmul_reduce_scatter(y: jax.Array, w: jax.Array, axis_name: str,
                               *, matmul: Optional[Callable] = None
                               ) -> jax.Array:
    """matmul-then-reduce_scatter, decomposed: (B, L, F/n) full-sequence
    activations x (F/n, D) row shard -> (B, L/n, D) fully summed over the
    axis, this device keeping sequence chunk ``axis_index``.

    A rotating accumulator makes one hop per round and picks up the local
    partial product for the chunk it is passing through — each round's
    matmul is independent of the accumulator transfer it overlaps.
    """
    mm = matmul or jnp.dot
    n = static_axis_size(axis_name)
    if n == 1:
        return mm(y, w)
    idx = jax.lax.axis_index(axis_name)
    lm = y.shape[1] // n
    if y.shape[1] % n:
        raise ValueError(f"sequence length {y.shape[1]} not divisible by "
                         f"the {axis_name} axis ({n})")
    perm = [(i, (i + 1) % n) for i in range(n)]

    def part(c):
        return mm(jax.lax.dynamic_slice_in_dim(y, (c % n) * lm, lm, axis=1),
                  w)

    # accumulator seeded for chunk (idx-1) lands home after n-1 hops
    acc = part(idx - 1)
    for k in range(1, n):
        acc = jax.lax.ppermute(acc, axis_name, perm)
        acc = acc + part(idx - k - 1)
    return acc


def seq_shard(x: jax.Array, axis_name: str = MODEL_AXIS) -> jax.Array:
    """This device's sequence chunk of a model-axis-replicated (B, L, ...)
    activation — the entry point into the seq-sharded ring residual
    stream. L must divide by the axis size."""
    n = static_axis_size(axis_name)
    if n == 1:
        return x
    if x.shape[1] % n:
        raise ValueError(
            f"tp_impl='ring' shards the sequence over the {axis_name} axis: "
            f"length {x.shape[1]} is not divisible by {n}")
    idx = jax.lax.axis_index(axis_name)
    lm = x.shape[1] // n
    return jax.lax.dynamic_slice_in_dim(x, idx * lm, lm, axis=1)


class RingDense(nn.Module):
    """Drop-in ring-parallel ``nn.Dense``: identical param names
    ("kernel"/"bias"), identical FULL param shapes and init — checkpoints,
    the Megatron TP sharding rules, and the ``quant`` knob all apply
    unchanged. The weights live replicated; each device slices its
    column/row shard at use (ring mode trades GSPMD-TP's param-memory
    sharding for explicit comm/compute overlap — compute and activations
    still shard over the axis).

    ``kind='column'`` consumes the full contraction dim and produces a
    feature shard; ``kind='row'`` consumes a feature shard and produces
    the summed full output. ``flavor`` picks the dataflow (module
    docstring): 'ring' = AG-matmul / matmul-RS over sequence chunks,
    'ring_ar' = local slice / chunked ring all-reduce. The inner per-chunk
    matmul routes through ops.quant.quant_matmul, so 'int8'/'int8_wo'
    ride the same ring path as fp.
    """

    features: int
    kind: str                  # 'column' | 'row'
    flavor: str = "ring"       # 'ring' | 'ring_ar'
    use_bias: bool = True
    dtype: Any = jnp.float32
    quant: str = "none"
    axis_name: str = MODEL_AXIS
    n_fused: int = 1           # the kernel fuses this many equal
                               # projections along the output dim (qkv = 3):
                               # a column shard takes the idx-th slice of
                               # EACH segment, so a downstream split stays
                               # q/k/v-aligned per device

    def _column_shard(self, t: jax.Array, idx, n: int) -> jax.Array:
        """idx-th output-feature shard of ``t`` (kernel dim -1 / bias dim
        0), sliced per fused segment."""
        seg = self.features // self.n_fused
        fs = seg // n
        ax = t.ndim - 1
        return jnp.concatenate(
            [jax.lax.dynamic_slice_in_dim(t, s * seg + idx * fs, fs, axis=ax)
             for s in range(self.n_fused)], axis=ax)

    @nn.compact
    def __call__(self, x):
        if self.kind not in ("column", "row"):
            raise ValueError(f"RingDense kind {self.kind!r} (column|row)")
        if self.flavor not in _RING_FLAVORS:
            raise ValueError(f"RingDense flavor {self.flavor!r} "
                             f"({'|'.join(_RING_FLAVORS)})")
        n = static_axis_size(self.axis_name)
        idx = jax.lax.axis_index(self.axis_name)
        if self.kind == "column":
            d_in = x.shape[-1]
            if self.features % (self.n_fused * n):
                raise ValueError(f"features {self.features} not divisible "
                                 f"by n_fused x the {self.axis_name} axis "
                                 f"({self.n_fused} x {n})")
        else:
            d_in = x.shape[-1] * n
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (d_in, self.features))
        if self.has_variable("params", "kernel_scale"):
            raise ValueError(
                "RingDense got a pre-quantized (wo_quantize_params) kernel; "
                "the ring path is a training path — decode rides the GSPMD "
                "layers (quant='int8_wo' with tp_impl='gspmd')")
        x = x.astype(self.dtype)
        mm = lambda a, b: quant_matmul(a, b, self.quant)
        if self.kind == "column":
            w = self._column_shard(kernel.astype(self.dtype), idx, n)
            if self.flavor == "ring":
                y = ring_allgather_matmul(x, w, self.axis_name, matmul=mm)
            else:          # ring_ar: replicated input, no gather needed
                y = mm(x, w)
        else:
            ls = x.shape[-1]
            w = jax.lax.dynamic_slice_in_dim(
                kernel.astype(self.dtype), idx * ls, ls, axis=0)
            if self.flavor == "ring":
                y = ring_matmul_reduce_scatter(x, w, self.axis_name,
                                               matmul=mm)
            else:          # ring_ar: chunked all-reduce of the partials
                y = ring_allreduce(mm(x, w), self.axis_name, n)
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros,
                              (self.features,)).astype(self.dtype)
            if self.kind == "column":
                bias = self._column_shard(bias, idx, n)
            y = y + bias
        return y


# ---- bucketed gradient sync ------------------------------------------------

GRAD_BUCKET_MB_DEFAULT = 25.0  # DDP's fusion-buffer default (Li et al. §3.2)


def grad_buckets(leaves: Sequence[jax.Array],
                 bucket_bytes: float) -> List[List[int]]:
    """Group consecutive leaf indices so each bucket targets
    ``bucket_bytes`` (DDP's fusion-buffer rule): a bucket closes when the
    next leaf would overflow it, an oversized leaf gets its own bucket,
    and dtype changes close a bucket (buckets concatenate flat)."""
    groups: List[List[int]] = []
    cur: List[int] = []
    size = 0
    for i, leaf in enumerate(leaves):
        b = leaf.size * leaf.dtype.itemsize
        if cur and (size + b > bucket_bytes
                    or leaf.dtype != leaves[cur[-1]].dtype):
            groups.append(cur)
            cur, size = [], 0
        cur.append(i)
        size += b
    if cur:
        groups.append(cur)
    return groups


def bucketed_grad_sync(tree, axis_name: str,
                       bucket_mb: float = GRAD_BUCKET_MB_DEFAULT,
                       mean: bool = True, axis_size: Optional[int] = None,
                       impl: str = "rs_ag"):
    """Cross-replica gradient sync as INDEPENDENT size-targeted bucket
    collectives instead of one fused tree-wide psum — DDP's bucket
    decomposition, which is what lets the scheduler overlap bucket k+1's
    transfer with bucket k's completion (and, fused into a step program,
    with adjacent backward compute).

    Each bucket is flattened+concatenated, padded to the axis size, and
    reduced as ``psum_scatter`` -> ``all_gather`` (``impl='rs_ag'``, the
    DDP wire pattern) or a chunked :func:`collectives.ring_allreduce`
    (``impl='ring'``). ``mean`` divides by the axis size (the dp grad
    average). Must run inside shard_map with ``axis_name`` bound; operates
    on the grads only, so buffer donation of the TrainState is untouched.
    """
    if impl not in ("rs_ag", "ring", "psum"):
        raise ValueError(f"unknown bucketed sync impl {impl!r}")
    n = axis_size if axis_size is not None else static_axis_size(axis_name)
    leaves, treedef = jax.tree.flatten(tree)
    out = list(leaves)
    for group in grad_buckets(leaves, bucket_mb * 1e6):
        flat = (leaves[group[0]].reshape(-1) if len(group) == 1 else
                jnp.concatenate([leaves[i].reshape(-1) for i in group]))
        size = flat.size
        pad = (-size) % n
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
        if impl == "rs_ag":
            red = jax.lax.all_gather(
                jax.lax.psum_scatter(flat, axis_name, scatter_dimension=0,
                                     tiled=True),
                axis_name, tiled=True)
        elif impl == "ring":
            red = ring_allreduce(flat, axis_name, n)
        else:
            red = jax.lax.psum(flat, axis_name)
        if mean:
            red = red / n
        off = 0
        for i in group:
            leaf = leaves[i]
            out[i] = red[off:off + leaf.size].reshape(leaf.shape)
            off += leaf.size
    return jax.tree.unflatten(treedef, out)
