"""Pipeline parallelism over a 'stage' mesh axis (GPipe + 1F1B, shard_map).

The last parallelism axis the framework lacked (absent upstream too —
SURVEY.md §2c). Two schedules over the same stage-stacked param layout:
GPipe (autodiff through the tick scan — simplest, activation stash O(M))
and 1F1B/PipeDream-flush (make_lm_pp_1f1b_train_step: manual jax.vjp per
stage, activation stash O(S) independent of the microbatch count — the
schedule that makes large-M, long-context pipeline runs fit in HBM).
TPU-first formulation: no per-stage processes, no RPC
schedulers — ONE shard_map program per device where

* each device along ``stage`` holds ``num_layers/num_stages`` consecutive
  transformer blocks, stage-stacked so every leaf carries a leading
  (stages, layers_per_stage) block of dims sharded ``P('stage')``;
* microbatches flow through a ``lax.scan`` over M + S - 1 ticks; activations
  hop stage->stage+1 via ``jax.lax.ppermute`` (ICI neighbor exchange);
* the whole pipeline — including the bubble — is differentiated by JAX
  autodiff: the transpose of ppermute is the reverse ppermute, so the
  backward pass is automatically the mirrored pipeline (GPipe schedule);
* embedding/head/final-LN are replicated as PARAMETERS, but their COMPUTE
  is gated with per-device ``lax.cond``: the embedding gather runs on
  stage 0 only, the ``ln_f`` + full-vocab ``lm_head`` matmul (and its vjp)
  on stage S-1 only, and bubble ticks skip the stage's block compute
  entirely. All collectives (ppermute / psum) stay OUTSIDE the branches, so
  every device still participates in every collective; a stage psum over
  the (exactly-zero elsewhere) embed/head gradients restores the replicated
  update. Block gradients stay stage-local. At a real vocabulary the head
  is ~25% of model FLOPs, so this gating is what makes S stages cost ~1x
  head work instead of Sx (VERDICT r3 weak #1).

Composes with data parallelism as a ('data', 'stage') mesh: batch rows
shard over 'data', gradients pmean over 'data' exactly like the other
engines. Validated equal to the pure-DP jit step in tests/test_pp.py.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from tpu_dist._compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_dist.engine.state import TrainState
from tpu_dist.engine.steps import _apply_update
from tpu_dist.parallel.mesh import DATA_AXIS, STAGE_AXIS


def _uses_tp(mesh: Mesh, model_axis: str = "model") -> bool:
    """True when the mesh carries a >1 tensor-parallel axis — the pipeline
    then leaves it to GSPMD as an *auto* axis, and block compute must not
    be branched around (its 'model' collectives would deadlock a cond)."""
    return model_axis in mesh.axis_names and mesh.shape[model_axis] > 1


def _tree_stack(trees):
    return jax.tree.map(lambda *xs: jnp.stack(xs), *trees)


def _tree_unstack(tree, n):
    return [jax.tree.map(lambda x: x[i], tree) for i in range(n)]


def stack_pipeline_params(params, num_stages: int):
    """TransformerLM params -> pipeline layout.

    {tok_emb, pos_emb, block0..N-1, ln_f, lm_head} becomes
    {embed_head: {tok_emb, pos_emb, ln_f, lm_head},
     blocks: leaves (S, N/S, ...)} — consecutive blocks per stage.
    """
    n_blocks = sum(1 for k in params if k.startswith("block"))
    if n_blocks % num_stages:
        raise ValueError(f"{n_blocks} blocks not divisible by "
                         f"{num_stages} stages")
    per = n_blocks // num_stages
    stages = [_tree_stack([params[f"block{s * per + i}"] for i in range(per)])
              for s in range(num_stages)]
    return {
        "embed_head": {k: params[k] for k in
                       ("tok_emb", "pos_emb", "ln_f", "lm_head")},
        "blocks": _tree_stack(stages),
    }


def unstack_pipeline_params(pp_params):
    """Inverse of stack_pipeline_params (tests / checkpoint interop)."""
    blocks = pp_params["blocks"]
    s = jax.tree.leaves(blocks)[0].shape[0]
    per = jax.tree.leaves(blocks)[0].shape[1]
    out = dict(pp_params["embed_head"])
    for si, stage_tree in enumerate(_tree_unstack(blocks, s)):
        for li, block_tree in enumerate(_tree_unstack(stage_tree, per)):
            out[f"block{si * per + li}"] = block_tree
    return out


def pp_state_specs(state, stage_axis: str = STAGE_AXIS) -> TrainState:
    """PartitionSpec pytree for a pipeline-layout tree (a TrainState, or a
    bare params dict — the rule is structural): 'blocks' subtrees
    P(stage_axis), the rest replicated."""
    from jax.tree_util import tree_map_with_path

    def spec(path, leaf):
        under_blocks = any(getattr(k, "key", None) == "blocks" for k in path)
        if under_blocks:
            return P(stage_axis, *([None] * (leaf.ndim - 1)))
        return P()

    return tree_map_with_path(spec, state)


def pp_tp_placement_specs(state, stage_axis: str = STAGE_AXIS,
                          model_axis: str = "model"):
    """PLACEMENT specs for pp x tp: blocks' leading dim on 'stage' AND the
    Megatron column/row dims on 'model' (tp.py's rules, applied under the
    stage-stacked (S, layers, ...) layout). Used only for device_put — the
    shard_map in_specs stay stage-only because 'model' runs as a GSPMD
    *auto* axis inside the manual pipeline program."""
    from jax.tree_util import keystr, tree_map_with_path

    from tpu_dist.parallel.mesh import MODEL_AXIS
    from tpu_dist.parallel.tp import _RULES

    def spec(path, leaf):
        k = keystr(path)
        if "'blocks'" not in k:
            # embed_head stays replicated over 'model' by design: the
            # pipeline program computes embedding/head on every stage
            return P()
        base = [stage_axis] + [None] * (leaf.ndim - 1)
        if leaf.ndim == 4:  # stacked (S, layers, in, out) KERNELS only
            for key, rule in _RULES:
                if f"'{key}'" in k and len(rule) == 2:
                    # map tp.py's canonical 2-dim kernel rule onto the last
                    # two dims of the stage-stacked leaf — ONE rule table
                    base[-2] = model_axis if rule[0] == MODEL_AXIS else None
                    base[-1] = model_axis if rule[1] == MODEL_AXIS else None
                    break
        elif leaf.ndim == 5:
            # stacked (S, layers, E, in, out) expert kernels: the MoE x tp
            # rule (parallel.ep._moe_leaf_spec) under the stage stacking —
            # w_in column-parallel on f, w_out row-parallel; the gate stays
            # replicated (it is a 2-dim kernel with no _RULES entry)
            if "'w_in'" in k:
                base[-1] = model_axis
            elif "'w_out'" in k:
                base[-2] = model_axis
        return P(*base)

    return tree_map_with_path(spec, state)


def shard_state_pp(mesh: Mesh, state, stage_axis: str = STAGE_AXIS,
                   model_axis: str = "model"):
    """Place a pipeline-layout TrainState: blocks (+ their optimizer state)
    sharded over 'stage', everything else replicated. When the mesh also
    carries a >1 'model' axis, block weights additionally shard
    Megatron-style over it (pp x tp composition)."""
    specs = (pp_tp_placement_specs(state, stage_axis, model_axis)
             if _uses_tp(mesh, model_axis)
             else pp_state_specs(state, stage_axis))
    return jax.tree.map(
        lambda leaf, s: jax.device_put(leaf, NamedSharding(mesh, s)),
        state, specs)


def _pp_shard_map(mesh: Mesh, per_device, in_specs, out_specs,
                  data_axis: str, stage_axis: str):
    """shard_map with 'data'/'stage' MANUAL and — when the mesh carries a
    >1 'model' axis — 'model' left as a GSPMD *auto* axis: the pipeline
    schedule stays hand-written while XLA partitions each stage's block
    math Megatron-style over 'model' (pp x tp composition; round-2 gap)."""
    kwargs = {}
    if _uses_tp(mesh):
        from tpu_dist._compat import PARTIAL_MANUAL_SHARD_MAP
        if not PARTIAL_MANUAL_SHARD_MAP:
            raise RuntimeError(
                "pp x tp needs partial-manual shard_map (an auto 'model' "
                "axis inside the manual pipeline program); this jax "
                f"({jax.__version__}) only ships the experimental "
                "shard_map, whose SPMD partitioner aborts on that "
                "composition. Upgrade jax, or drop the 'model' axis "
                "(plain pp) / the 'stage' axis (plain tp).")
        kwargs["axis_names"] = frozenset({data_axis, stage_axis})
    return shard_map(per_device, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_vma=False, **kwargs)


def _is_moe(model) -> bool:
    return getattr(model, "num_experts", 0) > 0


def _clip_pp_grads(grads, grad_clip: float, stage_axis: str):
    """optax.clip_by_global_norm semantics under the pipeline layout (runs
    INSIDE the pp shard_map, after grad reduction): block grads are
    stage-local while embed/head grads are already stage-replicated, so the
    TRUE global squared norm is psum('stage') of the block term plus ONE
    embed/head term. Every stage then scales by the same factor — which is
    what keeps the replicated embed/head update synchronized (the reason a
    naive per-device optax clip was rejected in round 4; the pp engine
    builds its optax chain WITHOUT the clip and applies this instead)."""
    block_sq = sum(jnp.sum(jnp.square(g))
                   for g in jax.tree.leaves(grads["blocks"]))
    eh_sq = sum(jnp.sum(jnp.square(g))
                for g in jax.tree.leaves(grads["embed_head"]))
    norm = jnp.sqrt(jax.lax.psum(block_sq, stage_axis) + eh_sq)
    scale = jnp.where(norm > grad_clip,
                      grad_clip / jnp.maximum(norm, 1e-30), 1.0)
    return jax.tree.map(lambda g: g * scale, grads)


def _head_logits(model, x, kernel, dtype):
    """The last stage's lm_head matmul under the model's quant mode — the
    same ops.quant treatment the non-pp head gets from make_dense, so the
    pipeline run trains the SAME program per layer (the chunked-CE path
    keeps its fp head in every mode, as documented on LMConfig.quant)."""
    from tpu_dist.ops.quant import quant_matmul

    quant = getattr(model, "quant", "none")
    return quant_matmul(x.astype(dtype), kernel.astype(dtype),
                        quant).astype(jnp.float32)


def _stage_apply_builder(model):
    """(apply_stage, ln_f, dtype) shared by every pipeline schedule: the
    per-stage block scan (remat-aware) and the final-norm module — ONE
    definition so GPipe and 1F1B can never diverge on what a stage computes."""
    import flax.linen as nn

    from tpu_dist.models.transformer import Block

    block = Block(num_heads=model.num_heads, dtype=model.dtype,
                  attn_fn=model.attn_fn,
                  quant=getattr(model, "quant", "none"))
    ln_f = nn.LayerNorm(dtype=jnp.float32)

    def apply_stage(blocks_local, x):
        # blocks_local leaves: (layers_per_stage, ...) — homogeneous scan
        def one(h, bp):
            return block.apply({"params": bp}, h), None
        if model.remat:  # same per-block checkpointing as the dense path
            one = jax.checkpoint(one)
        x, _ = jax.lax.scan(one, x, blocks_local)
        return x

    return apply_stage, ln_f, model.dtype


def _stage_apply_aux_builder(model):
    """MoE twin of :func:`_stage_apply_builder`: the stage scan runs
    MoEBlocks and ACCUMULATES their sown load-balancing aux losses —
    ``apply_stage(blocks_local, x) -> (x, aux_sum)``. Used by the GPipe
    forward (autodiff carries the aux gradient back into each stage's
    routers); the manual-vjp 1F1B schedule stays dense-only."""
    import flax.linen as nn

    from tpu_dist.models.moe import MoEBlock

    block = MoEBlock(num_heads=model.num_heads,
                     num_experts=model.num_experts, dtype=model.dtype,
                     attn_fn=model.attn_fn,
                     router_top_k=model.router_top_k,
                     group_size=model.group_size,
                     capacity_factor=model.capacity_factor,
                     quant=getattr(model, "quant", "none"))
    ln_f = nn.LayerNorm(dtype=jnp.float32)

    def apply_stage(blocks_local, x):
        def one(carry, bp):
            h, aux, mass, mass_n = carry
            out, muts = block.apply({"params": bp}, h,
                                    mutable=["intermediates"])
            for path, leaf in jax.tree_util.tree_flatten_with_path(
                    muts.get("intermediates", {}))[0]:
                keys = [getattr(k, "key", None) for k in path]
                if "aux_loss" in keys:
                    aux = aux + jnp.sum(leaf)
                elif "combine_mass" in keys:  # router health (RMass)
                    mass = mass + jnp.sum(leaf.astype(jnp.float32))
                    mass_n = mass_n + jnp.float32(leaf.size)
            return (out, aux, mass, mass_n), None
        if model.remat:
            one = jax.checkpoint(one)
        zero = jnp.float32(0.0)
        (x, aux, mass, mass_n), _ = jax.lax.scan(
            one, (x, zero, zero, zero), blocks_local)
        return x, (aux, mass, mass_n)

    return apply_stage, ln_f, model.dtype


def _zeros_metrics():
    from tpu_dist.engine.lm_steps import zeros_lm_metrics
    return zeros_lm_metrics()


def _pp_forward_builder(model, mesh: Mesh, num_microbatches: int,
                        stage_axis: str = STAGE_AXIS,
                        loss_chunk: int = 0) -> Callable:
    """Shared pipeline forward+loss for the train AND eval steps: returns
    ``fwd_loss(params, inputs, targets, row_valid) -> (loss_sum,
    metrics, aux)`` to run INSIDE shard_map. loss_sum and the CE metric
    sums are real on the LAST stage only (exact zeros elsewhere — the
    head never runs, via ``lax.cond`` — so a stage psum reassembles
    them); ``aux`` is the STAGE-LOCAL MoE router loss, nonzero on every
    stage that holds MoE blocks (0.0 for dense models), and the metrics
    carry per-stage router_mass sums the same way. ``row_valid`` (B,)
    masks sampler wrap-padding rows (ones for training)."""
    from tpu_dist.engine.lm_steps import (_chunked_loss_metrics,
                                          lm_loss_and_metrics)

    n_stages = mesh.shape[stage_axis]
    m = num_microbatches
    moe = _is_moe(model)
    if moe:
        apply_aux, ln_f, dtype = _stage_apply_aux_builder(model)
    else:
        apply_dense, ln_f, dtype = _stage_apply_builder(model)

        def apply_aux(blocks_local, x):
            zero = jnp.float32(0.0)
            return apply_dense(blocks_local, x), (zero, zero, zero)
    # lax.cond branches must contain NO collectives: a collective reached by
    # only some devices deadlocks the global rendezvous. With pp x tp the
    # block math carries GSPMD 'model' all-reduces, so bubble-tick gating
    # falls back to where() there; embed/head are 'model'-replicated by
    # design (pp_tp_placement_specs) so THEIR gating is always safe.
    gate_blocks = not _uses_tp(mesh)

    def fwd_loss(params, inputs, targets, row_valid):
        stage = jax.lax.axis_index(stage_axis)
        b_local, seq_len = inputs.shape
        if b_local % m:
            raise ValueError(f"local batch {b_local} not divisible by "
                             f"{m} microbatches")
        mb = b_local // m
        eh = params["embed_head"]
        blocks_local = jax.tree.map(lambda x: x[0], params["blocks"])
        d_model = eh["tok_emb"]["embedding"].shape[1]
        is_first = stage == 0
        is_last = stage == n_stages - 1

        # embedding gather runs on stage 0 ONLY (its vjp — the big vocab
        # scatter-add — is then stage-0-only too, via the cond transpose)
        def compute_emb():
            tok = eh["tok_emb"]["embedding"][inputs]      # (B, L, D) f32
            pos = eh["pos_emb"]["embedding"][jnp.arange(seq_len)][None]
            return (tok + pos).astype(dtype).reshape(
                m, mb, seq_len, d_model)

        emb_mb = jax.lax.cond(
            is_first, compute_emb,
            lambda: jnp.zeros((m, mb, seq_len, d_model), dtype))

        zeros_act = jnp.zeros((mb, seq_len, d_model), dtype)
        zeros_out = jnp.zeros((m, mb, seq_len, d_model), dtype)

        zeros3 = (jnp.float32(0.0),) * 3

        def tick(carry, t):
            recv, outs, acc = carry
            inp = jnp.where(is_first,
                            emb_mb[jnp.clip(t, 0, m - 1)], recv)
            # stage s works on microbatch t-s; outside [0, M) it's bubble —
            # and bubble ticks SKIP the block compute (cond, not where)
            valid = (t - stage >= 0) & (t - stage < m)
            if gate_blocks:
                out, aux3 = jax.lax.cond(
                    valid, lambda: apply_aux(blocks_local, inp),
                    lambda: (zeros_act, zeros3))
            else:  # tp: 'model' collectives forbid branching around blocks
                out, aux3 = apply_aux(blocks_local, inp)
                out = jnp.where(valid, out, 0.0)
                aux3 = tuple(jnp.where(valid, a, 0.0) for a in aux3)
            out_idx = jnp.clip(t - (n_stages - 1), 0, m - 1)
            outs = jnp.where(
                is_last & (t >= n_stages - 1),
                jax.lax.dynamic_update_index_in_dim(outs, out, out_idx, 0),
                outs)
            nxt = jax.lax.ppermute(
                out, stage_axis,
                [(i, i + 1) for i in range(n_stages - 1)])
            acc = tuple(a + b for a, b in zip(acc, aux3))
            return (nxt, outs, acc), None

        (_, outs, (aux_sum, mass_sum, mass_n)), _ = jax.lax.scan(
            tick, (zeros_act, zeros_out, zeros3),
            jnp.arange(m + n_stages - 1))

        # ln_f + full-vocab head matmul + loss run on the LAST stage only;
        # other stages return exact zeros so grads/metrics psum correctly
        def head():
            x = ln_f.apply({"params": eh["ln_f"]},
                           outs.reshape(b_local, seq_len, -1))
            mask = jnp.broadcast_to(row_valid[:, None],
                                    targets.shape).astype(jnp.float32)
            if loss_chunk:
                # chunked head+CE (ops.fused_xent): the custom_vjp has
                # no collectives, so it is cond-safe on the last stage;
                # the SHARED helper builds the metric dict so the key
                # set cannot drift from the jit/sp paths
                return _chunked_loss_metrics(model, eh, x, targets,
                                             mask, loss_chunk)
            logits = _head_logits(model, x, eh["lm_head"]["kernel"], dtype)
            return lm_loss_and_metrics(logits, targets, mask)

        loss_sum, metrics = jax.lax.cond(
            is_last, head, lambda: (jnp.float32(0.0), _zeros_metrics()))
        # router-mass diagnostic rides the metric sums (stage psum adds
        # each stage's contribution) so pp-MoE runs report a real RMass
        metrics = {**metrics,
                   "router_mass_sum": jax.lax.stop_gradient(mass_sum),
                   "router_mass_n": mass_n}
        # per-device aux: mean over this stage's microbatches (matching the
        # dp path's one-batch aux scale); stage-local — each stage's grads
        # carry its own routers' balance term, psum'd with the block grads
        return loss_sum, metrics, aux_sum / jnp.float32(m)

    return fwd_loss


def make_lm_pp_train_step(model, tx, mesh: Mesh, num_microbatches: int,
                          data_axis: str = DATA_AXIS,
                          stage_axis: str = STAGE_AXIS,
                          donate: bool = True,
                          aux_weight: float = 0.01,
                          loss_chunk: int = 0,
                          grad_clip: float = 0.0,
                          health: str = "record") -> Callable:
    """GPipe train step: (state, inputs (B,L), targets (B,L), rng) ->
    (state, metric sums). ``state.params`` must be in pipeline layout
    (stack_pipeline_params) and placed by shard_state_pp.

    ``model`` is the TransformerLM whose geometry the params came from (its
    Block/embedding hyperparameters are reused functionally here).
    ``grad_clip`` > 0 clips by the cross-stage global norm (_clip_pp_grads);
    ``tx`` must then be built WITHOUT its own clip.
    """
    per_device = _pp_gpipe_step_builder(model, tx, mesh, num_microbatches,
                                        data_axis, stage_axis, aux_weight,
                                        loss_chunk, grad_clip, health)

    def call(state, inputs, targets, rng):
        # specs are structural, so the caller's state pytree defines them
        # (manual axes only — a 'model' mesh axis rides as GSPMD auto)
        specs = pp_state_specs(state, stage_axis)
        sharded = _pp_shard_map(
            mesh, per_device,
            (specs, P(data_axis, None), P(data_axis, None), P()),
            (specs, P()), data_axis, stage_axis)
        return sharded(state, inputs, targets, rng)

    return jax.jit(call, donate_argnums=(0,) if donate else ())


def _pp_gpipe_step_builder(model, tx, mesh: Mesh, num_microbatches: int,
                           data_axis: str, stage_axis: str,
                           aux_weight: float = 0.01,
                           loss_chunk: int = 0,
                           grad_clip: float = 0.0,
                           health: str = "record") -> Callable:
    """Per-device GPipe train step (runs INSIDE shard_map), shared by the
    single-batch and indexed-window wrappers."""
    fwd_loss = _pp_forward_builder(model, mesh, num_microbatches,
                                   stage_axis, loss_chunk)

    def per_device(state: TrainState, inputs, targets, rng):
        del rng  # blocks are dropout-free; kept for engine-signature parity

        def loss_fn(params):
            ones = jnp.ones((inputs.shape[0],), jnp.float32)
            loss_sum, metrics, aux = fwd_loss(params, inputs, targets, ones)
            mean = loss_sum / jnp.float32(targets.size)  # local-shard mean
            return mean + aux_weight * aux, ({}, metrics)

        (_, (stats, metrics)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        # stage-local block grads average over data replicas only; the
        # replicated embed/head grads are nonzero on one stage each -> the
        # stage psum reassembles the full gradient on every stage
        grads = {
            "blocks": jax.tree.map(
                lambda g: jax.lax.pmean(g, data_axis), grads["blocks"]),
            "embed_head": jax.tree.map(
                lambda g: jax.lax.pmean(jax.lax.psum(g, stage_axis),
                                        data_axis), grads["embed_head"]),
        }
        if grad_clip > 0:
            grads = _clip_pp_grads(grads, grad_clip, stage_axis)
        metrics = jax.tree.map(
            lambda v: jax.lax.psum(jax.lax.psum(v, stage_axis), data_axis),
            metrics)
        # block grads are stage-local: psum the health probes over 'stage'
        # so they (and any skip gate) are identical on every device
        return _apply_update(
            tx, state, grads, stats, metrics, health,
            probe_sync=lambda p: {k: jax.lax.psum(v, stage_axis)
                                  for k, v in p.items()})

    return per_device


def make_lm_pp_1f1b_train_step(model, tx, mesh: Mesh, num_microbatches: int,
                               data_axis: str = DATA_AXIS,
                               stage_axis: str = STAGE_AXIS,
                               donate: bool = True,
                               aux_weight: float = 0.01,
                               loss_chunk: int = 0,
                               grad_clip: float = 0.0,
                               health: str = "record") -> Callable:
    """1F1B pipeline train step (PipeDream-flush schedule, VERDICT r2 #4).

    Same signature/state layout as :func:`make_lm_pp_train_step`, different
    schedule: each of the ``M + 2(S-1)`` lockstep ticks runs ONE forward and
    ONE backward microbatch per stage (stage s forwards microbatch ``t-s``
    and backwards microbatch ``t - (2(S-1)-s)``), with the backward hand-
    rolled through ``jax.vjp`` and the activation stash bounded by
    ``2(S-1)+1`` microbatches — **independent of M**. GPipe-by-autodiff
    stashes all ``M+S-1`` tick inputs (plus block intermediates unless
    remat), so its activation memory grows linearly with the microbatch
    count; this schedule holds it constant, which is what buys large-M runs
    (small bubble fraction (S-1)/(M+S-1)) at long sequence lengths. The
    backward RECOMPUTES the stage forward from the stashed input (flash-
    style), the standard memory/FLOPs trade for 1F1B.

    Numerics match GPipe/DP exactly (tests/test_pp.py): per-microbatch
    losses are normalized by the local shard size so their sum is the local
    mean; block grads stay stage-local, embed/head grads psum over 'stage',
    everything pmeans over 'data'.

    Round 5 closes the three 1f1b composition holes (VERDICT r4 #2): MoE
    router aux losses thread through the manual vjp as an explicit
    cotangent, ``loss_chunk`` > 0 runs the chunked CE (ops.fused_xent) on
    the last-stage head, and ``grad_clip`` > 0 clips by the cross-stage
    global norm (_clip_pp_grads; ``tx`` must then carry no clip of its own).
    """
    per_device = _pp_1f1b_step_builder(model, tx, mesh, num_microbatches,
                                       data_axis, stage_axis, aux_weight,
                                       loss_chunk, grad_clip, health)

    def call(state, inputs, targets, rng):
        specs = pp_state_specs(state, stage_axis)
        sharded = _pp_shard_map(
            mesh, per_device,
            (specs, P(data_axis, None), P(data_axis, None), P()),
            (specs, P()), data_axis, stage_axis)
        return sharded(state, inputs, targets, rng)

    return jax.jit(call, donate_argnums=(0,) if donate else ())


def _pp_1f1b_step_builder(model, tx, mesh: Mesh, num_microbatches: int,
                          data_axis: str, stage_axis: str,
                          aux_weight: float = 0.01,
                          loss_chunk: int = 0,
                          grad_clip: float = 0.0,
                          health: str = "record") -> Callable:
    """Per-device 1F1B train step (runs INSIDE shard_map), shared by the
    single-batch and indexed-window wrappers.

    MoE models thread the router aux losses through the manual vjp: each
    backward microbatch differentiates the stage forward's (activation,
    aux) pair with cotangents (dy, aux_weight/M) — exactly the coefficient
    autodiff gives each stage-local aux term in the GPipe objective (loss =
    CE mean + aux_weight * sum_over_microbatch_auxes / M), and the aux
    path's input cotangent rides the backward ppermute ring to earlier
    stages the same way the CE cotangent does."""
    from tpu_dist.engine.lm_steps import (_chunked_loss_metrics,
                                          lm_loss_and_metrics)

    S = mesh.shape[stage_axis]
    M = num_microbatches
    stash_depth = 2 * (S - 1) + 1  # max in-flight per stage, +1 tick slack
    moe = _is_moe(model)
    if moe:
        apply_aux, ln_f, dtype = _stage_apply_aux_builder(model)

        def stage_fwd(bp, x):
            return apply_aux(bp, x)          # (y, (aux, mass, mass_n))
    else:
        apply_dense, ln_f, dtype = _stage_apply_builder(model)

        def stage_fwd(bp, x):
            zero = jnp.float32(0.0)
            return apply_dense(bp, x), (zero, zero, zero)

    def stage_va(bp, x):
        # THE differentiated per-stage forward: (activation, aux). The mass
        # diagnostics are excluded so the vjp needs no zero cotangents for
        # them (XLA dead-code-eliminates their recompute in the backward).
        y, (aux, _, _) = stage_fwd(bp, x)
        return y, aux

    aux_ct = jnp.float32(aux_weight / M if moe else 0.0)
    # same collective-safety rule as the GPipe builder: block compute is
    # cond-gated only when it contains no 'model' collectives; the head /
    # embedding branches are 'model'-replicated so they are always gated
    gate_blocks = not _uses_tp(mesh)

    def per_device(state: TrainState, inputs, targets, rng):
        del rng
        stage = jax.lax.axis_index(stage_axis)
        is_first = stage == 0
        is_last = stage == S - 1
        b_local, seq_len = inputs.shape
        if b_local % M:
            raise ValueError(f"local batch {b_local} not divisible by "
                             f"{M} microbatches")
        mb = b_local // M
        params = state.params
        eh = params["embed_head"]
        blocks_local = jax.tree.map(lambda x: x[0], params["blocks"])
        d_model = eh["tok_emb"]["embedding"].shape[1]

        ids_mb = inputs.reshape(M, mb, seq_len)
        tgt_mb = targets.reshape(M, mb, seq_len)
        pos_ids = jnp.arange(seq_len)

        def embed(m):
            tok = eh["tok_emb"]["embedding"][ids_mb[m]]
            pos = eh["pos_emb"]["embedding"][pos_ids][None]
            return (tok + pos).astype(dtype)

        def head_loss(eh_p, y, m):
            """Per-microbatch mean-normalized loss + metric sums (real on
            the last stage only; the caller masks)."""
            x = ln_f.apply({"params": eh_p["ln_f"]}, y)
            mask = jnp.ones((mb, seq_len), jnp.float32)
            if loss_chunk:
                # chunked head+CE (ops.fused_xent): its custom_vjp is
                # collective-free, so it is cond-safe on the last stage
                loss_sum, metrics = _chunked_loss_metrics(
                    model, eh_p, x, tgt_mb[m], mask, loss_chunk)
            else:
                logits = _head_logits(model, x, eh_p["lm_head"]["kernel"],
                                      dtype)
                loss_sum, metrics = lm_loss_and_metrics(logits, tgt_mb[m],
                                                        mask)
            # normalize by the FULL local shard so the M losses sum to the
            # local mean (the GPipe step's mean = loss_sum / targets.size)
            return loss_sum / jnp.float32(b_local * seq_len), metrics

        zeros_act = jnp.zeros((mb, seq_len, d_model), dtype)
        zeros_blocks_g = jax.tree.map(
            lambda x: jnp.zeros(x.shape, jnp.float32), blocks_local)
        zeros_eh_g = jax.tree.map(
            lambda x: jnp.zeros(x.shape, jnp.float32), eh)
        zeros_metrics = _zeros_metrics()

        def tick(carry, t):
            fwd_recv, bwd_recv, stash, g_blocks, g_eh, macc, mass2 = carry

            # ---- forward half: stage s forwards microbatch t - s ----
            # Bubble ticks (valid_f false) skip the block compute AND the
            # stash write; the embedding gather runs on stage 0 only. All
            # gating is per-device lax.cond — collectives stay outside.
            m_f = t - stage
            valid_f = (m_f >= 0) & (m_f < M)
            mf_c = jnp.clip(m_f, 0, M - 1)

            if gate_blocks:
                def fwd_do(sm):
                    stash, mass2 = sm
                    x_in = jax.lax.cond(is_first, lambda: embed(mf_c),
                                        lambda: fwd_recv)
                    y, (_, ms, mn) = stage_fwd(blocks_local, x_in)
                    stash = jax.lax.dynamic_update_index_in_dim(
                        stash, x_in, m_f % stash_depth, 0)
                    return y, (stash, (mass2[0] + ms, mass2[1] + mn))

                y, (stash, mass2) = jax.lax.cond(
                    valid_f, fwd_do, lambda sm: (zeros_act, sm),
                    (stash, mass2))
            else:  # tp: block compute runs unconditionally, embed still gated
                x_in = jax.lax.cond(is_first, lambda: embed(mf_c),
                                    lambda: fwd_recv)
                y_raw, (_, ms, mn) = stage_fwd(blocks_local, x_in)
                y = jnp.where(valid_f, y_raw, 0.0)
                gate_f = jnp.where(valid_f, 1.0, 0.0)
                mass2 = (mass2[0] + ms * gate_f, mass2[1] + mn * gate_f)
                stash = jnp.where(
                    valid_f,
                    jax.lax.dynamic_update_index_in_dim(
                        stash, x_in, m_f % stash_depth, 0),
                    stash)

            # ---- backward half: microbatch t - (2(S-1) - s) ----
            m_b = t - (2 * (S - 1) - stage)
            valid_b = (m_b >= 0) & (m_b < M)
            mb_c = jnp.clip(m_b, 0, M - 1)

            def head_vjp_acc(eh_macc, y_b):
                """Head fwd+vjp + metric accumulation (last stage, valid
                ticks only — the callers' cond guarantees it). 'model'-
                replicated, so always safe to branch around."""
                g_eh, macc = eh_macc
                _, vjp_head, metrics = jax.vjp(
                    lambda ehp, yy: head_loss(ehp, yy, mb_c), eh, y_b,
                    has_aux=True)
                d_eh, dy_head = vjp_head(jnp.float32(1.0))
                g_eh = jax.tree.map(
                    lambda a, g: a + g.astype(jnp.float32), g_eh, d_eh)
                macc = jax.tree.map(jnp.add, macc, metrics)
                return (g_eh, macc), dy_head.astype(y_b.dtype)

            def emb_scatter(g_eh, dx):
                """Embedding backward (stage 0, valid ticks only): scatter
                dx into the tok_emb rows, reduce over batch for pos_emb
                (scatter, not add: max_len may exceed L)."""
                dxf = dx.astype(jnp.float32)
                g_eh = {**g_eh, "tok_emb": {"embedding":
                        g_eh["tok_emb"]["embedding"]
                        .at[ids_mb[mb_c]].add(dxf)}}
                g_eh["pos_emb"] = {"embedding":
                                   g_eh["pos_emb"]["embedding"]
                                   .at[pos_ids].add(jnp.sum(dxf, axis=0))}
                return g_eh

            def bwd_do(acc):
                g_blocks, g_eh, macc = acc
                x_b = stash[mb_c % stash_depth]
                # recompute this stage's forward from the stashed input and
                # differentiate it (activation memory stays O(S), not O(M));
                # the (y, aux) pair takes the router-aux cotangent too
                (y_b, _), vjp_stage = jax.vjp(stage_va, blocks_local, x_b)
                # head fwd+vjp and metrics run on the LAST stage only; the
                # other stages' cotangent is what arrived over the ring
                (g_eh, macc), dy = jax.lax.cond(
                    is_last, lambda c: head_vjp_acc(c, y_b),
                    lambda c: (c, bwd_recv), (g_eh, macc))
                d_blocks, dx = vjp_stage((dy, aux_ct))
                g_blocks = jax.tree.map(
                    lambda a, g: a + g.astype(jnp.float32),
                    g_blocks, d_blocks)
                g_eh = jax.lax.cond(
                    is_first, lambda g: emb_scatter(g, dx),
                    lambda g: g, g_eh)
                return (g_blocks, g_eh, macc), dx

            if gate_blocks:
                (g_blocks, g_eh, macc), dx = jax.lax.cond(
                    valid_b, bwd_do, lambda acc: (acc, zeros_act),
                    (g_blocks, g_eh, macc))
            else:
                # tp: the stage vjp carries 'model' collectives, so it runs
                # unconditionally with multiply-gating; head/embedding
                # branches stay cond-gated (collective-free)
                x_b = stash[mb_c % stash_depth]
                (y_b, _), vjp_stage = jax.vjp(stage_va, blocks_local, x_b)
                (g_eh, macc), dy = jax.lax.cond(
                    valid_b & is_last, lambda c: head_vjp_acc(c, y_b),
                    lambda c: (c, bwd_recv), (g_eh, macc))
                d_blocks, dx = vjp_stage((dy, aux_ct))
                gate_b = jnp.where(valid_b, 1.0, 0.0)
                g_blocks = jax.tree.map(
                    lambda a, g: a + g.astype(jnp.float32) * gate_b,
                    g_blocks, d_blocks)
                g_eh = jax.lax.cond(
                    valid_b & is_first, lambda g: emb_scatter(g, dx),
                    lambda g: g, g_eh)

            fwd_send = jax.lax.ppermute(
                y, stage_axis, [(i, i + 1) for i in range(S - 1)])
            bwd_send = jax.lax.ppermute(
                dx, stage_axis, [(i + 1, i) for i in range(S - 1)])
            return (fwd_send, bwd_send, stash, g_blocks, g_eh, macc,
                    mass2), None

        stash0 = jnp.zeros((stash_depth, mb, seq_len, d_model), dtype)
        mass0 = (jnp.float32(0.0), jnp.float32(0.0))
        (_, _, _, g_blocks, g_eh, metrics, mass2), _ = jax.lax.scan(
            tick,
            (zeros_act, zeros_act, stash0, zeros_blocks_g, zeros_eh_g,
             zeros_metrics, mass0),
            jnp.arange(M + 2 * (S - 1)))

        # same reduction structure as the GPipe step: blocks stage-local,
        # embed/head reassembled across stages, everything data-averaged
        grads = {
            "blocks": jax.tree.map(
                lambda g: jax.lax.pmean(g, data_axis), g_blocks),
            "embed_head": jax.tree.map(
                lambda g: jax.lax.pmean(jax.lax.psum(g, stage_axis),
                                        data_axis), g_eh),
        }
        # restore the stacked (1, layers, ...) leading dim of the blocks
        # leaves so the grad tree matches the P('stage')-sharded params
        grads["blocks"] = jax.tree.map(lambda g: g[None], grads["blocks"])
        if grad_clip > 0:
            grads = _clip_pp_grads(grads, grad_clip, stage_axis)
        # router-mass diagnostic rides the metric sums exactly like the
        # GPipe step's (zeros for dense models) so the two schedules return
        # the same metric pytree
        metrics = {**metrics,
                   "router_mass_sum": mass2[0], "router_mass_n": mass2[1]}
        metrics = jax.tree.map(
            lambda v: jax.lax.psum(jax.lax.psum(v, stage_axis), data_axis),
            metrics)
        # stage-local block grads: see the gpipe builder's probe_sync note
        return _apply_update(
            tx, state, grads, {}, metrics, health,
            probe_sync=lambda p: {k: jax.lax.psum(v, stage_axis)
                                  for k, v in p.items()})

    return per_device


def make_lm_pp_indexed_multi_train_step(model, tx, mesh: Mesh,
                                        num_microbatches: int,
                                        schedule: str = "gpipe",
                                        data_axis: str = DATA_AXIS,
                                        stage_axis: str = STAGE_AXIS,
                                        donate: bool = True,
                                        aux_weight: float = 0.01,
                                        loss_chunk: int = 0,
                                        grad_clip: float = 0.0,
                                        health: str = "record"
                                        ) -> Callable:
    """K pipeline optimizer steps per dispatch from HBM-resident rows
    (VERDICT r3 #3): a lax.scan over (K, B) index windows INSIDE the
    shard_map program, so pipeline runs amortize the host round-trip the
    same way the jit modes do.

    signature: (state, rows_all (N, L+1) i32 REPLICATED, idx (K, B) i32
    sharded (None, data), rng) -> (state, metric sums over K steps).
    Identical math to K sequential per-batch pp steps (parameter equality
    asserted to rtol 1e-5 in tests/test_lm_loop.py)."""
    if schedule not in ("gpipe", "1f1b"):
        raise ValueError(f"unknown pp schedule {schedule!r} (gpipe|1f1b)")
    if schedule == "1f1b":
        one_step = _pp_1f1b_step_builder(model, tx, mesh,
                                         num_microbatches, data_axis,
                                         stage_axis, aux_weight,
                                         loss_chunk, grad_clip, health)
    else:
        one_step = _pp_gpipe_step_builder(model, tx, mesh,
                                          num_microbatches, data_axis,
                                          stage_axis, aux_weight,
                                          loss_chunk, grad_clip, health)

    def per_device(state: TrainState, rows_all, idx, rng):
        def body(st, idx_b):
            rows = jnp.take(rows_all, idx_b, axis=0)   # (B_local, L+1)
            return one_step(st, rows[:, :-1], rows[:, 1:], rng)

        state, metrics_k = jax.lax.scan(body, state, idx)
        return state, jax.tree.map(lambda m: jnp.sum(m, axis=0), metrics_k)

    def call(state, rows_all, idx, rng):
        specs = pp_state_specs(state, stage_axis)
        sharded = _pp_shard_map(
            mesh, per_device,
            (specs, P(), P(None, data_axis), P()),
            (specs, P()), data_axis, stage_axis)
        return sharded(state, rows_all, idx, rng)

    return jax.jit(call, donate_argnums=(0,) if donate else ())


def make_lm_pp_indexed_eval_step(model, mesh: Mesh, num_microbatches: int,
                                 data_axis: str = DATA_AXIS,
                                 stage_axis: str = STAGE_AXIS,
                                 loss_chunk: int = 0) -> Callable:
    """Whole-val-set perplexity in ONE dispatch through the pipeline:
    (params, rows_all (N, L+1) REPLICATED, idx (K, B) sharded (None, data),
    valid (K, B) f32 same sharding) -> metric sums over all K batches,
    real on the last stage only, psum'd over 'stage' and 'data'."""
    fwd_loss = _pp_forward_builder(model, mesh, num_microbatches,
                                   stage_axis, loss_chunk)

    def per_device(params, rows_all, idx, valid):
        def body(sums, blk):
            idx_b, valid_b = blk
            rows = jnp.take(rows_all, idx_b, axis=0)
            _, m, _ = fwd_loss(params, rows[:, :-1], rows[:, 1:],
                            valid_b.astype(jnp.float32))
            # eval reports the CE metric sums only (the router-mass keys
            # the train path attaches are a training-time diagnostic)
            return {k: sums[k] + m[k] for k in sums}, None

        sums, _ = jax.lax.scan(body, _zeros_metrics(), (idx, valid))
        return jax.tree.map(
            lambda v: jax.lax.psum(jax.lax.psum(v, stage_axis), data_axis),
            sums)

    def call(params, rows_all, idx, valid):
        p_specs = pp_state_specs(params, stage_axis)
        sharded = _pp_shard_map(
            mesh, per_device,
            (p_specs, P(), P(None, data_axis), P(None, data_axis)),
            P(), data_axis, stage_axis)
        return sharded(params, rows_all, idx, valid)

    return jax.jit(call)


def make_lm_pp_eval_step(model, mesh: Mesh, num_microbatches: int,
                         data_axis: str = DATA_AXIS,
                         stage_axis: str = STAGE_AXIS,
                         loss_chunk: int = 0) -> Callable:
    """Held-out eval through the pipeline: (params, inputs, targets, valid)
    -> psum'd metric sums. ``valid`` (B,) masks sampler wrap-padding rows;
    the head (and loss) run on the last stage only — other stages
    contribute exact zeros to the psum — the round-2 gap where pp had no
    eval path."""
    from tpu_dist.engine.lm_steps import LM_METRIC_KEYS

    fwd_loss = _pp_forward_builder(model, mesh, num_microbatches,
                                   stage_axis, loss_chunk)

    def per_device(params, inputs, targets, valid):
        _, metrics, _ = fwd_loss(params, inputs, targets,
                              valid.astype(jnp.float32))
        # eval reports the CE metric sums only: the router-mass keys the
        # train forward attaches are a training-time diagnostic, and every
        # other eval path returns exactly the zeros_lm_metrics key set
        metrics = {k: metrics[k] for k in LM_METRIC_KEYS}
        return jax.tree.map(
            lambda v: jax.lax.psum(jax.lax.psum(v, stage_axis), data_axis),
            metrics)

    def call(params, inputs, targets, valid):
        p_specs = pp_state_specs(params, stage_axis)
        sharded = _pp_shard_map(
            mesh, per_device,
            (p_specs, P(data_axis, None), P(data_axis, None),
             P(data_axis)),
            P(), data_axis, stage_axis)
        return sharded(params, inputs, targets, valid)

    return jax.jit(call)
