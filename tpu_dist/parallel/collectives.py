"""Collective ops + metric reduction + allreduce microbenchmark.

Replaces, TPU-first, the reference's three collective mechanisms
(SURVEY.md §5 'Distributed communication backend'):

* NCCL ``all_reduce(SUM)/nprocs`` metric averaging with a ``dist.barrier()``
  before it (reference 2.distributed.py:71-75,219-223) -> :func:`reduce_mean`
  (inside shard_map) or simply computing on globally-sharded arrays under jit
  (XLA inserts the reduction);
* horovod ``hvd.allreduce`` which averages natively — the upstream
  double-average bug fix (reference 5.horovod_distributed.py:70-75,
  README_EN.md:7) is moot here: there is exactly one averaging point;
* ``dist.barrier()`` -> :func:`barrier`, a blocking 1-element psum across the
  mesh (a barrier on TPU *is* a tiny collective).

Also provides the bf16 gradient-compression hook (hvd.Compression.fp16-equiv,
reference 5.horovod_distributed.py:123-125) and the allreduce-latency
microbenchmark that BASELINE.md requires this repo to establish.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_dist.parallel.mesh import DATA_AXIS


# ---- in-step collectives (used under shard_map with an axis name) ----------

def psum(x, axis_name: str = DATA_AXIS):
    return jax.lax.psum(x, axis_name)


def pmean(x, axis_name: str = DATA_AXIS):
    return jax.lax.pmean(x, axis_name)


def reduce_mean(tree, axis_name: str = DATA_AXIS):
    """C16 equivalent: average a metric pytree across replicas.

    Reference pattern: clone -> all_reduce(SUM) -> /nprocs
    (2.distributed.py:71-75). Here a single fused pmean; no barrier is needed
    (XLA orders collectives), removing the reference's per-batch
    barrier+allreduce serialization bug (SURVEY.md §3.2 note).
    """
    return jax.tree.map(lambda t: jax.lax.pmean(t, axis_name), tree)


def compress_grads(tree, compression: str = "none"):
    """Gradient payload compression before cross-replica reduction.

    'bf16' mirrors hvd.Compression.fp16 (reference 5.horovod_distributed.py:
    123-125): cast to bf16, reduce, cast back — halves ICI bytes.
    """
    if compression == "none":
        return tree, lambda t: t
    if compression == "bf16":
        orig_dtypes = jax.tree.map(lambda t: t.dtype, tree)
        down = jax.tree.map(lambda t: t.astype(jnp.bfloat16), tree)
        up = lambda t: jax.tree.map(lambda x, d: x.astype(d), t, orig_dtypes)
        return down, up
    raise ValueError(f"unknown grad compression {compression!r}")


def adasum_reduce(tree, axis_name: str = DATA_AXIS, axis_size: int = None,
                  granularity: str = "leaf"):
    """Adasum gradient reduction (hvd.Adasum, reference 5.2...py:184).

    Recursive-halving over ``axis_name``: log2(N) rounds in which partner
    pairs exchange their partial reductions via ppermute and combine with

        adasum(a, b) = (1 - <a,b> / (2|a|^2)) a + (1 - <a,b> / (2|b|^2)) b

    — orthogonal gradients ADD (descent progress keeps both directions),
    parallel identical gradients AVERAGE (no double-stepping), the scale-
    robust middle ground Adasum was built for.

    ``granularity`` picks where the inner products live (VERDICT r3 #7):

    * ``"leaf"`` (default) — the operator applies PER PARAMETER LEAF, which
      is Horovod's actual semantics (it reduces per tensor / fusion
      buffer, reference 5.2...py:184): each layer adapts its own
      orthogonal-vs-parallel mix, so one huge near-parallel tensor cannot
      drag every other layer toward averaging.
    * ``"tree"`` — inner products span the WHOLE flattened gradient (the
      degenerate one-fusion-buffer case; rounds 1-3 shipped this as the
      default while claiming Horovod parity — kept as an option).

    Requires a power-of-two axis size (the recursive-halving exchange
    pattern); the formula is symmetric, so both partners compute the same
    combined value and no broadcast round is needed.
    """
    import math as _math

    if granularity not in ("leaf", "tree"):
        raise ValueError(f"unknown adasum granularity {granularity!r}")
    n = axis_size if axis_size is not None else jax.lax.axis_size(axis_name)
    if n & (n - 1):
        raise ValueError(f"adasum needs a power-of-two axis size, got {n}")

    def dot(t1, t2):
        return sum(jnp.sum(a.astype(jnp.float32) * b.astype(jnp.float32))
                   for a, b in zip(jax.tree.leaves(t1), jax.tree.leaves(t2)))

    def combine_leaf(x, y):
        xf, yf = x.astype(jnp.float32), y.astype(jnp.float32)
        ab = jnp.sum(xf * yf)
        na = jnp.maximum(jnp.sum(xf * xf), 1e-30)
        nb = jnp.maximum(jnp.sum(yf * yf), 1e-30)
        return ((1.0 - ab / (2.0 * na)) * xf
                + (1.0 - ab / (2.0 * nb)) * yf).astype(x.dtype)

    a = tree
    for k in range(int(_math.log2(n))):
        stride = 1 << k
        perm = [(i, i ^ stride) for i in range(n)]
        b = jax.tree.map(
            lambda x: jax.lax.ppermute(x, axis_name, perm), a)
        if granularity == "leaf":
            a = jax.tree.map(combine_leaf, a, b)
        else:
            ab = dot(a, b)
            na = jnp.maximum(dot(a, a), 1e-30)
            nb = jnp.maximum(dot(b, b), 1e-30)
            wa = 1.0 - ab / (2.0 * na)
            wb = 1.0 - ab / (2.0 * nb)
            a = jax.tree.map(
                lambda x, y: (wa * x.astype(jnp.float32)
                              + wb * y.astype(jnp.float32)).astype(x.dtype),
                a, b)
    return a


def ring_allreduce(x, axis_name: str = DATA_AXIS, axis_size: int = None):
    """Bandwidth-optimal ring all-reduce (sum) via ``lax.ppermute``.

    The classic two-pass decomposition NCCL runs internally (and DDP's
    bucket allreduce rides on): a reduce-scatter pass — n-1 rounds in which
    each device forwards a rotating accumulator one hop and adds its local
    chunk — then an all-gather pass circulating the n fully-reduced chunks.
    Unlike one fused ``psum``, every round is an independent ppermute whose
    transfer XLA's latency-hiding scheduler can overlap with whatever
    compute is adjacent (parallel.overlap builds on exactly this property);
    the payload per hop is 1/n of the buffer, the bandwidth-optimal
    schedule. Exposed standalone for tools/comm_bench.py and as the 'ring'
    reduction flavor of overlap.bucketed_grad_sync.

    Must run inside shard_map with ``axis_name`` bound. Returns the SUM
    across the axis (psum semantics); callers divide for a mean.
    """
    n = axis_size if axis_size is not None else jax.lax.psum(1, axis_name)
    if n == 1:
        return x
    idx = jax.lax.axis_index(axis_name)
    flat = x.reshape(-1)
    size = flat.size
    pad = (-size) % n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    parts = flat.reshape(n, flat.size // n)
    fwd = [(i, (i + 1) % n) for i in range(n)]

    def chunk(j):
        return jax.lax.dynamic_index_in_dim(parts, j % n, 0, keepdims=False)

    # reduce-scatter: accumulator seeded with chunk (idx-1) lands home on
    # device (idx) after n-1 forward hops, summing every device's copy
    acc = chunk(idx - 1)
    for k in range(1, n):
        acc = jax.lax.ppermute(acc, axis_name, fwd)
        acc = acc + chunk(idx - k - 1)
    # all-gather: circulate the n reduced chunks; after hop k the piece in
    # flight on device idx is chunk (idx - k)
    out = jnp.zeros_like(parts)
    out = jax.lax.dynamic_update_index_in_dim(out, acc, idx, 0)
    cur = acc
    for k in range(1, n):
        cur = jax.lax.ppermute(cur, axis_name, fwd)
        out = jax.lax.dynamic_update_index_in_dim(out, cur, (idx - k) % n, 0)
    return out.reshape(-1)[:size].reshape(x.shape)


# ---- host-level barrier ----------------------------------------------------

def barrier(mesh: Mesh | None = None) -> None:
    """Block until all devices (all hosts' chips) reach this point.

    dist.barrier() equivalent (reference 2.distributed.py:219): a 1-element
    psum across every device, then block on the result.
    """
    devices = list(mesh.devices.flat) if mesh is not None else jax.devices()
    m = Mesh(np.asarray(devices), ("all",))
    one = jax.device_put(
        jnp.zeros((len(devices),), jnp.int32),
        # distlint: disable=DL003 -- 'all' names this function's own throwaway 1-axis mesh (built one line up), not the training mesh
        NamedSharding(m, P("all")))
    jnp.sum(one).block_until_ready()


# ---- allreduce microbenchmark (BASELINE.md 'allreduce µs') -----------------

def allreduce_bench(mesh: Mesh | None = None,
                    sizes_mb: Sequence[float] = (0.004, 1.0, 16.0, 64.0),
                    dtype=jnp.float32, iters: int = 20) -> dict:
    """Measure cross-device allreduce latency/bandwidth on this mesh.

    Returns {size_mb: {"us": mean_latency_us, "gbps": algo_bandwidth}}.
    The reference's analog capability lives inside NCCL; on TPU we measure the
    XLA collective end-to-end (jit'd psum of a device-sharded buffer).
    """
    if mesh is None:
        from tpu_dist.parallel.mesh import make_mesh
        mesh = make_mesh()
    axis = mesh.axis_names[0]
    n = mesh.devices.size
    results = {}
    for mb in sizes_mb:
        elems_per_dev = max(1, int(mb * 1e6 / jnp.dtype(dtype).itemsize))
        # distlint: disable=DL008 -- comm bench stages its own operands once per size; no input pipeline in play
        x = jax.device_put(
            jnp.ones((n, elems_per_dev), dtype),
            NamedSharding(mesh, P(axis)))

        @partial(jax.jit,
                 in_shardings=NamedSharding(mesh, P(axis)),
                 out_shardings=NamedSharding(mesh, P(axis)))
        def allreduce(v):
            # sum over the sharded axis then broadcast back = allreduce; XLA
            # lowers this to a native all-reduce over ICI.
            return jnp.broadcast_to(jnp.sum(v, axis=0, keepdims=True), v.shape)

        # distlint: disable=DL002 -- compile+warm barrier before the timed window
        allreduce(x).block_until_ready()  # compile + warm
        t0 = time.perf_counter()
        for _ in range(iters):
            out = allreduce(x)
        # distlint: disable=DL002 -- the timed measurement barrier - benches measure the sync
        out.block_until_ready()
        dt = (time.perf_counter() - t0) / iters
        nbytes = elems_per_dev * jnp.dtype(dtype).itemsize
        results[mb] = {"us": dt * 1e6,
                       "gbps": (2 * (n - 1) / max(n, 1)) * nbytes / dt / 1e9}
    return results
