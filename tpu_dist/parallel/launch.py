"""Process launch / rendezvous layer (reference C23/C25 + the four rendezvous
flavors of SURVEY.md §5).

The reference rendezvouses four ways — env:// from torch.distributed.launch
(2.distributed.py:98), tcp:// (3.multiprocessing_distributed.py:102), file://
on a shared FS keyed by SLURM_JOBID (6.distributed_slurm_main.py:93-101), and
an MPI/Gloo controller under horovodrun (5.run.sh:3). On TPU these collapse to
one thing: coordinator-address discovery for ``jax.distributed.initialize``
over DCN. This module abstracts that discovery, in priority order:

1. explicit args / tpu_dist env (TPU_DIST_COORDINATOR, TPU_DIST_NUM_PROCESSES,
   TPU_DIST_PROCESS_ID)  — env:// equivalent;
2. Slurm env (SLURM_PROCID/SLURM_NPROCS/SLURM_JOB_NODELIST) — variant-6
   equivalent, same rank math;
3. TPU pod metadata — ``jax.distributed.initialize()`` with no args
   autodetects on Cloud TPU;
4. nothing set -> single-process (variants 1-style local run).
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass
from typing import Optional


@dataclass
class LaunchInfo:
    coordinator: Optional[str]
    num_processes: int
    process_id: int
    method: str  # env | slurm | tpu-metadata | local


def _slurm_first_host(nodelist: str) -> str:
    """Expand 'prefix[a-b,c],other' to its first hostname (no external tools)."""
    m = re.match(r"([^\[,]+)(\[([^\]]+)\])?", nodelist)
    if not m:
        return nodelist.split(",")[0]
    prefix, _, body = m.groups()
    if not body:
        return prefix
    first = body.split(",")[0].split("-")[0]
    return prefix + first


def detect_launch(coordinator: Optional[str] = None,
                  num_processes: Optional[int] = None,
                  process_id: Optional[int] = None,
                  port: int = 8476) -> LaunchInfo:
    env = os.environ
    if coordinator or env.get("TPU_DIST_COORDINATOR"):
        return LaunchInfo(
            coordinator or env["TPU_DIST_COORDINATOR"],
            int(num_processes if num_processes is not None
                else env.get("TPU_DIST_NUM_PROCESSES", "1")),
            int(process_id if process_id is not None
                else env.get("TPU_DIST_PROCESS_ID", "0")),
            "env")
    if "SLURM_PROCID" in env and env.get("SLURM_NPROCS", "1") != "1":
        # reference 6.distributed_slurm_main.py:89-94: rank from SLURM_PROCID,
        # world from SLURM_NPROCS; file:// rendezvous becomes coordinator TCP.
        host = _slurm_first_host(env.get("SLURM_JOB_NODELIST", "localhost"))
        return LaunchInfo(f"{host}:{port}", int(env["SLURM_NPROCS"]),
                          int(env["SLURM_PROCID"]), "slurm")
    workers = [h for h in env.get("TPU_WORKER_HOSTNAMES", "").split(",") if h]
    if len(workers) > 1 or env.get("MEGASCALE_COORDINATOR_ADDRESS"):
        return LaunchInfo(None, -1, -1, "tpu-metadata")
    return LaunchInfo(None, 1, 0, "local")


def initialize(coordinator: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> LaunchInfo:
    """Multi-host init (idempotent). The hvd.init()/init_process_group analog."""
    import jax
    # Pin the platform choice via jax.config BEFORE distributed init: on images
    # whose sitecustomize pre-registers a TPU plugin, the env var alone leaves
    # jax.distributed binding to the wrong backend (observed: process_count
    # stays 1 despite a successful coordination-service rendezvous).
    platform = os.environ.get("TPU_DIST_PLATFORM") or os.environ.get("JAX_PLATFORMS")
    if platform:
        jax.config.update("jax_platforms", platform)
    info = detect_launch(coordinator, num_processes, process_id)
    if info.method == "local":
        return info
    if info.method == "tpu-metadata":
        try:
            jax.distributed.initialize()
        except ValueError:
            # metadata incomplete (e.g. single-host dev box) -> local run
            return LaunchInfo(None, 1, 0, "local")
        return LaunchInfo(None, jax.process_count(), jax.process_index(),
                          "tpu-metadata")
    # the EFFECTIVE platform (the config value pinned above), not the env
    # vars: TPU_DIST_PLATFORM=tpu must win over a leftover JAX_PLATFORMS=cpu,
    # and a worker that pinned cpu via jax.config directly must still be
    # caught. Unset means backend auto-detection — leave that path alone
    # (reading the default backend here would initialize it prematurely).
    effective = getattr(jax.config, "jax_platforms", None) or ""
    if effective.split(",")[0] == "cpu" and info.num_processes > 1:
        from tpu_dist._compat import CPU_MULTIPROCESS
        if not CPU_MULTIPROCESS:
            raise RuntimeError(
                f"{info.num_processes}-process CPU run requested "
                f"({info.method} rendezvous), but this jax "
                f"({jax.__version__}) has no multi-process CPU "
                "computations — every collective would die with "
                "INVALID_ARGUMENT after rendezvous. Upgrade jax or run "
                "single-process with virtual devices "
                "(_compat.set_cpu_device_count).")
    jax.distributed.initialize(coordinator_address=info.coordinator,
                               num_processes=info.num_processes,
                               process_id=info.process_id)
    return info
