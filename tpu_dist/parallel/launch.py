"""Process launch / rendezvous layer (reference C23/C25 + the four rendezvous
flavors of SURVEY.md §5).

The reference rendezvouses four ways — env:// from torch.distributed.launch
(2.distributed.py:98), tcp:// (3.multiprocessing_distributed.py:102), file://
on a shared FS keyed by SLURM_JOBID (6.distributed_slurm_main.py:93-101), and
an MPI/Gloo controller under horovodrun (5.run.sh:3). On TPU these collapse to
one thing: coordinator-address discovery for ``jax.distributed.initialize``
over DCN. This module abstracts that discovery, in priority order:

1. explicit args / tpu_dist env (TPU_DIST_COORDINATOR, TPU_DIST_NUM_PROCESSES,
   TPU_DIST_PROCESS_ID)  — env:// equivalent;
2. Slurm env (SLURM_PROCID/SLURM_NPROCS/SLURM_JOB_NODELIST) — variant-6
   equivalent, same rank math;
3. TPU pod metadata — ``jax.distributed.initialize()`` with no args
   autodetects on Cloud TPU;
4. nothing set -> single-process (variants 1-style local run).
"""

from __future__ import annotations

import os
import re
import sys
import time
from dataclasses import dataclass
from typing import Callable, Optional

from tpu_dist.obs import faults as _faults


@dataclass
class LaunchInfo:
    coordinator: Optional[str]
    num_processes: int
    process_id: int
    method: str  # env | slurm | tpu-metadata | local


def _slurm_first_host(nodelist: str) -> str:
    """Expand 'prefix[a-b,c],other' to its first hostname (no external tools)."""
    m = re.match(r"([^\[,]+)(\[([^\]]+)\])?", nodelist)
    if not m:
        return nodelist.split(",")[0]
    prefix, _, body = m.groups()
    if not body:
        return prefix
    first = body.split(",")[0].split("-")[0]
    return prefix + first


def epoch_coordinator(coordinator: str, epoch: int) -> str:
    """Offset the coordinator port by the consensus mesh epoch
    (``TPU_DIST_MESH_EPOCH``, parallel.consensus): every re-formed mesh
    rendezvouses on a FRESH port, so a shrink/re-expansion relaunch never
    reconnects to the previous epoch's half-dead coordination service —
    the stale-coordinator hang the PR-10 rendezvous retries could only
    time out of, not avoid. Pure; unparseable inputs pass through."""
    if not coordinator or epoch <= 0 or ":" not in coordinator:
        return coordinator
    host, _, port = coordinator.rpartition(":")
    try:
        return f"{host}:{int(port) + epoch}"
    except ValueError:
        return coordinator


def detect_launch(coordinator: Optional[str] = None,
                  num_processes: Optional[int] = None,
                  process_id: Optional[int] = None,
                  port: int = 8476) -> LaunchInfo:
    env = os.environ
    if coordinator or env.get("TPU_DIST_COORDINATOR"):
        try:
            epoch = int(env.get("TPU_DIST_MESH_EPOCH", "0") or 0)
        except ValueError:
            epoch = 0
        return LaunchInfo(
            epoch_coordinator(coordinator or env["TPU_DIST_COORDINATOR"],
                              epoch),
            int(num_processes if num_processes is not None
                else env.get("TPU_DIST_NUM_PROCESSES", "1")),
            int(process_id if process_id is not None
                else env.get("TPU_DIST_PROCESS_ID", "0")),
            "env")
    if "SLURM_PROCID" in env and env.get("SLURM_NPROCS", "1") != "1":
        # reference 6.distributed_slurm_main.py:89-94: rank from SLURM_PROCID,
        # world from SLURM_NPROCS; file:// rendezvous becomes coordinator TCP.
        # The tpu_dist consensus overrides (dense renumbering + epoch) must
        # win over the static Slurm env: a supervisor relaunch after host
        # loss exports shrunken TPU_DIST_* values while SLURM_* still
        # describes the original allocation.
        host = _slurm_first_host(env.get("SLURM_JOB_NODELIST", "localhost"))
        try:
            epoch = int(env.get("TPU_DIST_MESH_EPOCH", "0") or 0)
        except ValueError:
            epoch = 0
        return LaunchInfo(
            epoch_coordinator(f"{host}:{port}", epoch),
            int(num_processes if num_processes is not None
                else env.get("TPU_DIST_NUM_PROCESSES")
                or env["SLURM_NPROCS"]),
            int(process_id if process_id is not None
                else env.get("TPU_DIST_PROCESS_ID") or env["SLURM_PROCID"]),
            "slurm")
    workers = [h for h in env.get("TPU_WORKER_HOSTNAMES", "").split(",") if h]
    if len(workers) > 1 or env.get("MEGASCALE_COORDINATOR_ADDRESS"):
        return LaunchInfo(None, -1, -1, "tpu-metadata")
    return LaunchInfo(None, 1, 0, "local")


def rendezvous_with_retry(init_fn: Callable[[], None], info: LaunchInfo,
                          retries: Optional[int] = None,
                          timeout_s: Optional[float] = None,
                          backoff_s: Optional[float] = None,
                          sleep: Callable[[float], None] = time.sleep) -> int:
    """Bounded retry + exponential backoff around one rendezvous call.

    A flaky coordinator (still booting, preempted mid-restart, transient
    DNS) used to surface as a raw grpc stack from deep inside
    ``jax.distributed.initialize``; a supervised restart needs the
    rendezvous to *ride out* the window where peers come back up. Retries
    ``init_fn`` up to ``TPU_DIST_RENDEZVOUS_RETRIES`` times (default 5)
    with ``TPU_DIST_RENDEZVOUS_BACKOFF_S``-based exponential backoff
    (default 2s, doubling, capped at 30s) under a
    ``TPU_DIST_RENDEZVOUS_TIMEOUT_S`` TOTAL deadline (default 300s).
    Returns the number of attempts used; on exhaustion raises ONE clear
    error naming the coordinator, method, and attempt count. The
    ``rendezvous_fail`` fault site (obs.faults) injects the failure
    deterministically — ``times=K`` fails the first K attempts."""
    env = os.environ
    retries = int(env.get("TPU_DIST_RENDEZVOUS_RETRIES", "5")
                  if retries is None else retries)
    timeout_s = float(env.get("TPU_DIST_RENDEZVOUS_TIMEOUT_S", "300")
                      if timeout_s is None else timeout_s)
    backoff_s = float(env.get("TPU_DIST_RENDEZVOUS_BACKOFF_S", "2")
                      if backoff_s is None else backoff_s)
    t0 = time.monotonic()
    last: Optional[BaseException] = None
    for attempt in range(1, max(retries, 1) + 1):
        try:
            if _faults.fire("rendezvous_fail", attempt_no=attempt):
                raise ConnectionError("injected rendezvous failure")
            init_fn()
            return attempt
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as e:  # grpc failures arrive as assorted types
            last = e
            elapsed = time.monotonic() - t0
            wait = min(backoff_s * (2 ** (attempt - 1)), 30.0)
            if attempt >= retries or elapsed + wait >= timeout_s:
                break
            print(f"rendezvous attempt {attempt}/{retries} with "
                  f"{info.coordinator} failed ({e}); retrying in "
                  f"{wait:.1f}s", file=sys.stderr, flush=True)
            sleep(wait)
    raise RuntimeError(
        f"rendezvous failed: could not reach coordinator "
        f"{info.coordinator!r} ({info.method} method, process "
        f"{info.process_id}/{info.num_processes}) after {attempt} "
        f"attempt(s) over {time.monotonic() - t0:.1f}s "
        f"(TPU_DIST_RENDEZVOUS_RETRIES={retries}, "
        f"TPU_DIST_RENDEZVOUS_TIMEOUT_S={timeout_s:g}). "
        f"Last error: {last}") from last


def initialize(coordinator: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> LaunchInfo:
    """Multi-host init (idempotent). The hvd.init()/init_process_group analog."""
    import jax
    # Pin the platform choice via jax.config BEFORE distributed init: on images
    # whose sitecustomize pre-registers a TPU plugin, the env var alone leaves
    # jax.distributed binding to the wrong backend (observed: process_count
    # stays 1 despite a successful coordination-service rendezvous).
    platform = os.environ.get("TPU_DIST_PLATFORM") or os.environ.get("JAX_PLATFORMS")
    if platform:
        jax.config.update("jax_platforms", platform)
    info = detect_launch(coordinator, num_processes, process_id)
    if info.method == "local":
        return info
    if info.method == "tpu-metadata":
        try:
            jax.distributed.initialize()
        except ValueError:
            # metadata incomplete (e.g. single-host dev box) -> local run
            return LaunchInfo(None, 1, 0, "local")
        return LaunchInfo(None, jax.process_count(), jax.process_index(),
                          "tpu-metadata")
    # the EFFECTIVE platform (the config value pinned above), not the env
    # vars: TPU_DIST_PLATFORM=tpu must win over a leftover JAX_PLATFORMS=cpu,
    # and a worker that pinned cpu via jax.config directly must still be
    # caught. Unset means backend auto-detection — leave that path alone
    # (reading the default backend here would initialize it prematurely).
    effective = getattr(jax.config, "jax_platforms", None) or ""
    if effective.split(",")[0] == "cpu" and info.num_processes > 1:
        from tpu_dist._compat import CPU_MULTIPROCESS
        if not CPU_MULTIPROCESS:
            raise RuntimeError(
                f"{info.num_processes}-process CPU run requested "
                f"({info.method} rendezvous), but this jax "
                f"({jax.__version__}) has no multi-process CPU "
                "computations — every collective would die with "
                "INVALID_ARGUMENT after rendezvous. Upgrade jax or run "
                "single-process with virtual devices "
                "(_compat.set_cpu_device_count).")
    rendezvous_with_retry(
        lambda: jax.distributed.initialize(
            coordinator_address=info.coordinator,
            num_processes=info.num_processes,
            process_id=info.process_id),
        info)
    return info
