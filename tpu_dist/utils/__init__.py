from tpu_dist.utils.meters import MeterBank, accuracy, topk_accuracy  # noqa: F401
