from tpu_dist.utils.meters import AverageMeter, ProgressMeter, accuracy, topk_accuracy  # noqa: F401
