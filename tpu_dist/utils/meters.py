"""Meters + accuracy (reference components C17/C18).

The reference carries a per-metric running-average object plus a separate
progress printer, copied verbatim into every script (reference:
1.dataparallel.py:291-329 and five clones). tpu_dist doesn't need that
machinery: the loss/accuracy numbers are exact SUMS computed on device inside
the jitted step and fetched in windows, so the host side only has to
accumulate (sum, count, last) per metric name and render the cookbook's
progress line — one :class:`MeterBank` per epoch does both. Only the printed
line's field layout (``Name last (avg)`` cells after an ``[i/N]`` header)
matches the reference, because that text IS the compatibility surface.

Accuracy exists in two reference flavors:

* a simplified top-1 (argmax == target fraction) returned twice as "top1/top5"
  (reference 1.dataparallel.py:339-364, documented in README_EN.md:654) — kept
  here as :func:`accuracy` for numeric parity with the cookbook's printouts;
* the real top-k percent version used by the Slurm variant
  (reference 6.distributed_slurm_main.py:335-349) — kept as
  :func:`topk_accuracy` and used by default in tpu_dist because it is correct.

On TPU the accuracy math runs *inside* the jitted step on device (returning
summed-correct counts so cross-replica reduction is an exact psum, not the
reference's equal-weight average of per-rank fractions — see SURVEY.md §7
"Metric parity"); these host-side helpers mirror the same math for tests and
for eval-on-host paths.
"""

from __future__ import annotations

import jax.numpy as jnp


class MeterBank:
    """Named running sums for one epoch of host-side telemetry (C17).

    ``fields`` is an ordered ``(name, format_spec)`` sequence — the spec is a
    plain Python format spec (e.g. ``".4e"``, ``"6.3f"``) applied to both the
    last value and the running average in the progress line. Device metrics
    are fed in at print-frequency boundaries as exact per-window sums; host
    timings are fed every iteration, so every average is
    total/size-weighted — there is no meter whose mean depends on how often
    the loop prints.
    """

    def __init__(self, total_batches: int, fields, prefix: str = ""):
        self.total_batches = total_batches
        self.prefix = prefix
        self._fields = list(fields)
        # per name: [weighted sum, total weight, last value]
        self._stats = {name: [0.0, 0, 0.0] for name, _ in self._fields}

    def update(self, name: str, value, n: int = 1) -> None:
        s = self._stats[name]
        v = float(value)
        s[0] += v * n
        s[1] += n
        s[2] = v

    def avg(self, name: str) -> float:
        s = self._stats[name]
        return s[0] / max(s[1], 1)

    def last(self, name: str) -> float:
        return self._stats[name][2]

    def snapshot(self) -> dict:
        """One read of every field: {name: {"last": x, "avg": y}}.

        THE shared view the loops feed both the progress printer and the
        run ledger from (``line()`` renders from this same dict), so the
        printed numbers and the recorded numbers can never drift — and
        callers stop reaching into the private ``_stats``.
        """
        return {name: {"last": self.last(name), "avg": self.avg(name)}
                for name, _ in self._fields}

    def line(self, batch: int, snapshot: dict = None) -> str:
        snap = snapshot if snapshot is not None else self.snapshot()
        w = len(str(self.total_batches))
        cells = [f"{self.prefix}[{batch:{w}d}/{self.total_batches}]"]
        cells += [f"{name} {snap[name]['last']:{spec}} "
                  f"({snap[name]['avg']:{spec}})"
                  for name, spec in self._fields]
        return "\t".join(cells)

    def display(self, batch: int, printer=print) -> None:
        printer(self.line(batch))


def accuracy(output, target):
    """Reference's simplified accuracy: argmax==target fraction, returned twice
    as (top1, top5) for printout parity (reference 1.dataparallel.py:339-364)."""
    pred = jnp.argmax(output, axis=-1)
    acc = jnp.mean((pred == target).astype(jnp.float32))
    return acc, acc


def topk_accuracy(output, target, topk=(1, 5)):
    """True top-k accuracy in percent (reference 6.distributed_slurm_main.py:335-349).

    Static-shape friendly: uses top_k + any-match rather than sort+index tricks.
    """
    maxk = max(topk)
    topk_idx = jnp.argsort(-output, axis=-1)[:, :maxk]
    correct = topk_idx == target[:, None]
    res = []
    batch = target.shape[0]
    for k in topk:
        correct_k = jnp.sum(jnp.any(correct[:, :k], axis=-1).astype(jnp.float32))
        res.append(correct_k * (100.0 / batch))
    return res


def correct_counts(output, target, topk=(1, 5)):
    """Summed correct-prediction counts for exact distributed metric reduction.

    Returning *counts* (not fractions) lets the engine psum them across replicas
    and divide by the true global sample count — fixing the reference's
    equal-weight averaging of unequal last batches (reference
    2.distributed.py:221-227; SURVEY.md §7 'Metric parity').
    """
    maxk = max(topk)
    topk_idx = jnp.argsort(-output, axis=-1)[:, :maxk]
    correct = topk_idx == target[:, None]
    return tuple(jnp.sum(jnp.any(correct[:, :k], axis=-1).astype(jnp.float32))
                 for k in topk)
