"""Meters + accuracy (reference components C17/C18).

The reference copies ``AverageMeter``/``ProgressMeter`` verbatim into every
script (reference: 1.dataparallel.py:291-329 and five clones). Accuracy exists
in two reference flavors:

* a simplified top-1 (argmax == target fraction) returned twice as "top1/top5"
  (reference 1.dataparallel.py:339-364, documented in README_EN.md:654) — kept
  here as :func:`accuracy` for numeric parity with the cookbook's printouts;
* the real top-k percent version used by the Slurm variant
  (reference 6.distributed_slurm_main.py:335-349) — kept as
  :func:`topk_accuracy` and used by default in tpu_dist because it is correct.

On TPU the accuracy math runs *inside* the jitted step on device (returning
summed-correct counts so cross-replica reduction is an exact psum, not the
reference's equal-weight average of per-rank fractions — see SURVEY.md §7
"Metric parity"); these host-side helpers mirror the same math for tests and
for eval-on-host paths.
"""

from __future__ import annotations

import jax.numpy as jnp


class AverageMeter:
    """Running value/avg/sum/count meter (reference 1.dataparallel.py:291-312)."""

    def __init__(self, name: str, fmt: str = ":f"):
        self.name = name
        self.fmt = fmt
        self.reset()

    def reset(self):
        self.val = 0.0
        self.avg = 0.0
        self.sum = 0.0
        self.count = 0

    def update(self, val, n: int = 1):
        val = float(val)
        self.val = val
        self.sum += val * n
        self.count += n
        self.avg = self.sum / max(self.count, 1)

    def __str__(self):
        fmtstr = "{name} {val" + self.fmt + "} ({avg" + self.fmt + "})"
        return fmtstr.format(**self.__dict__)


class ProgressMeter:
    """Tab-joined progress line every N batches (reference 1.dataparallel.py:315-329)."""

    def __init__(self, num_batches: int, meters, prefix: str = ""):
        self.batch_fmtstr = self._get_batch_fmtstr(num_batches)
        self.meters = meters
        self.prefix = prefix

    def display(self, batch: int, printer=print):
        entries = [self.prefix + self.batch_fmtstr.format(batch)]
        entries += [str(meter) for meter in self.meters]
        printer("\t".join(entries))

    @staticmethod
    def _get_batch_fmtstr(num_batches: int) -> str:
        num_digits = len(str(num_batches // 1))
        fmt = "{:" + str(num_digits) + "d}"
        return "[" + fmt + "/" + fmt.format(num_batches) + "]"


def accuracy(output, target):
    """Reference's simplified accuracy: argmax==target fraction, returned twice
    as (top1, top5) for printout parity (reference 1.dataparallel.py:339-364)."""
    pred = jnp.argmax(output, axis=-1)
    acc = jnp.mean((pred == target).astype(jnp.float32))
    return acc, acc


def topk_accuracy(output, target, topk=(1, 5)):
    """True top-k accuracy in percent (reference 6.distributed_slurm_main.py:335-349).

    Static-shape friendly: uses top_k + any-match rather than sort+index tricks.
    """
    maxk = max(topk)
    topk_idx = jnp.argsort(-output, axis=-1)[:, :maxk]
    correct = topk_idx == target[:, None]
    res = []
    batch = target.shape[0]
    for k in topk:
        correct_k = jnp.sum(jnp.any(correct[:, :k], axis=-1).astype(jnp.float32))
        res.append(correct_k * (100.0 / batch))
    return res


def correct_counts(output, target, topk=(1, 5)):
    """Summed correct-prediction counts for exact distributed metric reduction.

    Returning *counts* (not fractions) lets the engine psum them across replicas
    and divide by the true global sample count — fixing the reference's
    equal-weight averaging of unequal last batches (reference
    2.distributed.py:221-227; SURVEY.md §7 'Metric parity').
    """
    maxk = max(topk)
    topk_idx = jnp.argsort(-output, axis=-1)[:, :maxk]
    correct = topk_idx == target[:, None]
    return tuple(jnp.sum(jnp.any(correct[:, :k], axis=-1).astype(jnp.float32))
                 for k in topk)
