"""MFU accounting: XLA-cost-model FLOPs vs device peak (VERDICT r1 #4).

Shared by bench.py and the LM/image trainers so every throughput number can
carry a model-FLOPs-utilization figure. Peaks are public bf16 spec-sheet
numbers per chip; override with BENCH_PEAK_TFLOPS for unlisted devices.
"""

from __future__ import annotations

import os
import sys

# bf16 peak TFLOP/s per chip by device kind (public spec sheets)
PEAK_TFLOPS = (
    ("v6", 918.0), ("trillium", 918.0),
    ("v5p", 459.0),
    ("v5 lite", 197.0), ("v5e", 197.0), ("v5litepod", 197.0),
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 45.0),
)


def peak_tflops_for(device) -> float | None:
    env = os.environ.get("BENCH_PEAK_TFLOPS")
    if env:
        return float(env)
    kind = getattr(device, "device_kind", "").lower()
    for key, peak in PEAK_TFLOPS:
        if key in kind:
            return peak
    return None


def step_flops(jitted_step, *args) -> float | None:
    """One step's FLOPs from XLA's cost model (per-device SPMD program);
    None when the backend doesn't expose cost analysis."""
    try:
        cost = jitted_step.lower(*args).compile().cost_analysis()
        if isinstance(cost, list):  # older API: one dict per device program
            cost = cost[0]
        return float(cost["flops"])
    except Exception as e:
        print(f"cost_analysis unavailable: {e!r}", file=sys.stderr)
        return None
