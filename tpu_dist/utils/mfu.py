"""MFU accounting: XLA-cost-model FLOPs vs device peak (VERDICT r1 #4).

Shared by bench.py and the LM/image trainers so every throughput number can
carry a model-FLOPs-utilization figure. Peaks are public bf16 spec-sheet
numbers per chip; override with BENCH_PEAK_TFLOPS for unlisted devices.
"""

from __future__ import annotations

import os

# bf16 peak TFLOP/s per chip by device kind (public spec sheets)
PEAK_TFLOPS = (
    ("v6", 918.0), ("trillium", 918.0),
    ("v5p", 459.0),
    ("v5 lite", 197.0), ("v5e", 197.0), ("v5litepod", 197.0),
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 45.0),
)


def peak_tflops_for(device) -> float | None:
    env = os.environ.get("BENCH_PEAK_TFLOPS")
    if env:
        return float(env)
    kind = getattr(device, "device_kind", "").lower()
    for key, peak in PEAK_TFLOPS:
        if key in kind:
            return peak
    return None


def lm_flops_per_token(params, num_layers: int, seq_len: int,
                       d_model: int) -> float:
    """Analytical model FLOPs per trained token for a dense causal LM:
    6 * N_non-embedding + 6 * layers * L * d (fwd+bwd, causal-halved
    attention). THE shared accounting for bench.py and LMTrainer — XLA's
    cost model counts scan bodies once and cannot cost Pallas custom calls,
    so it understates flash-attention runs."""
    import jax
    import numpy as np

    n_params = sum(int(np.prod(x.shape))
                   for x in jax.tree_util.tree_leaves(params))
    n_embed = 0
    for path, leaf in jax.tree_util.tree_leaves_with_path(params):
        key = jax.tree_util.keystr(path)
        if "tok_emb" in key or "pos_emb" in key:
            n_embed += int(np.prod(leaf.shape))
    return 6.0 * (n_params - n_embed) + 6.0 * num_layers * seq_len * d_model


def moe_lm_flops_per_token(params, num_layers: int, seq_len: int,
                           d_model: int, num_experts: int,
                           router_top_k: int, total_tokens: int,
                           group_size: int = 512,
                           capacity_factor: float = 1.25) -> float:
    """Analytical model FLOPs per trained token for the MoE LM (VERDICT r3
    #4 — the XLA-cost-model fallback understates scan bodies and cannot see
    how many experts a token activates). Terms, all fwd+bwd (x6 per
    multiply-add pair, the same convention as lm_flops_per_token):

    * dense part: 6 x non-embedding, non-expert params (attention, norms,
      gate, head) + 6 x layers x L x d causal attention;
    * expert MLPs: a token activates top_k of E experts, so
      6 x top_k x (expert params / E);
    * dispatch/combine einsums: (G,S,E,C)x(G,S,D) contractions cost
      E x C x D multiply-adds per token per layer, twice (dispatch and
      combine) — the price of all-static GShard routing, which the XLA
      model DOES count but only per-scan-trip.
    The capacity C comes from the same moe_group_geometry the layer uses.
    """
    import jax
    import numpy as np

    from tpu_dist.models.moe import moe_group_geometry

    n_params = n_embed = n_expert = 0
    for path, leaf in jax.tree_util.tree_leaves_with_path(params):
        key = jax.tree_util.keystr(path)
        size = int(np.prod(leaf.shape))
        n_params += size
        if "tok_emb" in key or "pos_emb" in key:
            n_embed += size
        elif "w_in" in key or "w_out" in key:
            n_expert += size
    dense = 6.0 * (n_params - n_embed - n_expert) \
        + 6.0 * num_layers * seq_len * d_model
    experts = 6.0 * router_top_k * n_expert / num_experts
    _, cap = moe_group_geometry(total_tokens, seq_len, num_experts,
                                router_top_k, group_size, capacity_factor)
    routing = 2 * 6.0 * num_experts * cap * d_model * num_layers
    return dense + experts + routing


# (the former step_flops() XLA-cost-model probe lives in
# utils.telemetry.program_stats now — one AOT lower for flops/hbm/HLO
# together; its last caller, bench.py, moved there in round 10)
