"""Device-side telemetry (reference statistics.sh:1-4, the nvidia-smi analog).

The reference samples GPU memory + utilization to CSV every 500 ms with
nvidia-smi from a *separate process*. TPU device memory is only visible to
the owning process (the XLA client), so the analog is in-process: a daemon
thread samples ``device.memory_stats()`` — the runtime's live HBM counters
(bytes_in_use / peak_bytes_in_use / bytes_limit) — at the same cadence,
alongside host RSS. ``scripts/statistics.sh`` keeps the out-of-process host
view; engines start this sampler when ``--telemetry-csv`` is set.

CPU/virtual backends return no memory_stats; columns are left empty there so
the same CSV schema works in tests.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional

import jax

CSV_HEADER = "ts,hbm_bytes_in_use,hbm_peak_bytes,hbm_bytes_limit,host_rss_kb"


def device_memory_stats(device: Optional[jax.Device] = None) -> dict:
    """memory_stats() of the first addressable device; {} when the backend
    does not expose counters (CPU, some virtual platforms)."""
    dev = device or jax.local_devices()[0]
    try:
        stats = dev.memory_stats()
    except Exception:
        return {}
    return stats or {}


def peak_hbm_bytes(device: Optional[jax.Device] = None) -> Optional[int]:
    """High-water HBM mark since process start (the per-epoch CSV column).

    This is the allocator's own peak counter — it covers every compiled
    program and live buffer, which is what an OOM postmortem needs; the
    per-program view lives in compiled.memory_analysis() (tests/test_pp.py
    uses it to pin 1F1B's O(S) activation flatness).
    """
    return device_memory_stats(device).get("peak_bytes_in_use")


def program_hbm_bytes(jitted_fn, *args) -> Optional[int]:
    """Static peak-HBM estimate of ONE compiled program from XLA's own
    buffer assignment (compiled.memory_analysis()): arguments + outputs +
    temps - donated aliases. Works on every backend — including tunneled
    controllers where memory_stats() returns None — because it reads the
    executable, not allocator counters.

    CALL ORDER CONTRACT: probe AFTER the function's first real dispatch.
    The AOT ``lower().compile()`` here does not seed jit's dispatch cache,
    so probing first compiles the program twice (the round-5 advisor's
    double-compile finding); probed second, the lowering hits the trace/
    compilation cache and the probe is cheap. The engines enforce this by
    statement ORDER — the probe sits directly below the dispatch call in
    the same loop iteration (gated on ``_program_hbm is None`` so it runs
    once) — which also keeps the column on single-dispatch runs."""
    return program_stats(jitted_fn, *args)["hbm_bytes"]


def program_stats(jitted_fn, *args, with_hlo: bool = False) -> dict:
    """{'hbm_bytes', 'flops'[, 'hlo']} of ONE compiled program in ONE AOT
    lower+compile (both the buffer assignment and the cost model read the
    same executable, so probing them together halves the — cached, but not
    free — lowering work). Same post-dispatch call-order contract as
    :func:`program_hbm_bytes`. Either value is None when the backend does
    not expose it; on a multi-step (lax.scan) window program the cost
    model counts the scan body ONCE, so ``flops`` approximates one
    optimizer step's FLOPs there, not the window's.

    ``with_hlo=True`` additionally returns the OPTIMIZED (post-fusion) HLO
    text of the same executable under ``'hlo'`` — the input to
    :func:`tpu_dist.obs.attr.cost_buckets` — so cost attribution reuses
    this probe's lower+compile instead of paying its own. Off by default:
    the text can run to megabytes on real step programs."""
    out = {"hbm_bytes": None, "flops": None}
    if with_hlo:
        out["hlo"] = None
    try:
        compiled = jitted_fn.lower(*args).compile()
    except Exception:
        return out
    if with_hlo:
        try:
            out["hlo"] = compiled.as_text()
        except Exception:
            pass
    try:
        ma = compiled.memory_analysis()
        out["hbm_bytes"] = int(
            ma.argument_size_in_bytes + ma.output_size_in_bytes
            + ma.temp_size_in_bytes - ma.alias_size_in_bytes)
    except Exception:
        pass
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, list):  # older API: one dict per device program
            cost = cost[0]
        flops = float(cost["flops"])
        out["flops"] = flops if flops > 0 else None
    except Exception:
        pass
    return out


def _host_rss_kb() -> Optional[int]:
    try:
        with open(f"/proc/{os.getpid()}/status") as f:
            for line in f:
                if line.startswith("VmRSS"):
                    return int(line.split()[1])
    except OSError:
        pass
    return None


def start_hbm_sampler(path: str, interval_s: float = 0.5,
                      ledger=None) -> Callable[[], None]:
    """Write `CSV_HEADER` rows to ``path`` every ``interval_s`` until the
    returned stop() is called. Daemon thread: it never blocks exit.

    The returned stop() is idempotent and crash-safe: the file handle is
    flushed+closed in the sampler thread's ``finally`` (so a sampler
    exception still closes it exactly once), and repeated stop() calls are
    no-ops after the first. When a run :class:`~tpu_dist.obs.ledger.Ledger`
    is passed, each sample also lands there as an ``hbm`` event, so the
    JSONL record carries the memory timeline alongside the step records.
    """
    f = open(path, "w", buffering=1)
    f.write(CSV_HEADER + "\n")
    stop = threading.Event()

    def run():
        try:
            dev = jax.local_devices()[0]
            while not stop.is_set():
                s = device_memory_stats(dev)
                rss = _host_rss_kb()
                row = (time.time(), s.get("bytes_in_use", ""),
                       s.get("peak_bytes_in_use", ""),
                       s.get("bytes_limit", ""), rss or "")
                f.write(",".join(str(x) for x in row) + "\n")
                if ledger is not None:
                    ledger.emit("hbm",
                                bytes_in_use=s.get("bytes_in_use"),
                                peak_bytes=s.get("peak_bytes_in_use"),
                                bytes_limit=s.get("bytes_limit"),
                                host_rss_kb=rss)
                stop.wait(interval_s)
        finally:
            # the ONLY close site: a second stop() or a sampler crash can
            # neither double-close nor leave the handle open
            if not f.closed:
                f.flush()
                f.close()

    t = threading.Thread(target=run, daemon=True)
    t.start()

    def stop_fn():
        if stop.is_set():  # idempotent: later calls are no-ops
            return
        stop.set()
        t.join(timeout=5)

    return stop_fn
