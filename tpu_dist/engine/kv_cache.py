"""Paged KV cache pool: one arena per layer, a free-list, block tables.

The contiguous cache ``engine.generate`` allocates is sized ``(B, prompt +
steps)`` per call — fine for one batch, fatal for a server: N concurrent
sequences of mixed length would each reserve ``max_len`` rows of HBM whether
they use 4 or 4000, and finished sequences leave holes no later request
fits. The paged pool (vLLM's PagedAttention memory model, SOSP '23) fixes
both: K/V rows live in ONE preallocated ``[num_pages, page_size, heads,
head_dim]`` arena per layer, each sequence owns an ordered block table of
page indices, and allocation/eviction are O(pages) free-list ops — HBM
utilization follows *actual* lengths, and there is no fragmentation to
compact because every page is interchangeable.

Division of labor: the device-side scatter/gather/attention programs live
in ``ops.paged_attention`` (this module only *holds* arrays and page
bookkeeping); the request scheduler that drives both lives in
``engine.serve``. Arenas ride ``ops.paged_attention.PagedLayer`` packs —
int8 mode stores pages as int8 with per-(slot, head) fp32 scales (the
``quantize_kv`` layout, PR 9), halving the HBM the decode tick is
bandwidth-bound by; ``read='flash'`` additionally routes the tick's reads
through the int8-KV Pallas kernel.

The allocator is HOST-side state (plain Python ints): page grants happen
at admission time on the scheduler thread, never inside a jitted program —
the device programs only ever see block tables as arrays.
"""

from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp

from tpu_dist.ops.paged_attention import PagedLayer, pages_for


class PagedKVPool:
    """Preallocated paged KV arenas + the free-list allocator.

    ``num_pages`` is the real capacity; arenas carry one extra *trash* page
    (index ``num_pages``) that masked writes are routed to, so the jitted
    scatter needs no branches. ``alloc`` returns page indices or ``None``
    when the pool cannot satisfy the request — admission control's signal
    to queue (never a partial grant). ``high_water_used`` tracks the peak
    concurrent page usage for the ``kv_cache`` ledger event.

    A contiguous allocator serving the same ``max_len``-capable slots would
    need ``slots * pages_for(max_len, page_size)`` pages up front; the pool
    needs only the sum of live sequences' ACTUAL pages — the fragmentation
    pin in tests/test_serve.py runs mixed-length traffic through a pool the
    contiguous layout provably cannot fit.
    """

    def __init__(self, num_layers: int, num_pages: int, page_size: int,
                 num_heads: int, head_dim: int, dtype=jnp.float32,
                 kv_quant: str = "none", read: str = "exact"):
        if kv_quant not in ("none", "int8"):
            raise ValueError(f"kv_quant must be 'none' or 'int8', "
                             f"got {kv_quant!r}")
        if read not in ("exact", "flash"):
            raise ValueError(f"read must be 'exact' or 'flash', "
                             f"got {read!r}")
        if read == "flash" and kv_quant != "int8":
            raise ValueError("read='flash' is the int8-KV kernel path; "
                             "pass kv_quant='int8' (the fp exact path "
                             "needs no kernel)")
        self.num_layers = num_layers
        self.num_pages = num_pages
        self.page_size = page_size
        self.kv_quant = kv_quant
        self.read = read
        shape = (num_pages + 1, page_size, num_heads, head_dim)
        sshape = (num_pages + 1, page_size, num_heads)
        self._layers: List[PagedLayer] = []
        for _ in range(num_layers):
            if kv_quant == "int8":
                self._layers.append(PagedLayer(
                    jnp.zeros(shape, jnp.int8), jnp.zeros(shape, jnp.int8),
                    jnp.zeros(sshape, jnp.float32),
                    jnp.zeros(sshape, jnp.float32),
                    quant="int8", read=read))
            else:
                self._layers.append(PagedLayer(
                    jnp.zeros(shape, dtype), jnp.zeros(shape, dtype),
                    quant="none", read=read))
        # lowest-index-first keeps allocation deterministic run to run
        self._free: List[int] = list(range(num_pages))
        self.high_water_used = 0

    # -- allocator --------------------------------------------------------
    @property
    def pages_free(self) -> int:
        return len(self._free)

    @property
    def pages_used(self) -> int:
        return self.num_pages - len(self._free)

    def pages_needed(self, total_tokens: int) -> int:
        return pages_for(total_tokens, self.page_size)

    def alloc(self, n: int) -> Optional[List[int]]:
        """Grant ``n`` pages (all-or-nothing; None when short)."""
        if n > len(self._free):
            return None
        grant, self._free = self._free[:n], self._free[n:]
        self.high_water_used = max(self.high_water_used, self.pages_used)
        return grant

    def free(self, pages: List[int]) -> None:
        self._free.extend(pages)
        self._free.sort()

    def contiguous_pages_needed(self, slots: int, max_total: int) -> int:
        """What a contiguous per-slot allocator would preallocate for the
        same capacity — the fragmentation comparison baseline."""
        return slots * self.pages_needed(max_total)

    # -- arena plumbing ---------------------------------------------------
    def layers(self) -> tuple:
        """The per-layer ``PagedLayer`` packs, as jit arguments."""
        return tuple(self._layers)

    def adopt(self, new_layers) -> None:
        """Store the functionally-updated arenas a jitted program returned
        (the scheduler calls this after every prefill/tick)."""
        self._layers = list(new_layers)

    def stats(self) -> dict:
        return {"pages_free": self.pages_free,
                "pages_used": self.pages_used,
                "pages_total": self.num_pages,
                "page_size": self.page_size,
                "high_water_used": self.high_water_used,
                "kv_quant": self.kv_quant}
