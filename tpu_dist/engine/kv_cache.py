"""Paged KV cache pool: one arena per layer, a free-list, block tables.

The contiguous cache ``engine.generate`` allocates is sized ``(B, prompt +
steps)`` per call — fine for one batch, fatal for a server: N concurrent
sequences of mixed length would each reserve ``max_len`` rows of HBM whether
they use 4 or 4000, and finished sequences leave holes no later request
fits. The paged pool (vLLM's PagedAttention memory model, SOSP '23) fixes
both: K/V rows live in ONE preallocated ``[num_pages, page_size, heads,
head_dim]`` arena per layer, each sequence owns an ordered block table of
page indices, and allocation/eviction are O(pages) free-list ops — HBM
utilization follows *actual* lengths, and there is no fragmentation to
compact because every page is interchangeable.

Round 16 makes pages SHARED, not just interchangeable: every page carries a
refcount, and a prefix index keyed by the token-hash of whole pages maps a
new request's prompt prefix onto the physical pages an identical earlier
prefix already filled (system prompts and few-shot headers — the dominant
bytes in real multi-tenant traffic). A prefix hit costs ~0 fresh pages; a
page whose refcount drops to zero but that is still indexed parks in a
CACHED set (content preserved, reclaimed FIFO only under pool pressure), so
hits survive across non-overlapping requests and effective HBM capacity
multiplies with traffic similarity. Divergence is copy-on-write: the one
page a new request can ever write while shared — the frontier page holding
the tail of its prompt — is forked (``ops.paged_attention.cow_fork_pages``)
onto a destination page reserved at admission, at the moment of the first
divergent write.

Division of labor: the device-side scatter/gather/attention programs live
in ``ops.paged_attention`` (this module only *holds* arrays and page
bookkeeping); the request scheduler that drives both lives in
``engine.serve``. Arenas ride ``ops.paged_attention.PagedLayer`` packs —
int8 mode stores pages as int8 with per-(slot, head) fp32 scales (the
``quantize_kv`` layout, PR 9), halving the HBM the decode tick is
bandwidth-bound by; ``read='flash'`` additionally routes the tick's reads
through the int8-KV Pallas kernel.

The allocator is HOST-side state (plain Python ints): page grants happen
at admission time on the scheduler thread, never inside a jitted program —
the device programs only ever see block tables as arrays.
"""

from __future__ import annotations

import hashlib
import heapq
from collections import Counter, OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from tpu_dist.ops.paged_attention import PagedLayer, pages_for
from tpu_dist.parallel.mesh import SP_AXIS


def _prefix_key(tokens) -> str:
    """Content address of a token prefix: sha1 over the raw int32 bytes.
    Deterministic across runs/processes (unlike ``hash()``), collision-
    negligible, and O(len) — the whole-page token-hash the prefix index
    is keyed by."""
    return hashlib.sha1(
        np.ascontiguousarray(np.asarray(tokens, np.int32)).tobytes()
    ).hexdigest()


class PrefixMatch:
    """One admission's prefix-index result (``PagedKVPool.share_prefix``).

    ``pages`` are the shared physical pages, refcounts already bumped, in
    block-table order; ``full`` of them are whole-page hits (positions
    ``0..full*page_size`` never rewritten, never forked), and when
    ``partial`` is set the LAST entry is a frontier page matched through
    ``cov - full*page_size`` leading rows only — the one page the new
    request will write into, so it must fork on first write. ``cov`` is
    the total number of prompt positions whose K/V rows are already
    resident."""

    __slots__ = ("pages", "full", "partial", "cov")

    def __init__(self, pages: List[int], full: int, partial: bool,
                 cov: int):
        self.pages = pages
        self.full = full
        self.partial = partial
        self.cov = cov


class PagedKVPool:
    """Preallocated paged KV arenas + the refcounting free-list allocator.

    ``num_pages`` is the real capacity; arenas carry one extra *trash* page
    (index ``num_pages``) that masked writes are routed to, so the jitted
    scatter needs no branches. ``alloc`` returns page indices or ``None``
    when the pool cannot satisfy the request — admission control's signal
    to queue (never a partial grant). ``high_water_used`` tracks the peak
    concurrent page usage for the ``kv_cache`` ledger event.

    Allocation states per page: FREE (refcount 0, on the min-heap, grants
    come lowest-index-first for run-to-run determinism), LIVE (refcount
    >= 1 — shared when >= 2), or CACHED (refcount 0 but still in the
    prefix index: content preserved for future hits, reclaimed FIFO when
    the heap runs dry). ``pages_free`` counts FREE + CACHED — both are
    allocatable, so admission watermarks see true headroom.

    A contiguous allocator serving the same ``max_len``-capable slots would
    need ``slots * pages_for(max_len, page_size)`` pages up front; the pool
    needs only the sum of live sequences' ACTUAL pages — the fragmentation
    pin in tests/test_serve.py runs mixed-length traffic through a pool the
    contiguous layout provably cannot fit.
    """

    def __init__(self, num_layers: int, num_pages: int, page_size: int,
                 num_heads: int, head_dim: int, dtype=jnp.float32,
                 kv_quant: str = "none", read: str = "exact", mesh=None):
        if kv_quant not in ("none", "int8"):
            raise ValueError(f"kv_quant must be 'none' or 'int8', "
                             f"got {kv_quant!r}")
        if read not in ("exact", "flash"):
            raise ValueError(f"read must be 'exact' or 'flash', "
                             f"got {read!r}")
        if read == "flash" and kv_quant != "int8":
            raise ValueError("read='flash' is the int8-KV kernel path; "
                             "pass kv_quant='int8' (the fp exact path "
                             "needs no kernel)")
        # sp sharding (long-context serving): the arenas' page dimension is
        # laid out as `n` per-DEVICE blocks of `pages/n + 1` rows — every
        # device carries its own pages plus its own LOCAL trash row, so the
        # branch-free masked scatter survives sharding with zero cross-
        # device traffic. Logical page ids stay 0..num_pages-1 host-side;
        # device programs see FLAT rows via flat_block_table(). A 1-device
        # (or absent) mesh degenerates to the classic num_pages+1 layout
        # and an identity translation.
        self.sp_mesh = mesh
        n = 1
        if mesh is not None:
            if SP_AXIS not in mesh.shape:
                raise ValueError(
                    f"sharded pool needs a mesh with the {SP_AXIS!r} axis "
                    f"(got axes {tuple(mesh.axis_names)})")
            n = mesh.shape[SP_AXIS]
            if num_pages % n:
                raise ValueError(
                    f"num_pages {num_pages} must divide by the {SP_AXIS!r} "
                    f"axis size {n} (whole pages per device)")
        self.sharded_devices = n
        self.num_layers = num_layers
        self.num_pages = num_pages
        self.page_size = page_size
        self.kv_quant = kv_quant
        self.read = read
        self.pages_per_device = num_pages // n
        self._rows_local = self.pages_per_device + 1   # + local trash row
        rows = n * self._rows_local
        shape = (rows, page_size, num_heads, head_dim)
        sshape = (rows, page_size, num_heads)

        def zeros(shp, dt):
            z = jnp.zeros(shp, dt)
            if mesh is not None:
                z = jax.device_put(z, NamedSharding(mesh, P(SP_AXIS)))
            return z

        self._layers: List[PagedLayer] = []
        for _ in range(num_layers):
            if kv_quant == "int8":
                self._layers.append(PagedLayer(
                    zeros(shape, jnp.int8), zeros(shape, jnp.int8),
                    zeros(sshape, jnp.float32), zeros(sshape, jnp.float32),
                    quant="int8", read=read))
            else:
                self._layers.append(PagedLayer(
                    zeros(shape, dtype), zeros(shape, dtype),
                    quant="none", read=read))
        # per-device min-heaps of free page indices: O(log n) per
        # free/grant (round-18 discipline), grants lowest GLOBAL index
        # first across the heaps — for an unsharded pool this is ONE heap
        # and exactly the round-11 grant order (determinism pin in
        # test_serve). The per-device split exists for the sp prefill's
        # striped prompt allocation (alloc_for_slots), where each device
        # scatters its own shard's K/V into pages it physically holds.
        self._free_by_dev: List[List[int]] = [
            list(range(d * self.pages_per_device,
                       (d + 1) * self.pages_per_device))
            for d in range(n)]
        for h in self._free_by_dev:
            heapq.heapify(h)
        self._ref: List[int] = [0] * num_pages
        # rc==0 pages still carrying indexed prefix content, FIFO by
        # release order (deterministic reclaim under pressure)
        self._cached: "OrderedDict[int, None]" = OrderedDict()
        # prefix index: full-prefix sha1 -> page holding its last whole
        # page of K/V rows, plus parent-hash -> [(page_tokens, page)] for
        # frontier (partial-page) matches; _reg maps page -> its keys so
        # reclaim can unregister in O(children)
        self._full_index: Dict[str, int] = {}
        self._children: Dict[str, List[Tuple[Tuple[int, ...], int]]] = {}
        self._reg: Dict[int, Tuple[Optional[str], str,
                                   Tuple[int, ...]]] = {}
        self.high_water_used = 0
        # cumulative counters (the kv_cache ledger event + bench headline)
        self.prefix_hits = 0        # pages served from the index
        self.prefix_lookups = 0     # share_prefix calls
        self.cow_copies = 0         # frontier forks performed
        self.alloc_total = 0        # fresh pages granted (pages/request)
        # request tracing (obs.reqtrace): bound by the serving engine so
        # prefix hits and CoW forks surface as per-request detail spans;
        # a standalone pool stays silent
        self._tracer = None
        self._now = None

    def bind_trace(self, tracer, now_fn) -> None:
        """Attach the engine's trace context: ``tracer`` derives span ids
        (None disables), ``now_fn`` is the ENGINE clock — span timestamps
        must live on the same axis as the scheduler's queue/prefill
        spans, not this module's idea of time."""
        self._tracer = tracer
        self._now = now_fn

    # -- allocator --------------------------------------------------------
    @property
    def pages_free(self) -> int:
        """Allocatable pages: truly free + cached (reclaimable) ones."""
        return (sum(len(h) for h in self._free_by_dev)
                + len(self._cached))

    @property
    def pages_used(self) -> int:
        return self.num_pages - self.pages_free

    @property
    def shared_pages(self) -> int:
        """Pages currently referenced by 2+ sequences."""
        return sum(1 for r in self._ref if r >= 2)

    def pages_needed(self, total_tokens: int) -> int:
        return pages_for(total_tokens, self.page_size)

    def page_device(self, page: int) -> int:
        """The device block a logical page physically lives in (always 0
        for an unsharded pool)."""
        return page // self.pages_per_device

    def _pop_free(self) -> Optional[int]:
        """Pop the lowest GLOBAL free index across the per-device heaps
        (O(devices) peek — devices is single digits)."""
        best = None
        for h in self._free_by_dev:
            if h and (best is None or h[0] < best[0]):
                best = h
        return heapq.heappop(best) if best is not None else None

    def alloc(self, n: int) -> Optional[List[int]]:
        """Grant ``n`` fresh pages at refcount 1 (all-or-nothing; None
        when short). Free pages go first, lowest index first; cached
        prefix pages are reclaimed FIFO (and unregistered) only when the
        free heaps run dry — pool pressure evicts the cache, never the
        other way around."""
        if n > self.pages_free:
            return None
        grant: List[int] = []
        while len(grant) < n:
            page = self._pop_free()
            if page is None:
                page, _ = self._cached.popitem(last=False)
                self._unregister(page)
            grant.append(page)
        for p in grant:
            self._ref[p] = 1
        self.alloc_total += n
        self.high_water_used = max(self.high_water_used, self.pages_used)
        return grant

    def alloc_for_slots(self, devs: Sequence[int]) -> Optional[List[int]]:
        """Grant one page per requested DEVICE, in slot order (all-or-
        nothing; None when any device is short). The sp prefill's striped
        prompt allocation: block-table slot ``t`` of a sequence prefilled
        over ``n`` sequence shards must live on the device whose shard
        writes its rows (``(t * page_size) // shard_len``) — reads never
        care (the gather psum is location-free), so only the prompt slots
        an sp prefill will scatter into come through here. Per-device
        grants are lowest-index-first; cached pages on the right device
        reclaim FIFO, same policy as :meth:`alloc`."""
        need = Counter(devs)
        for d, c in need.items():
            avail = len(self._free_by_dev[d]) + sum(
                1 for p in self._cached if self.page_device(p) == d)
            if avail < c:
                return None
        grant: List[int] = []
        for d in devs:
            if self._free_by_dev[d]:
                p = heapq.heappop(self._free_by_dev[d])
            else:
                p = next(q for q in self._cached
                         if self.page_device(q) == d)
                del self._cached[p]
                self._unregister(p)
            self._ref[p] = 1
            grant.append(p)
        self.alloc_total += len(grant)
        self.high_water_used = max(self.high_water_used, self.pages_used)
        return grant

    def flat_block_table(self, bt: np.ndarray) -> np.ndarray:
        """Logical page ids -> FLAT arena rows (the device programs' view):
        page ``p`` sits at ``p + p // pages_per_device`` (its device block
        offset by one trash row per preceding device), and the unassigned
        sentinel (``num_pages``) maps to the LAST arena row — a trash row,
        so masked writes and padded gathers keep landing on garbage that
        no live sequence owns. Identity for an unsharded pool."""
        bt = np.asarray(bt)
        return np.where(
            bt >= self.num_pages,
            self.sharded_devices * self._rows_local - 1,
            bt + bt // self.pages_per_device).astype(np.int32)

    def free(self, pages: List[int]) -> None:
        """Drop one reference per listed page. A page parks in the cached
        set when it still carries indexed prefix content, else returns to
        the free heap. Double-frees raise — a leaked or double-counted
        page corrupts another sequence's cache silently otherwise."""
        for p in pages:
            if self._ref[p] <= 0:
                raise ValueError(f"double-free of page {p} (refcount 0)")
            self._ref[p] -= 1
            if self._ref[p] == 0:
                if p in self._reg:
                    self._cached[p] = None
                else:
                    heapq.heappush(self._free_by_dev[self.page_device(p)],
                                   p)

    def contiguous_pages_needed(self, slots: int, max_total: int) -> int:
        """What a contiguous per-slot allocator would preallocate for the
        same capacity — the fragmentation comparison baseline."""
        return slots * self.pages_needed(max_total)

    # -- prefix index -----------------------------------------------------
    def share_prefix(self, prompt: np.ndarray,
                     rid: Optional[int] = None) -> PrefixMatch:
        """Map the longest resident prefix of ``prompt`` onto shared
        pages: whole-page hits first (index walk by cumulative prefix
        hash), then one frontier page whose leading rows match the
        remaining tail. Bumps refcounts (un-parking cached pages) and
        returns a :class:`PrefixMatch`; ``unshare`` undoes it when the
        admission cannot complete. ``rid`` attributes a hit to a request
        trace (a ``prefix_hit`` detail span) when tracing is bound."""
        t0 = self._now() if self._now is not None else 0.0
        self.prefix_lookups += 1
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        ps = self.page_size
        pages: List[int] = []
        full = 0
        parent = _prefix_key(prompt[:0])
        while (full + 1) * ps <= prompt.size:
            key = _prefix_key(prompt[:(full + 1) * ps])
            page = self._full_index.get(key)
            if page is None:
                break
            self._retain(page)
            pages.append(page)
            full += 1
            parent = key
        cov = full * ps
        partial = False
        tail = tuple(int(t) for t in prompt[cov:])
        if tail:
            for content, page in self._children.get(parent, ()):
                if len(content) >= len(tail) \
                        and content[:len(tail)] == tail:
                    self._retain(page)
                    pages.append(page)
                    partial = True
                    cov += len(tail)
                    break
        self.prefix_hits += len(pages)
        if pages:
            self.high_water_used = max(self.high_water_used,
                                       self.pages_used)
        if pages and self._tracer is not None and rid is not None:
            # a HIT is trace-worthy (it explains a cheap prefill); misses
            # are the default and would only pad the ledger
            tr = self._tracer
            tid, sid, par = tr.ids(rid, "prefix_hit")
            tr.ledger.emit("span", trace_id=tid, span_id=sid,
                           parent_id=par, name="prefix_hit", rid=rid,
                           start=round(t0, 6), end=round(self._now(), 6),
                           pages=len(pages), full=full, partial=partial,
                           cov=cov, **tr.attrs())
        return PrefixMatch(pages, full, partial, cov)

    def unshare(self, match: PrefixMatch) -> None:
        """Roll back ``share_prefix`` (admission failed downstream)."""
        self.free(match.pages)
        self.prefix_hits -= len(match.pages)

    def _retain(self, page: int) -> None:
        if self._ref[page] == 0:
            self._cached.pop(page, None)
        self._ref[page] += 1

    def register_prefix(self, prompt: np.ndarray, pages: List[int],
                        skip_slots: int = 0) -> None:
        """Index a freshly-prefilled prompt's pages for future sharing:
        whole prompt pages under their cumulative prefix hash, every page
        (including the final partial one) as a child of its parent hash
        with its prompt-resident token content — the frontier-match side.
        ``skip_slots`` leading block-table slots came from ``share_prefix``
        and are already indexed (registering them again would double-map
        one hash to two pages)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        ps = self.page_size
        n_slots = pages_for(prompt.size, ps)
        for i in range(skip_slots, n_slots):
            page = pages[i]
            content = tuple(int(t) for t in prompt[i * ps:(i + 1) * ps])
            parent = _prefix_key(prompt[:i * ps])
            is_full = len(content) == ps
            full_key = _prefix_key(prompt[:(i + 1) * ps]) if is_full \
                else None
            if full_key is not None and full_key in self._full_index:
                continue         # identical prefix already indexed
            siblings = self._children.setdefault(parent, [])
            if any(c == content for c, _ in siblings):
                continue
            if page in self._reg:
                continue         # one page, one identity
            siblings.append((content, page))
            if full_key is not None:
                self._full_index[full_key] = page
            self._reg[page] = (full_key, parent, content)

    def _unregister(self, page: int) -> None:
        full_key, parent, content = self._reg.pop(page)
        if full_key is not None:
            self._full_index.pop(full_key, None)
        kids = self._children.get(parent)
        if kids:
            kids[:] = [(c, p) for c, p in kids if p != page]
            if not kids:
                del self._children[parent]

    def fork_page(self, src: int, dst: int,
                  rid: Optional[int] = None) -> None:
        """Copy-on-write fork: duplicate ``src``'s rows onto the already-
        granted ``dst`` in every layer's arenas and drop one reference
        from ``src`` (the forking sequence's). The caller swaps its block
        table entry; other holders keep reading ``src``. ``rid``
        attributes the fork cost to a request trace (a ``cow_fork``
        detail span) when tracing is bound."""
        from tpu_dist.ops.paged_attention import cow_fork_pages

        t0 = self._now() if self._now is not None else 0.0
        # arenas index by FLAT rows (sharded pools interleave trash rows);
        # identity when unsharded
        flat = self.flat_block_table(np.asarray([src, dst], np.int32))
        src_a = jnp.asarray(flat[:1])
        dst_a = jnp.asarray(flat[1:])
        self._layers = list(cow_fork_pages(tuple(self._layers),
                                           src_a, dst_a))
        self.free([src])
        self.cow_copies += 1
        if self._tracer is not None and rid is not None:
            tr = self._tracer
            tid, sid, par = tr.ids(rid, "cow_fork")
            tr.ledger.emit("span", trace_id=tid, span_id=sid,
                           parent_id=par, name="cow_fork", rid=rid,
                           start=round(t0, 6), end=round(self._now(), 6),
                           src=src, dst=dst, **tr.attrs())

    # -- arena plumbing ---------------------------------------------------
    def layers(self) -> tuple:
        """The per-layer ``PagedLayer`` packs, as jit arguments."""
        return tuple(self._layers)

    def adopt(self, new_layers) -> None:
        """Store the functionally-updated arenas a jitted program returned
        (the scheduler calls this after every prefill/tick)."""
        self._layers = list(new_layers)

    def stats(self) -> dict:
        return {"pages_free": self.pages_free,
                "pages_used": self.pages_used,
                "pages_total": self.num_pages,
                "pages_cached": len(self._cached),
                "page_size": self.page_size,
                "sharded_devices": self.sharded_devices,
                "pages_per_device": self.pages_per_device,
                "high_water_used": self.high_water_used,
                "shared_pages": self.shared_pages,
                "prefix_hits": self.prefix_hits,
                "prefix_lookups": self.prefix_lookups,
                "cow_copies": self.cow_copies,
                "alloc_total": self.alloc_total,
                "kv_quant": self.kv_quant}
