"""Language-model train/eval steps: DP, DP x TP, and DP x SP (ring attention).

Extends the image engine (tpu_dist.engine.steps) to token sequences — the
long-context, model-parallel half of the framework the reference never had.

Since round 15 this module holds the LM engine's step TEMPLATES — the ONE
shared objective (:func:`_lm_grads_and_metrics`) wrapped as the gspmd
template (:func:`_lm_step_fn`) and its explicit/ring/sp per-device flavors
(:func:`_lm_explicit_dp_step_fn` / :func:`_lm_tp_ring_step_fn` /
:func:`_lm_sp_step_fn`) — plus the eval kernel. Every public ``make_lm_*``
builder below is a THIN SHIM over the plan compiler
(``tpu_dist.plan.compile``): it names its variant as a declarative
:class:`tpu_dist.plan.ir.Plan` and the compiler's validate/template/
window/partition passes produce the callable (the jit/shard_map/scan
wrapper bodies live once, in the compiler). Signatures and math are
unchanged; loss/param parity with the pre-plan builders is pinned
bit-for-bit in tests/test_plan.py.

Builder map (mode selection is by mesh axes, exactly like scripts/8):

* :func:`make_lm_train_step` — jit over a (data[, model]) mesh. Batch sharded
  on 'data'; with TP param shardings (tpu_dist.parallel.tp) GSPMD emits the
  Megatron collectives. Works for pure DP (no 'model' axis) unchanged.
* :func:`make_lm_sp_train_step` — shard_map over (data, seq): each device
  holds a sequence shard, attention runs as a ring over 'seq'
  (tpu_dist.parallel.ring_attention), grads/metrics psum over both axes.
  This is the blockwise/ring long-context regime: per-device activation
  memory scales with L/n_seq.

Loss: next-token cross entropy. Shift-by-one happens ON THE HOST over the
global (B, L+1) token rows BEFORE any sharding (:func:`make_lm_batches`):
inputs = rows[:, :-1], targets = rows[:, 1:]. A sequence shard's targets
therefore already contain the first token of the following shard, so
interior shard boundaries need no masking and the SP step's per-shard loss
sums are exact — only the final position of the global sequence is consumed
by the shift itself.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from tpu_dist.engine.state import TrainState
from tpu_dist.engine.steps import _apply_update
from tpu_dist.ops.fused_xent import chunked_softmax_xent
from tpu_dist.parallel.mesh import DATA_AXIS, MODEL_AXIS, SEQ_AXIS
from tpu_dist.plan.ir import Plan


LM_METRIC_KEYS = ("loss_sum", "correct1", "count")


def zeros_lm_metrics():
    """Additive identity for lm_loss_and_metrics sums — THE definition of
    the metric-key set (every eval/accumulator path builds from it, so a
    new metric key cannot silently desynchronize a tree.map)."""
    return {k: jnp.float32(0.0) for k in LM_METRIC_KEYS}


def lm_loss_and_metrics(logits, targets, mask):
    """Per-token CE sums. logits (B,L,V) fp32; targets (B,L); mask (B,L).

    nll = logsumexp - target_logit, NOT -log_softmax[target]: the
    log_softmax form materializes a second (B,L,V) fp32 tensor just to
    gather one column of it — the round-5 LM profile attributed ~4.8
    ms/step of pure HBM `sub` traffic to exactly that at the bench
    geometry. logsumexp reduces on the fly; same max-shifted math, same
    softmax-minus-onehot backward."""
    logits32 = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(logits32, axis=-1)
    tgt = jnp.take_along_axis(logits32, targets[..., None], axis=-1)[..., 0]
    nll = lse - tgt
    loss_sum = jnp.sum(nll * mask)
    correct = (jnp.argmax(logits, axis=-1) == targets).astype(jnp.float32)
    return loss_sum, {
        "loss_sum": loss_sum,
        "correct1": jnp.sum(correct * mask),
        "count": jnp.sum(mask),
    }


def _apply_collect_aux(model, params, inputs, dropout_rng, pos_offset=0,
                       return_features=False):
    """Forward pass that also collects sown MoE intermediates.

    Returns (logits, aux, mass_sum, mass_n): only leaves sown under
    ``aux_loss`` enter the objective; ``combine_mass`` leaves (per-token
    combine weight — <1 when capacity dropped a token) are summed separately
    as a DIAGNOSTIC so training can report the dropped-token fraction
    without it ever leaking into the loss. Dense models return zeros.
    ``return_features=True`` yields post-ln_f features instead of logits
    (the chunked-loss path applies the head itself — ops.fused_xent).
    """
    logits, muts = model.apply(
        {"params": params}, inputs, train=True, rngs={"dropout": dropout_rng},
        pos_offset=pos_offset, return_features=return_features,
        mutable=["intermediates"])
    aux = jnp.float32(0.0)
    mass_sum = jnp.float32(0.0)
    mass_n = jnp.float32(0.0)
    for path, leaf in jax.tree_util.tree_flatten_with_path(
            muts.get("intermediates", {}))[0]:
        if any(getattr(k, "key", None) == "aux_loss" for k in path):
            aux = aux + jnp.sum(leaf)
        elif any(getattr(k, "key", None) == "combine_mass" for k in path):
            mass_sum = mass_sum + jnp.sum(leaf.astype(jnp.float32))
            mass_n = mass_n + jnp.float32(leaf.size)
    return logits, aux, mass_sum, mass_n


def make_lm_batches(tokens: np.ndarray):
    """Host-side: (B, L+1) token rows -> (inputs (B,L), targets (B,L)).

    Shifting happens BEFORE any sharding so sequence shards stay consistent:
    each shard's targets include the first token of the next shard.
    """
    return tokens[:, :-1], tokens[:, 1:]


def _chunked_loss_metrics(model, params, feats, targets, mask,
                          loss_chunk: int):
    """loss_sum + metric sums via the chunked head (ops.fused_xent): the
    (B, L, V) logits never materialize; the head kernel comes straight from
    the param tree so its gradient flows through the chunked vjp."""
    loss_sum, correct = chunked_softmax_xent(
        feats, params["lm_head"]["kernel"], targets, mask,
        loss_chunk, model.dtype)
    return loss_sum, {"loss_sum": loss_sum, "correct1": correct,
                      "count": jnp.sum(mask)}


def _lm_objective_metrics(model, params, out, targets, loss_chunk: int):
    """THE chunked-vs-full loss dispatch for the train steps: ``out`` is
    logits (loss_chunk == 0) or post-ln_f features (loss_chunk > 0, from
    _apply_collect_aux(return_features=True)). One definition shared by the
    jit and sp step fns so the two objectives cannot drift — the eval twin
    is _lm_eval_metrics."""
    mask = jnp.ones(targets.shape, jnp.float32)
    if loss_chunk:
        return _chunked_loss_metrics(model, params, out, targets, mask,
                                     loss_chunk)
    return lm_loss_and_metrics(out, targets, mask)


def _lm_grads_and_metrics(model, aux_weight: float, params, inputs, targets,
                          dropout_rng, loss_chunk: int = 0):
    """(grads, metrics): value_and_grad of THE LM objective (CE mean +
    aux_weight x sown aux losses, router-mass diagnostics attached) —
    shared by the single-step, windowed, AND grad-accum wrappers so the
    objective cannot drift between them. ``loss_chunk`` > 0 switches the
    head+CE to the chunked recompute path (ops.fused_xent) — identical math,
    O(chunk * V) instead of O(B * L * V) logits memory."""

    def loss_fn(p):
        out, aux, mass_sum, mass_n = _apply_collect_aux(
            model, p, inputs, dropout_rng,
            return_features=bool(loss_chunk))
        loss_sum, metrics = _lm_objective_metrics(
            model, p, out, targets, loss_chunk)
        metrics = {**metrics,
                   "router_mass_sum": jax.lax.stop_gradient(mass_sum),
                   "router_mass_n": mass_n}
        mean = loss_sum / jnp.maximum(metrics["count"], 1.0)
        return mean + aux_weight * aux, metrics

    (_, metrics), grads = jax.value_and_grad(
        loss_fn, has_aux=True)(params)
    return grads, metrics


def _lm_step_fn(model, tx, aux_weight: float, loss_chunk: int = 0,
                health: str = "record") -> Callable:
    """THE pure LM train step shared by every jit wrapper (single-batch and
    indexed-window) — the lm twin of steps.py _train_step_fn, so the
    windowed path's 'identical math to K sequential steps' contract is
    enforced structurally, not by parallel copies."""

    def step(state: TrainState, inputs, targets, rng):
        dropout_rng = jax.random.fold_in(rng, state.step)
        grads, metrics = _lm_grads_and_metrics(
            model, aux_weight, state.params, inputs, targets, dropout_rng,
            loss_chunk)
        return _apply_update(tx, state, grads, {}, metrics, health)

    return step


# ---- explicit-collective per-device step templates (parallel.overlap) ------

def _lm_explicit_dp_step_fn(model, tx, aux_weight: float, data_axis: str,
                            axis_size: int, grad_bucket_mb: float,
                            loss_chunk: int = 0,
                            health: str = "record") -> Callable:
    """Per-device dp step with EXPLICIT gradient sync: local-batch grads,
    then either one monolithic per-leaf pmean (bucket_mb <= 0) or DDP-style
    bucketed reduce-scatter+all-gather collectives
    (parallel.overlap.bucketed_grad_sync). Same math as the jit/GSPMD dp
    step — the local mean pmean'd equals the global-batch mean."""
    from tpu_dist.parallel.overlap import bucketed_grad_sync

    def step(state: TrainState, inputs, targets, rng):
        dropout_rng = jax.random.fold_in(rng, state.step)
        grads, metrics = _lm_grads_and_metrics(
            model, aux_weight, state.params, inputs, targets, dropout_rng,
            loss_chunk)
        if grad_bucket_mb > 0:
            grads = bucketed_grad_sync(grads, data_axis, grad_bucket_mb,
                                       mean=True, axis_size=axis_size)
        else:
            grads = jax.tree.map(lambda g: jax.lax.pmean(g, data_axis), grads)
        metrics = jax.tree.map(lambda m: jax.lax.psum(m, data_axis), metrics)
        return _apply_update(tx, state, grads, {}, metrics, health)

    return step


def _lm_tp_ring_step_fn(model, tx, aux_weight: float, data_axis: str,
                        model_axis: str, n_model: int,
                        loss_chunk: int = 0,
                        health: str = "record") -> Callable:
    """Per-device dp x ring-TP step: ``model`` must be built with
    tp_impl='ring' (parallel.overlap), so its projections run the
    AG-matmul / matmul-RS collective matmuls over ``model_axis`` and its
    outputs are this device's (B, L/n_model, ...) sequence chunk — the
    targets are sliced to match. Params stay replicated (ring trades
    GSPMD-TP's param sharding for explicit overlap); like the sp step,
    equal static shard sizes make the pmean of local-mean grads the global
    mean, with ``model_axis`` joining the reduction because every device
    holds the full param copy."""

    def step(state: TrainState, inputs, targets, rng):
        m_idx = jax.lax.axis_index(model_axis)
        shard_len = targets.shape[1] // n_model
        tgt = jax.lax.dynamic_slice_in_dim(targets, m_idx * shard_len,
                                           shard_len, axis=1)
        dropout_rng = jax.random.fold_in(rng, state.step)

        def loss_fn(p):
            out, aux, mass_sum, mass_n = _apply_collect_aux(
                model, p, inputs, dropout_rng,
                return_features=bool(loss_chunk))
            loss_sum, metrics = _lm_objective_metrics(
                model, p, out, tgt, loss_chunk)
            metrics = {**metrics,
                       "router_mass_sum": jax.lax.stop_gradient(mass_sum),
                       "router_mass_n": mass_n}
            # LOCAL mean over this device's (batch shard x seq chunk);
            # collectives stay OUT of the differentiated function (the
            # _lm_sp_step_fn contract — mean-of-local-means == global mean)
            mean = loss_sum / jnp.maximum(metrics["count"], 1.0)
            return mean + aux_weight * aux, metrics

        (_, metrics), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        grads = jax.tree.map(
            lambda g: jax.lax.pmean(jax.lax.pmean(g, model_axis), data_axis),
            grads)
        metrics = jax.tree.map(
            lambda m: jax.lax.psum(jax.lax.psum(m, model_axis), data_axis),
            metrics)
        return _apply_update(tx, state, grads, {}, metrics, health)

    return step


def _lm_eval_metrics(model, params, inputs, targets, mask,
                     loss_chunk: int = 0, pos_offset=0):
    """Forward-only metric sums, chunked-head when loss_chunk > 0 — the
    shared eval kernel so every eval wrapper (jit/indexed/sp) dispatches the
    loss path the same way the train steps do."""
    if loss_chunk:
        feats = model.apply({"params": params}, inputs, train=False,
                            pos_offset=pos_offset, return_features=True)
        _, metrics = _chunked_loss_metrics(model, params, feats, targets,
                                           mask, loss_chunk)
        return metrics
    logits = model.apply({"params": params}, inputs, train=False,
                         pos_offset=pos_offset)
    _, metrics = lm_loss_and_metrics(logits, targets, mask)
    return metrics


def _lm_sp_step_fn(model, tx, aux_weight: float, data_axis: str,
                   seq_axis: str, loss_chunk: int = 0,
                   health: str = "record") -> Callable:
    """THE per-device sp train step shared by the single-batch and
    indexed-window wrappers (the sp twin of _lm_step_fn): runs INSIDE
    shard_map on a (data, seq) mesh with (B/data, L/seq) token shards.
    ``loss_chunk`` chunks each device's LOCAL head+CE (the head kernel is
    replicated under sp, so the chunked vjp needs no collectives; grads
    pmean over both axes exactly as before)."""

    def step(state: TrainState, inputs, targets, rng):
        seq_idx = jax.lax.axis_index(seq_axis)
        dp_idx = jax.lax.axis_index(data_axis)
        dropout_rng = jax.random.fold_in(
            jax.random.fold_in(jax.random.fold_in(rng, state.step), seq_idx),
            dp_idx)
        shard_len = inputs.shape[1]
        pos_offset = seq_idx * shard_len

        def loss_fn(p):
            out, aux, mass_sum, mass_n = _apply_collect_aux(
                model, p, inputs, dropout_rng, pos_offset=pos_offset,
                return_features=bool(loss_chunk))
            loss_sum, metrics = _lm_objective_metrics(
                model, p, out, targets, loss_chunk)
            # router-mass diagnostic rides the metric sums (psum'd below)
            # so sp-MoE runs report a real RMass, like the jit modes
            metrics = {**metrics,
                       "router_mass_sum": jax.lax.stop_gradient(mass_sum),
                       "router_mass_n": mass_n}
            # LOCAL mean; collectives stay OUT of the differentiated function
            # (psum's transpose under shard_map would rescale the cotangent).
            # Equal static shard sizes make mean-of-local-means == global mean.
            mean = loss_sum / jnp.maximum(metrics["count"], 1.0)
            return mean + aux_weight * aux, ({}, metrics)

        (_, (stats, metrics)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        grads = jax.tree.map(
            lambda g: jax.lax.pmean(jax.lax.pmean(g, seq_axis), data_axis), grads)
        metrics = jax.tree.map(
            lambda m: jax.lax.psum(jax.lax.psum(m, seq_axis), data_axis), metrics)
        return _apply_update(tx, state, grads, stats, metrics, health)

    return step


def _sp_window_slices(rows, seq_idx, shard_len):
    """Device-side shift+shard: from replicated (B_local, L+1) token rows,
    this seq shard's (inputs, targets) — the same slices the host-side
    make_lm_batches + (data, seq) sharding would deliver (a shard's targets
    include the first token of the next shard, so no boundary masking)."""
    start = seq_idx * shard_len
    inputs = jax.lax.dynamic_slice_in_dim(rows, start, shard_len, axis=1)
    targets = jax.lax.dynamic_slice_in_dim(rows, start + 1, shard_len, axis=1)
    return inputs, targets


# ---- the make_lm_* builders: thin shims over the plan compiler -------------
# (plain `return f(...)` chains on purpose: distlint's jit-factory
# fixpoint follows them, so the engines' loops still derive as hot)

def _train(plan: Plan, **binds):
    from tpu_dist.plan.compile import Bindings, compile_train_step
    return compile_train_step(plan, Bindings(**binds))


def _eval(plan: Plan, **binds):
    from tpu_dist.plan.compile import Bindings, compile_eval_step
    return compile_eval_step(plan, Bindings(**binds))


def make_lm_train_step(model, tx, mesh: Mesh, data_axis: str = DATA_AXIS,
                       aux_weight: float = 0.01,
                       donate: bool = True, loss_chunk: int = 0,
                       health: str = "record") -> Callable:
    """jit step for DP — and for DP x TP / FSDP / EP when the TrainState was
    placed with the matching sharding helper (GSPMD propagates the param
    layout and emits the collectives; the step code is identical).
    ``aux_weight`` scales any sown MoE load-balancing losses."""
    plan = Plan(engine="lm", data_axis=data_axis, aux_weight=aux_weight,
                donate=donate, loss_chunk=loss_chunk, health=health)
    return _train(plan, mesh=mesh, model=model, tx=tx)


def make_lm_grad_accum_train_step(model, tx, mesh: Mesh,
                                  data_axis: str = DATA_AXIS,
                                  aux_weight: float = 0.01,
                                  donate: bool = True,
                                  loss_chunk: int = 0,
                                  health: str = "record") -> Callable:
    """ONE optimizer step from K microbatches (gradient accumulation), the
    LM twin of steps.py make_grad_accum_train_step.

    signature: (state, inputs (K, B, L), targets (K, B, L), rng) -> (state,
    metric sums over microbatches). Grads average over the K microbatches
    inside a lax.scan, then apply once — for global token batches beyond
    device memory. Equal microbatch sizes make the average of per-micro
    means equal the full-batch mean; dropout folds a per-microbatch index
    on top of the usual state.step fold.
    """
    # grad_accum_steps > 1 selects the accum template (K itself is read
    # from the stacked batch's leading dim at trace time)
    plan = Plan(engine="lm", grad_accum_steps=2, data_axis=data_axis,
                aux_weight=aux_weight, donate=donate, loss_chunk=loss_chunk,
                health=health)
    return _train(plan, mesh=mesh, model=model, tx=tx)


def make_lm_shard_map_train_step(model, tx, mesh: Mesh,
                                 data_axis: str = DATA_AXIS,
                                 aux_weight: float = 0.01,
                                 grad_bucket_mb: float = 25.0,
                                 donate: bool = True,
                                 loss_chunk: int = 0,
                                 health: str = "record") -> Callable:
    """Explicit-collective dp LM step — the LM twin of steps.py
    make_shard_map_train_step, carrying the ``grad_bucket_mb`` knob:
    gradient sync as independent ~25MB bucket reduce-scatters (DDP's
    overlap decomposition) instead of whatever single fused all-reduce
    GSPMD would emit. bucket_mb <= 0 keeps one monolithic pmean."""
    plan = Plan(engine="lm", sync="explicit", data_axis=data_axis,
                aux_weight=aux_weight, grad_bucket_mb=grad_bucket_mb,
                donate=donate, loss_chunk=loss_chunk, health=health)
    return _train(plan, mesh=mesh, model=model, tx=tx)


def make_lm_tp_ring_train_step(model, tx, mesh: Mesh,
                               data_axis: str = DATA_AXIS,
                               model_axis: str = MODEL_AXIS,
                               aux_weight: float = 0.01,
                               donate: bool = True,
                               loss_chunk: int = 0,
                               health: str = "record") -> Callable:
    """dp x TP step over the ring collective matmul (tp_impl='ring'):
    shard_map over (data, model), batch sharded on 'data', the model's
    ppermute rings running over 'model'. ``model`` must be built with
    tp_impl='ring'. Loss parity with the GSPMD TP step is exact for fp
    (tests/test_overlap.py); int8 quantizes per feature shard (finer
    granularity than GSPMD's global per-row amax), so quant parity is
    loss-level, not bitwise."""
    plan = Plan(engine="lm", sync="explicit", layout="tp", tp_impl="ring",
                data_axis=data_axis, model_axis=model_axis,
                aux_weight=aux_weight, donate=donate, loss_chunk=loss_chunk,
                health=health)
    return _train(plan, mesh=mesh, model=model, tx=tx)


def make_lm_explicit_indexed_multi_train_step(step_fn, mesh: Mesh,
                                              data_axis: str = DATA_AXIS,
                                              donate: bool = True) -> Callable:
    """K steps per dispatch for the explicit-collective LM steps
    (_lm_explicit_dp_step_fn / _lm_tp_ring_step_fn): a lax.scan over
    (K, B) index windows INSIDE the shard_map program, gathering rows from
    the HBM-resident (N, L+1) matrix and shifting on device — the explicit
    twin of make_lm_indexed_multi_train_step, same signature:
    (state, rows_all REPLICATED, idx (K, B) sharded (None, data), rng)."""
    plan = Plan(engine="lm", sync="explicit", window="indexed",
                steps_per_dispatch=2,  # K is read from the index window
                data_axis=data_axis, donate=donate)
    return _train(plan, mesh=mesh, explicit_step_fn=step_fn)


def make_lm_eval_step(model, mesh: Mesh, data_axis: str = DATA_AXIS,
                      loss_chunk: int = 0) -> Callable:
    """Forward-only metric sums on a held-out shard: (params, inputs,
    targets, valid) -> {loss_sum, correct1, count}. ``valid`` (B,) 0/1
    excludes sampler wrap-padding rows so perplexity is exact (the same
    masking contract as the image eval, steps.py make_eval_step). Works for
    any GSPMD placement the params carry (dp / fsdp / tp / ep)."""
    plan = Plan(engine="lm", data_axis=data_axis, loss_chunk=loss_chunk)
    return _eval(plan, mesh=mesh, model=model)


def make_lm_indexed_multi_train_step(model, tx, mesh: Mesh,
                                     data_axis: str = DATA_AXIS,
                                     aux_weight: float = 0.01,
                                     donate: bool = True,
                                     loss_chunk: int = 0,
                                     health: str = "record") -> Callable:
    """K optimizer steps per dispatch from an HBM-RESIDENT token corpus.

    signature: (state, rows_all (N, L+1) i32 REPLICATED, idx (K, B) i32
    sharded (None, data), rng) -> (state, metrics summed over K steps).

    The LM twin of steps.py make_indexed_multi_train_step: the whole row
    matrix lives on device once, each scan iteration gathers its (B, L+1)
    batch at HBM bandwidth and shifts inputs/targets ON DEVICE, and the host
    sends only the index window — so LM training throughput tracks the
    device step rate, not the host link. Identical math to K sequential
    make_lm_train_step calls (same per-step rng fold). Works under any
    GSPMD param placement (dp / fsdp / tp / ep) like the single step.
    """
    plan = Plan(engine="lm", window="indexed", steps_per_dispatch=2,
                data_axis=data_axis, aux_weight=aux_weight, donate=donate,
                loss_chunk=loss_chunk, health=health)
    return _train(plan, mesh=mesh, model=model, tx=tx)


def make_lm_indexed_eval_step(model, mesh: Mesh,
                              data_axis: str = DATA_AXIS,
                              loss_chunk: int = 0) -> Callable:
    """Whole-val-set perplexity in ONE dispatch from HBM-resident rows.

    signature: (params, rows_all (N, L+1) REPLICATED, idx (K, B) i32 sharded
    (None, data), valid (K, B) f32 same sharding) -> summed metrics over all
    K batches, sampler padding masked per row."""
    plan = Plan(engine="lm", window="indexed", steps_per_dispatch=2,
                data_axis=data_axis, loss_chunk=loss_chunk)
    return _eval(plan, mesh=mesh, model=model)


def make_lm_sp_eval_step(model_ctor: Callable, mesh: Mesh,
                         data_axis: str = DATA_AXIS,
                         seq_axis: str = SEQ_AXIS,
                         loss_chunk: int = 0) -> Callable:
    """Held-out eval under sequence parallelism: (params, inputs, targets,
    valid) with (data, seq)-sharded tokens, ring attention, metric sums
    psum'd over BOTH axes — closing the round-2 gap where sp had no eval."""
    plan = Plan(engine="lm", layout="sp", sync="explicit",
                data_axis=data_axis, seq_axis=seq_axis,
                loss_chunk=loss_chunk)
    return _eval(plan, mesh=mesh, model_ctor=model_ctor)


def make_lm_sp_train_step(model_ctor: Callable, tx, mesh: Mesh,
                          data_axis: str = DATA_AXIS,
                          seq_axis: str = SEQ_AXIS,
                          aux_weight: float = 0.01,
                          donate: bool = True,
                          loss_chunk: int = 0,
                          health: str = "record") -> Callable:
    """shard_map step: batch on 'data', sequence on 'seq', ring attention.

    ``model_ctor(attn_fn)`` builds the model with the given attention fn so
    the ring can be bound per-axis (tpu_dist.models.transformer.tiny_lm or a
    partial of TransformerLM).
    """
    plan = Plan(engine="lm", layout="sp", sync="explicit",
                data_axis=data_axis, seq_axis=seq_axis,
                aux_weight=aux_weight, donate=donate, loss_chunk=loss_chunk,
                health=health)
    return _train(plan, mesh=mesh, model_ctor=model_ctor, tx=tx)


def make_lm_sp_indexed_multi_train_step(model_ctor: Callable, tx, mesh: Mesh,
                                        data_axis: str = DATA_AXIS,
                                        seq_axis: str = SEQ_AXIS,
                                        aux_weight: float = 0.01,
                                        donate: bool = True,
                                        loss_chunk: int = 0,
                                        health: str = "record") -> Callable:
    """K sp optimizer steps per dispatch from HBM-resident rows (VERDICT r3
    #3 — the long-context mode was locked out of dispatch amortization,
    paying a host round-trip plus full token upload per step on exactly the
    workloads with the biggest per-step payload).

    signature: (state, rows_all (N, L+1) i32 REPLICATED, idx (K, B) i32
    sharded (None, data), rng) -> (state, metric sums over K steps).

    The lax.scan over index windows runs INSIDE the existing shard_map
    program: each iteration gathers its (B/data, L+1) rows at HBM bandwidth
    and takes this device's sequence shard with a device-side shift —
    identical math to K sequential make_lm_sp_train_step calls (same
    per-step rng fold; parameter equality asserted to rtol 1e-5 in
    tests/test_lm_loop.py)."""
    plan = Plan(engine="lm", layout="sp", sync="explicit", window="indexed",
                steps_per_dispatch=2, data_axis=data_axis,
                seq_axis=seq_axis, aux_weight=aux_weight, donate=donate,
                loss_chunk=loss_chunk, health=health)
    return _train(plan, mesh=mesh, model_ctor=model_ctor, tx=tx)


def make_lm_sp_indexed_eval_step(model_ctor: Callable, mesh: Mesh,
                                 data_axis: str = DATA_AXIS,
                                 seq_axis: str = SEQ_AXIS,
                                 loss_chunk: int = 0) -> Callable:
    """Whole-val-set perplexity in ONE dispatch under sequence parallelism:
    (params, rows_all (N, L+1) REPLICATED, idx (K, B) sharded (None, data),
    valid (K, B) f32 same sharding) -> metric sums over all K batches,
    sampler wrap-padding masked per row, psum'd over both axes."""
    plan = Plan(engine="lm", layout="sp", sync="explicit", window="indexed",
                steps_per_dispatch=2, data_axis=data_axis,
                seq_axis=seq_axis, loss_chunk=loss_chunk)
    return _eval(plan, mesh=mesh, model_ctor=model_ctor)
