"""Checkpoint save/RESUME (reference component C20, strictly extended).

The reference only saves — ``torch.save({epoch, arch, state_dict, best_acc1})``
plus a ``model_best`` copy, rank-0-guarded in variants 2-5 (reference
1.dataparallel.py:283-288, 2.distributed.py:182-189) and unguarded (racy) in
variant 6 (reference 6.distributed_slurm_main.py:190). It has **no load path
at all** (zero torch.load in the repo — SURVEY.md §5 'Checkpoint / resume').

tpu_dist does what the reference should have done:
* process-0-only writes (atomic: tmp file + rename);
* full TrainState (params, BN stats, optimizer state, step, loss scale)
  serialized with flax msgpack after gathering to host;
* REAL resume: restore into a template state, continuing epoch/step/best;
* ``model_best`` copy on improvement, same filename convention
  (``{arch}-checkpoint.msgpack`` ≈ the reference's arch-prefixed .pth.tar).
"""

from __future__ import annotations

import json
import os
import shutil
import threading
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np
from flax import serialization

_async_writer: Optional[threading.Thread] = None
_async_error: Optional[BaseException] = None


def gather_to_host(tree):
    """Host numpy copy of every leaf, reassembling sharded global arrays.

    Replicated leaves — even over a multi-host mesh — read out locally via
    device_get (jax materializes fully-replicated arrays from the local
    replica). Only leaves that are BOTH non-addressable and non-replicated
    (multi-host FSDP/TP/EP shards) need ``process_allgather`` — a COLLECTIVE
    over processes, so every process must reach this call for such states
    (save_checkpoint gathers before its process-0 gate for exactly this
    reason). Fully-replicated states therefore never enter a collective and
    process 0 can save them single-sidedly (e.g. from an interrupt handler).
    """
    from jax.experimental import multihost_utils

    def get(x):
        if (isinstance(x, jax.Array) and not x.is_fully_addressable
                and not x.is_fully_replicated):
            return np.asarray(multihost_utils.process_allgather(x, tiled=True))
        return np.asarray(jax.device_get(x))

    return jax.tree.map(get, tree)


_to_host = gather_to_host  # internal alias


# single-file container so blob+meta commit in ONE os.replace (a two-file
# scheme always has a crash window that pairs a new blob with old meta):
# MAGIC | u64-le meta_len | meta json | msgpack blob
_MAGIC = b"TPUDIST1\n"


def _split_container(raw: bytes) -> Tuple[Dict, Any]:
    """(meta, blob_view) from container bytes — THE header parse, shared by
    every reader. Pre-container files (bare msgpack) return ({}, raw)."""
    if not raw.startswith(_MAGIC):
        return {}, raw
    off = len(_MAGIC)
    meta_len = int.from_bytes(raw[off:off + 8], "little")
    meta = json.loads(raw[off + 8:off + 8 + meta_len])
    # memoryview: don't hold a second full copy of a multi-GB state
    return meta, memoryview(raw)[off + 8 + meta_len:]


def _write(ckpt_dir: str, path: str, host_state, meta: Dict,
           arch: str, is_best: bool) -> None:
    meta_bytes = json.dumps(meta).encode()
    blob = serialization.to_bytes(host_state)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(_MAGIC)
        f.write(len(meta_bytes).to_bytes(8, "little"))
        f.write(meta_bytes)
        f.write(blob)
    os.replace(tmp, path)
    # sidecar json is a human-readable convenience only; load reads the
    # embedded copy, so a crash here cannot desynchronize blob and meta
    with open(path + ".json.tmp", "w") as f:
        json.dump(meta, f)
    os.replace(path + ".json.tmp", path + ".json")
    if is_best:
        # reference shutil.copyfile to 'model_best' (1.dataparallel.py:287-288),
        # made atomic so a crash mid-copy can't destroy the previous best
        for src, dst in ((path, f"{arch}-model_best.msgpack"),
                         (path + ".json", f"{arch}-model_best.msgpack.json")):
            best = os.path.join(ckpt_dir, dst)
            shutil.copyfile(src, best + ".tmp")
            os.replace(best + ".tmp", best)


def wait_for_async_save() -> None:
    """Block until a pending async write finishes (call before exit/load).

    Re-raises any exception the background writer hit (ENOSPC, permissions)
    — write failures must stop the run, not rot checkpoints silently.
    """
    global _async_writer, _async_error
    if _async_writer is not None:
        _async_writer.join()
        _async_writer = None
    if _async_error is not None:
        err, _async_error = _async_error, None
        raise RuntimeError("async checkpoint write failed") from err


# a process must never exit with a write in flight (daemon threads are
# killed mid-write at interpreter shutdown)
import atexit  # noqa: E402

atexit.register(wait_for_async_save)


def save_checkpoint(ckpt_dir: str, state, epoch: int, best_acc1: float,
                    arch: str, is_best: bool,
                    extra_meta: Optional[Dict] = None,
                    async_write: bool = False) -> Optional[str]:
    """Atomic save; returns path on process 0, None elsewhere.

    For states with cross-host SHARDED leaves, ALL processes must call this
    (the gather is collective); replicated states save process-0-only.

    ``async_write=True`` moves serialization + disk I/O to a background
    thread (the device->host gather stays synchronous — it must read the
    state before training mutates it). At most one writer is in flight;
    a second save joins the previous one first, and atomic tmp+rename means
    a crash mid-write never corrupts the last complete checkpoint. NOTE:
    the returned path is not valid to read until
    :func:`wait_for_async_save` returns (which also re-raises writer
    errors; an atexit hook joins any writer left pending at exit).
    """
    needs_collective = any(
        isinstance(x, jax.Array) and not x.is_fully_addressable
        and not x.is_fully_replicated for x in jax.tree.leaves(state))
    if jax.process_index() != 0 and not needs_collective:
        return None  # replicated state: no reason to host-copy it everywhere
    host_state = _to_host(state)  # collective only for cross-host shards
    if jax.process_index() != 0:
        return None
    os.makedirs(ckpt_dir, exist_ok=True)
    path = os.path.join(ckpt_dir, f"{arch}-checkpoint.msgpack")
    meta = {"epoch": epoch, "arch": arch, "best_acc1": float(best_acc1),
            "step": int(host_state.step), **(extra_meta or {})}
    global _async_writer
    wait_for_async_save()  # serialize writers, surface prior write errors
    if async_write:
        def run():
            global _async_error
            try:
                _write(ckpt_dir, path, host_state, meta, arch, is_best)
            except BaseException as e:  # re-raised by wait_for_async_save
                _async_error = e
        _async_writer = threading.Thread(target=run, daemon=True)
        _async_writer.start()
    else:
        _write(ckpt_dir, path, host_state, meta, arch, is_best)
    return path


def read_checkpoint_meta(path: str) -> Dict:
    """Metadata only, without deserializing the blob — validate geometry
    BEFORE from_bytes (whose structure-mismatch errors are opaque).

    Reads just the header (same layout _split_container parses), never the
    multi-GB blob."""
    with open(path, "rb") as f:
        head = f.read(len(_MAGIC) + 8)
        if head.startswith(_MAGIC):
            meta_len = int.from_bytes(head[len(_MAGIC):], "little")
            return json.loads(f.read(meta_len))
    if os.path.exists(path + ".json"):  # pre-container checkpoint
        with open(path + ".json") as f:
            return json.load(f)
    return {}


def load_warmstart(path: str) -> Tuple[Dict, Dict, Dict]:
    """(params, batch_stats, meta) from a checkpoint WITHOUT a template.

    The ``--pretrained PATH`` path (reference 1.dataparallel.py:97-102 loads
    torchvision weights; zero egress means local files are the weight
    source here — e.g. this repo's own ``{arch}-model_best.msgpack``).
    Restores the raw msgpack state dict, so it needs no TrainState template
    and carries no optimizer state — warm-starts always begin a FRESH
    trajectory (fresh opt state, step 0), unlike --resume.
    """
    with open(path, "rb") as f:
        raw = f.read()
    meta, blob = _split_container(raw)
    # msgpack_restore takes any buffer — no bytes() copy of a multi-GB blob
    tree = serialization.msgpack_restore(blob)
    return tree.get("params", {}), tree.get("batch_stats", {}) or {}, meta


def graft_params(fresh, loaded, cast_dtype: bool = True):
    """Overlay ``loaded`` leaves onto ``fresh`` where path AND shape match.

    Returns (grafted_tree, n_loaded, skipped_paths). Mismatched or missing
    leaves keep their fresh init — that is the fine-tune contract: a
    checkpoint trained at num_classes=1000 warm-starts a 10-class model
    with every tensor except the classifier head. Loaded leaves cast to the
    fresh leaf's dtype (the storage-policy dtype of THIS run)."""
    from flax import traverse_util

    flat_f = traverse_util.flatten_dict(fresh)
    flat_l = traverse_util.flatten_dict(loaded)
    out, skipped, n = {}, [], 0
    for k, v in flat_f.items():
        lv = flat_l.get(k)
        if lv is not None and getattr(lv, "shape", None) == v.shape:
            out[k] = np.asarray(lv, dtype=v.dtype) if cast_dtype else lv
            n += 1
        else:
            out[k] = v
            skipped.append("/".join(map(str, k)))
    return traverse_util.unflatten_dict(out), n, skipped


def load_checkpoint(path: str, template_state) -> Tuple[Any, Dict]:
    """Restore a TrainState saved by save_checkpoint into template's structure."""
    with open(path, "rb") as f:
        raw = f.read()
    meta, blob = _split_container(raw)
    if not meta and os.path.exists(path + ".json"):
        # pre-container checkpoint: bare msgpack + sidecar json
        with open(path + ".json") as f:
            meta = json.load(f)
    try:
        state = serialization.from_bytes(template_state, blob)
    except (ValueError, KeyError) as e:
        # The opt_state pytree is part of the serialized structure, so any
        # flag that changes the optax chain between save and resume —
        # --grad-clip on<->off (inserts/removes clip_by_global_norm state),
        # --optimizer sgd<->adamw, --weight-decay 0<->nonzero — makes
        # from_bytes fail with an opaque structure mismatch (ADVICE r4).
        raise ValueError(
            f"checkpoint {path!r} does not match the current run's state "
            "structure. Common causes: a different model geometry, a "
            "truncated/corrupt file, or optimizer-chain flags that differ "
            "from the run that wrote it (--grad-clip on<->off inserts/"
            "removes clip state; --optimizer; --weight-decay 0<->nonzero). "
            f"Original error: {e}") from e
    return state, meta
