"""Checkpoint save/RESUME (reference component C20, strictly extended).

The reference only saves — ``torch.save({epoch, arch, state_dict, best_acc1})``
plus a ``model_best`` copy, rank-0-guarded in variants 2-5 (reference
1.dataparallel.py:283-288, 2.distributed.py:182-189) and unguarded (racy) in
variant 6 (reference 6.distributed_slurm_main.py:190). It has **no load path
at all** (zero torch.load in the repo — SURVEY.md §5 'Checkpoint / resume').

tpu_dist does what the reference should have done:
* process-0-only writes (atomic: tmp file + rename);
* full TrainState (params, BN stats, optimizer state, step, loss scale)
  serialized with flax msgpack after gathering to host;
* REAL resume: restore into a template state, continuing epoch/step/best;
* ``model_best`` copy on improvement, same filename convention
  (``{arch}-checkpoint.msgpack`` ≈ the reference's arch-prefixed .pth.tar).
"""

from __future__ import annotations

import errno
import glob
import json
import os
import re
import shutil
import sys
import threading
import zlib
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np
from flax import serialization

from tpu_dist.obs import faults as _faults


class CheckpointCorruptError(ValueError):
    """The named checkpoint AND every retained fallback failed integrity
    checks (CRC32/length from the container header) — nothing valid to
    resume from."""


def gather_to_host(tree):
    """Host numpy copy of every leaf, reassembling sharded global arrays.

    Replicated leaves — even over a multi-host mesh — read out locally via
    device_get (jax materializes fully-replicated arrays from the local
    replica). Only leaves that are BOTH non-addressable and non-replicated
    (multi-host FSDP/TP/EP shards) need ``process_allgather`` — a COLLECTIVE
    over processes, so every process must reach this call for such states
    (save_checkpoint gathers before its process-0 gate for exactly this
    reason). Fully-replicated states therefore never enter a collective and
    process 0 can save them single-sidedly (e.g. from an interrupt handler).
    """
    from jax.experimental import multihost_utils

    def get(x):
        if (isinstance(x, jax.Array) and not x.is_fully_addressable
                and not x.is_fully_replicated):
            return np.asarray(multihost_utils.process_allgather(x, tiled=True))
        return np.asarray(jax.device_get(x))

    return jax.tree.map(get, tree)


_to_host = gather_to_host  # internal alias


# single-file container so blob+meta commit in ONE os.replace (a two-file
# scheme always has a crash window that pairs a new blob with old meta):
# MAGIC | u64-le meta_len | meta json | msgpack blob. Since round 10 the
# meta carries blob_len + crc32 of the blob, so a truncated or bit-rotted
# file is DETECTABLE at load (and load falls back to a retained sibling)
_MAGIC = b"TPUDIST1\n"


def _split_container(raw: bytes) -> Tuple[Dict, Any]:
    """(meta, blob_view) from container bytes — THE header parse, shared by
    every reader. Pre-container files (bare msgpack) return ({}, raw)."""
    if not raw.startswith(_MAGIC):
        return {}, raw
    off = len(_MAGIC)
    meta_len = int.from_bytes(raw[off:off + 8], "little")
    meta = json.loads(raw[off + 8:off + 8 + meta_len])
    # memoryview: don't hold a second full copy of a multi-GB state
    return meta, memoryview(raw)[off + 8 + meta_len:]


def _integrity_error(meta: Dict, blob) -> Optional[str]:
    """Why this container fails its own header's integrity stamps (None =
    intact, or a pre-crc file with nothing to check)."""
    want_len = meta.get("blob_len")
    if want_len is not None and len(blob) != int(want_len):
        return (f"blob is {len(blob)} bytes, header says {want_len} "
                "(truncated write?)")
    want_crc = meta.get("crc32")
    if want_crc is not None:
        got = zlib.crc32(blob) & 0xFFFFFFFF
        if got != int(want_crc):
            return f"CRC32 mismatch (header {want_crc:#010x}, file {got:#010x})"
    return None


def _retained_path(path: str, step: int) -> str:
    root, ext = os.path.splitext(path)
    return f"{root}.r{int(step)}{ext}"


def retained_checkpoints(path: str) -> List[str]:
    """The keep-last-K retained siblings of a checkpoint path, newest
    (highest step) first — the fallback order for a corrupt newest."""
    root, ext = os.path.splitext(path)
    found = []
    for p in glob.glob(f"{glob.escape(root)}.r*{ext}"):
        m = re.fullmatch(re.escape(root) + r"\.r(\d+)" + re.escape(ext), p)
        if m:
            found.append((int(m.group(1)), p))
    return [p for _, p in sorted(found, reverse=True)]


def _retain(ckpt_dir: str, path: str, meta: Dict, keep: int,
            is_best: bool) -> None:
    """Keep-last-K retention + the newest-valid pointer file. Hard links
    where the FS allows (zero extra bytes), copies otherwise. Runs AFTER
    the atomic replace of ``path`` — a crash here loses at most history,
    never the newest checkpoint."""
    retained = []
    if keep > 0:
        snap = _retained_path(path, meta.get("step", 0))
        try:
            if os.path.exists(snap):
                os.remove(snap)
            try:
                os.link(path, snap)
            except OSError:  # FS without hard links
                shutil.copyfile(path, snap)
        except OSError as e:
            print(f"warning: checkpoint retention copy failed: {e}",
                  file=sys.stderr)
        retained = retained_checkpoints(path)
        for stale in retained[keep:]:
            try:
                os.remove(stale)
            except OSError:
                pass
        retained = retained[:keep]
    # the pointer: written only after a fully-committed container, so it
    # always names the newest VALID checkpoint (an ENOSPC'd write never
    # advances it) — parallel.supervisor resumes from this
    root, _ = os.path.splitext(path)
    index = {"newest": os.path.basename(path),
             "step": meta.get("step"), "epoch": meta.get("epoch"),
             "crc32": meta.get("crc32"),
             "retained": [os.path.basename(p) for p in retained],
             "best": (f"{meta.get('arch')}-model_best.msgpack"
                      if is_best else None)}
    tmp = root + ".index.json.tmp"
    with open(tmp, "w") as f:
        json.dump(index, f)
    os.replace(tmp, root + ".index.json")


def _write(ckpt_dir: str, path: str, host_state, meta: Dict,
           arch: str, is_best: bool, keep: int = 0) -> None:
    fault = _faults.fire("ckpt_enospc")
    if fault is not None:
        # before any byte lands: the checkpoint on disk stays the previous
        # valid one, which is exactly what the fallback path must find
        raise OSError(errno.ENOSPC,
                      f"No space left on device (injected: {fault.spec})")
    blob = serialization.to_bytes(host_state)
    meta = dict(meta, blob_len=len(blob),
                crc32=zlib.crc32(blob) & 0xFFFFFFFF)
    meta_bytes = json.dumps(meta).encode()
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(_MAGIC)
        f.write(len(meta_bytes).to_bytes(8, "little"))
        f.write(meta_bytes)
        f.write(blob)
    os.replace(tmp, path)
    # sidecar json is a human-readable convenience only; load reads the
    # embedded copy, so a crash here cannot desynchronize blob and meta
    with open(path + ".json.tmp", "w") as f:
        json.dump(meta, f)
    os.replace(path + ".json.tmp", path + ".json")
    _retain(ckpt_dir, path, meta, keep, is_best)
    if is_best:
        # reference shutil.copyfile to 'model_best' (1.dataparallel.py:287-288),
        # made atomic so a crash mid-copy can't destroy the previous best
        for src, dst in ((path, f"{arch}-model_best.msgpack"),
                         (path + ".json", f"{arch}-model_best.msgpack.json")):
            best = os.path.join(ckpt_dir, dst)
            shutil.copyfile(src, best + ".tmp")
            os.replace(best + ".tmp", best)


# -- async writer state, PER ckpt_dir ---------------------------------------
# One registry entry per checkpoint directory: module-level singleton state
# (rounds 6-9) serialized ALL checkpoint streams behind one thread and let
# concurrent dirs race each other's error slot. Distinct dirs now overlap
# freely; within one dir, writes still serialize (atomic tmp+rename only
# protects readers, not two writers interleaving history/retention).

class _AsyncWriter:
    __slots__ = ("thread", "error")

    def __init__(self):
        self.thread: Optional[threading.Thread] = None
        self.error: Optional[BaseException] = None


_writers: Dict[str, _AsyncWriter] = {}
_writers_lock = threading.Lock()


def _writer_for(ckpt_dir: str) -> _AsyncWriter:
    key = os.path.abspath(ckpt_dir or ".")
    with _writers_lock:
        return _writers.setdefault(key, _AsyncWriter())


def wait_for_async_save(ckpt_dir: Optional[str] = None) -> None:
    """Block until pending async writes finish (``None`` = every dir —
    the exit-path call; a dir joins only its own stream, so concurrent
    checkpoint streams never serialize behind each other).

    Re-raises any exception the background writer hit (ENOSPC, permissions)
    — write failures must stop the run, not rot checkpoints silently.
    """
    with _writers_lock:
        if ckpt_dir is None:
            pending = list(_writers.values())
        else:
            w = _writers.get(os.path.abspath(ckpt_dir))
            pending = [w] if w is not None else []
    first_err = None
    for w in pending:
        if w.thread is not None:
            w.thread.join()
            w.thread = None
        if w.error is not None:
            first_err = first_err or w.error
            w.error = None
    if first_err is not None:
        raise RuntimeError("async checkpoint write failed") from first_err


# a process must never exit with a write in flight (daemon threads are
# killed mid-write at interpreter shutdown)
import atexit  # noqa: E402

atexit.register(wait_for_async_save)


def save_checkpoint(ckpt_dir: str, state, epoch: int, best_acc1: float,
                    arch: str, is_best: bool,
                    extra_meta: Optional[Dict] = None,
                    async_write: bool = False, keep: int = 0) -> Optional[str]:
    """Atomic save; returns path on process 0, None elsewhere.

    For states with cross-host SHARDED leaves, ALL processes must call this
    (the gather is collective); replicated states save process-0-only.

    ``async_write=True`` moves serialization + disk I/O to a background
    thread (the device->host gather stays synchronous — it must read the
    state before training mutates it). At most one writer per ``ckpt_dir``
    is in flight; a second save to the SAME dir joins the previous one
    first (distinct dirs overlap freely), and atomic tmp+rename means a
    crash mid-write never corrupts the last complete checkpoint. NOTE:
    the returned path is not valid to read until
    :func:`wait_for_async_save` returns (which also re-raises writer
    errors; an atexit hook joins any writer left pending at exit).

    ``keep > 0`` additionally retains the last ``keep`` checkpoints as
    step-stamped hard links (``{arch}-checkpoint.r<step>.msgpack``) and
    writes a ``{arch}-checkpoint.index.json`` pointer to the newest valid
    container — the fallback set :func:`load_checkpoint` walks when the
    newest file fails its CRC, and what ``parallel.supervisor`` resumes
    from.
    """
    needs_collective = any(
        isinstance(x, jax.Array) and not x.is_fully_addressable
        and not x.is_fully_replicated for x in jax.tree.leaves(state))
    if jax.process_index() != 0 and not needs_collective:
        return None  # replicated state: no reason to host-copy it everywhere
    host_state = _to_host(state)  # collective only for cross-host shards
    if jax.process_index() != 0:
        return None
    os.makedirs(ckpt_dir, exist_ok=True)
    path = os.path.join(ckpt_dir, f"{arch}-checkpoint.msgpack")
    meta = {"epoch": epoch, "arch": arch, "best_acc1": float(best_acc1),
            "step": int(host_state.step), **(extra_meta or {})}
    writer = _writer_for(ckpt_dir)
    wait_for_async_save(ckpt_dir)  # serialize THIS dir's writers, surface
    # its prior write errors (other dirs' streams are untouched)
    if async_write:
        def run():
            try:
                _write(ckpt_dir, path, host_state, meta, arch, is_best,
                       keep=keep)
            except BaseException as e:  # re-raised by wait_for_async_save
                writer.error = e
        writer.thread = threading.Thread(target=run, daemon=True)
        writer.thread.start()
    else:
        _write(ckpt_dir, path, host_state, meta, arch, is_best, keep=keep)
    return path


def peer_restore_state(state, broadcast=None) -> Tuple[Any, bool]:
    """Checkpoint-less dp-pure recovery (round 13): adopt process 0's
    state over a broadcast collective instead of the disk round-trip.

    On mesh re-expansion a returning host has no (or a stale) local
    checkpoint, but every survivor holds the live replicated state — and
    the consensus renumbering (parallel.consensus, survivors-first)
    guarantees process 0 IS a survivor. All processes must call this
    (the broadcast is collective; the distributed analog of the
    reference's ring-allreduce variant 5). Returns
    ``(host_state, True)`` after a broadcast, or ``(state, False)``
    untouched on a single process — callers re-place the result with
    their mode's sharding. Only valid for REPLICATED (dp-pure) states:
    sharded layouts must take the disk path, whose container knows the
    global layout.

    ``broadcast`` is injectable for tests; the default is
    ``multihost_utils.broadcast_one_to_all`` (source = process 0).
    """
    if jax.process_count() <= 1 and broadcast is None:
        return state, False
    if broadcast is None:
        from jax.experimental import multihost_utils

        broadcast = multihost_utils.broadcast_one_to_all
    host = jax.tree.map(lambda x: np.asarray(jax.device_get(x)), state)
    return broadcast(host), True


def read_checkpoint_meta(path: str) -> Dict:
    """Metadata only, without deserializing the blob — validate geometry
    BEFORE from_bytes (whose structure-mismatch errors are opaque).

    Reads just the header (same layout _split_container parses), never the
    multi-GB blob."""
    with open(path, "rb") as f:
        head = f.read(len(_MAGIC) + 8)
        if head.startswith(_MAGIC):
            meta_len = int.from_bytes(head[len(_MAGIC):], "little")
            return json.loads(f.read(meta_len))
    if os.path.exists(path + ".json"):  # pre-container checkpoint
        with open(path + ".json") as f:
            return json.load(f)
    return {}


def load_warmstart(path: str) -> Tuple[Dict, Dict, Dict]:
    """(params, batch_stats, meta) from a checkpoint WITHOUT a template.

    The ``--pretrained PATH`` path (reference 1.dataparallel.py:97-102 loads
    torchvision weights; zero egress means local files are the weight
    source here — e.g. this repo's own ``{arch}-model_best.msgpack``).
    Restores the raw msgpack state dict, so it needs no TrainState template
    and carries no optimizer state — warm-starts always begin a FRESH
    trajectory (fresh opt state, step 0), unlike --resume.
    """
    with open(path, "rb") as f:
        raw = f.read()
    meta, blob = _split_container(raw)
    # msgpack_restore takes any buffer — no bytes() copy of a multi-GB blob
    tree = serialization.msgpack_restore(blob)
    return tree.get("params", {}), tree.get("batch_stats", {}) or {}, meta


def graft_params(fresh, loaded, cast_dtype: bool = True):
    """Overlay ``loaded`` leaves onto ``fresh`` where path AND shape match.

    Returns (grafted_tree, n_loaded, skipped_paths). Mismatched or missing
    leaves keep their fresh init — that is the fine-tune contract: a
    checkpoint trained at num_classes=1000 warm-starts a 10-class model
    with every tensor except the classifier head. Loaded leaves cast to the
    fresh leaf's dtype (the storage-policy dtype of THIS run)."""
    from flax import traverse_util

    flat_f = traverse_util.flatten_dict(fresh)
    flat_l = traverse_util.flatten_dict(loaded)
    out, skipped, n = {}, [], 0
    for k, v in flat_f.items():
        lv = flat_l.get(k)
        if lv is not None and getattr(lv, "shape", None) == v.shape:
            out[k] = np.asarray(lv, dtype=v.dtype) if cast_dtype else lv
            n += 1
        else:
            out[k] = v
            skipped.append("/".join(map(str, k)))
    return traverse_util.unflatten_dict(out), n, skipped


def _load_one(path: str, template_state) -> Tuple[Any, Dict]:
    """Restore ONE container file, integrity-checked. Raises
    CheckpointCorruptError for a truncated/bit-rotted container (the
    header's own crc32/blob_len disagree with the bytes — the fallback-
    eligible failure) and ValueError for a structure mismatch (a crc-valid
    blob that does not fit the template: wrong geometry or optimizer
    flags — falling back would silently resume an incompatible run)."""
    with open(path, "rb") as f:
        raw = f.read()
    meta, blob = _split_container(raw)
    if not meta and os.path.exists(path + ".json"):
        # pre-container checkpoint: bare msgpack + sidecar json
        with open(path + ".json") as f:
            meta = json.load(f)
    bad = _integrity_error(meta, blob)
    if bad:
        raise CheckpointCorruptError(f"checkpoint {path!r} is corrupt: {bad}")
    try:
        state = serialization.from_bytes(template_state, blob)
    except (ValueError, KeyError) as e:
        # The opt_state pytree is part of the serialized structure, so any
        # flag that changes the optax chain between save and resume —
        # --grad-clip on<->off (inserts/removes clip_by_global_norm state),
        # --optimizer sgd<->adamw, --weight-decay 0<->nonzero — makes
        # from_bytes fail with an opaque structure mismatch (ADVICE r4).
        raise ValueError(
            f"checkpoint {path!r} does not match the current run's state "
            "structure. Common causes: a different model geometry, a "
            "truncated/corrupt file, or optimizer-chain flags that differ "
            "from the run that wrote it (--grad-clip on<->off inserts/"
            "removes clip state; --optimizer; --weight-decay 0<->nonzero). "
            f"Original error: {e}") from e
    return state, meta


def load_checkpoint(path: str, template_state,
                    fallback: bool = True) -> Tuple[Any, Dict]:
    """Restore a TrainState saved by save_checkpoint into template's
    structure. When the named file fails its container integrity check
    (crc32/blob_len — a write torn by the very crash being recovered
    from), ``fallback=True`` walks the retained keep-last-K siblings
    newest-first and loads the first intact one, with a loud warning —
    losing a few steps beats losing the run. Structure mismatches never
    fall back (every retained sibling shares the structure; the error is
    the caller's flags, not the file)."""
    candidates = [path] + (retained_checkpoints(path) if fallback else [])
    last_err: Optional[Exception] = None
    for i, p in enumerate(candidates):
        try:
            state, meta = _load_one(p, template_state)
        except (CheckpointCorruptError, FileNotFoundError) as e:
            print(f"warning: {e}"
                  + ("; falling back to the previous retained checkpoint"
                     if i + 1 < len(candidates) else ""), file=sys.stderr)
            last_err = e
            continue
        if i > 0:
            print(f"warning: resumed from RETAINED checkpoint {p!r} "
                  f"(step {meta.get('step')}) — the newest container was "
                  "corrupt; steps after it are lost and will be retrained",
                  file=sys.stderr)
        return state, meta
    raise CheckpointCorruptError(
        f"checkpoint {path!r} is corrupt and no intact retained fallback "
        f"exists ({len(candidates) - 1} sibling(s) tried)") from last_err
