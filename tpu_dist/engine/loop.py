"""Trainer: epoch loop, distributed eval, metering, CSV logs (C14/C15/C17/C21).

Orchestrates the reference's train()/validate()/checkpoint skeleton (reference
2.distributed.py:166-189) around the fused TPU step functions. Differences by
design:

* metric tensors are NOT pulled to host every batch (the reference's
  per-batch barrier+allreduce serialized the step — SURVEY.md §3.2 note);
  device metrics are fetched only at print-frequency boundaries, so the TPU
  queue stays full (JAX async dispatch);
* printing/logging is process-0-only (the reference printed on every rank —
  duplicated output, 2.distributed.py:238-239);
* per-epoch CSV timing matches reference format [wall_start, seconds]
  (reference 1.dataparallel.py:187-190).
"""

from __future__ import annotations

import os
import time
from functools import partial
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from tpu_dist import configs
from tpu_dist.data import (DataLoader, DistributedSampler, assemble_global,
                           load_dataset, make_transform, prefetch_to_device)
from tpu_dist.engine import checkpoint as ckpt
from tpu_dist.engine.state import TrainState, init_model
from tpu_dist.engine.steps import (make_eval_step, make_indexed_multi_train_step,
                                   make_multi_train_step,
                                   make_shard_map_train_step, make_train_step)
from tpu_dist.models import create_model
from tpu_dist.obs import (HealthError, RunObs, faults, profile_session,
                          step_annotation)
from tpu_dist.ops import LossScaleState, make_optimizer, make_policy, step_decay_schedule
from tpu_dist.parallel.mesh import batch_sharding, make_mesh, replicated
from tpu_dist.parallel.supervisor import PREEMPT_SNAPSHOT_RC
from tpu_dist.utils.meters import MeterBank


class Trainer:
    """One engine for all cookbook variants; flavor picked by config.

    ``cfg.variant``: 'jit' (compiler-partitioned, DDP-equiv) or 'shard_map'
    (explicit psum, horovod-equiv). Multi-host vs single-host is decided by
    how the process was launched (tpu_dist.parallel.launch), not here.
    """

    def __init__(self, cfg: configs.TrainConfig, mesh=None):
        # step plan (tpu_dist.plan): the `plan` knob rewrites the
        # plan-owned config fields (incl. variant) and flips the
        # trace-time kernel switches BEFORE anything below reads them
        from tpu_dist.plan.compile import resolve_config_plan
        cfg, self._plan_info = resolve_config_plan(cfg)
        self.cfg = cfg
        # fail fast on bad config, before device/model setup
        if cfg.resume and not os.path.exists(cfg.resume):
            raise FileNotFoundError(f"--resume checkpoint not found: {cfg.resume}")
        if cfg.pretrained and not os.path.exists(cfg.pretrained):
            raise FileNotFoundError(
                f"--pretrained checkpoint not found: {cfg.pretrained}")
        if cfg.optimizer not in ("sgd", "fused_sgd", "adamw"):
            raise ValueError(f"unknown optimizer {cfg.optimizer!r} "
                             "(sgd|fused_sgd|adamw)")
        from tpu_dist.models.registry import model_kind
        if model_kind(cfg.arch) != "image":
            raise ValueError(
                f"--arch {cfg.arch} is a language model; this trainer drives "
                "image classifiers — use scripts/8.lm_longcontext.py")
        if cfg.variant not in ("jit", "shard_map"):
            raise ValueError(f"unknown variant {cfg.variant!r} (jit|shard_map)")
        from tpu_dist.obs.health import validate_health
        validate_health(cfg.health)  # record | skip | halt, before any build
        self.mesh = mesh if mesh is not None else make_mesh(cfg.mesh_shape, cfg.mesh_axes)
        self.policy = make_policy(cfg.precision)
        self.train_ds, self.val_ds = load_dataset(
            cfg.dataset, cfg.data, cfg.synth_train_size, cfg.synth_val_size,
            seed=cfg.seed if cfg.seed is not None else 1234)
        self.num_classes = self.train_ds.num_classes

        nprocs = jax.process_count()
        # global batch divided per process (reference 2.distributed.py:113);
        # then further split per device by the mesh sharding.
        ndev = self.mesh.devices.size
        # nprocs always divides ndev (equal local devices per process), so
        # batch % ndev == 0 also guarantees an integral per-process batch
        if cfg.batch_size % ndev:
            raise ValueError(
                f"global batch {cfg.batch_size} not divisible by device count "
                f"{ndev} ({nprocs} processes x {ndev // nprocs} local devices)")
        self.local_batch = cfg.batch_size // nprocs

        model_kw = {}
        if cfg.norm:
            model_kw["norm"] = cfg.norm
        if cfg.norm_dtype:
            if cfg.norm_dtype not in ("bf16", "fp32"):
                raise ValueError(f"--norm-dtype {cfg.norm_dtype!r} "
                                 "(bf16|fp32)")
            if cfg.norm_dtype == "bf16":
                model_kw["norm_dtype"] = jnp.bfloat16
        if cfg.stem:
            model_kw["stem"] = cfg.stem
        if model_kw and not cfg.arch.startswith(("resnet", "resnext",
                                                 "wide_resnet")):
            raise ValueError(
                f"--norm/--norm-dtype/--stem are ResNet-family knobs; "
                f"arch {cfg.arch!r} does not take them")
        if cfg.quant and cfg.quant != "none":
            from tpu_dist.ops.quant import validate_quant
            validate_quant(cfg.quant)
            if not cfg.arch.startswith("vit"):
                # int8 matmuls live in the transformer families (ops.quant);
                # conv stacks would need a quantized-conv path this repo
                # does not carry yet — refuse rather than silently ignore
                raise ValueError(
                    f"--quant {cfg.quant} applies to the transformer-family "
                    f"image archs (vit_*); arch {cfg.arch!r} does not take it")
            model_kw["quant"] = cfg.quant
        from tpu_dist.parallel.overlap import validate_tp_impl
        validate_tp_impl(cfg.tp_impl)
        if cfg.tp_impl == "ring":
            # ring collective-matmul TP (parallel.overlap) for the
            # transformer-family image archs: needs the explicit-collective
            # engine (the ppermute rings run inside its shard_map) and a
            # 'model' mesh axis for them to ride
            if not cfg.arch.startswith("vit"):
                raise ValueError(
                    f"--tp-impl ring applies to the transformer-family "
                    f"image archs (vit_*); arch {cfg.arch!r} has no "
                    "column/row-parallel projections")
            if cfg.variant != "shard_map":
                raise ValueError("--tp-impl ring requires "
                                 "variant='shard_map' (the ring collectives "
                                 "are explicit)")
            if "model" not in self.mesh.axis_names \
                    or self.mesh.shape["model"] < 2:
                raise ValueError("--tp-impl ring needs a 'model' mesh axis "
                                 "of size >= 2 (e.g. --mesh-shape=-1,2 "
                                 "--mesh-axes=data,model)")
        if cfg.grad_bucket_mb > 0 and cfg.variant != "shard_map":
            raise ValueError("--grad-bucket-mb decomposes the explicit "
                             "gradient allreduce; it requires "
                             "variant='shard_map' (the jit flavor's sync "
                             "is GSPMD-scheduled)")
        self.model = create_model(
            cfg.arch, num_classes=self.num_classes,
            dtype=self.policy.compute_dtype, pretrained=cfg.pretrained,
            warmstart_handled=True,  # grafted below (registry guard)
            **model_kw)
        if cfg.tp_impl == "ring":
            # config-time twin of the LMTrainer check: each shard's qkv
            # slice must hold whole heads (vit_tiny's 3 heads cannot split
            # over a 2-wide model axis)
            tp = self.mesh.shape["model"]
            heads = getattr(self.model, "num_heads", 0)
            if heads % tp:
                raise ValueError(
                    f"--tp-impl ring shards attention heads: num_heads "
                    f"{heads} of {cfg.arch!r} must divide by the 'model' "
                    f"axis ({tp})")

        seed = cfg.seed if cfg.seed is not None else 0
        self.rng = jax.random.PRNGKey(seed)
        h, w, c = self.train_ds.image_shape
        params, batch_stats = init_model(
            self.model, self.rng, (2, h, w, c))
        params = self.policy.cast_params_for_storage(params)
        if cfg.pretrained:  # existence checked first-line in __init__
            pre_params, pre_stats, pre_meta = ckpt.load_warmstart(
                cfg.pretrained)
            params, n_p, skipped = ckpt.graft_params(params, pre_params)
            batch_stats, n_s, _ = ckpt.graft_params(batch_stats, pre_stats)
            if n_p == 0:
                raise ValueError(
                    f"--pretrained {cfg.pretrained} (arch "
                    f"{pre_meta.get('arch', '?')!r}) shares no tensors with "
                    f"{cfg.arch!r} — wrong checkpoint?")
            self.log(f"=> warm-started {n_p} param tensors (+{n_s} BN stats)"
                     f" from {cfg.pretrained}"
                     + (f"; fresh init kept for {skipped}" if skipped else ""))

        # ceil: the sampler pads to full batches, so an epoch really runs
        # ceil(N/batch) optimizer steps — floor would fire LR decay early
        self.steps_per_epoch = max(1, -(-len(self.train_ds) // cfg.batch_size))
        self.schedule = step_decay_schedule(
            cfg.scaled_lr(jax.device_count() if cfg.lr_scale_by_world else 1),
            self.steps_per_epoch, cfg.lr_step_epochs)
        if cfg.optimizer == "fused_sgd":  # validated at __init__ entry
            from tpu_dist.ops.pallas_sgd import FusedSGD
            self.tx = FusedSGD(self.schedule, cfg.momentum, cfg.weight_decay,
                               interpret=jax.default_backend() == "cpu")
        else:
            self.tx = make_optimizer(
                cfg.lr, cfg.momentum, cfg.weight_decay, self.steps_per_epoch,
                cfg.lr_step_epochs, schedule=self.schedule,
                kind=cfg.optimizer, b1=cfg.adam_b1, b2=cfg.adam_b2,
                eps=cfg.adam_eps)
        loss_scale = (LossScaleState.create(cfg.loss_scale)
                      if cfg.loss_scale else None)
        state = TrainState.create(params, batch_stats, self.tx, loss_scale)
        # replicate state across the mesh explicitly
        self.state = jax.device_put(state, replicated(self.mesh))

        augment = self.train_ds.name.startswith(("imagenet", "synth-imagenet"))
        self.transform = make_transform(
            self.train_ds.mean, self.train_ds.std, augment=augment,
            dtype=self.policy.compute_dtype)
        eval_transform = make_transform(
            self.val_ds.mean, self.val_ds.std, augment=False,
            dtype=self.policy.compute_dtype)

        # gradient accumulation: split each global batch into N sequential
        # microbatches whose grads average into ONE optimizer step (steps.py
        # make_grad_accum_train_step) — for global batches beyond HBM
        self.accum = cfg.grad_accum_steps
        if self.accum < 1:
            raise ValueError("grad_accum_steps must be >= 1")
        if self.accum > 1 and cfg.variant != "jit":
            raise ValueError("grad_accum_steps > 1 requires variant='jit'")
        if cfg.adasum and cfg.variant != "shard_map":
            # the Adasum operator lives in the explicit-collective engine;
            # silently averaging instead would misreport the run's math
            raise ValueError("adasum requires variant='shard_map'")
        if cfg.adasum and cfg.grad_compression != "none":
            raise ValueError("adasum replaces the compressed-mean allreduce; "
                             "use grad_compression='none' with it")
        if self.accum > 1 and cfg.steps_per_dispatch > 1:
            raise ValueError("grad_accum_steps and steps_per_dispatch > 1 "
                             "are mutually exclusive")
        if self.accum > 1 and cfg.batch_size % (self.accum * ndev):
            raise ValueError(
                f"global batch {cfg.batch_size} not divisible by "
                f"grad_accum_steps x device count ({self.accum} x {ndev})")

        if self.accum > 1:
            from tpu_dist.engine.steps import make_grad_accum_train_step
            self.train_step = make_grad_accum_train_step(
                self.model, self.tx, self.transform, self.mesh,
                health=cfg.health)
        elif cfg.variant == "shard_map":
            # ring TP trains through a tp_impl='ring' CLONE (identical
            # params — parallel.overlap); init/eval/checkpoints keep the
            # plain model, which the replicated params drive unchanged
            train_model = (self.model.clone(tp_impl=cfg.tp_impl)
                           if cfg.tp_impl != "gspmd" else self.model)
            self.train_step = make_shard_map_train_step(
                train_model, self.tx, self.transform, self.mesh,
                grad_compression=cfg.grad_compression,
                predivide_factor=cfg.gradient_predivide_factor,
                adasum=cfg.adasum,
                grad_bucket_mb=cfg.grad_bucket_mb,
                model_axis="model" if cfg.tp_impl == "ring" else None,
                health=cfg.health)
        else:
            self.train_step = make_train_step(
                self.model, self.tx, self.transform, self.mesh,
                health=cfg.health)
        self.eval_step = make_eval_step(self.model, eval_transform, self.mesh)

        # K-steps-per-dispatch window (VERDICT r1 #3: the bench's multi-step
        # machinery wired into real training). Math is identical to K
        # sequential dispatches; only the host round-trip count changes.
        self.k = cfg.steps_per_dispatch
        if self.k < 1:
            raise ValueError("steps_per_dispatch must be >= 1")
        if self.k > 1 and cfg.variant != "jit":
            raise ValueError("steps_per_dispatch > 1 requires variant='jit'")
        if cfg.data_placement not in ("auto", "host", "device"):
            raise ValueError(f"unknown data_placement {cfg.data_placement!r}")
        in_memory = isinstance(getattr(self.train_ds, "images", None), np.ndarray)
        if cfg.data_placement == "device" and not in_memory:
            raise ValueError("data_placement='device' needs an in-memory "
                             "(ArrayDataset) training set")
        if cfg.data_placement == "device" and self.accum > 1:
            # the indexed window step has no microbatch loop; accumulation
            # rides the host-fed per-batch path
            raise ValueError("grad_accum_steps > 1 requires "
                             "data_placement='host' or 'auto'")
        if cfg.data_placement == "device" and cfg.variant != "jit":
            # the indexed window step is compiler-partitioned; routing a
            # shard_map config through it would silently drop grad
            # compression/predivide and per-replica BN semantics
            raise ValueError("data_placement='device' requires variant='jit'")
        # budget covers BOTH splits when the val set can ride along into HBM
        # (in-memory, same image shape — the upload gate below)
        val_rides = (in_memory and
                     isinstance(getattr(self.val_ds, "images", None),
                                np.ndarray)
                     and self.val_ds.image_shape == self.train_ds.image_shape)
        data_bytes = (self.train_ds.images.nbytes
                      + (self.val_ds.images.nbytes if val_rides else 0)
                      ) if in_memory else 0
        fits_hbm = (in_memory and data_bytes
                    <= int(os.environ.get("TPU_DIST_DEVICE_DATA_MAX",
                                          str(1 << 30))))
        self.device_data = (cfg.data_placement == "device" or
                            (cfg.data_placement == "auto" and fits_hbm
                             and self.k > 1))
        self._train_data_dev = None
        self._val_data_dev = None
        self._prefetched_windows = None  # (epoch, [(n, device idx window)])
        if self.device_data:
            # whole training set resident in HBM (rows packed into i32 words
            # for native 32-bit gathers), replicated per chip; per-step
            # batches are gathered on device from an index window
            from tpu_dist.engine.steps import (make_indexed_eval_step,
                                               pack_images_for_device)
            self._train_data_dev = (
                jax.device_put(pack_images_for_device(self.train_ds.images),
                               replicated(self.mesh)),
                jax.device_put(self.train_ds.labels.astype(np.int32),
                               replicated(self.mesh)))
            self.window_step = make_indexed_multi_train_step(
                self.model, self.tx, self.transform, self.mesh,
                self.train_ds.image_shape, health=cfg.health)
            # the val set rides along in HBM too (same placement rules):
            # the whole distributed eval becomes ONE dispatch per epoch
            if val_rides:
                self._val_data_dev = (
                    jax.device_put(pack_images_for_device(self.val_ds.images),
                                   replicated(self.mesh)),
                    jax.device_put(self.val_ds.labels.astype(np.int32),
                                   replicated(self.mesh)))
                self.window_eval_step = make_indexed_eval_step(
                    self.model, eval_transform, self.mesh,
                    self.val_ds.image_shape)
        elif self.k > 1:
            self.window_step = make_multi_train_step(
                self.model, self.tx, self.transform, self.mesh,
                health=cfg.health)

        self.batch_sharding = batch_sharding(self.mesh)
        self.best_acc1 = 0.0
        self.start_epoch = cfg.start_epoch
        self._skip_batches = 0
        self.is_main = jax.process_index() == 0
        # geometry stamped into every checkpoint: resume math (step ->
        # epoch/skip mapping, LR schedule) is only valid against the same
        # steps_per_epoch, and the blob only loads correctly into the same
        # model/dataset shapes (flax from_bytes does NOT validate them) —
        # mismatches must not pass silently
        self._run_meta = {"steps_per_epoch": self.steps_per_epoch,
                          "batch_size": cfg.batch_size,
                          "dataset_len": len(self.train_ds),
                          "arch": cfg.arch,
                          "dataset": self.train_ds.name,
                          "num_classes": self.num_classes,
                          "image_shape": list(self.train_ds.image_shape)}

        if cfg.resume:
            # hard geometry first, from the meta header alone: a wrong-arch
            # blob fails inside flax from_bytes with an opaque structure
            # mismatch, so the clear error must fire BEFORE deserialization
            pre = ckpt.read_checkpoint_meta(cfg.resume)
            hard_pre = {k: (pre[k], v) for k, v in self._run_meta.items()
                        if k in ("arch", "num_classes", "image_shape")
                        and k in pre and pre[k] != v}
            if hard_pre:
                raise ValueError(
                    "--resume checkpoint is from a different model geometry ("
                    + ", ".join(f"{k}: checkpoint {a} vs run {b}"
                                for k, (a, b) in hard_pre.items()) + ")")
            self.state, meta = ckpt.load_checkpoint(cfg.resume, state)
            self.state = jax.device_put(self.state, replicated(self.mesh))
            self.start_epoch = meta.get("epoch", 0)
            self.best_acc1 = meta.get("best_acc1", 0.0)
            self.log(f"=> resumed from {cfg.resume} (epoch {self.start_epoch})")
            mismatch = {k: (meta[k], v) for k, v in self._run_meta.items()
                        if k in meta and meta[k] != v}
            detail = ", ".join(f"{k}: checkpoint {a} vs run {b}"
                               for k, (a, b) in mismatch.items())
            # model/input identity: the blob would load into wrong-shaped
            # arrays without any error from flax (or train a wrong-width
            # head) — always fatal
            hard = {"arch", "num_classes", "image_shape"} & mismatch.keys()
            if hard:
                raise ValueError(
                    f"--resume checkpoint is from a different model geometry "
                    f"({detail})")
            if mismatch:
                if meta.get("mid_epoch"):
                    # the skip count below would misplace the resume point:
                    # double-applied or skipped batches + LR-schedule drift
                    raise ValueError(
                        "mid-epoch resume requires the checkpoint's data/"
                        f"batch geometry ({detail})")
                self.log(f"warning: resume with changed geometry ({detail}); "
                         "the LR schedule will not line up with the original run")
            # mid-epoch (interrupt) checkpoint: the sampler's per-epoch
            # permutation is deterministic, so resume is STEP-exact — derive
            # the true epoch from the step counter and skip the batches whose
            # updates are already in the state (no double-applied gradients,
            # no LR-schedule drift). Covers interrupts during validation too
            # (training complete -> next epoch, zero skips). The reference
            # had no resume at all.
            if meta.get("mid_epoch"):
                step_done = int(jax.device_get(self.state.step))
                self.start_epoch = step_done // self.steps_per_epoch
                self._skip_batches = step_done % self.steps_per_epoch
                if self._skip_batches:
                    self.log(f"=> mid-epoch checkpoint: resuming epoch "
                             f"{self.start_epoch}, skipping "
                             f"{self._skip_batches} already-applied batches")
        # checkpoint-less dp-pure recovery (round 13): on a supervisor
        # mesh re-expansion (TPU_DIST_PEER_RESUME), adopt a survivor's
        # live replicated state over a broadcast collective — the joining
        # host has no local checkpoint, and the consensus renumbering
        # keeps process 0 a survivor. Replicated (pure-dp) layouts only.
        self._dp_pure = all(s == 1 for n, s in self.mesh.shape.items()
                            if n != "data")
        self._peer_restored = False
        if os.environ.get("TPU_DIST_PEER_RESUME") == "1" and self._dp_pure:
            host_state, did = ckpt.peer_restore_state(self.state)
            if did:
                self._peer_restored = True
                self.state = jax.device_put(host_state,
                                            replicated(self.mesh))
                # epoch/skip re-derive from the adopted step counter —
                # the same math as a mid-epoch resume
                step_done = int(np.asarray(host_state.step))
                self.start_epoch = step_done // self.steps_per_epoch
                self._skip_batches = step_done % self.steps_per_epoch
                self.log(f"=> peer-restored state from a survivor at step "
                         f"{step_done} (no disk round-trip); resuming "
                         f"epoch {self.start_epoch}")
        self._epoch_in_progress = self.start_epoch
        self._program_hbm = None    # post-dispatch probe (telemetry contract)
        self._program_flops = None  # per-device step FLOPs (XLA cost model)
        # run observability: ledger + step tracer + skew monitor + hang
        # watchdog, wired from cfg (obs.RunObs); a pathless ledger is free
        self.obs = RunObs("image", cfg, self.mesh, unit="img/s",
                          plan_info=self._plan_info)
        # program audit (tpu_dist.analysis.proglint via plan.compile):
        # armed here so the compile-time pass and the drain-boundary
        # recompile sentry see every program this run builds
        from tpu_dist.plan.compile import set_audit
        set_audit(cfg.audit, self.obs.ledger)
        # whether int8 matmuls (vit_* quant archs) route through the fused
        # Pallas kernel — trace-time static; stamped into step records so
        # ledger_report can attribute MFU deltas (LMTrainer twin)
        from tpu_dist.ops.quant import fused_quant_active
        self._fused_quant = cfg.quant == "int8" and fused_quant_active()

    # ------------------------------------------------------------------
    def log(self, *a, **k):
        # getattr: log is callable from __init__ before is_main is set
        if getattr(self, "is_main", jax.process_index() == 0):
            print(*a, **k, flush=True)

    def _sampler(self, ds, train: bool, epoch: int) -> DistributedSampler:
        sampler = DistributedSampler(
            len(ds), num_replicas=jax.process_count(),
            rank=jax.process_index(), shuffle=train,
            seed=(self.cfg.seed or 0) + (17 if not train else 0),
            batch_size=self.local_batch)
        sampler.set_epoch(epoch)
        return sampler

    def _loader(self, ds, train: bool, epoch: int) -> DataLoader:
        return DataLoader(ds, self._sampler(ds, train, epoch), self.local_batch,
                          workers=self.cfg.workers, emit_valid=not train)

    def _drain(self, pending, meters) -> None:
        """Pull queued device metric sums into the meter bank (ONE blocking
        transfer per print window — the async-dispatch sync point) and emit
        one ledger ``step`` record per drained entry: the device-block time
        of the transfer is apportioned across the window's steps, so every
        record carries the full data/dispatch/device phase breakdown. The
        fused health probes (obs.health) ride the same fetch; the sentry
        consumes them here — under ``skip`` a non-finite record is kept
        out of the meter averages (its update was already zeroed on
        device), and under ``halt`` the sentry raises out of the loop."""
        import math

        with self.obs.tracer.span("device"):
            # distlint: disable=DL002 -- THE drain boundary: the one sanctioned fetch point of the loop
            fetched = jax.device_get([m for m, _ in pending])
        device_s = self.obs.tracer.pop().get("device", 0.0)
        total_steps = sum(info["n_steps"] for _, info in pending) or 1
        from tpu_dist.utils.telemetry import device_memory_stats
        hbm = device_memory_stats()
        for m, (_, info) in zip(fetched, pending):
            cnt = float(m["count"])
            loss = float(m["loss_sum"]) / cnt
            acc1 = float(m["correct1"]) / cnt
            # under 'skip' the non-finite step's update was zeroed on
            # device, so its NaN loss must not poison the epoch averages;
            # under 'record'/'halt' the NaN flows through — divergence
            # should be VISIBLE in the printed loss, as it always was
            if math.isfinite(loss) or self.obs.health.policy != "skip":
                meters.update("Loss", loss, int(cnt))
                meters.update("Acc@1", acc1, int(cnt))
                meters.update("Acc@5", float(m["correct5"]) / cnt, int(cnt))
            n = info["n_steps"]
            share = device_s * n / total_steps
            gn = float(m["grad_norm"]) / n
            nf = float(m["nonfinite_count"])
            un = float(m["update_norm"]) / n
            self.obs.step(
                info["step"], loss, info["n_items"],
                wall_s=info["data_s"] + info["dispatch_s"] + share,
                data_s=info["data_s"], dispatch_s=info["dispatch_s"],
                device_s=share, device_flops=self._program_flops,
                steps_in_dispatch=n,
                warm=info.get("warm", False), fused=self._fused_quant,
                acc1=acc1,
                grad_norm=gn, nonfinite_count=nf, update_norm=un,
                hbm_bytes_in_use=hbm.get("bytes_in_use"),
                hbm_peak_bytes=hbm.get("peak_bytes_in_use"))
            self.obs.health.observe(info["step"], loss, nonfinite=nf,
                                    grad_norm=gn, update_norm=un, n_steps=n)
        pending.clear()
        self.obs.heartbeat()  # watchdog: device progress proven at this sync
        # recompile sentry (PL005): a host-only trace-cache counter read
        # at the sanctioned boundary — no device sync rides on it
        from tpu_dist.plan.compile import check_audit_sentry
        check_audit_sentry()

    def _apply_nan_fault(self) -> None:
        """The ``nan_batch`` injection effect (obs.faults): pixel inputs
        are uint8, so the numeric fault lands on the param tree — the next
        step's loss/grads go non-finite exactly as a NaN batch would make
        them, and the health sentry/policy takes it from there."""
        self.state = self.state.replace(
            params=faults.poison_params(self.state.params))

    def _preempt_snapshot(self, pending=None, meters=None) -> None:
        """Coordinated snapshot on preemption (round 13): the drain blocks
        until the in-flight dispatched steps land, then a consistent
        checkpoint commits through the CRC/keep-K container (the
        collective gather inside save_checkpoint is the cross-host
        barrier for sharded state) and the process exits ``PREEMPT_SNAPSHOT_RC`` — the supervisor
        classifies ``preemption_snapshotted`` and the restart resumes
        from THIS step, not the last periodic checkpoint."""
        cfg = self.cfg
        if pending:
            self._drain(pending, meters)
        self.obs.pause()  # the snapshot write is not a stall
        # distlint: disable=DL002 -- preemption boundary: one scalar fetch after the final drain
        step_done = int(jax.device_get(self.state.step))
        try:
            mesh_epoch = int(os.environ.get("TPU_DIST_MESH_EPOCH", "0") or 0)
        except ValueError:
            mesh_epoch = 0
        if cfg.checkpoint_dir:
            # cross-host consistency comes from save_checkpoint itself:
            # sharded states gather via a COLLECTIVE (every live host
            # blocks in it — the barrier), replicated dp state is in
            # per-step lockstep so process 0's replica IS the global cut.
            # No explicit sync_global_devices here: on a shrink-triggered
            # SIGTERM the lost host would never arrive and the barrier
            # would hang every survivor into its SIGKILL deadline.
            t0_ck = time.time()
            ckpt.save_checkpoint(
                cfg.checkpoint_dir, self.state, self._epoch_in_progress,
                self.best_acc1, cfg.arch, is_best=False,
                extra_meta={"mid_epoch": True, "preempt": True,
                            **self._run_meta},
                keep=cfg.keep_checkpoints)
            self.obs.ledger.emit(
                "ckpt", epoch=self._epoch_in_progress,
                path=cfg.checkpoint_dir, is_best=False,
                seconds=round(time.time() - t0_ck, 6), preempt=True)
        self.obs.ledger.emit(
            "scale", action="preempt_snapshot",
            processes=jax.process_count(), epoch=mesh_epoch, step=step_done)
        self.log(f"preempted ({self.obs.preempt_source}, deadline "
                 f"{self.obs.preempt_deadline_s}s): snapshot at step "
                 f"{step_done} — exiting for supervised resume")
        self.obs.run_end(status="preempted", snapshot_step=step_done,
                         best_acc1=self.best_acc1)
        raise SystemExit(PREEMPT_SNAPSHOT_RC)

    # ------------------------------------------------------------------
    def train_epoch(self, epoch: int) -> Dict[str, float]:
        if self.k > 1 or self.device_data:
            return self._train_epoch_windowed(epoch)
        cfg = self.cfg
        loader = self._loader(self.train_ds, True, epoch)
        nb = len(loader)
        meters = MeterBank(nb, [("Time", "6.3f"), ("Data", "6.3f"),
                                ("Loss", ".4e"), ("Acc@1", "6.3f"),
                                ("Acc@5", "6.3f")],
                           prefix=f"Epoch: [{epoch}]")
        skip = self._skip_batches
        self._skip_batches = 0
        self.obs.resume()  # watchdog watches from epoch entry
        pending = []
        end = time.time()
        if self.accum > 1:
            # host-side split into (N, B/N, ...) microbatches; sharded
            # (None, 'data') so every microbatch spans all devices
            n = self.accum

            def split(b):
                imgs, lbls = b
                return (imgs.reshape(n, -1, *imgs.shape[1:]),
                        lbls.reshape(n, -1))

            micro_sh = NamedSharding(self.mesh, P(None, "data"))
            it = prefetch_to_device(map(split, iter(loader)), micro_sh)
        else:
            it = prefetch_to_device(iter(loader), self.batch_sharding)
        tr = self.obs.tracer
        for i, (images, labels) in enumerate(it):
            if i < skip:  # step-exact resume of a mid-epoch checkpoint
                end = time.time()
                continue
            data_s = time.time() - end
            meters.update("Data", data_s)
            gstep = epoch * self.steps_per_epoch + i
            effects = self.obs.fire_step_faults(gstep)
            if "nan_batch" in effects:
                self._apply_nan_fault()
            if "preempt_deadline" in effects:
                self.obs.request_preemption(
                    deadline_s=effects["preempt_deadline"].args.get("secs"),
                    source="fault")
            if self.obs.preempt_pending():
                self._preempt_snapshot(pending, meters)  # raises SystemExit
            was_cold = self._program_hbm is None  # this dispatch compiles
            with step_annotation(gstep, self.obs.profiling), \
                    tr.span("dispatch"):
                self.state, metrics = self.train_step(
                    self.state, images, labels, self.rng)
            dispatch_s = tr.pop().get("dispatch", 0.0)
            if self._program_hbm is None:
                # static per-program peak + step FLOPs (CSV column / MFU;
                # lower() is abstract, so donation is untouched). Probed
                # AFTER the dispatch just above: the AOT compile would not
                # seed jit's dispatch cache, so probing first would compile
                # the step twice (utils.telemetry.program_stats contract) —
                # and probing post-dispatch in the SAME iteration means
                # even a single-dispatch run still records the column
                from tpu_dist.plan.compile import audit_mode, audit_program
                from tpu_dist.utils.telemetry import program_stats
                st = program_stats(self.train_step, self.state, images,
                                   labels, self.rng,
                                   with_hlo=bool(self.obs.ledger.path)
                                   or audit_mode() != "none")
                self._program_hbm = st["hbm_bytes"] or False
                self._program_flops = st["flops"]
                self.obs.ledger.emit("compile", program="train_step",
                                     hbm_bytes=st["hbm_bytes"],
                                     flops=st["flops"])
                # compile-time audit pass against the SAME lowered
                # artifact (plan.compile.audit_program) — a no-op under
                # audit=none, one 'audit' ledger event per program else
                audit_program("train_step", self.train_step, self.state,
                              images, labels, self.rng, hlo=st.get("hlo"),
                              precision=cfg.precision)
                if st.get("hlo"):
                    # static cost attribution of the same executable (one
                    # lower for hbm/flops/buckets — obs.attr); feeds the
                    # ledger_report roofline section
                    from tpu_dist.obs.attr import emit_cost_model
                    emit_cost_model(self.obs.ledger, "train_step",
                                    st["hlo"], xla_flops=st["flops"])
            pending.append((metrics, {
                "step": gstep, "n_steps": 1, "n_items": cfg.batch_size,
                "data_s": data_s, "dispatch_s": dispatch_s,
                "warm": was_cold}))
            boundary = i % cfg.print_freq == 0 or i == nb - 1
            if boundary:
                self._drain(pending, meters)
            # every iteration, so avg(Time) = wall/batches; under async
            # dispatch the device wait lands on boundary iterations (the
            # device_get above) and non-boundary Time is dispatch-only
            meters.update("Time", time.time() - end)
            if boundary and self.is_main:
                meters.display(i)
            end = time.time()
        self.obs.pause()  # eval/ckpt follow: step completions stop by design
        snap = meters.snapshot()  # ONE read feeds printer, ledger, and return
        return {"loss": snap["Loss"]["avg"], "top1": snap["Acc@1"]["avg"],
                "top5": snap["Acc@5"]["avg"], "batches": nb - skip}

    def _host_windows(self, loader, skip: int):
        """Yield (n_batches, (imgs (K,B,...), lbls (K,B))) host-stacked
        windows, skipping the first ``skip`` batches (step-exact resume). A
        short tail yields a smaller window (jit retraces once per K)."""
        it = iter(loader)
        for _ in range(skip):
            next(it)
        while True:
            stack = []
            for batch in it:
                stack.append(batch)
                if len(stack) == self.k:
                    break
            if not stack:
                return
            imgs = np.stack([b[0] for b in stack])
            lbls = np.stack([b[1] for b in stack])
            yield len(stack), (imgs, lbls)

    def _epoch_indices(self, ds, train: bool, epoch: int):
        """THE sampler->(nb, local_batch) index layout shared by the windowed
        train path and the one-dispatch eval (they must never diverge: the
        sampler's batch-blocked ordering is load-bearing for N-process
        bit-exactness). Returns (idx (nb,B) i32, valid (nb,B) f32)."""
        sampler = self._sampler(ds, train, epoch)
        idx, valid = sampler.indices_with_valid()
        nb = sampler.num_samples // self.local_batch
        n = nb * self.local_batch
        shape = (nb, self.local_batch)
        return (np.asarray(idx[:n], np.int32).reshape(shape),
                np.asarray(valid[:n], np.float32).reshape(shape))

    def _streamed_host_windows(self, loader, skip: int, put):
        """(n, device window) items via a BOUNDED background pipeline
        (tpu_dist.data.loader.stream_prefetch): the producer thread
        assembles window w+1's uint8 batches and dispatches their
        host->device upload while window w trains — the epoch-prefetch
        trick (device mode's index uploads) applied to pixel windows, for
        datasets too large for HBM residency (ImageNet-224 scale)."""
        from tpu_dist.data.loader import stream_prefetch

        return stream_prefetch(
            (n, put(p)) for n, p in self._host_windows(loader, skip))

    def _device_windows(self, epoch: int, skip: int, put):
        """(K,B) index windows for the HBM-resident dataset, already ON
        device. The transfers are dispatched asynchronously here, so calling
        this for epoch e+1 while epoch e's validation runs hides the
        host->device index upload entirely (epoch-granularity prefetch)."""
        batches, _ = self._epoch_indices(self.train_ds, True, epoch)
        batches = batches[skip:]
        return [(len(w), put(np.ascontiguousarray(w)))
                for w in (batches[i:i + self.k]
                          for i in range(0, len(batches), self.k))]

    def _train_epoch_windowed(self, epoch: int) -> Dict[str, float]:
        """K-steps-per-dispatch epoch (VERDICT r1 #3): same math as the
        per-batch loop, ~1/K the host round-trips, and (device mode) only
        index windows cross the host->device link."""
        cfg = self.cfg
        nb = self.steps_per_epoch  # == len(loader): sampler pads to batches
        meters = MeterBank(nb, [("Time", "6.3f"), ("Data", "6.3f"),
                                ("Loss", ".4e"), ("Acc@1", "6.3f"),
                                ("Acc@5", "6.3f")],
                           prefix=f"Epoch: [{epoch}]")
        skip = self._skip_batches
        self._skip_batches = 0
        self.obs.resume()  # watchdog watches from epoch entry
        win_sh = NamedSharding(self.mesh, P(None, "data"))
        put = partial(assemble_global, win_sh)
        if self.device_data:
            def dispatch(state, dev_payload):
                return self.window_step(state, *self._train_data_dev,
                                        dev_payload, self.rng)

            cached = self._prefetched_windows
            self._prefetched_windows = None
            if cached is not None and cached[0] == epoch and skip == 0:
                windows = cached[1]
            else:
                windows = self._device_windows(epoch, skip, put)
        else:
            def dispatch(state, dev_payload):
                return self.window_step(state, *dev_payload, self.rng)

            loader = self._loader(self.train_ds, True, epoch)
            windows = self._streamed_host_windows(loader, skip, put)

        pending = []  # window metric sums awaiting the next print boundary
        done = skip
        last_print = skip - 1
        tr = self.obs.tracer
        end = time.time()
        for n, dev_payload in windows:
            # per-BATCH seconds (window seconds / n, weighted n) so the
            # printed avg keeps the per-batch path's meaning:
            # avg(Time) = wall / batches in both paths
            data_s = time.time() - end
            meters.update("Data", data_s / n, n)
            effects = self.obs.fire_step_faults(
                epoch * self.steps_per_epoch + done)
            if "nan_batch" in effects:
                self._apply_nan_fault()
            if "preempt_deadline" in effects:
                self.obs.request_preemption(
                    deadline_s=effects["preempt_deadline"].args.get("secs"),
                    source="fault")
            if self.obs.preempt_pending():
                self._preempt_snapshot(pending, meters)  # raises SystemExit
            was_cold = self._program_hbm is None  # this dispatch compiles
            with step_annotation(epoch * self.steps_per_epoch + done,
                                 self.obs.profiling), tr.span("dispatch"):
                self.state, metrics = dispatch(self.state, dev_payload)
            dispatch_s = tr.pop().get("dispatch", 0.0)
            if self._program_hbm is None:
                # post-dispatch probe (same iteration, so single-window
                # runs record it too): see telemetry.program_stats; the
                # cost model counts the scan body once, so flops ~= ONE
                # optimizer step of the window program
                from tpu_dist.plan.compile import audit_mode, audit_program
                from tpu_dist.utils.telemetry import program_stats
                args = ((*self._train_data_dev, dev_payload, self.rng)
                        if self.device_data else (*dev_payload, self.rng))
                st = program_stats(self.window_step, self.state, *args,
                                   with_hlo=bool(self.obs.ledger.path)
                                   or audit_mode() != "none")
                self._program_hbm = st["hbm_bytes"] or False
                self._program_flops = st["flops"]
                self.obs.ledger.emit("compile", program="window_step",
                                     hbm_bytes=st["hbm_bytes"],
                                     flops=st["flops"])
                # same-artifact compile-time audit (plan.compile)
                audit_program("window_step", self.window_step, self.state,
                              *args, hlo=st.get("hlo"),
                              precision=cfg.precision)
                if st.get("hlo"):
                    # static cost attribution (obs.attr), same executable
                    from tpu_dist.obs.attr import emit_cost_model
                    emit_cost_model(self.obs.ledger, "window_step",
                                    st["hlo"], xla_flops=st["flops"])
            done += n
            pending.append((metrics, {
                "step": epoch * self.steps_per_epoch + done - 1,
                "n_steps": n, "n_items": n * cfg.batch_size,
                "data_s": data_s, "dispatch_s": dispatch_s,
                "warm": was_cold}))
            boundary = (done - 1) - last_print >= cfg.print_freq or done == nb
            if boundary and done == nb and self.device_data \
                    and epoch + 1 < cfg.epochs:
                # queue next epoch's index uploads BEFORE blocking on this
                # epoch's metrics: they land during drain/validate/checkpoint
                self._prefetched_windows = (
                    epoch + 1, self._device_windows(epoch + 1, 0, put))
            if boundary:
                self._drain(pending, meters)
                last_print = done - 1
            meters.update("Time", (time.time() - end) / n, n)
            if boundary and self.is_main:
                meters.display(done - 1)
            end = time.time()
        self.obs.pause()  # eval/ckpt follow: step completions stop by design
        snap = meters.snapshot()
        return {"loss": snap["Loss"]["avg"], "top1": snap["Acc@1"]["avg"],
                "top5": snap["Acc@5"]["avg"], "batches": nb - skip}

    def validate(self, epoch: int = 0) -> float:
        """Distributed eval (C15): metric sums psum'd across replicas, padding
        masked out, exact division by the true sample count. device_get
        happens ONCE after the loop so eval batches pipeline (async dispatch),
        unlike the reference's per-batch barrier+allreduce. With an
        HBM-resident val set the whole eval is ONE dispatch."""
        t0_eval = time.time()  # exact eval badput for the goodput ledger
        if self._val_data_dev is not None:
            idx, valid = self._epoch_indices(self.val_ds, False, epoch)
            win_sh = NamedSharding(self.mesh, P(None, "data"))
            idx_d = assemble_global(win_sh, np.ascontiguousarray(idx))
            valid_d = assemble_global(win_sh, np.ascontiguousarray(valid))
            # distlint: disable=DL002 -- one-dispatch eval: the eval drain boundary
            m = jax.device_get(self.window_eval_step(
                self.state.params, self.state.batch_stats,
                *self._val_data_dev, idx_d, valid_d))
            sums = {k: float(m[k]) for k in
                    ("loss_sum", "correct1", "correct5", "count")}
        else:
            loader = self._loader(self.val_ds, False, epoch)
            pending = []
            it = prefetch_to_device(iter(loader), self.batch_sharding)
            for images, labels, valid in it:
                pending.append(self.eval_step(
                    self.state.params, self.state.batch_stats, images, labels,
                    valid))
            sums = {"loss_sum": 0.0, "correct1": 0.0, "correct5": 0.0,
                    "count": 0.0}
            # distlint: disable=DL002 -- eval drain boundary: pending eval metrics fetched in one batch
            for m in jax.device_get(pending):
                for k in sums:
                    sums[k] += float(m[k])
        n = max(sums["count"], 1.0)
        acc1 = sums["correct1"] / n
        acc5 = sums["correct5"] / n
        self.obs.ledger.emit("eval", epoch=epoch, loss=sums["loss_sum"] / n,
                             acc1=acc1, acc5=acc5, count=int(sums["count"]),
                             seconds=round(time.time() - t0_eval, 6))
        self.log(f" * Acc@1 {acc1 * 100:.3f} Acc@5 {acc5 * 100:.3f} "
                 f"Loss {sums['loss_sum'] / n:.4f}")
        return acc1

    # ------------------------------------------------------------------
    def fit(self) -> float:
        cfg = self.cfg
        # SIGTERM becomes a snapshot request this loop drains at its next
        # step boundary (the coordinated-preemption contract)
        self.obs.enable_preempt_snapshot()
        self.obs.run_start()
        if self._peer_restored:
            try:
                mesh_epoch = int(
                    os.environ.get("TPU_DIST_MESH_EPOCH", "0") or 0)
            except ValueError:
                mesh_epoch = 0
            self.obs.ledger.emit(
                "scale", action="peer_restore",
                processes=jax.process_count(), epoch=mesh_epoch)
        if cfg.evaluate:
            try:
                return self.validate()
            finally:
                self.obs.run_end(best_acc1=self.best_acc1)
        stop_telemetry = None
        if cfg.telemetry_csv:
            # EVERY process samples (multi-host skew forensics need the
            # straggler's memory timeline too); non-main paths are
            # .pN-suffixed so files never clobber (obs.per_process_path)
            from tpu_dist.obs import per_process_path
            from tpu_dist.utils.telemetry import start_hbm_sampler
            stop_telemetry = start_hbm_sampler(
                per_process_path(cfg.telemetry_csv, jax.process_index()),
                ledger=self.obs.ledger)
        try:
            # device tracing (reference's only profiling was wall-clock CSVs
            # + nvidia-smi sampling, statistics.sh:1-4; the TPU-native answer
            # is a real XLA trace — obs.profile_session flushes it even on
            # OOM/interrupt: a failing run is exactly the one worth
            # profiling)
            with profile_session(cfg.profile_dir, self.obs.profiling):
                self._fit_epochs()
        except HealthError:
            # a halt must never abandon an in-flight async write: join this
            # dir's writer before re-raising, surfacing any write failure
            # as a warning rather than masking the halt itself
            try:
                ckpt.wait_for_async_save(cfg.checkpoint_dir or None)
            except RuntimeError as we:
                self.log(f"warning: async checkpoint write failed during "
                         f"health halt: {we}")
            raise
        except KeyboardInterrupt:
            self.obs.pause()  # slow interrupt-save is not a stall
            # strictly better than the reference (no try/except around its
            # training at all, SURVEY.md §5 'Failure detection'): an interrupt
            # leaves a resumable checkpoint instead of losing the run
            ckpt.save_checkpoint(cfg.checkpoint_dir, self.state,
                                 self._epoch_in_progress, self.best_acc1,
                                 cfg.arch, is_best=False,
                                 extra_meta={"mid_epoch": True,
                                             **self._run_meta},
                                 keep=cfg.keep_checkpoints)
            self.log(f"interrupted — checkpoint saved at epoch "
                     f"{self._epoch_in_progress}; resume with --resume")
            raise
        finally:
            if stop_telemetry is not None:
                stop_telemetry()
            ckpt.wait_for_async_save()  # never exit with a write in flight
            self.obs.run_end(best_acc1=self.best_acc1)
        return self.best_acc1

    def _fit_epochs(self) -> None:
        cfg = self.cfg
        for epoch in range(self.start_epoch, cfg.epochs):
            self._epoch_in_progress = epoch
            if self.obs.preempt_pending():
                # SIGTERM landed during the previous eval/checkpoint span
                self._preempt_snapshot()
            t0 = time.time()
            train_metrics = self.train_epoch(epoch)
            train_secs = time.time() - t0
            acc1 = self.validate(epoch)
            epoch_secs = time.time() - t0
            # end-to-end train-phase rate (loader + dispatch + device), the
            # number the bench's device rate is compared against in
            # BASELINE.md; counts only batches actually trained (a resumed
            # mid-epoch runs fewer than steps_per_epoch)
            train_imgs = train_metrics.get(
                "batches", self.steps_per_epoch) * cfg.batch_size
            train_ips = train_imgs / max(train_secs, 1e-9)
            is_best = acc1 > self.best_acc1
            self.best_acc1 = max(acc1, self.best_acc1)
            # the epoch record; the legacy CSV row (reference format
            # [wall start, epoch seconds] + train-img/s and peak-HBM
            # columns, VERDICT r4 #5) renders from THIS event via the
            # EpochCsvSink the obs layer registered — one source of truth.
            # hbm: allocator truth when the backend exposes it, else XLA's
            # static per-program analysis (empty when neither exists)
            from tpu_dist.utils.telemetry import peak_hbm_bytes
            self.obs.ledger.emit(
                "epoch", epoch=epoch, start_ts=t0, seconds=epoch_secs,
                throughput=train_ips, unit="img/s",
                loss=train_metrics["loss"], acc1=acc1,
                hbm_bytes=peak_hbm_bytes() or self._program_hbm or None,
                batches=train_metrics.get("batches"))
            # async: serialization + disk write overlap the next epoch (the
            # device->host gather stays on the critical path by necessity);
            # the goodput ledger charges only the blocking share
            t0_ck = time.time()
            ckpt.save_checkpoint(cfg.checkpoint_dir, self.state, epoch + 1,
                                 self.best_acc1, cfg.arch, is_best,
                                 extra_meta=self._run_meta, async_write=True,
                                 keep=cfg.keep_checkpoints)
            self.obs.ledger.emit(
                "ckpt", epoch=epoch + 1, path=cfg.checkpoint_dir,
                is_best=is_best, seconds=round(time.time() - t0_ck, 6))
            self.log(f"Epoch {epoch}: train_loss={train_metrics['loss']:.4f} "
                     f"val_acc1={acc1 * 100:.3f} best={self.best_acc1 * 100:.3f} "
                     f"({epoch_secs:.1f}s, train {train_ips:,.0f} img/s)")
