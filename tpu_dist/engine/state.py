"""Train state pytree.

The reference's mutable triple (model, optimizer, amp state) spread across
wrapper objects (reference 2.distributed.py:114-120, 4.apex_distributed2.py:
177-178) becomes one immutable pytree threaded through the jitted step —
the functional JAX idiom. ``batch_stats`` carries BatchNorm running stats
(torch buffers); ``loss_scale`` is the optional apex-style dynamic scale.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.struct
import jax
import jax.numpy as jnp
import optax

from tpu_dist.ops.precision import LossScaleState


@flax.struct.dataclass
class TrainState:
    step: jax.Array
    params: Any
    batch_stats: Any
    opt_state: Any
    loss_scale: Optional[LossScaleState] = None

    @classmethod
    def create(cls, params, batch_stats, tx: optax.GradientTransformation,
               loss_scale: Optional[LossScaleState] = None) -> "TrainState":
        return cls(step=jnp.int32(0), params=params, batch_stats=batch_stats,
                   opt_state=tx.init(params), loss_scale=loss_scale)


def init_model(model, rng: jax.Array, input_shape, train: bool = True):
    """Initialize params/batch_stats with a dummy batch (static shapes)."""
    dummy = jnp.zeros(input_shape, jnp.float32)
    variables = model.init({"params": rng, "dropout": rng}, dummy, train=False)
    return variables.get("params"), variables.get("batch_stats", {})
