"""Autoregressive decoding for the LM family (greedy / temperature).

The reference is a training-only cookbook; a framework user still expects to
sample from the model they trained. TPU-first constraints shape the design:

* static shapes end to end — the (B, prompt+steps) token buffer is
  allocated once and a ``lax.scan`` fills one position per tick, so the
  whole decode is ONE compiled program (no per-token host round-trip, which
  on a tunneled controller would cost ~50 ms/token);
* full-recompute attention per tick (O(steps * L^2)): causal masking makes
  positions > current length invisible to the read position, so the padded
  buffer is safe. At cookbook scales this is MXU-cheap; a KV-cache path is
  the obvious extension and slots behind the same signature;
* works with any attn_fn flavor and any mesh placement the params carry
  (replicated for decode is the normal case).
"""

from __future__ import annotations

from functools import lru_cache
from typing import Optional

import jax
import jax.numpy as jnp


def _sample(nxt_logits, temperature, rng, top_k=0, top_p=0.0):
    if temperature <= 0.0:
        return jnp.argmax(nxt_logits, axis=-1), rng
    logits = nxt_logits / temperature
    if top_k:
        # keep the k best logits per row, mask the rest (static k)
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits >= kth, logits, -jnp.inf)
    if top_p > 0.0:
        # nucleus: smallest prefix of the sorted distribution with mass >=
        # top_p stays; everything after it is masked
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep_sorted = cum - probs < top_p  # first token always kept
        cutoff = jnp.min(jnp.where(keep_sorted, sorted_logits, jnp.inf),
                         axis=-1)[:, None]
        logits = jnp.where(logits >= cutoff, logits, -jnp.inf)
    rng, sub = jax.random.split(rng)
    return jax.random.categorical(sub, logits), rng


def generate(model, params, prompt: jax.Array, steps: int,
             temperature: float = 0.0,
             rng: Optional[jax.Array] = None,
             use_cache: bool = False,
             top_k: int = 0, top_p: float = 0.0) -> jax.Array:
    """Continue ``prompt`` (B, P) int32 by ``steps`` tokens.

    temperature 0 = greedy argmax (deterministic); > 0 = categorical over
    logits/temperature, optionally truncated to the ``top_k`` best tokens
    and/or the ``top_p`` nucleus. Returns the full (B, P+steps) buffer.
    P+steps must not exceed the model's max_len.

    ``use_cache=True`` decodes through the model's per-block KV cache
    (TransformerLM ``decode=True``): each tick embeds ONE token and attends
    over the cached keys/values — O(L·d) per token instead of the
    full-recompute path's O(L²·d). Requires a cache-capable model (the
    dense TransformerLM; MoE models use the default full-recompute path).
    """
    b, p = prompt.shape
    if steps <= 0:
        # nothing to generate: return the prompt untouched (the cache
        # path's prefill would otherwise clamp its first-token write into
        # the last prompt column, and burn an rng split)
        return prompt
    total = p + steps
    if rng is None:
        rng = jax.random.PRNGKey(0)
    buf = jnp.zeros((b, total), jnp.int32).at[:, :p].set(prompt)

    if use_cache:
        cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                             _cache_shapes(model, b, total))
        decode = _cache_decode_program(model, b, p, total, temperature,
                                       top_k, top_p)
        return decode(params, cache, buf, rng)

    decode = _full_decode_program(model, b, p, total, temperature,
                                  top_k, top_p)
    return decode(params, buf, rng)


# The compiled programs are memoized per (model, geometry, sampling)
# signature: a fresh `jax.jit` closure per generate() call would make EVERY
# call retrace and recompile (jit caches by function identity) — measured at
# ~13 ms/token vs the 0.7 ms/token the compiled tick actually costs.


@lru_cache(maxsize=32)
def _cache_shapes(model, b, total):
    """KV-cache shape tree via eval_shape — no real init forward, and
    memoized so a sampling loop does not re-trace the whole model per call
    just to learn shapes that depend only on (model, b, total)."""
    return jax.eval_shape(
        lambda: model.init({"params": jax.random.PRNGKey(0)},
                           jnp.zeros((b, total), jnp.int32), train=False,
                           decode=True))["cache"]

@lru_cache(maxsize=32)
def _cache_decode_program(model, b, p, total, temperature, top_k, top_p):
    @jax.jit
    def decode(params, cache, buf, rng):
        # prefill: ONE forward over the whole prompt writes cache[0:p)
        # (the per-block dynamic_update_slice handles a (B, P, ...) write)
        # and its last position's logits sample the first generated token —
        # P times fewer ticks than feeding the prompt one token at a time
        prompt = jax.lax.dynamic_slice(buf, (0, 0), (b, p))
        logits, muts = model.apply(
            {"params": params, "cache": cache}, prompt, train=False,
            pos_offset=0, decode=True, mutable=["cache"])
        cache = muts["cache"]
        if temperature > 0.0:
            nxt, rng = _sample(logits[:, -1], temperature, rng, top_k, top_p)
        else:
            nxt = jnp.argmax(logits[:, -1], axis=-1)
        buf = jax.lax.dynamic_update_slice(
            buf, nxt[:, None].astype(jnp.int32), (0, p))

        def tick(carry, pos):
            buf, cache, rng = carry
            tok = jax.lax.dynamic_slice(buf, (0, pos), (b, 1))
            logits, muts = model.apply(
                {"params": params, "cache": cache}, tok, train=False,
                pos_offset=pos, decode=True, mutable=["cache"])
            # rng splits once per generated token, in generation order —
            # the same stream as the full-recompute path
            if temperature > 0.0:
                nxt, rng = _sample(logits[:, 0], temperature, rng,
                                   top_k, top_p)
            else:
                nxt = jnp.argmax(logits[:, 0], axis=-1)
            buf = jax.lax.dynamic_update_slice(
                buf, nxt[:, None].astype(jnp.int32), (0, pos + 1))
            return (buf, muts["cache"], rng), None

        (buf, _, _), _ = jax.lax.scan(
            tick, (buf, cache, rng), jnp.arange(p, total - 1))
        return buf

    return decode


@lru_cache(maxsize=32)
def _full_decode_program(model, b, p, total, temperature, top_k, top_p):
    @jax.jit
    def decode(params, buf, rng):
        def tick(carry, pos):
            buf, rng = carry
            logits = model.apply({"params": params}, buf, train=False)
            nxt_logits = jnp.take_along_axis(
                logits, pos[None, None, None].astype(jnp.int32)
                .repeat(b, 0), axis=1)[:, 0]          # (B, V) at position pos
            tok, rng = _sample(nxt_logits, temperature, rng, top_k, top_p)
            buf = jax.lax.dynamic_update_slice(
                buf, tok[:, None].astype(jnp.int32), (0, pos + 1))
            return (buf, rng), tok

        (buf, _), _ = jax.lax.scan(
            tick, (buf, rng), jnp.arange(p - 1, total - 1))
        return buf

    return decode
