"""Autoregressive decoding for the LM family (greedy / temperature).

The reference is a training-only cookbook; a framework user still expects to
sample from the model they trained. TPU-first constraints shape the design:

* static shapes end to end — the (B, prompt+steps) token buffer is
  allocated once and a ``lax.scan`` fills one position per tick, so the
  whole decode is ONE compiled program (no per-token host round-trip, which
  on a tunneled controller would cost ~50 ms/token);
* full-recompute attention per tick (O(steps * L^2)): causal masking makes
  positions > current length invisible to the read position, so the padded
  buffer is safe. At cookbook scales this is MXU-cheap; a KV-cache path is
  the obvious extension and slots behind the same signature;
* works with any attn_fn flavor and any mesh placement the params carry
  (replicated for decode is the normal case).

This module is the ONE-SHOT batch call; the serving layer
(``engine.serve`` + ``engine.kv_cache``) runs the same model under
continuous batching with a paged KV cache, sharing this module's sampling
(:func:`_sample`) and weight-quantization (:func:`_quantize_for_decode`)
helpers — the contiguous flax-cache program here is the single-request
degenerate case of that paged path, and greedy tokens are bit-identical
across the two (tests/test_serve.py).
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import OrderedDict
from functools import lru_cache
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _sample(nxt_logits, temperature, rng, top_k=0, top_p=0.0):
    if temperature <= 0.0:
        return jnp.argmax(nxt_logits, axis=-1), rng
    logits = nxt_logits / temperature
    if top_k:
        # keep the k best logits per row, mask the rest (static k)
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits >= kth, logits, -jnp.inf)
    if top_p > 0.0:
        # nucleus: smallest prefix of the sorted distribution with mass >=
        # top_p stays; everything after it is masked
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep_sorted = cum - probs < top_p  # first token always kept
        cutoff = jnp.min(jnp.where(keep_sorted, sorted_logits, jnp.inf),
                         axis=-1)[:, None]
        logits = jnp.where(logits >= cutoff, logits, -jnp.inf)
    rng, sub = jax.random.split(rng)
    return jax.random.categorical(sub, logits), rng


def generate(model, params, prompt: jax.Array, steps: int,
             temperature: float = 0.0,
             rng: Optional[jax.Array] = None,
             use_cache: bool = False,
             top_k: int = 0, top_p: float = 0.0,
             mesh: Optional[Mesh] = None,
             quant: str = "none",
             ledger=None) -> jax.Array:
    """Continue ``prompt`` (B, P) int32 by ``steps`` tokens.

    temperature 0 = greedy argmax (deterministic); > 0 = categorical over
    logits/temperature, optionally truncated to the ``top_k`` best tokens
    and/or the ``top_p`` nucleus. Returns the full (B, P+steps) buffer.
    P+steps must not exceed the model's max_len.

    ``use_cache=True`` decodes through the model's per-block KV cache
    (``decode=True``): each tick embeds ONE token and attends over the
    cached keys/values — O(L·d) per token instead of the full-recompute
    path's O(L²·d). Both the dense TransformerLM and MoETransformerLM are
    cache-capable (they share models.transformer.attend_maybe_cached). MoE
    caveat: per-expert capacity is GROUP-LENGTH-dependent (cap = S/E *
    capacity_factor * k) and the cached prefill groups only the prompt
    while the full path groups the whole padded buffer, so the two paths
    can drop DIFFERENT tokens. Drop-free capacity (capacity_factor >= E/k,
    --moe-capacity-factor) makes them bitwise equal at any batch size —
    every token is admitted, so grouping can't matter. Under capacity
    pressure both remain valid decodes with training's dropped-token
    semantics, just not bitwise equal to each other.

    ``quant`` (ops.quant) decodes through quantized matmuls: ``int8_wo``
    pre-quantizes every dense kernel / MoE expert tensor to int8 with fp32
    per-channel scales (weights stay int8 in HBM — the decode tick is
    weight-bandwidth-bound, BASELINE.md decode section, so weight bytes
    halve vs bf16), ``int8`` additionally quantizes activations
    dynamically inside the tick. Pass the TRAINED (fp/bf16) params; they
    are quantized here once. Greedy tokens match the unquantized decode on
    trained models (per-channel int8 keeps argmax margins —
    tests/test_quant.py pins this).

    ``mesh`` (VERDICT r4 #3) runs the SAME compiled programs sharded: the
    token buffer batch-shards over 'data' (when it divides B), the weights
    take the Megatron TP layout over 'model' (tpu_dist.parallel.tp rules:
    heads column/row-split, vocab-sharded lm_head) and the KV cache shards
    its heads axis to match — GSPMD inserts the collectives; no new decode
    code path exists. jit re-lowers per input-sharding layout, so the
    single-device memoized program and its mesh variants coexist in the
    same cache. The decode tick is weight-bandwidth-bound (BASELINE.md
    decode section: ~340 MB params/tick at 0.9B), exactly the regime where
    TP's 1/n_model weight traffic per chip cuts ms/token.

    ``ledger`` (an :class:`tpu_dist.obs.ledger.Ledger`) records the call as
    one ``decode`` event — tokens, wall seconds, tok/s, dispatch vs
    device-block split. Observability implies a sync: the buffer is blocked
    on before returning (the same array is returned, now ready).
    """
    b, p = prompt.shape
    if steps <= 0:
        # nothing to generate: return the prompt untouched (the cache
        # path's prefill would otherwise clamp its first-token write into
        # the last prompt column, and burn an rng split)
        return prompt
    if quant != "none":
        model, params = _quantize_for_decode(model, params, quant)
    else:
        _refuse_wo_tree(getattr(model, "quant", "none"), params)
    total = p + steps
    if rng is None:
        rng = jax.random.PRNGKey(0)
    buf = jnp.zeros((b, total), jnp.int32).at[:, :p].set(prompt)

    data_ax = model_ax = None
    if mesh is not None:
        params, buf, rng, data_ax, model_ax = _shard_decode_inputs(
            model, mesh, params, buf, rng)

    if use_cache:
        if mesh is not None:
            # allocate each leaf DIRECTLY under its sharding — building the
            # full replicated cache on one device first could OOM device 0
            # at exactly the scales sharded decode exists for
            cache = jax.tree.map(
                lambda s: jnp.zeros(
                    s.shape, s.dtype,
                    device=NamedSharding(
                        mesh, P(data_ax, None, model_ax, None)
                        if len(s.shape) == 4 else P())),
                _cache_shapes(model, b, total))
        else:
            cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                                 _cache_shapes(model, b, total))
        decode = _cache_decode_program(model, b, p, total, temperature,
                                       top_k, top_p)
        args = (params, cache, buf, rng)
    else:
        decode = _full_decode_program(model, b, p, total, temperature,
                                      top_k, top_p)
        args = (params, buf, rng)
    if ledger is None:
        return decode(*args)
    t0 = time.perf_counter()
    out = decode(*args)
    dispatch_s = time.perf_counter() - t0
    jax.block_until_ready(out)
    total_s = time.perf_counter() - t0
    tokens = b * steps
    ledger.emit("decode", tokens=tokens, seconds=round(total_s, 6),
                throughput=round(tokens / max(total_s, 1e-9), 1),
                dispatch_s=round(dispatch_s, 6),
                device_s=round(total_s - dispatch_s, 6),
                cached=use_cache, batch=b, prompt_len=p, steps=steps,
                quant=quant)
    return out


def prepare_draft(base_model, draft_model, draft_params, quant: str):
    """Validate + quantize a speculative-decoding DRAFT tree against its
    base (``engine.serve`` calls this once at engine construction).

    The draft proposes token IDS the base verifies, so the vocabularies
    must be literally the same space — a mismatched draft would propose
    ids the base never emits and silently decode at acceptance ~0. Depth,
    width and heads are free to differ (that is the whole point: a
    shallower draft makes k cheap proposals per one base verification).
    The draft rides the same weight-quant mode as the base, through the
    same memoized :func:`_quantize_for_decode` path, so a serving process
    holding base+draft trees quantizes each exactly once."""
    if getattr(draft_model, "vocab_size", None) != base_model.vocab_size:
        raise ValueError(
            f"draft vocab_size={getattr(draft_model, 'vocab_size', None)} "
            f"!= base vocab_size={base_model.vocab_size}: speculative "
            "verification compares token ids, so the vocabularies must "
            "be the same space")
    if draft_model.max_len < base_model.max_len:
        raise ValueError(
            f"draft max_len={draft_model.max_len} < base "
            f"max_len={base_model.max_len}: the draft must be able to "
            "sit at every position the base serves")
    if quant != "none":
        return _quantize_for_decode(draft_model, draft_params, quant)
    _refuse_wo_tree(getattr(draft_model, "quant", "none"), draft_params)
    return draft_model, draft_params


def _refuse_wo_tree(effective_mode: str, params) -> None:
    """Raise when a wo-quantized tree meets any decode mode but 'int8_wo':
    plain nn.Dense would silently use the raw int8 kernels as weights
    (flax ignores the extra scale leaves) and decode garbage, and the
    dynamic-int8 program cannot be built without the fp weights."""
    from tpu_dist.ops.quant import params_are_wo_quantized

    if effective_mode != "int8_wo" and params_are_wo_quantized(params):
        raise ValueError(
            "params are wo-quantized (int8 kernels + kernel_scale leaves) "
            f"but the decode mode is {effective_mode!r}; pass "
            "generate(..., quant='int8_wo') for a pre-quantized tree, or "
            "keep the fp params.")


def _quantize_for_decode(model, params, quant: str):
    """Rebind the model's quant mode for decode; for weight-only int8,
    pre-quantize the params (ops.quant.wo_quantize_params) so dense kernels
    and MoE expert tensors sit int8 in HBM with fp32 scale leaves — the
    decode tick is weight-bandwidth-bound, so halving the weight bytes is
    THE quant win here. Cloned modules hash by field value, so the memoized
    decode programs still cache-hit across generate() calls — and the
    quantized TREE is memoized too: a small LRU keyed on (treedef, mode,
    fp-leaf identities), so a long-lived serving process alternating
    between quant modes or between several live base trees (engine.serve
    keeps one per deployed model) never re-quantizes a live tree — the
    round-10 single-entry memo thrashed on exactly that alternation. Each
    entry holds only weakrefs to its fp leaves and self-evicts when any is
    collected, so neither tree copy is pinned past its natural lifetime."""
    from tpu_dist.ops.quant import (params_are_wo_quantized, validate_quant,
                                    wo_quantize_params)

    validate_quant(quant)
    _refuse_wo_tree(quant, params)
    if getattr(model, "quant", "none") != quant:
        if not hasattr(model, "quant"):
            raise ValueError(
                f"quant={quant!r} decode needs a quant-capable model "
                "(TransformerLM / MoETransformerLM)")
        model = model.clone(quant=quant)
    if quant == "int8_wo" and not params_are_wo_quantized(params):
        leaves, treedef = jax.tree_util.tree_flatten(params)
        # id()s make the key hashable; the stored weakrefs then verify the
        # leaves are genuinely the same objects (an id can be recycled
        # after gc — the eviction callback removes the entry first, but
        # the identity check makes a lost race a re-quantize, never a
        # wrong-tree hit)
        key = (treedef, quant, tuple(id(l) for l in leaves))
        with _wo_memo_lock:
            hit = _wo_memo.get(key)
            if (hit is not None
                    and all(r() is l for r, l in zip(hit[0], leaves))):
                _wo_memo.move_to_end(key)
                return model, hit[1]
        quantized = wo_quantize_params(params)

        def _evict(_ref, _key=key):  # a fp leaf died: drop its entry
            with _wo_memo_lock:
                _wo_memo.pop(_key, None)

        # evicted entries are DESTROYED outside the lock: dropping a
        # quantized tree can trigger gc, gc can fire another entry's
        # weakref _evict on this same thread, and _evict takes the lock —
        # an RLock makes the re-entry safe and the deferred del keeps the
        # critical section free of arbitrary destructor work (the DL101
        # hazard class, in allocator form)
        evicted = []
        with _wo_memo_lock:
            _wo_memo[key] = (tuple(weakref.ref(l, _evict) for l in leaves),
                             quantized)
            _wo_memo.move_to_end(key)
            while len(_wo_memo) > _WO_MEMO_MAX:
                evicted.append(_wo_memo.popitem(last=False))
        del evicted
        params = quantized
    return model, params


# (treedef, mode, leaf ids) -> (leaf weakrefs, quantized tree): the small
# LRU of _quantize_for_decode. A serving process keeps a handful of live
# base trees at most; beyond that the caller should pre-quantize
# (wo_quantize_params) and pass the quantized tree in.
_WO_MEMO_MAX = 4
_wo_memo: "OrderedDict" = OrderedDict()
# RLock, not Lock: gc may run a weakref _evict on the thread that already
# holds the lock (see the eviction note in _quantize_for_decode)
_wo_memo_lock = threading.RLock()


def _shard_decode_inputs(model, mesh: Mesh, params, buf, rng):
    """device_put the decode inputs onto their mesh shardings.

    Returns (params, buf, rng, data_axis_or_None, model_axis_or_None).
    'data' shards the batch when it divides B; 'model' > 1 applies the
    training TP rules to the params (requires num_heads divisible). Axes
    the mesh doesn't carry (or that don't divide) fall back to replication,
    so a ('data',)-only mesh and a ('model',)-only mesh both just work.
    """
    from tpu_dist.parallel.ep import EXPERT_AXIS, shard_moe_params
    from tpu_dist.parallel.mesh import DATA_AXIS, MODEL_AXIS
    from tpu_dist.parallel.tp import shard_lm_params

    b = buf.shape[0]
    data_ax = (DATA_AXIS if DATA_AXIS in mesh.shape
               and mesh.shape[DATA_AXIS] > 1 and b % mesh.shape[DATA_AXIS] == 0
               else None)
    model_ax = (MODEL_AXIS if MODEL_AXIS in mesh.shape
                and mesh.shape[MODEL_AXIS] > 1 else None)
    experts = getattr(model, "num_experts", 0)
    expert_ax = (EXPERT_AXIS if experts and EXPERT_AXIS in mesh.shape
                 and mesh.shape[EXPERT_AXIS] > 1 else None)
    if model_ax:
        heads = getattr(model, "num_heads", 0)
        if heads % mesh.shape[MODEL_AXIS]:
            raise ValueError(
                f"TP decode shards attention heads: num_heads={heads} "
                f"must divide by mesh 'model' size {mesh.shape[MODEL_AXIS]}")
    if expert_ax:
        if experts % mesh.shape[EXPERT_AXIS]:
            raise ValueError(
                f"EP decode shards experts: num_experts={experts} must "
                f"divide by mesh 'expert' size {mesh.shape[EXPERT_AXIS]}")
        # training EP placement (+ Megatron split when 'model' rides along);
        # GSPMD turns the dispatch/combine einsums into decode all-to-alls
        params = shard_moe_params(mesh, params, model_axis=model_ax)
    elif model_ax:
        params = shard_lm_params(mesh, params)  # THE training TP placement
    else:
        params = jax.device_put(params, NamedSharding(mesh, P()))
    buf = jax.device_put(buf, NamedSharding(mesh, P(data_ax)))
    rng = jax.device_put(rng, NamedSharding(mesh, P()))
    return params, buf, rng, data_ax, model_ax


# The compiled programs are memoized per (model, geometry, sampling)
# signature: a fresh `jax.jit` closure per generate() call would make EVERY
# call retrace and recompile (jit caches by function identity) — measured at
# ~13 ms/token vs the 0.7 ms/token the compiled tick actually costs.
#
# Flax modules hash by field VALUE, and the attn_fn field hashes by function
# identity — so the attn-fn factories (flash/blockwise/ring) are lru_cached
# at their definitions: same-config factories return the same callable,
# making logically identical models (fresh LMTrainer, sp rebind) hit this
# cache instead of silently recompiling (ADVICE r4). A hand-rolled closure
# passed as attn_fn still misses; that's inherent to identity keying.


@lru_cache(maxsize=32)
def _cache_shapes(model, b, total):
    """KV-cache shape tree via eval_shape — no real init forward, and
    memoized so a sampling loop does not re-trace the whole model per call
    just to learn shapes that depend only on (model, b, total)."""
    return jax.eval_shape(
        lambda: model.init({"params": jax.random.PRNGKey(0)},
                           jnp.zeros((b, total), jnp.int32), train=False,
                           decode=True))["cache"]

@lru_cache(maxsize=32)
def _cache_decode_program(model, b, p, total, temperature, top_k, top_p):
    @jax.jit
    def decode(params, cache, buf, rng):
        # prefill: ONE forward over the whole prompt writes cache[0:p)
        # (the per-block dynamic_update_slice handles a (B, P, ...) write)
        # and its last position's logits sample the first generated token —
        # P times fewer ticks than feeding the prompt one token at a time
        prompt = jax.lax.dynamic_slice(buf, (0, 0), (b, p))
        logits, muts = model.apply(
            {"params": params, "cache": cache}, prompt, train=False,
            pos_offset=0, decode=True, mutable=["cache"])
        cache = muts["cache"]
        if temperature > 0.0:
            nxt, rng = _sample(logits[:, -1], temperature, rng, top_k, top_p)
        else:
            nxt = jnp.argmax(logits[:, -1], axis=-1)
        buf = jax.lax.dynamic_update_slice(
            buf, nxt[:, None].astype(jnp.int32), (0, p))

        def tick(carry, pos):
            buf, cache, rng = carry
            tok = jax.lax.dynamic_slice(buf, (0, pos), (b, 1))
            logits, muts = model.apply(
                {"params": params, "cache": cache}, tok, train=False,
                pos_offset=pos, decode=True, mutable=["cache"])
            # rng splits once per generated token, in generation order —
            # the same stream as the full-recompute path
            if temperature > 0.0:
                nxt, rng = _sample(logits[:, 0], temperature, rng,
                                   top_k, top_p)
            else:
                nxt = jnp.argmax(logits[:, 0], axis=-1)
            buf = jax.lax.dynamic_update_slice(
                buf, nxt[:, None].astype(jnp.int32), (0, pos + 1))
            return (buf, muts["cache"], rng), None

        (buf, _, _), _ = jax.lax.scan(
            tick, (buf, cache, rng), jnp.arange(p, total - 1))
        return buf

    return decode


@lru_cache(maxsize=32)
def _full_decode_program(model, b, p, total, temperature, top_k, top_p):
    @jax.jit
    def decode(params, buf, rng):
        def tick(carry, pos):
            buf, rng = carry
            logits = model.apply({"params": params}, buf, train=False)
            nxt_logits = jnp.take_along_axis(
                logits, pos[None, None, None].astype(jnp.int32)
                .repeat(b, 0), axis=1)[:, 0]          # (B, V) at position pos
            tok, rng = _sample(nxt_logits, temperature, rng, top_k, top_p)
            buf = jax.lax.dynamic_update_slice(
                buf, tok[:, None].astype(jnp.int32), (0, pos + 1))
            return (buf, rng), tok

        (buf, _), _ = jax.lax.scan(
            tick, (buf, rng), jnp.arange(p - 1, total - 1))
        return buf

    return decode
