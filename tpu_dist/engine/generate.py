"""Autoregressive decoding for the LM family (greedy / temperature).

The reference is a training-only cookbook; a framework user still expects to
sample from the model they trained. TPU-first constraints shape the design:

* static shapes end to end — the (B, prompt+steps) token buffer is
  allocated once and a ``lax.scan`` fills one position per tick, so the
  whole decode is ONE compiled program (no per-token host round-trip, which
  on a tunneled controller would cost ~50 ms/token);
* full-recompute attention per tick (O(steps * L^2)): causal masking makes
  positions > current length invisible to the read position, so the padded
  buffer is safe. At cookbook scales this is MXU-cheap; a KV-cache path is
  the obvious extension and slots behind the same signature;
* works with any attn_fn flavor and any mesh placement the params carry
  (replicated for decode is the normal case).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def generate(model, params, prompt: jax.Array, steps: int,
             temperature: float = 0.0,
             rng: Optional[jax.Array] = None) -> jax.Array:
    """Continue ``prompt`` (B, P) int32 by ``steps`` tokens.

    temperature 0 = greedy argmax (deterministic); > 0 = categorical over
    logits/temperature. Returns the full (B, P+steps) buffer. P+steps must
    not exceed the model's max_len.
    """
    b, p = prompt.shape
    total = p + steps
    if rng is None:
        rng = jax.random.PRNGKey(0)
    buf = jnp.zeros((b, total), jnp.int32).at[:, :p].set(prompt)

    @jax.jit
    def decode(params, buf, rng):
        def tick(carry, pos):
            buf, rng = carry
            logits = model.apply({"params": params}, buf, train=False)
            nxt_logits = jnp.take_along_axis(
                logits, pos[None, None, None].astype(jnp.int32)
                .repeat(b, 0), axis=1)[:, 0]          # (B, V) at position pos
            if temperature > 0.0:
                rng, sub = jax.random.split(rng)
                tok = jax.random.categorical(sub, nxt_logits / temperature)
            else:
                tok = jnp.argmax(nxt_logits, axis=-1)
            buf = jax.lax.dynamic_update_slice(
                buf, tok[:, None].astype(jnp.int32), (0, pos + 1))
            return (buf, rng), tok

        (buf, _), _ = jax.lax.scan(
            tick, (buf, rng), jnp.arange(p - 1, total - 1))
        return buf

    return decode(params, buf, rng)
