"""Continuous-batching decode engine over the paged KV cache.

``engine.generate`` answers "decode this batch"; a server has to answer
"decode this *stream*": requests arrive at their own times with their own
prompt/output lengths, and the offline pattern — admit a fixed batch, run
it to the longest sequence's completion, repeat — leaves most slots idle
most of the time. This module implements Orca-style iteration-level
scheduling [OSDI '22]: admission decisions happen at every decode tick, a
finished sequence's slot and pages are reclaimed and refilled the same
tick, and the headline metric becomes throughput-under-load (completed
requests/s at a latency SLO), not offline tok/s.

Composition (each piece usable alone):

* :class:`tpu_dist.engine.kv_cache.PagedKVPool` backs every sequence with
  block-table pages (bf16/fp32, or int8+scales via the PR 9 ``quantize_kv``
  convention) — mixed-length sequences share HBM without fragmentation;
* two jitted programs serve all traffic: ``prefill`` (one admit's prompt,
  padded to a length bucket, writing its pages and sampling the first
  token) and ``decode_tick`` (the packed slot set, one token per active
  sequence, per-slot positions — inactive slots ride along masked to the
  pool's trash page, so the program never re-specializes on occupancy);
* **speculative decoding** (``spec_k > 0``): a small draft model over the
  shared base proposes k greedy tokens per slot and ONE jitted program per
  tick both drafts and verifies — the draft scan rides its own page arenas
  (same block tables, so pages stay interchangeable) and the base
  verification is a single (k+1)-wide multi-position read over the main
  arenas; accept/reject resolves as an in-program per-row gather, so the
  tick stays one dispatch and emits up to k tokens per slot. Greedy
  emission is token-for-token identical to non-speculative greedy decode
  for ANY draft (the verifier's argmax corrects the first divergence), so
  acceptance rate only moves THROUGHPUT, never output;
* **copy-on-write prefix caching** (``prefix_cache``): admission asks the
  pool for pages an identical earlier prompt prefix already filled
  (refcounted sharing + token-hash prefix index, ``engine.kv_cache``),
  prefill skips the resident rows, and the one shareable page a request
  can ever write — the frontier page holding its prompt tail — is forked
  onto a page reserved at admission (``ops.paged_attention.
  cow_fork_pages``) right before its first divergent write. Hot system
  prompts cost ~0 fresh pages per request; shared decode is bit-identical
  to unshared because shared rows are the original writer's bits, re-read
  not re-written;
* admission control is SLO-aware: hard queue-depth and free-page
  watermarks reject at submit time, and an EMA of queue wait (the
  ``GoodputMonitor`` hysteresis pattern) sheds new work while the backlog
  breaches the floor — emitting the standard ``slo`` ledger event, which
  auto-triggers the flight recorder through the existing sink fan-out;
* every request lands in the ledger (``admit``/``request`` events), pool
  pressure in periodic ``kv_cache`` events, and the metrics sink exports
  ``tpu_dist_serve_queue_depth`` / ``tpu_dist_serve_active_seqs`` /
  ``tpu_dist_kv_pages_free`` gauges — scrape-able on day one.

Sampling and weight quantization are SHARED with ``engine.generate``
(:func:`~tpu_dist.engine.generate._sample`,
:func:`~tpu_dist.engine.generate._quantize_for_decode`): the one-shot
contiguous-cache call is the single-request degenerate case of this path,
and greedy tokens are bit-identical across the two (tests/test_serve.py).

The scheduler itself is host-side and clock-agnostic: ``now_fn`` defaults
to ``time.monotonic``, and tests/trace replay pass a virtual clock for
fully deterministic runs (the ROADMAP's million-user-on-CPU direction).
Multi-host/mesh serving is future work — params stay wherever the caller
put them (single-process serving is the shape this PR pins down).
"""

from __future__ import annotations

import signal
import threading
import time
from collections import deque
from dataclasses import dataclass
from functools import lru_cache, partial
from typing import Callable, Deque, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from jax.sharding import PartitionSpec as P

from tpu_dist._compat import shard_map
from tpu_dist.engine.generate import (_quantize_for_decode, _refuse_wo_tree,
                                      _sample, prepare_draft)
from tpu_dist.engine.kv_cache import PagedKVPool, PrefixMatch
from tpu_dist.obs.reqtrace import RequestTracer
from tpu_dist.ops.paged_attention import cow_fork_pages
from tpu_dist.parallel.mesh import SP_AXIS
from tpu_dist.parallel.ring_attention import ring_attention_fn
from tpu_dist.plan.compile import check_audit_sentry, register_audit_program


@dataclass
class DecodeRequest:
    """One generation request: continue ``prompt`` by ``max_new_tokens``
    (or until ``ServeConfig.eos_id``). ``rid`` is the caller's correlation
    id — it rides every ledger event this request produces — and
    ``tenant`` (optional) names the traffic class, so multi-tenant
    deployments get per-tenant SLO accounting from the same ``request``
    events (tools/fleet_report.py renders the percentiles)."""

    rid: int
    prompt: np.ndarray
    max_new_tokens: int
    tenant: Optional[str] = None


@dataclass
class Completion:
    """A finished request with its serving timeline (engine-clock
    timestamps: real seconds under the default clock, virtual units under
    an injected one)."""

    rid: int
    tokens: np.ndarray           # (prompt + generated,) int32
    prompt_len: int
    n_generated: int
    admit_ts: float              # entered the queue (submit time)
    start_ts: float              # left the queue (prefill start)
    first_token_ts: float
    finish_ts: float

    @property
    def queue_wait_s(self) -> float:
        return self.start_ts - self.admit_ts

    @property
    def ttft_s(self) -> float:
        return self.first_token_ts - self.admit_ts


@dataclass
class ServeConfig:
    """Scheduler + paged-cache knobs (README "Serving" has the tour)."""

    max_slots: int = 4           # concurrent sequences (the packed batch)
    page_size: int = 16          # tokens per KV page
    num_pages: int = 256         # pool capacity (per layer, +1 trash page)
    max_len: Optional[int] = None   # per-sequence cap (default model.max_len)
    quant: str = "none"          # weight quant (int8_wo pre-quantizes once)
    kv_quant: str = "none"       # page dtype: none (model dtype) | int8
    attn_read: str = "exact"     # exact | flash (int8-KV Pallas kernel)
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 0.0
    eos_id: Optional[int] = None
    prefill_buckets: Tuple[int, ...] = ()   # () = powers of 2 up to max_len
    refill: str = "continuous"   # continuous | drain (static-batch baseline)
    queue_depth_max: int = 64    # hard admission cap
    free_page_watermark: float = 0.0   # reject below this free fraction
    slo_queue_wait_s: float = 0.0      # EMA floor; 0 disables shedding
    slo_alpha: float = 0.5
    slo_min_samples: int = 2
    kv_event_every: int = 0      # ticks between kv_cache events (0 = final)
    spec_k: int = 0              # draft tokens per tick (0 = plain decode)
    prefix_cache: bool = False   # CoW prefix sharing across requests
    # chunked prefill (long-context tail stability): prompts longer than
    # this run as fixed-size chunks, at most ONE chunk interleaved per
    # scheduler iteration with the decode tick — a 16k admit costs many
    # bounded steps instead of one full-prompt stall. 0 = monolithic.
    prefill_chunk: int = 0
    # sequence-parallel prefill (needs ServeEngine(mesh=...)): prompts at
    # or past this threshold prefill under ring attention over the 'sp'
    # axis, each device scattering its shard's K/V into its LOCAL pages —
    # no full-sequence K/V on any one device. 0 = never.
    sp_prefill_threshold: int = 0
    # request tracing: decode spans coalesce this many ticks per slot into
    # one window span (per-token spans would dwarf the ledger; windows
    # keep the waterfall readable AND tile first-token->finish exactly)
    trace_window_ticks: int = 8


@dataclass
class _Slot:
    req: DecodeRequest
    pages: List[int]
    block_table: np.ndarray      # (max_pages_per_seq,) int32
    buf: np.ndarray              # (prompt + max_new,) int32
    prompt_len: int
    admit_ts: float
    start_ts: float
    position: int = 0            # next KV write position
    generated: int = 0
    first_token_ts: float = 0.0
    finish_ts: float = 0.0
    done: bool = False
    # copy-on-write: (bt_slot, src_page, dst_page) of a SHARED frontier
    # page this sequence will write into — forked right before its first
    # decode write (engine._resolve_cow), None once private
    cow_pending: Optional[Tuple[int, int, int]] = None
    # chunked prefill state: the next prompt offset to prefill (-1 once
    # the prompt is fully resident and the first token sampled — only
    # then does the slot join the decode tick's active set)
    chunk_next: int = -1
    shared_len: int = 0          # prefix-cache-resident prompt rows
    n_fresh: int = 0             # admission page accounting (span fields)
    n_shared: int = 0
    # request tracing: the open decode-window span (obs.reqtrace) — opens
    # at the first token, closes every trace_window_ticks ticks and at
    # finish, so the windows tile first-token->finish contiguously
    win_start_ts: float = 0.0
    win_ticks: int = 0
    win_tokens: int = 0
    win_drafted: int = 0


def _default_buckets(max_len: int) -> Tuple[int, ...]:
    """Powers of two up to max_len (plus max_len itself): each bucket is
    one compiled prefill geometry, so a handful covers every prompt."""
    out = []
    b = 8
    while b < max_len:
        out.append(b)
        b *= 2
    out.append(max_len)
    return tuple(out)


# The compiled serving programs are memoized per (model, sampling)
# signature — jit itself re-specializes per shape (prefill buckets, slot
# count), so one entry serves every geometry of one deployment. Same
# rationale as engine.generate's program caches.

@lru_cache(maxsize=32)
def _prefill_program(model, temperature, top_k, top_p, sp_mesh=None):
    # the arenas are DONATED: the caller (the pool) adopts the returned
    # ones and never touches the old buffers again, and without aliasing
    # every call would copy every layer's whole page arena — per admitted
    # prompt, in the feature that exists to keep KV HBM tight
    @partial(jax.jit, donate_argnums=(1,))
    def prefill(params, layers, block_table, length, shared_len, prompt,
                rng):
        # block_table (1, max_pages) i32, length () i32, prompt (1, bucket):
        # causal self-attention over the padded prompt (positions >= length
        # influence nothing earlier), pages written for the live prefix,
        # first token sampled from the last LIVE row's logits. Rows below
        # ``shared_len`` sit on pages SHARED with an earlier identical
        # prefix (prefix caching): already resident, so the write mask
        # skips them — rewriting could drift bits across prefill buckets
        # and would race the other holders' reads. shared_len is traced
        # (0 when nothing is shared), so sharing never re-specializes.
        valid = (jnp.arange(prompt.shape[1], dtype=jnp.int32)[None, :]
                 >= jnp.asarray(shared_len, jnp.int32))
        paged = {"layers": layers, "block_tables": block_table,
                 "positions": jnp.zeros((1,), jnp.int32),
                 "lengths": jnp.asarray(length, jnp.int32)[None],
                 "valid": valid, "sp_mesh": sp_mesh}
        logits, new_layers = model.apply(
            {"params": params}, prompt, train=False,
            paged=paged, paged_prefill=True)
        last = jnp.take_along_axis(
            logits, jnp.reshape(length - 1, (1, 1, 1)).astype(jnp.int32),
            axis=1)[:, 0]
        nxt, rng = _sample(last, temperature, rng, top_k, top_p)
        return nxt[0].astype(jnp.int32), new_layers, rng

    return prefill


@lru_cache(maxsize=32)
def _tick_program(model, temperature, top_k, top_p, sp_mesh=None):
    # arenas donated for the same reason as _prefill_program: the tick
    # writes one row per slot and the un-aliased alternative is a full
    # arena copy per generated token
    @partial(jax.jit, donate_argnums=(1,))
    def tick(params, layers, block_tables, tokens, positions, rng):
        # one token per slot at its OWN position; inactive slots carry
        # all-trash block tables and position 0, so their writes land on
        # the trash page and their (ignored) logits cost one lane of the
        # same program — occupancy changes never retrace
        paged = {"layers": layers, "block_tables": block_tables,
                 "positions": positions, "lengths": positions + 1,
                 "sp_mesh": sp_mesh}
        logits, new_layers = model.apply(
            {"params": params}, tokens[:, None], train=False,
            pos_offset=positions, paged=paged)
        nxt, rng = _sample(logits[:, 0], temperature, rng, top_k, top_p)
        return nxt.astype(jnp.int32), new_layers, rng

    return tick


@lru_cache(maxsize=32)
def _chunk_prefill_program(model, chunk, sp_mesh=None):
    # One prefill CHUNK: rows [start, start+chunk) of a prompt, written
    # and attended through the SAME per-row-position machinery the decode
    # tick uses (ops.paged_attention, prefill=False) — the chunk's queries
    # read the gathered pages, which at that point hold exactly the
    # earlier chunks' rows plus this chunk's own (causally masked), so
    # chunked greedy is token-for-token the monolithic prefill
    # (tests/test_serve.py pins it; int8 KV pages are the one exception —
    # earlier chunks re-read quantized rows monolithic never quantizes).
    # Returns the last LIVE row's logits (meaningful on the final chunk
    # only) + updated arenas; sampling stays host-sequenced in
    # _sample_first_program so the rng stream advances exactly once per
    # admit, same as monolithic.
    @partial(jax.jit, donate_argnums=(1,))
    def chunk_step(params, layers, block_table, start, length, shared_len,
                   tokens):
        pos = jnp.asarray(start, jnp.int32)[None]               # (1,)
        rows = pos[:, None] + jnp.arange(chunk, dtype=jnp.int32)[None]
        valid = ((rows < jnp.asarray(length, jnp.int32))
                 & (rows >= jnp.asarray(shared_len, jnp.int32)))
        paged = {"layers": layers, "block_tables": block_table,
                 "positions": pos, "lengths": pos + chunk,
                 "valid": valid, "sp_mesh": sp_mesh}
        logits, new_layers = model.apply(
            {"params": params}, tokens, train=False,
            pos_offset=pos, paged=paged)
        last = jnp.take_along_axis(
            logits,
            jnp.reshape(jnp.clip(length - 1 - start, 0, chunk - 1),
                        (1, 1, 1)).astype(jnp.int32), axis=1)[:, 0]
        return last, new_layers

    return chunk_step


@lru_cache(maxsize=32)
def _sample_first_program(temperature, top_k, top_p):
    # the final chunk's first-token sample: the same _sample call (and the
    # same single rng consumption) _prefill_program fuses in-program
    @jax.jit
    def sample_first(last, rng):
        nxt, rng = _sample(last, temperature, rng, top_k, top_p)
        return nxt[0].astype(jnp.int32), rng

    return sample_first


@lru_cache(maxsize=32)
def _sp_prefill_program(model, mesh, temperature, top_k, top_p):
    # Sequence-parallel prefill: the padded prompt splits into n
    # contiguous shards over the 'sp' axis inside shard_map; each device
    # runs the model on ITS shard with ring attention as the attention
    # contraction (parallel.ring_attention — K/V rotate, exact causal
    # attention, O(bucket/n) sequence memory per device) and scatters its
    # shard's K/V rows straight into the pages it physically owns (the
    # sp-sharded pool's striped prompt allocation guarantees ownership).
    # The full-sequence K/V never materializes on any one device — the
    # whole point. The last live row's logits live on one shard; a
    # masked psum replicates them for the (replicated) first-token sample.
    n = mesh.shape[SP_AXIS]
    sp_model = model.clone(attn_fn=ring_attention_fn(SP_AXIS))

    @partial(jax.jit, donate_argnums=(1,))
    def prefill(params, layers, block_table, length, shared_len, prompt,
                rng):
        lsh = prompt.shape[1] // n         # bucket % (n * page_size) == 0

        def shard_fn(params, layers, bt, length, shared_len, prompt):
            rows_local = layers[0].k.shape[0]
            me = jax.lax.axis_index(SP_AXIS)
            pos = jnp.asarray(me * lsh, jnp.int32)[None]        # (1,)
            rows = pos[:, None] + jnp.arange(lsh, dtype=jnp.int32)[None]
            valid = rows >= jnp.asarray(shared_len, jnp.int32)
            # FLAT global rows -> my local rows; foreign pages route to my
            # LOCAL trash row (their owner writes the real bits)
            local_bt = jnp.where(bt // rows_local == me,
                                 bt % rows_local, rows_local - 1)
            paged = {"layers": layers, "block_tables": local_bt,
                     "positions": pos,
                     "lengths": jnp.asarray(length, jnp.int32)[None],
                     "valid": valid}
            logits, new_layers = sp_model.apply(
                {"params": params}, prompt, train=False, pos_offset=pos,
                paged=paged, paged_prefill=True)
            idx = jnp.clip(length - 1 - pos[0], 0, lsh - 1)
            last = jnp.take_along_axis(
                logits, jnp.reshape(idx, (1, 1, 1)).astype(jnp.int32),
                axis=1)[:, 0]
            owns_last = (length - 1 >= pos[0]) & (length - 1 < pos[0] + lsh)
            last = jax.lax.psum(
                jnp.where(owns_last, last, jnp.zeros_like(last)), SP_AXIS)
            return last, new_layers

        last, new_layers = shard_map(
            shard_fn, mesh=mesh,
            in_specs=(P(), P(SP_AXIS), P(), P(), P(), P(None, SP_AXIS)),
            out_specs=(P(), P(SP_AXIS)))(
            params, layers, block_table,
            jnp.asarray(length, jnp.int32),
            jnp.asarray(shared_len, jnp.int32), prompt)
        nxt, rng = _sample(last, temperature, rng, top_k, top_p)
        return nxt[0].astype(jnp.int32), new_layers, rng

    return prefill


@lru_cache(maxsize=32)
def _draft_prefill_program(draft_model):
    # the draft's prompt pass: writes the DRAFT arenas' prompt rows through
    # the same block table the base prefill used (the pools share page
    # indices) and discards the logits — the first emitted token is the
    # base's, sampled by _prefill_program
    @partial(jax.jit, donate_argnums=(1,))
    def prefill(params, layers, block_table, length, shared_len, prompt):
        valid = (jnp.arange(prompt.shape[1], dtype=jnp.int32)[None, :]
                 >= jnp.asarray(shared_len, jnp.int32))
        paged = {"layers": layers, "block_tables": block_table,
                 "positions": jnp.zeros((1,), jnp.int32),
                 "lengths": jnp.asarray(length, jnp.int32)[None],
                 "valid": valid}
        _, new_layers = draft_model.apply(
            {"params": params}, prompt, train=False,
            paged=paged, paged_prefill=True)
        return new_layers

    return prefill


@lru_cache(maxsize=32)
def _spec_tick_program(model, draft_model, k):
    # The speculative tick: k greedy draft steps (a lax.scan over the draft
    # arenas, one token per step) + ONE (k+1)-wide base verification over
    # the main arenas + the accept/reject gather — all inside a single
    # jitted dispatch, so speculation never adds host round-trips.
    #
    # Greedy emission rule (the bit-parity invariant): with drafts d_1..d_k
    # and base argmaxes g_0..g_k at offsets 0..k, let ``a`` be the length
    # of the longest prefix with d_i == g_{i-1}. Emit d_1..d_a plus the
    # correction g_a when a < k (a+1 tokens — the correction IS what
    # non-speculative greedy would have emitted next), and exactly d_1..d_k
    # when a == k (k tokens, NO bonus token: g_k's source row is the k-th
    # draft's KV, which the DRAFT arenas don't hold yet — emitting it would
    # break the "draft rows cover 0..position-1" invariant the next tick's
    # scan relies on). Either way every emitted token equals the base
    # model's greedy continuation, for ANY draft — acceptance moves
    # throughput, never output.
    #
    # Stale-row discipline: both pools' arenas accumulate speculative rows
    # past the accepted frontier. They are invisible (per-row causal
    # horizon) and the next tick overwrites them in position order before
    # any read, so rejection needs NO rollback work — the block table and
    # position simply don't advance past the accepted count.
    @partial(jax.jit, donate_argnums=(2, 3))
    def tick(params, draft_params, layers, draft_layers, block_tables,
             tokens, positions, caps):
        b = tokens.shape[0]

        def draft_step(carry, _):
            dlayers, tok, pos = carry
            # a draft can overrun a short request's allocated rows; the
            # cap mask routes those writes to the trash page (an unmasked
            # overrun would CLAMP into the sequence's last live page)
            paged = {"layers": dlayers, "block_tables": block_tables,
                     "positions": pos, "lengths": pos + 1,
                     "valid": (pos < caps)[:, None]}
            logits, new_dlayers = draft_model.apply(
                {"params": draft_params}, tok[:, None], train=False,
                pos_offset=pos, paged=paged)
            nxt = jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32)
            return (new_dlayers, nxt, pos + 1), nxt

        (draft_layers, _, _), drafts = jax.lax.scan(
            draft_step, (draft_layers, tokens, positions), None, length=k)
        drafts = jnp.swapaxes(drafts, 0, 1)              # (B, k)

        # one multi-position verify: row b carries queries for [t0, d1..dk]
        # at positions pos..pos+k, writing all their K/V rows and reading
        # each at its own causal horizon (ops.paged_attention)
        ver = jnp.concatenate([tokens[:, None], drafts], axis=1)  # (B, k+1)
        write_pos = positions[:, None] + jnp.arange(k + 1,
                                                    dtype=jnp.int32)[None, :]
        paged = {"layers": layers, "block_tables": block_tables,
                 "positions": positions, "lengths": positions + k + 1,
                 "valid": write_pos < caps[:, None]}
        logits, new_layers = model.apply(
            {"params": params}, ver, train=False,
            pos_offset=positions, paged=paged)
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # (B, k+1)

        # longest accepted prefix, resolved per row with no host trip
        matches = (drafts == greedy[:, :k]).astype(jnp.int32)
        a = jnp.sum(jnp.cumprod(matches, axis=1), axis=1)       # (B,)
        emit_n = jnp.minimum(a + 1, k)
        corr = jnp.take_along_axis(greedy, a[:, None], axis=1)  # (B, 1)
        idx = jnp.arange(k, dtype=jnp.int32)[None, :]
        emitted = jnp.where(idx < a[:, None], drafts, corr)     # (B, k)
        return emitted, emit_n, new_layers, draft_layers

    return tick


class ServeEngine:
    """The continuous-batching scheduler (module docstring has the tour).

    Drive it either with :meth:`run` (submit everything, drain — the test
    and bit-identity shape) or manually: ``submit()`` as requests arrive,
    ``step()`` once per scheduler iteration (evict -> admit+prefill ->
    decode tick), each returning the requests that finished.
    """

    def __init__(self, model, params, config: Optional[ServeConfig] = None,
                 *, draft_model=None, draft_params=None, ledger=None,
                 tracer: Optional[RequestTracer] = None,
                 now_fn: Callable[[], float] = time.monotonic,
                 rng: Optional[jax.Array] = None, mesh=None):
        config = config if config is not None else ServeConfig()
        if getattr(model, "num_experts", 0):
            raise NotImplementedError(
                "paged serving covers the dense TransformerLM family; the "
                "MoE capacity-factor dispatch needs its own scheduling "
                "story (ROADMAP item 4)")
        cfg = config
        if cfg.quant != "none":
            model, params = _quantize_for_decode(model, params, cfg.quant)
        else:
            _refuse_wo_tree(getattr(model, "quant", "none"), params)
        self.model = model
        self.params = params
        self.cfg = cfg
        self.max_len = min(cfg.max_len or model.max_len, model.max_len)
        # sp-sharded serving (mesh= with the 'sp' axis): the pool's arenas
        # shard over the axis, so effective KV capacity scales with the
        # mesh and contexts larger than ONE device's page budget serve
        self.sp_mesh = mesh
        self.sp_n = 1
        if mesh is not None:
            if SP_AXIS not in mesh.shape:
                raise ValueError(
                    f"ServeEngine mesh needs the {SP_AXIS!r} axis (got "
                    f"axes {tuple(mesh.axis_names)})")
            self.sp_n = mesh.shape[SP_AXIS]
            if cfg.spec_k > 0:
                raise NotImplementedError(
                    "speculative decoding over an sp-sharded pool is the "
                    "named residue: the draft scan's per-step sharded "
                    "writes need their own collective story")
        head_dim = model.d_model // model.num_heads
        self.pool = PagedKVPool(
            model.num_layers, cfg.num_pages, cfg.page_size,
            model.num_heads, head_dim, dtype=model.dtype,
            kv_quant=cfg.kv_quant, read=cfg.attn_read, mesh=mesh)
        self.max_pages_per_seq = self.pool.pages_needed(self.max_len)
        # speculative decoding: a draft proposes cfg.spec_k tokens per tick
        # over its OWN arenas (a second pool, same page geometry + indices,
        # so it rides the SAME block tables and the base pool's allocator
        # is the single source of truth for page ownership)
        self.draft_model = self.draft_params = self.draft_pool = None
        if cfg.spec_k > 0:
            if cfg.temperature > 0.0:
                raise ValueError(
                    "speculative decoding serves greedy verification only "
                    "(spec_k > 0 needs temperature == 0): sampled "
                    "acceptance is a different estimator with different "
                    "output distribution guarantees")
            if draft_model is None:
                # self-speculation: the base drafts for itself (useful as a
                # default and as the acceptance upper bound — the draft
                # arenas still diverge numerically from the multi-position
                # verify, so acceptance is high, not trivially 1.0)
                self.draft_model, self.draft_params = self.model, self.params
            else:
                self.draft_model, self.draft_params = prepare_draft(
                    self.model, draft_model, draft_params, cfg.quant)
            d_head = (self.draft_model.d_model
                      // self.draft_model.num_heads)
            # draft reads stay on the exact path: the flash kernel is a
            # bandwidth optimization for the big base arenas; the draft's
            # are small by construction
            self.draft_pool = PagedKVPool(
                self.draft_model.num_layers, cfg.num_pages, cfg.page_size,
                self.draft_model.num_heads, d_head,
                dtype=self.draft_model.dtype, kv_quant=cfg.kv_quant,
                read="exact")
        elif draft_model is not None:
            raise ValueError("draft_model given but cfg.spec_k == 0: set "
                             "spec_k to the draft window size")
        # max_len always terminates the bucket ladder: a custom list that
        # stops short of a legal prompt must widen to max_len, not crash
        # the admit (and leak its granted pages) on a missing bucket
        self.buckets = tuple(sorted({self.max_len, *(
            b for b in (cfg.prefill_buckets or _default_buckets(self.max_len))
            if b <= self.max_len)}))
        # sp prefill needs buckets whose shards hold WHOLE pages: the
        # striped prompt allocation places block-table slot t on device
        # (t*page_size)//shard_len, which is only well-defined when
        # shard_len % page_size == 0
        self.sp_buckets: Tuple[int, ...] = ()
        if cfg.sp_prefill_threshold > 0:
            if mesh is None:
                raise ValueError("sp_prefill_threshold > 0 needs "
                                 "ServeEngine(mesh=...) with the "
                                 f"{SP_AXIS!r} axis")
            step = self.sp_n * cfg.page_size
            if self.max_len % step:
                raise ValueError(
                    f"sp prefill needs max_len ({self.max_len}) divisible "
                    f"by sp devices x page_size ({self.sp_n} x "
                    f"{cfg.page_size}) so every prompt has an sp bucket")
            self.sp_buckets = tuple(b for b in self.buckets if b % step == 0)
        self.slots: List[Optional[_Slot]] = [None] * cfg.max_slots
        self.queue: Deque[Tuple[DecodeRequest, float]] = deque()
        self._now = now_fn
        self._rng = rng if rng is not None else jax.random.PRNGKey(0)
        self.ledger = ledger
        # request tracing (obs.reqtrace): a ledger implies spans — callers
        # with a fleet identity (sim.worker) inject their own tracer so
        # trace ids stitch across hosts; standalone serving defaults to a
        # local single-job namespace
        self.tracer = tracer
        if self.tracer is None and ledger is not None:
            self.tracer = RequestTracer(ledger, job_id="serve", attempt=0)
        # the pool's prefix/CoW work happens inside admission — bind the
        # trace context so hits and forks surface as detail spans
        self.pool.bind_trace(self.tracer, self._now)
        # counters / SLO state
        self.ticks = 0
        self.completed = 0
        self.rejected = 0
        self.prefills = 0
        self.sp_prefills = 0
        # chunked-prefill accounting: chunk dispatches interleaved with
        # the decode stream, plus the cumulative prefill TOKEN work — the
        # per-step delta is the virtual cost-model clock's prefill term
        # (tools/decode_bench.py --long-context)
        self.chunk_ticks = 0
        self.prefill_token_work = 0
        # speculative accounting: emitted tokens vs active-slot tick
        # opportunities — accepted_per_tick = spec_emitted/spec_slot_ticks
        # (identically 1.0 for plain decode; > 1.0 is speculation's win)
        self.spec_emitted = 0
        self.spec_slot_ticks = 0
        # prefix-cache accounting: prompt pages needed vs served shared
        self.prompt_pages = 0
        self.shared_prompt_pages = 0
        self._occupancy_sum = 0.0
        self._wait_ema: Optional[float] = None
        self._wait_samples = 0
        self._in_breach = False
        self.shedding = False
        self._last_kv_tick = 0
        # graceful drain (round 13): a preemption SIGTERM finishes the
        # in-flight sequences and sheds the rest instead of dying mid-tick
        self._t_start = self._now()
        self.draining = False
        self._drained = False
        self._preempt_event = threading.Event()
        self._prev_sigterm = None

    # -- admission --------------------------------------------------------
    def submit(self, req: DecodeRequest) -> bool:
        """Queue one request; False = rejected by admission control (the
        caller's signal to back off / retry elsewhere)."""
        now = self._now()
        prompt_len = int(np.asarray(req.prompt).size)
        total = prompt_len + req.max_new_tokens
        if prompt_len < 1 or req.max_new_tokens < 1 or total > self.max_len:
            # degenerate geometry (empty prompt, nothing to generate, or
            # beyond max_len) can never be served — reject at the door
            # rather than crash a slot after pages were granted
            self._emit_admit(req, now, False, "too_long")
            return False
        if self.pool.pages_needed(total) > self.pool.num_pages:
            self._emit_admit(req, now, False, "exceeds_pool")
            return False
        if self.draining:
            # a draining server takes nothing new: the caller's signal to
            # retry elsewhere, same contract as SLO shedding
            self._emit_admit(req, now, False, "shed")
            return False
        if self.shedding:
            self._emit_admit(req, now, False, "slo_shedding")
            return False
        if len(self.queue) >= self.cfg.queue_depth_max:
            self._emit_admit(req, now, False, "queue_full")
            return False
        free_frac = self.pool.pages_free / max(self.pool.num_pages, 1)
        if free_frac < self.cfg.free_page_watermark:
            self._emit_admit(req, now, False, "page_watermark")
            return False
        self.queue.append((req, now))
        self._emit_admit(req, now, True, None)
        return True

    def _emit_admit(self, req, now, accepted, reason, enq_ts=None):
        if not accepted:
            self.rejected += 1
        if self.ledger is None:
            return
        self.ledger.emit("admit", rid=req.rid, accepted=accepted,
                         queue_depth=len(self.queue),
                         pages_free=self.pool.pages_free,
                         reason=reason, tenant=req.tenant,
                         ts_engine=round(now, 6))
        if accepted or self.tracer is None:
            return
        # every rejection is a 'shed' span: zero-duration at the door
        # (submit-time admission control), enq->now for a queued request
        # shed by drain — the trace-side record that lets a re-admission
        # on ANOTHER host stitch into the same trace_id
        tr = self.tracer
        tid, sid, par = tr.ids(req.rid, "shed")
        tr.ledger.emit("span", trace_id=tid, span_id=sid, parent_id=par,
                       name="shed", rid=req.rid,
                       start=round(now if enq_ts is None else enq_ts, 6),
                       end=round(now, 6), reason=reason,
                       tenant=req.tenant, **tr.attrs())

    def _observe_wait(self, wait: float) -> None:
        a = self.cfg.slo_alpha
        self._wait_ema = (wait if self._wait_ema is None
                          else a * wait + (1 - a) * self._wait_ema)
        self._wait_samples += 1
        floor = self.cfg.slo_queue_wait_s
        if floor <= 0 or self._wait_samples < self.cfg.slo_min_samples:
            return
        if self._wait_ema > floor and not self._in_breach:
            self._in_breach = True
            self.shedding = True
            if self.ledger is not None:
                # the standard progress-SLO event: the flight recorder and
                # the slo-breach counter hang off the normal sink fan-out
                self.ledger.emit("slo", step=self.ticks, kind="queue_wait",
                                 value=round(self._wait_ema, 6), floor=floor,
                                 unit="s")
        elif self._wait_ema <= floor and self._in_breach:
            self._in_breach = False   # re-arm; resume admitting
            self.shedding = False

    def _decay_wait_if_idle(self) -> None:
        """While shedding with an EMPTY queue, the only wait evidence left
        is stale — a fresh request would start from a drained backlog. The
        EMA only updates on admissions, so without this decay a transient
        overload would shed forever once the queue drained (no admissions
        -> no observations -> no re-arm). One alpha-decay toward zero per
        scheduler iteration restores the hysteresis loop's downswing."""
        if not self.shedding or self.queue or self._wait_ema is None:
            return
        self._wait_ema *= (1 - self.cfg.slo_alpha)
        if self._wait_ema <= self.cfg.slo_queue_wait_s:
            self._in_breach = False
            self.shedding = False

    # -- the scheduler iteration -----------------------------------------
    def step(self) -> List[Completion]:
        """One iteration: evict finished sequences (freeing their slots
        and pages), admit + prefill from the queue into the free slots,
        then run one decode tick over the packed active set. Returns the
        completions evicted this iteration."""
        completions = self._evict()
        self._admit()
        self._chunk_tick()
        self._tick()
        self._decay_wait_if_idle()
        every = self.cfg.kv_event_every
        # keyed on DECODE ticks, deduplicated: idle iterations don't
        # advance the counter and must neither spam one event per loop
        # nor re-emit the same tick's snapshot
        if (every > 0 and self.ticks % every == 0
                and self.ticks != self._last_kv_tick):
            self._last_kv_tick = self.ticks
            self._emit_kv_cache()
        return completions

    def run(self, requests=(), max_ticks: int = 100_000) -> List[Completion]:
        """Submit everything, then step until drained (tests, batch jobs).
        Rejected submissions are simply absent from the completions. A
        preemption SIGTERM (:meth:`install_sigterm_drain`) switches to
        :meth:`drain` at the next tick boundary instead of dying mid-tick."""
        for req in requests:
            self.submit(req)
        out: List[Completion] = []
        while self.queue or any(s is not None for s in self.slots):
            if self._preempt_event.is_set() and not self.draining:
                # drain() already emitted the final kv_cache + run_end;
                # falling through to the normal-completion epilogue would
                # double-emit the final pressure snapshot
                out.extend(self.drain(reason="sigterm"))
                return out
            out.extend(self.step())
            if self.ticks > max_ticks:
                raise RuntimeError(
                    f"serve drain exceeded {max_ticks} ticks "
                    f"({len(self.queue)} queued, "
                    f"{sum(s is not None for s in self.slots)} active)")
        self._emit_kv_cache()
        return out

    # -- graceful shutdown (round 13) -------------------------------------
    def install_sigterm_drain(self):
        """Route the scheduler's preemption SIGTERM into a graceful drain:
        the handler only sets a flag (signal-safe — no jax, no locks), and
        :meth:`run` drains at its next tick boundary. Main thread only;
        returns an uninstall callable."""
        prev = signal.signal(signal.SIGTERM,
                             lambda signum, frame: self._preempt_event.set())
        self._prev_sigterm = prev

        def uninstall():
            signal.signal(signal.SIGTERM, prev)
            self._prev_sigterm = None

        return uninstall

    def drain(self, reason: str = "sigterm", max_ticks: int = 100_000,
              emit_run_end: bool = True) -> List[Completion]:
        """Graceful shutdown: finish every IN-FLIGHT sequence (they hold
        pages and partial generations — killing them wastes the work),
        reject the whole queue with a ``shed`` admission record (the
        caller's signal to retry elsewhere), free all pages via the normal
        eviction path, and emit ``run_end`` so the ledger shows a drained
        server, not a mid-tick corpse. Idempotent; returns the completions
        of the in-flight sequences. ``emit_run_end=False`` leaves the
        final ``run_end`` to a caller that owns run lifecycle already
        (the fleet-sim worker's RunObs stamps its own status/lineage —
        two run_end records in one attempt would corrupt classification)."""
        if self._drained:
            return []
        self.draining = True
        shed = list(self.queue)
        self.queue.clear()
        now = self._now()
        for req, enq_ts in shed:
            # the shed span covers the request's whole queued life — the
            # wait it paid before this host gave up on it
            self._emit_admit(req, now, False, "shed", enq_ts=enq_ts)
        out: List[Completion] = []
        t0_ticks = self.ticks
        while any(s is not None for s in self.slots):
            out.extend(self.step())
            if self.ticks - t0_ticks > max_ticks:
                raise RuntimeError(
                    f"graceful drain exceeded {max_ticks} ticks with "
                    f"{sum(s is not None for s in self.slots)} still active")
        self._drained = True
        self._emit_kv_cache()  # final pressure snapshot: all pages free
        if self.ledger is not None:
            self.ledger.emit(
                "scale", action="drain", processes=1, epoch=None,
                reason=reason, shed=len(shed), finished=len(out))
            if emit_run_end:
                self.ledger.emit(
                    "run_end", steps=self.ticks,
                    seconds=round(self._now() - self._t_start, 6),
                    status="preempted", reason=reason,
                    completed=self.completed, rejected=self.rejected,
                    shed=len(shed))
        return out

    # -- internals --------------------------------------------------------
    def _evict(self) -> List[Completion]:
        out = []
        for i, slot in enumerate(self.slots):
            if slot is None or not slot.done:
                continue
            self.pool.free(slot.pages)
            self.slots[i] = None
            n = slot.prompt_len + slot.generated
            comp = Completion(
                rid=slot.req.rid, tokens=slot.buf[:n].copy(),
                prompt_len=slot.prompt_len, n_generated=slot.generated,
                admit_ts=slot.admit_ts, start_ts=slot.start_ts,
                first_token_ts=slot.first_token_ts,
                finish_ts=slot.finish_ts)
            self.completed += 1
            out.append(comp)
            if self.ledger is not None:
                self.ledger.emit(
                    "request", rid=comp.rid, tokens=comp.n_generated,
                    queue_wait_s=round(comp.queue_wait_s, 6),
                    admit_ts=round(comp.admit_ts, 6),
                    first_token_ts=round(comp.first_token_ts, 6),
                    finish_ts=round(comp.finish_ts, 6),
                    prompt_len=comp.prompt_len,
                    tenant=slot.req.tenant,
                    ttft_s=round(comp.ttft_s, 6))
            if self.tracer is not None:
                # the root span: this (job, attempt)'s whole view of the
                # request, admit->finish. Emitted at eviction, after every
                # child — readers key the tree on ids, not emit order
                tr = self.tracer
                tid, sid, par = tr.root_ids(comp.rid)
                tr.ledger.emit("span", trace_id=tid, span_id=sid,
                               parent_id=par, name="request", rid=comp.rid,
                               start=round(comp.admit_ts, 6),
                               end=round(comp.finish_ts, 6),
                               ttft_s=round(comp.ttft_s, 6),
                               queue_wait_s=round(comp.queue_wait_s, 6),
                               tokens=comp.n_generated,
                               prompt_len=comp.prompt_len,
                               tenant=slot.req.tenant, **tr.attrs())
        return out

    def _admit(self) -> None:
        if self.cfg.refill == "drain" and any(
                s is not None for s in self.slots):
            return  # static batching: refill only once the batch drained
        for i in range(len(self.slots)):
            if not self.queue:
                break
            if self.slots[i] is not None:
                continue
            req, enq_ts = self.queue[0]
            prompt = np.asarray(req.prompt, np.int32).reshape(-1)
            p = prompt.size
            total = p + req.max_new_tokens
            total_slots = self.pool.pages_needed(total)
            use_sp = (self.cfg.sp_prefill_threshold > 0
                      and p >= self.cfg.sp_prefill_threshold)
            use_chunk = (not use_sp and self.cfg.prefill_chunk > 0
                         and p > self.cfg.prefill_chunk)
            sp_bucket = (next(b for b in self.sp_buckets if b >= p)
                         if use_sp else None)
            match = (self.pool.share_prefix(prompt, rid=req.rid)
                     if self.cfg.prefix_cache else None)
            # fresh pages: everything past the FULL-page hits. A frontier
            # (partial-page) hit replaces one fresh prompt page but
            # reserves one fresh page as its copy-on-write destination —
            # reserving at admission means the later fork can never fail,
            # so the net fresh cost is total_slots - full either way.
            n_fresh = total_slots - (match.full if match is not None else 0)
            if use_sp:
                # striped prompt pages: slot t's rows are scattered by the
                # device whose prompt shard covers them, so the page must
                # physically live there. Shared slots sit wherever their
                # writer put them (reads are location-free); decode-tail
                # pages (and the CoW reserve) are unconstrained.
                shard = sp_bucket // self.sp_n
                shared_slots = len(match.pages) if match is not None else 0
                stripe = [(t * self.cfg.page_size) // shard
                          for t in range(shared_slots,
                                         self.pool.pages_needed(p))]
                fresh = self.pool.alloc_for_slots(stripe)
                if fresh is not None:
                    rest = self.pool.alloc(n_fresh - len(stripe))
                    if rest is None:
                        self.pool.free(fresh)
                        fresh = None
                    else:
                        fresh = fresh + rest
            else:
                fresh = self.pool.alloc(n_fresh)
            if fresh is None:
                if match is not None:
                    self.pool.unshare(match)
                break  # pool pressure: leave it queued, decode on
            self.queue.popleft()
            now = self._now()
            self._observe_wait(now - enq_ts)
            if self.tracer is not None:
                # the queue span closes the moment the request leaves the
                # backlog — with prefill starting the same instant, queue +
                # prefill tile admit->first-token exactly (the attribution
                # sum-check's first half)
                tr = self.tracer
                tid, sid, par = tr.ids(req.rid, "queue")
                tr.ledger.emit("span", trace_id=tid, span_id=sid,
                               parent_id=par, name="queue", rid=req.rid,
                               start=round(enq_ts, 6), end=round(now, 6),
                               queue_depth=len(self.queue),
                               tenant=req.tenant, **tr.attrs())
            if use_sp:
                self._prefill_sp(i, req, prompt, fresh, enq_ts, now, match,
                                 sp_bucket)
            elif use_chunk:
                self._begin_chunked(i, req, prompt, fresh, enq_ts, now,
                                    match)
            else:
                self._prefill(i, req, prompt, fresh, enq_ts, now, match)

    def _prefill(self, slot_idx, req, prompt, fresh, enq_ts, start_ts,
                 match: Optional[PrefixMatch] = None):
        p = prompt.size
        bucket = next(b for b in self.buckets if b >= p)
        shared = list(match.pages) if match is not None else []
        shared_len = match.cov if match is not None else 0
        cow = None
        if match is not None and match.partial:
            # the block table reads through the SHARED frontier page at
            # slot match.full; the last fresh page is its reserved CoW
            # destination, forked right before this sequence's first
            # decode write (_resolve_cow)
            cow = (match.full, shared[-1], fresh[-1])
            bt_pages = shared + fresh[:-1]
        else:
            bt_pages = shared + fresh
        bt = np.full((self.max_pages_per_seq,), self.pool.num_pages,
                     np.int32)                       # unassigned -> trash
        bt[:len(bt_pages)] = bt_pages
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :p] = prompt
        program = _prefill_program(self.model, self.cfg.temperature,
                                   self.cfg.top_k, self.cfg.top_p,
                                   self.sp_mesh)
        # recompile sentry (analysis.proglint PL005): prefill specializes
        # per bucket BY DESIGN, so its allowed trace-cache size is the
        # bucket-ladder length, not 1 (no-op when the audit is off)
        register_audit_program("serve_prefill", program,
                               allowed=len(self.buckets))
        tok, new_layers, self._rng = program(
            self.params, self.pool.layers(),
            jnp.asarray(self.pool.flat_block_table(bt[None])),
            jnp.int32(p), jnp.int32(shared_len), jnp.asarray(padded),
            self._rng)
        self.pool.adopt(new_layers)
        self.prefill_token_work += bucket
        if self.draft_pool is not None:
            # the draft's prompt rows, through the same block table (the
            # pools share page indices); shared rows were written by the
            # earlier prefix owner's draft prefill, so the mask matches
            dprog = _draft_prefill_program(self.draft_model)
            self.draft_pool.adopt(dprog(
                self.draft_params, self.draft_pool.layers(),
                jnp.asarray(bt[None]), jnp.int32(p), jnp.int32(shared_len),
                jnp.asarray(padded)))
        if self.cfg.prefix_cache:
            # index this prompt's freshly-written pages for future sharers
            # (shared slots are already indexed by their original writer)
            self.pool.register_prefix(prompt, bt_pages,
                                      skip_slots=len(shared))
            self.prompt_pages += self.pool.pages_needed(p)
            self.shared_prompt_pages += len(shared)
        self.prefills += 1
        # the scheduler IS the drain boundary: the first token decides
        # done/eos and the TTFT stamp before the next iteration
        # distlint: disable=DL002 -- iteration-level scheduling syncs once per admit by design
        tok = int(jax.device_get(tok))
        now = self._now()
        slot = _Slot(req=req, pages=shared + fresh, block_table=bt,
                     buf=np.zeros((p + req.max_new_tokens,), np.int32),
                     prompt_len=p, admit_ts=enq_ts, start_ts=start_ts,
                     position=p, generated=1, first_token_ts=now,
                     cow_pending=cow, win_start_ts=now)
        slot.buf[:p] = prompt
        slot.buf[p] = tok
        if (slot.generated >= req.max_new_tokens
                or tok == self.cfg.eos_id):
            slot.done = True
            slot.finish_ts = now
        self.slots[slot_idx] = slot
        if self.tracer is not None:
            # prefill span: queue-exit -> first token, carrying the knobs
            # that explain a slow one (bucket padding, fresh vs shared
            # pages, a pending CoW fork)
            tr = self.tracer
            tid, sid, par = tr.ids(req.rid, "prefill")
            tr.ledger.emit("span", trace_id=tid, span_id=sid,
                           parent_id=par, name="prefill", rid=req.rid,
                           start=round(start_ts, 6), end=round(now, 6),
                           bucket=bucket, prompt_len=p,
                           pages_fresh=len(fresh),
                           pages_shared=len(shared),
                           shared_len=shared_len, cow=cow is not None,
                           tenant=req.tenant, **tr.attrs())

    # -- chunked prefill ---------------------------------------------------
    def _begin_chunked(self, slot_idx, req, prompt, fresh, enq_ts, start_ts,
                       match: Optional[PrefixMatch] = None):
        """Admit a long prompt WITHOUT running its prefill: the slot parks
        with ``chunk_next >= 0`` (outside the decode tick's active set) and
        :meth:`_chunk_tick` feeds it one fixed-size chunk per scheduler
        iteration — a 16k admit costs many bounded steps interleaved with
        the decode stream instead of one full-prompt stall. First token,
        prefix registration, and the prefill span all land on the FINAL
        chunk (the pages only hold the whole prompt then)."""
        p = prompt.size
        chunk = self.cfg.prefill_chunk
        shared = list(match.pages) if match is not None else []
        shared_len = match.cov if match is not None else 0
        cow = None
        if match is not None and match.partial:
            cow = (match.full, shared[-1], fresh[-1])
            bt_pages = shared + fresh[:-1]
        else:
            bt_pages = shared + fresh
        bt = np.full((self.max_pages_per_seq,), self.pool.num_pages,
                     np.int32)
        bt[:len(bt_pages)] = bt_pages
        slot = _Slot(req=req, pages=shared + fresh, block_table=bt,
                     buf=np.zeros((p + req.max_new_tokens,), np.int32),
                     prompt_len=p, admit_ts=enq_ts, start_ts=start_ts,
                     position=p, generated=0, cow_pending=cow,
                     # start at the chunk holding the first NON-shared row
                     # (a fully-shared prompt still runs its last chunk:
                     # writes are masked, but the final chunk's logits are
                     # where the first token comes from)
                     chunk_next=min(shared_len, p - 1) // chunk * chunk,
                     shared_len=shared_len,
                     n_fresh=len(fresh), n_shared=len(shared))
        slot.buf[:p] = prompt
        self.slots[slot_idx] = slot

    def _chunk_tick(self) -> None:
        """At most ONE prefill chunk per scheduler iteration — the knob
        that bounds how much prefill compute any decode tick waits behind
        (the TPOT-interference contract tools/decode_bench.py measures).
        Lowest slot index first: admission order, no starvation."""
        for i, s in enumerate(self.slots):
            if s is not None and not s.done and s.chunk_next >= 0:
                self._run_chunk(i, s)
                return

    def _run_chunk(self, slot_idx: int, s: _Slot) -> None:
        cfg = self.cfg
        chunk = cfg.prefill_chunk
        p = s.prompt_len
        start = s.chunk_next
        tokens = np.zeros((1, chunk), np.int32)
        seg = s.buf[start:min(start + chunk, p)]
        tokens[0, :seg.size] = seg
        program = _chunk_prefill_program(self.model, chunk, self.sp_mesh)
        # one chunk geometry per deployment: any retrace is a bug
        register_audit_program("serve_chunk_prefill", program)
        last, new_layers = program(
            self.params, self.pool.layers(),
            jnp.asarray(self.pool.flat_block_table(s.block_table[None])),
            jnp.int32(start), jnp.int32(p), jnp.int32(s.shared_len),
            jnp.asarray(tokens))
        self.pool.adopt(new_layers)
        self.chunk_ticks += 1
        self.prefill_token_work += chunk
        if start + chunk < p:
            s.chunk_next = start + chunk
            return
        # final chunk: the prompt is fully resident — sample the first
        # token (ONE rng consumption per admit, same as monolithic),
        # index the pages for future sharers, open the decode life
        s.chunk_next = -1
        sampler = _sample_first_program(cfg.temperature, cfg.top_k,
                                        cfg.top_p)
        register_audit_program("serve_chunk_sample", sampler)
        tok, self._rng = sampler(last, self._rng)
        if self.draft_pool is not None:
            # the draft arenas are tiny: its prompt pass stays monolithic
            # (and on the LOGICAL block table — the draft pool is never
            # sharded), keeping the chunked path draft-compatible
            bucket = next(b for b in self.buckets if b >= p)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :p] = s.buf[:p]
            dprog = _draft_prefill_program(self.draft_model)
            self.draft_pool.adopt(dprog(
                self.draft_params, self.draft_pool.layers(),
                jnp.asarray(s.block_table[None]), jnp.int32(p),
                jnp.int32(s.shared_len), jnp.asarray(padded)))
        if cfg.prefix_cache:
            # register only NOW: until the final chunk the pages hold a
            # partial prompt and a hit against them would read garbage
            bt_pages = [int(x) for x in s.block_table
                        if int(x) < self.pool.num_pages]
            self.pool.register_prefix(s.buf[:p], bt_pages,
                                      skip_slots=s.n_shared)
            self.prompt_pages += self.pool.pages_needed(p)
            self.shared_prompt_pages += s.n_shared
        self.prefills += 1
        # distlint: disable=DL002 -- iteration-level scheduling syncs once per admit by design
        tok = int(jax.device_get(tok))
        now = self._now()
        s.buf[p] = tok
        s.generated = 1
        s.first_token_ts = now
        s.win_start_ts = now
        if s.generated >= s.req.max_new_tokens or tok == cfg.eos_id:
            s.done = True
            s.finish_ts = now
        if self.tracer is not None:
            tr = self.tracer
            tid, sid, par = tr.ids(s.req.rid, "prefill")
            first = min(s.shared_len, p - 1) // chunk * chunk
            tr.ledger.emit("span", trace_id=tid, span_id=sid,
                           parent_id=par, name="prefill", rid=s.req.rid,
                           start=round(s.start_ts, 6), end=round(now, 6),
                           mode="chunked", chunk=chunk,
                           chunks=-(-(p - first) // chunk),
                           prompt_len=p, pages_fresh=s.n_fresh,
                           pages_shared=s.n_shared,
                           shared_len=s.shared_len,
                           cow=s.cow_pending is not None,
                           tenant=s.req.tenant, **tr.attrs())

    # -- sequence-parallel prefill -----------------------------------------
    def _prefill_sp(self, slot_idx, req, prompt, fresh, enq_ts, start_ts,
                    match: Optional[PrefixMatch], bucket: int):
        """Monolithic-shaped admission, sequence-parallel execution: the
        prompt pads to an sp bucket and every device prefills ITS shard
        under ring attention, scattering K/V into the pages the striped
        allocation placed on it (_sp_prefill_program has the mechanics)."""
        p = prompt.size
        shared = list(match.pages) if match is not None else []
        shared_len = match.cov if match is not None else 0
        cow = None
        if match is not None and match.partial:
            cow = (match.full, shared[-1], fresh[-1])
            bt_pages = shared + fresh[:-1]
        else:
            bt_pages = shared + fresh
        bt = np.full((self.max_pages_per_seq,), self.pool.num_pages,
                     np.int32)
        bt[:len(bt_pages)] = bt_pages
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :p] = prompt
        program = _sp_prefill_program(self.model, self.sp_mesh,
                                      self.cfg.temperature, self.cfg.top_k,
                                      self.cfg.top_p)
        # specializes per sp bucket, same contract as serve_prefill
        register_audit_program("serve_sp_prefill", program,
                               allowed=max(len(self.sp_buckets), 1))
        tok, new_layers, self._rng = program(
            self.params, self.pool.layers(),
            jnp.asarray(self.pool.flat_block_table(bt[None])),
            jnp.int32(p), jnp.int32(shared_len), jnp.asarray(padded),
            self._rng)
        self.pool.adopt(new_layers)
        if self.cfg.prefix_cache:
            self.pool.register_prefix(prompt, bt_pages,
                                      skip_slots=len(shared))
            self.prompt_pages += self.pool.pages_needed(p)
            self.shared_prompt_pages += len(shared)
        self.prefills += 1
        self.sp_prefills += 1
        # each device touches bucket/n rows: that's the wall the scheduler
        # waited behind, so that's what the virtual clock charges
        self.prefill_token_work += bucket // self.sp_n
        # distlint: disable=DL002 -- iteration-level scheduling syncs once per admit by design
        tok = int(jax.device_get(tok))
        now = self._now()
        slot = _Slot(req=req, pages=shared + fresh, block_table=bt,
                     buf=np.zeros((p + req.max_new_tokens,), np.int32),
                     prompt_len=p, admit_ts=enq_ts, start_ts=start_ts,
                     position=p, generated=1, first_token_ts=now,
                     cow_pending=cow, shared_len=shared_len,
                     n_fresh=len(fresh), n_shared=len(shared),
                     win_start_ts=now)
        slot.buf[:p] = prompt
        slot.buf[p] = tok
        if (slot.generated >= req.max_new_tokens
                or tok == self.cfg.eos_id):
            slot.done = True
            slot.finish_ts = now
        self.slots[slot_idx] = slot
        if self.tracer is not None:
            tr = self.tracer
            tid, sid, par = tr.ids(req.rid, "prefill")
            tr.ledger.emit("span", trace_id=tid, span_id=sid,
                           parent_id=par, name="prefill", rid=req.rid,
                           start=round(start_ts, 6), end=round(now, 6),
                           mode="sp", sp_devices=self.sp_n,
                           bucket=bucket, prompt_len=p,
                           pages_fresh=len(fresh),
                           pages_shared=len(shared),
                           shared_len=shared_len, cow=cow is not None,
                           tenant=req.tenant, **tr.attrs())

    def _resolve_cow(self, active) -> None:
        """Fork every pending shared frontier page before this tick's
        writes: each forking sequence gets the page's bits duplicated onto
        its admission-reserved destination (both pools when speculating —
        the arenas mirror page indices) and swaps its block-table entry;
        the other holders keep reading the original page untouched."""
        for _i, s in active:
            if s.cow_pending is None:
                continue
            bt_slot, src, dst = s.cow_pending
            # copies arenas, drops our src ref
            self.pool.fork_page(src, dst, rid=s.req.rid)
            if self.draft_pool is not None:
                src_a = jnp.asarray([src], jnp.int32)
                dst_a = jnp.asarray([dst], jnp.int32)
                self.draft_pool.adopt(cow_fork_pages(
                    self.draft_pool.layers(), src_a, dst_a))
            s.block_table[bt_slot] = dst
            s.pages.remove(src)
            s.cow_pending = None

    def _tick(self) -> None:
        # a slot mid-chunked-prefill (chunk_next >= 0) has no token to
        # decode yet — it keeps its pages but sits out the tick
        active = [(i, s) for i, s in enumerate(self.slots)
                  if s is not None and not s.done and s.chunk_next < 0]
        if not active:
            return
        self._resolve_cow(active)
        if self.cfg.spec_k > 0:
            return self._tick_spec(active)
        n = len(self.slots)
        tokens = np.zeros((n,), np.int32)
        positions = np.zeros((n,), np.int32)
        bts = np.full((n, self.max_pages_per_seq), self.pool.num_pages,
                      np.int32)
        for i, s in active:
            tokens[i] = s.buf[s.prompt_len + s.generated - 1]
            positions[i] = s.position
            bts[i] = s.block_table
        program = _tick_program(self.model, self.cfg.temperature,
                                self.cfg.top_k, self.cfg.top_p,
                                self.sp_mesh)
        # tick shapes are occupancy-invariant (inactive slots ride the
        # trash page), so ANY cache growth is a retrace hazard: allowed=1
        register_audit_program("serve_tick", program)
        nxt, new_layers, self._rng = program(
            self.params, self.pool.layers(),
            jnp.asarray(self.pool.flat_block_table(bts)),
            jnp.asarray(tokens), jnp.asarray(positions), self._rng)
        self.pool.adopt(new_layers)
        # iteration-level scheduling: every tick's tokens come back to the
        # host so finished sequences free their slot/pages for the SAME-
        # tick refill — the one sync per tick is the scheduling primitive,
        # not an accident (Orca's design point)
        # distlint: disable=DL002 -- the per-tick sync is the scheduler's eviction/refill decision point
        nxt = np.asarray(jax.device_get(nxt))
        now = self._now()
        for i, s in active:
            tok = int(nxt[i])
            s.buf[s.prompt_len + s.generated] = tok
            s.generated += 1
            s.position += 1
            if (s.generated >= s.req.max_new_tokens
                    or tok == self.cfg.eos_id):
                s.done = True
                s.finish_ts = now
            self._note_decode(s, now, tokens=1)
        self.ticks += 1
        self._occupancy_sum += len(active) / max(len(self.slots), 1)

    def _tick_spec(self, active) -> None:
        """One speculative iteration: k draft proposals + one base verify
        per active slot, all in one dispatch (_spec_tick_program), then
        host-side emission with per-slot budget/eos truncation — the same
        sync point the plain tick already pays, now worth up to k tokens."""
        n = len(self.slots)
        k = self.cfg.spec_k
        tokens = np.zeros((n,), np.int32)
        positions = np.zeros((n,), np.int32)
        caps = np.zeros((n,), np.int32)
        bts = np.full((n, self.max_pages_per_seq), self.pool.num_pages,
                      np.int32)
        for i, s in active:
            tokens[i] = s.buf[s.prompt_len + s.generated - 1]
            positions[i] = s.position
            # the write-mask cap: rows past the allocation routed to trash
            # (a draft window can overrun a nearly-done request)
            caps[i] = s.prompt_len + s.req.max_new_tokens
            bts[i] = s.block_table
        program = _spec_tick_program(self.model, self.draft_model, k)
        # same occupancy-invariance as the plain tick: allowed=1
        register_audit_program("serve_spec_tick", program)
        emitted, emit_n, new_layers, new_dlayers = program(
            self.params, self.draft_params, self.pool.layers(),
            self.draft_pool.layers(),
            jnp.asarray(self.pool.flat_block_table(bts)),
            jnp.asarray(tokens), jnp.asarray(positions), jnp.asarray(caps))
        self.pool.adopt(new_layers)
        self.draft_pool.adopt(new_dlayers)
        # distlint: disable=DL002 -- the per-tick sync is the scheduler's eviction/refill decision point
        emitted, emit_n = map(np.asarray, jax.device_get((emitted, emit_n)))
        now = self._now()
        for i, s in active:
            took = 0
            for j in range(int(emit_n[i])):
                tok = int(emitted[i, j])
                s.buf[s.prompt_len + s.generated] = tok
                s.generated += 1
                s.position += 1
                self.spec_emitted += 1
                took += 1
                if (s.generated >= s.req.max_new_tokens
                        or tok == self.cfg.eos_id):
                    s.done = True
                    s.finish_ts = now
                    break
            self.spec_slot_ticks += 1
            self._note_decode(s, now, tokens=took, drafted=k)
        self.ticks += 1
        self._occupancy_sum += len(active) / max(len(self.slots), 1)

    def _note_decode(self, s: _Slot, now: float, tokens: int,
                     drafted: int = 0) -> None:
        """Advance the slot's open decode window; close it into a span
        every ``trace_window_ticks`` ticks and at finish. Consecutive
        windows share their boundary timestamp, so a request's decode
        spans tile first-token->finish with zero residue — the property
        the attribution sum-check (tools/request_report.py) leans on."""
        if self.tracer is None:
            return
        s.win_ticks += 1
        s.win_tokens += tokens
        s.win_drafted += drafted
        if not s.done and s.win_ticks < max(self.cfg.trace_window_ticks, 1):
            return
        tr = self.tracer
        tid, sid, par = tr.ids(s.req.rid, "decode")
        tr.ledger.emit("span", trace_id=tid, span_id=sid, parent_id=par,
                       name="decode", rid=s.req.rid,
                       start=round(s.win_start_ts, 6), end=round(now, 6),
                       ticks=s.win_ticks, tokens=s.win_tokens,
                       spec_drafted=s.win_drafted, **tr.attrs())
        s.win_start_ts = now
        s.win_ticks = s.win_tokens = s.win_drafted = 0

    def _emit_kv_cache(self) -> None:
        # serving's drain boundary: the periodic pressure snapshot — the
        # recompile sentry's host-only counter read rides it (PL005)
        check_audit_sentry()
        if self.ledger is None:
            return
        st = self.pool.stats()
        self.ledger.emit("kv_cache", pages_free=st["pages_free"],
                         pages_used=st["pages_used"],
                         active_seqs=sum(s is not None for s in self.slots),
                         pages_total=st["pages_total"],
                         high_water_used=st["high_water_used"],
                         shared_pages=st["shared_pages"],
                         cow_copies=st["cow_copies"],
                         prefix_hits=st["prefix_hits"],
                         spec_emitted=self.spec_emitted,
                         spec_slot_ticks=self.spec_slot_ticks,
                         sharded_devices=st["sharded_devices"],
                         chunks_pending=self.chunks_pending,
                         chunk_ticks=self.chunk_ticks,
                         slots=len(self.slots), tick=self.ticks)

    # -- introspection ----------------------------------------------------
    @property
    def occupancy(self) -> float:
        """Mean active-slot share across decode ticks — the utilization
        number that separates continuous from static batching."""
        return self._occupancy_sum / self.ticks if self.ticks else 0.0

    @property
    def accepted_per_tick(self) -> Optional[float]:
        """Mean tokens emitted per active-slot tick — identically 1.0 for
        plain decode (not tracked there: None), > 1.0 is speculation's
        whole win. The serving-side analog of offline tok/s."""
        if not self.spec_slot_ticks:
            return None
        return self.spec_emitted / self.spec_slot_ticks

    @property
    def chunks_pending(self) -> int:
        """Prefill chunks still owed to parked slots — the chunk-queue
        depth the ledger's kv_cache events trend (a growing number means
        admission outruns the one-chunk-per-iteration budget)."""
        c = self.cfg.prefill_chunk
        if c <= 0:
            return 0
        return sum(-(-(s.prompt_len - s.chunk_next) // c)
                   for s in self.slots
                   if s is not None and s.chunk_next >= 0)

    @property
    def prefix_hit_rate(self) -> Optional[float]:
        """Share of prompt pages served from the prefix cache instead of
        freshly written (None until a prefix-cached prompt is admitted)."""
        if not self.prompt_pages:
            return None
        return self.shared_prompt_pages / self.prompt_pages

    def stats(self) -> dict:
        apt = self.accepted_per_tick
        phr = self.prefix_hit_rate
        return {"ticks": self.ticks, "completed": self.completed,
                "rejected": self.rejected, "prefills": self.prefills,
                "sp_prefills": self.sp_prefills,
                "chunk_ticks": self.chunk_ticks,
                "chunks_pending": self.chunks_pending,
                "prefill_token_work": self.prefill_token_work,
                "occupancy": round(self.occupancy, 6),
                "spec_k": self.cfg.spec_k,
                "spec_emitted": self.spec_emitted,
                "spec_slot_ticks": self.spec_slot_ticks,
                "accepted_per_tick": (None if apt is None
                                      else round(apt, 6)),
                "prompt_pages": self.prompt_pages,
                "shared_prompt_pages": self.shared_prompt_pages,
                "prefix_hit_rate": (None if phr is None
                                    else round(phr, 6)),
                "pages_per_request": (
                    round(self.pool.alloc_total / self.completed, 6)
                    if self.completed else None),
                "queue_depth": len(self.queue),
                "active_seqs": sum(s is not None for s in self.slots),
                "wait_ema_s": self._wait_ema,
                "shedding": self.shedding,
                "draining": self.draining,
                **self.pool.stats()}
