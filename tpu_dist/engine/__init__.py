from tpu_dist.engine.checkpoint import load_checkpoint, save_checkpoint  # noqa: F401
from tpu_dist.engine.loop import Trainer  # noqa: F401
from tpu_dist.engine.state import TrainState, init_model  # noqa: F401
from tpu_dist.engine.steps import (  # noqa: F401
    cross_entropy_sum, make_eval_step, make_multi_train_step,
    make_shard_map_train_step, make_train_step)
