"""Jitted train/eval steps (reference components C14/C15/C16 fused).

The reference's ~45-line per-batch hot loop (H2D copy -> forward -> loss ->
accuracy -> barrier -> metric allreduce -> zero_grad -> backward (grad
allreduce) -> step; reference 2.distributed.py:205-239) becomes ONE compiled
XLA program: normalize/augment, forward, loss, grads, cross-replica reduction,
optimizer update, and metric counts all fuse; there is no per-batch host
round-trip and no barrier (XLA orders the collectives).

Since round 15 this module holds the image engine's ONE step template
(:func:`_train_step_fn` around the shared :func:`_apply_update` funnel) and
the metric/loss helpers; every public ``make_*`` builder below is a THIN
SHIM over the plan compiler (``tpu_dist.plan.compile``) — it names its
variant as a declarative :class:`tpu_dist.plan.ir.Plan` and the compiler's
validate/template/window/partition passes produce the callable. The
builders' signatures and math are unchanged (loss/param parity is pinned
bit-for-bit in tests/test_plan.py); what changed is that the jit/
shard_map/windowed/bucketed/ring wrapper bodies now live once, in the
compiler, instead of once per builder.

Two interchangeable distribution flavors produce bit-comparable updates for
BatchNorm-free models (for BN models the gradient math still agrees, but the
running statistics differ by design — global-batch SyncBN vs per-replica +
pmean, see below):

* :func:`make_train_step` — *compiler-partitioned* (DDP-equivalent,
  reference variants 2/3/6): ``jit`` over a Mesh with the batch sharded on
  the ``data`` axis and params replicated; XLA inserts the gradient
  all-reduce exactly where DDP's bucketed NCCL allreduce fired. BatchNorm
  statistics are computed over the GLOBAL batch (SyncBN semantics — a
  documented improvement over per-replica torch BN).
* :func:`make_shard_map_train_step` — *explicit-collective*
  (horovod-equivalent, reference variant 5): ``shard_map`` gives one program
  per device; gradients are explicitly ``psum``'d with optional bf16
  compression (hvd.Compression.fp16-equiv) and predivide factor. BatchNorm
  stats stay per-replica then get pmean'd — mirroring horovod's
  local-BN-plus-broadcast behavior.

Metrics are returned as SUMS (loss*n, correct counts, sample count) so the
cross-replica reduction is exact regardless of ragged last batches — fixing
the reference's equal-weight averaging of per-rank fractions
(reference 2.distributed.py:221-227; SURVEY.md §7 'Metric parity').
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from tpu_dist.engine.state import TrainState
from tpu_dist.ops import precision as prec
from tpu_dist.parallel.mesh import DATA_AXIS
from tpu_dist.plan.ir import Plan


def cross_entropy_sum(logits: jax.Array, labels: jax.Array,
                      weights: jax.Array | None = None) -> jax.Array:
    """Summed (not averaged) NLL of log_softmax — numerically the reference's
    CrossEntropyLoss / F.nll_loss(log_softmax) (reference 5.2...py:52,66).
    Optional per-sample weights (eval padding mask)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
    if weights is not None:
        nll = nll * weights
    return jnp.sum(nll)


def _metric_sums(logits, labels, loss_sum, weights=None):
    """Metric SUMS; ``weights`` (0/1 per sample) excludes sampler padding."""
    w = jnp.ones(labels.shape, jnp.float32) if weights is None else weights
    top1 = (jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32)
    k = min(5, logits.shape[-1])
    topk_idx = jax.lax.top_k(logits, k)[1]
    top5 = jnp.any(topk_idx == labels[:, None], axis=-1).astype(jnp.float32)
    return {
        "loss_sum": loss_sum,
        "correct1": jnp.sum(top1 * w),
        "correct5": jnp.sum(top5 * w),
        "count": jnp.sum(w),
    }


def _loss_and_metrics(model, transform, params, batch_stats, images_u8, labels,
                      dropout_rng, aug_rng, loss_scale, train: bool):
    x = transform(images_u8, aug_rng)
    variables = {"params": params, "batch_stats": batch_stats}
    if train:
        logits, mutated = model.apply(
            variables, x, train=True, rngs={"dropout": dropout_rng},
            mutable=["batch_stats"])
        new_stats = mutated["batch_stats"]
    else:
        logits = model.apply(variables, x, train=False)
        new_stats = batch_stats
    n = jnp.float32(labels.shape[0])
    loss_sum = cross_entropy_sum(logits, labels)
    mean_loss = loss_sum / n
    metrics = _metric_sums(logits, labels, loss_sum)
    return prec.scale_loss(mean_loss, loss_scale), (new_stats, metrics)


def _apply_update(tx, state: TrainState, grads, new_stats, metrics,
                  health: str = "record", probe_sync=None):
    """Optimizer update + the fused numerical-health probes (obs.health).

    Every engine flavor — jit, shard_map, windowed, bucketed, ring, sp, pp
    — funnels its post-sync gradients through here, so the probes
    (grad_norm / nonfinite_count / update_norm) join EVERY step's metric
    sums and ride the existing drain-boundary fetch: zero new host syncs.
    ``health='skip'`` additionally gates the whole step on the probes: a
    non-finite gradient or update keeps params, optimizer state AND batch
    stats bit-identical while the step counter still advances — the data
    stream and the per-step RNG fold (both keyed on ``state.step``) move
    on, so N hosts stay in lockstep (the gate reads post-sync grads and is
    identical everywhere). ``health`` is trace-time static.

    ``probe_sync`` covers the one caller whose grads are NOT fully synced
    here: pipeline parallelism keeps block grads stage-local, so the pp
    step builders pass a stage-psum that makes the probe scalars (and any
    skip decision) identical on every device. The psum'd values are
    INDICATORS, not exact global quantities: the stage-replicated
    embed/head grads contribute once per stage, so a non-finite leaf
    there counts n_stages times and the summed per-stage norms upper-
    bound the true global norm — the >0 / finiteness gates are unaffected.
    """
    from tpu_dist.obs.health import probe_update_metrics, probes_ok

    grads, new_scale, finite = prec.unscale_and_update(grads, state.loss_scale)
    if hasattr(tx, "apply"):  # FusedSGD protocol: fused params+momentum update
        new_params, new_opt = tx.apply(state.params, grads, state.opt_state,
                                       state.step)
    else:  # optax GradientTransformation
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = jax.tree.map(lambda p, u: p + u, state.params, updates)
    # loss-scale skip: on non-finite grads keep old params/opt (apex behavior)
    if state.loss_scale is not None:
        new_params = jax.tree.map(
            lambda n, o: jnp.where(finite, n, o), new_params, state.params)
        new_opt = jax.tree.map(
            lambda n, o: jnp.where(finite, n, o), new_opt, state.opt_state)
    probes = probe_update_metrics(grads, state.params, new_params)
    if state.loss_scale is not None:
        # a dynamic-loss-scale overflow is ROUTINE (apex semantics: the
        # finite gate above already reverted the update and halved the
        # scale) — report the probes as clean zeros for that step so the
        # sentry never counts a healthy fp16 run as a health trip
        probes = jax.tree.map(
            lambda v: jnp.where(finite, v, jnp.zeros_like(v)), probes)
    if probe_sync is not None:
        probes = probe_sync(probes)
    if health == "skip":
        ok = probes_ok(probes)
        new_params = jax.tree.map(
            lambda n, o: jnp.where(ok, n, o), new_params, state.params)
        new_opt = jax.tree.map(
            lambda n, o: jnp.where(ok, n, o), new_opt, state.opt_state)
        # a NaN forward poisons BN running stats too — skip means skip
        new_stats = jax.tree.map(
            lambda n, o: jnp.where(ok, n, o), new_stats, state.batch_stats)
    metrics = {**metrics, **probes}
    return TrainState(step=state.step + 1, params=new_params,
                      batch_stats=new_stats, opt_state=new_opt,
                      loss_scale=new_scale), metrics


def _train_step_fn(model, tx, transform, health: str = "record") -> Callable:
    """The pure (unjitted) train step shared by all wrappers — THE image
    engine step template the plan compiler lowers."""

    def step(state: TrainState, images_u8, labels, rng):
        dropout_rng, aug_rng = jax.random.split(jax.random.fold_in(rng, state.step))
        grad_fn = jax.value_and_grad(
            lambda p: _loss_and_metrics(model, transform, p, state.batch_stats,
                                        images_u8, labels, dropout_rng, aug_rng,
                                        state.loss_scale, True),
            has_aux=True)
        (_, (new_stats, metrics)), grads = grad_fn(state.params)
        # grads of replicated params w.r.t. a sharded-batch mean ARE the
        # cross-replica mean — XLA emits the all-reduce (DDP equivalence).
        return _apply_update(tx, state, grads, new_stats, metrics, health)

    return step


def pack_images_for_device(images_u8):
    """Host-side zero-copy pack of (N,H,W,C) u8 rows into (N, HWC/4) i32.

    TPU gathers move 32-bit words natively; a row gather over uint8 data
    decomposes into byte traffic and measurably slows the indexed step
    (~10% end-to-end on ResNet-50/CIFAR). When H*W*C is not a multiple of 4
    the images pass through unpacked (u8 gather fallback).
    """
    import numpy as np

    n = images_u8.shape[0]
    flat = images_u8.reshape(n, -1)
    if flat.shape[1] % 4 or not flat.flags.c_contiguous:
        return images_u8
    return flat.view(np.int32)


# ---- the make_* builders: thin shims over the plan compiler ----------------
# (the two hops below are plain `return f(...)` chains on purpose: distlint's
# jit-factory fixpoint follows them, so `self.train_step = make_*(...)`
# still derives the engine loops as hot)

def _train(plan: Plan, **binds):
    from tpu_dist.plan.compile import Bindings, compile_train_step
    return compile_train_step(plan, Bindings(**binds))


def _eval(plan: Plan, **binds):
    from tpu_dist.plan.compile import Bindings, compile_eval_step
    return compile_eval_step(plan, Bindings(**binds))


def make_train_step(model, tx, transform, mesh: Mesh,
                    data_axis: str = DATA_AXIS, donate: bool = True,
                    health: str = "record") -> Callable:
    """Compiler-partitioned step: jit over mesh, batch sharded, params replicated."""
    plan = Plan(engine="image", data_axis=data_axis, donate=donate,
                health=health)
    return _train(plan, mesh=mesh, model=model, tx=tx,
                     transform=transform)


def make_multi_train_step(model, tx, transform, mesh: Mesh,
                          data_axis: str = DATA_AXIS,
                          donate: bool = True,
                          health: str = "record") -> Callable:
    """K optimizer steps in ONE dispatch: lax.scan over stacked batches.

    signature: (state, images_u8 (K,B,...), labels (K,B), rng) -> (state,
    metrics summed over the K steps). The TPU-idiomatic answer to dispatch
    latency on a remote/high-latency controller link (the reference's analog
    concern was CUDA-stream overlap, C13): the whole window executes on-device
    with zero host round-trips. K is a trace-time constant (leading dim).
    """
    plan = Plan(engine="image", window="stacked", data_axis=data_axis,
                donate=donate, health=health)
    return _train(plan, mesh=mesh, model=model, tx=tx,
                     transform=transform)


def make_indexed_multi_train_step(model, tx, transform, mesh: Mesh,
                                  image_shape, data_axis: str = DATA_AXIS,
                                  donate: bool = True,
                                  health: str = "record") -> Callable:
    """K steps per dispatch reading a DEVICE-RESIDENT dataset by index.

    signature: (state, images_all REPLICATED (packed via
    :func:`pack_images_for_device` — (N,HWC/4) i32, or (N,H,W,C) u8
    fallback), labels_all (N,) REPLICATED, idx (K,B) i32 sharded
    (None, data), rng) -> (state, metrics summed over the K steps).

    TPU-first data path for datasets that fit in HBM (CIFAR-scale): the
    arrays live on device once, each scan iteration gathers its batch at HBM
    bandwidth, and the host sends only the (K,B) int32 index window per
    dispatch — a few KB instead of ~3 KB/image. End-to-end training
    throughput then tracks the device step rate instead of the host->device
    link (the reference's prefetcher fought the same battle on CUDA streams
    and lost, reference 4.apex_distributed2.py:80). Identical math to K
    sequential :func:`make_train_step` calls (same per-step rng fold).
    """
    plan = Plan(engine="image", window="indexed", data_axis=data_axis,
                donate=donate, health=health)
    return _train(plan, mesh=mesh, model=model, tx=tx,
                     transform=transform, image_shape=image_shape)


def make_indexed_eval_step(model, transform, mesh: Mesh, image_shape,
                           data_axis: str = DATA_AXIS) -> Callable:
    """Whole-validation-set eval in ONE dispatch from HBM-resident data.

    signature: (params, batch_stats, images_all (packed, REPLICATED),
    labels_all, idx (K,B) i32 sharded (None, data), valid (K,B) f32 same
    sharding) -> summed metrics over all K batches. The companion of
    :func:`make_indexed_multi_train_step` for the eval loop: sampler padding
    is masked per sample via ``valid`` exactly like the host-fed
    :func:`make_eval_step`.
    """
    plan = Plan(engine="image", window="indexed", data_axis=data_axis)
    return _eval(plan, mesh=mesh, model=model,
                     eval_transform=transform,
                     image_shape=image_shape)


def make_eval_step(model, transform, mesh: Mesh,
                   data_axis: str = DATA_AXIS) -> Callable:
    """Distributed eval step (C15): metric sums on the global sharded batch."""
    plan = Plan(engine="image", data_axis=data_axis)
    return _eval(plan, mesh=mesh, model=model,
                     eval_transform=transform)


def make_grad_accum_train_step(model, tx, transform, mesh: Mesh,
                               data_axis: str = DATA_AXIS,
                               donate: bool = True,
                               health: str = "record") -> Callable:
    """ONE optimizer step from K microbatches (gradient accumulation).

    signature: (state, images_u8 (K,B,...), labels (K,B), rng) -> (state,
    metrics summed over microbatches). Grads are averaged over the K
    microbatches inside a lax.scan, then applied once — the standard recipe
    for global batches that exceed device memory (absent from the reference,
    whose answer to batch 3200 was requiring 4x V100s). BN statistics advance
    per microbatch (same semantics as torch accumulation loops).
    """
    # the accum template reads K from the batch's leading dim at trace
    # time; any grad_accum_steps > 1 selects it (2 = the mode marker)
    plan = Plan(engine="image", grad_accum_steps=2, data_axis=data_axis,
                donate=donate, health=health)
    return _train(plan, mesh=mesh, model=model, tx=tx,
                     transform=transform)


def make_shard_map_train_step(model, tx, transform, mesh: Mesh,
                              data_axis: str = DATA_AXIS,
                              grad_compression: str = "none",
                              predivide_factor: float = 1.0,
                              adasum: bool = False,
                              donate: bool = True,
                              grad_bucket_mb: float = 0.0,
                              model_axis: Optional[str] = None,
                              health: str = "record") -> Callable:
    """Explicit-collective step (horovod-equivalent, reference variant 5).

    Per-device program via shard_map; gradient averaging is an explicit psum
    with optional bf16 payload compression (reference 5.horovod_distributed.py:
    123-125) and horovod's gradient_predivide_factor placement (pre-scale
    before summation, post-scale after; reference 5.2...py:185). With
    ``adasum=True`` the mean is replaced by the Adasum recursive-halving
    operator (hvd.Adasum, reference 5.2...py:184 —
    tpu_dist.parallel.collectives.adasum_reduce); predivide/compression are
    mean-path knobs and do not apply.

    ``grad_bucket_mb > 0`` replaces the tree-wide psum with DDP-style
    size-targeted bucket collectives (parallel.overlap.bucketed_grad_sync:
    independent reduce-scatter+all-gather per ~bucket_mb of grads), the
    decomposition XLA's scheduler can overlap. ``model_axis`` names a ring-TP
    mesh axis (models built with tp_impl='ring'/'ring_ar'): the model's
    collectives run over it inside this same program, compute is replicated
    across it per data shard, and the grads of the (replicated) params are
    additionally pmean'd over it.
    """
    plan = Plan(engine="image", sync="explicit",
                layout="tp" if model_axis is not None else "dp",
                tp_impl="ring" if model_axis is not None else "gspmd",
                model_axis=model_axis or "model",
                data_axis=data_axis, grad_compression=grad_compression,
                predivide_factor=predivide_factor, adasum=adasum,
                grad_bucket_mb=grad_bucket_mb, donate=donate, health=health)
    return _train(plan, mesh=mesh, model=model, tx=tx,
                     transform=transform)
