"""LMTrainer: the language-model twin of engine.loop.Trainer (VERDICT r2 #1).

Round 2 drove the LM parallelism surface (dp/tp/sp/pp/ep/fsdp, flash, remat)
from a fixed-batch demo loop in scripts/8; this module gives the LM family
the SAME orchestration the image family has — epochs over a real token
corpus (tpu_dist.data.tokens), DistributedSampler rows with N-process
bit-exactness, K-steps-per-dispatch windows from an HBM-resident row matrix,
MeterBank progress lines + per-epoch CSV, exact held-out perplexity in EVERY
parallelism mode (sp and pp included), step-exact mid-epoch resume, and
tokens/sec with MFU from XLA's cost model.

Mode selection is by mesh axes, exactly like scripts/8:
  data=N                      pure DP (jit; GSPMD allreduce)
  data=N  + fsdp=True         ZeRO-3 param+opt sharding, same step
  data=N,model=M              tensor parallel (Megatron shardings via GSPMD)
  data=N,expert=M             MoE expert parallelism (GShard dispatch)
  data=N,seq=M                sequence parallel (ring attention, shard_map)
  data=N,stage=M              pipeline parallel (GPipe microbatches)
"""

from __future__ import annotations

import os
import time
from functools import partial
from typing import Dict, Optional

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from tpu_dist.configs import LMConfig
from tpu_dist.data import DistributedSampler, assemble_global
from tpu_dist.data.tokens import load_token_dataset
from tpu_dist.engine import checkpoint as ckpt
from tpu_dist.engine.lm_steps import (LM_METRIC_KEYS, make_lm_batches,
                                      make_lm_eval_step,
                                      make_lm_indexed_eval_step,
                                      make_lm_indexed_multi_train_step,
                                      make_lm_sp_eval_step,
                                      make_lm_sp_train_step,
                                      make_lm_train_step)
from tpu_dist.engine.state import TrainState
from tpu_dist.obs import (HealthError, RunObs, faults, profile_session,
                          step_annotation)
from tpu_dist.ops import lm_lr_schedule, make_optimizer, make_policy
from tpu_dist.parallel.mesh import make_mesh, replicated
from tpu_dist.parallel.supervisor import PREEMPT_SNAPSHOT_RC
from tpu_dist.utils.meters import MeterBank


class LMTrainer:
    """One engine for every LM parallelism flavor; mode picked by the mesh."""

    def __init__(self, cfg: LMConfig, mesh=None):
        # step plan (tpu_dist.plan): the `plan` knob rewrites the
        # plan-owned config fields and flips the trace-time kernel
        # switches BEFORE anything below reads them; run_start + a
        # 'plan' ledger event record the resolved hash
        from tpu_dist.plan.compile import resolve_config_plan
        cfg, self._plan_info = resolve_config_plan(cfg)
        self.cfg = cfg
        if cfg.resume and not os.path.exists(cfg.resume):
            raise FileNotFoundError(f"--resume checkpoint not found: {cfg.resume}")
        if cfg.pretrained and not os.path.exists(cfg.pretrained):
            raise FileNotFoundError(
                f"--pretrained checkpoint not found: {cfg.pretrained}")
        if cfg.optimizer not in ("sgd", "adamw", "fused_adamw"):
            # fail fast, BEFORE corpus/model setup (the image Trainer's
            # contract; fused_sgd is image-only — its Pallas kernel assumes
            # the SGD update form)
            raise ValueError(f"unknown optimizer {cfg.optimizer!r} "
                             "(sgd|adamw|fused_adamw)")
        from tpu_dist.obs.health import validate_health
        validate_health(cfg.health)  # record | skip | halt, before any build
        mesh_shape = cfg.mesh_shape or (jax.device_count(),)
        self.mesh = mesh if mesh is not None else make_mesh(
            tuple(mesh_shape), tuple(cfg.mesh_axes))
        self.policy = make_policy(cfg.precision)

        # ---- corpus ----
        seed = cfg.seed if cfg.seed is not None else 0
        self.train_ds, self.val_ds = load_token_dataset(
            cfg.data, cfg.seq_len, cfg.vocab_size, val_frac=cfg.val_frac,
            synth_tokens=cfg.synth_tokens, seed=seed, val_data=cfg.val_data)
        self.vocab_size = self.train_ds.vocab_size

        # ---- mode ----
        names = self.mesh.axis_names
        shape = self.mesh.shape
        self.use_sp = "seq" in names and shape["seq"] > 1
        self.use_tp = "model" in names and shape["model"] > 1
        self.use_ep = "expert" in names and shape["expert"] > 1
        self.use_pp = "stage" in names and shape["stage"] > 1
        from tpu_dist.parallel.overlap import validate_tp_impl
        validate_tp_impl(cfg.tp_impl)
        self.use_ring = self.use_tp and cfg.tp_impl == "ring"
        self.use_bucket = cfg.grad_bucket_mb > 0
        self._validate_mode()
        self.mode = (f"pp-{cfg.pp_schedule}"
                     + ("+tp" if self.use_pp and self.use_tp else "")
                     if self.use_pp else
                     "sp-ring" if self.use_sp else
                     ("ep-moe" + ("+tp" if self.use_tp else ""))
                     if self.use_ep else
                     ("tp-ring" if self.use_ring else "tp") if self.use_tp
                     else
                     "fsdp" if cfg.fsdp else
                     ("dp-moe" if cfg.num_experts else "dp")
                     + ("-bucketed" if self.use_bucket else ""))

        # ---- batch geometry ----
        nprocs = jax.process_count()
        d_size = shape.get("data", 1)
        if cfg.batch_size % max(d_size, nprocs):
            raise ValueError(
                f"global batch {cfg.batch_size} (sequences) must divide by "
                f"the data axis ({d_size}) and process count ({nprocs})")
        if self.use_sp and cfg.seq_len % shape["seq"]:
            raise ValueError(f"seq_len {cfg.seq_len} not divisible by the "
                             f"seq axis ({shape['seq']})")
        if self.use_pp and (cfg.batch_size // d_size) % cfg.pp_microbatches:
            raise ValueError(
                f"per-data-shard batch {cfg.batch_size // d_size} not "
                f"divisible by {cfg.pp_microbatches} microbatches")
        self.local_batch = cfg.batch_size // nprocs

        # ---- model ----
        self.model, self._model_ctor_kw = self._build_model()
        params = self.model.init(
            {"params": jax.random.PRNGKey(seed)},
            np.zeros((1, cfg.seq_len), np.int32), train=False)["params"]
        if cfg.pretrained:
            # warm-start BEFORE any pipeline stacking, so the donor must be
            # an UNSTACKED (per-block) param tree. Non-pp runs save exactly
            # that; a pp run's checkpoint keeps its stage-stacked blocks
            # (resume needs the stacked layout) and is therefore NOT a
            # valid --pretrained donor as-is — convert it first with
            # parallel.pp.unstack_pipeline_params. The stamped pp_stages
            # meta makes the mismatch detectable, so refuse loudly instead
            # of letting graft_params silently keep fresh init for every
            # block. (Shape-matched graft, fresh optimizer state; --resume
            # is the continue-a-run path; existence checked first-line in
            # __init__.)
            pre_params, _, pre_meta = ckpt.load_warmstart(cfg.pretrained)
            if pre_meta.get("pp_stages"):
                raise ValueError(
                    f"--pretrained {cfg.pretrained} was saved by a "
                    f"pipeline-parallel run ({pre_meta['pp_stages']} stages):"
                    " its blocks are stage-stacked and would not graft onto "
                    "a fresh model. Unstack it first (parallel.pp."
                    "unstack_pipeline_params) and re-save, or warm-start "
                    "from a non-pp checkpoint.")
            params, n_p, skipped = ckpt.graft_params(params, pre_params)
            if n_p == 0:
                raise ValueError(
                    f"--pretrained {cfg.pretrained} (arch "
                    f"{pre_meta.get('arch', '?')!r}) shares no tensors with "
                    f"this model — wrong checkpoint?")
            self.log(f"=> warm-started {n_p} param tensors from "
                     f"{cfg.pretrained}"
                     + (f"; fresh init kept for {skipped}" if skipped else ""))
        self.steps_per_epoch = max(
            1, -(-len(self.train_ds) // cfg.batch_size))
        # warmup + constant/cosine/step LR as a pure function of the step
        # count inside the jitted update (VERDICT r3 #2); the count lives in
        # the checkpointed optax state, so --resume continues the trajectory
        total_steps = (cfg.lr_decay_steps or cfg.max_steps
                       or cfg.epochs * self.steps_per_epoch)
        self.lr_schedule = lm_lr_schedule(
            cfg.lr, cfg.lr_schedule, warmup_steps=cfg.warmup_steps,
            total_steps=total_steps, steps_per_epoch=self.steps_per_epoch,
            step_epochs=cfg.lr_step_epochs, min_frac=cfg.lr_min_frac)
        # pp clips inside the step by the cross-stage global norm
        # (parallel.pp._clip_pp_grads), so its optax chain carries no clip
        # of its own — which also keeps the opt_state pytree structure
        # independent of the --grad-clip flag under pp
        if cfg.optimizer == "fused_adamw":
            # Pallas fused update (ops.pallas_adamw): engine steps dispatch
            # on the apply() protocol, pp included. grad_clip fuses INTO
            # the kernel (the scalar-row clip slot) for the non-pp modes;
            # under pp the step clips by the cross-stage global norm
            # BEFORE _apply_update, so the kernel-side clip stays off —
            # exactly the optax-chain split above
            from tpu_dist.ops.pallas_adamw import FusedAdamW
            self.tx = FusedAdamW(self.lr_schedule, b1=cfg.adam_b1,
                                 b2=cfg.adam_b2, eps=cfg.adam_eps,
                                 weight_decay=cfg.weight_decay,
                                 clip_norm=0.0 if self.use_pp
                                 else cfg.grad_clip,
                                 interpret=jax.default_backend() != "tpu")
        else:
            self.tx = make_optimizer(cfg.lr, cfg.momentum, cfg.weight_decay,
                                     schedule=self.lr_schedule,
                                     kind=cfg.optimizer, b1=cfg.adam_b1,
                                     b2=cfg.adam_b2, eps=cfg.adam_eps,
                                     grad_clip=0.0 if self.use_pp
                                     else cfg.grad_clip)
        if self.use_pp:
            from tpu_dist.parallel.pp import stack_pipeline_params
            params = stack_pipeline_params(params, shape["stage"])
        state = TrainState.create(params, {}, self.tx)

        # ---- steps ----
        self.rng = jax.random.PRNGKey(seed + 1)
        self._build_steps()

        # ---- windows / device-resident rows ----
        self.k = cfg.steps_per_dispatch
        if self.k < 1:
            raise ValueError("steps_per_dispatch must be >= 1")
        if cfg.data_placement not in ("auto", "host", "device"):
            raise ValueError(f"unknown data_placement {cfg.data_placement!r}")
        # gradient accumulation (jit modes): N sequential microbatches per
        # optimizer step — the same mutual exclusions as the image Trainer
        self.accum = cfg.grad_accum_steps
        if self.accum < 1:
            raise ValueError("grad_accum_steps must be >= 1")
        if self.accum > 1:
            if self.use_sp or self.use_pp:
                raise ValueError("grad_accum_steps > 1 supports the jit "
                                 "modes (dp/fsdp/tp/ep); pp already "
                                 "microbatches via --pp-microbatches")
            if self.k > 1:
                raise ValueError("grad_accum_steps and steps_per_dispatch "
                                 "> 1 are mutually exclusive")
            if cfg.data_placement == "device":
                raise ValueError("grad_accum_steps > 1 requires "
                                 "data_placement='host'/'auto' (the indexed "
                                 "window step has no microbatch loop)")
            if cfg.batch_size % (self.accum * d_size):
                raise ValueError(
                    f"global batch {cfg.batch_size} not divisible by "
                    f"grad_accum_steps x data axis ({self.accum} x {d_size})")
            from tpu_dist.engine.lm_steps import (
                make_lm_grad_accum_train_step)
            self.train_step = make_lm_grad_accum_train_step(
                self.model, self.tx, self.mesh, loss_chunk=cfg.loss_chunk,
                aux_weight=cfg.moe_aux_weight, health=cfg.health)
        rows_bytes = (len(self.train_ds) + len(self.val_ds)) * \
            (cfg.seq_len + 1) * 4
        fits = rows_bytes <= int(os.environ.get("TPU_DIST_DEVICE_DATA_MAX",
                                                str(1 << 30)))
        self.device_data = (cfg.data_placement == "device" or
                            (cfg.data_placement == "auto" and fits
                             and self.k > 1))
        self._train_rows_dev = None
        self._val_rows_dev = None
        self._prefetched_windows = None
        if self.device_data:
            # distlint: disable=DL008 -- one-time whole-dataset HBM residency at init; per-step uploads don't exist in this mode
            self._train_rows_dev = jax.device_put(
                self.train_ds.rows_array(), replicated(self.mesh))
            # distlint: disable=DL008 -- one-time whole-dataset HBM residency at init (see _train_rows_dev)
            self._val_rows_dev = jax.device_put(
                self.val_ds.rows_array(), replicated(self.mesh))
            # every mode gets the K-steps-per-dispatch window path: the jit
            # modes via the GSPMD step, sp/pp via a lax.scan over index
            # windows INSIDE their shard_map programs (VERDICT r3 #3)
            if self.use_pp:
                from tpu_dist.parallel.pp import (
                    make_lm_pp_indexed_eval_step,
                    make_lm_pp_indexed_multi_train_step)
                self.window_step = make_lm_pp_indexed_multi_train_step(
                    self.model, self.tx, self.mesh, cfg.pp_microbatches,
                    schedule=cfg.pp_schedule, loss_chunk=cfg.loss_chunk,
                    aux_weight=cfg.moe_aux_weight,
                    grad_clip=cfg.grad_clip, health=cfg.health)
                self.window_eval_step = make_lm_pp_indexed_eval_step(
                    self.model, self.mesh, cfg.pp_microbatches,
                    loss_chunk=cfg.loss_chunk)
            elif self.use_sp:
                from tpu_dist.engine.lm_steps import (
                    make_lm_sp_indexed_eval_step,
                    make_lm_sp_indexed_multi_train_step)
                self.window_step = make_lm_sp_indexed_multi_train_step(
                    self._sp_ctor, self.tx, self.mesh,
                    loss_chunk=cfg.loss_chunk,
                    aux_weight=cfg.moe_aux_weight, health=cfg.health)
                self.window_eval_step = make_lm_sp_indexed_eval_step(
                    self._sp_ctor, self.mesh, loss_chunk=cfg.loss_chunk)
            elif self.use_ring or self.use_bucket:
                # the explicit-collective modes scan index windows inside
                # their own shard_map program; eval (forward-only, no grad
                # sync, replicated params) rides the GSPMD indexed step
                from tpu_dist.engine.lm_steps import (
                    make_lm_explicit_indexed_multi_train_step)
                self.window_step = make_lm_explicit_indexed_multi_train_step(
                    self._explicit_step_fn, self.mesh)
                self.window_eval_step = make_lm_indexed_eval_step(
                    self.model, self.mesh, loss_chunk=cfg.loss_chunk)
            else:
                self.window_step = make_lm_indexed_multi_train_step(
                    self.model, self.tx, self.mesh,
                    loss_chunk=cfg.loss_chunk,
                    aux_weight=cfg.moe_aux_weight, health=cfg.health)
                self.window_eval_step = make_lm_indexed_eval_step(
                    self.model, self.mesh, loss_chunk=cfg.loss_chunk)
        elif self.k > 1:
            raise ValueError(
                "steps_per_dispatch > 1 needs the device-resident row path "
                "(corpus too large for TPU_DIST_DEVICE_DATA_MAX, or "
                "data_placement='host')")

        # ---- geometry meta / resume ----
        self._run_meta = {
            "vocab_size": self.vocab_size, "num_layers": cfg.num_layers,
            "d_model": cfg.d_model, "num_heads": cfg.num_heads,
            "seq_len": cfg.seq_len, "num_experts": cfg.num_experts,
            "pp_stages": shape["stage"] if self.use_pp else 0,
            "steps_per_epoch": self.steps_per_epoch,
            "batch_size": cfg.batch_size, "dataset_len": len(self.train_ds),
            "mode": self.mode,
        }
        self.start_epoch = 0
        self._skip_batches = 0
        self.best_ppl = float("inf")
        self.is_main = jax.process_index() == 0
        if cfg.resume:
            hard = ("vocab_size", "num_layers", "d_model", "num_heads",
                    "seq_len", "num_experts", "pp_stages")
            pre = ckpt.read_checkpoint_meta(cfg.resume)
            bad = {k: (pre[k], self._run_meta[k]) for k in hard
                   if k in pre and pre[k] != self._run_meta[k]}
            if bad:
                raise ValueError(
                    "--resume checkpoint has different model geometry: " +
                    ", ".join(f"{k}: checkpoint {a} vs run {b}"
                              for k, (a, b) in bad.items()))
            state, meta = ckpt.load_checkpoint(cfg.resume, state)
            self.start_epoch = meta.get("epoch", 0)
            self.best_ppl = meta.get("best_ppl", float("inf"))
            soft = {k: (meta[k], self._run_meta[k])
                    for k in ("steps_per_epoch", "batch_size", "dataset_len")
                    if k in meta and meta[k] != self._run_meta[k]}
            if meta.get("mid_epoch"):
                if soft:
                    raise ValueError(
                        "mid-epoch resume requires the checkpoint's data/"
                        "batch geometry (" + ", ".join(
                            f"{k}: checkpoint {a} vs run {b}"
                            for k, (a, b) in soft.items()) + ")")
                step_done = int(np.asarray(state.step))
                self.start_epoch = step_done // self.steps_per_epoch
                self._skip_batches = step_done % self.steps_per_epoch
                if self._skip_batches:
                    self.log(f"=> mid-epoch checkpoint: resuming epoch "
                             f"{self.start_epoch}, skipping "
                             f"{self._skip_batches} already-applied batches")
            self.log(f"=> resumed from {cfg.resume} "
                     f"(epoch {self.start_epoch})")
        # checkpoint-less dp-pure recovery (round 13): on a supervisor
        # mesh re-expansion (TPU_DIST_PEER_RESUME), adopt a survivor's
        # live replicated state over a broadcast collective — the
        # returning host has no local checkpoint, and the consensus
        # renumbering keeps process 0 a survivor. Replicated layouts
        # only; sharded modes take the disk path above.
        self._dp_pure = not (self.use_sp or self.use_tp or self.use_ep
                             or self.use_pp or cfg.fsdp)
        self._peer_restored = False
        if os.environ.get("TPU_DIST_PEER_RESUME") == "1" and self._dp_pure:
            state, did = ckpt.peer_restore_state(state)
            if did:
                self._peer_restored = True
                # epoch/skip re-derive from the adopted step counter, the
                # same math as a mid-epoch resume (best_ppl is the one
                # piece a joiner cannot recover — it only gates is_best)
                step_done = int(np.asarray(state.step))
                self.start_epoch = step_done // self.steps_per_epoch
                self._skip_batches = step_done % self.steps_per_epoch
                self.log(f"=> peer-restored state from a survivor at step "
                         f"{step_done} (no disk round-trip); resuming "
                         f"epoch {self.start_epoch}")
        self.state = self._place(state)
        self._epoch_in_progress = self.start_epoch
        self._flops_per_step = None  # analytical, lazily (utils.mfu)
        self._program_hbm = None     # post-dispatch probe (telemetry contract)
        self.last_tok_s = 0.0        # last epoch's train-phase tokens/sec
        self._warmed = False         # first dispatch carries XLA compile;
                                     # its wall time is excluded from tok/s
        # run observability: ledger + tracer + skew monitor + hang watchdog
        # (obs.RunObs) — the LM engine's step records carry tok/s + MFU
        self.obs = RunObs("lm", cfg, self.mesh, unit="tok/s",
                          plan_info=self._plan_info)
        # program audit (tpu_dist.analysis.proglint via plan.compile):
        # armed here so the compile-time pass and the drain-boundary
        # recompile sentry see every program this run builds
        from tpu_dist.plan.compile import set_audit
        set_audit(cfg.audit, self.obs.ledger)
        # whether the int8 matmuls route through the fused Pallas kernel
        # (ops.pallas_quant) — trace-time static, so ONE read here is the
        # truth for every step record; ledger_report attributes MFU deltas
        # to the kernel by splitting records on this flag
        from tpu_dist.ops.quant import fused_quant_active
        self._fused_quant = cfg.quant == "int8" and fused_quant_active()
        # comm phase for the step ledger records: when grad sync is an
        # explicit decomposed collective (grad_bucket_mb), time the sync
        # alone once — the UNOVERLAPPED per-step comm cost readers compare
        # device_s against (tools/ledger_report renders the share). Ring
        # TP's comm interleaves with the matmul chunks by construction and
        # cannot be isolated post-fusion, so its records carry comm_s=None.
        self._comm_probe_s = (self._measure_comm_probe()
                              if self.use_bucket else None)

    # ------------------------------------------------------------------
    def _validate_mode(self):
        cfg = self.cfg
        multi = [a for a in ("seq", "model", "expert", "stage")
                 if a in self.mesh.axis_names and self.mesh.shape[a] > 1]
        if len(multi) > 1 and set(multi) not in ({"stage", "model"},
                                                 {"expert", "model"}):
            raise ValueError(
                f"unsupported model-parallel axis combination {multi} "
                "(one axis at a time, stage+model for pp x tp, or "
                "expert+model for MoE x tp)")
        if self.use_pp and cfg.fsdp:
            raise ValueError("a 'stage' mesh axis does not compose with "
                             "fsdp (blocks already shard over 'stage')")
        # (--grad-clip composes with pp since round 5: the pp steps clip by
        # the cross-stage global norm — parallel.pp._clip_pp_grads — so the
        # optax chain must NOT carry its own per-device clip. MoE composes
        # with both pp schedules and with pp x tp: GPipe carries the router
        # aux through autodiff, 1f1b threads it as an explicit vjp
        # cotangent, and pp_tp_placement_specs shards the stacked expert
        # kernels Megatron-style over 'model'.)
        if self.use_ep and not cfg.num_experts:
            raise ValueError("an 'expert' mesh axis requires num_experts > 0")
        # (MoE composes with a 'seq' axis: experts are replicated and the
        # GShard dispatch is group-local math, so it runs unchanged inside
        # the sp shard_map — router groups become shard-local; a
        # --moe-group-size dividing the shard keeps routing dp-identical)
        if (self.use_tp and cfg.num_experts
                and not (self.use_ep or self.use_pp)):
            raise ValueError("MoE + pure tensor parallelism not supported: "
                             "use data=N,expert=M[,model=K] or "
                             "data=N,stage=S,model=K")
        if cfg.fsdp and (self.use_sp or self.use_tp or self.use_ep):
            self.log("warning: fsdp applies to the pure data-parallel "
                     "layout; ignored with a seq/model/expert mesh axis")
        if self.use_ring:
            tp = self.mesh.shape["model"]
            if self.use_pp or self.use_ep:
                raise ValueError("tp_impl='ring' drives the pure "
                                 "data x model layout; pp/ep compositions "
                                 "ride the GSPMD impl")
            if cfg.seq_len % tp:
                raise ValueError(f"tp_impl='ring' seq-shards the residual: "
                                 f"seq_len {cfg.seq_len} must divide by the "
                                 f"model axis ({tp})")
            if cfg.num_heads % tp:
                raise ValueError(f"tp_impl='ring' shards heads: num_heads "
                                 f"{cfg.num_heads} must divide by the model "
                                 f"axis ({tp})")
            if cfg.grad_accum_steps > 1:
                raise ValueError("tp_impl='ring' does not compose with "
                                 "grad_accum_steps > 1 yet (the accum step "
                                 "is GSPMD-partitioned)")
        if self.use_bucket:
            if self.use_tp or self.use_sp or self.use_pp or self.use_ep \
                    or cfg.fsdp:
                raise ValueError(
                    "grad_bucket_mb > 0 decomposes the pure-dp gradient "
                    "allreduce (replicated params); fsdp/tp/sp/pp/ep keep "
                    "their GSPMD-scheduled sync")
            if cfg.grad_accum_steps > 1:
                raise ValueError("grad_bucket_mb does not compose with "
                                 "grad_accum_steps > 1 yet")

    def _build_model(self):
        cfg = self.cfg
        import jax.numpy as jnp

        if cfg.attn == "blockwise":
            from tpu_dist.ops.flash_attention import blockwise_attention_fn
            attn_fn = blockwise_attention_fn(cfg.attn_block)
        elif cfg.attn == "flash":
            from tpu_dist.ops.flash_attention import flash_attention_fn
            attn_fn = flash_attention_fn(block_k=cfg.attn_block)
        elif cfg.attn == "full":
            from tpu_dist.models.transformer import full_attention
            attn_fn = full_attention
        else:
            raise ValueError(f"unknown attn {cfg.attn!r}")
        if self.use_sp and cfg.attn != "full":
            self.log(f"warning: a 'seq' mesh axis uses ring attention; "
                     f"attn={cfg.attn} ignored")
        from tpu_dist.ops.quant import validate_quant
        validate_quant(cfg.quant)
        lm_kw = dict(vocab_size=self.vocab_size, num_layers=cfg.num_layers,
                     d_model=cfg.d_model, num_heads=cfg.num_heads,
                     max_len=cfg.seq_len, dtype=self.policy.compute_dtype,
                     attn_fn=attn_fn, remat=cfg.remat, quant=cfg.quant)
        if cfg.num_experts:
            from tpu_dist.models.moe import MoETransformerLM
            # the MoE knobs ride in the ctor kwargs so EVERY mode (jit, sp
            # rebind, windowed) builds the identical model from ONE dict
            lm_kw = dict(lm_kw, num_experts=cfg.num_experts,
                         router_top_k=cfg.router_top_k,
                         group_size=cfg.moe_group_size,
                         capacity_factor=cfg.moe_capacity_factor)
            model = MoETransformerLM(**lm_kw)
        else:
            from tpu_dist.models.transformer import tiny_lm
            model = tiny_lm(**lm_kw)
        return model, lm_kw

    def _build_steps(self):
        cfg = self.cfg
        if self.use_pp:
            from tpu_dist.parallel.pp import (make_lm_pp_1f1b_train_step,
                                              make_lm_pp_eval_step,
                                              make_lm_pp_train_step)
            if cfg.pp_schedule not in ("gpipe", "1f1b"):
                raise ValueError(f"unknown pp_schedule {cfg.pp_schedule!r} "
                                 "(gpipe|1f1b)")
            maker = (make_lm_pp_1f1b_train_step
                     if cfg.pp_schedule == "1f1b" else make_lm_pp_train_step)
            self.train_step = maker(
                self.model, self.tx, self.mesh, cfg.pp_microbatches,
                loss_chunk=cfg.loss_chunk, aux_weight=cfg.moe_aux_weight,
                grad_clip=cfg.grad_clip, health=cfg.health)
            self.eval_step = make_lm_pp_eval_step(
                self.model, self.mesh, cfg.pp_microbatches,
                loss_chunk=cfg.loss_chunk)
            self.data_spec = P("data", None)
            self.valid_spec = P("data")
        elif self.use_sp:
            from tpu_dist.models.moe import MoETransformerLM
            from tpu_dist.models.transformer import tiny_lm
            kw = {k: v for k, v in self._model_ctor_kw.items()
                  if k != "attn_fn"}
            ctor = partial(MoETransformerLM if cfg.num_experts else tiny_lm,
                           **kw)
            self._sp_ctor = ctor  # the windowed sp steps rebind it per-axis
            self.train_step = make_lm_sp_train_step(
                ctor, self.tx, self.mesh, loss_chunk=cfg.loss_chunk,
                aux_weight=cfg.moe_aux_weight, health=cfg.health)
            self.eval_step = make_lm_sp_eval_step(
                ctor, self.mesh, loss_chunk=cfg.loss_chunk)
            self.data_spec = P("data", "seq")
            self.valid_spec = P("data")
        elif self.use_ring:
            # ring collective-matmul TP (parallel.overlap): the train step
            # runs a tp_impl='ring' CLONE of the model (identical params)
            # inside shard_map over (data, model); eval and checkpoints keep
            # the plain model — params are replicated, so the GSPMD eval
            # step applies unchanged
            from tpu_dist.engine.lm_steps import (_lm_tp_ring_step_fn,
                                                  make_lm_tp_ring_train_step)
            self._ring_model = self.model.clone(tp_impl="ring")
            self._explicit_step_fn = _lm_tp_ring_step_fn(
                self._ring_model, self.tx, cfg.moe_aux_weight, "data",
                "model", self.mesh.shape["model"],
                loss_chunk=cfg.loss_chunk, health=cfg.health)
            self.train_step = make_lm_tp_ring_train_step(
                self._ring_model, self.tx, self.mesh,
                loss_chunk=cfg.loss_chunk, aux_weight=cfg.moe_aux_weight,
                health=cfg.health)
            self.eval_step = make_lm_eval_step(
                self.model, self.mesh, loss_chunk=cfg.loss_chunk)
            self.data_spec = P("data")
            self.valid_spec = P("data")
        elif self.use_bucket:
            # explicit bucketed dp grad sync (parallel.overlap): DDP's
            # fusion-buffer decomposition behind --grad-bucket-mb
            from tpu_dist.engine.lm_steps import (_lm_explicit_dp_step_fn,
                                                  make_lm_shard_map_train_step)
            self._explicit_step_fn = _lm_explicit_dp_step_fn(
                self.model, self.tx, cfg.moe_aux_weight, "data",
                self.mesh.shape["data"], cfg.grad_bucket_mb,
                loss_chunk=cfg.loss_chunk, health=cfg.health)
            self.train_step = make_lm_shard_map_train_step(
                self.model, self.tx, self.mesh,
                grad_bucket_mb=cfg.grad_bucket_mb,
                loss_chunk=cfg.loss_chunk, aux_weight=cfg.moe_aux_weight,
                health=cfg.health)
            self.eval_step = make_lm_eval_step(
                self.model, self.mesh, loss_chunk=cfg.loss_chunk)
            self.data_spec = P("data")
            self.valid_spec = P("data")
        else:
            self.train_step = make_lm_train_step(
                self.model, self.tx, self.mesh, loss_chunk=cfg.loss_chunk,
                aux_weight=cfg.moe_aux_weight, health=cfg.health)
            self.eval_step = make_lm_eval_step(
                self.model, self.mesh, loss_chunk=cfg.loss_chunk)
            self.data_spec = P("data")
            self.valid_spec = P("data")

    def _place(self, st):
        """Apply the mode's parameter sharding (also re-places resumes)."""
        cfg = self.cfg
        if self.use_pp:
            from tpu_dist.parallel.pp import shard_state_pp
            return shard_state_pp(self.mesh, st)
        if self.use_ep:
            from tpu_dist.parallel.ep import shard_state_ep
            return shard_state_ep(self.mesh, st)
        if self.use_ring:
            # ring TP keeps params replicated (each device slices its
            # column/row shard at use — parallel.overlap design note)
            # distlint: disable=DL008 -- state placement at init/resume, not a per-step input upload
            return jax.device_put(st, replicated(self.mesh))
        if self.use_tp:
            from tpu_dist.parallel.tp import shard_lm_params
            # distlint: disable=DL008 -- state placement at init/resume, not a per-step input upload
            return TrainState(
                step=jax.device_put(st.step, NamedSharding(self.mesh, P())),
                params=shard_lm_params(self.mesh, st.params), batch_stats={},
                opt_state=jax.device_put(st.opt_state,
                                         NamedSharding(self.mesh, P())),
                loss_scale=None)
        if cfg.fsdp and not (self.use_sp or self.use_pp):
            from tpu_dist.parallel.fsdp import shard_state_fsdp
            return shard_state_fsdp(self.mesh, st)
        # distlint: disable=DL008 -- state placement at init/resume, not a per-step input upload
        return jax.device_put(st, replicated(self.mesh))

    # ------------------------------------------------------------------
    def _measure_comm_probe(self) -> float:
        """Wall seconds of ONE standalone bucketed grad sync at this run's
        exact bucket geometry (zeros in the params' shapes) — the comm_s
        estimate stamped on step ledger records. One extra tiny compile,
        paid only when grad_bucket_mb > 0."""
        import jax.numpy as jnp
        from tpu_dist._compat import shard_map
        from tpu_dist.parallel.overlap import bucketed_grad_sync

        n = self.mesh.shape["data"]
        mb = self.cfg.grad_bucket_mb
        sync = jax.jit(shard_map(
            lambda g: bucketed_grad_sync(g, "data", mb, mean=True,
                                         axis_size=n),
            mesh=self.mesh, in_specs=P(), out_specs=P(), check_vma=False))
        zeros = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32),
                             self.state.params)
        # distlint: disable=DL002 -- comm probe: compile+warm barrier, measures the sync on purpose
        jax.block_until_ready(sync(zeros))  # compile + warm
        t0 = time.time()
        # distlint: disable=DL002 -- comm probe: the measured barrier itself
        jax.block_until_ready(sync(zeros))
        return time.time() - t0

    def log(self, *a, **kw):
        if getattr(self, "is_main", jax.process_index() == 0):
            print(*a, **kw, flush=True)

    def _sampler(self, ds, train: bool, epoch: int) -> DistributedSampler:
        sampler = DistributedSampler(
            len(ds), num_replicas=jax.process_count(),
            rank=jax.process_index(), shuffle=train,
            seed=(self.cfg.seed or 0) + (17 if not train else 0),
            batch_size=self.local_batch)
        sampler.set_epoch(epoch)
        return sampler

    def _epoch_indices(self, ds, train: bool, epoch: int):
        """(idx (nb, B), valid (nb, B)) — the SAME batch-blocked layout as
        the image Trainer (load-bearing for N-process bit-exactness)."""
        sampler = self._sampler(ds, train, epoch)
        idx, valid = sampler.indices_with_valid()
        nb = sampler.num_samples // self.local_batch
        n = nb * self.local_batch
        shape = (nb, self.local_batch)
        return (np.asarray(idx[:n], np.int32).reshape(shape),
                np.asarray(valid[:n], np.float32).reshape(shape))

    def _drain(self, pending, meters) -> None:
        """One blocking transfer per print window (the async-dispatch sync
        point), then one ledger ``step`` record per drained entry with the
        transfer's device-block time apportioned across the window. The
        fused health probes ride the same fetch (obs.health): under
        ``skip`` a non-finite record stays out of the meter averages (its
        update was already zeroed on device), and under ``halt`` the
        sentry raises out of the loop."""
        import math

        with self.obs.tracer.span("device"):
            # distlint: disable=DL002 -- THE drain boundary: the one sanctioned fetch point of the loop
            fetched = jax.device_get([m for m, _ in pending])
        device_s = self.obs.tracer.pop().get("device", 0.0)
        total_steps = sum(info["n_steps"] for _, info in pending) or 1
        from tpu_dist.utils.telemetry import device_memory_stats
        hbm = device_memory_stats()
        for m, (_, info) in zip(fetched, pending):
            cnt = float(m["count"])
            loss = float(m["loss_sum"]) / cnt
            # under 'skip' the non-finite step's update was zeroed on
            # device, so its NaN loss must not poison the epoch averages;
            # under 'record'/'halt' the NaN flows through — divergence
            # should be VISIBLE in the printed loss, as it always was
            if math.isfinite(loss) or self.obs.health.policy != "skip":
                meters.update("Loss", loss, int(cnt))
                meters.update("Acc", float(m["correct1"]) / cnt, int(cnt))
            # MoE router health: mean per-token combine mass (1.0 = no
            # capacity drops; the dropped fraction is ~(1 - RMass) for
            # top-2, and (1 - RMass/avg_gate) for top-1)
            n = float(m.get("router_mass_n", 0.0))
            if n > 0:
                meters.update("RMass", float(m["router_mass_sum"]) / n,
                              int(n))
            k = info["n_steps"]
            share = device_s * k / total_steps
            gn = float(m["grad_norm"]) / k
            nf = float(m["nonfinite_count"])
            un = float(m["update_norm"]) / k
            self.obs.step(
                info["step"], loss, info["n_items"],
                wall_s=info["data_s"] + info["dispatch_s"] + share,
                data_s=info["data_s"], dispatch_s=info["dispatch_s"],
                device_s=share, device_flops=self._device_step_flops(),
                steps_in_dispatch=k,
                warm=info.get("warm", False),
                comm_s=(self._comm_probe_s * k
                        if self._comm_probe_s else None),
                fused=self._fused_quant,
                grad_norm=gn, nonfinite_count=nf, update_norm=un,
                hbm_bytes_in_use=hbm.get("bytes_in_use"),
                hbm_peak_bytes=hbm.get("peak_bytes_in_use"))
            self.obs.health.observe(info["step"], loss, nonfinite=nf,
                                    grad_norm=gn, update_norm=un, n_steps=k)
        pending.clear()
        self.obs.heartbeat()  # watchdog: device progress proven at this sync
        # recompile sentry (PL005): a host-only trace-cache counter read
        # at the sanctioned boundary — no device sync rides on it
        from tpu_dist.plan.compile import check_audit_sentry
        check_audit_sentry()

    def _meter_fields(self):
        fields = [("Time", "6.3f"), ("Data", "6.3f"), ("Loss", ".4e"),
                  ("Acc", "6.3f")]
        if self.cfg.num_experts:
            fields.append(("RMass", "5.3f"))
        return fields

    # ------------------------------------------------------------------
    def train_epoch(self, epoch: int) -> Dict[str, float]:
        if self.device_data:
            return self._train_epoch_windowed(epoch)
        cfg = self.cfg
        idx, _ = self._epoch_indices(self.train_ds, True, epoch)
        nb = len(idx)
        meters = MeterBank(nb, self._meter_fields(),
                           prefix=f"Epoch: [{epoch}]")
        skip = self._skip_batches
        self._skip_batches = 0
        self.obs.resume()  # watchdog watches from epoch entry
        if self.accum > 1:
            # host-side split into (N, B/N, L) microbatches, sharded
            # (None, 'data') so every microbatch spans all devices
            sh = NamedSharding(self.mesh, P(None, "data"))
            shape = lambda a: a.reshape(self.accum, -1, a.shape[-1])
        else:
            sh = NamedSharding(self.mesh, self.data_spec)
            shape = lambda a: a

        def batches():
            # row gather + shift + upload dispatch, run in the prefetch
            # thread so assembly never stalls the dispatch loop
            for j in range(skip, nb):
                rows = self.train_ds.get_rows(idx[j])
                inputs, targets = make_lm_batches(rows)
                yield (j,
                       assemble_global(sh, np.ascontiguousarray(
                           shape(inputs))),
                       assemble_global(sh, np.ascontiguousarray(
                           shape(targets))))

        from tpu_dist.data.loader import stream_prefetch
        pending = []
        warm_secs, warm_batches = 0.0, 0
        i = skip - 1
        tokens_per_batch = cfg.batch_size * cfg.seq_len
        tr = self.obs.tracer
        end = time.time()
        for i, inputs_d, targets_d in stream_prefetch(batches()):
            data_s = time.time() - end
            meters.update("Data", data_s)
            gstep = epoch * self.steps_per_epoch + i
            effects = self.obs.fire_step_faults(gstep)
            if "nan_batch" in effects:
                self._apply_nan_fault()
            if "preempt_deadline" in effects:
                self.obs.request_preemption(
                    deadline_s=effects["preempt_deadline"].args.get("secs"),
                    source="fault")
            if self.obs.preempt_pending():
                self._preempt_snapshot(pending, meters)  # raises SystemExit
            was_cold = not self._warmed  # this dispatch carries the compile
            with step_annotation(gstep, self.obs.profiling), \
                    tr.span("dispatch"):
                self.state, metrics = self.train_step(
                    self.state, inputs_d, targets_d, self.rng)
            dispatch_s = tr.pop().get("dispatch", 0.0)
            if not self._warmed:
                # compile + first step, to the wall — a deliberate one-time
                # block so warm_secs excludes XLA compile from tok/s
                # distlint: disable=DL002 -- intentional single sync on the run's first dispatch (compile-wall measurement)
                jax.device_get(metrics)
                self._warmed = True
                warm_secs = time.time() - end
                warm_batches = 1
            if self._program_hbm is None:
                # probe AFTER the dispatch (and after the warm-timing
                # device_get, so warm_secs stays honest): the AOT lower/
                # compile would not seed jit's dispatch cache, so probing
                # first would compile the step twice (telemetry.py
                # contract); same-iteration probing keeps the column on
                # single-dispatch runs
                from tpu_dist.plan.compile import audit_mode, audit_program
                from tpu_dist.utils.telemetry import program_stats
                st = program_stats(self.train_step, self.state, inputs_d,
                                   targets_d, self.rng,
                                   with_hlo=bool(self.obs.ledger.path)
                                   or audit_mode() != "none")
                self._program_hbm = st["hbm_bytes"] or False
                self.obs.ledger.emit(
                    "compile", program="train_step",
                    seconds=warm_secs or None,
                    hbm_bytes=st["hbm_bytes"], flops=st["flops"])
                # compile-time audit pass against the SAME lowered
                # artifact (plan.compile.audit_program) — a no-op under
                # audit=none, one 'audit' ledger event per program else
                audit_program("train_step", self.train_step, self.state,
                              inputs_d, targets_d, self.rng,
                              hlo=st.get("hlo"), precision=cfg.precision)
                if st.get("hlo"):
                    # static cost attribution of the same executable (one
                    # lower for hbm/flops/buckets — obs.attr roofline)
                    from tpu_dist.obs.attr import emit_cost_model
                    emit_cost_model(self.obs.ledger, "train_step",
                                    st["hlo"], xla_flops=st["flops"])
            pending.append((metrics, {
                "step": gstep, "n_steps": 1, "n_items": tokens_per_batch,
                "data_s": data_s, "dispatch_s": dispatch_s,
                "warm": was_cold}))
            boundary = i % cfg.print_freq == 0 or i == nb - 1
            if boundary:
                self._drain(pending, meters)
            meters.update("Time", time.time() - end)
            if boundary and self.is_main:
                meters.display(i)
            end = time.time()
            if self._step_cap_hit(epoch, i + 1):
                break
        if pending:  # a max_steps break can land between print boundaries
            self._drain(pending, meters)
        self.obs.pause()  # eval/ckpt follow: steps stop completing by design
        done = i + 1 - skip if nb else 0
        snap = meters.snapshot()  # ONE read feeds printer, ledger, and return
        out = {"loss": snap["Loss"]["avg"], "acc": snap["Acc"]["avg"],
               "batches": done, "warmup_secs": warm_secs,
               "warmup_batches": warm_batches}
        if self.cfg.num_experts:
            out["rmass"] = snap["RMass"]["avg"]
        return out

    def _device_windows(self, epoch: int, skip: int, put):
        batches, _ = self._epoch_indices(self.train_ds, True, epoch)
        batches = batches[skip:]
        if self.cfg.max_steps:
            # a K-step dispatch is atomic, so clip the window list to the
            # remaining step budget — otherwise the windowed path would
            # overshoot max_steps by up to K-1 optimizer steps
            remaining = self.cfg.max_steps - \
                (epoch * self.steps_per_epoch + skip)
            batches = batches[:max(remaining, 0)]
        return [(len(w), put(np.ascontiguousarray(w)))
                for w in (batches[i:i + self.k]
                          for i in range(0, len(batches), self.k))]

    def _train_epoch_windowed(self, epoch: int) -> Dict[str, float]:
        """K optimizer steps per dispatch over HBM-resident rows: the host
        sends only (K, B) int32 index windows (the image Trainer's indexed
        path, loop.py, applied to tokens)."""
        cfg = self.cfg
        nb = self.steps_per_epoch
        meters = MeterBank(nb, self._meter_fields(),
                           prefix=f"Epoch: [{epoch}]")
        skip = self._skip_batches
        self._skip_batches = 0
        self.obs.resume()  # watchdog watches from epoch entry
        win_sh = NamedSharding(self.mesh, P(None, "data"))
        put = partial(assemble_global, win_sh)
        cached = self._prefetched_windows
        self._prefetched_windows = None
        if cached is not None and cached[0] == epoch and skip == 0:
            windows = cached[1]
        else:
            windows = self._device_windows(epoch, skip, put)
        pending = []
        done = skip
        last_print = skip - 1
        warm_secs, warm_batches = 0.0, 0
        tokens_per_batch = cfg.batch_size * cfg.seq_len
        tr = self.obs.tracer
        end = time.time()
        for n, idx_dev in windows:
            data_s = time.time() - end
            meters.update("Data", data_s / n, n)
            effects = self.obs.fire_step_faults(
                epoch * self.steps_per_epoch + done)
            if "nan_batch" in effects:
                self._apply_nan_fault()
            if "preempt_deadline" in effects:
                self.obs.request_preemption(
                    deadline_s=effects["preempt_deadline"].args.get("secs"),
                    source="fault")
            if self.obs.preempt_pending():
                self._preempt_snapshot(pending, meters)  # raises SystemExit
            was_cold = not self._warmed  # this dispatch carries the compile
            with step_annotation(epoch * self.steps_per_epoch + done,
                                 self.obs.profiling), tr.span("dispatch"):
                self.state, metrics = self.window_step(
                    self.state, self._train_rows_dev, idx_dev, self.rng)
            dispatch_s = tr.pop().get("dispatch", 0.0)
            if not self._warmed:
                # compile + first window, to the wall (see train_epoch)
                # distlint: disable=DL002 -- intentional single sync on the run's first dispatch (compile-wall measurement)
                jax.device_get(metrics)
                self._warmed = True
                warm_secs = time.time() - end
                warm_batches = n
            if self._program_hbm is None:
                # post-dispatch probe (same iteration, so single-window
                # runs record it too): see telemetry.program_stats
                from tpu_dist.plan.compile import audit_mode, audit_program
                from tpu_dist.utils.telemetry import program_stats
                st = program_stats(self.window_step, self.state,
                                   self._train_rows_dev, idx_dev, self.rng,
                                   with_hlo=bool(self.obs.ledger.path)
                                   or audit_mode() != "none")
                self._program_hbm = st["hbm_bytes"] or False
                self.obs.ledger.emit(
                    "compile", program="window_step",
                    seconds=warm_secs or None,
                    hbm_bytes=st["hbm_bytes"], flops=st["flops"])
                # same-artifact compile-time audit (plan.compile)
                audit_program("window_step", self.window_step, self.state,
                              self._train_rows_dev, idx_dev, self.rng,
                              hlo=st.get("hlo"), precision=cfg.precision)
                if st.get("hlo"):
                    # static cost attribution (obs.attr), same executable
                    from tpu_dist.obs.attr import emit_cost_model
                    emit_cost_model(self.obs.ledger, "window_step",
                                    st["hlo"], xla_flops=st["flops"])
            done += n
            pending.append((metrics, {
                "step": epoch * self.steps_per_epoch + done - 1,
                "n_steps": n, "n_items": n * tokens_per_batch,
                "data_s": data_s, "dispatch_s": dispatch_s,
                "warm": was_cold}))
            boundary = (done - 1) - last_print >= cfg.print_freq or done == nb
            if boundary and done == nb and epoch + 1 < cfg.epochs:
                # queue next epoch's index uploads before blocking on metrics
                self._prefetched_windows = (
                    epoch + 1, self._device_windows(epoch + 1, 0, put))
            if boundary:
                self._drain(pending, meters)
                last_print = done - 1
            meters.update("Time", (time.time() - end) / n, n)
            if boundary and self.is_main:
                meters.display(done - 1)
            end = time.time()
            if self._step_cap_hit(epoch, done):
                break
        if pending:  # a max_steps break can land between print boundaries
            self._drain(pending, meters)
        self.obs.pause()  # eval/ckpt follow: steps stop completing by design
        snap = meters.snapshot()
        out = {"loss": snap["Loss"]["avg"], "acc": snap["Acc"]["avg"],
               "batches": done - skip, "warmup_secs": warm_secs,
               "warmup_batches": warm_batches}
        if self.cfg.num_experts:
            out["rmass"] = snap["RMass"]["avg"]
        return out

    def _step_cap_hit(self, epoch: int, batches_done: int) -> bool:
        cap = self.cfg.max_steps
        return bool(cap) and epoch * self.steps_per_epoch + batches_done >= cap

    def _apply_nan_fault(self) -> None:
        """The ``nan_batch`` injection effect (obs.faults): token inputs
        are integers, so the numeric fault lands on the param tree — the
        next step's loss/grads go non-finite exactly as a NaN batch would
        make them, and the health sentry/policy takes it from there."""
        self.state = self.state.replace(
            params=faults.poison_params(self.state.params))

    def _preempt_snapshot(self, pending=None, meters=None) -> None:
        """Coordinated snapshot on preemption (round 13): the drain blocks
        until the in-flight dispatched steps land, then a consistent
        checkpoint commits through the CRC/keep-K container (the
        collective gather inside save_checkpoint is the cross-host
        barrier for sharded state) and the process exits ``PREEMPT_SNAPSHOT_RC`` — the supervisor
        classifies ``preemption_snapshotted`` and the restart resumes
        from THIS step, not the last periodic checkpoint."""
        cfg = self.cfg
        if pending:
            self._drain(pending, meters)
        self.obs.pause()  # the snapshot write is not a stall
        # distlint: disable=DL002 -- preemption boundary: one scalar fetch after the final drain
        step_done = int(jax.device_get(self.state.step))
        try:
            mesh_epoch = int(os.environ.get("TPU_DIST_MESH_EPOCH", "0") or 0)
        except ValueError:
            mesh_epoch = 0
        if cfg.checkpoint_dir:
            # cross-host consistency comes from save_checkpoint itself:
            # sharded states gather via a COLLECTIVE (every live host
            # blocks in it — the barrier), replicated dp state is in
            # per-step lockstep so process 0's replica IS the global cut.
            # No explicit sync_global_devices here: on a shrink-triggered
            # SIGTERM the lost host would never arrive and the barrier
            # would hang every survivor into its SIGKILL deadline.
            t0_ck = time.time()
            ckpt.save_checkpoint(
                cfg.checkpoint_dir, self.state, self._epoch_in_progress,
                0.0, "lm", is_best=False,
                extra_meta={"mid_epoch": True, "preempt": True,
                            "best_ppl": self.best_ppl, **self._run_meta},
                keep=cfg.keep_checkpoints)
            self.obs.ledger.emit(
                "ckpt", epoch=self._epoch_in_progress,
                path=cfg.checkpoint_dir, is_best=False,
                seconds=round(time.time() - t0_ck, 6), preempt=True)
        self.obs.ledger.emit(
            "scale", action="preempt_snapshot",
            processes=jax.process_count(), epoch=mesh_epoch, step=step_done)
        self.log(f"preempted ({self.obs.preempt_source}, deadline "
                 f"{self.obs.preempt_deadline_s}s): snapshot at step "
                 f"{step_done} — exiting for supervised resume")
        self.obs.run_end(status="preempted", snapshot_step=step_done,
                         best_ppl=self.best_ppl)
        raise SystemExit(PREEMPT_SNAPSHOT_RC)

    # ------------------------------------------------------------------
    def validate(self, epoch: int = 0):
        """Exact held-out metrics in EVERY mode: (loss, ppl, acc).
        Sampler wrap-padding is masked per row; sums divide by the true
        token count (the image Trainer's C15 contract, for tokens)."""
        t0_eval = time.time()  # exact eval badput for the goodput ledger
        idx, valid = self._epoch_indices(self.val_ds, False, epoch)
        if self._val_rows_dev is not None:
            win_sh = NamedSharding(self.mesh, P(None, "data"))
            # distlint: disable=DL002 -- one-dispatch eval: the eval drain boundary
            m = jax.device_get(self.window_eval_step(
                self.state.params, self._val_rows_dev,
                assemble_global(win_sh, np.ascontiguousarray(idx)),
                assemble_global(win_sh, np.ascontiguousarray(valid))))
            sums = {k: float(m[k]) for k in LM_METRIC_KEYS}
        else:
            sh = NamedSharding(self.mesh, self.data_spec)
            vsh = NamedSharding(self.mesh, self.valid_spec)
            pending = []
            for i in range(len(idx)):
                rows = self.val_ds.get_rows(idx[i])
                inputs, targets = make_lm_batches(rows)
                pending.append(self.eval_step(
                    self.state.params,
                    assemble_global(sh, np.ascontiguousarray(inputs)),
                    assemble_global(sh, np.ascontiguousarray(targets)),
                    assemble_global(vsh, np.ascontiguousarray(valid[i]))))
            sums = {k: 0.0 for k in LM_METRIC_KEYS}
            # distlint: disable=DL002 -- eval drain boundary: pending eval metrics fetched in one batch
            for m in jax.device_get(pending):
                for k in sums:
                    sums[k] += float(m[k])
        n = max(sums["count"], 1.0)
        loss = sums["loss_sum"] / n
        ppl = float(np.exp(min(loss, 30.0)))
        acc = sums["correct1"] / n
        self.obs.ledger.emit("eval", epoch=epoch, loss=loss, ppl=ppl,
                             acc=acc, count=int(sums["count"]),
                             seconds=round(time.time() - t0_eval, 6))
        self.log(f" * val_loss {loss:.4f} ppl {ppl:.2f} acc {acc:.3f}")
        return loss, ppl, acc

    # ------------------------------------------------------------------
    def _device_step_flops(self):
        """Per-device-program share of ONE optimizer step's model FLOPs
        (analytical — utils.mfu; computed once, lazily). Feeds both the
        epoch-line MFU (:meth:`_mfu`) and the per-step ledger records."""
        cfg = self.cfg
        if self._flops_per_step is None:
            from tpu_dist.utils.mfu import (lm_flops_per_token,
                                            moe_lm_flops_per_token)
            if cfg.num_experts:
                per_token = moe_lm_flops_per_token(
                    self.state.params, cfg.num_layers, cfg.seq_len,
                    cfg.d_model, cfg.num_experts, cfg.router_top_k,
                    total_tokens=cfg.batch_size * cfg.seq_len,
                    group_size=cfg.moe_group_size,
                    capacity_factor=cfg.moe_capacity_factor)
            else:
                per_token = lm_flops_per_token(
                    self.state.params, cfg.num_layers, cfg.seq_len,
                    cfg.d_model)
            ndev = self.mesh.devices.size
            self._flops_per_step = per_token * cfg.batch_size * \
                cfg.seq_len / ndev
        return self._flops_per_step or None

    def _mfu(self, tok_per_sec: float):
        """(tflops, mfu). ANALYTICAL model-FLOPs accounting for dense
        (6*N_non-embed + 6*layers*L*d, fwd+bwd, causal) AND MoE (dense part
        + top_k-activated expert params + the GShard dispatch/combine
        einsums) — XLA's cost model counts scan bodies once and cannot cost
        Pallas custom calls, so it understates flash runs, and it cannot
        see how many experts a token activates (VERDICT r3 #4)."""
        from tpu_dist.utils.mfu import peak_tflops_for
        if not self._device_step_flops():
            return None, None
        # per-device program FLOPs over the tokens IT processes per step
        tokens_per_step = self.cfg.batch_size * self.cfg.seq_len
        ndev = self.mesh.devices.size
        flops_per_token = self._flops_per_step / (tokens_per_step / ndev)
        tflops = (tok_per_sec / ndev) * flops_per_token / 1e12
        peak = peak_tflops_for(jax.devices()[0])
        return tflops, (tflops / peak if peak else None)

    # ------------------------------------------------------------------
    def fit(self) -> float:
        """Returns best val perplexity."""
        cfg = self.cfg
        # SIGTERM becomes a snapshot request this loop drains at its next
        # step boundary (the coordinated-preemption contract)
        self.obs.enable_preempt_snapshot()
        self.obs.run_start()
        if self._peer_restored:
            try:
                mesh_epoch = int(
                    os.environ.get("TPU_DIST_MESH_EPOCH", "0") or 0)
            except ValueError:
                mesh_epoch = 0
            self.obs.ledger.emit(
                "scale", action="peer_restore",
                processes=jax.process_count(), epoch=mesh_epoch)
        if cfg.evaluate:
            try:
                return self.validate(0)[1]
            finally:
                self.obs.run_end(best_ppl=self.best_ppl)
        stop_telemetry = None
        if cfg.telemetry_csv:
            # EVERY process samples; non-main paths are .pN-suffixed so
            # multi-host runs never clobber one file (obs.per_process_path)
            from tpu_dist.obs import per_process_path
            from tpu_dist.utils.telemetry import start_hbm_sampler
            stop_telemetry = start_hbm_sampler(
                per_process_path(cfg.telemetry_csv, jax.process_index()),
                ledger=self.obs.ledger)
        try:
            # real XLA trace (per-op device time, HBM, MXU utilization) —
            # the same C22 hook the image Trainer has; obs.profile_session
            # flushes it even on OOM/interrupt
            with profile_session(cfg.profile_dir, self.obs.profiling):
                self._fit_epochs()
        except HealthError:
            # a halt must never abandon an in-flight async write: join this
            # dir's writer before re-raising, surfacing any write failure
            # as a warning rather than masking the halt itself
            try:
                ckpt.wait_for_async_save(cfg.checkpoint_dir or None)
            except RuntimeError as we:
                self.log(f"warning: async checkpoint write failed during "
                         f"health halt: {we}")
            raise
        except KeyboardInterrupt:
            self.obs.pause()  # slow interrupt-save is not a stall
            if cfg.checkpoint_dir:
                ckpt.save_checkpoint(cfg.checkpoint_dir, self.state,
                                     self._epoch_in_progress,
                                     0.0, "lm", is_best=False,
                                     extra_meta={"mid_epoch": True,
                                                 "best_ppl": self.best_ppl,
                                                 **self._run_meta},
                                     keep=cfg.keep_checkpoints)
                self.log(f"interrupted — checkpoint saved at epoch "
                         f"{self._epoch_in_progress}; resume with --resume")
            else:
                self.log("interrupted — no checkpoint_dir, nothing saved")
            raise
        finally:
            if stop_telemetry is not None:
                stop_telemetry()
            ckpt.wait_for_async_save()
            self.obs.run_end(best_ppl=self.best_ppl)
        return self.best_ppl

    def _fit_epochs(self) -> None:
        cfg = self.cfg
        for epoch in range(self.start_epoch, cfg.epochs):
            self._epoch_in_progress = epoch
            if self.obs.preempt_pending():
                # SIGTERM landed during the previous eval/checkpoint span
                self._preempt_snapshot()
            t0 = time.time()
            train_metrics = self.train_epoch(epoch)
            train_secs = time.time() - t0
            loss, ppl, acc = self.validate(epoch)
            epoch_secs = time.time() - t0
            # throughput excludes the first dispatch of the RUN (XLA compile
            # rides on it — the old scripts/8 loop's 'first step compiles'
            # exclusion, kept through the Trainer rewrite)
            w_secs = train_metrics.get("warmup_secs", 0.0)
            w_batches = train_metrics.get("warmup_batches", 0)
            timed_batches = train_metrics["batches"] - w_batches
            if timed_batches > 0:
                tok_s = (timed_batches * cfg.batch_size * cfg.seq_len
                         / max(train_secs - w_secs, 1e-9))
            else:  # single-dispatch epoch: report the compile-laden rate
                tok_s = (train_metrics["batches"] * cfg.batch_size
                         * cfg.seq_len / max(train_secs, 1e-9))
            self.last_tok_s = tok_s
            tflops, mfu = self._mfu(tok_s)
            is_best = ppl < self.best_ppl
            self.best_ppl = min(ppl, self.best_ppl)
            # the epoch record; the legacy per-epoch CSV row renders from
            # THIS event via the obs layer's EpochCsvSink — one source
            from tpu_dist.utils.telemetry import peak_hbm_bytes
            self.obs.ledger.emit(
                "epoch", epoch=epoch, start_ts=t0, seconds=epoch_secs,
                throughput=tok_s, unit="tok/s",
                loss=train_metrics["loss"], ppl=ppl, mfu=mfu, tflops=tflops,
                hbm_bytes=peak_hbm_bytes() or self._program_hbm or None,
                batches=train_metrics.get("batches"))
            if cfg.checkpoint_dir:
                t0_ck = time.time()  # sync-path save cost (async writes
                # overlap the next epoch; the goodput ledger charges only
                # what actually blocked the loop)
                ckpt.save_checkpoint(
                    cfg.checkpoint_dir, self.state, epoch + 1, 0.0, "lm",
                    is_best, extra_meta={"best_ppl": self.best_ppl,
                                         **self._run_meta},
                    async_write=True, keep=cfg.keep_checkpoints)
                self.obs.ledger.emit(
                    "ckpt", epoch=epoch + 1, path=cfg.checkpoint_dir,
                    is_best=is_best,
                    seconds=round(time.time() - t0_ck, 6))
            # LR actually applied by the LAST update of this epoch (the
            # schedule is evaluated at the pre-increment step counter)
            # distlint: disable=DL002 -- epoch boundary: validate() just drained the device queue, one scalar fetch is free
            step_done = int(jax.device_get(self.state.step))
            lr_now = float(self.lr_schedule(max(step_done - 1, 0)))
            self.log(
                f"Epoch {epoch} [{self.mode}]: "
                f"train_loss={train_metrics['loss']:.4f} "
                f"val_ppl={ppl:.2f} best={self.best_ppl:.2f} "
                f"lr={lr_now:.3g} "
                f"({epoch_secs:.1f}s, train {tok_s:,.0f} tok/s"
                + (f", {tflops:.1f} TF/s/chip" if tflops else "")
                + (f", MFU {mfu * 100:.1f}%" if mfu else "") + ")")
            if self._step_cap_hit(epoch, self.steps_per_epoch):
                self.log(f"max_steps={cfg.max_steps} reached")
                return
