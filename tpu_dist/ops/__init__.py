from tpu_dist.ops.optim import (  # noqa: F401
    lm_lr_schedule, make_optimizer, step_decay_schedule)
from tpu_dist.ops.precision import (  # noqa: F401
    LossScaleState, Policy, make_policy, scale_loss, unscale_and_update)
