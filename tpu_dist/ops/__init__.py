from tpu_dist.ops.optim import make_optimizer, step_decay_schedule  # noqa: F401
from tpu_dist.ops.precision import (  # noqa: F401
    LossScaleState, Policy, make_policy, scale_loss, unscale_and_update)
