from tpu_dist.ops.optim import (  # noqa: F401
    lm_lr_schedule, make_optimizer, step_decay_schedule)
from tpu_dist.ops.precision import (  # noqa: F401
    LossScaleState, Policy, make_policy, scale_loss, unscale_and_update)
from tpu_dist.ops.quant import (  # noqa: F401
    QUANT_MODES, QuantDense, quant_einsum, quant_matmul, quantize_int8,
    validate_quant, wo_quantize_params)
