"""Int8 quantized matmuls (AQT-style): the rung above bf16 on the
precision ladder.

The reference's precision stack tops out at apex AMP O1/O2
(4.apex_distributed2.py), which this repo maps to the bf16 policy
(ops.precision). TPU MXUs additionally execute int8 x int8 -> int32 dots at
up to 2x the bf16 rate, and quantized training in the AQT mold captures that
without losing convergence:

* **weights**: per-channel symmetric int8 — one scale per output channel
  (amax over the contracting dims / 127), so a single outlier row cannot
  crush the resolution of every other channel;
* **activations**: dynamic per-row symmetric int8, computed inside the
  jitted step from the live tensor (no calibration pass, no state);
* **accumulation**: ``preferred_element_type=jnp.int32`` — the MXU's native
  int8 path — with the dequant folded into one fp multiply on the way out
  (``scale_lhs x scale_rhs`` broadcast into the output tile);
* **backward**: straight-through estimator — gradients flow as if the dot
  were the fp dot of the unquantized operands, the standard QAT recipe
  (quantization noise is treated as identity-gradient noise).

Two modes ride one knob (``quant`` in configs.TrainConfig/LMConfig):

* ``int8``    — quantize BOTH operands (the 2x-MXU training mode);
* ``int8_wo`` — weight-only: weights fake-quantize (train) or live in HBM
  as int8 with fp32 scales (decode — :func:`wo_quantize_params`), while
  activations stay in the compute dtype. This is the memory-bound-decode
  mode: the per-tick weight traffic halves vs bf16 and the matmul itself
  stays fp.

Scales are tiny (one fp32 per output channel) and replicated, so GSPMD
partitioning of the surrounding program is unchanged — under dp x tp the
quantize/amax ops partition like any other elementwise/reduce op.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

QUANT_MODES = ("none", "int8", "int8_wo")

_INT8_MAX = 127.0
_EPS = 1e-8  # floor for all-zero channels: keeps scale finite, q = 0

# ---- fused-kernel dispatch (ops.pallas_quant) ------------------------------
# Tri-state: None = auto (fused on TPU, reference math elsewhere — the
# interpret-mode kernel is correct but slow, so CPU tests keep the cheap
# XLA path unless they opt in); True/False force it. The env knob
# TPU_DIST_FUSED_QUANT=1/0 seeds the state so bench/CLI runs can flip it
# without code. Trace-time static: set it BEFORE building step functions.
_FUSED_QUANT: Optional[bool] = (
    None if os.environ.get("TPU_DIST_FUSED_QUANT", "") == ""
    else os.environ["TPU_DIST_FUSED_QUANT"] not in ("0", "false", ""))


def set_fused_quant(enabled: Optional[bool]) -> None:
    """Force the fused Pallas int8 kernel on/off (None restores auto).
    Trace-time static — call before step functions are built."""
    global _FUSED_QUANT
    _FUSED_QUANT = enabled


def fused_quant_active() -> bool:
    """Whether ``quant_matmul(mode='int8')`` routes through the fused
    Pallas kernel right now (the engines stamp this into step records as
    the ``fused`` flag so ledger readers can attribute MFU deltas)."""
    if _FUSED_QUANT is not None:
        return _FUSED_QUANT
    return jax.default_backend() == "tpu"


def validate_quant(mode: str) -> str:
    if mode not in QUANT_MODES:
        raise ValueError(f"unknown quant mode {mode!r} "
                         f"({'|'.join(QUANT_MODES)})")
    return mode


def quantize_int8(x: jax.Array, reduce_dims) -> tuple[jax.Array, jax.Array]:
    """Symmetric int8 quantization of ``x`` with one scale per slice along
    the non-reduced dims (``reduce_dims`` = the contracting dims: amax over
    them, keepdims). Returns (q int8, scale fp32); ``q * scale`` dequantizes.
    """
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=tuple(reduce_dims), keepdims=True)
    scale = jnp.maximum(amax, _EPS) / _INT8_MAX
    q = jnp.clip(jnp.round(xf / scale), -_INT8_MAX, _INT8_MAX).astype(jnp.int8)
    return q, scale


def dequantize(q: jax.Array, scale: jax.Array, dtype=jnp.float32) -> jax.Array:
    return (q.astype(jnp.float32) * scale).astype(dtype)


def _contracted_dims(spec: str, operand: str) -> tuple:
    """Dims of ``operand`` (one side of an 'ab,bc->ac' einsum) that do not
    survive to the output — the contracting dims the scale reduces over."""
    out = spec.split("->")[1]
    return tuple(i for i, ch in enumerate(operand) if ch not in out)


@partial(jax.custom_vjp, nondiff_argnums=(0,))
def quant_einsum(spec: str, lhs: jax.Array, rhs: jax.Array) -> jax.Array:
    """``jnp.einsum(spec, lhs, rhs)`` with both operands int8-quantized and
    int32 accumulation; backward is the straight-through estimator (the vjp
    of the FP einsum on the unquantized operands).

    ``spec`` must be a two-operand explicit einsum (``'...->...'``). Scales
    reduce over each operand's contracted dims, so the dequant is exact:
    the same einsum applied to the (1-sized over contracted dims) scale
    tensors yields the per-output-element ``scale_lhs * scale_rhs`` product.
    """
    return _quant_einsum_fwd_impl(spec, lhs, rhs)


def _quant_einsum_fwd_impl(spec, lhs, rhs):
    ins, _ = spec.split("->")
    l_sub, r_sub = ins.split(",")
    ql, sl = quantize_int8(lhs, _contracted_dims(spec, l_sub))
    qr, sr = quantize_int8(rhs, _contracted_dims(spec, r_sub))
    out_i32 = jnp.einsum(spec, ql, qr, preferred_element_type=jnp.int32)
    out_scale = jnp.einsum(spec, sl, sr)  # contracted dims are size 1: product
    return (out_i32.astype(jnp.float32) * out_scale).astype(lhs.dtype)


def _quant_einsum_fwd(spec, lhs, rhs):
    return _quant_einsum_fwd_impl(spec, lhs, rhs), (lhs, rhs)


def _quant_einsum_bwd(spec, res, g):
    lhs, rhs = res
    _, vjp = jax.vjp(lambda a, b: jnp.einsum(spec, a, b), lhs, rhs)
    return vjp(g)


quant_einsum.defvjp(_quant_einsum_fwd, _quant_einsum_bwd)


def wo_fake_quant(w: jax.Array, reduce_dims=(0,)) -> jax.Array:
    """Weight-only fake quantization with an STE: forward sees the int8
    round-trip of ``w`` (per-channel scales over ``reduce_dims``), backward
    sees identity — plain autodiff delivers the STE, no custom_vjp needed."""
    q, scale = quantize_int8(w, reduce_dims)
    wq = dequantize(q, scale, w.dtype)
    return w + jax.lax.stop_gradient(wq - w)


def _dense_spec(ndim: int) -> str:
    """'abd,dZ->abZ'-style spec for an (..., D) x (D, F) dense matmul."""
    batch = "abcegh"[:ndim - 1]  # skip d/f/Z, enough for any sane rank
    return f"{batch}d,dZ->{batch}Z"


def quant_matmul(x: jax.Array, w: jax.Array, mode: str) -> jax.Array:
    """THE mode dispatch for a (..., D) x (D, F) matmul — the single home
    of what each quant mode means, shared by QuantDense and the pipeline
    head (parallel.pp._head_logits) so the two can never diverge: dynamic-
    activation int8 einsum for 'int8', fake-quantized weights for
    'int8_wo', an exact fp matmul for 'none'. Both operands must already
    be in the compute dtype."""
    if mode == "int8":
        if fused_quant_active():
            # one Pallas kernel: quantize + int8 MXU dot + dequant, no
            # int8/int32 HBM intermediates (ops.pallas_quant); identical
            # scales/rounding to the reference einsum, STE backward
            from tpu_dist.ops.pallas_quant import fused_quant_matmul
            return fused_quant_matmul(x, w)
        # both operands quantized, int32 accumulation, STE backward
        return quant_einsum(_dense_spec(x.ndim), x, w)
    if mode == "int8_wo":
        return jnp.dot(x, wo_fake_quant(w))
    validate_quant(mode)  # 'none' (exact fp) is all that remains
    return jnp.dot(x, w)


class QuantDense(nn.Module):
    """Drop-in quantized ``nn.Dense``: same param names ("kernel"/"bias"),
    same init, same (in, out) kernel layout — checkpoints and the Megatron
    TP sharding rules (parallel.tp) apply unchanged.

    ``mode='int8'`` quantizes activations (dynamic per-row) AND weights
    (per-output-channel) into an int32-accumulated dot with an STE backward;
    ``mode='int8_wo'`` fake-quantizes only the weights and keeps the matmul
    in the compute dtype.

    Weight-only DECODE: when the param dict carries a pre-quantized kernel
    (int8 ``kernel`` + fp32 ``kernel_scale`` — :func:`wo_quantize_params`),
    the kernel stays int8 in HBM and is dequantized on the fly, halving the
    per-tick weight traffic that bounds autoregressive decode. The branch is
    static (variable presence), so train and decode programs never mix.
    """

    features: int
    mode: str = "int8"
    use_bias: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        validate_quant(self.mode)
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (x.shape[-1], self.features))
        x = x.astype(self.dtype)
        if self.has_variable("params", "kernel_scale"):
            if self.mode == "int8":
                # refuse rather than silently degrade: a wo-quantized tree
                # has lost the fp weights, so the dynamic-activation int8
                # program the caller asked for cannot be built from it
                raise ValueError(
                    "params carry a pre-quantized int8 kernel "
                    "(kernel_scale leaf, wo_quantize_params) but "
                    "mode='int8' was requested; pre-quantized trees only "
                    "support the weight-only path — pass quant='int8_wo', "
                    "or keep the fp params for dynamic int8.")
            # pre-quantized weight-only path (decode): int8-resident kernel
            scale = self.get_variable("params", "kernel_scale")
            w = dequantize(kernel, scale, self.dtype)
            y = jnp.dot(x, w)
        else:
            y = quant_matmul(x, kernel.astype(self.dtype), self.mode)
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros,
                              (self.features,))
            y = y + bias.astype(self.dtype)
        return y


def make_dense(features: int, *, use_bias: bool = True,
               dtype=jnp.float32, name: Optional[str] = None,
               quant: str = "none", tp_impl: str = "gspmd",
               tp_kind: Optional[str] = None, tp_fused: int = 1) -> nn.Module:
    """THE dense-layer factory of the transformer families: ``nn.Dense``
    when quantization is off, :class:`QuantDense` (identical param tree)
    otherwise — so the quant knob never forks model param structure.

    ``tp_impl`` other than 'gspmd' with a ``tp_kind`` ('column'|'row')
    routes through the ring collective matmul
    (:class:`tpu_dist.parallel.overlap.RingDense` — still the identical
    param tree, quant riding the same ring); layers with no parallel role
    (tp_kind=None, e.g. a replicated lm_head under ring) stay on the
    plain/quant path whatever the impl."""
    if tp_impl != "gspmd" and tp_kind is not None:
        # local import: parallel.overlap imports quant_matmul from here
        from tpu_dist.parallel.overlap import RingDense
        return RingDense(features, kind=tp_kind, flavor=tp_impl,
                         use_bias=use_bias, dtype=dtype, n_fused=tp_fused,
                         quant=validate_quant(quant), name=name)
    if validate_quant(quant) == "none":
        return nn.Dense(features, use_bias=use_bias, dtype=dtype, name=name)
    return QuantDense(features, mode=quant, use_bias=use_bias, dtype=dtype,
                      name=name)


# ---- MoE expert matmuls ----------------------------------------------------
# The expert contractions carry a batch dim (the expert index e) next to the
# contracting dim, so they route through quant_einsum directly with
# per-expert-per-channel weight scales; the router gate and the one-hot
# dispatch/combine einsums stay in fp (they are selection, not compute).

def moe_expert_matmul(spec: str, acts: jax.Array, w: jax.Array,
                      quant: str = "none") -> jax.Array:
    """One expert contraction ('gecd,edf->gecf' or 'gecf,efd->gecd') under
    the active quant mode: fp einsum (none), weight fake-quant (int8_wo),
    or fully quantized with STE (int8)."""
    if validate_quant(quant) == "none":
        return jnp.einsum(spec, acts, w)
    if quant == "int8_wo":
        r_sub = spec.split("->")[0].split(",")[1]
        return jnp.einsum(spec, acts,
                          wo_fake_quant(w, _contracted_dims(spec, r_sub)))
    return quant_einsum(spec, acts, w)


# ---- weight-only decode: pre-quantized param trees -------------------------

_MOE_EXPERT_LEAVES = ("w_in", "w_out")


def _quantize_tree(tree):
    out = {}
    for k, v in tree.items():
        if isinstance(v, dict):
            # the router gate stays fp32: its argmax picks the expert, and
            # int8 logits would reroute tokens rather than perturb them
            out[k] = v if k == "gate" else _quantize_tree(v)
        elif k == "kernel" and getattr(v, "ndim", 0) == 2:
            q, s = quantize_int8(v, (0,))
            out[k], out[k + "_scale"] = q, s
        elif k in _MOE_EXPERT_LEAVES and getattr(v, "ndim", 0) == 3:
            q, s = quantize_int8(v, (1,))  # (E, in, out): amax over in
            out[k], out[k + "_scale"] = q, s
        else:
            out[k] = v  # embeddings, norms, biases, cls/pos tokens
    return out


def wo_quantize_params(params):
    """Pre-quantize a transformer-family param tree for weight-only int8
    decode: every 2D dense ``kernel`` (and 3D MoE expert tensor) becomes an
    int8 leaf with a sibling ``<name>_scale`` fp32 leaf; everything else
    (embeddings, norms, biases, the MoE router gate) is untouched. The
    quantized tree feeds ``model.apply`` of a ``quant='int8_wo'`` model —
    QuantDense/MoEMLP detect the scale leaves and read the int8 weights
    directly (engine.generate wires this up for decode)."""
    return _quantize_tree(params)


def params_are_wo_quantized(params) -> bool:
    """True if ``params`` already carries wo-quantized scale leaves."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    return any(str(getattr(k, "key", "")).endswith("_scale")
               for path, _ in flat for k in path)
