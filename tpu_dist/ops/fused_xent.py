"""Chunked vocab cross-entropy: the LM-head memory/bandwidth lever.

The straightforward LM loss (engine.lm_steps.lm_loss_and_metrics)
materializes the full (B, L, V) fp32 logits — at the bench geometry (B8,
L2048, V32k) ~2 GB of HBM written by the head matmul, reduced by a
logsumexp (since round 5 it no longer writes a second log_softmax tensor),
and rematerialized as softmax-minus-onehot in the backward. The reference
never hits this (it trains CNNs with a 10-to-1000-way head:
/root/reference/1.dataparallel.py); a large-vocab LM pays it every step —
and at 100k+ vocabs the (B, L, V) tensor stops fitting at all, which is
when this chunked path wins (at V=32k it measures net-negative vs the
unfused loss: BASELINE.md round-5 0.9B table).

:func:`chunked_softmax_xent` computes the identical loss without ever holding
more than one (chunk, V) logits tile:

* forward — a ``lax.scan`` over row chunks of the flattened (B*L, D)
  features: each iteration does the chunk's head matmul (fp32 accumulation on
  the MXU), reduces it to per-row logsumexp / target-logit / argmax-hit, and
  discards the tile. Only the (N,) fp32 logsumexp survives as a residual.
* backward — ``jax.custom_vjp``: a second scan recomputes each chunk's
  logits, forms softmax-minus-onehot against the SAVED logsumexp (bitwise the
  forward's normalizer, no drift), and accumulates d_features rows and the
  (D, V) head-weight cotangent in fp32.

Peak extra memory is O(chunk * V + D * V) instead of O(B * L * V), and the
logits never round-trip HBM in fp32 — the same recompute-what's-cheap trade
the flash-attention kernels make, applied to the other big tile in the model.

The head matmul runs in ``compute_dtype`` (bf16 under the bf16 policy) with
fp32 accumulation — slightly MORE accurate than the unfused path, which
rounds the Dense output to bf16 before upcasting for the softmax.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def _chunk_stats(x_c, w, t_c, compute_dtype):
    """One chunk's (logsumexp, target-logit, argmax==target). The backward
    does NOT reuse this — it rebuilds the logits tile and normalizes against
    the forward's saved lse, so fwd/bwd softmax agree bitwise."""
    logits = jnp.dot(x_c.astype(compute_dtype), w.astype(compute_dtype),
                     preferred_element_type=jnp.float32)        # (C, V) fp32
    m = jnp.max(logits, axis=-1)
    lse = m + jnp.log(jnp.sum(jnp.exp(logits - m[:, None]), axis=-1))
    tgt = jnp.take_along_axis(logits, t_c[:, None], axis=-1)[:, 0]
    hit = (jnp.argmax(logits, axis=-1) == t_c).astype(jnp.float32)
    return lse, tgt, hit


def _pad_rows(a, n_pad):
    return a if n_pad == 0 else jnp.pad(a, [(0, n_pad)] + [(0, 0)] * (a.ndim - 1))


def _forward(x, w, targets, mask, chunk, compute_dtype):
    b, l, d = x.shape
    n = b * l
    xf = x.reshape(n, d)
    tf = targets.reshape(n)
    mf = mask.reshape(n).astype(jnp.float32)
    chunk = max(1, min(chunk, n))
    n_pad = (-n) % chunk
    xf_p = _pad_rows(xf, n_pad)
    tf_p = _pad_rows(tf, n_pad)
    mf_p = _pad_rows(mf, n_pad)       # padded rows carry mask 0 -> no effect
    k = (n + n_pad) // chunk

    def body(sums, blk):
        x_c, t_c, m_c = blk
        lse, tgt, hit = _chunk_stats(x_c, w, t_c, compute_dtype)
        loss_s, corr_s = sums
        return (loss_s + jnp.sum((lse - tgt) * m_c),
                corr_s + jnp.sum(hit * m_c)), lse

    (loss_sum, correct), lse_all = jax.lax.scan(
        body, (jnp.float32(0.0), jnp.float32(0.0)),
        (xf_p.reshape(k, chunk, d), tf_p.reshape(k, chunk),
         mf_p.reshape(k, chunk)))
    return loss_sum, correct, lse_all, (xf_p, tf_p, mf_p, n_pad)


@partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def chunked_softmax_xent(x, w, targets, mask, chunk=1024,
                         compute_dtype=jnp.float32):
    """(loss_sum, correct1) over masked positions, without full logits.

    x (B, L, D) features after the final norm; w (D, V) lm_head kernel;
    targets (B, L) int; mask (B, L). Differentiable in x and w only; the
    metrics output carries no gradient. Matches
    ``lm_loss_and_metrics(x @ w, targets, mask)`` to fp32 accumulation order.
    """
    loss_sum, correct, _, _ = _forward(x, w, targets, mask, chunk,
                                       compute_dtype)
    return loss_sum, correct


def _fwd(x, w, targets, mask, chunk, compute_dtype):
    loss_sum, correct, lse_all, (xf_p, tf_p, mf_p, n_pad) = _forward(
        x, w, targets, mask, chunk, compute_dtype)
    res = (xf_p, w, tf_p, mf_p, lse_all, x.shape, n_pad)
    return (loss_sum, correct), res


def _bwd(chunk, compute_dtype, res, g):
    g_loss = g[0]  # cotangent of loss_sum; correct1 carries no gradient
    xf_p, w, tf_p, mf_p, lse_all, x_shape, n_pad = res
    n_rows = xf_p.shape[0]
    c = max(1, min(chunk, x_shape[0] * x_shape[1]))
    k = n_rows // c
    d, v = w.shape
    cd = compute_dtype

    def body(dw_acc, blk):
        x_c, t_c, m_c, lse = blk
        logits = jnp.dot(x_c.astype(cd), w.astype(cd),
                         preferred_element_type=jnp.float32)
        p = jnp.exp(logits - lse[:, None])                     # softmax, fp32
        scale = (m_c * g_loss)[:, None]
        dlogits = (p - jax.nn.one_hot(t_c, v, dtype=jnp.float32)) * scale
        dl_c = dlogits.astype(cd)
        dx_c = jnp.dot(dl_c, w.astype(cd).T,
                       preferred_element_type=jnp.float32)
        dw_acc = dw_acc + jnp.dot(x_c.astype(cd).T, dl_c,
                                  preferred_element_type=jnp.float32)
        return dw_acc, dx_c

    dw, dx_chunks = jax.lax.scan(
        body, jnp.zeros((d, v), jnp.float32),
        (xf_p.reshape(k, c, d), tf_p.reshape(k, c), mf_p.reshape(k, c),
         lse_all))
    dx = dx_chunks.reshape(n_rows, d)
    if n_pad:
        dx = dx[:n_rows - n_pad]
    return (dx.reshape(x_shape).astype(xf_p.dtype), dw.astype(w.dtype),
            None, None)


chunked_softmax_xent.defvjp(_fwd, _bwd)
