"""Pallas fused SGD-momentum update kernel (apex fused-optimizer analog).

The reference leaned on apex's fused CUDA optimizer kernels
(reference 4.apex_distributed2.py:21-22,177; README_EN.md:292-326 documents
the nvcc --cuda_ext build). TPU-native equivalent: one Pallas kernel applies
weight decay + momentum + parameter update in a single pass over each leaf —
read (p, g, m), write (p', m') — instead of the optax chain's conceptual
multi-pass (XLA usually fuses that chain inside the jitted step too, so the
honest value here is guaranteed fusion + a vehicle for lower-precision
momentum experiments; the microbenchmark in tests reports both paths).

Update rule, exactly torch.optim.SGD (reference 1.dataparallel.py:114-116),
with optional global-norm clipping fused in:
    g  <- g * cs           (cs = clip/norm when norm > clip, else 1)
    g' = g + wd * p
    m' = mu * m + g'
    p' = p - lr * m'

``clip_norm > 0`` is torch.nn.utils.clip_grad_norm_ placement (raw grads,
before weight decay and momentum) at zero extra passes over the params:
the global norm is one squared-sum reduction per leaf (:func:`clip_scale`)
and the resulting scale rides the scalar row into the kernel, where the
multiply fuses with the update sweep — no standalone clip pass ever
touches HBM. ops.pallas_adamw mirrors the same slot.

All math in fp32 regardless of the param dtype (bf16 params round once, at
the final store) — matching fp32 master-weight semantics.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANE = 128          # VPU lane width
BLOCK_ROWS = 512    # default rows per grid step: 512x128 fp32 = 256 KiB/buffer

# searchable block size (plan IR, round 15): the 512-row tile was
# hard-coded through round 14; the plan auto-tuner threads it now.
# Trace-time static — set before building step functions
# (plan.compile.activate_plan does). Env seed for bench/CLI runs.
import os as _os

_BLOCK_ROWS = BLOCK_ROWS


def set_block_rows(rows=None) -> None:
    """Set the fused-optimizer kernels' VMEM tile rows (None restores the
    512 default; shared setting with ops.pallas_adamw). Legality lives in
    plan.ir.validate_opt_block_rows — the ONE rule the IR also enforces."""
    from tpu_dist.plan.ir import validate_opt_block_rows

    global _BLOCK_ROWS
    rows = BLOCK_ROWS if rows is None else int(rows)
    validate_opt_block_rows(rows)
    _BLOCK_ROWS = rows


if _os.environ.get("TPU_DIST_OPT_BLOCK_ROWS"):
    # the env seed rides the validated setter: a bad value fails loudly
    # at import, not as a Mosaic tiling abort at first trace
    set_block_rows(int(_os.environ["TPU_DIST_OPT_BLOCK_ROWS"]))


def block_rows() -> int:
    """The row-tile size the next trace will use."""
    return _BLOCK_ROWS


def clip_scale(grads, clip_norm: float):
    """Global-norm clip factor for a grad tree: ``clip/norm`` when the fp32
    global norm exceeds ``clip_norm``, else 1.0 (optax.clip_by_global_norm /
    torch clip_grad_norm_ semantics, the parallel.pp._clip_pp_grads
    formula). One squared-sum reduction per leaf; the factor then rides the
    fused kernels' scalar row so the clip multiply costs no extra pass.
    ``clip_norm <= 0`` returns a constant 1.0 (clipping off)."""
    if clip_norm <= 0:
        return jnp.float32(1.0)
    sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
             for g in jax.tree.leaves(grads))
    norm = jnp.sqrt(sq)
    return jnp.where(norm > clip_norm,
                     jnp.float32(clip_norm) / jnp.maximum(norm, 1e-30),
                     jnp.float32(1.0))


def _sgd_kernel(scal_ref, p_ref, g_ref, m_ref, p_out, m_out):
    lr = scal_ref[0, 0]
    mu = scal_ref[0, 1]
    wd = scal_ref[0, 2]
    cs = scal_ref[0, 3]   # global-norm clip scale (1.0 = no clip)
    p = p_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32) * cs + wd * p
    m = mu * m_ref[:].astype(jnp.float32) + g
    p_out[:] = (p - lr * m).astype(p_out.dtype)
    m_out[:] = m


def _fused_sgd_2d(p2, g2, m2, scalars, interpret: bool):
    rows = p2.shape[0]
    grid = (pl.cdiv(rows, _BLOCK_ROWS),)
    bs = lambda: pl.BlockSpec((_BLOCK_ROWS, LANE), lambda i: (i, 0),
                              memory_space=pl.ANY if interpret else pltpu.VMEM)
    return pl.pallas_call(
        _sgd_kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((1, 4), lambda i: (0, 0),
                               memory_space=pltpu.SMEM),
                  bs(), bs(), bs()],
        out_specs=[bs(), bs()],
        out_shape=[jax.ShapeDtypeStruct(p2.shape, p2.dtype),
                   jax.ShapeDtypeStruct(m2.shape, jnp.float32)],
        input_output_aliases={1: 0, 3: 1},  # donate p and m buffers
        interpret=interpret,
    )(scalars, p2, g2, m2)


def fused_sgd_leaf(p, g, m, lr, momentum, weight_decay, interpret=False,
                   clip=1.0):
    """Apply the fused update to one array (any shape/dtype); returns
    (p', m'). ``clip`` is the shared global-norm scale (:func:`clip_scale`;
    1.0 = clipping off) — computed ONCE per step over the whole tree, not
    per leaf."""
    shape, size = p.shape, p.size
    rows = -(-size // LANE)
    pad = rows * LANE - size
    def to2d(x, dtype):
        flat = x.astype(dtype).reshape(-1)
        if pad:
            flat = jnp.pad(flat, (0, pad))
        return flat.reshape(rows, LANE)
    scalars = jnp.stack([jnp.asarray(lr, jnp.float32),
                         jnp.asarray(momentum, jnp.float32),
                         jnp.asarray(weight_decay, jnp.float32),
                         jnp.asarray(clip, jnp.float32)]).reshape(1, 4)
    p2, m2 = _fused_sgd_2d(to2d(p, p.dtype), to2d(g, jnp.float32),
                           to2d(m, jnp.float32), scalars, interpret)
    unpad = lambda x2, dt: x2.reshape(-1)[:size].reshape(shape).astype(dt)
    return unpad(p2, p.dtype), unpad(m2, jnp.float32)


class FusedSGDState(NamedTuple):
    trace: Any  # momentum buffers, fp32


class FusedSGD:
    """Fused-kernel optimizer with the engine-facing apply() protocol.

    Unlike an optax GradientTransformation (which returns *updates* that the
    caller adds — forcing an extra pass), apply() fuses the whole update and
    returns new params directly. The engine step builders accept either.
    """

    def __init__(self, schedule: Callable, momentum: float = 0.9,
                 weight_decay: float = 1e-4, clip_norm: float = 0.0,
                 interpret: bool = False):
        self.schedule = schedule
        self.momentum = momentum
        self.weight_decay = weight_decay
        self.clip_norm = clip_norm
        self.interpret = interpret

    def init(self, params) -> FusedSGDState:
        return FusedSGDState(trace=jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params))

    def apply(self, params, grads, state: FusedSGDState, step):
        lr = jnp.asarray(self.schedule(step), jnp.float32)
        cs = clip_scale(grads, self.clip_norm)
        out = jax.tree.map(
            partial(self._leaf, lr, cs), params, grads, state.trace)
        new_params = jax.tree.map(lambda o: o[0], out,
                                  is_leaf=lambda x: isinstance(x, tuple))
        new_trace = jax.tree.map(lambda o: o[1], out,
                                 is_leaf=lambda x: isinstance(x, tuple))
        return new_params, FusedSGDState(trace=new_trace)

    def _leaf(self, lr, cs, p, g, m):
        return fused_sgd_leaf(p, g, m, lr, self.momentum, self.weight_decay,
                              interpret=self.interpret, clip=cs)
