"""Block-table (paged) KV attention primitives — the serving read/write path.

vLLM's PagedAttention [SOSP '23] observation, TPU-shaped: a contiguous
per-sequence KV cache sized to the worst-case total fragments HBM the moment
requests of mixed length share a batch — every slot pays max_len whether it
decodes 4 tokens or 4000. Instead the KV lives in ONE preallocated arena of
fixed-size pages per layer (``[num_pages, page_size, heads, head_dim]``) and
each sequence owns an ordered *block table* of page indices; allocation is a
free-list pop, eviction a push, and utilization follows actual lengths.

This module is the ops half (pure array programs — the pool/allocator lives
in ``engine.kv_cache``, the scheduler in ``engine.serve``):

* :func:`paged_write` — scatter new K/V rows into the arena through a block
  table at per-row positions (prefill writes a whole prompt, the decode tick
  one token per sequence). Masked rows route to the arena's *trash page*
  (index ``num_pages``, the reason arenas carry one extra page): the scatter
  stays branch-free and fully static under jit.
* :func:`gather_pages` — the read half: block table -> contiguous
  ``(B, max_pages * page_size, ...)`` view of each sequence's cache.
* :func:`paged_attend` — the attention entry ``models.transformer.
  attend_maybe_cached`` delegates to: prefill attends within the prompt via
  the model's own ``attn_fn`` (+ page writes); the decode tick writes one
  row and attends over the gathered pages with PER-ROW positions — the
  continuous-batching difference from the flax cache, whose scalar
  ``cache_index`` forces every batch row to the same position. The same
  non-prefill path generalizes to Lq > 1 as the speculative-decoding
  VERIFY read: row ``b`` carries ``Lq`` queries at positions
  ``pos[b]..pos[b]+Lq-1`` (the last real token plus the draft proposals),
  writes all their K/V rows through the block table, and attends each
  local query at its own causal horizon — one dispatch validates a whole
  draft window.
* :func:`cow_fork_pages` — the copy-on-write fork behind cross-request
  prefix sharing (``engine.kv_cache``): gather the shared source pages,
  scatter them onto freshly-granted destinations, so the writer diverges
  on its own copy and the other holders keep reading the original bits.
* int8 arenas: pages hold int8 values + one fp32 scale per (page-slot, head)
  row — the ``ops.flash_attention.quantize_kv`` layout, quantized by
  ``ops.quant.quantize_int8`` itself so the rounding convention can never
  drift. The exact read path dequantizes the gathered tiles;
  :func:`int8kv_paged_flash_attention_fn` is the Pallas variant that
  consumes the gathered int8 layout directly (dequant per VMEM tile, K/V
  never fp in HBM) with a per-row LENGTH mask instead of the training
  kernels' causal offsets — the decode-tick geometry where every batch row
  sits at a different position.

Exactness contract: the exact read path mirrors ``full_attention``'s math
op-for-op (fp32 scores/softmax, same einsum contractions), and masked slots
contribute *exactly zero* weight — so greedy decode through pages is
bit-identical to the contiguous-cache path (tests/test_serve.py pins it).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tpu_dist._compat import shard_map
from tpu_dist.ops.flash_attention import _STAT_LANES, NEG_INF, _blocks, _fold
from tpu_dist.parallel.mesh import SP_AXIS


def pages_for(length: int, page_size: int) -> int:
    """Pages a sequence of ``length`` tokens occupies (host-side helper)."""
    return -(-int(length) // int(page_size))


@jax.tree_util.register_pytree_node_class
class PagedLayer:
    """One layer's KV page arenas as a jit-traversable pack.

    ``k``/``v`` are ``(num_pages + 1, page_size, heads, head_dim)`` — the
    +1 is the trash page masked writes land on. int8 arenas additionally
    carry ``k_scale``/``v_scale`` ``(num_pages + 1, page_size, heads)``
    fp32. ``quant`` ("none" | "int8") and ``read`` ("exact" | "flash")
    ride in the pytree *aux data*: they are static, participate in jit
    cache keys, and can never be confused for traced values.
    """

    def __init__(self, k, v, k_scale=None, v_scale=None, *,
                 quant: str = "none", read: str = "exact"):
        self.k, self.v = k, v
        self.k_scale, self.v_scale = k_scale, v_scale
        self.quant, self.read = quant, read

    @property
    def num_pages(self) -> int:
        return self.k.shape[0] - 1               # minus the trash page

    @property
    def page_size(self) -> int:
        return self.k.shape[1]

    def replace(self, **kw) -> "PagedLayer":
        fields = dict(k=self.k, v=self.v, k_scale=self.k_scale,
                      v_scale=self.v_scale, quant=self.quant,
                      read=self.read)
        fields.update(kw)
        return PagedLayer(**fields)

    def tree_flatten(self):
        return ((self.k, self.v, self.k_scale, self.v_scale),
                (self.quant, self.read))

    @classmethod
    def tree_unflatten(cls, aux, children):
        k, v, ks, vs = children
        return cls(k, v, ks, vs, quant=aux[0], read=aux[1])


# ---------------------------------------------------------------------------
# arena scatter / gather
# ---------------------------------------------------------------------------

def flat_slot_index(block_table, positions, page_size: int):
    """(B, L) global arena slot indices for per-row token positions.

    ``page_size`` is static (an arena shape constant); positions beyond the
    table's reach are the CALLER's bug — the scheduler sizes tables to
    ``ceil(max_len / page_size)`` so every legal position has a page.
    """
    page = jnp.take_along_axis(block_table,
                               positions // page_size, axis=1)
    return page * page_size + positions % page_size


def paged_write(arena, block_table, positions, values, valid,
                trash_page: int):
    """Scatter ``values`` (B, L, ...) into ``arena`` (N+1, page_size, ...)
    at per-row ``positions`` (B, L); rows where ``valid`` (B, L) is False
    land on the trash page (slot 0) instead — a branch-free masked write.

    Distinct live sequences own disjoint pages (the allocator's contract),
    so live scatter indices never collide; trash collisions are harmless by
    definition.
    """
    n1, page_size = arena.shape[0], arena.shape[1]
    flat = flat_slot_index(block_table, positions, page_size)
    flat = jnp.where(valid, flat, trash_page * page_size)
    flat_arena = arena.reshape((n1 * page_size,) + arena.shape[2:])
    flat_arena = flat_arena.at[flat.reshape(-1)].set(
        values.reshape((-1,) + values.shape[2:]).astype(arena.dtype))
    return flat_arena.reshape(arena.shape)


def gather_pages(arena, block_table):
    """Block table (B, max_pages) -> (B, max_pages * page_size, ...) —
    each sequence's cache as one contiguous view (gather, no copy under
    XLA fusion when consumed immediately)."""
    g = arena[block_table]                       # (B, P, page_size, ...)
    b, p, s = g.shape[:3]
    return g.reshape((b, p * s) + g.shape[3:])


# ---------------------------------------------------------------------------
# sp-sharded arenas (engine.kv_cache sharded pool)
# ---------------------------------------------------------------------------
#
# When the pool shards its arenas over the serving sequence-parallel axis
# (``parallel.mesh.SP_AXIS``), dim 0 is laid out as ``n`` per-device blocks
# of ``rows_local = pages_per_device + 1`` rows — each device carries its
# own pages PLUS its own local trash row (the block's last row), so the
# branch-free masked-write discipline survives sharding without any
# cross-device scatter. Block tables then hold FLAT arena row indices
# (``engine.kv_cache.PagedKVPool.flat_block_table``); ownership of row
# ``r`` is ``r // rows_local``. The two collectives below are the ONLY
# sharded-arena primitives: every read/write composes out of them, and for
# a 1-device mesh both degenerate to the unsharded gather/scatter exactly.

def _sp_local_bt(block_table, rows_local: int, me):
    """Global flat rows -> this device's local rows; foreign rows route to
    the LOCAL trash (rows_local - 1), which their owner will serve."""
    owner = block_table // rows_local
    local = jnp.where(owner == me, block_table % rows_local, rows_local - 1)
    return owner, local


def sp_gather_pages(arena, block_table, mesh):
    """:func:`gather_pages` over an sp-sharded arena: each device gathers
    the pages it owns (foreign entries masked to exact zeros) and one
    ``psum`` over the sp axis assembles the full per-sequence view on
    every device. Bit-exact: every page has exactly one owner, so each
    output row is one contribution plus zeros."""

    def gather(local_arena, bt):
        rows_local = local_arena.shape[0]
        me = jax.lax.axis_index(SP_AXIS)
        owner, local_bt = _sp_local_bt(bt, rows_local, me)
        g = gather_pages(local_arena, local_bt)      # (B, P*ps, ...)
        own = jnp.repeat(owner == me, local_arena.shape[1], axis=1)
        own = own.reshape(own.shape + (1,) * (g.ndim - 2))
        g = jnp.where(own, g, jnp.zeros((), g.dtype))
        return jax.lax.psum(g, SP_AXIS)

    return shard_map(gather, mesh=mesh, in_specs=(P(SP_AXIS), P()),
                     out_specs=P())(arena, block_table)


def sp_paged_write(arena, block_table, positions, values, valid, mesh):
    """:func:`paged_write` over an sp-sharded arena: every device sees the
    (replicated) values and scatters exactly the rows whose page it owns;
    everything else — foreign rows and masked rows alike — lands on the
    device's LOCAL trash row. No communication at all: ownership is a
    pure function of the flat row index."""

    def write(local_arena, bt, pos, vals, ok):
        rows_local = local_arena.shape[0]
        me = jax.lax.axis_index(SP_AXIS)
        _, local_bt = _sp_local_bt(bt, rows_local, me)
        return paged_write(local_arena, local_bt, pos, vals, ok,
                           rows_local - 1)

    return shard_map(write, mesh=mesh,
                     in_specs=(P(SP_AXIS), P(), P(), P(), P()),
                     out_specs=P(SP_AXIS))(
        arena, block_table, positions, values, valid)


def _fork_arena(arena, src_pages, dst_pages):
    """Whole-page gather-then-scatter: arena[dst] <- arena[src]."""
    return arena.at[dst_pages].set(arena[src_pages])


@functools.partial(jax.jit, donate_argnums=(0,))
def cow_fork_pages(layers, src_pages, dst_pages):
    """Copy-on-write fork: duplicate ``src_pages`` onto ``dst_pages`` in
    every layer's arenas (K, V and, for int8 arenas, their scales).

    The prefix-sharing allocator (``engine.kv_cache``) hands a new request
    the SAME physical pages another sequence's identical prompt prefix
    already occupies; the first write that would diverge (the frontier
    page's first generated token) must land on a private copy instead.
    This is that fork as one jitted gather-then-scatter over all layers —
    whole pages are copied (stale rows beyond the shared prefix ride
    along harmlessly: the per-row causal mask hides them until the new
    owner overwrites them in position order), and the arenas are DONATED
    like every other page program so a fork never duplicates an arena.

    ``src_pages``/``dst_pages`` are (n,) i32; forks are rare host-decided
    events (at most one frontier page per admitted request), so n is tiny
    and jit re-specialization per n is immaterial.
    """
    out = []
    for layer in layers:
        fields = {"k": _fork_arena(layer.k, src_pages, dst_pages),
                  "v": _fork_arena(layer.v, src_pages, dst_pages)}
        if layer.k_scale is not None:
            fields["k_scale"] = _fork_arena(layer.k_scale, src_pages,
                                            dst_pages)
            fields["v_scale"] = _fork_arena(layer.v_scale, src_pages,
                                            dst_pages)
        out.append(layer.replace(**fields))
    return tuple(out)


# ---------------------------------------------------------------------------
# exact read path (per-row positions)
# ---------------------------------------------------------------------------

def masked_attention(q, k, v, q_positions):
    """``full_attention`` with a PER-ROW causal horizon: row ``b`` of ``q``
    (B, Lq, H, D) sits at global position ``q_positions[b]`` (+ the local
    offset for Lq > 1) and may attend to keys ``kpos <= qpos``. Mirrors
    ``models.transformer.full_attention`` op-for-op (fp32 scores/softmax,
    identical contractions) so the scalar-offset case is bit-identical —
    the serving tick's degenerate-to-generate contract rides on this."""
    d = q.shape[-1]
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k,
        preferred_element_type=jnp.float32) / jnp.sqrt(d).astype(jnp.float32)
    qpos = q_positions[:, None] + jnp.arange(q.shape[1])[None, :]  # (B, Lq)
    kpos = jnp.arange(k.shape[1])                                  # (Lk,)
    mask = kpos[None, None, :] <= qpos[:, :, None]                 # (B,Lq,Lk)
    scores = jnp.where(mask[:, None, :, :], scores, -jnp.inf)
    weights = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", weights.astype(v.dtype), v)


# ---------------------------------------------------------------------------
# int8-KV paged flash kernel (per-row length mask)
# ---------------------------------------------------------------------------
#
# The training-side kernels (ops.flash_attention) mask causally from static
# q/kv offsets — every batch row shares one geometry. A continuous-batching
# decode tick breaks that: each row is ONE query at its OWN position over
# its OWN gathered pages. This variant replaces the causal bounds with a
# per-row live-length input read from SMEM-adjacent stat lanes (same
# (B*H, L, _STAT_LANES) layout as the int8 scales), masking kpos >= length.

def _paged_int8kv_kernel(q_ref, k_ref, v_ref, ks_ref, vs_ref, len_ref,
                         o_ref, acc_ref, m_ref, l_ref, *,
                         bq, bk, nk, scale):
    import jax.experimental.pallas as pl

    ik = pl.program_id(1)
    k_start = ik * bk

    @pl.when(ik == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # dequant on the VMEM tile — the only fp copy of this KV block
    kf = k_ref[0].astype(jnp.float32) * ks_ref[0][:, :1]         # (bk, D)
    vf = v_ref[0].astype(jnp.float32) * vs_ref[0][:, :1]
    s = jax.lax.dot_general(
        q_ref[0].astype(jnp.float32), kf, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale               # (bq, bk)
    live = len_ref[0][:1, :1]                                     # (1, 1)
    kpos = k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    s = jnp.where(kpos < live.astype(jnp.int32), s, NEG_INF)
    m_prev = m_ref[...]
    l_prev = l_ref[...]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - m_new[:, :1]))
    l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc_ref[...] = (acc_ref[...] * alpha[:, :1]
                    + jax.lax.dot_general(
                        p, vf, (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32))
    m_ref[...] = m_new
    l_ref[...] = l_new

    @pl.when(ik == nk - 1)
    def _finalize():
        l_cur = jnp.maximum(l_ref[..., :1], 1e-30)
        o_ref[0] = (acc_ref[...] / l_cur).astype(o_ref.dtype)


@functools.lru_cache(maxsize=None)
def int8kv_paged_flash_attention_fn(block_k: int = 512,
                                    interpret: bool | None = None):
    """Returns ``attn(q, kq, ks, vq, vs, lengths)`` over GATHERED int8 KV
    pages: ``q`` (B, 1, H, D) one query per row, ``kq``/``vq``
    (B, L, H, D) int8 with per-(b, l, h) fp32 scales (the
    ``quantize_kv``/arena layout), ``lengths`` (B,) live tokens per row —
    keys at ``kpos >= length`` are masked, which IS the causal mask when
    ``length = position + 1``. Dequant happens per VMEM tile inside the
    kernel; the fp K/V never exist in HBM. Forward-only (decode).
    ``interpret=None`` auto-selects interpreter mode off-TPU."""

    def attn(q, kq, ks, vq, vs, lengths):
        import jax.experimental.pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        use_interpret = (interpret if interpret is not None
                         else jax.default_backend() != "tpu")
        b, lq, h, d = q.shape
        if lq != 1:
            raise ValueError(f"paged decode kernel is one query per row "
                             f"(got Lq={lq})")
        lk = kq.shape[1]
        _, bk = _blocks(lq, lk, lq, block_k)
        qf = _fold(q)                                  # (B*H, 1, D)
        kf, vf = _fold(kq), _fold(vq)                  # (B*H, L, D) int8
        scale = 1.0 / math.sqrt(d)

        def fold_scale(s):
            s2 = jnp.swapaxes(s, 1, 2).reshape(b * h, lk)
            return jnp.broadcast_to(s2[..., None], (b * h, lk, _STAT_LANES))
        ksf, vsf = fold_scale(ks), fold_scale(vs)
        # per-(b, h) live length in the stat-lane layout: (B*H, 1, LANES)
        lens = jnp.broadcast_to(
            jnp.repeat(lengths.astype(jnp.float32), h)[:, None, None],
            (b * h, 1, _STAT_LANES))
        grid = (b * h, lk // bk)

        out = pl.pallas_call(
            functools.partial(_paged_int8kv_kernel, bq=lq, bk=bk,
                              nk=lk // bk, scale=scale),
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, lq, d), lambda bh, ik: (bh, 0, 0)),
                pl.BlockSpec((1, bk, d), lambda bh, ik: (bh, ik, 0)),
                pl.BlockSpec((1, bk, d), lambda bh, ik: (bh, ik, 0)),
                pl.BlockSpec((1, bk, _STAT_LANES),
                             lambda bh, ik: (bh, ik, 0)),
                pl.BlockSpec((1, bk, _STAT_LANES),
                             lambda bh, ik: (bh, ik, 0)),
                pl.BlockSpec((1, 1, _STAT_LANES),
                             lambda bh, ik: (bh, 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, lq, d), lambda bh, ik: (bh, 0, 0)),
            out_shape=jax.ShapeDtypeStruct(qf.shape, q.dtype),
            scratch_shapes=[
                pltpu.VMEM((lq, d), jnp.float32),           # acc
                pltpu.VMEM((lq, _STAT_LANES), jnp.float32),  # running max
                pltpu.VMEM((lq, _STAT_LANES), jnp.float32),  # running sum
            ],
            interpret=use_interpret,
        )(qf, kf, vf, ksf, vsf, lens)
        return jnp.swapaxes(out.reshape(b, h, lq, d), 1, 2)

    return attn


# ---------------------------------------------------------------------------
# the attend_maybe_cached delegate
# ---------------------------------------------------------------------------

def _quantize_rows(x):
    """(B, L, H, D) -> int8 values + per-(b, l, h) fp32 scales — the
    ``quantize_kv`` arena convention, via ``ops.quant.quantize_int8``."""
    from tpu_dist.ops.quant import quantize_int8

    q, scale = quantize_int8(x, (-1,))
    return q, scale[..., 0].astype(jnp.float32)


def paged_attend(q, k, v, paged: dict, *, prefill: bool, attn_fn, dtype):
    """One layer's paged-cache attention step; the delegate
    ``models.transformer.attend_maybe_cached`` calls when a ``paged`` pack
    is threaded through the model.

    ``paged`` carries the layer's arenas plus the shared context:
    ``{"layer": PagedLayer, "block_tables": (B, max_pages) i32,
    "positions": (B,) i32, "lengths": (B,) i32}`` plus an optional
    ``"valid"`` (B, Lq) bool write mask and an optional ``"sp_mesh"``
    (a static ``jax.sharding.Mesh`` carrying :data:`~tpu_dist.parallel.
    mesh.SP_AXIS`): when set, the arenas are sp-sharded, the block tables
    hold FLAT arena rows, and reads/writes route through
    :func:`sp_gather_pages` / :func:`sp_paged_write`. Prefill (``prefill=True``): the
    queries attend within the prompt through the model's own ``attn_fn``
    (plain causal self-attention — nothing to read back), and the leading
    ``lengths[b]`` K/V rows are written to the pages — unless ``valid``
    narrows them further (prefix caching skips the rows whose pages are
    SHARED with an identical earlier prompt: rewriting them would race
    the frontier fork and the bits are already there). The tick
    (``prefill=False``) writes Lq rows at ``positions[b]..positions[b]+
    Lq-1`` and attends each local query at its own per-row position —
    Lq == 1 is the classic decode tick, Lq > 1 the speculative-decoding
    verify window (``valid`` masks rows past a sequence's token cap to
    the trash page: a draft can overrun the end of a short request, and
    an unmasked overrun would clamp into a LIVE page).

    Returns ``(out, new_layer)`` — the functionally-updated arenas thread
    back out through the model call.
    """
    layer = paged["layer"]
    bt = paged["block_tables"]
    positions = paged["positions"]
    lengths = paged["lengths"]
    sp_mesh = paged.get("sp_mesh")               # None = unsharded arenas
    trash = layer.num_pages                      # the extra page's index

    b, lq = q.shape[0], q.shape[1]
    # unified write geometry: rows land at positions[b]..positions[b]+Lq-1.
    # Monolithic prefill passes positions == 0 (identical indices to the
    # old arange-only form); CHUNKED prefill and the sp prefill shard pass
    # the chunk/shard's global start here, which is what lets one scatter
    # serve whole-prompt, chunk-at-a-time, and per-device-shard writes.
    write_pos = (positions[:, None].astype(jnp.int32)
                 + jnp.arange(lq, dtype=jnp.int32)[None, :])      # (B, Lq)
    if prefill:
        valid = write_pos < lengths[:, None]
    else:
        valid = jnp.ones((b, lq), dtype=bool)
    if paged.get("valid") is not None:
        valid = valid & paged["valid"]

    if sp_mesh is None:
        def write(arena, vals):
            return paged_write(arena, bt, write_pos, vals, valid, trash)

        def read(arena):
            return gather_pages(arena, bt)
    else:
        # sp-sharded arenas: block tables hold FLAT rows, ownership is
        # row // rows_local, and the collectives above do the routing
        def write(arena, vals):
            return sp_paged_write(arena, bt, write_pos, vals, valid,
                                  sp_mesh)

        def read(arena):
            return sp_gather_pages(arena, bt, sp_mesh)

    if layer.quant == "int8":
        kq, ks = _quantize_rows(k)
        vq, vs = _quantize_rows(v)
        new_layer = layer.replace(
            k=write(layer.k, kq), v=write(layer.v, vq),
            k_scale=write(layer.k_scale, ks),
            v_scale=write(layer.v_scale, vs))
    else:
        new_layer = layer.replace(
            k=write(layer.k, k), v=write(layer.v, v))

    if prefill:
        # causal self-attention over the prompt itself — exactly the
        # training contraction, so flash/blockwise plug-ins keep working
        return attn_fn(q, k, v), new_layer

    if layer.quant == "int8" and layer.read == "flash" and lq == 1:
        # the Pallas kernel is one-query-per-row (the decode tick); the
        # Lq > 1 verify window reads through the exact dequant path below
        # — same math, and verify dispatches are 1-in-k ticks by design.
        # Under an sp-sharded pool the gathered view is replicated by the
        # psum, so the kernel composes UNCHANGED — sharding lives entirely
        # in the gather.
        out = int8kv_paged_flash_attention_fn()(
            q, read(new_layer.k), read(new_layer.k_scale),
            read(new_layer.v), read(new_layer.v_scale),
            positions + 1)
        return out.astype(q.dtype), new_layer

    gk = read(new_layer.k)
    gv = read(new_layer.v)
    if layer.quant == "int8":
        gk = (gk.astype(jnp.float32)
              * read(new_layer.k_scale)[..., None]).astype(dtype)
        gv = (gv.astype(jnp.float32)
              * read(new_layer.v_scale)[..., None]).astype(dtype)
    return masked_attention(q, gk, gv, positions), new_layer
