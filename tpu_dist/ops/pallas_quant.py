"""Pallas fused int8 matmul kernel: quantize -> MXU int8 dot -> dequant in
ONE kernel (the kernel-side half of ROADMAP item 4).

The reference math (ops.quant.quant_einsum) builds the int8 path out of
separate XLA ops: quantize lhs, quantize rhs, int32 einsum, scale multiply.
XLA fuses the elementwise pieces it can, but the int8 operand tensors and
the int32 accumulator are real HBM intermediates at matmul boundaries —
an int8 matmul that still pays ~fp8-sized quantize/dequantize round trips
around every dot. This kernel moves the whole ladder into VMEM:

* **activation quantization** — dynamic per-row symmetric int8 (amax over
  the contracting dim, computed on the (bm, K) VMEM tile);
* **weight quantization** — per-output-channel symmetric int8 (amax over
  K on the (K, bn) tile; K is whole per grid cell, so the block-local
  amax IS the exact global per-channel scale);
* **MXU accumulation** — int8 x int8 -> int32 ``dot_general``;
* **dequant** — one fp32 multiply by ``scale_x * scale_w`` broadcast into
  the output tile, cast to the input dtype on the way out.

Nothing int8 or int32 ever touches HBM; the only HBM traffic is the fp
inputs in and the fp output out. The re-quantize per (row-block, col-block)
pair is deliberate recompute — the FlashAttention trade of VMEM math for
HBM bytes.

Backward is the straight-through estimator, exactly like
``quant_einsum``: the custom_vjp's bwd is the vjp of the FP matmul on the
unquantized operands, so swapping the kernel in changes no training
semantics. ``interpret=True`` (auto-selected off-TPU) keeps the kernel
CPU-testable like ops.pallas_adamw; parity against the reference math is
pinned in tests/test_pallas_quant.py.

Entry point: :func:`fused_quant_matmul` — wired behind
``ops.quant.quant_matmul(mode='int8')`` when the fused path is active
(``ops.quant.set_fused_quant`` / ``TPU_DIST_FUSED_QUANT``), so QuantDense,
RingDense and the pipeline head all ride it with zero new plumbing.
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_INT8_MAX = 127.0
_EPS = 1e-8          # all-zero rows/channels: scale floor keeps q = 0
BLOCK_M = 128        # default output tile rows per grid cell
BLOCK_N = 128        # default output tile cols per grid cell

# ---- searchable block sizes (plan IR, round 15) ---------------------------
# The 128x128 tiles above were hard-coded through round 14; the plan
# auto-tuner searches (bm, bn, bk) now. bm/bn pick the output tile; bk
# chunks the int8 MXU dot over the contracting dim INSIDE the kernel —
# the int32 accumulation is exact, and the per-row/per-channel amaxes are
# still taken over the WHOLE (bm, K)/(K, bn) VMEM blocks, so any bk
# produces bit-identical results to bk=0 (whole-K, the default): the knob
# trades MXU issue shape, never numerics. Trace-time static: set before
# building step functions (plan.compile.activate_plan does).
_BLOCKS: Tuple[int, int, int] = (BLOCK_M, BLOCK_N, 0)


def set_quant_blocks(bm: Optional[int] = None, bn: Optional[int] = None,
                     bk: Optional[int] = None) -> None:
    """Set the fused-kernel tile sizes ((None, None, None) restores the
    128x128 whole-K defaults). Legality (bm: multiple of 8; bn: multiple
    of 128; bk: 0 = whole contracting dim, else a multiple of 128) is THE
    shared rule in plan.ir.validate_quant_block — the IR and this setter
    cannot drift."""
    from tpu_dist.plan.ir import validate_quant_block

    global _BLOCKS
    bm = BLOCK_M if bm is None else int(bm)
    bn = BLOCK_N if bn is None else int(bn)
    bk = 0 if bk is None else int(bk)
    validate_quant_block(bm, bn, bk)
    _BLOCKS = (bm, bn, bk)


def quant_blocks() -> Tuple[int, int, int]:
    """The (bm, bn, bk) tile sizes the next trace will use."""
    return _BLOCKS


def _seed_blocks_from_env() -> None:
    # the env seed goes through the SAME validated setter, so a malformed
    # TPU_DIST_QUANT_BLOCKS fails loudly at import, not as a Mosaic
    # tiling abort at first trace
    spec = os.environ.get("TPU_DIST_QUANT_BLOCKS", "")
    if not spec:
        return
    parts = spec.split(",")
    if len(parts) != 3:
        raise ValueError(f"TPU_DIST_QUANT_BLOCKS={spec!r}: expected "
                         "'bm,bn,bk' (bk 0 = whole contracting dim)")
    set_quant_blocks(*(int(v) for v in parts))


_seed_blocks_from_env()


def _fused_quant_kernel(x_ref, w_ref, o_ref, *, bk: int):
    """One (bm, bn) output tile: quantize the (bm, K) activation block and
    the (K, bn) weight block in VMEM, int8 dot with int32 accumulation,
    dequant into the output dtype. K is whole per grid cell, so both
    amaxes are exact; ``bk`` > 0 chunks only the MXU dot over K (int32
    adds are exact — identical output, different issue shape)."""
    x = x_ref[...].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)
    sx = jnp.maximum(jnp.max(jnp.abs(x), axis=1, keepdims=True),
                     _EPS) / _INT8_MAX                      # (bm, 1)
    sw = jnp.maximum(jnp.max(jnp.abs(w), axis=0, keepdims=True),
                     _EPS) / _INT8_MAX                      # (1, bn)
    qx = jnp.clip(jnp.round(x / sx), -_INT8_MAX, _INT8_MAX).astype(jnp.int8)
    qw = jnp.clip(jnp.round(w / sw), -_INT8_MAX, _INT8_MAX).astype(jnp.int8)
    k = qx.shape[1]
    dot = lambda a, b: jax.lax.dot_general(
        a, b, (((1,), (0,)), ((), ())), preferred_element_type=jnp.int32)
    if bk and bk < k:
        acc = dot(qx[:, :bk], qw[:bk, :])
        for lo in range(bk, k, bk):
            hi = min(lo + bk, k)
            acc = acc + dot(qx[:, lo:hi], qw[lo:hi, :])
    else:
        acc = dot(qx, qw)
    o_ref[...] = (acc.astype(jnp.float32) * (sx * sw)).astype(o_ref.dtype)


def _pad_to(x, axis, mult):
    size = x.shape[axis]
    pad = -size % mult
    if not pad:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _fused_quant_matmul_2d(x2, w, interpret: bool):
    """(M, K) x (K, N) with padding to the block grid; padded rows/cols
    quantize against the EPS floor to exact zeros and are sliced away."""
    m, k = x2.shape
    n = w.shape[1]
    blk_m, blk_n, blk_k = _BLOCKS
    # block rows rounded UP to the fp32 sublane multiple (8): a ragged
    # (12, K) block compiles under interpret but violates Mosaic's (8,128)
    # tiling on the TPU — exactly the backend where the fused path is
    # auto-enabled; the padding below absorbs the excess rows. bn rounds
    # up to the LANE multiple (128) for the same reason: with a tuned
    # blk_n > 128, min(blk_n, n) could land on a ragged lane tile (e.g.
    # n=200 under blk_n=256) that interpret accepts and Mosaic aborts on
    bm = min(blk_m, -(-max(m, 1) // 8) * 8)
    bn = min(blk_n, -(-max(n, 128) // 128) * 128)
    xp = _pad_to(x2, 0, bm)
    wp = _pad_to(w, 1, bn)
    grid = (xp.shape[0] // bm, wp.shape[1] // bn)
    out = pl.pallas_call(
        functools.partial(_fused_quant_kernel, bk=blk_k),
        grid=grid,
        in_specs=[pl.BlockSpec((bm, k), lambda i, j: (i, 0)),
                  pl.BlockSpec((k, bn), lambda i, j: (0, j))],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((xp.shape[0], wp.shape[1]), x2.dtype),
        interpret=interpret,
    )(xp, wp)
    return out[:m, :n]


def _pick_interpret(interpret):
    if interpret is not None:
        return interpret
    return jax.default_backend() != "tpu"


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def fused_quant_matmul(x, w, interpret=None):
    """``quant_matmul(x, w, 'int8')`` as one fused Pallas kernel.

    ``x`` (..., K) in the compute dtype, ``w`` (K, N); returns (..., N) in
    ``x.dtype``. Forward is the fused quantize/int8-dot/dequant kernel
    (numerically the reference ``quant_einsum`` dense path: same per-row /
    per-channel scales, same round/clip, int32 accumulation); backward is
    the straight-through estimator — the vjp of the FP matmul on the
    unquantized operands. ``interpret=None`` auto-selects interpreter mode
    off-TPU (the pallas_adamw convention)."""
    return _fused_fwd_impl(x, w, _pick_interpret(interpret))


def _fused_fwd_impl(x, w, interpret: bool):
    lead = x.shape[:-1]
    out2 = _fused_quant_matmul_2d(x.reshape(-1, x.shape[-1]), w, interpret)
    return out2.reshape(*lead, w.shape[1])


def _fused_fwd(x, w, interpret):
    return _fused_fwd_impl(x, w, _pick_interpret(interpret)), (x, w)


def _fused_bwd(interpret, res, g):
    x, w = res
    # STE: gradients of the FP matmul (ops.quant custom_vjp contract)
    _, vjp = jax.vjp(lambda a, b: jnp.dot(a, b), x, w)
    return vjp(g)


fused_quant_matmul.defvjp(_fused_fwd, _fused_bwd)
