"""Pallas fused AdamW update kernel (the LM twin of ops.pallas_sgd).

The reference's apex fused optimizers (reference 4.apex_distributed2.py:
21-22,177) cover Adam too (apex.optimizers.FusedAdam); this is the
TPU-native analog for the decoupled-weight-decay AdamW the LM engine
defaults to. One Pallas pass per leaf reads (p, g, m, v) and writes
(p', m', v') — moment updates, bias correction, eps-stabilized scaling and
decoupled weight decay fused into a single VMEM-resident sweep, instead of
the optax chain's conceptual multi-pass (XLA usually fuses that inside the
jitted step too; the honest value is guaranteed fusion + donated buffers,
and a vehicle for lower-precision moment experiments).

Update rule, exactly optax.adamw (ops.optim.make_optimizer kind='adamw',
eps_root=0), with optional global-norm clipping fused in:
    g  <- g * cs           (cs = clip/norm when norm > clip, else 1)
    m' = b1 m + (1-b1) g
    v' = b2 v + (1-b2) g^2
    mhat = m' / (1 - b1^t);  vhat = v' / (1 - b2^t)
    p' = p - lr (mhat / (sqrt(vhat) + eps) + wd p)

``clip_norm > 0`` is optax.clip_by_global_norm semantics (raw grads,
before the moment statistics) at zero extra passes: the norm is one
squared-sum reduction per leaf and the scale rides the scalar row into
the kernel, where the multiply fuses with the moment update — the
standalone clip pass optax pays disappears.

All math fp32 regardless of param dtype (bf16 params round once at the
final store) — fp32 master-moment semantics. ``t`` is the 1-indexed step.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpu_dist.ops.pallas_sgd import clip_scale

LANE = 128
BLOCK_ROWS = 512    # default: 512x128 fp32 = 256 KiB per VMEM buffer

# searchable block size (plan IR, round 15) — ONE setting shared with
# ops.pallas_sgd so the plan's opt_block_rows drives both fused kernels;
# the authority (setter, env seed, validation) lives there
from tpu_dist.ops import pallas_sgd as _psgd


def set_block_rows(rows=None) -> None:
    """Alias of ops.pallas_sgd.set_block_rows (one shared setting)."""
    _psgd.set_block_rows(rows)


def _adamw_kernel(scal_ref, p_ref, g_ref, m_ref, v_ref, p_out, m_out, v_out):
    lr = scal_ref[0, 0]
    b1 = scal_ref[0, 1]
    b2 = scal_ref[0, 2]
    eps = scal_ref[0, 3]
    wd = scal_ref[0, 4]
    c1 = scal_ref[0, 5]   # 1 - b1^t
    c2 = scal_ref[0, 6]   # 1 - b2^t
    cs = scal_ref[0, 7]   # global-norm clip scale (1.0 = no clip)
    p = p_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32) * cs
    m = b1 * m_ref[:].astype(jnp.float32) + (1.0 - b1) * g
    v = b2 * v_ref[:].astype(jnp.float32) + (1.0 - b2) * g * g
    update = (m / c1) / (jnp.sqrt(v / c2) + eps) + wd * p
    p_out[:] = (p - lr * update).astype(p_out.dtype)
    m_out[:] = m
    v_out[:] = v


def _fused_adamw_2d(p2, g2, m2, v2, scalars, interpret: bool):
    rows = p2.shape[0]
    grid = (pl.cdiv(rows, _psgd.block_rows()),)
    bs = lambda: pl.BlockSpec((_psgd.block_rows(), LANE), lambda i: (i, 0),
                              memory_space=pl.ANY if interpret else pltpu.VMEM)
    return pl.pallas_call(
        _adamw_kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((1, 8), lambda i: (0, 0),
                               memory_space=pltpu.SMEM),
                  bs(), bs(), bs(), bs()],
        out_specs=[bs(), bs(), bs()],
        out_shape=[jax.ShapeDtypeStruct(p2.shape, p2.dtype),
                   jax.ShapeDtypeStruct(m2.shape, jnp.float32),
                   jax.ShapeDtypeStruct(v2.shape, jnp.float32)],
        input_output_aliases={1: 0, 3: 1, 4: 2},  # donate p, m, v
        interpret=interpret,
    )(scalars, p2, g2, m2, v2)


def fused_adamw_leaf(p, g, m, v, scalars, interpret=False):
    """Apply the fused update to one array; returns (p', m', v').

    ``scalars`` is the shared (1, 8) fp32 row [lr, b1, b2, eps, wd,
    1-b1^t, 1-b2^t, clip_scale] — built once per step, not per leaf
    (clip_scale = 1.0 when clipping is off)."""
    shape, size = p.shape, p.size
    rows = -(-size // LANE)
    pad = rows * LANE - size

    def to2d(x, dtype):
        flat = x.astype(dtype).reshape(-1)
        if pad:
            flat = jnp.pad(flat, (0, pad))
        return flat.reshape(rows, LANE)

    p2, m2, v2 = _fused_adamw_2d(to2d(p, p.dtype), to2d(g, jnp.float32),
                                 to2d(m, jnp.float32), to2d(v, jnp.float32),
                                 scalars, interpret)
    unpad = lambda x2, dt: x2.reshape(-1)[:size].reshape(shape).astype(dt)
    return unpad(p2, p.dtype), unpad(m2, jnp.float32), unpad(v2, jnp.float32)


class FusedAdamWState(NamedTuple):
    mu: Any   # first moments, fp32
    nu: Any   # second moments, fp32


class FusedAdamW:
    """Fused-kernel AdamW with the engine-facing apply() protocol
    (tpu_dist.engine.steps._apply_update dispatches on hasattr(tx, 'apply'),
    so this slots into the image AND LM jit step builders)."""

    def __init__(self, schedule: Callable, b1: float = 0.9, b2: float = 0.95,
                 eps: float = 1e-8, weight_decay: float = 0.1,
                 clip_norm: float = 0.0, interpret: bool = False):
        self.schedule = schedule
        self.b1, self.b2, self.eps = b1, b2, eps
        self.weight_decay = weight_decay
        self.clip_norm = clip_norm
        self.interpret = interpret

    def init(self, params) -> FusedAdamWState:
        z = lambda p: jnp.zeros(p.shape, jnp.float32)
        return FusedAdamWState(mu=jax.tree.map(z, params),
                               nu=jax.tree.map(z, params))

    def apply(self, params, grads, state: FusedAdamWState, step):
        t = (step + 1).astype(jnp.float32)  # 1-indexed like optax
        lr = jnp.asarray(self.schedule(step), jnp.float32)
        scalars = jnp.stack([
            lr, jnp.float32(self.b1), jnp.float32(self.b2),
            jnp.float32(self.eps), jnp.float32(self.weight_decay),
            1.0 - jnp.float32(self.b1) ** t,
            1.0 - jnp.float32(self.b2) ** t,
            clip_scale(grads, self.clip_norm)]).reshape(1, 8)
        out = jax.tree.map(partial(self._leaf, scalars),
                           params, grads, state.mu, state.nu)
        pick = lambda i: jax.tree.map(
            lambda o: o[i], out, is_leaf=lambda x: isinstance(x, tuple))
        return pick(0), FusedAdamWState(mu=pick(1), nu=pick(2))

    def _leaf(self, scalars, p, g, m, v):
        return fused_adamw_leaf(p, g, m, v, scalars,
                                interpret=self.interpret)
