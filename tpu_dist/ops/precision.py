"""Mixed-precision policy layer (reference components C11/C12).

The reference's precision stack is apex AMP: ``amp.initialize(model,
optimizer)`` + dynamic loss scaling around backward
(reference 4.apex_distributed2.py:177,289-290) and horovod's fp16-compressed
gradient allreduce (reference 5.horovod_distributed.py:123-125).

TPU-first mapping (SURVEY.md §2b apex row):

* **bf16 compute** is the native TPU mixed precision — same exponent range as
  fp32, so *no loss scaling is required*. ``Policy("bf16")`` runs matmuls/convs
  in bf16 on the MXU with fp32 master params and fp32 batch-norm statistics
  (the apex O1-ish default).
* ``Policy("bf16_params")`` additionally keeps params in bf16 (apex O2-ish;
  halves HBM traffic for weights).
* Optional **dynamic loss scaling** is provided anyway for semantic parity
  with apex's fp16 path (and for numerics experiments): scale up the loss,
  unscale grads, skip the step and halve the scale on non-finite grads, double
  every ``growth_interval`` good steps — the apex algorithm, as a pure pytree
  so it lives inside the jitted step (no Python control flow).
* fp16-compressed allreduce maps to bf16 grad compression in
  tpu_dist.parallel.collectives.compress_grads.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class Policy:
    """Dtype policy: where params live, where compute happens."""

    name: str = "fp32"

    @property
    def compute_dtype(self):
        return jnp.bfloat16 if self.name in ("bf16", "bf16_params") else jnp.float32

    @property
    def param_dtype(self):
        return jnp.bfloat16 if self.name == "bf16_params" else jnp.float32

    def cast_params_for_storage(self, params):
        return jax.tree.map(
            lambda p: p.astype(self.param_dtype)
            if jnp.issubdtype(p.dtype, jnp.floating) else p, params)


def make_policy(name: str) -> Policy:
    if name not in ("fp32", "bf16", "bf16_params"):
        raise ValueError(f"unknown precision {name!r} (fp32|bf16|bf16_params)")
    return Policy(name)


class LossScaleState(NamedTuple):
    """Dynamic loss-scale state (apex amp.scale_loss equivalent)."""

    scale: jax.Array          # current multiplicative scale
    good_steps: jax.Array     # consecutive finite-grad steps

    @staticmethod
    def create(initial: float = 2.0 ** 15):
        return LossScaleState(jnp.float32(initial), jnp.int32(0))


def scale_loss(loss: jax.Array, state: LossScaleState | None) -> jax.Array:
    return loss if state is None else loss * state.scale


def unscale_and_update(grads: Any, state: LossScaleState | None,
                       growth_interval: int = 2000,
                       ) -> Tuple[Any, LossScaleState | None, jax.Array]:
    """Unscale grads; decide whether the step is safe (all-finite).

    Returns (unscaled_grads, new_state, grads_finite). With ``state=None``
    (bf16/fp32 path) grads pass through and grads_finite is True — the step is
    unconditional, exactly like the reference's non-apex variants.
    """
    if state is None:
        return grads, None, jnp.bool_(True)
    inv = 1.0 / state.scale
    grads = jax.tree.map(lambda g: g * inv, grads)
    finite = jnp.all(jnp.stack([jnp.all(jnp.isfinite(g))
                                for g in jax.tree.leaves(grads)]))
    new_good = jnp.where(finite, state.good_steps + 1, 0)
    grow = new_good >= growth_interval
    new_scale = jnp.where(
        finite,
        jnp.where(grow, state.scale * 2.0, state.scale),
        jnp.maximum(state.scale * 0.5, 1.0))
    new_good = jnp.where(grow, 0, new_good)
    return grads, LossScaleState(new_scale, new_good), finite
