"""Optimizer + LR schedule (reference components C19 and the SGD setup).

Reference recipe: SGD momentum 0.9, weight decay 1e-4, lr 0.1 stepped x0.1
every 30 epochs by mutating param_groups (reference 1.dataparallel.py:114-116,
332-336); horovod scales base lr by world size (reference
5.2.horovod_pytorch_mnist.py:159-171) and supports a gradient predivide factor
(reference 5.2...py:185).

TPU-first: the schedule is a pure function of the step counter evaluated
*inside* the jitted update (no host mutation of optimizer state), built on
optax. Weight decay matches torch SGD semantics exactly: wd*param is added to
the gradient *before* momentum (optax.add_decayed_weights ordering).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp
import optax


def step_decay_schedule(base_lr: float, steps_per_epoch: int,
                        step_epochs: int = 30, factor: float = 0.1
                        ) -> Callable:
    """lr = base * factor^(epoch // step_epochs)  (reference 1.dataparallel.py:332-336)."""
    def schedule(step):
        epoch = step // max(steps_per_epoch, 1)
        return base_lr * factor ** (epoch // step_epochs)
    return schedule


def make_optimizer(lr: float, momentum: float = 0.9, weight_decay: float = 1e-4,
                   steps_per_epoch: int = 1, lr_step_epochs: int = 30,
                   schedule: Optional[Callable] = None
                   ) -> optax.GradientTransformation:
    """torch.optim.SGD(momentum, weight_decay)-equivalent with step-decay LR.

    Horovod's gradient_predivide_factor lives in the explicit-psum step
    (tpu_dist.engine.steps.make_shard_map_train_step), matching horovod's
    placement around the allreduce — NOT here, so it cannot double-apply.
    """
    sched = schedule or step_decay_schedule(lr, steps_per_epoch, lr_step_epochs)
    chain = []
    if weight_decay:
        chain.append(optax.add_decayed_weights(weight_decay))
    # torch SGD momentum: buf = mu*buf + grad; update = -lr*buf
    chain.append(optax.trace(decay=momentum, nesterov=False))
    chain.append(optax.scale_by_learning_rate(sched))
    return optax.chain(*chain)


def current_lr(schedule: Callable, step) -> jnp.ndarray:
    return jnp.asarray(schedule(step))
