"""Optimizer + LR schedule (reference components C19 and the SGD setup).

Reference recipe: SGD momentum 0.9, weight decay 1e-4, lr 0.1 stepped x0.1
every 30 epochs by mutating param_groups (reference 1.dataparallel.py:114-116,
332-336); horovod scales base lr by world size (reference
5.2.horovod_pytorch_mnist.py:159-171) and supports a gradient predivide factor
(reference 5.2...py:185).

TPU-first: the schedule is a pure function of the step counter evaluated
*inside* the jitted update (no host mutation of optimizer state), built on
optax. Weight decay matches torch SGD semantics exactly: wd*param is added to
the gradient *before* momentum (optax.add_decayed_weights ordering).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp
import optax


def step_decay_schedule(base_lr: float, steps_per_epoch: int,
                        step_epochs: int = 30, factor: float = 0.1
                        ) -> Callable:
    """lr = base * factor^(epoch // step_epochs)  (reference 1.dataparallel.py:332-336)."""
    def schedule(step):
        epoch = step // max(steps_per_epoch, 1)
        return base_lr * factor ** (epoch // step_epochs)
    return schedule


def lm_lr_schedule(base_lr: float, kind: str = "constant",
                   warmup_steps: int = 0, total_steps: int = 0,
                   steps_per_epoch: int = 1, step_epochs: int = 30,
                   factor: float = 0.1, min_frac: float = 0.0) -> Callable:
    """LM learning-rate schedule: linear warmup into constant | cosine |
    step decay (VERDICT r3 #2 — the LM engine had no schedule at all).

    A pure function of the optimizer step, evaluated INSIDE the jitted
    update like :func:`step_decay_schedule`; resume-safe because the step
    count lives in the checkpointed optax state, so the trajectory
    continues exactly across a --resume boundary.

    * warmup: lr ramps linearly from base/warmup_steps to base over the
      first ``warmup_steps`` updates (step 0 applies a nonzero lr).
    * constant: base thereafter.
    * cosine: half-cosine from base to ``min_frac * base`` over
      ``total_steps - warmup_steps`` updates, flat at the floor after.
    * step: the reference's C19 decay — x ``factor`` every ``step_epochs``
      epochs of ``steps_per_epoch`` (reference 1.dataparallel.py:332-336).
    """
    if kind not in ("constant", "cosine", "step"):
        raise ValueError(f"unknown lr schedule {kind!r} "
                         "(constant|cosine|step)")
    if kind == "cosine" and total_steps <= warmup_steps:
        raise ValueError(f"cosine needs total_steps ({total_steps}) > "
                         f"warmup_steps ({warmup_steps})")

    def schedule(step):
        s = jnp.asarray(step, jnp.float32)
        if kind == "cosine":
            horizon = jnp.float32(max(total_steps - warmup_steps, 1))
            t = jnp.clip((s - warmup_steps) / horizon, 0.0, 1.0)
            lr = base_lr * (min_frac
                            + (1.0 - min_frac) * 0.5 * (1.0 + jnp.cos(
                                jnp.float32(jnp.pi) * t)))
        elif kind == "step":
            epoch = jnp.floor(s / max(steps_per_epoch, 1))
            lr = base_lr * jnp.power(jnp.float32(factor),
                                     jnp.floor(epoch / step_epochs))
        else:
            lr = jnp.float32(base_lr)
        if warmup_steps:
            warm = base_lr * (s + 1.0) / jnp.float32(warmup_steps)
            lr = jnp.where(s < warmup_steps, warm, lr)
        return lr

    return schedule


def make_optimizer(lr: float, momentum: float = 0.9, weight_decay: float = 1e-4,
                   steps_per_epoch: int = 1, lr_step_epochs: int = 30,
                   schedule: Optional[Callable] = None, kind: str = "sgd",
                   b1: float = 0.9, b2: float = 0.95, eps: float = 1e-8,
                   grad_clip: float = 0.0
                   ) -> optax.GradientTransformation:
    """torch.optim.SGD(momentum, weight_decay)-equivalent with step-decay LR,
    or decoupled AdamW (``kind='adamw'``) — the transformer-family default
    the reference (image-only, SGD throughout) never needed. b2 defaults to
    0.95, the large-LM convention, not torch's 0.999.

    Horovod's gradient_predivide_factor lives in the explicit-psum step
    (tpu_dist.engine.steps.make_shard_map_train_step), matching horovod's
    placement around the allreduce — NOT here, so it cannot double-apply.
    """
    sched = schedule or step_decay_schedule(lr, steps_per_epoch, lr_step_epochs)
    # grad_clip > 0: clip the RAW gradient by global norm BEFORE any
    # momentum/adam statistics (torch.nn.utils.clip_grad_norm_ placement)
    clip = ([optax.clip_by_global_norm(grad_clip)] if grad_clip > 0 else [])
    if kind == "adamw":
        # decoupled wd (AdamW): applied AFTER the adam scaling, with lr.
        # Unwrapped when no clip so the opt_state pytree structure (and
        # therefore existing adamw checkpoints) is unchanged at the default.
        adamw = optax.adamw(learning_rate=sched, b1=b1, b2=b2, eps=eps,
                            weight_decay=weight_decay)
        return optax.chain(*clip, adamw) if clip else adamw
    if kind != "sgd":
        raise ValueError(f"unknown optimizer kind {kind!r} (sgd|adamw)")
    chain = list(clip)
    if weight_decay:
        chain.append(optax.add_decayed_weights(weight_decay))
    # torch SGD momentum: buf = mu*buf + grad; update = -lr*buf
    chain.append(optax.trace(decay=momentum, nesterov=False))
    chain.append(optax.scale_by_learning_rate(sched))
    return optax.chain(*chain)


def current_lr(schedule: Callable, step) -> jnp.ndarray:
    return jnp.asarray(schedule(step))
