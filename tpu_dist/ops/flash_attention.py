"""Memory-efficient attention: blockwise (online softmax) + Pallas flash.

The reference has no attention at all (SURVEY.md §2c); tpu_dist's LM family
takes a pluggable ``attn_fn`` (tpu_dist.models.transformer), so these drop
into the SAME weights as full attention:

* :func:`blockwise_attention_fn` — pure-JAX flash-attention math: a
  ``lax.scan`` over KV blocks with a running (max, sum, acc) online softmax.
  Never materializes the (B,H,L,L) score matrix — peak activation memory is
  O(L * block) — and autodiff/remat work out of the box. Runs on any
  backend; this is the long-context workhorse and the ground truth for the
  kernel below.
* :func:`flash_attention_fn` — Pallas TPU FlashAttention-2: forward grid
  (batch*head, q_blocks, kv_blocks) with VMEM scratch accumulators carried
  across the innermost KV dimension (scores never touch HBM; O(bq*bk)
  working set at ANY sequence length), causal above-diagonal blocks skipped,
  fp32 online math, per-row logsumexp written out. Backward is two Pallas
  kernels (dq; dk+dv) that re-derive probabilities from the stashed
  logsumexp — score recompute only, not a second full forward.

Both are numerically validated against full attention (tests/test_flash.py)
and compose with the causal offsets ring attention uses.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

NEG_INF = -1e30  # avoids -inf - -inf = nan in the online max updates


def _causal_mask(scores, q_pos, k_pos):
    return jnp.where(k_pos[None, :] <= q_pos[:, None], scores, NEG_INF)


@functools.lru_cache(maxsize=None)
def blockwise_attention_fn(block_size: int = 512):
    """Returns attn(q, k, v, causal=True, q_offset=0, kv_offset=0).

    Shapes follow the model convention: (B, L, H, D). fp32 softmax state
    regardless of input dtype, like tpu_dist.models.transformer.full_attention.
    Memoized per config so identical-hyperparameter models (which carry
    this closure as a hash field) compare equal — see ring_attention_fn.
    """

    def attn(q, k, v, *, causal: bool = True, q_offset=0, kv_offset=0):
        b, lq, h, d = q.shape
        lk = k.shape[1]
        # same fit rule as the flash kernels (_blocks): clamp to the kv
        # length, shrink to gcd when it doesn't divide (lk=1536 with
        # block 1024 -> 512), so the shared attn_block default works here
        blk = min(block_size, lk)
        if lk % blk:
            blk = math.gcd(blk, lk)
        if blk < 1:
            raise ValueError(f"kv length {lk} has no usable block "
                             f"<= {block_size}")
        nk = lk // blk
        scale = 1.0 / math.sqrt(d)

        # (B, L, H, D) -> (B, H, L, D) once; scan over KV blocks
        qh = jnp.swapaxes(q, 1, 2).astype(jnp.float32) * scale
        kh = jnp.swapaxes(k, 1, 2).reshape(b, h, nk, blk, d)
        vh = jnp.swapaxes(v, 1, 2).reshape(b, h, nk, blk, d)
        kh = jnp.moveaxis(kh, 2, 0)  # (nk, B, H, blk, D)
        vh = jnp.moveaxis(vh, 2, 0)

        q_pos = q_offset + jnp.arange(lq)

        def body(carry, blk_in):
            acc, m, l, i = carry
            kb, vb = blk_in
            s = jnp.einsum("bhqd,bhkd->bhqk", qh, kb.astype(jnp.float32))
            if causal:
                k_pos = kv_offset + i * blk + jnp.arange(blk)
                s = jnp.where(k_pos[None, None, None, :]
                              <= q_pos[None, None, :, None], s, NEG_INF)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1))
            alpha = jnp.exp(m - m_new)
            # masked scores must contribute ZERO probability even when the
            # whole row is masked (m_new == NEG_INF -> exp(s - m_new) would
            # be 1 for every masked key, yielding the unmasked mean of V
            # instead of zeros — reachable via q_offset/kv_offset composition)
            p = jnp.where(s <= NEG_INF / 2, 0.0,
                          jnp.exp(s - m_new[..., None]))
            l = l * alpha + jnp.sum(p, axis=-1)
            acc = acc * alpha[..., None] + jnp.einsum(
                "bhqk,bhkd->bhqd", p, vb.astype(jnp.float32))
            return (acc, m_new, l, i + 1), None

        acc0 = jnp.zeros((b, h, lq, d), jnp.float32)
        m0 = jnp.full((b, h, lq), NEG_INF, jnp.float32)
        l0 = jnp.zeros((b, h, lq), jnp.float32)
        (acc, _, l, _), _ = jax.lax.scan(
            body, (acc0, m0, l0, jnp.int32(0)), (kh, vh))
        out = acc / jnp.maximum(l, 1e-30)[..., None]
        return jnp.swapaxes(out, 1, 2).astype(v.dtype)

    return attn


# ---------------------------------------------------------------------------
# Pallas flash attention (FlashAttention-2 schedule, forward + backward)
# ---------------------------------------------------------------------------
#
# Forward: grid (B*H, q_blocks, kv_blocks) with the KV dimension INNERMOST,
# so the VMEM scratch accumulators (acc, running max m, running sum l) carry
# across KV steps of one q block — peak memory is O(bq * bk) regardless of
# sequence length (no whole-K/V fetch, unlike the round-2 kernel). Causal
# blocks strictly above the diagonal are skipped (pl.when), saving ~half the
# FLOPs. The (bq,) logsumexp per row is written out for the backward.
#
# Backward: two Pallas kernels re-derive p = exp(s - lse) from the stashed
# statistics (FLASH-style recompute of SCORES only, never a second full
# forward): dq accumulates over KV blocks; dk/dv accumulate over q blocks.
# delta = rowsum(o * dout) is a cheap fused elementwise pass outside Pallas.

_LANES = 128      # TPU vector lane count: scratch row-stats are (bq, _LANES)
_STAT_LANES = 8   # lse/delta HBM layout: (B*H, L, 8) — Mosaic block tiling
                  # wants the last dim either 128-divisible or equal to the
                  # array's, so an 8-wide stat lane keeps blocks legal while
                  # costing 8 (not 128) floats per row


def _causal_bounds(causal, q_start, k_start, bq, bk):
    """(skip_block, needs_mask) for one (q block, kv block) pair."""
    if not causal:
        return False, False
    skip = k_start > q_start + bq - 1          # entirely above the diagonal
    needs_mask = k_start + bk - 1 > q_start    # straddles the diagonal
    return skip, needs_mask


def _fa_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                   acc_ref, m_ref, l_ref, *,
                   bq, bk, nk, scale, causal, q_offset, kv_offset):
    import jax.experimental.pallas as pl

    iq, ik = pl.program_id(1), pl.program_id(2)
    q_start = q_offset + iq * bq
    k_start = kv_offset + ik * bk

    @pl.when(ik == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    skip, needs_mask = _causal_bounds(causal, q_start, k_start, bq, bk)

    @pl.when(jnp.logical_not(skip))
    def _step():
        # inputs stay in their storage dtype (bf16 at real scales): the MXU
        # takes bf16 x bf16 -> fp32 natively; upcasting first would force
        # the ~4x-slower fp32 matmul path
        s = jax.lax.dot_general(
            q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale      # (bq, bk) f32
        if causal:
            qpos = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            kpos = k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            mask = kpos <= qpos
            s = jnp.where(jnp.logical_or(jnp.logical_not(needs_mask), mask),
                          s, NEG_INF)
        m_prev = m_ref[...]                     # (bq, LANES), lanes equal
        l_prev = l_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        # masked scores contribute ZERO even when the whole row is masked
        # (m_new == NEG_INF would make exp(s - m_new) = 1 otherwise)
        p = jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - m_new[:, :1]))
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = (acc_ref[...] * alpha[:, :1]
                        + jax.lax.dot_general(
                            p.astype(v_ref.dtype), v_ref[0],
                            (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32))
        m_ref[...] = m_new
        l_ref[...] = l_new

    # finalize ONCE, at this q block's last live KV step (computable from the
    # causal geometry; nk-1 when not causal or when the diagonal lies beyond
    # the kv range) — not a per-step write-through
    if causal:
        last_live = jnp.clip((q_start + bq - 1 - kv_offset) // bk, 0, nk - 1)
    else:
        last_live = nk - 1

    @pl.when(ik == last_live)
    def _finalize():
        l_cur = jnp.maximum(l_ref[..., :1], 1e-30)
        o_ref[0] = (acc_ref[...] / l_cur).astype(o_ref.dtype)
        lse_ref[0] = jnp.broadcast_to(m_ref[..., :1] + jnp.log(l_cur),
                                      (bq, _STAT_LANES))


def _fa_bwd_dq_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                      dq_ref, dq_acc, *,
                      bq, bk, nk, scale, causal, q_offset, kv_offset):
    import jax.experimental.pallas as pl

    iq, ik = pl.program_id(1), pl.program_id(2)
    q_start = q_offset + iq * bq
    k_start = kv_offset + ik * bk

    @pl.when(ik == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    skip, needs_mask = _causal_bounds(causal, q_start, k_start, bq, bk)

    @pl.when(jnp.logical_not(skip))
    def _step():
        s = jax.lax.dot_general(
            q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            qpos = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            kpos = k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(jnp.logical_or(jnp.logical_not(needs_mask),
                                         kpos <= qpos), s, NEG_INF)
        lse = lse_ref[0][:, :1]                 # (bq, 1)
        p = jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - lse))
        dp = jax.lax.dot_general(
            g_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # (bq, bk)
        ds = p * (dp - delta_ref[0][:, :1]) * scale
        dq_acc[...] += jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        last_live = jnp.clip((q_start + bq - 1 - kv_offset) // bk, 0, nk - 1)
    else:
        last_live = nk - 1

    @pl.when(ik == last_live)
    def _finalize():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _fa_bwd_dkv_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                       dk_ref, dv_ref, dk_acc, dv_acc, *,
                       bq, bk, nq, scale, causal, q_offset, kv_offset):
    import jax.experimental.pallas as pl

    ik, iq = pl.program_id(1), pl.program_id(2)   # q blocks INNERMOST here
    q_start = q_offset + iq * bq
    k_start = kv_offset + ik * bk

    @pl.when(iq == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    skip, needs_mask = _causal_bounds(causal, q_start, k_start, bq, bk)

    @pl.when(jnp.logical_not(skip))
    def _step():
        s = jax.lax.dot_general(
            q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            qpos = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            kpos = k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(jnp.logical_or(jnp.logical_not(needs_mask),
                                         kpos <= qpos), s, NEG_INF)
        lse = lse_ref[0][:, :1]
        p = jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - lse))
        dv_acc[...] += jax.lax.dot_general(
            p.astype(g_ref.dtype), g_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # (bk, D)
        dp = jax.lax.dot_general(
            g_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0][:, :1]) * scale         # (bq, bk)
        dk_acc[...] += jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    # every causal kv block's LAST live q block is the final one (later q
    # rows attend to all earlier kv), so finalize exactly once at iq == nq-1
    @pl.when(iq == nq - 1)
    def _finalize():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _fold(x):
    """(B, L, H, D) -> (B*H, L, D)."""
    b, l, h, d = x.shape
    return jnp.swapaxes(x, 1, 2).reshape(b * h, l, d)


def _blocks(lq, lk, block_q, block_k):
    """Largest usable block sizes <= the requested ones: when the requested
    block doesn't divide the sequence, shrink to gcd so every length that is
    a multiple of a small power of two still works (e.g. lq=768 with
    block_q=512 -> 256)."""
    def fit(block, length):
        b = min(block, length)
        if length % b:
            b = math.gcd(b, length)
        if b < 8 and b != length:  # Mosaic sublane minimum
            raise ValueError(
                f"sequence length {length} has no usable block <= {block} "
                "(needs a divisor that is a multiple of 8)")
        return b
    return fit(block_q, lq), fit(block_k, lk)


def _fa_forward(q, k, v, causal, q_offset, kv_offset, block_q, block_k,
                interpret):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, lq, h, d = q.shape
    lk = k.shape[1]
    bq, bk = _blocks(lq, lk, block_q, block_k)
    qf, kf, vf = _fold(q), _fold(k), _fold(v)
    scale = 1.0 / math.sqrt(d)
    grid = (b * h, lq // bq, lk // bk)          # kv INNERMOST: scratch carries

    out, lse = pl.pallas_call(
        functools.partial(_fa_fwd_kernel, bq=bq, bk=bk, nk=lk // bk,
                          scale=scale, causal=causal,
                          q_offset=q_offset, kv_offset=kv_offset),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, iq, ik: (bh, iq, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, iq, ik: (bh, ik, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, iq, ik: (bh, ik, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, iq, ik: (bh, iq, 0)),
            pl.BlockSpec((1, bq, _STAT_LANES),
                         lambda bh, iq, ik: (bh, iq, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(qf.shape, v.dtype),
            jax.ShapeDtypeStruct((b * h, lq, _STAT_LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),        # acc
            pltpu.VMEM((bq, _LANES), jnp.float32),   # running max
            pltpu.VMEM((bq, _LANES), jnp.float32),   # running sum
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return jnp.swapaxes(out.reshape(b, h, lq, d), 1, 2), lse


def _fa_backward(q, k, v, out, lse, g, causal, q_offset, kv_offset,
                 block_q, block_k, interpret):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, lq, h, d = q.shape
    lk = k.shape[1]
    bq, bk = _blocks(lq, lk, block_q, block_k)
    scale = 1.0 / math.sqrt(d)
    qf, kf, vf, gf = _fold(q), _fold(k), _fold(v), _fold(g)
    # delta_i = sum_d o_i * do_i — the softmax-jacobian row term; a single
    # fused elementwise+reduce, no reason to put it in the kernel. Stored
    # in the same (B*H, Lq, STAT_LANES) layout as lse (Mosaic block tiling).
    delta = jnp.sum(_fold(out).astype(jnp.float32) * gf.astype(jnp.float32),
                    axis=-1)                              # (B*H, Lq)
    delta = jnp.broadcast_to(delta[..., None],
                             (*delta.shape, _STAT_LANES))

    q_spec = pl.BlockSpec((1, bq, d), lambda bh, iq, ik: (bh, iq, 0))
    k_spec = pl.BlockSpec((1, bk, d), lambda bh, iq, ik: (bh, ik, 0))
    row_spec = pl.BlockSpec((1, bq, _STAT_LANES),
                            lambda bh, iq, ik: (bh, iq, 0))

    dq = pl.pallas_call(
        functools.partial(_fa_bwd_dq_kernel, bq=bq, bk=bk, nk=lk // bk,
                          scale=scale, causal=causal,
                          q_offset=q_offset, kv_offset=kv_offset),
        grid=(b * h, lq // bq, lk // bk),       # kv innermost: dq carries
        in_specs=[q_spec, k_spec, k_spec, q_spec, row_spec, row_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct(qf.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf, gf, lse, delta)

    # second pass: kv block fixed, q blocks innermost (dk/dv carry)
    q_spec2 = pl.BlockSpec((1, bq, d), lambda bh, ik, iq: (bh, iq, 0))
    k_spec2 = pl.BlockSpec((1, bk, d), lambda bh, ik, iq: (bh, ik, 0))
    row_spec2 = pl.BlockSpec((1, bq, _STAT_LANES),
                             lambda bh, ik, iq: (bh, iq, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_fa_bwd_dkv_kernel, bq=bq, bk=bk, nq=lq // bq,
                          scale=scale, causal=causal,
                          q_offset=q_offset, kv_offset=kv_offset),
        grid=(b * h, lk // bk, lq // bq),
        in_specs=[q_spec2, k_spec2, k_spec2, q_spec2, row_spec2, row_spec2],
        out_specs=[k_spec2, k_spec2],
        out_shape=[jax.ShapeDtypeStruct(kf.shape, k.dtype),
                   jax.ShapeDtypeStruct(vf.shape, v.dtype)],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf, gf, lse, delta)

    unfold = lambda x, l: jnp.swapaxes(x.reshape(b, h, l, d), 1, 2)
    return unfold(dq, lq), unfold(dk, lk), unfold(dv, lk)


# ---------------------------------------------------------------------------
# int8-KV flash attention (pre-quantized keys/values, decode-path variant)
# ---------------------------------------------------------------------------
#
# The decode tick is KV-bandwidth-bound once contexts grow: every generated
# token re-reads the whole cache. Storing K/V as int8 with one fp32 scale
# per (batch, position, head) row halves that HBM traffic; this kernel
# consumes the quantized layout DIRECTLY — the dequant multiply happens on
# the (bk, D) VMEM tile inside the kernel, so the fp16/fp32 K/V never exist
# in HBM at all. Forward-only by design (decode never differentiates);
# training keeps the fp kernels above.

def quantize_kv(k, v):
    """Per-row symmetric int8 quantization of a KV pair in model layout.

    ``k``/``v`` are (B, L, H, D); returns ``(k_q, k_scale, v_q, v_scale)``
    with int8 values and one fp32 scale per (b, l, h) row (amax over D) —
    the layout :func:`int8kv_flash_attention_fn` consumes, and the HBM
    format an int8 KV cache would hold. Rows are quantized by
    ``ops.quant.quantize_int8`` itself (not a copy of its math), so the
    round/clip/EPS convention can never drift from the training path's."""
    from tpu_dist.ops.quant import quantize_int8

    def one(x):
        q, scale = quantize_int8(x, (-1,))
        return q, scale[..., 0].astype(jnp.float32)
    kq, ks = one(k)
    vq, vs = one(v)
    return kq, ks, vq, vs


def _fa_fwd_int8kv_kernel(q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref,
                          acc_ref, m_ref, l_ref, *,
                          bq, bk, nk, scale, causal, q_offset, kv_offset):
    import jax.experimental.pallas as pl

    iq, ik = pl.program_id(1), pl.program_id(2)
    q_start = q_offset + iq * bq
    k_start = kv_offset + ik * bk

    @pl.when(ik == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    skip, needs_mask = _causal_bounds(causal, q_start, k_start, bq, bk)

    @pl.when(jnp.logical_not(skip))
    def _step():
        # dequant on the VMEM tile: int8 rows x per-row fp32 scale — the
        # only fp copy of this KV block that ever exists
        kf = k_ref[0].astype(jnp.float32) * ks_ref[0][:, :1]     # (bk, D)
        vf = v_ref[0].astype(jnp.float32) * vs_ref[0][:, :1]
        s = jax.lax.dot_general(
            q_ref[0].astype(jnp.float32), kf, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale          # (bq, bk)
        if causal:
            qpos = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            kpos = k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(jnp.logical_or(jnp.logical_not(needs_mask),
                                         kpos <= qpos), s, NEG_INF)
        m_prev = m_ref[...]
        l_prev = l_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - m_new[:, :1]))
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = (acc_ref[...] * alpha[:, :1]
                        + jax.lax.dot_general(
                            p, vf, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32))
        m_ref[...] = m_new
        l_ref[...] = l_new

    if causal:
        last_live = jnp.clip((q_start + bq - 1 - kv_offset) // bk, 0, nk - 1)
    else:
        last_live = nk - 1

    @pl.when(ik == last_live)
    def _finalize():
        l_cur = jnp.maximum(l_ref[..., :1], 1e-30)
        o_ref[0] = (acc_ref[...] / l_cur).astype(o_ref.dtype)


@functools.lru_cache(maxsize=None)
def int8kv_flash_attention_fn(block_q: int = 1024, block_k: int | None = None,
                              interpret: bool | None = None):
    """Returns ``attn(q, kv, causal=True, q_offset=0, kv_offset=0)`` over a
    PRE-QUANTIZED KV pack ``kv = quantize_kv(k, v)`` (int8 values + per-row
    fp32 scales): the decode-path flash variant — K/V stay int8 in HBM,
    halving the cache traffic the autoregressive tick is bound by, and the
    dequant happens per VMEM tile inside the kernel. Forward-only (decode
    never differentiates; the bwd kernels above serve training).
    ``interpret=None`` auto-selects interpreter mode off-TPU."""
    if block_k is None:
        block_k = 1024

    def attn(q, kv, *, causal: bool = True, q_offset=0, kv_offset=0):
        import jax.experimental.pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        kq, ks, vq, vs = kv
        use_interpret = (interpret if interpret is not None
                         else jax.default_backend() != "tpu")
        b, lq, h, d = q.shape
        lk = kq.shape[1]
        bq, bk = _blocks(lq, lk, block_q, block_k)
        qf = _fold(q)
        kf, vf = _fold(kq), _fold(vq)                # (B*H, L, D) int8
        # scales to the lse/delta stat layout: (B*H, L, _STAT_LANES)
        def fold_scale(s):
            s2 = jnp.swapaxes(s, 1, 2).reshape(b * h, lk)
            return jnp.broadcast_to(s2[..., None], (b * h, lk, _STAT_LANES))
        ksf, vsf = fold_scale(ks), fold_scale(vs)
        scale = 1.0 / math.sqrt(d)
        grid = (b * h, lq // bq, lk // bk)

        out = pl.pallas_call(
            functools.partial(_fa_fwd_int8kv_kernel, bq=bq, bk=bk,
                              nk=lk // bk, scale=scale, causal=causal,
                              q_offset=q_offset, kv_offset=kv_offset),
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, bq, d), lambda bh, iq, ik: (bh, iq, 0)),
                pl.BlockSpec((1, bk, d), lambda bh, iq, ik: (bh, ik, 0)),
                pl.BlockSpec((1, bk, d), lambda bh, iq, ik: (bh, ik, 0)),
                pl.BlockSpec((1, bk, _STAT_LANES),
                             lambda bh, iq, ik: (bh, ik, 0)),
                pl.BlockSpec((1, bk, _STAT_LANES),
                             lambda bh, iq, ik: (bh, ik, 0)),
            ],
            out_specs=pl.BlockSpec((1, bq, d), lambda bh, iq, ik: (bh, iq, 0)),
            out_shape=jax.ShapeDtypeStruct(qf.shape, q.dtype),
            scratch_shapes=[
                pltpu.VMEM((bq, d), jnp.float32),        # acc
                pltpu.VMEM((bq, _LANES), jnp.float32),   # running max
                pltpu.VMEM((bq, _LANES), jnp.float32),   # running sum
            ],
            interpret=use_interpret,
        )(qf, kf, vf, ksf, vsf)
        return jnp.swapaxes(out.reshape(b, h, lq, d), 1, 2)

    return attn


@functools.lru_cache(maxsize=None)
def flash_attention_fn(block_q: int = 1024, block_k: int | None = None,
                       interpret: bool | None = None,
                       recompute_block: int | None = None):
    """Returns attn(q, k, v, causal=True, q_offset=0, kv_offset=0) backed by
    the Pallas FlashAttention-2 kernels (forward AND backward — the backward
    recomputes scores from the stashed logsumexp, it does not re-run a full
    blockwise forward).

    ``interpret=None`` auto-selects interpreter mode off-TPU so the same
    code runs in the CPU test mesh. ``recompute_block`` is a legacy alias
    for ``block_k`` (the round-2 kernel's recompute granularity); passing
    both is an error rather than a silent override (ADVICE r3). ``block_k``
    defaults to 1024 — a round-4 on-chip sweep at B8/L2048/H16/D64 measured
    1024x1024 ~20% faster fwd+bwd than the round-3 512x512 default (blocks
    clamp to the sequence length, so short sequences are unaffected).
    """
    if recompute_block is not None:
        if block_k is not None:
            raise ValueError("pass block_k or its legacy alias "
                             "recompute_block, not both")
        block_k = recompute_block
    if block_k is None:
        block_k = 1024

    def pick_interpret():
        if interpret is not None:
            return interpret
        return jax.default_backend() != "tpu"

    @functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
    def attn_core(q, k, v, causal, q_offset, kv_offset):
        out, _ = _fa_forward(q, k, v, causal, q_offset, kv_offset,
                             block_q, block_k, pick_interpret())
        return out

    def fwd(q, k, v, causal, q_offset, kv_offset):
        out, lse = _fa_forward(q, k, v, causal, q_offset, kv_offset,
                               block_q, block_k, pick_interpret())
        return out, (q, k, v, out, lse)

    def bwd(causal, q_offset, kv_offset, res, g):
        q, k, v, out, lse = res
        return _fa_backward(q, k, v, out, lse, g, causal,
                            q_offset, kv_offset, block_q, block_k,
                            pick_interpret())

    attn_core.defvjp(fwd, bwd)

    def attn(q, k, v, *, causal: bool = True, q_offset=0, kv_offset=0):
        return attn_core(q, k, v, causal, q_offset, kv_offset)

    return attn
