"""Memory-efficient attention: blockwise (online softmax) + Pallas flash.

The reference has no attention at all (SURVEY.md §2c); tpu_dist's LM family
takes a pluggable ``attn_fn`` (tpu_dist.models.transformer), so these drop
into the SAME weights as full attention:

* :func:`blockwise_attention_fn` — pure-JAX flash-attention math: a
  ``lax.scan`` over KV blocks with a running (max, sum, acc) online softmax.
  Never materializes the (B,H,L,L) score matrix — peak activation memory is
  O(L * block) — and autodiff/remat work out of the box. Runs on any
  backend; this is the long-context workhorse and the ground truth for the
  kernel below.
* :func:`flash_attention_fn` — Pallas TPU kernel for the forward hot path:
  one grid step per (batch*head, q-block) computes q_blk @ k^T in VMEM
  (scores never touch HBM), fp32 online math, causal masking by global
  position. Backward is a ``jax.custom_vjp`` that recomputes through the
  blockwise path (flash-style recompute instead of stashing probabilities).
  VMEM bounds the kv length per head (~4k at head_dim 128 fp32); beyond
  that use the blockwise path.

Both are numerically validated against full attention (tests/test_flash.py)
and compose with the causal offsets ring attention uses.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

NEG_INF = -1e30  # avoids -inf - -inf = nan in the online max updates


def _causal_mask(scores, q_pos, k_pos):
    return jnp.where(k_pos[None, :] <= q_pos[:, None], scores, NEG_INF)


def blockwise_attention_fn(block_size: int = 512):
    """Returns attn(q, k, v, causal=True, q_offset=0, kv_offset=0).

    Shapes follow the model convention: (B, L, H, D). fp32 softmax state
    regardless of input dtype, like tpu_dist.models.transformer.full_attention.
    """

    def attn(q, k, v, *, causal: bool = True, q_offset=0, kv_offset=0):
        b, lq, h, d = q.shape
        lk = k.shape[1]
        blk = min(block_size, lk)
        if lk % blk:
            raise ValueError(f"kv length {lk} not divisible by block {blk}")
        nk = lk // blk
        scale = 1.0 / math.sqrt(d)

        # (B, L, H, D) -> (B, H, L, D) once; scan over KV blocks
        qh = jnp.swapaxes(q, 1, 2).astype(jnp.float32) * scale
        kh = jnp.swapaxes(k, 1, 2).reshape(b, h, nk, blk, d)
        vh = jnp.swapaxes(v, 1, 2).reshape(b, h, nk, blk, d)
        kh = jnp.moveaxis(kh, 2, 0)  # (nk, B, H, blk, D)
        vh = jnp.moveaxis(vh, 2, 0)

        q_pos = q_offset + jnp.arange(lq)

        def body(carry, blk_in):
            acc, m, l, i = carry
            kb, vb = blk_in
            s = jnp.einsum("bhqd,bhkd->bhqk", qh, kb.astype(jnp.float32))
            if causal:
                k_pos = kv_offset + i * blk + jnp.arange(blk)
                s = jnp.where(k_pos[None, None, None, :]
                              <= q_pos[None, None, :, None], s, NEG_INF)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1))
            alpha = jnp.exp(m - m_new)
            p = jnp.exp(s - m_new[..., None])
            l = l * alpha + jnp.sum(p, axis=-1)
            acc = acc * alpha[..., None] + jnp.einsum(
                "bhqk,bhkd->bhqd", p, vb.astype(jnp.float32))
            return (acc, m_new, l, i + 1), None

        acc0 = jnp.zeros((b, h, lq, d), jnp.float32)
        m0 = jnp.full((b, h, lq), NEG_INF, jnp.float32)
        l0 = jnp.zeros((b, h, lq), jnp.float32)
        (acc, _, l, _), _ = jax.lax.scan(
            body, (acc0, m0, l0, jnp.int32(0)), (kh, vh))
        out = acc / jnp.maximum(l, 1e-30)[..., None]
        return jnp.swapaxes(out, 1, 2).astype(v.dtype)

    return attn


# ---------------------------------------------------------------------------
# Pallas flash forward kernel
# ---------------------------------------------------------------------------

def _flash_fwd_kernel(q_ref, k_ref, v_ref, off_ref, o_ref, *, blk_q, causal):
    import jax.experimental.pallas as pl

    iq = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)          # (blk_q, D)
    k = k_ref[0].astype(jnp.float32)          # (Lk, D)
    v = v_ref[0].astype(jnp.float32)          # (Lk, D)
    d = q.shape[-1]
    s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) / jnp.sqrt(
        jnp.float32(d))                        # (blk_q, Lk) — VMEM only
    if causal:
        q_pos = off_ref[0] + iq * blk_q + jax.lax.iota(
            jnp.int32, blk_q)
        k_pos = off_ref[1] + jax.lax.iota(jnp.int32, s.shape[-1])
        s = jnp.where(k_pos[None, :] <= q_pos[:, None], s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.dot(p, v, preferred_element_type=jnp.float32) / jnp.maximum(
        l, 1e-30)
    o_ref[0] = o.astype(o_ref.dtype)


def _flash_fwd(q, k, v, causal, q_offset, kv_offset, blk_q, interpret):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, lq, h, d = q.shape
    lk = k.shape[1]
    bq = min(blk_q, lq)
    if lq % bq:
        raise ValueError(f"q length {lq} not divisible by block {bq}")
    # (B, L, H, D) -> (B*H, L, D)
    fold = lambda x: jnp.swapaxes(x, 1, 2).reshape(b * h, x.shape[1], d)
    qf, kf, vf = fold(q), fold(k), fold(v)
    offsets = jnp.asarray([q_offset, kv_offset], jnp.int32)

    grid = (b * h, lq // bq)
    out = pl.pallas_call(
        functools.partial(_flash_fwd_kernel, blk_q=bq, causal=causal),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, iq: (bh, iq, 0)),
            # constant in iq -> fetched once per (batch, head)
            pl.BlockSpec((1, lk, d), lambda bh, iq: (bh, 0, 0)),
            pl.BlockSpec((1, lk, d), lambda bh, iq: (bh, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda bh, iq: (bh, iq, 0)),
        out_shape=jax.ShapeDtypeStruct(qf.shape, v.dtype),
        interpret=interpret,
    )(qf, kf, vf, offsets)
    return jnp.swapaxes(out.reshape(b, h, lq, d), 1, 2)


def flash_attention_fn(block_q: int = 128, recompute_block: int = 512,
                       interpret: bool | None = None):
    """Returns a Pallas-forward attention with recompute backward.

    ``interpret=None`` auto-selects interpreter mode off-TPU so the same
    code runs in the CPU test mesh.
    """

    def pick_interpret():
        if interpret is not None:
            return interpret
        return jax.default_backend() != "tpu"

    @functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
    def attn_core(q, k, v, causal, q_offset, kv_offset):
        return _flash_fwd(q, k, v, causal, q_offset, kv_offset,
                          block_q, pick_interpret())

    def fwd(q, k, v, causal, q_offset, kv_offset):
        return attn_core(q, k, v, causal, q_offset, kv_offset), (q, k, v)

    def bwd(causal, q_offset, kv_offset, res, g):
        q, k, v = res
        ref = blockwise_attention_fn(recompute_block)
        _, vjp = jax.vjp(
            lambda q_, k_, v_: ref(q_, k_, v_, causal=causal,
                                   q_offset=q_offset, kv_offset=kv_offset),
            q, k, v)
        return vjp(g)

    attn_core.defvjp(fwd, bwd)

    def attn(q, k, v, *, causal: bool = True, q_offset=0, kv_offset=0):
        return attn_core(q, k, v, causal, q_offset, kv_offset)

    return attn
