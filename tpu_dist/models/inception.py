"""GoogLeNet (Inception v1) plan (C2 catalog breadth).

torchvision's googlenet with aux_logits=False: the training-time auxiliary
classifiers exist upstream for the original paper's vanishing-gradient
workaround, which BatchNorm (this plan, like torchvision's) already solves —
the deploy-time network is identical. Faithful quirk preserved: torchvision's
"5x5" inception branch actually uses a 3x3 kernel (the long-standing upstream
bug, kept for weight/parameter compatibility) — branch3 here does the same.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from tpu_dist.models.cnn_zoo import _max_pool_ceil


class _BasicConv(nn.Module):
    """conv (no bias) + BN(eps 1e-3, torchvision's) + relu."""

    ch: int
    kernel: int
    stride: int = 1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        k, p = self.kernel, self.kernel // 2
        x = nn.Conv(self.ch, (k, k), (self.stride, self.stride),
                    padding=[(p, p), (p, p)], use_bias=False,
                    dtype=self.dtype, name="conv")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-3, dtype=jnp.float32, name="bn")(x)
        return nn.relu(x)


class _Inception(nn.Module):
    """Four parallel branches concatenated on channels: 1x1 / 1x1->3x3 /
    1x1->'5x5'(really 3x3) / pool->1x1."""

    ch1: int
    ch3r: int
    ch3: int
    ch5r: int
    ch5: int
    pool_proj: int
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(_BasicConv, dtype=self.dtype)
        b1 = conv(self.ch1, 1, name="b1")(x, train)
        b2 = conv(self.ch3, 3, name="b2_3x3")(
            conv(self.ch3r, 1, name="b2_1x1")(x, train), train)
        b3 = conv(self.ch5, 3, name="b3_5x5")(  # 3x3 kernel: see module doc
            conv(self.ch5r, 1, name="b3_1x1")(x, train), train)
        b4 = conv(self.pool_proj, 1, name="b4_1x1")(
            nn.max_pool(x, (3, 3), strides=(1, 1),
                        padding=[(1, 1), (1, 1)]), train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


# (ch1x1, ch3x3red, ch3x3, ch5x5red, ch5x5, pool_proj) per torchvision
_PLAN = {
    "3a": (64, 96, 128, 16, 32, 32),
    "3b": (128, 128, 192, 32, 96, 64),
    "4a": (192, 96, 208, 16, 48, 64),
    "4b": (160, 112, 224, 24, 64, 64),
    "4c": (128, 128, 256, 24, 64, 64),
    "4d": (112, 144, 288, 32, 64, 64),
    "4e": (256, 160, 320, 32, 128, 128),
    "5a": (256, 160, 320, 32, 128, 128),
    "5b": (384, 192, 384, 48, 128, 128),
}


class GoogLeNet(nn.Module):
    """torchvision googlenet (aux_logits=False): 7x7/2 stem, 1x1+3x3
    convs, nine inception blocks with ceil-mode pools between stages,
    GAP + dropout + linear head."""

    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(_BasicConv, dtype=self.dtype)
        x = x.astype(self.dtype)
        x = conv(64, 7, 2, name="conv1")(x, train)
        x = _max_pool_ceil(x)
        x = conv(64, 1, name="conv2")(x, train)
        x = conv(192, 3, name="conv3")(x, train)
        x = _max_pool_ceil(x)
        for name in ("3a", "3b"):
            x = _Inception(*_PLAN[name], self.dtype,
                           name=f"inception{name}")(x, train)
        x = _max_pool_ceil(x)
        for name in ("4a", "4b", "4c", "4d", "4e"):
            x = _Inception(*_PLAN[name], self.dtype,
                           name=f"inception{name}")(x, train)
        x = _max_pool_ceil(x, k=2)
        for name in ("5a", "5b"):
            x = _Inception(*_PLAN[name], self.dtype,
                           name=f"inception{name}")(x, train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(0.2, deterministic=not train, name="drop")(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return x.astype(jnp.float32)
