"""GoogLeNet (Inception v1) plan (C2 catalog breadth).

torchvision's googlenet with aux_logits=False: the training-time auxiliary
classifiers exist upstream for the original paper's vanishing-gradient
workaround, which BatchNorm (this plan, like torchvision's) already solves —
the deploy-time network is identical. Faithful quirk preserved: torchvision's
"5x5" inception branch actually uses a 3x3 kernel (the long-standing upstream
bug, kept for weight/parameter compatibility) — branch3 here does the same.
"""

from __future__ import annotations

from functools import partial


import flax.linen as nn
import jax.numpy as jnp

from tpu_dist.models.cnn_zoo import _max_pool_ceil


class _BasicConv(nn.Module):
    """conv (no bias) + BN(eps 1e-3, torchvision's) + relu.

    ``kernel`` is an int or (kh, kw) — inception v3's factorized 1x7/7x1
    branches use the asymmetric form. ``pad`` 'same' centers the padding
    (odd kernels); 'valid' is the unpadded stem/downsample flavor."""

    ch: int
    kernel: int | tuple = 1
    stride: int = 1
    pad: str = "same"
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        k = (self.kernel if isinstance(self.kernel, tuple)
             else (self.kernel, self.kernel))
        padding = ("VALID" if self.pad == "valid"
                   else [(k[0] // 2, k[0] // 2), (k[1] // 2, k[1] // 2)])
        x = nn.Conv(self.ch, k, (self.stride, self.stride),
                    padding=padding, use_bias=False,
                    dtype=self.dtype, name="conv")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-3, dtype=jnp.float32, name="bn")(x)
        return nn.relu(x)


class _Inception(nn.Module):
    """Four parallel branches concatenated on channels: 1x1 / 1x1->3x3 /
    1x1->'5x5'(really 3x3) / pool->1x1."""

    ch1: int
    ch3r: int
    ch3: int
    ch5r: int
    ch5: int
    pool_proj: int
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(_BasicConv, dtype=self.dtype)
        b1 = conv(self.ch1, 1, name="b1")(x, train)
        b2 = conv(self.ch3, 3, name="b2_3x3")(
            conv(self.ch3r, 1, name="b2_1x1")(x, train), train)
        b3 = conv(self.ch5, 3, name="b3_5x5")(  # 3x3 kernel: see module doc
            conv(self.ch5r, 1, name="b3_1x1")(x, train), train)
        b4 = conv(self.pool_proj, 1, name="b4_1x1")(
            nn.max_pool(x, (3, 3), strides=(1, 1),
                        padding=[(1, 1), (1, 1)]), train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


# (ch1x1, ch3x3red, ch3x3, ch5x5red, ch5x5, pool_proj) per torchvision
_PLAN = {
    "3a": (64, 96, 128, 16, 32, 32),
    "3b": (128, 128, 192, 32, 96, 64),
    "4a": (192, 96, 208, 16, 48, 64),
    "4b": (160, 112, 224, 24, 64, 64),
    "4c": (128, 128, 256, 24, 64, 64),
    "4d": (112, 144, 288, 32, 64, 64),
    "4e": (256, 160, 320, 32, 128, 128),
    "5a": (256, 160, 320, 32, 128, 128),
    "5b": (384, 192, 384, 48, 128, 128),
}


class GoogLeNet(nn.Module):
    """torchvision googlenet (aux_logits=False): 7x7/2 stem, 1x1+3x3
    convs, nine inception blocks with ceil-mode pools between stages,
    GAP + dropout + linear head."""

    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(_BasicConv, dtype=self.dtype)
        x = x.astype(self.dtype)
        x = conv(64, 7, 2, name="conv1")(x, train)
        x = _max_pool_ceil(x)
        x = conv(64, 1, name="conv2")(x, train)
        x = conv(192, 3, name="conv3")(x, train)
        x = _max_pool_ceil(x)
        for name in ("3a", "3b"):
            x = _Inception(*_PLAN[name], self.dtype,
                           name=f"inception{name}")(x, train)
        x = _max_pool_ceil(x)
        for name in ("4a", "4b", "4c", "4d", "4e"):
            x = _Inception(*_PLAN[name], self.dtype,
                           name=f"inception{name}")(x, train)
        x = _max_pool_ceil(x, k=2)
        for name in ("5a", "5b"):
            x = _Inception(*_PLAN[name], self.dtype,
                           name=f"inception{name}")(x, train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(0.2, deterministic=not train, name="drop")(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return x.astype(jnp.float32)


# ---------------------------------------------------------------------------
# Inception v3 (torchvision inception_v3, aux_logits=False — like GoogLeNet
# above, the aux head is a train-time-only artifact of the pre-BN era; the
# deploy network is identical). Minimum input ~75px (the VALID stem and two
# stride-2 reductions shrink 32px inputs to nothing, exactly as upstream).

class _InceptionA(nn.Module):
    pool_ch: int
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(_BasicConv, dtype=self.dtype)
        b1 = conv(64, name="b1")(x, train)
        b5 = conv(64, 5, name="b5_2")(conv(48, name="b5_1")(x, train), train)
        b3 = conv(96, 3, name="b3_3")(
            conv(96, 3, name="b3_2")(
                conv(64, name="b3_1")(x, train), train), train)
        bp = conv(self.pool_ch, name="bp")(
            nn.avg_pool(x, (3, 3), strides=(1, 1),
                        padding=[(1, 1), (1, 1)]), train)
        return jnp.concatenate([b1, b5, b3, bp], axis=-1)


class _InceptionB(nn.Module):
    """Grid reduction: stride-2 3x3 + double-3x3 + maxpool."""

    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(_BasicConv, dtype=self.dtype)
        b3 = conv(384, 3, 2, "valid", name="b3")(x, train)
        bd = conv(96, 3, 2, "valid", name="bd_3")(
            conv(96, 3, name="bd_2")(
                conv(64, name="bd_1")(x, train), train), train)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2))
        return jnp.concatenate([b3, bd, bp], axis=-1)


class _InceptionC(nn.Module):
    """Factorized 7x7 branches at width c7."""

    c7: int
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(_BasicConv, dtype=self.dtype)
        c7 = self.c7
        b1 = conv(192, name="b1")(x, train)
        b7 = conv(192, (7, 1), name="b7_3")(
            conv(c7, (1, 7), name="b7_2")(
                conv(c7, name="b7_1")(x, train), train), train)
        h = conv(c7, name="bd_1")(x, train)
        for i, k in enumerate(((7, 1), (1, 7), (7, 1))):
            h = conv(c7, k, name=f"bd_{i + 2}")(h, train)
        bd = conv(192, (1, 7), name="bd_5")(h, train)
        bp = conv(192, name="bp")(
            nn.avg_pool(x, (3, 3), strides=(1, 1),
                        padding=[(1, 1), (1, 1)]), train)
        return jnp.concatenate([b1, b7, bd, bp], axis=-1)


class _InceptionD(nn.Module):
    """Grid reduction: 1x1->3x3/2 + 1x1->1x7->7x1->3x3/2 + maxpool."""

    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(_BasicConv, dtype=self.dtype)
        b3 = conv(320, 3, 2, "valid", name="b3_2")(
            conv(192, name="b3_1")(x, train), train)
        h = conv(192, name="b7_1")(x, train)
        h = conv(192, (1, 7), name="b7_2")(h, train)
        h = conv(192, (7, 1), name="b7_3")(h, train)
        b7 = conv(192, 3, 2, "valid", name="b7_4")(h, train)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2))
        return jnp.concatenate([b3, b7, bp], axis=-1)


class _InceptionE(nn.Module):
    """Expanded-filter-bank block: 1x3/3x1 splits concatenated."""

    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(_BasicConv, dtype=self.dtype)
        b1 = conv(320, name="b1")(x, train)
        h = conv(384, name="b3_1")(x, train)
        b3 = jnp.concatenate(
            [conv(384, (1, 3), name="b3_2a")(h, train),
             conv(384, (3, 1), name="b3_2b")(h, train)], axis=-1)
        h = conv(384, 3, name="bd_2")(conv(448, name="bd_1")(x, train), train)
        bd = jnp.concatenate(
            [conv(384, (1, 3), name="bd_3a")(h, train),
             conv(384, (3, 1), name="bd_3b")(h, train)], axis=-1)
        bp = conv(192, name="bp")(
            nn.avg_pool(x, (3, 3), strides=(1, 1),
                        padding=[(1, 1), (1, 1)]), train)
        return jnp.concatenate([b1, b3, bd, bp], axis=-1)


class InceptionV3(nn.Module):
    """torchvision inception_v3 (aux_logits=False): VALID-conv stem to
    192ch, 3xA (pool 32/64/64), B, 4xC (c7 128/160/160/192), D, 2xE,
    GAP + dropout + linear head."""

    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(_BasicConv, dtype=self.dtype)
        x = x.astype(self.dtype)
        x = conv(32, 3, 2, "valid", name="stem1a")(x, train)
        x = conv(32, 3, pad="valid", name="stem2a")(x, train)
        x = conv(64, 3, name="stem2b")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = conv(80, name="stem3b")(x, train)
        x = conv(192, 3, pad="valid", name="stem4a")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        for i, pool_ch in enumerate((32, 64, 64)):
            x = _InceptionA(pool_ch, self.dtype, name=f"mixed5{'bcd'[i]}")(
                x, train)
        x = _InceptionB(self.dtype, name="mixed6a")(x, train)
        for i, c7 in enumerate((128, 160, 160, 192)):
            x = _InceptionC(c7, self.dtype, name=f"mixed6{'bcde'[i]}")(
                x, train)
        x = _InceptionD(self.dtype, name="mixed7a")(x, train)
        for i in range(2):
            x = _InceptionE(self.dtype, name=f"mixed7{'bc'[i]}")(x, train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(0.5, deterministic=not train, name="drop")(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return x.astype(jnp.float32)
