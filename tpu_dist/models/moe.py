"""Mixture-of-Experts MLP + expert parallelism (GShard/Switch-style).

Absent from the reference (SURVEY.md §2c: EP/MoE ABSENT). TPU-first MoE is
the GShard dispatch pattern: top-1 (Switch) or top-2 (GShard) gating, fixed
expert capacity so every shape is static, and one-hot dispatch/combine
einsums that XLA turns into all-to-alls when the expert dimension is sharded
over the ``expert`` mesh axis (tpu_dist.parallel.ep) — no dynamic
gather/scatter, no host routing.

Load-balancing: the Switch auxiliary loss (fraction-of-tokens x mean-gate
per expert) is ``sow``n into the 'intermediates' collection under
``aux_loss``; the LM train step picks every sown aux_loss up generically and
adds ``aux_weight`` times their sum to the objective.
"""

from __future__ import annotations

from typing import Callable

import flax.linen as nn
import jax
import jax.numpy as jnp

from tpu_dist.ops.quant import dequantize, make_dense, moe_expert_matmul


def moe_group_geometry(total_tokens: int, seq_len: int, num_experts: int,
                       router_top_k: int, group_size: int = 512,
                       capacity_factor: float = 1.25):
    """(group tokens S, per-expert capacity C) — THE dispatch geometry,
    shared by MoEMLP and the analytical MFU accounting
    (tpu_dist.utils.mfu.moe_lm_flops_per_token) so they cannot drift."""
    s = min(group_size, total_tokens)
    if total_tokens % s:  # group size must divide tokens; fall back to rows
        s = seq_len
    cap = max(1, int(s / num_experts * capacity_factor * router_top_k))
    return s, cap


class MoEMLP(nn.Module):
    """MoE feed-forward: top-1 (Switch) or top-2 (GShard) gate,
    capacity-bounded dispatch.

    Input (B, L, D) -> (B, L, D). Expert weights carry a leading experts dim
    sharded over the 'expert' axis by tpu_dist.parallel.ep.ep_param_specs.

    GShard grouping: tokens are processed in groups of ``group_size`` with
    per-group capacity, so the dispatch/combine tensors are (G, S, E, C) with
    C = S/E * factor — memory O(T * S * factor) instead of the O(T^2) a
    global dispatch would cost, and the cumsum that assigns capacity slots is
    group-local (no cross-shard sequential dependency when the group dim is
    sharded over 'data'). Dispatch one-hots are kept in the compute dtype
    (bf16 halves their footprint under the bf16 policy).
    """

    num_experts: int = 4
    mlp_ratio: int = 4
    capacity_factor: float = 1.25
    group_size: int = 512
    router_top_k: int = 1      # 1 = Switch, 2 = GShard-style top-2
    z_loss_coef: float = 1e-3  # router z-loss weight RELATIVE to the balance
                               # loss (both ride the single sown aux_loss,
                               # scaled by the step's aux_weight)
    dtype: jnp.dtype = jnp.float32
    quant: str = "none"        # none | int8 | int8_wo (ops.quant): the
                               # expert matmuls only — the fp32 router gate
                               # and the one-hot dispatch/combine einsums
                               # are selection, not compute, and stay fp

    @nn.compact
    def __call__(self, x, train: bool = True):
        if self.router_top_k not in (1, 2):
            raise ValueError("router_top_k must be 1 or 2")
        b, l, d = x.shape
        t = b * l
        e = self.num_experts
        f = self.mlp_ratio * d
        s, cap = moe_group_geometry(t, l, e, self.router_top_k,
                                    self.group_size, self.capacity_factor)
        g = t // s

        tokens = x.reshape(g, s, d)
        gate_logits = nn.Dense(e, use_bias=False, dtype=jnp.float32,
                               name="gate")(tokens.astype(jnp.float32))
        probs = jax.nn.softmax(gate_logits, axis=-1)          # (G, S, E) fp32
        expert_idx = jnp.argmax(probs, axis=-1)               # (G, S)
        gate = jnp.max(probs, axis=-1)                        # (G, S)

        onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)  # (G, S, E)
        # position of each token in its expert's queue within the group
        pos = (jnp.cumsum(onehot, axis=1) * onehot - onehot).astype(jnp.int32)
        keep = (pos < cap).astype(jnp.float32) * onehot
        # dispatch tensor (G, S, E, C): one-hot over capacity slots
        disp = keep[..., None] * jax.nn.one_hot(pos, cap, dtype=jnp.float32)

        if self.router_top_k == 1:
            combine = disp * gate[..., None, None]
        else:
            # second choice: argmax with the first expert masked out; its
            # tokens queue BEHIND every first-choice token of that expert
            # (GShard order), and the two gates renormalize to sum to 1
            probs2 = probs * (1.0 - onehot)
            idx2 = jnp.argmax(probs2, axis=-1)
            gate2 = jnp.max(probs2, axis=-1)
            denom = jnp.maximum(gate + gate2, 1e-9)
            combine = disp * (gate / denom)[..., None, None]
            onehot2 = jax.nn.one_hot(idx2, e, dtype=jnp.float32)
            count1 = jnp.sum(keep, axis=1, keepdims=True)     # (G, 1, E)
            pos2 = (jnp.cumsum(onehot2, axis=1) * onehot2 - onehot2
                    + count1 * onehot2).astype(jnp.int32)
            keep2 = (pos2 < cap).astype(jnp.float32) * onehot2
            disp2 = keep2[..., None] * jax.nn.one_hot(pos2, cap,
                                                      dtype=jnp.float32)
            disp = disp + disp2
            combine = combine + disp2 * (gate2 / denom)[..., None, None]

        # Switch aux loss: E * sum_e( token_fraction_e * mean_prob_e ),
        # plus the router z-loss mean(logsumexp(logits)^2) that keeps gate
        # logits from drifting to magnitudes where softmax saturates
        frac = jnp.mean(onehot, axis=(0, 1))
        mean_prob = jnp.mean(probs, axis=(0, 1))
        z = jnp.mean(jax.scipy.special.logsumexp(gate_logits, axis=-1) ** 2)
        self.sow("intermediates", "aux_loss",
                 e * jnp.sum(frac * mean_prob) + self.z_loss_coef * z)
        # diagnostic (NOT part of the objective — the step only sums
        # 'aux_loss' leaves): per-token combine mass, ~gate1 for top-1 and
        # ~1.0 for top-2 when capacity admits both choices
        self.sow("intermediates", "combine_mass",
                 jnp.sum(combine, axis=(-2, -1)))

        w_in = self.param("w_in", nn.initializers.lecun_normal(), (e, d, f))
        w_out = self.param("w_out", nn.initializers.lecun_normal(), (e, f, d))
        if self.has_variable("params", "w_in_scale"):
            # pre-quantized weight-only decode (ops.quant.wo_quantize_params):
            # experts live int8 in HBM, dequantized on the fly
            w_in = dequantize(w_in, self.get_variable("params", "w_in_scale"),
                              self.dtype)
            w_out = dequantize(w_out,
                               self.get_variable("params", "w_out_scale"),
                               self.dtype)
            expert_quant = "none"
        else:
            w_in, w_out = w_in.astype(self.dtype), w_out.astype(self.dtype)
            expert_quant = self.quant

        disp_c = disp.astype(self.dtype)
        expert_in = jnp.einsum("gsec,gsd->gecd", disp_c,
                               tokens.astype(self.dtype))      # (G, E, C, D)
        h = moe_expert_matmul("gecd,edf->gecf", expert_in, w_in,
                              quant=expert_quant)
        h = nn.gelu(h)
        expert_out = moe_expert_matmul("gecf,efd->gecd", h, w_out,
                                       quant=expert_quant)     # (G, E, C, D)
        out = jnp.einsum("gsec,gecd->gsd", combine.astype(self.dtype),
                         expert_out)
        # dropped tokens (over capacity) pass through the residual unchanged
        return out.reshape(b, l, d)


class MoEBlock(nn.Module):
    """Transformer block whose MLP is a MoEMLP (attention unchanged)."""

    num_heads: int
    num_experts: int = 4
    dtype: jnp.dtype = jnp.float32
    attn_fn: Callable = None  # default set in __call__ to avoid import cycle
    router_top_k: int = 1
    group_size: int = 512
    capacity_factor: float = 1.25
    quant: str = "none"
    tp_impl: str = "gspmd"  # ring = collective-matmul attention projections
                            # over a seq-sharded residual (parallel.overlap);
                            # the MoE MLP then routes SHARD-LOCALLY, the same
                            # composition contract as MoE x sp

    @nn.compact
    def __call__(self, x, train: bool = True, decode: bool = False):
        from tpu_dist.models.transformer import (attend_maybe_cached,
                                                 full_attention)

        ring = self.tp_impl != "gspmd"
        if ring and decode:
            raise ValueError("tp_impl='ring' is a training path; decode "
                             "rides the GSPMD layers")
        attn = self.attn_fn or full_attention
        d_model = x.shape[-1]
        head_dim = d_model // self.num_heads
        tp = dict(tp_impl=self.tp_impl) if ring else {}
        h = nn.LayerNorm(dtype=jnp.float32, name="ln1")(x)
        qkv = make_dense(3 * d_model, use_bias=False, dtype=self.dtype,
                         name="qkv", quant=self.quant,
                         tp_kind="column", tp_fused=3, **tp)(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shp = (q.shape[0], q.shape[1], -1, head_dim)  # local heads if ring
        out = attend_maybe_cached(self, q.reshape(shp), k.reshape(shp),
                                  v.reshape(shp), decode=decode,
                                  attn_fn=attn, dtype=self.dtype)
        out = out.reshape(out.shape[0], out.shape[1], -1)
        x = x + make_dense(d_model, use_bias=False, dtype=self.dtype,
                           name="proj", quant=self.quant,
                           tp_kind="row", **tp)(out)
        h = nn.LayerNorm(dtype=jnp.float32, name="ln2")(x)
        x = x + MoEMLP(self.num_experts, dtype=self.dtype,
                       router_top_k=self.router_top_k,
                       group_size=self.group_size,
                       capacity_factor=self.capacity_factor,
                       quant=self.quant,
                       name="moe")(h, train)
        return x


class MoETransformerLM(nn.Module):
    """Decoder-only LM with MoE feed-forward in every block."""

    vocab_size: int = 256
    num_layers: int = 2
    d_model: int = 64
    num_heads: int = 4
    num_experts: int = 4
    max_len: int = 512
    dtype: jnp.dtype = jnp.float32
    attn_fn: Callable = None
    router_top_k: int = 1
    group_size: int = 512  # router group tokens (GShard grouping; under
                           # sequence parallelism groups are shard-local,
                           # so a group_size dividing the shard's tokens
                           # keeps routing identical to the dp grouping)
    capacity_factor: float = 1.25  # per-expert queue = S/E * factor * k.
                           # Capacity is GROUP-LENGTH-dependent, so paths
                           # that group the same tokens differently (e.g.
                           # KV-cache prefill vs full-recompute decode)
                           # only agree exactly when capacity admits every
                           # token; factor >= E/k makes dispatch drop-free.
    remat: bool = False  # rematerialize each MoE block in the backward pass
                         # (the expert dispatch/combine tensors are the
                         # memory hogs — jax.checkpoint per block is the
                         # same HBM lever the dense LM has)
    quant: str = "none"  # none | int8 | int8_wo (ops.quant): attention
                         # projections + expert matmuls + lm_head; router
                         # gate and dispatch/combine stay fp
    tp_impl: str = "gspmd"  # ring = seq-sharded collective-matmul attention
                            # with shard-local expert routing (MoEBlock;
                            # group_size must divide the shard's tokens)

    @nn.compact
    def __call__(self, tokens, train: bool = True, pos_offset=0,
                 decode: bool = False, return_features: bool = False):
        # decode=True enables the per-block KV cache (same pattern as the
        # dense TransformerLM — engine.generate's use_cache path); the MoE
        # MLP itself is per-token/stateless, so routing a single decode
        # position is exact (its group is just the current batch column)
        x = nn.Embed(self.vocab_size, self.d_model, dtype=self.dtype,
                     name="tok_emb")(tokens)
        pos = pos_offset + jnp.arange(tokens.shape[1])
        x = x + nn.Embed(self.max_len, self.d_model, dtype=self.dtype,
                         name="pos_emb")(pos)[None]
        if self.tp_impl == "ring":
            if decode:
                raise ValueError("tp_impl='ring' is a training path; "
                                 "decode rides the GSPMD layers")
            from tpu_dist.parallel.overlap import seq_shard
            x = seq_shard(x)
        block_cls = (nn.remat(MoEBlock, static_argnums=(2, 3)) if self.remat
                     else MoEBlock)
        for i in range(self.num_layers):
            x = block_cls(self.num_heads, self.num_experts, self.dtype,
                          self.attn_fn, self.router_top_k, self.group_size,
                          self.capacity_factor, self.quant, self.tp_impl,
                          name=f"block{i}")(x, train, decode)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_f")(x)
        if return_features:
            # chunked-loss path (ops.fused_xent): head applied per row-chunk
            return x
        logits = make_dense(self.vocab_size, use_bias=False, dtype=self.dtype,
                            name="lm_head", quant=self.quant)(x)
        return logits.astype(jnp.float32)
