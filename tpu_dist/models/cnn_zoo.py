"""Non-ResNet CNN plans: VGG and DenseNet (reference component C2 breadth).

The reference's factory accepts ANY lowercase torchvision callable by name
(reference 1.dataparallel.py:23-24), so its catalog includes families beyond
ResNet.  These two prove the registry generalizes past one family — the
torchvision layer plans (vgg16 with BatchNorm, densenet121) rebuilt
TPU-first in the same idiom as tpu_dist.models.resnet:

* NHWC layout, flax.linen, configurable compute dtype with fp32 norm
  statistics (SyncBN semantics under a data-sharded jit);
* an adaptive classifier head: torchvision's vgg flattens a fixed 7x7 map
  (valid only at 224px); here global average pooling feeds the FC stack, so
  the same plan trains on CIFAR 32x32 and ImageNet 224x224 — the reference's
  own scripts push 32x32 CIFAR through torchvision archs the same way.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


class VGG(nn.Module):
    """torchvision vgg plan (batch-norm flavor): conv stacks + maxpool.

    ``plan`` lists channel widths with 'M' for maxpool, exactly torchvision's
    cfgs['D'] for vgg16.
    """

    plan: Sequence
    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=jnp.float32)
        x = x.astype(self.dtype)
        i = 0
        for entry in self.plan:
            if entry == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.Conv(entry, (3, 3), padding=[(1, 1), (1, 1)],
                            use_bias=False, dtype=self.dtype,
                            name=f"conv{i}")(x)
                x = norm(name=f"bn{i}")(x)
                x = nn.relu(x)
                i += 1
        x = jnp.mean(x, axis=(1, 2))  # adaptive pool (any input size)
        for j, width in enumerate((4096, 4096)):
            x = nn.Dense(width, dtype=self.dtype, name=f"fc{j}")(x)
            x = nn.relu(x)
            x = nn.Dropout(0.5, deterministic=not train,
                           name=f"drop{j}")(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return x.astype(jnp.float32)


class _DenseLayer(nn.Module):
    """DenseNet layer: BN-ReLU-1x1(4k) -> BN-ReLU-3x3(k), concat input."""

    growth: int
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x, train: bool = True):
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=jnp.float32)
        y = nn.relu(norm(name="bn1")(x))
        y = nn.Conv(4 * self.growth, (1, 1), use_bias=False,
                    dtype=self.dtype, name="conv1")(y)
        y = nn.relu(norm(name="bn2")(y))
        y = nn.Conv(self.growth, (3, 3), padding=[(1, 1), (1, 1)],
                    use_bias=False, dtype=self.dtype, name="conv2")(y)
        return jnp.concatenate([x, y], axis=-1)


class DenseNet(nn.Module):
    """torchvision DenseNet plan: dense blocks + 1x1/avgpool transitions.

    densenet121 = growth 32, blocks [6, 12, 24, 16], init 64.
    """

    block_sizes: Sequence[int]
    growth: int = 32
    init_features: int = 64
    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=jnp.float32)
        x = x.astype(self.dtype)
        x = nn.Conv(self.init_features, (7, 7), (2, 2),
                    padding=[(3, 3), (3, 3)], use_bias=False,
                    dtype=self.dtype, name="conv0")(x)
        x = nn.relu(norm(name="bn0")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        features = self.init_features
        for b, n_layers in enumerate(self.block_sizes):
            for l in range(n_layers):
                x = _DenseLayer(self.growth, self.dtype,
                                name=f"block{b}_layer{l}")(x, train)
            features += n_layers * self.growth
            if b != len(self.block_sizes) - 1:  # transition halves channels
                features //= 2
                x = nn.relu(norm(name=f"trans{b}_bn")(x))
                x = nn.Conv(features, (1, 1), use_bias=False,
                            dtype=self.dtype, name=f"trans{b}_conv")(x)
                x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(norm(name="bn_final")(x))
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return x.astype(jnp.float32)


# torchvision plans
VGG16 = partial(VGG, plan=[64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
                           512, 512, 512, "M", 512, 512, 512, "M"])
VGG11 = partial(VGG, plan=[64, "M", 128, "M", 256, 256, "M",
                           512, 512, "M", 512, 512, "M"])
DenseNet121 = partial(DenseNet, block_sizes=[6, 12, 24, 16])
