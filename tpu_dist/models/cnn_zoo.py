"""Non-ResNet CNN plans: VGG, DenseNet, MobileNetV2, SqueezeNet,
ShuffleNetV2, EfficientNet (C2 breadth).

The reference's factory accepts ANY lowercase torchvision callable by name
(reference 1.dataparallel.py:23-24), so its catalog includes families beyond
ResNet.  These families prove the registry generalizes — the torchvision
layer plans (vgg16 with BatchNorm, densenet121, mobilenet_v2's inverted
residuals with depthwise convs, squeezenet1_1's fire modules,
shufflenet_v2_x1_0's channel-split/shuffle units, efficientnet_b0's
MBConv + squeeze-excite + stochastic depth) rebuilt TPU-first in the same
idiom as tpu_dist.models.resnet:

* NHWC layout, flax.linen, configurable compute dtype with fp32 norm
  statistics (SyncBN semantics under a data-sharded jit);
* an adaptive classifier head: torchvision's vgg flattens a fixed 7x7 map
  (valid only at 224px); here global average pooling feeds the FC stack, so
  the same plan trains on CIFAR 32x32 and ImageNet 224x224 — the reference's
  own scripts push 32x32 CIFAR through torchvision archs the same way.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp


class VGG(nn.Module):
    """torchvision vgg plan (batch-norm flavor): conv stacks + maxpool.

    ``plan`` lists channel widths with 'M' for maxpool, exactly torchvision's
    cfgs['D'] for vgg16.
    """

    plan: Sequence
    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=jnp.float32)
        x = x.astype(self.dtype)
        i = 0
        for entry in self.plan:
            if entry == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.Conv(entry, (3, 3), padding=[(1, 1), (1, 1)],
                            use_bias=False, dtype=self.dtype,
                            name=f"conv{i}")(x)
                x = norm(name=f"bn{i}")(x)
                x = nn.relu(x)
                i += 1
        x = jnp.mean(x, axis=(1, 2))  # adaptive pool (any input size)
        for j, width in enumerate((4096, 4096)):
            x = nn.Dense(width, dtype=self.dtype, name=f"fc{j}")(x)
            x = nn.relu(x)
            x = nn.Dropout(0.5, deterministic=not train,
                           name=f"drop{j}")(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return x.astype(jnp.float32)


class _DenseLayer(nn.Module):
    """DenseNet layer: BN-ReLU-1x1(4k) -> BN-ReLU-3x3(k), concat input."""

    growth: int
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x, train: bool = True):
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=jnp.float32)
        y = nn.relu(norm(name="bn1")(x))
        y = nn.Conv(4 * self.growth, (1, 1), use_bias=False,
                    dtype=self.dtype, name="conv1")(y)
        y = nn.relu(norm(name="bn2")(y))
        y = nn.Conv(self.growth, (3, 3), padding=[(1, 1), (1, 1)],
                    use_bias=False, dtype=self.dtype, name="conv2")(y)
        return jnp.concatenate([x, y], axis=-1)


class DenseNet(nn.Module):
    """torchvision DenseNet plan: dense blocks + 1x1/avgpool transitions.

    densenet121 = growth 32, blocks [6, 12, 24, 16], init 64.
    """

    block_sizes: Sequence[int]
    growth: int = 32
    init_features: int = 64
    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=jnp.float32)
        x = x.astype(self.dtype)
        x = nn.Conv(self.init_features, (7, 7), (2, 2),
                    padding=[(3, 3), (3, 3)], use_bias=False,
                    dtype=self.dtype, name="conv0")(x)
        x = nn.relu(norm(name="bn0")(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        features = self.init_features
        for b, n_layers in enumerate(self.block_sizes):
            for l in range(n_layers):
                x = _DenseLayer(self.growth, self.dtype,
                                name=f"block{b}_layer{l}")(x, train)
            features += n_layers * self.growth
            if b != len(self.block_sizes) - 1:  # transition halves channels
                features //= 2
                x = nn.relu(norm(name=f"trans{b}_bn")(x))
                x = nn.Conv(features, (1, 1), use_bias=False,
                            dtype=self.dtype, name=f"trans{b}_conv")(x)
                x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(norm(name="bn_final")(x))
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return x.astype(jnp.float32)


class _InvertedResidual(nn.Module):
    """Inverted residual: 1x1 expand -> kxk depthwise -> 1x1 project,
    residual when stride 1 and channels match; linear bottleneck (no
    activation after the projection). MobileNetV2's flavor is ReLU6 /
    kernel 3; MnasNet reuses the block with plain ReLU and 3 or 5 kernels
    (models.mobile)."""

    out_ch: int
    stride: int
    expand: int
    dtype: jnp.dtype
    kernel: int = 3
    act: str = "relu6"  # relu6 | relu

    @nn.compact
    def __call__(self, x, train: bool = True):
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=jnp.float32)
        act = ((lambda h: jnp.clip(h, 0.0, 6.0)) if self.act == "relu6"
               else nn.relu)
        in_ch = x.shape[-1]
        k, p = self.kernel, self.kernel // 2
        h = x
        if self.expand != 1:
            h = nn.Conv(in_ch * self.expand, (1, 1), use_bias=False,
                        dtype=self.dtype, name="expand")(h)
            h = act(norm(name="bn_expand")(h))
        ch = h.shape[-1]
        h = nn.Conv(ch, (k, k), (self.stride, self.stride),
                    padding=[(p, p), (p, p)], feature_group_count=ch,
                    use_bias=False, dtype=self.dtype, name="depthwise")(h)
        h = act(norm(name="bn_dw")(h))
        h = nn.Conv(self.out_ch, (1, 1), use_bias=False, dtype=self.dtype,
                    name="project")(h)
        h = norm(name="bn_project")(h)
        if self.stride == 1 and in_ch == self.out_ch:
            h = x + h
        return h


class MobileNetV2(nn.Module):
    """torchvision mobilenet_v2 plan: stem 32/s2, seven inverted-residual
    stages (t, c, n, s), 1280-wide head conv, global pool + classifier."""

    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32
    # (expand t, channels c, repeats n, first-stride s) — torchvision's table
    plan: Sequence = ((1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2),
                      (6, 64, 4, 2), (6, 96, 3, 1), (6, 160, 3, 2),
                      (6, 320, 1, 1))

    @nn.compact
    def __call__(self, x, train: bool = True):
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=jnp.float32)
        x = x.astype(self.dtype)
        x = nn.Conv(32, (3, 3), (2, 2), padding=[(1, 1), (1, 1)],
                    use_bias=False, dtype=self.dtype, name="stem")(x)
        x = jnp.clip(norm(name="bn_stem")(x), 0.0, 6.0)
        for si, (t, c, n, s) in enumerate(self.plan):
            for i in range(n):
                x = _InvertedResidual(c, s if i == 0 else 1, t, self.dtype,
                                      name=f"stage{si}_block{i}")(x, train)
        x = nn.Conv(1280, (1, 1), use_bias=False, dtype=self.dtype,
                    name="head_conv")(x)
        x = jnp.clip(norm(name="bn_head")(x), 0.0, 6.0)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(0.2, deterministic=not train, name="drop")(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return x.astype(jnp.float32)


class _SqueezeExcite(nn.Module):
    """Squeeze-excite: global pool -> 1x1 reduce (act) -> 1x1 expand (gate)
    -> scale. EfficientNet's flavor is silu/sigmoid with ``reduce_ch`` the
    block's INPUT channels // 4 (torchvision, not the expanded width);
    MobileNetV3 reuses the block with relu/hard_sigmoid on round8(exp/4)
    channels (models.mobile)."""

    reduce_ch: int
    dtype: jnp.dtype
    act: Callable = nn.silu
    gate: Callable = nn.sigmoid

    @nn.compact
    def __call__(self, x):
        s = jnp.mean(x, axis=(1, 2), keepdims=True)
        s = self.act(nn.Conv(self.reduce_ch, (1, 1), dtype=self.dtype,
                             name="fc1")(s))
        s = self.gate(nn.Conv(x.shape[-1], (1, 1), dtype=self.dtype,
                              name="fc2")(s))
        return x * s


class _MBConv(nn.Module):
    """EfficientNet MBConv: [1x1 expand] -> kxk depthwise -> SE -> 1x1
    project (linear), residual with stochastic depth when shapes match."""

    out_ch: int
    expand: int
    kernel: int
    stride: int
    sd_rate: float  # stochastic-depth drop prob for this block
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x, train: bool = True):
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-3, dtype=jnp.float32)
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        in_ch = x.shape[-1]
        h = x
        if self.expand != 1:
            h = nn.silu(norm(name="bn_expand")(
                conv(in_ch * self.expand, (1, 1), name="expand")(h)))
        ch = h.shape[-1]
        pad = self.kernel // 2
        h = nn.silu(norm(name="bn_dw")(
            conv(ch, (self.kernel, self.kernel),
                 (self.stride, self.stride), padding=[(pad, pad)] * 2,
                 feature_group_count=ch, name="dw")(h)))
        h = _SqueezeExcite(max(1, in_ch // 4), self.dtype, name="se")(h)
        h = norm(name="bn_project")(
            conv(self.out_ch, (1, 1), name="project")(h))
        if self.stride == 1 and in_ch == self.out_ch:
            if train and self.sd_rate > 0:
                # stochastic depth (row-wise): drop the residual branch
                keep = 1.0 - self.sd_rate
                rng = self.make_rng("dropout")
                mask = jax.random.bernoulli(
                    rng, keep, (h.shape[0], 1, 1, 1)).astype(h.dtype)
                h = h * mask / keep
            h = x + h
        return h


class EfficientNet(nn.Module):
    """torchvision efficientnet_b0 plan: 32-ch SiLU stem, seven MBConv
    stages (expand, channels, repeats, stride, kernel), 1280-ch head."""

    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32
    plan: Sequence = ((1, 16, 1, 1, 3), (6, 24, 2, 2, 3), (6, 40, 2, 2, 5),
                      (6, 80, 3, 2, 3), (6, 112, 3, 1, 5),
                      (6, 192, 4, 2, 5), (6, 320, 1, 1, 3))
    sd_max: float = 0.2  # stochastic depth ramps linearly to this

    @nn.compact
    def __call__(self, x, train: bool = True):
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-3, dtype=jnp.float32)
        x = x.astype(self.dtype)
        x = nn.silu(norm(name="bn_stem")(
            nn.Conv(32, (3, 3), (2, 2), padding=[(1, 1), (1, 1)],
                    use_bias=False, dtype=self.dtype, name="stem")(x)))
        total = sum(n for _, _, n, _, _ in self.plan)
        bi = 0
        for si, (t, c, n, s, k) in enumerate(self.plan):
            for i in range(n):
                x = _MBConv(c, t, k, s if i == 0 else 1,
                            self.sd_max * bi / total, self.dtype,
                            name=f"stage{si}_block{i}")(x, train)
                bi += 1
        x = nn.silu(norm(name="bn_head")(
            nn.Conv(1280, (1, 1), use_bias=False, dtype=self.dtype,
                    name="head_conv")(x)))
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(0.2, deterministic=not train, name="drop")(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return x.astype(jnp.float32)


def _channel_shuffle(x, groups: int = 2):
    """ShuffleNet channel shuffle: interleave the two branch halves so
    information crosses the split at every unit."""
    b, h, w, c = x.shape
    return (x.reshape(b, h, w, groups, c // groups)
            .swapaxes(3, 4).reshape(b, h, w, c))


class _ShuffleUnit(nn.Module):
    """ShuffleNetV2 unit. stride 1: channel-split, right branch
    1x1 -> 3x3 dw -> 1x1, concat, shuffle. stride 2: both branches
    downsample the full input (left 3x3 dw -> 1x1; right as above)."""

    out_ch: int
    stride: int
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x, train: bool = True):
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=jnp.float32)
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        branch = self.out_ch // 2

        def right(h, name):
            h = nn.relu(norm(name=f"{name}_bn1")(
                conv(branch, (1, 1), name=f"{name}_pw1")(h)))
            h = norm(name=f"{name}_bn2")(
                conv(branch, (3, 3), (self.stride, self.stride),
                     padding=[(1, 1), (1, 1)], feature_group_count=branch,
                     name=f"{name}_dw")(h))
            return nn.relu(norm(name=f"{name}_bn3")(
                conv(branch, (1, 1), name=f"{name}_pw2")(h)))

        if self.stride == 1:
            left, rest = jnp.split(x, 2, axis=-1)
            out = jnp.concatenate([left, right(rest, "r")], axis=-1)
        else:
            in_ch = x.shape[-1]
            l = norm(name="l_bn1")(
                conv(in_ch, (3, 3), (2, 2), padding=[(1, 1), (1, 1)],
                     feature_group_count=in_ch, name="l_dw")(x))
            l = nn.relu(norm(name="l_bn2")(conv(branch, (1, 1),
                                                name="l_pw")(l)))
            out = jnp.concatenate([l, right(x, "r")], axis=-1)
        return _channel_shuffle(out)


class ShuffleNetV2(nn.Module):
    """torchvision shufflenet_v2 plan: 24-ch stem + 3 stages of
    (downsample + repeat) shuffle units, 1x1 head conv, GAP + classifier.
    Width multipliers are pure plans: x0_5 (48/96/192), x1_0 (116/232/464),
    x1_5 (176/352/704), x2_0 (244/488/976 with a 2048 head)."""

    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32
    stage_out: Sequence[int] = (116, 232, 464)
    stage_repeats: Sequence[int] = (4, 8, 4)
    head_ch: int = 1024

    @nn.compact
    def __call__(self, x, train: bool = True):
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=jnp.float32)
        x = x.astype(self.dtype)
        x = nn.relu(norm(name="bn1")(
            nn.Conv(24, (3, 3), (2, 2), padding=[(1, 1), (1, 1)],
                    use_bias=False, dtype=self.dtype, name="conv1")(x)))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        for si, (ch, n) in enumerate(zip(self.stage_out, self.stage_repeats)):
            for i in range(n):
                x = _ShuffleUnit(ch, 2 if i == 0 else 1, self.dtype,
                                 name=f"stage{si}_unit{i}")(x, train)
        x = nn.relu(norm(name="bn5")(
            nn.Conv(self.head_ch, (1, 1), use_bias=False, dtype=self.dtype,
                    name="conv5")(x)))
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return x.astype(jnp.float32)


def _max_pool_ceil(x, k: int = 3, s: int = 2):
    """torchvision's MaxPool2d(ceil_mode=True): pad the end of each spatial
    dim (flax pads max_pool with -inf) so partial windows count. Load-bearing
    for squeezenet1_0 even at 224px (its 54 -> 27 pool needs ceil; floor
    gives 26) and for both versions on 32px CIFAR inputs."""
    pads = []
    for dim in x.shape[1:3]:
        rem = (dim - k) % s
        pads.append((0, s - rem if rem else 0))
    return nn.max_pool(x, (k, k), strides=(s, s), padding=pads)


class _Fire(nn.Module):
    """SqueezeNet fire module: 1x1 squeeze, parallel 1x1 + 3x3 expands."""

    squeeze: int
    e1: int
    e3: int
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x):
        s = nn.relu(nn.Conv(self.squeeze, (1, 1), dtype=self.dtype,
                            name="squeeze")(x))
        a = nn.relu(nn.Conv(self.e1, (1, 1), dtype=self.dtype,
                            name="expand1")(s))
        b = nn.relu(nn.Conv(self.e3, (3, 3), padding=[(1, 1), (1, 1)],
                            dtype=self.dtype, name="expand3")(s))
        return jnp.concatenate([a, b], axis=-1)


class AlexNet(nn.Module):
    """torchvision alexnet feature plan (biased convs, no BatchNorm) with
    the same GAP-head adaptation as VGG (module docstring): the 256-ch map
    is globally pooled into the 4096-wide FC stack instead of flattening a
    fixed 6x6 grid, so CIFAR 32px and ImageNet 224px both run. Pools are
    skipped when the map is smaller than the window (32px reaches 1x1
    before the final pool)."""

    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        def pool(h):
            return _max_pool_ceil(h) if min(h.shape[1:3]) >= 3 else h

        x = x.astype(self.dtype)
        x = nn.relu(nn.Conv(64, (11, 11), (4, 4), padding=[(2, 2), (2, 2)],
                            dtype=self.dtype, name="conv0")(x))
        x = pool(x)
        x = nn.relu(nn.Conv(192, (5, 5), padding=[(2, 2), (2, 2)],
                            dtype=self.dtype, name="conv1")(x))
        x = pool(x)
        x = nn.relu(nn.Conv(384, (3, 3), padding=[(1, 1), (1, 1)],
                            dtype=self.dtype, name="conv2")(x))
        x = nn.relu(nn.Conv(256, (3, 3), padding=[(1, 1), (1, 1)],
                            dtype=self.dtype, name="conv3")(x))
        x = nn.relu(nn.Conv(256, (3, 3), padding=[(1, 1), (1, 1)],
                            dtype=self.dtype, name="conv4")(x))
        x = pool(x)
        x = jnp.mean(x, axis=(1, 2))  # adaptive pool (any input size)
        for j in range(2):
            x = nn.Dropout(0.5, deterministic=not train,
                           name=f"drop{j}")(x)
            x = nn.relu(nn.Dense(4096, dtype=self.dtype, name=f"fc{j}")(x))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return x.astype(jnp.float32)


# torchvision fire sequences, pools marked 'M' (the VGG plan idiom):
# 1_0 = 96-ch 7x7 stem, pools after fire4/fire8; 1_1 = 64-ch 3x3 stem,
# pools after fire3/fire5. Fire numbering starts at 2 upstream.
_SQUEEZE_PLANS = {
    "1_0": [(16, 64, 64), (16, 64, 64), (32, 128, 128), "M",
            (32, 128, 128), (48, 192, 192), (48, 192, 192),
            (64, 256, 256), "M", (64, 256, 256)],
    "1_1": [(16, 64, 64), (16, 64, 64), "M", (32, 128, 128),
            (32, 128, 128), "M", (48, 192, 192), (48, 192, 192),
            (64, 256, 256), (64, 256, 256)],
}


class SqueezeNet(nn.Module):
    """torchvision squeezenet plan (fire modules, no BatchNorm, conv
    classifier head with global average pooling). ``version`` picks the
    1.0 geometry or the lighter 1.1 (_SQUEEZE_PLANS)."""

    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32
    version: str = "1_1"

    @nn.compact
    def __call__(self, x, train: bool = True):
        # torchvision geometry: stem conv and pools are UNPADDED, pools in
        # ceil mode (1_1 at 224px: 111 -> 55 -> 27 -> 13 where floor would
        # agree; 1_0's 109 -> 54 -> 27 chain and CIFAR 32px inputs both
        # NEED the ceil — see _max_pool_ceil)
        fire = partial(_Fire, dtype=self.dtype)
        x = x.astype(self.dtype)
        stem_ch, stem_k = (96, 7) if self.version == "1_0" else (64, 3)
        x = nn.relu(nn.Conv(stem_ch, (stem_k, stem_k), (2, 2),
                            padding="VALID", dtype=self.dtype,
                            name="stem")(x))
        x = _max_pool_ceil(x)
        i = 2
        for entry in _SQUEEZE_PLANS[self.version]:
            if entry == "M":
                x = _max_pool_ceil(x)
            else:
                x = fire(*entry, name=f"fire{i}")(x)
                i += 1
        x = nn.Dropout(0.5, deterministic=not train, name="drop")(x)
        x = nn.relu(nn.Conv(self.num_classes, (1, 1), dtype=self.dtype,
                            name="head_conv")(x))
        x = jnp.mean(x, axis=(1, 2))
        return x.astype(jnp.float32)


# torchvision plans
VGG16 = partial(VGG, plan=[64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
                           512, 512, 512, "M", 512, 512, 512, "M"])
VGG11 = partial(VGG, plan=[64, "M", 128, "M", 256, 256, "M",
                           512, 512, "M", 512, 512, "M"])
DenseNet121 = partial(DenseNet, block_sizes=[6, 12, 24, 16])
VGG13 = partial(VGG, plan=[64, 64, "M", 128, 128, "M", 256, 256, "M",
                           512, 512, "M", 512, 512, "M"])
VGG19 = partial(VGG, plan=[64, 64, "M", 128, 128, "M", 256, 256, 256, 256,
                           "M", 512, 512, 512, 512, "M",
                           512, 512, 512, 512, "M"])
DenseNet169 = partial(DenseNet, block_sizes=[6, 12, 32, 32])
DenseNet201 = partial(DenseNet, block_sizes=[6, 12, 48, 32])
DenseNet161 = partial(DenseNet, block_sizes=[6, 12, 36, 24], growth=48,
                      init_features=96)
SqueezeNet1_0 = partial(SqueezeNet, version="1_0")
ShuffleNetV2_x0_5 = partial(ShuffleNetV2, stage_out=(48, 96, 192))
ShuffleNetV2_x1_5 = partial(ShuffleNetV2, stage_out=(176, 352, 704))
ShuffleNetV2_x2_0 = partial(ShuffleNetV2, stage_out=(244, 488, 976),
                            head_ch=2048)
