"""MnasNet and MobileNetV3 plans (C2 catalog breadth).

The reference factory exposes every lowercase torchvision callable
(reference 1.dataparallel.py:23-24); these are the NAS-derived mobile
families rebuilt in the cnn_zoo idiom: NHWC flax, fp32 BatchNorm
statistics over a configurable compute dtype, GAP heads.

* MnasNet (torchvision mnasnet0_5/mnasnet1_0): plain-ReLU inverted
  residuals (cnn_zoo._InvertedResidual with act='relu', kernels 3/5) whose
  widths scale by alpha through torchvision's round-to-multiple-of-8 rule
  (_scale_depths) — the bias-0.9 round-up is what makes the 0.5 plan's
  widths (40 not 16, etc.) come out right.
* MobileNetV3 (large/small): per-row block tables (expand width is given
  absolutely, not as a ratio), squeeze-excite on exp//4 channels with
  hardsigmoid gates, hardswish activations in the deep half, and the
  1280/1024-wide FC head applied after pooling.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from tpu_dist.models.cnn_zoo import _InvertedResidual, _SqueezeExcite


def _round8(val: float, round_up_bias: float = 0.9) -> int:
    """torchvision mnasnet's _round_to_multiple_of(val, 8)."""
    new_val = max(8, int(val + 4) // 8 * 8)
    return new_val if new_val >= round_up_bias * val else new_val + 8


def _scale_depths(alpha: float) -> list:
    return [_round8(d * alpha) for d in (32, 16, 24, 40, 80, 96, 192, 320)]


class MnasNet(nn.Module):
    """torchvision mnasnet plan: stem + sepconv + six inverted-residual
    stacks (kernel, expansion, repeats per torchvision's table), 1280 head.
    """

    alpha: float = 1.0
    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=jnp.float32)
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        d = _scale_depths(self.alpha)
        x = x.astype(self.dtype)
        x = nn.relu(norm(name="bn0")(
            conv(d[0], (3, 3), (2, 2), padding=[(1, 1), (1, 1)],
                 name="conv0")(x)))
        # separable conv: depthwise 3x3 + linear 1x1 projection
        x = nn.relu(norm(name="bn_dw")(
            conv(d[0], (3, 3), padding=[(1, 1), (1, 1)],
                 feature_group_count=d[0], name="sep_dw")(x)))
        x = norm(name="bn_sep")(conv(d[1], (1, 1), name="sep_pw")(x))
        # (kernel, expansion, repeats, first-stride) per torchvision stack
        plan = ((3, 3, 3, 2), (5, 3, 3, 2), (5, 6, 3, 2),
                (3, 6, 2, 1), (5, 6, 4, 2), (3, 6, 1, 1))
        for si, (k, e, n, s) in enumerate(plan):
            out = d[si + 2]
            for i in range(n):
                x = _InvertedResidual(out, s if i == 0 else 1, e, self.dtype,
                                      kernel=k, act="relu",
                                      name=f"stack{si}_block{i}")(x, train)
        x = nn.relu(norm(name="bn_head")(conv(1280, (1, 1),
                                              name="conv_head")(x)))
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(0.2, deterministic=not train, name="drop")(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return x.astype(jnp.float32)


class _V3Block(nn.Module):
    """MobileNetV3 inverted residual: expand to an ABSOLUTE width, kxk
    depthwise, optional SE, linear projection; relu or hardswish."""

    out_ch: int
    exp_ch: int
    kernel: int
    stride: int
    use_se: bool
    act: str  # 'relu' | 'hswish'
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x, train: bool = True):
        # torchvision mobilenet_v3 builds its BNs with eps=1e-3
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-3, dtype=jnp.float32)
        act = nn.relu if self.act == "relu" else nn.hard_swish
        in_ch = x.shape[-1]
        k, p = self.kernel, self.kernel // 2
        h = x
        if self.exp_ch != in_ch:
            h = nn.Conv(self.exp_ch, (1, 1), use_bias=False,
                        dtype=self.dtype, name="expand")(h)
            h = act(norm(name="bn_expand")(h))
        h = nn.Conv(self.exp_ch, (k, k), (self.stride, self.stride),
                    padding=[(p, p), (p, p)],
                    feature_group_count=self.exp_ch, use_bias=False,
                    dtype=self.dtype, name="depthwise")(h)
        h = act(norm(name="bn_dw")(h))
        if self.use_se:
            h = _SqueezeExcite(_round8(self.exp_ch / 4), self.dtype,
                               act=nn.relu, gate=nn.hard_sigmoid,
                               name="se")(h)
        h = nn.Conv(self.out_ch, (1, 1), use_bias=False, dtype=self.dtype,
                    name="project")(h)
        h = norm(name="bn_project")(h)
        if self.stride == 1 and in_ch == self.out_ch:
            h = x + h
        return h


# (kernel, exp, out, SE, act, stride) — torchvision's settings tables
_V3_LARGE = (
    (3, 16, 16, False, "relu", 1),
    (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1),
    (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1),
    (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hswish", 2),
    (3, 200, 80, False, "hswish", 1),
    (3, 184, 80, False, "hswish", 1),
    (3, 184, 80, False, "hswish", 1),
    (3, 480, 112, True, "hswish", 1),
    (3, 672, 112, True, "hswish", 1),
    (5, 672, 160, True, "hswish", 2),
    (5, 960, 160, True, "hswish", 1),
    (5, 960, 160, True, "hswish", 1),
)
_V3_SMALL = (
    (3, 16, 16, True, "relu", 2),
    (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1),
    (5, 96, 40, True, "hswish", 2),
    (5, 240, 40, True, "hswish", 1),
    (5, 240, 40, True, "hswish", 1),
    (5, 120, 48, True, "hswish", 1),
    (5, 144, 48, True, "hswish", 1),
    (5, 288, 96, True, "hswish", 2),
    (5, 576, 96, True, "hswish", 1),
    (5, 576, 96, True, "hswish", 1),
)


class MobileNetV3(nn.Module):
    """torchvision mobilenet_v3 plan: 16-ch hardswish stem, the per-variant
    block table, 6x-width hardswish conv, GAP, FC head (1280 large / 1024
    small) with hardswish + dropout before the classifier."""

    plan: Sequence = _V3_LARGE
    head_width: int = 1280
    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        # torchvision mobilenet_v3 builds its BNs with eps=1e-3
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-3, dtype=jnp.float32)
        x = x.astype(self.dtype)
        x = nn.hard_swish(norm(name="bn_stem")(
            nn.Conv(16, (3, 3), (2, 2), padding=[(1, 1), (1, 1)],
                    use_bias=False, dtype=self.dtype, name="stem")(x)))
        for i, (k, e, c, se, act, s) in enumerate(self.plan):
            x = _V3Block(c, e, k, s, se, act, self.dtype,
                         name=f"block{i}")(x, train)
        last_conv = 6 * x.shape[-1]
        x = nn.hard_swish(norm(name="bn_last")(
            nn.Conv(last_conv, (1, 1), use_bias=False, dtype=self.dtype,
                    name="conv_last")(x)))
        x = jnp.mean(x, axis=(1, 2))
        x = nn.hard_swish(nn.Dense(self.head_width, dtype=self.dtype,
                               name="fc_head")(x))
        x = nn.Dropout(0.2, deterministic=not train, name="drop")(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return x.astype(jnp.float32)


MnasNet0_5 = partial(MnasNet, alpha=0.5)
MnasNet0_75 = partial(MnasNet, alpha=0.75)
MnasNet1_0 = partial(MnasNet, alpha=1.0)
MnasNet1_3 = partial(MnasNet, alpha=1.3)
MobileNetV3Large = partial(MobileNetV3, plan=_V3_LARGE, head_width=1280)
MobileNetV3Small = partial(MobileNetV3, plan=_V3_SMALL, head_width=1024)
