"""MNIST conv net (reference component C3).

Capability-equivalent of the reference's 17-line ``Net``
(reference 5.2.horovod_pytorch_mnist.py:36-52): conv(10,5x5) -> maxpool -> relu
-> conv(20,5x5) -> dropout2d -> maxpool -> relu -> fc(50) -> dropout -> fc(10)
-> log_softmax.

TPU notes: NHWC layout (XLA:TPU's native conv layout), flax.linen module,
dropout driven by an explicit PRNG key (functional — no global RNG state).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class LeNet(nn.Module):
    """MNIST classifier; input (B, 28, 28, 1) NHWC."""

    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        x = nn.Conv(10, (5, 5), padding="VALID", dtype=self.dtype, name="conv1")(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(x)
        x = nn.Conv(20, (5, 5), padding="VALID", dtype=self.dtype, name="conv2")(x)
        x = nn.Dropout(0.5, deterministic=not train, name="conv2_drop")(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))  # (B, 320)
        x = nn.Dense(50, dtype=self.dtype, name="fc1")(x)
        x = nn.relu(x)
        x = nn.Dropout(0.5, deterministic=not train, name="drop")(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="fc2")(x)
        # reference returns log_softmax + NLL loss; we return logits and fold
        # log_softmax into the loss (numerically identical, XLA fuses it).
        return x.astype(jnp.float32)
