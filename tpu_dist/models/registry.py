"""Model factory (reference component C2).

The reference selects any lowercase callable from
``torchvision.models.__dict__`` by name (reference 1.dataparallel.py:23-24,
97-102). tpu_dist keeps the same UX — ``create_model("resnet50")`` — over an
explicit registry (no torchvision on TPU; ``--pretrained`` is accepted for CLI
parity but there are no bundled weights in a zero-egress environment, so it
raises a clear error instead of silently ignoring the flag).
"""

from __future__ import annotations

from typing import Callable, Dict

import jax.numpy as jnp

from tpu_dist.models import lenet, resnet

_REGISTRY: Dict[str, Callable] = {
    "resnet18": resnet.ResNet18,
    "resnet34": resnet.ResNet34,
    "resnet50": resnet.ResNet50,
    "resnet101": resnet.ResNet101,
    "resnet152": resnet.ResNet152,
    "lenet": lenet.LeNet,
    "mnist_net": lenet.LeNet,  # reference 5.2 'Net' alias
}

model_names = sorted(_REGISTRY)  # reference 1.dataparallel.py:23-24 equivalent


def register(name: str):
    def deco(ctor: Callable):
        _REGISTRY[name] = ctor
        return ctor
    return deco


def create_model(arch: str, num_classes: int = 10, dtype=jnp.float32,
                 pretrained: bool = False, **kwargs):
    if pretrained:
        raise ValueError(
            "--pretrained requires downloaded weights; this environment has no "
            "egress. Train from scratch or point --resume at a checkpoint.")
    if arch not in _REGISTRY:
        raise ValueError(f"unknown arch {arch!r}; choose from {model_names}")
    return _REGISTRY[arch](num_classes=num_classes, dtype=dtype, **kwargs)
