"""Model factory (reference component C2).

The reference selects any lowercase callable from
``torchvision.models.__dict__`` by name (reference 1.dataparallel.py:23-24,
97-102). tpu_dist keeps the same UX — ``create_model("resnet50")`` — over an
explicit registry (no torchvision on TPU). ``--pretrained`` takes a local
checkpoint PATH to warm-start from (engine.checkpoint.load_warmstart /
graft_params — fine-tune keeps fresh init for shape-mismatched heads);
boolean True still raises a clear error because a zero-egress environment
has no weights to download.

Each entry carries its *kind* ("image" classifier vs "lm") so construction
and engine dispatch stay in one place: image ctors take ``num_classes``, LM
ctors take vocab/layer kwargs, and the image Trainer refuses LM archs with a
clear error instead of crashing inside flax init.
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

import jax.numpy as jnp

from tpu_dist.models import (cnn_zoo, inception, lenet, mobile, moe, resnet,
                             transformer, vit)

# name -> (constructor, kind)
_REGISTRY: Dict[str, Tuple[Callable, str]] = {
    "resnet18": (resnet.ResNet18, "image"),
    "resnet34": (resnet.ResNet34, "image"),
    "resnet50": (resnet.ResNet50, "image"),
    "resnet101": (resnet.ResNet101, "image"),
    "resnet152": (resnet.ResNet152, "image"),
    "resnext50_32x4d": (resnet.ResNeXt50_32x4d, "image"),
    "resnext101_32x8d": (resnet.ResNeXt101_32x8d, "image"),
    "wide_resnet50_2": (resnet.WideResNet50_2, "image"),
    "wide_resnet101_2": (resnet.WideResNet101_2, "image"),
    "vgg11": (cnn_zoo.VGG11, "image"),
    "vgg13": (cnn_zoo.VGG13, "image"),
    "vgg16": (cnn_zoo.VGG16, "image"),
    "vgg19": (cnn_zoo.VGG19, "image"),
    "densenet121": (cnn_zoo.DenseNet121, "image"),
    "densenet161": (cnn_zoo.DenseNet161, "image"),
    "densenet169": (cnn_zoo.DenseNet169, "image"),
    "densenet201": (cnn_zoo.DenseNet201, "image"),
    "alexnet": (cnn_zoo.AlexNet, "image"),
    "googlenet": (inception.GoogLeNet, "image"),
    "inception_v3": (inception.InceptionV3, "image"),
    "mnasnet0_5": (mobile.MnasNet0_5, "image"),
    "mnasnet0_75": (mobile.MnasNet0_75, "image"),
    "mnasnet1_0": (mobile.MnasNet1_0, "image"),
    "mnasnet1_3": (mobile.MnasNet1_3, "image"),
    "mobilenet_v2": (cnn_zoo.MobileNetV2, "image"),
    "mobilenet_v3_large": (mobile.MobileNetV3Large, "image"),
    "mobilenet_v3_small": (mobile.MobileNetV3Small, "image"),
    "squeezenet1_0": (cnn_zoo.SqueezeNet1_0, "image"),
    "squeezenet1_1": (cnn_zoo.SqueezeNet, "image"),
    "shufflenet_v2_x0_5": (cnn_zoo.ShuffleNetV2_x0_5, "image"),
    "shufflenet_v2_x1_0": (cnn_zoo.ShuffleNetV2, "image"),
    "shufflenet_v2_x1_5": (cnn_zoo.ShuffleNetV2_x1_5, "image"),
    "shufflenet_v2_x2_0": (cnn_zoo.ShuffleNetV2_x2_0, "image"),
    "efficientnet_b0": (cnn_zoo.EfficientNet, "image"),
    "lenet": (lenet.LeNet, "image"),
    "mnist_net": (lenet.LeNet, "image"),  # reference 5.2 'Net' alias
    "vit_tiny": (vit.ViTTiny, "image"),
    "vit_small": (vit.ViTSmall, "image"),
    "vit_base": (vit.ViTBase, "image"),
    "vit_cifar": (vit.ViTCifar, "image"),
    "transformer_lm": (transformer.TransformerLM, "lm"),
    "tiny_lm": (transformer.tiny_lm, "lm"),
    "moe_lm": (moe.MoETransformerLM, "lm"),
}

model_names = sorted(_REGISTRY)  # reference 1.dataparallel.py:23-24 equivalent


def register(name: str, kind: str = "image"):
    def deco(ctor: Callable):
        _REGISTRY[name] = (ctor, kind)
        return ctor
    return deco


def model_kind(arch: str) -> str:
    if arch not in _REGISTRY:
        raise ValueError(f"unknown arch {arch!r}; choose from {model_names}")
    return _REGISTRY[arch][1]


def create_model(arch: str, num_classes: int = 10, dtype=jnp.float32,
                 pretrained=False, warmstart_handled: bool = False,
                 **kwargs):
    if pretrained is True:
        raise ValueError(
            "--pretrained without a path requires downloaded weights; this "
            "environment has no egress. Pass --pretrained PATH (a local "
            "checkpoint, e.g. an {arch}-model_best.msgpack from this repo) "
            "to warm-start, or train from scratch.")
    if pretrained and not warmstart_handled:
        # a str path is handled by the ENGINES (params live outside the
        # module in jax — this factory only builds architecture); they pass
        # warmstart_handled=True. Any other caller handing a path here
        # would get a fresh-init model while believing it loaded weights —
        # fail loudly instead of silently ignoring the request.
        raise ValueError(
            f"create_model does not load weights: pretrained={pretrained!r} "
            "would be silently ignored. Use Trainer/LMTrainer (which graft "
            "the checkpoint onto the init), or load it yourself via "
            "engine.checkpoint.load_warmstart + graft_params.")
    kind = model_kind(arch)
    ctor = _REGISTRY[arch][0]
    if kind == "lm":
        return ctor(dtype=dtype, **kwargs)  # vocab_size etc. via kwargs
    return ctor(num_classes=num_classes, dtype=dtype, **kwargs)
