from tpu_dist.models.lenet import LeNet  # noqa: F401
from tpu_dist.models.registry import create_model, model_names, register  # noqa: F401
from tpu_dist.models.resnet import (  # noqa: F401
    ResNet, ResNet18, ResNet34, ResNet50, ResNet101, ResNet152)
