"""Causal transformer LM family (long-context / parallelism testbed).

The reference trains only CNN image classifiers (SURVEY.md §2c: no attention,
no sequence dimension anywhere). tpu_dist adds a transformer family because
long-context and model parallelism are first-class in this framework: this
model is the substrate for sequence parallelism (ring attention over a 'seq'
mesh axis — tpu_dist.parallel.ring_attention) and tensor parallelism (head/
mlp sharding over a 'model' axis — tpu_dist.parallel.tp).

TPU-first design choices:
* pre-LN blocks, GELU MLP (4x), learned positional embeddings — all shapes
  static, MXU-friendly (head_dim and mlp sized in multiples of 128 at real
  scales);
* ``attn_fn`` is pluggable: the module computes qkv/out projections and
  delegates the attention contraction, so the SAME parameters run under full
  attention (single device), ring attention (seq-sharded shard_map), or any
  future pallas flash kernel — sharding changes never touch the weights;
* fp32 softmax/logits regardless of compute dtype.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from tpu_dist.ops.quant import make_dense
from tpu_dist.parallel.mesh import MODEL_AXIS


def full_attention(q, k, v, *, causal: bool = True,
                   q_offset: int = 0, kv_offset: int = 0):
    """Reference attention: (B, L, H, D) tensors, fp32 softmax.

    ``q_offset``/``kv_offset`` give the global position of the first row of
    q/k when the sequence axis is sharded (ring attention passes these).
    """
    d = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) / jnp.sqrt(d).astype(jnp.float32)
    if causal:
        qpos = q_offset + jnp.arange(q.shape[1])[:, None]
        kpos = kv_offset + jnp.arange(k.shape[1])[None, :]
        scores = jnp.where(kpos <= qpos, scores, -jnp.inf)
    weights = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", weights.astype(v.dtype), v)


def attend_maybe_cached(mdl: nn.Module, q, k, v, *, decode: bool,
                        attn_fn: Callable, dtype, paged=None,
                        paged_prefill: bool = False):
    """Attention contraction, maintaining ``mdl``'s per-block KV cache when
    ``decode`` (the standard flax decode pattern): the cache is allocated
    at init time from the full-length input, then one position is written
    per step, and attention runs over the whole buffer with the causal mask
    hiding positions > cache_index (they are zeros anyway). Shared by the
    dense Block and MoEBlock so both families decode through ONE cache
    implementation. Decode always uses exact full attention over the cache:
    the attn_fn plug-in (flash/blockwise/ring) exists for TRAINING-time
    memory, and flash's custom_vjp can't take the traced cache index as its
    static offset anyway.

    ``paged`` (engine.kv_cache / ops.paged_attention) swaps the flax cache
    for this layer's slice of an EXTERNAL paged KV pool: the pack carries
    the layer's page arenas plus per-row block tables and positions, so
    every batch row can sit at its own position — the continuous-batching
    serving path, where the flax cache's scalar ``cache_index`` is exactly
    what doesn't work. Returns ``(out, updated_layer)`` in that mode; the
    flax-cache contiguous path remains the single-batch degenerate case
    (engine.generate) and is bit-identical on greedy tokens
    (tests/test_serve.py pins it)."""
    if paged is not None:
        from tpu_dist.ops.paged_attention import paged_attend

        return paged_attend(q, k, v, paged, prefill=paged_prefill,
                            attn_fn=attn_fn, dtype=dtype)
    if not decode:
        return attn_fn(q, k, v)
    is_init = mdl.has_variable("cache", "cached_k")
    ck = mdl.variable("cache", "cached_k", jnp.zeros, k.shape, dtype)
    cv = mdl.variable("cache", "cached_v", jnp.zeros, v.shape, dtype)
    ci = mdl.variable("cache", "cache_index",
                      lambda: jnp.zeros((), jnp.int32))
    if not is_init:
        return attn_fn(q, k, v)
    idx = ci.value
    z = jnp.zeros((), idx.dtype)  # match idx dtype (x64-safe)
    ck.value = jax.lax.dynamic_update_slice(
        ck.value, k.astype(dtype), (z, idx, z, z))
    cv.value = jax.lax.dynamic_update_slice(
        cv.value, v.astype(dtype), (z, idx, z, z))
    ci.value = idx + q.shape[1]
    return full_attention(q, ck.value, cv.value, q_offset=idx, kv_offset=0)


class Block(nn.Module):
    num_heads: int
    dtype: jnp.dtype = jnp.float32
    attn_fn: Callable = full_attention
    quant: str = "none"  # none | int8 | int8_wo — dense/attention
                         # projections via ops.quant (the attention
                         # contraction itself and the norms stay fp)
    tp_impl: str = "gspmd"  # gspmd (compiler-partitioned, the default) |
                            # ring (AG-matmul / matmul-RS collective matmul
                            # over a seq-sharded residual, inside shard_map
                            # with the 'model' axis bound) | ring_ar
                            # (full-token residual, chunked ring allreduce
                            # of the row partials — parallel.overlap)

    @nn.compact
    def __call__(self, x, train: bool = True, decode: bool = False,
                 paged=None, paged_prefill: bool = False):
        ring = self.tp_impl != "gspmd"
        if ring and decode:
            raise ValueError("tp_impl='ring' is a training path; decode "
                             "rides the GSPMD layers")
        if ring:
            # fail with the real constraint, not a reshape error three ops
            # later: each shard's qkv slice must hold whole heads
            from tpu_dist.parallel.overlap import static_axis_size
            n = static_axis_size(MODEL_AXIS)
            if self.num_heads % n:
                raise ValueError(
                    f"tp_impl='{self.tp_impl}' shards attention heads: "
                    f"num_heads {self.num_heads} must divide by the "
                    f"'{MODEL_AXIS}' axis ({n})")
        # under tp_impl='ring' the residual x is this device's SEQUENCE
        # chunk (B, L/n, D): the column projections gather the full
        # sequence for a head/feature shard, the row projections scatter
        # it back reduced — all shapes below derive from the inputs, so
        # one body serves the replicated and both ring dataflows
        d_model = x.shape[-1]
        head_dim = d_model // self.num_heads
        tp = dict(tp_impl=self.tp_impl) if ring else {}
        h = nn.LayerNorm(dtype=jnp.float32, name="ln1")(x)
        qkv = make_dense(3 * d_model, use_bias=False, dtype=self.dtype,
                         name="qkv", quant=self.quant,
                         tp_kind="column", tp_fused=3, **tp)(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shp = (q.shape[0], q.shape[1], -1, head_dim)  # local heads if ring
        q, k, v = q.reshape(shp), k.reshape(shp), v.reshape(shp)
        out = attend_maybe_cached(self, q, k, v, decode=decode,
                                  attn_fn=self.attn_fn, dtype=self.dtype,
                                  paged=paged, paged_prefill=paged_prefill)
        new_layer = None
        if paged is not None:
            out, new_layer = out
        out = out.reshape(out.shape[0], out.shape[1], -1)
        x = x + make_dense(d_model, use_bias=False, dtype=self.dtype,
                           name="proj", quant=self.quant,
                           tp_kind="row", **tp)(out)
        h = nn.LayerNorm(dtype=jnp.float32, name="ln2")(x)
        h = make_dense(4 * d_model, dtype=self.dtype, name="mlp_in",
                       quant=self.quant, tp_kind="column", **tp)(h)
        h = nn.gelu(h)
        x = x + make_dense(d_model, dtype=self.dtype, name="mlp_out",
                           quant=self.quant, tp_kind="row", **tp)(h)
        if paged is not None:
            return x, new_layer
        return x


class TransformerLM(nn.Module):
    """Decoder-only LM. Input: int32 tokens (B, L); output fp32 logits."""

    vocab_size: int = 32000
    num_layers: int = 4
    d_model: int = 256
    num_heads: int = 8
    max_len: int = 2048
    dtype: jnp.dtype = jnp.float32
    attn_fn: Callable = full_attention
    remat: bool = False  # rematerialize each block's activations in the
                         # backward pass (jax.checkpoint): trades FLOPs for
                         # HBM — the long-context memory lever
    quant: str = "none"  # none | int8 | int8_wo (ops.quant): int8 dense/
                         # attention projections + lm_head; param tree is
                         # IDENTICAL to the unquantized model, so the knob
                         # composes with checkpoints and every sharding
    tp_impl: str = "gspmd"  # gspmd (declarative TP via parallel.tp specs)
                            # | ring (manual collective-matmul TP inside
                            # shard_map over the 'model' axis — parallel.
                            # overlap; param tree IDENTICAL, so both impls
                            # load the same checkpoints). Under ring the
                            # residual stream is seq-sharded between the
                            # projections; outputs are this device's
                            # (B, L/n, ...) sequence chunk.

    @nn.compact
    def __call__(self, tokens, train: bool = True, pos_offset=0,
                 decode: bool = False, return_features: bool = False,
                 paged=None, paged_prefill: bool = False):
        # pos_offset: global position of this shard's first token (sequence
        # parallelism passes axis_index * shard_len, a traced scalar; 0 when
        # the sequence axis is unsharded; the paged serving tick passes a
        # (B,) vector — every slot sits at its own position). decode=True
        # enables the per-block KV cache ('cache' collection) for
        # autoregressive generation; `paged` instead threads an EXTERNAL
        # paged KV pool through the blocks (engine.kv_cache) and makes the
        # call return (logits, updated_layers). return_features=True skips
        # lm_head and returns the (B, L, D) post-ln_f features — the
        # chunked-loss path (ops.fused_xent) applies the head itself, one
        # row-chunk at a time, so the full (B, L, V) logits never
        # materialize.
        x = nn.Embed(self.vocab_size, self.d_model, dtype=self.dtype,
                     name="tok_emb")(tokens)
        pos_emb = nn.Embed(self.max_len, self.d_model, dtype=self.dtype,
                           name="pos_emb")
        off = jnp.asarray(pos_offset)
        if off.ndim:  # per-row positions: (B,) + (L,) -> (B, L) lookups
            pos = off[:, None] + jnp.arange(tokens.shape[1])[None, :]
            x = x + pos_emb(pos)
        else:
            x = x + pos_emb(pos_offset + jnp.arange(tokens.shape[1]))[None]
        if self.tp_impl == "ring":
            # enter the seq-sharded ring residual: from here each device
            # carries its (B, L/n, D) chunk; the blocks' column/row ring
            # projections gather/scatter around it (parallel.overlap)
            if decode:
                raise ValueError("tp_impl='ring' is a training path; "
                                 "decode rides the GSPMD layers")
            from tpu_dist.parallel.overlap import seq_shard
            x = seq_shard(x)
        # remat exists for the training backward; the paged serving path
        # never differentiates, and remat's static_argnums would try to
        # make the traced `paged` pack static — plain blocks there, always
        block_cls = (nn.remat(Block, static_argnums=(2, 3))
                     if self.remat and paged is None else Block)
        new_layers = []
        ctx = (None if paged is None else
               {k: paged.get(k) for k in ("block_tables", "positions",
                                          "lengths", "valid", "sp_mesh")})
        for i in range(self.num_layers):
            blk = block_cls(self.num_heads, self.dtype, self.attn_fn,
                            self.quant, self.tp_impl, name=f"block{i}")
            if paged is None:
                x = blk(x, train, decode)
            else:
                x, nl = blk(x, train, decode,
                            {**ctx, "layer": paged["layers"][i]},
                            paged_prefill)
                new_layers.append(nl)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_f")(x)
        if return_features:
            if paged is not None:
                # the early return would silently DROP the updated arenas
                # (stale KV on every later tick, no error) — refuse until
                # a chunked-head serving path actually threads them
                raise ValueError("return_features=True cannot ride the "
                                 "paged cache path: the updated page "
                                 "arenas would be discarded")
            return x
        # the head stays a full local matmul under ring (kernel replicated,
        # rows = this device's seq chunk), so the fp32 softmax/loss math is
        # untouched; parity with GSPMD's vocab-sharded head is exact
        logits = make_dense(self.vocab_size, use_bias=False, dtype=self.dtype,
                            name="lm_head", quant=self.quant)(x)
        logits = logits.astype(jnp.float32)
        if paged is not None:
            return logits, tuple(new_layers)
        return logits


def tiny_lm(vocab_size=256, num_layers=2, d_model=64, num_heads=4,
            max_len=512, dtype=jnp.float32, attn_fn=full_attention,
            remat=False, quant="none", tp_impl="gspmd", **_):
    return TransformerLM(vocab_size=vocab_size, num_layers=num_layers,
                        d_model=d_model, num_heads=num_heads, max_len=max_len,
                        dtype=dtype, attn_fn=attn_fn, remat=remat,
                        quant=quant, tp_impl=tp_impl)
