"""Vision Transformer family (TPU-first image classifier).

Beyond the reference's torchvision-CNN catalog (reference
1.dataparallel.py:23-24): on TPU the ResNet family tops out around ~25% MFU
at CIFAR/ImageNet shapes (BASELINE.md norm/stem experiments — the conv
stack underfills the MXU), while a ViT is matmuls end to end. Same Trainer,
same data pipeline, same `--arch` UX.

Design:
* patchify = one strided Conv (the standard trick; XLA lowers it to a
  matmul over unfolded patches), learned positional embeddings, a learned
  [CLS] token read out by the head;
* reuses tpu_dist.models.transformer.Block (pre-LN, pluggable attn_fn) —
  non-causal full attention here;
* fp32 LayerNorm/softmax/logits regardless of compute dtype, matching the
  family-wide precision policy.

vit_tiny/16 etc. follow the standard depth/width/heads plans; `patch_size`
defaults suit 224px inputs — `vit_cifar` uses 4px patches so 32px inputs
give 8x8=64 tokens.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import flax.linen as nn
import jax.numpy as jnp

from tpu_dist.models.transformer import Block, full_attention


class ViT(nn.Module):
    num_classes: int = 10
    patch_size: int = 16
    num_layers: int = 12
    d_model: int = 192
    num_heads: int = 3
    dtype: jnp.dtype = jnp.float32
    attn_fn: Callable = partial(full_attention, causal=False)
    quant: str = "none"  # none | int8 | int8_wo — quantized block matmuls
                         # (ops.quant); the patch-embed conv and the tiny
                         # classifier head stay in the compute dtype
    tp_impl: str = "gspmd"  # ring = collective-matmul TP for the block
                            # projections inside shard_map over 'model'.
                            # The [CLS] token makes the token count odd, so
                            # the sequence axis cannot shard evenly: ViT
                            # maps 'ring' onto the full-token 'ring_ar'
                            # flavor (parallel.overlap) — column shards are
                            # local slices and the row-parallel reduction is
                            # the chunked ppermute ring_allreduce, so the
                            # overlap decomposition is preserved without a
                            # divisibility demand on tokens

    @nn.compact
    def __call__(self, x, train: bool = True):
        b, h, w, c = x.shape
        p = self.patch_size
        if h % p or w % p:
            raise ValueError(f"image {h}x{w} not divisible by patch {p}")
        x = nn.Conv(self.d_model, (p, p), strides=(p, p), dtype=self.dtype,
                    name="patch_embed")(x.astype(self.dtype))
        x = x.reshape(b, -1, self.d_model)               # (B, T, D)
        cls = self.param("cls", nn.initializers.zeros, (1, 1, self.d_model))
        x = jnp.concatenate([jnp.broadcast_to(cls, (b, 1, self.d_model))
                             .astype(self.dtype), x], axis=1)
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (1, x.shape[1], self.d_model))
        x = x + pos.astype(self.dtype)
        block_tp = "ring_ar" if self.tp_impl == "ring" else self.tp_impl
        for i in range(self.num_layers):
            x = Block(self.num_heads, self.dtype, self.attn_fn, self.quant,
                      block_tp, name=f"block{i}")(x, train)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_f")(x)
        logits = nn.Dense(self.num_classes, dtype=self.dtype,
                          name="head")(x[:, 0])
        return logits.astype(jnp.float32)


# standard plans (depth, width, heads); patch size overridable per call
ViTTiny = partial(ViT, num_layers=12, d_model=192, num_heads=3)
ViTSmall = partial(ViT, num_layers=12, d_model=384, num_heads=6)
ViTBase = partial(ViT, num_layers=12, d_model=768, num_heads=12)
# CIFAR-native: 4px patches -> 64 tokens from a 32px image
ViTCifar = partial(ViT, patch_size=4, num_layers=8, d_model=256, num_heads=8)
