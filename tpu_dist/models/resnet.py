"""ResNet family (reference component C2's main archs).

The reference pulls ResNets from ``torchvision.models.__dict__[arch]()``
(reference 1.dataparallel.py:23-24,97-102) and trains them on CIFAR10 (32x32
through the ImageNet stem) and ImageNet. This module provides the same family
— resnet18/34/50/101/152 with torchvision's layer plan — built TPU-first:

* NHWC layout (XLA:TPU native), channels padded to MXU-friendly multiples by
  XLA automatically;
* flax.linen with an fp32-master / configurable compute dtype split: conv and
  dense run in ``dtype`` (bf16 for the apex-AMP-equivalent variant), batch-norm
  statistics always accumulate in fp32 (SURVEY.md §7 'bf16 vs apex fp16');
* under ``jit`` over a data-sharded mesh, batch-norm batch statistics are
  computed over the *global* batch (XLA inserts the cross-device reduction),
  which is SyncBN semantics — strictly stronger than the reference's
  per-replica BN (documented capability delta);
* an optional CIFAR stem (3x3/s1, no maxpool) for the TPU-native CIFAR recipe;
  default stem matches torchvision (7x7/s2 + 3x3 maxpool) for parity.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BasicBlock(nn.Module):
    """torchvision BasicBlock: 3x3 -> 3x3 with identity shortcut (expansion 1)."""

    filters: int
    strides: Tuple[int, int] = (1, 1)
    expansion: int = 1
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides, padding=[(1, 1), (1, 1)])(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), padding=[(1, 1), (1, 1)])(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)  # zero-init last BN gamma
        if residual.shape != y.shape:
            residual = self.conv(self.filters * self.expansion, (1, 1),
                                 self.strides, name="downsample_conv")(residual)
            residual = self.norm(name="downsample_bn")(residual)
        return nn.relu(y + residual)


class Bottleneck(nn.Module):
    """torchvision Bottleneck: 1x1 -> 3x3 -> 1x1 (expansion 4).

    ``groups``/``base_width`` follow torchvision's generalization: the inner
    width is ``filters * base_width/64 * groups`` and the 3x3 conv is
    grouped — resnext50_32x4d = (32, 4), wide_resnet50_2 = (1, 128)."""

    filters: int
    strides: Tuple[int, int] = (1, 1)
    expansion: int = 4
    groups: int = 1
    base_width: int = 64
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm

    @nn.compact
    def __call__(self, x):
        residual = x
        width = int(self.filters * (self.base_width / 64.0)) * self.groups
        y = self.conv(width, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(width, (3, 3), self.strides, padding=[(1, 1), (1, 1)],
                      feature_group_count=self.groups)(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * self.expansion, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * self.expansion, (1, 1),
                                 self.strides, name="downsample_conv")(residual)
            residual = self.norm(name="downsample_bn")(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    """torchvision-plan ResNet, NHWC, fp32 BN statistics.

    stage_sizes/block follow torchvision exactly (e.g. resnet50 = Bottleneck
    [3,4,6,3]); `cifar_stem=True` swaps the 7x7/s2+maxpool stem for 3x3/s1.
    """

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32
    cifar_stem: bool = False
    stem: str = ""  # "" = cifar_stem bool decides (legacy); "imagenet" |
    # "cifar" | "s2d". "s2d" is the MLPerf-TPU space-to-depth stem
    # (VERDICT r4 #1): pad the image 3px, rearrange 2x2 spatial blocks into
    # channels ((B,38,38,3) -> (B,19,19,12)), then a 4x4/s1 VALID conv —
    # which spans exactly the function space of the 7x7/s2 pad-3 stem conv
    # (pad the 7x7 kernel to 8x8, split each tap index into (block, offset):
    # y[p,q] = sum_{a,b,u,v,c} w[2a+u,2b+v,c] x_pad[2(p+a)+u,2(q+b)+v,c] is
    # a 4x4 conv over the s2d channels (u,v,c)). Same 16x16x64 output
    # geometry into the same maxpool. The point: XLA lowers a stride-2
    # conv over 3 channels miserably (pad/space-to-batch, ~2% MXU fill);
    # the s2d form is a dense stride-1 contraction over 192 inputs.
    norm: str = "bn"  # bn = torchvision parity (SyncBN under jit);
                      # gn = GroupNorm(32): no running stats / batch coupling
                      # (identical math at any batch size or replica count)
    norm_dtype: Any = None
    # norm_dtype None = fp32 normalization OUTPUTS (torch parity: AMP keeps
    # the BN->relu->residual chain fp32). jnp.bfloat16 emits bf16 normalized
    # activations while BN/GN STATISTICS still accumulate in fp32 (flax
    # computes mean/var in f32 internally, and running stats/affine params
    # stay f32 param_dtype) — the MLPerf-TPU ResNet practice. The round-5
    # profile (tools/profile_image.py, BASELINE.md) showed the training
    # step HBM-bandwidth-bound with fp32 activation/cotangent tensors
    # between every bf16 conv; bf16 norm outputs halve that traffic.

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        ndt = self.norm_dtype or jnp.float32
        if self.norm == "gn":
            norm = partial(nn.GroupNorm, num_groups=32, epsilon=1e-5,
                           dtype=ndt)
        elif self.norm == "bn":
            norm = partial(nn.BatchNorm, use_running_average=not train,
                           momentum=0.9, epsilon=1e-5,
                           dtype=ndt)  # stats & affine always fp32
        else:
            raise ValueError(f"unknown norm {self.norm!r} (bn|gn)")

        stem = self.stem or ("cifar" if self.cifar_stem else "imagenet")
        x = x.astype(self.dtype)
        if stem == "cifar":
            x = conv(64, (3, 3), padding=[(1, 1), (1, 1)], name="conv1")(x)
            x = norm(name="bn1")(x)
            x = nn.relu(x)
        elif stem == "s2d":
            b, h, w, c = x.shape
            if h % 2 or w % 2:
                raise ValueError(f"s2d stem needs even H,W, got {h}x{w}")
            x = jnp.pad(x, ((0, 0), (3, 3), (3, 3), (0, 0)))
            hp, wp = h + 6, w + 6
            x = x.reshape(b, hp // 2, 2, wp // 2, 2, c)
            x = x.transpose(0, 1, 3, 2, 4, 5).reshape(b, hp // 2, wp // 2,
                                                      4 * c)
            x = conv(64, (4, 4), padding="VALID", name="conv1")(x)
            x = norm(name="bn1")(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        elif stem == "imagenet":
            x = conv(64, (7, 7), (2, 2), padding=[(3, 3), (3, 3)], name="conv1")(x)
            x = norm(name="bn1")(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        else:
            raise ValueError(f"unknown stem {stem!r} (imagenet|cifar|s2d)")
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(filters=64 * 2 ** i, strides=strides,
                                   conv=conv, norm=norm,
                                   name=f"layer{i + 1}_block{j}")(x)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="fc")(x)
        return x.astype(jnp.float32)


# torchvision layer plans (reference models.__dict__ factory surface)
ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=BasicBlock)
ResNet34 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=Bottleneck)
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3], block_cls=Bottleneck)
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3], block_cls=Bottleneck)
ResNeXt50_32x4d = partial(ResNet, stage_sizes=[3, 4, 6, 3],
                          block_cls=partial(Bottleneck, groups=32,
                                            base_width=4))
ResNeXt101_32x8d = partial(ResNet, stage_sizes=[3, 4, 23, 3],
                           block_cls=partial(Bottleneck, groups=32,
                                             base_width=8))
WideResNet50_2 = partial(ResNet, stage_sizes=[3, 4, 6, 3],
                         block_cls=partial(Bottleneck, base_width=128))
WideResNet101_2 = partial(ResNet, stage_sizes=[3, 4, 23, 3],
                          block_cls=partial(Bottleneck, base_width=128))
