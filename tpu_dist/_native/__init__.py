"""ctypes bindings for the native host-data-path library (csrc/).

Auto-builds with the in-tree Makefile on first import if g++ is available;
every entry point has a pure-numpy fallback, so the framework works without a
toolchain (the native path just makes the 1-core host loader faster and lets
batch assembly overlap compute by releasing the GIL during memcpy).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Tuple

import numpy as np

_LIB_PATH = os.path.join(os.path.dirname(__file__), "libtpudist.so")
_CSRC = os.path.join(os.path.dirname(__file__), "..", "..", "csrc")
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _stale() -> bool:
    """True when the .so is missing or older than any csrc/ source — a
    stale binary would dlopen but lack newer entry points."""
    if not os.path.exists(_LIB_PATH):
        return True
    built = os.path.getmtime(_LIB_PATH)
    for fn in os.listdir(_CSRC):
        if fn.endswith((".cpp", ".h")) or fn == "Makefile":
            if os.path.getmtime(os.path.join(_CSRC, fn)) > built:
                return True
    return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    if os.path.isdir(_CSRC) and _stale():
        # cross-process build lock: spawned ranks / multi-host shared FS must
        # not run `make` concurrently onto the same .so (a reader could dlopen
        # a half-written ELF and silently pin itself to the numpy fallback)
        import fcntl
        lock_path = os.path.join(_CSRC, ".build.lock")
        try:
            with open(lock_path, "w") as lock:
                fcntl.flock(lock, fcntl.LOCK_EX)
                if _stale():  # re-check under the lock
                    subprocess.run(["make", "-C", _CSRC, "-B"], check=True,
                                   capture_output=True, timeout=120)
        except Exception:
            if not os.path.exists(_LIB_PATH):
                return None  # no binary at all; else try the stale one
    if os.path.exists(_LIB_PATH):
        try:
            lib = ctypes.CDLL(_LIB_PATH)
            lib.gather_rows_u8.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_int64, ctypes.c_int64]
            lib.gather_i32.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_int64]
            try:
                # newer entry points bound separately: a stale .so (no
                # toolchain to rebuild) must keep its working gather path
                lib.decode_available.restype = ctypes.c_int
                lib.decode_jpeg_resize_crop.argtypes = [
                    ctypes.c_void_p, ctypes.c_int64, ctypes.c_int,
                    ctypes.c_int, ctypes.c_void_p]
                lib.decode_jpeg_resize_crop.restype = ctypes.c_int
            except AttributeError:
                pass
            _lib = lib
        except (OSError, AttributeError):
            _lib = None
    return _lib


def available() -> bool:
    return _load() is not None


from contextlib import contextmanager


@contextmanager
def numpy_fallback():
    """Force the pure-numpy path inside the block (benchmark/debug hook —
    tools/data_rate.py compares the two implementations with it), however
    the lazy-load cache is organized internally."""
    global _lib, _tried
    saved = (_lib, _tried)
    _lib, _tried = None, True
    try:
        yield
    finally:
        _lib, _tried = saved


def gather_batch(images: np.ndarray, labels: np.ndarray,
                 indices: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """out = (images[indices], labels[indices]) via native memcpy rows.

    Falls back to numpy fancy indexing when the library is unavailable.
    """
    lib = _load()
    idx_arr = np.asarray(indices)
    # the native path has no bounds checking (raw memcpy); route anything
    # numpy-special (negative indices, out-of-range -> IndexError) to numpy
    in_bounds = (idx_arr.size == 0 or
                 (idx_arr.min() >= 0 and idx_arr.max() < images.shape[0]))
    if lib is None or not images.flags.c_contiguous or not in_bounds:
        # int32 labels to match the native path's output dtype exactly
        return images[indices], labels[indices].astype(np.int32)
    idx = np.ascontiguousarray(idx_arr, np.int64)
    n = idx.shape[0]
    row_bytes = images.dtype.itemsize * int(np.prod(images.shape[1:]))
    out_imgs = np.empty((n,) + images.shape[1:], images.dtype)
    lib.gather_rows_u8(images.ctypes.data, idx.ctypes.data,
                       out_imgs.ctypes.data, n, row_bytes)
    lab = np.ascontiguousarray(labels, np.int32)
    out_lab = np.empty((n,), np.int32)
    lib.gather_i32(lab.ctypes.data, idx.ctypes.data, out_lab.ctypes.data, n)
    return out_imgs, out_lab


def decode_available() -> bool:
    """True when the library was built against libjpeg (csrc/decode.cpp).
    False for missing library, stale pre-decode .so, or no-libjpeg build."""
    lib = _load()
    fn = getattr(lib, "decode_available", None) if lib is not None else None
    return bool(fn and fn())


def decode_jpeg(data: bytes, size: int) -> Optional[np.ndarray]:
    """JPEG bytes -> (size, size, 3) RGB u8 via the native decoder, or None.

    Native path = libjpeg DCT-scaled decode + bilinear short-side resize to
    size*256//224 + center crop — the same framing as the PIL fallback in
    tpu_dist.data.imagefolder._decode (resampling kernels differ). Returns
    None (caller falls back to PIL) when the library/libjpeg is missing or
    the bytes fail to decode.
    """
    if not decode_available():
        return None
    lib = _load()
    out = np.empty((size, size, 3), np.uint8)
    pre_short = size * 256 // 224
    rc = lib.decode_jpeg_resize_crop(data, len(data), size, pre_short,
                                     out.ctypes.data)
    return out if rc == 0 else None
