#!/usr/bin/env python
"""Headline benchmark: CIFAR10 ResNet-50 training throughput per chip + MFU.

BASELINE.md: the reference publishes no numbers; this repo establishes the
baseline (images/sec/chip on the flagship config, scripts/7.jax_tpu.py:
ResNet-50, bf16 compute, fused on-device input pipeline, donated state).

Methodology: K training steps per dispatch (lax.scan multi-step,
tpu_dist.engine.steps.make_multi_train_step) so controller/dispatch latency
— substantial on tunneled or remote-controller links — is excluded from the
device-rate measurement; best window of several trials is reported (median
and all trials inform stderr diagnostics).

MFU accounting (VERDICT r1 #4): per-step FLOPs come from XLA's own cost
model (compiled.cost_analysis()), peak from the device kind (override with
BENCH_PEAK_TFLOPS). Set BENCH_SWEEP=1 for a stderr table over per-chip batch
sizes and both ResNet stems (the 7x7/s2+maxpool ImageNet stem shrinks 32x32
inputs to 8x8 before stage 1 and starves the MXU; `cifar_stem=True` is the
standard 3x3/s1 CIFAR variant).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "mfu",
"tflops", "flops_per_img"}. vs_baseline is vs BASELINE.json's published
number when present, else 1.0 (this run IS the baseline).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# bf16 peak TFLOP/s per chip by device kind (public spec sheets)
PEAK_TFLOPS = (
    ("v6", 918.0), ("trillium", 918.0),
    ("v5p", 459.0),
    ("v5 lite", 197.0), ("v5e", 197.0), ("v5litepod", 197.0),
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 45.0),
)


def peak_tflops_for(device) -> float | None:
    env = os.environ.get("BENCH_PEAK_TFLOPS")
    if env:
        return float(env)
    kind = getattr(device, "device_kind", "").lower()
    for key, peak in PEAK_TFLOPS:
        if key in kind:
            return peak
    return None


IMG = int(os.environ.get("BENCH_IMAGE_SIZE", "32"))       # 224 = ImageNet
ARCH = os.environ.get("BENCH_ARCH", "resnet50")
NUM_CLASSES = int(os.environ.get("BENCH_NUM_CLASSES", "10"))


def bench_ledger(kind: str, config: dict):
    """(ledger, path, goodput_acc) when BENCH_LEDGER names a JSONL path,
    else (None, None, None): the bench feeds the SAME obs.ledger event
    stream the engines write — run_start with the BENCH_* geometry, one
    'step' per timed trial with the dispatch/device phase split, run_end
    — so bench runs are queryable with tools/ledger_report.py like any
    training run. A GoodputAccumulator rides as a sink so the headline
    JSON carries the run's wall-clock partition (the 'goodput' block).
    The LM bench emits live (plus a 'compile' event for the warm
    dispatch); the image path constructs the ledger only after measure()
    returns and emits its trial records retrospectively, so its 'ts'
    stamps are end-of-run and it carries no 'compile' event."""
    path = os.environ.get("BENCH_LEDGER", "")
    if not path:
        return None, None, None
    import jax

    from tpu_dist.obs import GoodputAccumulator, Ledger, effective_peak_tflops

    eff_peak, nominal = effective_peak_tflops()
    ledger = Ledger(path)
    acc = GoodputAccumulator()
    ledger.add_sink(acc.add)
    ledger.emit("run_start", kind=kind, config=config, mesh=None,
                devices=sorted({d.device_kind for d in jax.local_devices()}),
                process_count=jax.process_count(),
                device_count=jax.device_count(),
                peak_tflops=eff_peak, peak_is_nominal=nominal)
    return ledger, path, acc


def goodput_block(acc):
    """Headline-JSON goodput block from the bench ledger's accumulator.
    None without BENCH_LEDGER (no partition without an event stream) AND
    on the image bench's retrospective path: its records are all emitted
    after measure() returns, so the timestamp span is milliseconds while
    the itemized phase seconds are real — the overrun guard below refuses
    to publish that nonsense ratio rather than hide it."""
    part = acc.finalize() if acc is not None else None
    if not part:
        return None
    if part["overrun_s"] > 0.5 * part["wall_s"]:
        return None
    return {"ratio": part["ratio"], "wall_s": part["wall_s"],
            "goodput_s": part["goodput_s"],
            "overrun_s": part["overrun_s"],
            "categories": part["categories"]}


def lm_geometry():
    """(env-derived) LM bench geometry — THE single parse of the BENCH_*
    geometry knobs, shared by lm_build and profile_lm's parse-only path so
    trace renormalization can never drift from the capture."""
    import jax

    n_chips = jax.device_count()
    return dict(
        n_chips=n_chips,
        L=int(os.environ.get("BENCH_SEQ_LEN", "2048")),
        d_model=int(os.environ.get("BENCH_D_MODEL", "1024")),
        layers=int(os.environ.get("BENCH_LAYERS", "8")),
        heads=int(os.environ.get("BENCH_HEADS", "8")),
        vocab=int(os.environ.get("BENCH_VOCAB", "32000")),
        batch=int(os.environ.get("BENCH_LM_BATCH", "8")) * n_chips,
        attn_kind=os.environ.get("BENCH_ATTN", "flash"),
        k=int(os.environ.get("BENCH_STEPS_PER_WINDOW",
                             os.environ.get("BENCH_STEPS", "20"))),
        loss_chunk=int(os.environ.get("BENCH_LOSS_CHUNK", "0")),
        quant=os.environ.get("BENCH_QUANT") or "none",
        tp_impl=os.environ.get("BENCH_TP_IMPL") or "gspmd",
        tp=int(os.environ.get("BENCH_TP_DEGREE", "2")),
        grad_bucket_mb=float(os.environ.get("BENCH_GRAD_BUCKET_MB", "0")))


_PLAN_BLOCK = None   # set by apply_bench_plan; rides in every headline JSON


def apply_bench_plan():
    """BENCH_PLAN=<plan JSON path>: drive this bench run from a tuned step
    plan (tools/tune.py output, selected for this device kind) instead of
    hand-set BENCH_* knobs. The plan's knobs are written INTO the BENCH_*
    env (plan wins — that is the point) so the one geometry parse
    (lm_geometry) stays the single source; the Pallas block sizes / fused
    switch apply via plan.compile.activate_plan. The headline JSON gains a
    'plan' block ({source, hash, knobs}) and tools/bench_track.py tracks
    plan-tagged headlines independently. Returns the block (or None)."""
    global _PLAN_BLOCK
    spec = os.environ.get("BENCH_PLAN", "")
    if not spec:
        return None
    import jax

    from tpu_dist.models.registry import model_kind
    from tpu_dist.plan.compile import activate_plan
    from tpu_dist.plan.ir import (load_plan_file, plan_for_device,
                                  plan_hash, plan_knob_summary)

    kind = getattr(jax.devices()[0], "device_kind", "unknown")
    plan = plan_for_device(load_plan_file(spec), kind)
    engine = "lm" if model_kind(ARCH) == "lm" else "image"
    if plan.engine != engine:
        raise SystemExit(f"BENCH_PLAN={spec}: plan engine {plan.engine!r} "
                         f"does not drive BENCH_ARCH={ARCH} ({engine})")
    # the bench has no knob for these plan dimensions; silently dropping
    # them while stamping the FULL plan hash would make bench_track gate
    # a [plan:<hash>] series on numbers the plan did not produce — refuse
    unmappable = {k: v for k, v in (
        ("precision", plan.precision), ("health", plan.health),
        ("grad_accum_steps", plan.grad_accum_steps),
        ("window", plan.window if plan.window == "stacked" else "none"),
    ) if v not in ("fp32", "record", 1, "none")}
    if unmappable:
        raise SystemExit(
            f"BENCH_PLAN={spec}: plan {sorted(unmappable)} have no BENCH_* "
            "knob — the headline would carry a plan hash the run did not "
            "execute; re-emit the plan without them for benching")
    os.environ["BENCH_QUANT"] = plan.quant
    os.environ["BENCH_TP_IMPL"] = plan.tp_impl
    os.environ["BENCH_GRAD_BUCKET_MB"] = str(plan.grad_bucket_mb)
    if engine == "lm":
        os.environ["BENCH_LOSS_CHUNK"] = str(plan.loss_chunk)
    # plan wins over PRE-EXPORTED knobs too: a stale BENCH_STEPS_PER_WINDOW
    # or BENCH_FUSED_QUANT from an earlier sweep must never leak into a
    # plan-tagged headline (bench_track gates the [plan:<hash>] series on
    # these numbers). window='none' / fused_quant='auto' mean "the bench's
    # own default / the auto dispatch", so the env overrides are CLEARED
    if plan.window != "none":
        os.environ["BENCH_STEPS_PER_WINDOW"] = str(plan.steps_per_dispatch)
    else:
        os.environ.pop("BENCH_STEPS_PER_WINDOW", None)
        os.environ.pop("BENCH_STEPS", None)
    if plan.fused_quant != "auto":
        os.environ["BENCH_FUSED_QUANT"] = (
            "1" if plan.fused_quant == "on" else "0")
    else:
        os.environ.pop("BENCH_FUSED_QUANT", None)
    activate_plan(plan)
    _PLAN_BLOCK = {"source": spec, "hash": plan_hash(plan),
                   "device_kind": kind, "knobs": plan_knob_summary(plan)}
    print(f"bench plan: {_PLAN_BLOCK['hash']} from {spec} "
          f"(device {kind}): {_PLAN_BLOCK['knobs']}", file=sys.stderr)
    return _PLAN_BLOCK


def apply_fused_quant_knob():
    """BENCH_FUSED_QUANT=1/0 forces the fused Pallas int8 kernel on/off
    (ops.quant.set_fused_quant; unset = auto: fused on TPU). Must run
    BEFORE any step function is built — the dispatch is trace-time static.
    Returns the active state for the config block."""
    knob = os.environ.get("BENCH_FUSED_QUANT", "")
    from tpu_dist.ops.quant import fused_quant_active, set_fused_quant
    if knob != "":
        set_fused_quant(knob == "1")
    return fused_quant_active()


def prefetch_enabled() -> bool:
    """BENCH_PREFETCH=1: stream each trial's batch host->device through
    data.loader.DevicePrefetcher instead of pre-placing it in HBM, so the
    step records carry a MEASURED data_s (the consumer's queue wait —
    ~0 when staging overlaps the previous trial's compute) and the
    headline JSON a 'prefetch' overlap block."""
    return os.environ.get("BENCH_PREFETCH") == "1"



def health_block(metrics, k: int) -> dict:
    """Headline-JSON numerical-health block from the fused step probes
    (obs.health riding the window's metric sums) — shared by both benches
    so the two JSON schemas cannot drift."""
    import jax

    # distlint: disable=DL002 -- bench health gate: deliberate drain to act on probe values
    hm = jax.device_get({kk: metrics[kk] for kk in
                         ("grad_norm", "nonfinite_count", "update_norm")})
    return {"nonfinite_leaves": float(hm["nonfinite_count"]),
            "grad_norm_per_step": round(float(hm["grad_norm"]) / k, 4),
            "update_norm_per_step": round(float(hm["update_norm"]) / k, 4)}


def lm_build():
    """THE windowed-LM-step builder shared by lm_bench and
    tools/profile_lm.py (the profiler must capture the SAME program the
    bench times — a hand-copied setup drifts; ADVICE/code-review r5).
    Reads the BENCH_* env knobs (lm_geometry) and returns a dict with the
    compiled-input pieces plus the geometry the callers report."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_dist.engine.lm_steps import make_lm_indexed_multi_train_step
    from tpu_dist.engine.state import TrainState
    from tpu_dist.models.transformer import TransformerLM, full_attention
    from tpu_dist.ops import make_optimizer
    from tpu_dist.parallel.mesh import make_mesh, replicated

    g = lm_geometry()
    n_chips, L, d_model = g["n_chips"], g["L"], g["d_model"]
    layers, heads, vocab = g["layers"], g["heads"], g["vocab"]
    batch, attn_kind, k = g["batch"], g["attn_kind"], g["k"]
    loss_chunk = g["loss_chunk"]
    from tpu_dist.ops.quant import validate_quant
    quant = validate_quant(g["quant"])
    from tpu_dist.parallel.overlap import validate_tp_impl
    tp_impl = validate_tp_impl(g["tp_impl"])
    grad_bucket_mb = g["grad_bucket_mb"]
    if tp_impl == "ring" and grad_bucket_mb > 0:
        raise SystemExit("BENCH_TP_IMPL=ring and BENCH_GRAD_BUCKET_MB are "
                         "separate overlap paths (ring TP vs dp bucketed "
                         "sync); set one per run so the headline is "
                         "attributable")

    if attn_kind == "flash":
        from tpu_dist.ops.flash_attention import flash_attention_fn
        attn_fn = flash_attention_fn()
    elif attn_kind == "blockwise":
        from tpu_dist.ops.flash_attention import blockwise_attention_fn
        attn_fn = blockwise_attention_fn(512)
    else:
        attn_fn = full_attention
    if tp_impl == "ring":
        tp = g["tp"]
        if n_chips % tp or heads % tp or L % tp:
            raise SystemExit(
                f"BENCH_TP_IMPL=ring needs BENCH_TP_DEGREE ({tp}) dividing "
                f"the chip count ({n_chips}), BENCH_HEADS ({heads}) and "
                f"BENCH_SEQ_LEN ({L})")
        mesh = make_mesh((-1, tp), ("data", "model"))
    else:
        mesh = make_mesh()
    model = TransformerLM(
        vocab_size=vocab, num_layers=layers, d_model=d_model,
        num_heads=heads, max_len=L, dtype=jnp.bfloat16, attn_fn=attn_fn,
        remat=os.environ.get("BENCH_REMAT") == "1", quant=quant)
    params = model.init({"params": jax.random.PRNGKey(0)},
                        np.zeros((1, L), np.int32), train=False)["params"]
    opt = os.environ.get("BENCH_OPTIMIZER", "sgd")
    if opt == "fused_adamw":  # Pallas single-pass update (ops.pallas_adamw)
        from tpu_dist.ops.pallas_adamw import FusedAdamW
        tx = FusedAdamW(lambda s: 1e-3,
                        interpret=jax.default_backend() != "tpu")
    elif opt == "adamw":
        tx = make_optimizer(1e-3, weight_decay=0.1, kind="adamw",
                            schedule=lambda s: 1e-3)
    elif opt == "sgd":
        tx = make_optimizer(1e-3, 0.9, 0.0, steps_per_epoch=10 ** 6)
    else:
        raise SystemExit(f"BENCH_OPTIMIZER={opt}: sgd|adamw|fused_adamw")
    state = jax.device_put(TrainState.create(params, {}, tx),
                           replicated(mesh))
    if tp_impl == "ring":
        # ring collective-matmul TP (parallel.overlap): K-step windows scan
        # inside the explicit shard_map program; params stay replicated
        from tpu_dist.engine.lm_steps import (
            _lm_tp_ring_step_fn, make_lm_explicit_indexed_multi_train_step)
        ring_step = _lm_tp_ring_step_fn(
            model.clone(tp_impl="ring"), tx, 0.01, "data", "model",
            mesh.shape["model"], loss_chunk=loss_chunk)
        window = make_lm_explicit_indexed_multi_train_step(ring_step, mesh)
    elif grad_bucket_mb > 0:
        from tpu_dist.engine.lm_steps import (
            _lm_explicit_dp_step_fn, make_lm_explicit_indexed_multi_train_step)
        dp_step = _lm_explicit_dp_step_fn(
            model, tx, 0.01, "data", mesh.shape["data"], grad_bucket_mb,
            loss_chunk=loss_chunk)
        window = make_lm_explicit_indexed_multi_train_step(dp_step, mesh)
    else:
        window = make_lm_indexed_multi_train_step(model, tx, mesh,
                                                  loss_chunk=loss_chunk)

    rng = np.random.default_rng(0)
    rows = rng.integers(0, vocab, (batch, L + 1)).astype(np.int32)
    rows_dev = jax.device_put(rows, replicated(mesh))
    idx = np.tile(np.arange(batch, dtype=np.int32), (k, 1))
    idx_dev = jax.device_put(idx, NamedSharding(mesh, P(None, "data")))
    key = jax.random.PRNGKey(1)
    return dict(window=window, state=state, rows_dev=rows_dev,
                idx_dev=idx_dev, key=key, params=params, mesh=mesh,
                rows_host=rows, idx_host=idx,
                n_chips=n_chips, L=L, d_model=d_model, layers=layers,
                batch=batch, k=k, attn_kind=attn_kind,
                loss_chunk=loss_chunk, quant=quant, tp_impl=tp_impl,
                grad_bucket_mb=grad_bucket_mb)


def lm_bench():
    """BENCH_ARCH=transformer_lm: tokens/sec/chip + MFU for the LM engine.

    Drives the SAME windowed HBM-resident path LMTrainer trains with
    (make_lm_indexed_multi_train_step): K optimizer steps per dispatch over
    device-resident rows, bf16 compute, flash attention. Knobs:
    BENCH_SEQ_LEN (2048), BENCH_D_MODEL (1024), BENCH_LAYERS (8),
    BENCH_HEADS (8), BENCH_VOCAB (32000), BENCH_LM_BATCH per chip (8),
    BENCH_ATTN full|blockwise|flash (flash), BENCH_REMAT=1,
    BENCH_OPTIMIZER sgd|adamw|fused_adamw, BENCH_LOSS_CHUNK,
    BENCH_FUSED_QUANT 1|0 (force the fused Pallas int8 kernel on/off;
    unset = auto), BENCH_PREFETCH=1 (stream trial batches host->device
    through data.loader.DevicePrefetcher — data_s becomes measured).
    Completion is forced with a device_get readback (block_until_ready does
    not reliably block across tunneled controllers); the ~0.1s readback is
    amortized over the multi-second window.
    """
    import jax
    from tpu_dist.utils.mfu import lm_flops_per_token, peak_tflops_for

    if ARCH != "transformer_lm":
        raise SystemExit(
            f"BENCH_ARCH={ARCH}: the LM bench drives the dense "
            "TransformerLM only (its analytical MFU accounting assumes "
            "dense); use BENCH_ARCH=transformer_lm with BENCH_* geometry "
            "knobs")

    if os.environ.get("BENCH_FUSED_QUANT", "") != "" \
            and (os.environ.get("BENCH_QUANT") or "none") != "int8":
        # same refuse-rather-than-mislead rule as the conv-arch guard:
        # forcing the fused kernel with no int8 matmuls in the program
        # would publish a plain bf16 number under a fused-int8 intent
        raise SystemExit(
            "BENCH_FUSED_QUANT only means something with BENCH_QUANT=int8 "
            f"(got BENCH_QUANT={os.environ.get('BENCH_QUANT') or 'none'}); "
            "unset it or set BENCH_QUANT=int8")
    fused_quant = apply_fused_quant_knob()  # BEFORE lm_build traces steps
    b = lm_build()
    window, state = b["window"], b["state"]
    rows_dev, idx_dev, key = b["rows_dev"], b["idx_dev"], b["key"]
    n_chips, L, batch, k = b["n_chips"], b["L"], b["batch"], b["k"]
    layers, d_model = b["layers"], b["d_model"]
    attn_kind, loss_chunk, quant = b["attn_kind"], b["loss_chunk"], b["quant"]
    tp_impl, grad_bucket_mb = b["tp_impl"], b["grad_bucket_mb"]
    trials = int(os.environ.get("BENCH_TRIALS", "3"))
    prefetcher = None
    if prefetch_enabled():
        # stream each trial's (rows, idx) host->device on the prefetcher's
        # producer thread; the consumer wait IS the step record's data_s
        from jax.sharding import NamedSharding, PartitionSpec as P
        from tpu_dist.data.loader import DevicePrefetcher
        from tpu_dist.parallel.mesh import replicated
        mesh = b["mesh"]
        idx_sh = NamedSharding(mesh, P(None, "data"))

        def stage(batch_pair):
            r, ix = batch_pair
            return (jax.device_put(r, replicated(mesh)),
                    jax.device_put(ix, idx_sh))
        prefetcher = DevicePrefetcher(
            ((b["rows_host"], b["idx_host"]) for _ in range(trials)),
            put=stage)
        trial_batches = iter(prefetcher)

    # analytical model FLOPs (tpu_dist.utils.mfu.lm_flops_per_token; XLA's
    # cost model undercounts scan bodies and cannot cost Pallas kernels)
    flops_per_token = lm_flops_per_token(b["params"], layers, L, d_model)
    ledger, ledger_path, goodput_acc = bench_ledger(
        "bench_lm", {**lm_geometry(),
                     "fused_quant": fused_quant and quant == "int8",
                     "prefetch": prefetcher is not None})
    t_warm = time.perf_counter()
    state, m = window(state, rows_dev, idx_dev, key)           # compile+warm
    jax.device_get(m)
    if ledger:
        ledger.emit("compile", program="window_step",
                    seconds=round(time.perf_counter() - t_warm, 3))
    # probe AFTER the warm dispatch (telemetry.program_stats contract —
    # the AOT lower does not seed jit's dispatch cache, so probing first
    # would compile the window twice); one lower yields the cost-model
    # cross-check AND the HLO for cost attribution when a ledger rides
    from tpu_dist.utils.telemetry import program_stats
    st = program_stats(window, state, rows_dev, idx_dev, key,
                       with_hlo=bool(ledger))
    xla_flops = st["flops"]
    if xla_flops:
        print(f"xla cost model (diagnostic only): "
              f"{xla_flops / (batch * L / n_chips) / 1e6:.2f} MFLOP/token vs "
              f"analytical {flops_per_token / 1e6:.2f}", file=sys.stderr)
    else:
        print("xla cost model unavailable on this backend (cross-check "
              "and ledger cost attribution skipped)", file=sys.stderr)
    if ledger and st.get("hlo"):
        from tpu_dist.obs.attr import emit_cost_model
        emit_cost_model(ledger, "window_step", st["hlo"],
                        xla_flops=xla_flops)
    peak = peak_tflops_for(jax.devices()[0])
    rates, phases = [], []
    for i in range(trials):
        t0 = time.perf_counter()
        if prefetcher is not None:
            rows_dev, idx_dev = next(trial_batches)
        data_s = time.perf_counter() - t0
        state, m = window(state, rows_dev, idx_dev, key)
        disp_s = time.perf_counter() - t0 - data_s
        jax.device_get(m)  # forces completion through the tunnel
        dt = time.perf_counter() - t0
        rates.append(k * batch * L / dt)
        phases.append({"data_s": round(data_s, 6),
                       "dispatch_s": round(disp_s, 6),
                       "device_s": round(dt - data_s - disp_s, 6)})
        if ledger:
            # ledger MFU uses the engines' nominal-peak fallback (non-null
            # on CPU); the headline JSON's mfu stays real-peak-only
            from tpu_dist.obs import effective_peak_tflops
            t_tf = rates[-1] / n_chips * flops_per_token / 1e12
            ledger.emit("step", step=i, loss=None,
                        throughput=round(rates[-1] / n_chips, 1),
                        unit="tok/s/chip",
                        mfu=t_tf / effective_peak_tflops()[0],
                        steps_in_dispatch=k,
                        data_s=phases[-1]["data_s"],
                        dispatch_s=phases[-1]["dispatch_s"],
                        device_s=phases[-1]["device_s"],
                        comm_s=None, fused=fused_quant and quant == "int8")
    best = max(rates)
    best_phases = phases[rates.index(best)]
    prefetch_stats = None
    if prefetcher is not None:
        prefetcher.close()
        prefetch_stats = prefetcher.stats()
    # the headline carries the last trial's numerical-health block
    health = health_block(m, k)
    tok_chip = best / n_chips
    tflops = tok_chip * flops_per_token / 1e12
    mfu = tflops / peak if peak else None
    if ledger:
        ledger.emit("run_end", steps=trials * k,
                    seconds=round(time.perf_counter() - t_warm, 3))
        ledger.close()
    print(f"lm {layers}L/d{d_model} L={L} b/chip={batch // n_chips} "
          f"attn={attn_kind}"
          + (f" loss_chunk={loss_chunk}" if loss_chunk else "")
          + (f" quant={quant}" if quant != "none" else "")
          + (f" tp_impl={tp_impl}" if tp_impl != "gspmd" else "")
          + (f" grad_bucket_mb={grad_bucket_mb:g}" if grad_bucket_mb else "")
          + f": {tok_chip:,.0f} tok/s/chip, trials "
          f"{[round(r / n_chips) for r in rates]}"
          + (f", {tflops:.1f} TFLOP/s/chip" if tflops else "")
          + (f", MFU {mfu * 100:.1f}% of {peak} TF peak (bf16 peak; the "
             "int8 MXU path doubles it)" if mfu and quant == "int8" else
             f", MFU {mfu * 100:.1f}% of {peak} TF peak" if mfu else ""),
          file=sys.stderr)
    # BENCH_QUANT / BENCH_TP_IMPL publish their OWN metric names: variants
    # ride alongside the bf16 GSPMD headline, never replacing it (the
    # headline's name — and its baseline comparison — must stay
    # like-for-like), and the config block pins tp_impl/grad_bucket_mb so
    # two runs are never silently cross-compared
    quant_tag = f"_{quant}" if quant != "none" else ""
    impl_tag = (f"_{tp_impl}" if tp_impl != "gspmd" else
                "_bucketed" if grad_bucket_mb else "")
    print(json.dumps({
        "metric": f"lm_{layers}l_d{d_model}_seq{L}{quant_tag}{impl_tag}"
                  "_tokens_per_sec_per_chip",
        "value": round(tok_chip, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": 1.0,
        "config": {"tp_impl": tp_impl, "grad_bucket_mb": grad_bucket_mb,
                   "quant": quant, "attn": attn_kind,
                   "loss_chunk": loss_chunk,
                   "fused_quant": fused_quant and quant == "int8",
                   "prefetch": prefetcher is not None,
                   "tp_degree": (b["mesh"].shape["model"]
                                 if tp_impl == "ring" else 1)},
        "mfu": round(mfu, 4) if mfu else None,
        "tflops": round(tflops, 2) if tflops else None,
        "phases": best_phases,
        "prefetch": prefetch_stats,
        "health": health,
        "goodput": goodput_block(goodput_acc),
        "plan": _PLAN_BLOCK,
        "ledger": ledger_path,
    }))


def build(model_kwargs, batch, k):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_dist.data import make_transform
    from tpu_dist.data.datasets import CIFAR10_MEAN, CIFAR10_STD
    from tpu_dist.engine.state import TrainState, init_model
    from tpu_dist.engine.steps import make_multi_train_step
    from tpu_dist.models import create_model
    from tpu_dist.ops import make_optimizer
    from tpu_dist.parallel.mesh import make_mesh, replicated

    mesh = make_mesh()
    model = create_model(ARCH, num_classes=NUM_CLASSES, dtype=jnp.bfloat16,
                         **model_kwargs)
    params, batch_stats = init_model(model, jax.random.PRNGKey(0), (2, IMG, IMG, 3))
    tx = make_optimizer(0.1, 0.9, 1e-4, steps_per_epoch=100)
    # distlint: disable=DL008 -- one-time state replication at bench setup, not a per-step upload
    state = jax.device_put(TrainState.create(params, batch_stats, tx),
                           replicated(mesh))
    transform = make_transform(CIFAR10_MEAN, CIFAR10_STD, dtype=jnp.bfloat16)
    step = make_multi_train_step(model, tx, transform, mesh)

    from tpu_dist.engine.steps import make_train_step
    single = make_train_step(model, tx, transform, mesh, donate=False)

    rng = np.random.default_rng(0)
    images = rng.integers(0, 255, (k, batch, IMG, IMG, 3)).astype(np.uint8)
    labels = rng.integers(0, NUM_CLASSES, (k, batch)).astype(np.int32)
    sh_img = NamedSharding(mesh, P(None, "data"))
    # distlint: disable=DL008 -- HBM-resident bench design: the whole K-step window is pre-placed before timing (BENCH_PREFETCH=1 is the streamed mode)
    images_dev = jax.device_put(images, sh_img)
    # distlint: disable=DL008 -- HBM-resident bench design: pre-placed window (see images_dev)
    labels_dev = jax.device_put(labels, sh_img)
    return (step, single, state, images_dev, labels_dev,
            (images, labels), sh_img)


def flops_per_step(single, state, images, labels, key,
                   with_hlo: bool = False) -> dict:
    """One training step's {'flops', 'hlo'} from the SINGLE-step program
    (the scan flavor's cost analysis counts its body only once, so it
    can't be trusted for per-step math; `single` is never dispatched, so
    its AOT compile is the only one it pays). ``with_hlo`` additionally
    returns the optimized HLO for cost attribution (obs.attr)."""
    from tpu_dist.utils.telemetry import program_stats

    st = program_stats(single, state, images[0], labels[0], key,
                       with_hlo=with_hlo)
    if st["flops"] is None:
        print("cost_analysis unavailable", file=sys.stderr)
    return st


def measure(model_kwargs, per_chip_batch, k, trials, with_hlo=False):
    import jax

    n_chips = jax.device_count()
    batch = per_chip_batch * n_chips
    (step, single, state, images, labels,
     host_batch, sh_img) = build(model_kwargs, batch, k)
    key = jax.random.PRNGKey(0)
    # with_hlo only on the headline run: the sweep discards everything
    # past the rate, and the optimized-HLO text can run to megabytes
    st = flops_per_step(single, state, images, labels, key,
                        with_hlo=with_hlo)
    step_flops = st["flops"]

    # warmup: compile + one full window
    state, metrics = step(state, images, labels, key)
    # distlint: disable=DL002 -- compile+warm barrier before the timed window
    jax.block_until_ready(metrics)

    prefetcher = None
    if prefetch_enabled():
        # per-trial host->device staging on the producer thread: data_s
        # below becomes a measured queue wait instead of the synthetic 0.0
        from tpu_dist.data.loader import DevicePrefetcher

        def stage(pair):
            return (jax.device_put(pair[0], sh_img),
                    jax.device_put(pair[1], sh_img))
        prefetcher = DevicePrefetcher(
            (host_batch for _ in range(trials)), put=stage)
        trial_batches = iter(prefetcher)

    rates, phases = [], []
    for _ in range(trials):
        t0 = time.perf_counter()
        if prefetcher is not None:
            images, labels = next(trial_batches)
        data_s = time.perf_counter() - t0
        state, metrics = step(state, images, labels, key)
        disp_s = time.perf_counter() - t0 - data_s
        # distlint: disable=DL002 -- the timed measurement barrier - benches measure the sync
        jax.block_until_ready(metrics)
        dt = time.perf_counter() - t0
        rates.append(batch * k / dt)
        phases.append({"data_s": round(data_s, 6),
                       "dispatch_s": round(disp_s, 6),
                       "device_s": round(dt - data_s - disp_s, 6)})
    prefetch_stats = None
    if prefetcher is not None:
        prefetcher.close()
        prefetch_stats = prefetcher.stats()
    best_phases = phases[rates.index(max(rates))]
    return (max(rates), sorted(rates), step_flops, batch, best_phases,
            list(zip(rates, phases)),  # trials in timing order (ledger)
            health_block(metrics, k), st.get("hlo"), prefetch_stats)


def main():
    import jax
    jax.config.update("jax_compilation_cache_dir",
                      os.environ.get("JAX_CACHE_DIR", "/tmp/jaxcache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)

    # a tuned plan (BENCH_PLAN) rewrites the BENCH_* knobs BEFORE the
    # guards/geometry below read them
    apply_bench_plan()

    from tpu_dist.models.registry import model_kind
    if model_kind(ARCH) == "lm":
        lm_bench()
        return

    if os.environ.get("BENCH_QUANT", "none") not in ("", "none") \
            or os.environ.get("BENCH_FUSED_QUANT", "") != "":
        # refuse rather than silently publish a bf16 number under the
        # user's int8 intent: the conv models have no quantized path
        raise SystemExit(
            "BENCH_QUANT/BENCH_FUSED_QUANT apply to the LM "
            f"bench only (BENCH_ARCH=transformer_lm); BENCH_ARCH={ARCH} "
            "has no quantized path")
    if os.environ.get("BENCH_TP_IMPL", "gspmd") not in ("", "gspmd") \
            or float(os.environ.get("BENCH_GRAD_BUCKET_MB", "0") or 0) > 0:
        # same guard pattern: the overlap knobs drive the LM bench; the
        # image bench's jit window has no explicit collectives to decompose
        raise SystemExit(
            "BENCH_TP_IMPL/BENCH_GRAD_BUCKET_MB apply to the LM bench only "
            f"(BENCH_ARCH=transformer_lm); BENCH_ARCH={ARCH} rides the "
            "compiler-scheduled path")

    n_chips = jax.device_count()
    per_chip_batch = int(os.environ.get("BENCH_PER_CHIP_BATCH", "1024"))
    # BENCH_STEPS kept as an alias (earlier recipe name). K=160 amortizes
    # dispatch latency to <8% of the window (device-side rate ~148k img/s/chip
    # per the XLA trace; measured wall rate 137k at K=160 vs 95k at K=20).
    k = int(os.environ.get("BENCH_STEPS_PER_WINDOW",
                           os.environ.get("BENCH_STEPS", "160")))
    trials = int(os.environ.get("BENCH_TRIALS", "4"))
    peak = peak_tflops_for(jax.devices()[0])

    def report(tag, best, rates, step_flops, batch):
        ips_chip = best / n_chips
        tflops = mfu = fpi = None
        if step_flops:
            # cost_analysis describes the per-device SPMD program, which
            # processes batch/n_chips images per step
            fpi = step_flops / (batch / n_chips)
            tflops = ips_chip * fpi / 1e12
            mfu = tflops / peak if peak else None
        print(f"{tag}: {ips_chip:,.0f} img/s/chip, trials "
              f"{[round(r / n_chips) for r in rates]}"
              + (f", {fpi / 1e9:.3f} GFLOP/img, {tflops:.1f} TFLOP/s/chip"
                 if fpi else "")
              + (f", MFU {mfu * 100:.1f}% of {peak} TF peak" if mfu else ""),
              file=sys.stderr)
        return ips_chip, tflops, mfu, fpi

    if os.environ.get("BENCH_SWEEP") == "1":
        if not ARCH.startswith("resnet"):
            raise SystemExit("BENCH_SWEEP sweeps ResNet stems; unset "
                             f"BENCH_ARCH={ARCH}")
        for stem in (False, True):
            for pcb in (1024, 2048, 4096):
                try:
                    res = measure({"cifar_stem": stem}, pcb,
                                  min(k, 40), max(2, trials // 2))
                    report(f"sweep stem={'cifar' if stem else 'imagenet'} "
                           f"b/chip={pcb} k={min(k, 40)}", *res[:4])
                except Exception as e:
                    print(f"sweep stem={stem} b={pcb}: failed {e!r}",
                          file=sys.stderr)

    # Round-5 headline defaults (BASELINE.md round-5): bf16 normalized
    # activations (fp32 BN statistics — the MLPerf-TPU ResNet practice) and
    # the space-to-depth stem. Both are convergence-parity-verified
    # (tools/convergence.py --norm-dtype bf16 --stem s2d) and the s2d stem
    # spans exactly the 7x7/s2 function space
    # (tests/test_models.py::test_s2d_stem_spans_imagenet_stem). Opt back
    # into the round-1-4 torch-parity config with BENCH_NORM_DTYPE=fp32
    # BENCH_STEM=imagenet.
    kwargs = {}
    norm_dtype = os.environ.get("BENCH_NORM_DTYPE", "bf16")
    if norm_dtype not in ("bf16", "fp32"):
        raise SystemExit(f"BENCH_NORM_DTYPE={norm_dtype}: use bf16 "
                         "(fp32-stats/bf16-activations) or fp32")
    if norm_dtype == "bf16":
        import jax.numpy as jnp
        kwargs["norm_dtype"] = jnp.bfloat16
    if os.environ.get("BENCH_CIFAR_STEM") == "1":
        kwargs["cifar_stem"] = True  # composes with norm_dtype
        default_model = False
    else:
        stem = os.environ.get("BENCH_STEM", "s2d")
        kwargs["stem"] = stem  # imagenet|cifar|s2d (models/resnet.py)
        default_model = stem == "s2d" and norm_dtype == "bf16"
    if os.environ.get("BENCH_NORM") and os.environ["BENCH_NORM"] != "bn":
        kwargs["norm"] = os.environ["BENCH_NORM"]  # bn/empty = default
        default_model = False
    if not ARCH.startswith(("resnet", "resnext", "wide_resnet")):
        # raise only on knobs that actually ASK for something non-default
        # (BENCH_NORM=bn / BENCH_NORM_DTYPE=bf16-by-default / unset are
        # no-ops and stay accepted for wrapper-script compatibility)
        asked = (os.environ.get("BENCH_CIFAR_STEM") == "1"
                 or os.environ.get("BENCH_NORM") not in (None, "", "bn")
                 or os.environ.get("BENCH_NORM_DTYPE") == "bf16"
                 or os.environ.get("BENCH_STEM") not in (None, "", "imagenet"))
        if asked:
            raise SystemExit(
                "BENCH_CIFAR_STEM/BENCH_NORM/BENCH_NORM_DTYPE/BENCH_STEM are "
                f"ResNet knobs; unset them with BENCH_ARCH={ARCH}")
        kwargs = {}
        default_model = True
    (best, rates, window_flops, batch, phases, trial_data, health,
     step_hlo, prefetch_stats) = measure(
         kwargs, per_chip_batch, k, trials,
         with_hlo=bool(os.environ.get("BENCH_LEDGER")))
    ips_per_chip, tflops, mfu, fpi = report("headline", best, rates,
                                            window_flops, batch)
    ledger, ledger_path, goodput_acc = bench_ledger(
        "bench_image", {"arch": ARCH, "img": IMG, "classes": NUM_CLASSES,
                        "per_chip_batch": per_chip_batch, "k": k,
                        "prefetch": prefetch_stats is not None,
                        **{kk: getattr(v, "__name__", str(v))
                           for kk, v in kwargs.items()}})
    if ledger:
        # one 'step' per timed trial, in timing order — emitted
        # retrospectively (measure() ran before the ledger existed); MFU
        # vs the engines' effective peak (nominal fallback keeps it
        # non-null on CPU — run_start carries peak_is_nominal)
        from tpu_dist.obs import effective_peak_tflops
        eff_peak = effective_peak_tflops()[0]
        if step_hlo:
            # cost attribution of the single-step program (obs.attr) —
            # the ledger_report roofline reads it back beside the trials
            from tpu_dist.obs.attr import emit_cost_model
            emit_cost_model(ledger, "train_step", step_hlo,
                            xla_flops=window_flops)
        for i, (rate, ph) in enumerate(trial_data):
            r_chip = rate / n_chips
            tf = r_chip * fpi / 1e12 if fpi else None
            ledger.emit("step", step=i, loss=None,
                        throughput=round(r_chip, 1), unit="img/s/chip",
                        mfu=round(tf / eff_peak, 6) if tf else None,
                        steps_in_dispatch=k, data_s=ph["data_s"],
                        dispatch_s=ph["dispatch_s"],
                        device_s=ph["device_s"], comm_s=None)
        ledger.emit("run_end", steps=trials * k,
                    seconds=round(sum(batch * k / r for r in rates), 3))
        ledger.close()

    default_workload = (IMG == 32 and NUM_CLASSES == 10 and default_model
                        and ARCH == "resnet50")
    if not default_workload:
        # a different image size/class count/model variant is a different
        # workload: name it and do NOT compare against the CIFAR baseline
        variant = "_".join(
            f"{k}-{getattr(v, '__name__', v)}"
            for k, v in sorted(kwargs.items()))
        print(json.dumps({
            "metric": f"{ARCH}_{IMG}px"
                      + (f"_{variant}" if variant else "")
                      + "_images_per_sec_per_chip",
            "value": round(ips_per_chip, 1),
            "unit": "images/sec/chip",
            "vs_baseline": 1.0,
            "mfu": round(mfu, 4) if mfu else None,
            "tflops": round(tflops, 2) if tflops else None,
            "flops_per_img": round(fpi) if fpi else None,
            "phases": phases,
            "prefetch": prefetch_stats,
            "health": health,
            "goodput": goodput_block(goodput_acc),
            "plan": _PLAN_BLOCK,
            "ledger": ledger_path,
        }))
        return

    baseline = None
    try:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BASELINE.json")) as f:
            baseline = json.load(f).get("published", {}).get(
                "cifar10_resnet50_images_per_sec_per_chip")
    except Exception:
        pass
    vs = ips_per_chip / baseline if baseline else 1.0

    # like-for-like tagging: BASELINE.json's published number is the ROUND-1
    # config (7x7 imagenet stem, fp32 norm outputs); today's default is
    # s2d+bf16-norm. The ratio is still published (it tracks the headline's
    # drift across rounds), but both configs ride the JSON so the comparison
    # is never silently cross-config.
    active_cfg = (f"stem={kwargs.get('stem', 'imagenet')}"
                  f",norm_dtype={norm_dtype}")
    baseline_cfg = "stem=imagenet,norm_dtype=fp32"
    print(json.dumps({
        "metric": "cifar10_resnet50_images_per_sec_per_chip",
        "value": round(ips_per_chip, 1),
        "unit": "images/sec/chip",
        "config": active_cfg,
        "vs_baseline": round(vs, 3),
        "vs_baseline_config": baseline_cfg if baseline else None,
        "mfu": round(mfu, 4) if mfu else None,
        "tflops": round(tflops, 2) if tflops else None,
        "flops_per_img": round(fpi) if fpi else None,
        "phases": phases,
        "prefetch": prefetch_stats,
        "health": health,
        "goodput": goodput_block(goodput_acc),
        "plan": _PLAN_BLOCK,
        "ledger": ledger_path,
    }))


if __name__ == "__main__":
    main()
