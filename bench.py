#!/usr/bin/env python
"""Headline benchmark: CIFAR10 ResNet-50 training throughput per chip.

BASELINE.md: the reference publishes no numbers; this repo establishes the
baseline (images/sec/chip on the flagship config, scripts/7.jax_tpu.py:
ResNet-50, bf16 compute, fused on-device input pipeline, donated state).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline is vs BASELINE.json's published number when present, else 1.0
(this run IS the baseline).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_dist.data import make_transform
    from tpu_dist.data.datasets import CIFAR10_MEAN, CIFAR10_STD
    from tpu_dist.engine.state import TrainState, init_model
    from tpu_dist.engine.steps import make_train_step
    from tpu_dist.models import create_model
    from tpu_dist.ops import make_optimizer
    from tpu_dist.parallel.mesh import batch_sharding, make_mesh, replicated

    n_chips = jax.device_count()
    per_chip_batch = int(os.environ.get("BENCH_PER_CHIP_BATCH", "512"))
    batch = per_chip_batch * n_chips
    steps = int(os.environ.get("BENCH_STEPS", "30"))

    mesh = make_mesh()
    model = create_model("resnet50", num_classes=10, dtype=jnp.bfloat16)
    params, batch_stats = init_model(model, jax.random.PRNGKey(0), (2, 32, 32, 3))
    tx = make_optimizer(0.1, 0.9, 1e-4, steps_per_epoch=100)
    state = jax.device_put(TrainState.create(params, batch_stats, tx),
                           replicated(mesh))
    transform = make_transform(CIFAR10_MEAN, CIFAR10_STD, dtype=jnp.bfloat16)
    step = make_train_step(model, tx, transform, mesh)

    rng = np.random.default_rng(0)
    images = rng.integers(0, 255, (batch, 32, 32, 3)).astype(np.uint8)
    labels = rng.integers(0, 10, (batch,)).astype(np.int32)
    sh = batch_sharding(mesh)
    images = jax.device_put(images, sh)
    labels = jax.device_put(labels, sh)
    key = jax.random.PRNGKey(0)

    # warmup: compile + 3 steps
    for _ in range(3):
        state, metrics = step(state, images, labels, key)
    jax.block_until_ready(state.params)

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, images, labels, key)
    jax.block_until_ready(state.params)
    dt = time.perf_counter() - t0

    ips = batch * steps / dt
    ips_per_chip = ips / n_chips

    baseline = None
    try:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BASELINE.json")) as f:
            baseline = json.load(f).get("published", {}).get(
                "cifar10_resnet50_images_per_sec_per_chip")
    except Exception:
        pass
    vs = ips_per_chip / baseline if baseline else 1.0

    print(json.dumps({
        "metric": "cifar10_resnet50_images_per_sec_per_chip",
        "value": round(ips_per_chip, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(vs, 3),
    }))


if __name__ == "__main__":
    main()
