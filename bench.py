#!/usr/bin/env python
"""Headline benchmark: CIFAR10 ResNet-50 training throughput per chip.

BASELINE.md: the reference publishes no numbers; this repo establishes the
baseline (images/sec/chip on the flagship config, scripts/7.jax_tpu.py:
ResNet-50, bf16 compute, fused on-device input pipeline, donated state).

Methodology: K training steps per dispatch (lax.scan multi-step,
tpu_dist.engine.steps.make_multi_train_step) so controller/dispatch latency
— substantial on tunneled or remote-controller links — is excluded from the
device-rate measurement; best window of several trials is reported (median
and all trials inform stderr diagnostics).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline is vs BASELINE.json's published number when present, else 1.0
(this run IS the baseline).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def main():
    import jax
    jax.config.update("jax_compilation_cache_dir",
                      os.environ.get("JAX_CACHE_DIR", "/tmp/jaxcache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)
    import jax.numpy as jnp
    import numpy as np

    from tpu_dist.data import make_transform
    from tpu_dist.data.datasets import CIFAR10_MEAN, CIFAR10_STD
    from tpu_dist.engine.state import TrainState, init_model
    from tpu_dist.engine.steps import make_multi_train_step
    from tpu_dist.models import create_model
    from tpu_dist.ops import make_optimizer
    from tpu_dist.parallel.mesh import make_mesh, replicated
    from jax.sharding import NamedSharding, PartitionSpec as P

    n_chips = jax.device_count()
    per_chip_batch = int(os.environ.get("BENCH_PER_CHIP_BATCH", "1024"))
    batch = per_chip_batch * n_chips
    # BENCH_STEPS kept as an alias (earlier recipe name). K=160 amortizes
    # dispatch latency to <8% of the window (device-side rate ~148k img/s/chip
    # per the XLA trace; measured wall rate 137k at K=160 vs 95k at K=20).
    k = int(os.environ.get("BENCH_STEPS_PER_WINDOW",
                           os.environ.get("BENCH_STEPS", "160")))
    trials = int(os.environ.get("BENCH_TRIALS", "4"))

    mesh = make_mesh()
    model = create_model("resnet50", num_classes=10, dtype=jnp.bfloat16)
    params, batch_stats = init_model(model, jax.random.PRNGKey(0), (2, 32, 32, 3))
    tx = make_optimizer(0.1, 0.9, 1e-4, steps_per_epoch=100)
    state = jax.device_put(TrainState.create(params, batch_stats, tx),
                           replicated(mesh))
    transform = make_transform(CIFAR10_MEAN, CIFAR10_STD, dtype=jnp.bfloat16)
    step = make_multi_train_step(model, tx, transform, mesh)

    rng = np.random.default_rng(0)
    images = rng.integers(0, 255, (k, batch, 32, 32, 3)).astype(np.uint8)
    labels = rng.integers(0, 10, (k, batch)).astype(np.int32)
    sh_img = NamedSharding(mesh, P(None, "data"))
    images = jax.device_put(images, sh_img)
    labels = jax.device_put(labels, sh_img)
    key = jax.random.PRNGKey(0)

    # warmup: compile + one full window
    state, metrics = step(state, images, labels, key)
    jax.block_until_ready(metrics)

    rates = []
    for _ in range(trials):
        t0 = time.perf_counter()
        state, metrics = step(state, images, labels, key)
        jax.block_until_ready(metrics)
        dt = time.perf_counter() - t0
        rates.append(batch * k / dt)
    best = max(rates)
    print(f"trials (img/s): {[round(r) for r in sorted(rates)]}",
          file=sys.stderr)

    ips_per_chip = best / n_chips
    baseline = None
    try:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BASELINE.json")) as f:
            baseline = json.load(f).get("published", {}).get(
                "cifar10_resnet50_images_per_sec_per_chip")
    except Exception:
        pass
    vs = ips_per_chip / baseline if baseline else 1.0

    print(json.dumps({
        "metric": "cifar10_resnet50_images_per_sec_per_chip",
        "value": round(ips_per_chip, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(vs, 3),
    }))


if __name__ == "__main__":
    main()
