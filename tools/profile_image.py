#!/usr/bin/env python
"""Capture + attribute an XLA profile of the image bench step (VERDICT r4 #1).

Runs the SAME windowed ResNet-50 training step bench.py times (K steps per
dispatch, device-resident uint8 batch, bf16 compute), captures a device
trace with jax.profiler, then post-processes the xplane with xprof's
converter into a per-op-category time table so the ~71% non-MXU time is
ATTRIBUTED, not asserted. Usage:

    python tools/profile_image.py [out_dir]        # default /tmp/imgprof

Env knobs mirror bench.py: BENCH_ARCH / BENCH_PER_CHIP_BATCH / BENCH_STEPS /
BENCH_NORM / BENCH_CIFAR_STEM / BENCH_STEM.
"""

import json
import os
import sys
import time
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def capture(out_dir: str):
    import jax

    import bench

    k = int(os.environ.get("BENCH_STEPS", "20"))
    per_chip = int(os.environ.get("BENCH_PER_CHIP_BATCH", "1024"))
    kwargs = {}
    if os.environ.get("BENCH_CIFAR_STEM") == "1":
        kwargs["cifar_stem"] = True
    if os.environ.get("BENCH_NORM") and os.environ["BENCH_NORM"] != "bn":
        kwargs["norm"] = os.environ["BENCH_NORM"]
    if os.environ.get("BENCH_NORM_DTYPE") == "bf16":
        import jax.numpy as jnp
        kwargs["norm_dtype"] = jnp.bfloat16
    if os.environ.get("BENCH_STEM"):
        kwargs["stem"] = os.environ["BENCH_STEM"]
    batch = per_chip * jax.device_count()
    (step, single, state, images, labels,
     _host, _sh) = bench.build(kwargs, batch, k)
    key = jax.random.PRNGKey(0)
    state, m = step(state, images, labels, key)     # compile + warm
    jax.block_until_ready(m)

    t0 = time.perf_counter()
    with jax.profiler.trace(out_dir):
        state, m = step(state, images, labels, key)
        jax.device_get(m)  # forces completion through the tunnel
    wall = time.perf_counter() - t0
    print(f"captured: {k}-step window, batch {batch}, wall {wall:.3f}s "
          f"-> {batch * k / wall:,.0f} img/s", file=sys.stderr)
    return wall, batch, k


def find_xplane(out_dir: str) -> str:
    hits = []
    for root, _, files in os.walk(out_dir):
        for f in files:
            if f.endswith(".xplane.pb"):
                p = os.path.join(root, f)
                hits.append((os.path.getmtime(p), p))
    if not hits:
        raise SystemExit(f"no .xplane.pb under {out_dir}")
    return max(hits)[1]


def op_table(xplane_path: str):
    """Device op rows from the xplane, via xprof's converter (the same
    backend the TensorBoard profile UI uses): list of dicts with op id,
    type, occurrences, self-time, flop rate, memory BW, bound_by."""
    from xprof.convert import raw_to_tool_data

    data, _ = raw_to_tool_data.xspace_to_tool_data(
        [xplane_path], "framework_op_stats", {})
    tables = json.loads(data) if isinstance(data, (str, bytes)) else data
    tbl = tables[0]
    cols = [c["id"] for c in tbl["cols"]]
    rows = []
    for r in tbl["rows"]:
        d = {k: cell.get("v") for k, cell in zip(cols, r["c"])}
        if d.get("host_or_device") == "Device":
            rows.append(d)
    return rows


def attribute(rows, k: int, batch: int, unit: str = "img"):
    """Aggregate device self-time by op type; print attribution tables.
    ``unit`` labels the rate line ("img" here, "tok" for profile_lm)."""
    by_type = defaultdict(lambda: [0.0, 0.0, 0])   # time, flops, count
    total = 0.0
    for d in rows:
        t = float(d["total_self_time"])
        fl = float(d.get("measured_flop_rate") or 0.0) * t / 1e6  # MFLOPs... rate*us
        by_type[d["type"]][0] += t
        by_type[d["type"]][1] += fl
        by_type[d["type"]][2] += int(d["occurrences"])
        total += t
    print(f"\n== device self-time by op type "
          f"(device busy total {total/1e3:.2f} ms over {k} steps; "
          f"{total/k/1e3:.3f} ms/step; "
          f"{batch*k/(total/1e6):,.0f} {unit}/s device-busy bound) ==")
    for typ, (t, fl, n) in sorted(by_type.items(), key=lambda kv: -kv[1][0]):
        print(f"  {typ:<28} {t/1e3:9.2f} ms  {100*t/total:5.1f}%  x{n}")
    print("\n== top 30 ops by self-time ==")
    top = sorted(rows, key=lambda d: -float(d["total_self_time"]))[:30]
    for d in top:
        name = d["operation"]
        if len(name) > 84:
            name = "..." + name[-81:]
        bw = float(d.get("measured_memory_bw") or 0)
        fr = float(d.get("measured_flop_rate") or 0) / 1e12
        print(f"  {float(d['total_self_time'])/1e3:8.2f} ms {100*float(d['total_self_time'])/total:5.1f}% "
              f"[{d.get('bound_by','?'):>4}] {fr:6.2f} TF/s {bw:7.1f} GB/s  {name}")
    return total


def main():
    out_dir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/imgprof"
    if os.environ.get("PROFILE_PARSE_ONLY") != "1":
        wall, batch, k = capture(out_dir)
    else:
        batch = int(os.environ.get("BENCH_PER_CHIP_BATCH", "1024"))
        k = int(os.environ.get("BENCH_STEPS", "20"))
    xp = find_xplane(out_dir)
    print(f"xplane: {xp}", file=sys.stderr)
    rows = op_table(xp)
    attribute(rows, k, batch)


if __name__ == "__main__":
    main()
