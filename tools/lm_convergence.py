#!/usr/bin/env python
"""Steps/seconds to a val-perplexity threshold (the LM convergence north star).

The LM twin of tools/convergence.py: trains over the synthetic affine corpus
with the SAME LMTrainer the cookbook script uses and reports the first
optimizer step count (and wall seconds) at which held-out perplexity drops
to --threshold. The affine stream (x -> 5x+7 mod V, 5% noise) has an
entropy floor of ~0.05*ln(V) + H(0.05) nats/token, so ppl approaches ~2 for
V=512 when the rule is fully learned — a threshold of 4 proves real
learning in any parallelism mode.

Usage:
    python tools/lm_convergence.py                        # dp
    python tools/lm_convergence.py --mesh data=2,seq=4    # any scripts/8 mesh
    python tools/lm_convergence.py --attn flash --precision bf16
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--mesh", default=None,
                    help="e.g. data=2,seq=4 (scripts/8 syntax)")
    ap.add_argument("--precision", default="fp32", choices=["fp32", "bf16"])
    ap.add_argument("--attn", default="full",
                    choices=["full", "blockwise", "flash"])
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--seq-len", type=int, default=256)
    ap.add_argument("--vocab-size", type=int, default=512)
    ap.add_argument("--d-model", type=int, default=128)
    ap.add_argument("--num-layers", type=int, default=2)
    ap.add_argument("--num-heads", type=int, default=4)
    ap.add_argument("--synth-tokens", type=int, default=500_000)
    ap.add_argument("--lr", type=float, default=3e-2)
    ap.add_argument("--optimizer", default="sgd", choices=["sgd", "adamw"])
    ap.add_argument("--lr-schedule", default="constant",
                    choices=["constant", "cosine", "step"])
    ap.add_argument("--warmup-steps", type=int, default=0)
    ap.add_argument("--lr-decay-steps", type=int, default=0)
    ap.add_argument("--lr-min-frac", type=float, default=0.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--threshold", type=float, default=4.0)
    ap.add_argument("--max-epochs", type=int, default=10)
    ap.add_argument("--steps-per-dispatch", type=int, default=8)
    ap.add_argument("--pp-microbatches", type=int, default=4)
    ap.add_argument("--pp-schedule", default="gpipe",
                    choices=["gpipe", "1f1b"])
    args = ap.parse_args()

    from tpu_dist.parallel import launch
    launch.initialize()

    import jax

    from tpu_dist.configs import LMConfig
    from tpu_dist.engine.lm_loop import LMTrainer

    mesh_shape = mesh_axes = None
    if args.mesh:
        parts = [p.split("=") for p in args.mesh.split(",")]
        mesh_shape = tuple(int(n) for _, n in parts)
        mesh_axes = tuple(name.strip() for name, _ in parts)
    cfg = LMConfig(
        batch_size=args.batch_size, seq_len=args.seq_len,
        vocab_size=args.vocab_size, d_model=args.d_model,
        num_layers=args.num_layers, num_heads=args.num_heads,
        synth_tokens=args.synth_tokens, lr=args.lr, seed=args.seed,
        optimizer=args.optimizer,
        lr_schedule=args.lr_schedule, warmup_steps=args.warmup_steps,
        lr_decay_steps=args.lr_decay_steps, lr_min_frac=args.lr_min_frac,
        precision=args.precision, attn=args.attn,
        epochs=args.max_epochs, print_freq=10 ** 9,
        steps_per_dispatch=args.steps_per_dispatch,
        mesh_shape=mesh_shape,
        mesh_axes=mesh_axes or ("data",),
        pp_microbatches=args.pp_microbatches,
        pp_schedule=args.pp_schedule,
        checkpoint_dir=os.path.join("/tmp", "lm_convergence_ck"))
    tr = LMTrainer(cfg)

    t0 = time.time()
    result = None
    for epoch in range(cfg.epochs):
        tr.train_epoch(epoch)
        # distlint: disable=DL002 -- epoch boundary: train_epoch just drained the device queue
        steps = int(jax.device_get(tr.state.step))
        _, ppl, acc = tr.validate(epoch)
        if jax.process_index() == 0:
            print(f"epoch {epoch}: step {steps} val_ppl {ppl:.2f} "
                  f"acc {acc:.3f}", file=sys.stderr, flush=True)
        if ppl <= args.threshold:
            result = {"steps_to_threshold": steps,
                      "seconds_to_threshold": round(time.time() - t0, 2),
                      # distlint: disable=DL002 -- validate() returns an already-drained host scalar
                      "epochs": epoch + 1, "val_ppl": round(float(ppl), 3)}
            break
    if jax.process_index() == 0:
        out = {"metric": f"steps_to_ppl_{args.threshold:g}",
               "mode": tr.mode, "attn": args.attn,
               "lr_schedule": args.lr_schedule,
               "warmup_steps": args.warmup_steps,
               "precision": args.precision,
               "batch_size": args.batch_size, "seq_len": args.seq_len,
               "seed": args.seed,
               **(result or {"steps_to_threshold": None,
                             "note": f"not reached in {cfg.epochs} epochs"})}
        print(json.dumps(out))


if __name__ == "__main__":
    main()
